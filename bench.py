#!/usr/bin/env python
"""Headline benchmark: KV-cache-aware routing vs round-robin TTFT.

Mirrors the reference's benchmark design (``benchmarking/*/README.md``:
"precise" scheduling = Indexer-routed vs random/load baselines) scaled to
one host: N in-process engine pods share a workload with heavy shared-prefix
reuse; requests are routed either round-robin or by
``Indexer.score_tokens``, and TTFT (admission+prefill wall time) is
compared. Prefix-cache hits skip prefill compute, so routing quality shows
up directly as p50 TTFT.

Prints ONE JSON line:
  {"metric": "p50 TTFT reduction, KV-aware routing vs round-robin",
   "value": <percent>, "unit": "%", "vs_baseline": <value/40>}

vs_baseline is measured against the north-star target of a >=40% p50 TTFT
reduction (BASELINE.md). Runs on whatever backend JAX selects (the real
TPU chip under the driver; CPU elsewhere).
"""

from __future__ import annotations

import json
import os
import statistics
import time

import numpy as np

# Capacity-constrained per-pod default (the regime where routing matters;
# see make_pods) — one constant so variant arms (fp8 2x-page pools)
# derive from the same baseline budget.
DEFAULT_POD_KW = {"num_pages": 72, "max_pages_per_seq": 64}


def build_workload(rng, n_requests=64, n_prefixes=8, prefix_len=256, suffix_len=32,
                   vocab=8000):
    """Shared-prefix replay: most requests reuse one of a few system prompts."""
    prefixes = [
        rng.integers(1, vocab, prefix_len).tolist() for _ in range(n_prefixes)
    ]
    workload = []
    for i in range(n_requests):
        prefix = prefixes[rng.integers(0, n_prefixes)]
        suffix = rng.integers(1, vocab, suffix_len).tolist()
        workload.append(prefix + suffix)
    return workload


def make_pods(n_pods, model_cfg, engine_mod, indexer, params=None,
              pod_kw=None, offload_spec_factory=None):
    """Fresh engine pods wired to feed the indexer's index via events.

    All pods share one parameter tree (same seed anyway — the engines
    never donate params); per-pod init costs ~minutes of per-op dispatch
    on a remote-tunneled TPU.
    """
    import jax

    from llmd_kv_cache_tpu.events.model import EventBatch
    from llmd_kv_cache_tpu.events.pool import Pool, PoolConfig
    from llmd_kv_cache_tpu.models.llama import init_params, maybe_fuse_params

    if params is None:
        params = init_params(jax.random.PRNGKey(0), model_cfg)
    # Fuse ONCE before sharing — but only when the shape profits
    # (fuse_profitable: the 0.9B bench model's hidden 2048 measured ~8%
    # SLOWER fused on the v5e, benchmarking/r5-tpu). Fusing a shared
    # unfused tree per pod would materialize n_pods private weight
    # copies (~1 GiB each at the TPU bench shape); fuse_params is a
    # no-op on an already-fused tree, so the engines just adopt it.
    params = maybe_fuse_params(params, model_cfg)
    # Capacity-constrained page pool (the regime where routing matters:
    # each pod can hold a few of the workload's shared prefixes, like the
    # reference's 73%-capacity setup). Round-robin thrashes the prefix
    # cache; KV-aware routing lets each pod own a prefix subset.
    pod_kw = dict(pod_kw) if pod_kw is not None else dict(DEFAULT_POD_KW)
    pool = Pool(PoolConfig(concurrency=1), indexer.kv_block_index,
                indexer.token_processor)
    pods = {}
    for i in range(n_pods):
        name = f"pod-{i}"

        def sink(events, pod_name=name):
            pool.process_event_batch(
                EventBatch(timestamp=time.time(), events=list(events)),
                pod_name, MODEL_NAME,
            )

        pods[name] = engine_mod.MiniEngine(
            engine_mod.EngineConfig(
                model=model_cfg,
                model_name=MODEL_NAME,
                pod_identifier=name,
                **pod_kw,
            ),
            event_sink=sink,
            params=params,
            seed=0,
            offload_spec=(offload_spec_factory()
                          if offload_spec_factory is not None else None),
        )
    return pods


MODEL_NAME = "bench-llama"


def run_replay(pods, workload, router, tag=""):
    """Admit each request on the routed pod, measuring real service times.

    Returns ``(services, chosen, hit_rate)``: per-request measured prefill
    wall time, the routed pod per request, and the prefix-cache hit-rate
    (cached prompt tokens / total prompt tokens — the metric the
    reference's EPP tables track alongside TTFT,
    `benchmarking/73-capacity/README.md` "KV Cache Metrics Summary").

    Coarse progress goes to stderr (the stdout contract is one JSON line);
    on a tunneled TPU a silent 25-minute run is undebuggable without it.
    """
    import sys

    services, chosen, cached_lens = [], [], []
    hit_tokens = total_tokens = 0
    pod_names = list(pods.keys())
    arm_start = time.perf_counter()
    for i, prompt in enumerate(workload):
        pod_name = router(i, prompt, pod_names)
        engine = pods[pod_name]
        start = time.perf_counter()
        req = engine.add_request(f"r{i}", prompt, max_new_tokens=1)
        services.append(time.perf_counter() - start)
        chosen.append(pod_name)
        # cached_len at admission = tokens served from cache (HBM prefix
        # hits and, on offload-enabled pods, storage-tier restores).
        cached_lens.append(min(req.cached_len, len(prompt)))
        hit_tokens += cached_lens[-1]
        total_tokens += len(prompt)
        if i % 16 == 15:
            print(f"[bench {tag}] {i + 1}/{len(workload)} requests, "
                  f"{time.perf_counter() - arm_start:.1f}s elapsed",
                  file=sys.stderr, flush=True)
    return services, chosen, hit_tokens / max(total_tokens, 1), cached_lens


def run_concurrent(pods, workload, router, arrivals, max_new_tokens=8,
                   tag=""):
    """Arrival-timed CONCURRENT replay through ``enqueue()``/``step()``.

    The virtual-time FIFO model (``queueing_ttfts``) composes serially
    measured service times, so they never interact with concurrency. This
    arm serves the workload through each pod's continuous-batching
    scheduler instead: requests are admitted when they arrive (in virtual
    time), prefill chunks interleave with running decodes, and decode
    steps batch every live request — so a measured TTFT includes queue
    wait, chunked-prefill stalls, batching interference, and decode load
    (reference analog: the real inference-perf runs behind
    ``benchmarking/73-capacity/README.md``).

    Virtual-time accounting over real compute: each pod has a clock;
    every ``enqueue``/``step`` call's wall time advances it. A pod picks
    up work when its clock is the fleet minimum, admissions happen at
    ``max(arrival, pod clock)``, and a request's TTFT is the clock at the
    end of the step that emitted its first token minus its arrival. Wall
    clock on one host would serialize the pods against each other (they
    share the machine), so virtual time is what makes an N-pod fleet
    honest here — the same reasoning as ``queueing_ttfts``, but with the
    service process real.

    Returns ``(ttfts, hit_rate, out_tok_s, decode)`` — one TTFT per
    request, the prefix hit rate, the fleet's sustained output throughput
    (decoded tokens / virtual makespan — the reference capacity tables'
    headline unit, 73-capacity README "Summary across QPS"), and decode
    latency samples: ``decode["itl"]`` is every inter-token gap in
    virtual time (the reference tables' "ITL mean" unit) and
    ``decode["tpot"]`` one per-request mean time-per-output-token
    (requests with ≥2 tokens).
    """
    import math
    import sys
    from collections import deque

    names = list(pods.keys())
    queues: dict = {p: deque() for p in names}
    clocks: dict = {p: 0.0 for p in names}
    arr_of: dict = {}
    ttfts: dict = {}
    emitted_once: set = set()
    # Decode latency accounting: last emission clock and token count per
    # request; gaps between consecutive emissions are the ITL samples.
    last_emit: dict = {}
    first_emit: dict = {}
    n_emitted: dict = {}
    itls: list = []
    hit_tokens = total_tokens = out_tokens = 0
    n = len(workload)
    i = 0
    arm_start = time.perf_counter()

    def inflight(p):
        return len(pods[p]._running)

    def busy(p):
        return bool(queues[p]) or inflight(p) > 0

    while i < n or any(busy(p) for p in names):
        t_arr = arrivals[i] if i < n else math.inf
        t_pod, pick = math.inf, None
        for p in names:
            if busy(p) and clocks[p] < t_pod:
                t_pod, pick = clocks[p], p
        if t_arr <= t_pod:
            # Next event is an arrival: route it with the index as of the
            # work already performed (events publish inside step()); load
            # routers also see each pod's outstanding work (queued +
            # in-flight) as of now.
            # Lazy: only the load router pays for the fleet scan.
            p = router(i, workload[i], names,
                       lambda: {q: len(queues[q]) + inflight(q)
                                for q in names})
            queues[p].append(i)
            arr_of[i] = t_arr
            if inflight(p) == 0 and len(queues[p]) == 1:
                clocks[p] = max(clocks[p], t_arr)  # idle pod fast-forwards
            i += 1
            continue

        p, eng = pick, pods[pick]
        # Admit everything that has arrived by this pod's clock (pool
        # permitting; an out-of-pages admission retries after steps free
        # pages as requests finish).
        while queues[p]:
            j = queues[p][0]
            t0 = time.perf_counter()
            try:
                req = eng.enqueue(f"r{j}", workload[j],
                                  max_new_tokens=max_new_tokens)
            except RuntimeError:
                clocks[p] += time.perf_counter() - t0
                if inflight(p) == 0:
                    raise  # nothing running will ever free pages
                break
            clocks[p] += time.perf_counter() - t0
            queues[p].popleft()
            hit_tokens += min(req.cached_len, len(workload[j]))
            total_tokens += len(workload[j])
        t0 = time.perf_counter()
        emitted = eng.step()
        clocks[p] += time.perf_counter() - t0
        out_tokens += len(emitted)
        new_first = False
        for rid in emitted:
            if rid not in emitted_once:
                emitted_once.add(rid)
                new_first = True
                j = int(rid[1:])
                ttfts[j] = clocks[p] - arr_of[j]
                first_emit[rid] = clocks[p]
                n_emitted[rid] = 1
            else:
                itls.append(clocks[p] - last_emit[rid])
                n_emitted[rid] += 1
            last_emit[rid] = clocks[p]
        if new_first and len(emitted_once) % 16 == 0:
            print(f"[bench {tag}] {len(emitted_once)}/{n} first tokens, "
                  f"{time.perf_counter() - arm_start:.1f}s elapsed",
                  file=sys.stderr, flush=True)

    assert len(ttfts) == n, f"served {len(ttfts)} of {n}"
    makespan = max(clocks.values())
    tpots = [
        (last_emit[rid] - first_emit[rid]) / (n_emitted[rid] - 1)
        for rid in first_emit if n_emitted[rid] > 1
    ]
    return ([ttfts[j] for j in range(n)], hit_tokens / max(total_tokens, 1),
            out_tokens / max(makespan, 1e-9),
            {"itl": itls, "tpot": tpots})


def make_kv_router(indexer):
    """Score-argmax router with round-robin fallback — shared by every
    KV-routed arm so the arms cannot silently diverge in policy.

    This is the reference's "precise scheduling" strategy (the EPP
    scoring from this indexer, benchmarking/37-capacity README); the
    factories below mirror its comparison strategies. Each score_tokens
    call is timed into ``router.score_latencies`` so arms can report
    scheduler overhead (see ``score_path_stats``)."""
    rr_counter = [0]
    latencies: list = []

    def router(_i, prompt, names, loads=None):
        t0 = time.perf_counter()
        scores = indexer.score_tokens(prompt, MODEL_NAME)
        latencies.append(time.perf_counter() - t0)
        if scores:
            return max(scores.items(), key=lambda kv: kv[1])[0]
        pick = names[rr_counter[0] % len(names)]
        rr_counter[0] += 1
        return pick

    router.score_latencies = latencies
    return router


def score_path_stats(router, indexer) -> dict:
    """Scheduler-overhead summary for a KV-routed arm: score_tokens
    latency percentiles plus the token processor's prefix-cache hit
    counters, so BENCH_r*.json tracks score-path cost over time."""
    out = {}
    lat = getattr(router, "score_latencies", None)
    if lat:
        out["score_tokens_p50_us"] = round(statistics.median(lat) * 1e6, 1)
        out["score_tokens_p99_us"] = round(
            float(np.quantile(lat, 0.99)) * 1e6, 1)
        out["score_tokens_calls"] = len(lat)
    pc = indexer.prefix_cache_stats()
    if pc is not None:
        out["prefix_cache_hit_rate"] = round(pc["block_hit_rate"], 4)
        out["prefix_cache_hits"] = pc["hits"]
        out["prefix_cache_misses"] = pc["misses"]
    return out


def make_rr_router(_indexer=None):
    """Round-robin baseline (deterministic uniform spread)."""
    def router(i, _p, names, loads=None):
        return names[i % len(names)]
    return router


def make_random_router(_indexer=None, seed=11):
    """Uniform-random scheduling — the reference's "random" strategy."""
    r = np.random.default_rng(seed)

    def router(_i, _p, names, loads=None):
        return names[int(r.integers(len(names)))]
    return router


def make_load_router(_indexer=None):
    """Least-outstanding-work scheduling — the reference's "load-aware"
    strategy: route to the pod with the fewest queued + in-flight
    requests at arrival (name order breaks ties)."""
    def router(_i, _p, names, loads=None):
        loads = (loads() if callable(loads) else loads) or {}
        return min(names, key=lambda p: (loads.get(p, 0), p))
    return router


def queueing_ttfts(services, chosen, arrivals):
    """Open-loop TTFTs from measured service times, in virtual time.

    Each pod serves FIFO; TTFT = queue wait + service. This is the regime
    behind the reference's headline tables — at saturation, routing
    quality compounds through queue depth, not just prefill skip
    (`benchmarking/73-capacity/README.md`: precise 0.542 s vs 92.5 s p90
    is queue-dominated). ``arrivals=None`` → bare service times. Because
    service times are fixed measurements, one replay supports a whole
    arrival-rate sweep (the reference's "Summary across QPS").
    """
    if arrivals is None:
        return list(services)
    pod_free: dict = {}
    ttfts = []
    for i, (svc, pod) in enumerate(zip(services, chosen)):
        begin = max(arrivals[i], pod_free.get(pod, 0.0))
        pod_free[pod] = begin + svc
        ttfts.append(begin + svc - arrivals[i])
    return ttfts


def bench_index_add(native: bool = True) -> dict:
    """Fallback metric: index Add throughput vs the reference's documented
    Go micro-benchmark (BenchmarkInMemory_Add: 6,086,106 ns/op on the same
    fixed-seed 10k-key workload, tests/profiling/kv_cache_index/README.md)."""
    import time

    from llmd_kv_cache_tpu.core import PodEntry

    if native:
        from llmd_kv_cache_tpu.index.native import NativeIndex as IndexImpl
        from llmd_kv_cache_tpu.index.native import NativeIndexConfig as ConfigImpl
        backend = "native C++ index"
    else:
        from llmd_kv_cache_tpu.index import InMemoryIndex as IndexImpl
        from llmd_kv_cache_tpu.index import InMemoryIndexConfig as ConfigImpl
        backend = "python in-memory index"

    rng = np.random.default_rng(42)
    keys = [int(x) for x in rng.integers(0, 2**63, 10_000, dtype=np.int64)]
    entries = [PodEntry("pod1", "gpu")]
    times = []
    for _ in range(30):
        idx = IndexImpl(ConfigImpl())
        start = time.perf_counter()
        idx.add(keys, keys, entries)
        times.append(time.perf_counter() - start)
    ns_op = min(times) * 1e9
    go_baseline_ns = 6_086_106
    return {
        "metric": f"index Add ns/op (10k-key workload, {backend}; "
                  "reference Go BenchmarkInMemory_Add = 6086106)",
        "value": round(ns_op),
        "unit": "ns/op",
        "vs_baseline": round(go_baseline_ns / ns_op, 3),
    }


def bench_offload_throughput() -> dict:
    """Secondary metric: offload store+load throughput through the full
    stack (device page gather → host slab → native file write, and back).
    Printed by ``--offload``; informational (the reference publishes no
    comparable figure)."""
    import shutil
    import tempfile
    import time

    import jax.numpy as jnp

    from llmd_kv_cache_tpu.offload.spec import SharedStorageOffloadSpec

    root = tempfile.mkdtemp(prefix="kvtpu-bench-offload-")
    try:
        layers, pages, page_size, kvh, hd = 16, 256, 16, 8, 128
        spec = SharedStorageOffloadSpec(
            root=root, model_name="bench", page_size=page_size,
            num_layers=layers, kv_heads=kvh, head_dim=hd, io_threads=4,
            parallel_agnostic=True,
        )
        rng = np.random.default_rng(0)
        shape = (layers, pages, kvh, page_size, hd)
        k = jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
        v = jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
        handlers = spec.get_handlers(k, v)

        # 64 blocks of 2 pages each
        transfers = [(0x1000 + i, [1 + 2 * i, 2 + 2 * i]) for i in range(64)]
        start = time.perf_counter()
        job = handlers.async_store_blocks(transfers)
        result = None
        while result is None:
            for res in handlers.get_finished():
                if res.job_id == job:
                    result = res
            time.sleep(0.001)
        store_s = time.perf_counter() - start
        if not result.success or result.shed_hashes:
            raise RuntimeError(
                f"store leg degraded (success={result.success}, "
                f"shed={len(result.shed_hashes)}): throughput not measurable"
            )
        store_bytes = result.bytes_transferred

        start = time.perf_counter()
        job = handlers.async_load_blocks(transfers)
        result = None
        while result is None:
            for res in handlers.get_finished():
                if res.job_id == job:
                    result = res
            time.sleep(0.001)
        load_s = time.perf_counter() - start
        if not result.success:
            raise RuntimeError("load leg failed: throughput not measurable")
        load_bytes = result.bytes_transferred
        handlers.shutdown()

        return {
            "metric": "offload store/load throughput (64 blocks, "
                      f"{store_bytes / 1e6:.0f} MB, device↔host↔disk)",
            "value": round(store_bytes / store_s / 1e9, 3),
            "unit": "GB/s store "
                    f"({load_bytes / load_s / 1e9:.2f} GB/s load)",
            "vs_baseline": 1.0,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_decode_throughput(hybrid: bool = False) -> dict:
    """Secondary metric: steady-state greedy decode tokens/s through the
    engine, single-token stepping vs fused 32-token bursts
    (``forward_decode_steps``). The burst factor is the dispatch-overhead
    amortization — the figure that matters on real deployments where
    per-launch latency competes with per-token compute.

    ``hybrid=True`` runs a mixed full/SWA model instead: the burst rides
    the two-pool scan with freeze-and-reclaim window paging
    (``forward_decode_steps_hybrid``) — the arm VERDICT r2 #4 asked for,
    proving SWA families keep the dispatch-amortization win."""
    import time

    from llmd_kv_cache_tpu.models import engine as engine_mod
    from llmd_kv_cache_tpu.models.llama import LlamaConfig, init_params

    import jax

    hybrid_kw = dict(
        sliding_window=128, swa_layers=(1, 3),
    ) if hybrid else {}
    cfg = LlamaConfig(
        # head_dim 128: the Mosaic lane-tiling unit, so the real-TPU run
        # exercises the Pallas kernels (sub-128 head dims fall back to XLA)
        # — and the shape real model families (Llama/Qwen) actually use.
        vocab_size=8192, hidden_size=512, num_layers=4, num_heads=8,
        num_kv_heads=4, head_dim=128, intermediate_size=1408, page_size=16,
        **hybrid_kw,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, 8000, 64).tolist() for _ in range(8)]
    max_new = 128
    rates = {}
    bursts = (1, 32)
    for burst in bursts:
        eng = engine_mod.MiniEngine(
            engine_mod.EngineConfig(
                model=cfg, num_pages=256, max_pages_per_seq=16,
                model_name="bench-decode", pod_identifier="p",
                decode_burst=burst,
            ),
            params=params, seed=0,
        )
        reqs = [eng.add_request(f"r{i}", p, max_new_tokens=max_new)
                for i, p in enumerate(prompts)]
        # one warm step so the decode program is compiled before timing
        eng.step()
        start = time.perf_counter()
        tokens_before = sum(len(r.output) for r in reqs)
        while not all(r.done for r in reqs):
            eng.step()
        elapsed = time.perf_counter() - start
        rates[burst] = (sum(len(r.output) for r in reqs) - tokens_before) / elapsed
    kind = "hybrid full/SWA" if hybrid else "dense"
    return {
        "metric": f"greedy decode tok/s, batch 8, {kind} (burst "
                  f"{bursts[-1]} vs single-step {rates[1]:.0f} tok/s)",
        "value": round(rates[bursts[-1]], 1),
        "unit": f"tok/s (x{rates[bursts[-1]] / rates[1]:.2f} vs single-step)",
        "vs_baseline": 1.0,
    }


def bench_ragged() -> dict:
    """Ragged single-kernel mixed prefill+decode dispatch vs the padded
    two-kernel path (``EngineConfig.ragged_attention``).

    Three replay mixes (prefill-heavy / decode-heavy / 50-50) run through
    engine pairs differing only in the ``ragged_attention`` knob. Padding
    waste is read from the engines' dispatch-token telemetry (the
    ``kvtpu_engine_ragged_*_tokens_total`` pair) — the padded path
    dispatches ``max_batch`` decode rows and full prefill chunks, the
    ragged path dispatches one flat token axis bucketed to the next power
    of two.

    On CPU the Pallas kernels run in interpret mode, so this is a
    correctness smoke: token streams must match the padded path exactly
    (greedy fp32) and only the waste ratios are meaningful. On a real TPU
    the workload scales up and the gate asserts >=1.5x decode throughput
    on the decode-heavy mix.
    """
    import time

    import jax

    from llmd_kv_cache_tpu.models import engine as engine_mod
    from llmd_kv_cache_tpu.models.llama import LlamaConfig, init_params
    from llmd_kv_cache_tpu.telemetry.engine_telemetry import (
        EngineTelemetryConfig,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=8192, hidden_size=512, num_layers=4, num_heads=8,
            num_kv_heads=4, head_dim=128, intermediate_size=1408,
            page_size=16,
        )
        # (prompt_len, max_new_tokens, n_requests) per replay mix
        mixes = {"prefill_heavy": (384, 8, 8), "decode_heavy": (32, 96, 8),
                 "mixed": (128, 32, 8)}
        num_pages, max_pps, max_batch = 1024, 64, 8
    else:
        import dataclasses

        import jax.numpy as jnp

        # fp32: the equivalence gate compares greedy argmax streams between
        # two differently-compiled programs — at bf16 resolution random tiny
        # models hit top-2 logit ties (~2^-9 gaps) that flip on benign
        # accumulation-order differences.
        cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
        mixes = {"prefill_heavy": (20, 2, 4), "decode_heavy": (5, 8, 4),
                 "mixed": (12, 4, 4)}
        num_pages, max_pps, max_batch = 128, 16, 4
    params = init_params(jax.random.PRNGKey(0), cfg)

    arms = {}
    for mix, (plen, max_new, nreq) in mixes.items():
        rng = np.random.default_rng(11)
        prompts = [
            rng.integers(1, cfg.vocab_size - 1,
                         plen + int(rng.integers(0, max(plen // 2, 2)))
                         ).tolist()
            for _ in range(nreq)
        ]
        per_path = {}
        for ragged in (False, True):
            eng = engine_mod.MiniEngine(
                engine_mod.EngineConfig(
                    model=cfg, num_pages=num_pages,
                    max_pages_per_seq=max_pps, max_batch=max_batch,
                    model_name="bench-ragged",
                    pod_identifier="ragged" if ragged else "padded",
                    ragged_attention=ragged,
                    telemetry=EngineTelemetryConfig(),
                ),
                params=params, seed=0,
            )
            if ragged:
                assert eng._ragged, "ragged path did not engage"
            reqs = [eng.enqueue(f"r{i}", p, max_new_tokens=max_new)
                    for i, p in enumerate(prompts)]
            eng.step()  # compile the dispatch before timing
            start = time.perf_counter()
            steps = 0
            while not all(r.done for r in reqs):
                eng.step()
                steps += 1
                assert steps < 10_000, f"{mix}: engine did not converge"
            elapsed = time.perf_counter() - start
            waste = eng.telemetry.debug_vars()["ragged"]
            real = waste["real_tokens_total"]
            padded = waste["padded_tokens_total"]
            per_path[ragged] = {
                "tok_s": sum(len(r.output) for r in reqs) / elapsed,
                "tokens": [list(r.output) for r in reqs],
                "waste_ratio": 1.0 - real / max(padded, 1),
            }
        if not on_tpu:
            # Interpret-mode equivalence gate: same greedy streams as the
            # padded two-kernel path, token for token (fp32 tiny model).
            assert per_path[True]["tokens"] == per_path[False]["tokens"], (
                f"{mix}: ragged token streams diverge from the padded path")
        arms[mix] = {
            "ragged_tok_s": round(per_path[True]["tok_s"], 2),
            "padded_tok_s": round(per_path[False]["tok_s"], 2),
            "speedup": round(per_path[True]["tok_s"]
                             / per_path[False]["tok_s"], 3),
            "ragged_waste": round(per_path[True]["waste_ratio"], 4),
            "padded_waste": round(per_path[False]["waste_ratio"], 4),
        }
    if on_tpu:
        # The on-chip gate: ragged dispatch must beat the padded two-kernel
        # path by >=1.5x on the decode-heavy replay (padding-FLOP + launch
        # elimination is the whole point of the single-kernel path).
        speed = arms["decode_heavy"]["speedup"]
        assert speed >= 1.5, (
            f"ragged decode-heavy speedup {speed:.2f}x < 1.5x gate")
        value = arms["decode_heavy"]["speedup"]
        unit = "x decode-heavy tok/s vs padded two-kernel path"
    else:
        # CPU smoke: the gate is token-stream equivalence (asserted above
        # for every mix) — throughput in interpret mode is meaningless.
        value = float(len(arms))
        unit = "replay mixes token-equivalent to the padded path (smoke)"
    return {
        "metric": "ragged single-kernel vs padded two-kernel dispatch "
                  "(prefill-heavy / decode-heavy / 50-50 replays)",
        "value": value,
        "unit": unit,
        "vs_baseline": 1.0,
        "arms": arms,
        "platform": "tpu" if on_tpu else "cpu-interpret",
    }


def bench_fp8_bandwidth() -> dict:
    """fp8 vs bf16 decode KV bandwidth at real batch shapes (the VERDICT
    r5 item-1 closeout: the fp8 arm's justification is halved attention
    HBM traffic, and it had zero measured perf).

    Times ``pallas_paged_decode_attention`` over identical page tables
    with a bf16 cache and its fp8 (e4m3) cast at the bandwidth-bound
    shape from benchmarking/r5-tpu (b32 / ctx2048 / 8 kv heads / hd128),
    and reports ms/step next to the analytic KV bytes/step each dtype
    must stream. On CPU the kernel runs in interpret mode — timing is
    meaningless, so the probe degrades to a correctness smoke (fp8 kernel
    vs the XLA upcast-on-gather reference) plus the analytic byte counts;
    the decision rule (flip the default only if fp8's measured ms/step
    wins) is encoded in the output either way. The roofline argument
    lives in benchmarking/fp8-roofline/README.md.
    """
    import time

    import jax
    import jax.numpy as jnp

    from llmd_kv_cache_tpu.ops.paged_attention import paged_attention
    from llmd_kv_cache_tpu.ops.pallas_paged_attention import (
        pallas_paged_decode_attention,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        batch, ctx, kv_heads, q_heads, head_dim, page_size = (
            32, 2048, 8, 16, 128, 16)
        iters, compute_dtype = 30, jnp.bfloat16
    else:
        batch, ctx, kv_heads, q_heads, head_dim, page_size = (
            2, 64, 2, 4, 128, 8)
        iters, compute_dtype = 1, jnp.float32
    pages_per_seq = ctx // page_size
    num_pages = batch * pages_per_seq + 1
    key = jax.random.PRNGKey(0)
    kk, kv, kq = jax.random.split(key, 3)
    k16 = jax.random.normal(
        kk, (num_pages, kv_heads, page_size, head_dim), compute_dtype)
    v16 = jax.random.normal(
        kv, (num_pages, kv_heads, page_size, head_dim), compute_dtype)
    k8 = k16.astype(jnp.float8_e4m3fn)
    v8 = v16.astype(jnp.float8_e4m3fn)
    q = jax.random.normal(kq, (batch, q_heads, head_dim), compute_dtype)
    page_table = (np.arange(batch * pages_per_seq, dtype=np.int32)
                  .reshape(batch, pages_per_seq) + 1)
    page_table = jnp.asarray(page_table)
    ctx_lens = jnp.full((batch,), ctx, jnp.int32)

    def run(k_cache, v_cache):
        return pallas_paged_decode_attention(
            q, k_cache, v_cache, page_table, ctx_lens,
            interpret=not on_tpu)

    wide = "bf16" if on_tpu else "f32"  # interpret smoke runs fp32
    results = {}
    kv_bytes = {}
    for name, (kc, vc) in {wide: (k16, v16), "fp8": (k8, v8)}.items():
        out = run(kc, vc)
        out.block_until_ready()
        start = time.perf_counter()
        for _ in range(iters):
            out = run(kc, vc)
        out.block_until_ready()
        results[name] = (time.perf_counter() - start) / iters * 1e3
        # Analytic KV stream per decode step: every live key+value page.
        kv_bytes[name] = int(
            2 * batch * ctx * kv_heads * head_dim * kc.dtype.itemsize)
    if not on_tpu:
        # Interpret smoke: the fp8 quant arm must match the XLA
        # upcast-on-gather reference on the same 1-byte cache.
        q_pos = jnp.full((batch, 1), ctx, jnp.int32)
        ref = paged_attention(
            q[:, None].transpose(0, 1, 2, 3).reshape(batch, 1, q_heads,
                                                     head_dim),
            k8, v8, page_table, q_pos, ctx_lens)[:, 0]
        np.testing.assert_allclose(
            np.asarray(run(k8, v8), np.float32),
            np.asarray(ref, np.float32), rtol=2e-2, atol=2e-2)
    fp8_wins = on_tpu and results["fp8"] < results[wide] * 0.8
    return {
        "metric": f"fp8 vs {wide} decode ms/step, b{batch}/ctx{ctx}/"
                  f"kvh{kv_heads}/hd{head_dim} "
                  f"(KV stream {kv_bytes[wide] >> 10} KiB -> "
                  f"{kv_bytes['fp8'] >> 10} KiB per step)",
        "value": round(results["fp8"], 3),
        "unit": f"ms/step fp8 ({wide} {results[wide]:.3f} ms/step)",
        "vs_baseline": round(results[wide] / max(results["fp8"], 1e-9), 3),
        "kv_bytes_per_step": kv_bytes,
        "fp8_wins": bool(fp8_wins),
        "decision": ("flip kv_cache_dtype default to f8_e4m3"
                     if fp8_wins else
                     "keep bf16 default; see benchmarking/fp8-roofline"),
        "platform": "tpu" if on_tpu else "cpu-interpret",
    }


def bench_event_ingestion() -> dict:
    """Write-path capacity: raw ZMQ-shaped messages through the sharded
    pool into the (native) index, end to end (msgpack parse → request-key
    recompute → index add). Events/sec across 8 simulated pods."""
    import time

    import msgpack

    from llmd_kv_cache_tpu.core import ChunkedTokenDatabase, TokenProcessorConfig
    from llmd_kv_cache_tpu.events import Pool, PoolConfig, RawMessage
    from llmd_kv_cache_tpu.index.base import create_index

    block = 16
    processor = ChunkedTokenDatabase(TokenProcessorConfig(block_size_tokens=block))
    index = create_index(None)
    pool = Pool(PoolConfig(concurrency=4), index, processor)
    pool.start()

    rng = np.random.default_rng(0)
    n_msgs = 4000
    msgs = []
    for i in range(n_msgs):
        pod = f"pod-{i % 8}"
        tokens = rng.integers(1, 30000, 4 * block).tolist()  # 4 blocks/event
        ev = ["BlockStored", [int(h) for h in rng.integers(1, 2**62, 4)],
              None, tokens, block]
        msgs.append(RawMessage(
            topic=f"kv@{pod}@m", sequence=i,
            payload=msgpack.packb([float(i), [ev]], use_bin_type=True),
        ))

    start = time.perf_counter()
    for m in msgs:
        pool.add_task(m)
    pool.join()
    elapsed = time.perf_counter() - start
    pool.shutdown()

    return {
        "metric": "KV-event ingestion (BlockStored, 4 blocks/event, "
                  "parse+hash+index, 8 pods, 4 shards)",
        "value": round(n_msgs / elapsed),
        "unit": "events/s",
        "vs_baseline": 1.0,
        # Batched-drain effectiveness (events/pool.py): messages per
        # worker wakeup and index calls saved by digest coalescing.
        "ingest_batches": pool.ingest_batches,
        "ingest_messages": pool.ingest_messages,
        "ingest_coalesced_ops": pool.coalesced_ops,
    }


def bench_flight_recorder() -> dict:
    """Observability overhead: flight-recorder cost per record, its share
    of the Python-path score hot path (<1% asserted — the recorder rides
    every ``score_tokens`` call), and event-ingest lag p50/p99 through the
    sharded pool."""
    import time

    import msgpack

    from llmd_kv_cache_tpu.core import ChunkedTokenDatabase, TokenProcessorConfig
    from llmd_kv_cache_tpu.core.keys import PodEntry
    from llmd_kv_cache_tpu.events import Pool, PoolConfig, RawMessage
    from llmd_kv_cache_tpu.index.base import create_index
    from llmd_kv_cache_tpu.scoring import Indexer
    from llmd_kv_cache_tpu.telemetry.flight_recorder import KIND_SCORE, FlightRecorder

    # -- ns/record: the exact hot-path shape (dict literal + ring store) --
    recorder = FlightRecorder()
    scores = {f"pod-{i}": float(i) for i in range(4)}
    n_records = 200_000
    start = time.perf_counter_ns()
    for _ in range(n_records):
        recorder.record(
            KIND_SCORE,
            {"model": "bench", "blocks": 64, "hits": 32, "scores": scores},
        )
    ns_per_record = (time.perf_counter_ns() - start) / n_records

    # -- score-path baseline (Python path: lookup + prefix scorer) --------
    indexer = Indexer()
    block = indexer.token_processor.block_size
    rng = np.random.default_rng(7)
    tokens = rng.integers(1, 30000, 16 * block).tolist()
    block_keys = indexer.compute_block_keys(tokens, "bench")
    entries = [PodEntry(f"pod-{i}", "gpu") for i in range(4)]
    indexer.kv_block_index.add(None, block_keys, entries)
    n_scores = 2_000
    samples = []
    for _ in range(n_scores):
        t0 = time.perf_counter_ns()
        indexer.score_tokens(tokens, "bench")
        samples.append(time.perf_counter_ns() - t0)
    samples.sort()
    score_p50_ns = samples[len(samples) // 2]
    overhead_pct = 100.0 * ns_per_record / score_p50_ns
    # The recorder must stay invisible on the score hot path.
    assert overhead_pct < 1.0, (
        f"flight recorder {ns_per_record:.0f} ns/record is "
        f"{overhead_pct:.2f}% of the {score_p50_ns} ns score p50"
    )

    # -- event-ingest lag through the sharded pool ------------------------
    processor = ChunkedTokenDatabase(TokenProcessorConfig(block_size_tokens=block))
    pool = Pool(PoolConfig(concurrency=4), create_index(None), processor)
    pool.start()
    n_msgs = 2000
    for i in range(n_msgs):
        pod = f"pod-{i % 8}"
        ev_tokens = rng.integers(1, 30000, 4 * block).tolist()
        ev = ["BlockStored", [int(h) for h in rng.integers(1, 2**62, 4)],
              None, ev_tokens, block]
        pool.add_task(RawMessage(
            topic=f"kv@{pod}@m", sequence=i,
            payload=msgpack.packb([time.time(), [ev]], use_bin_type=True),
        ))
    pool.join()
    lag = pool.lag_stats()
    pool.shutdown()

    return {
        "metric": "flight-recorder overhead on the score hot path "
                  "(Python path, 16-block prompt, 4 pods)",
        "value": round(overhead_pct, 4),
        "unit": "% of score p50",
        "vs_baseline": 1.0,
        "flight_recorder_ns_per_record": round(ns_per_record, 1),
        "score_p50_us": round(score_p50_ns / 1e3, 1),
        # Same-process publish→ingest, so skew-free: pure queueing+parse.
        "ingest_lag_p50_ms": round(lag.get("lag_p50_s", 0.0) * 1e3, 3),
        "ingest_lag_p99_ms": round(lag.get("lag_p99_s", 0.0) * 1e3, 3),
        "index_staleness_s": round(lag.get("staleness_s", 0.0), 3),
    }


def bench_snapshot_overhead() -> dict:
    """Crash-recovery overhead: score-path p50 with the periodic
    snapshotter running hot vs without it (<1% regression asserted —
    snapshots ride a background thread, never the score path), plus the
    cost of one snapshot of a populated index."""
    import tempfile
    import time

    from llmd_kv_cache_tpu.core.keys import PodEntry
    from llmd_kv_cache_tpu.recovery import RecoveryConfig, RecoveryManager
    from llmd_kv_cache_tpu.scoring import Indexer

    indexer = Indexer()
    block = indexer.token_processor.block_size
    rng = np.random.default_rng(7)
    tokens = rng.integers(1, 30000, 16 * block).tolist()
    block_keys = indexer.compute_block_keys(tokens, "bench")
    entries = [PodEntry(f"pod-{i}", "gpu") for i in range(4)]
    indexer.kv_block_index.add(None, block_keys, entries)
    # Realistic index population so dump_state moves real bytes.
    for i in range(2000):
        extra = rng.integers(1, 30000, 4 * block).tolist()
        indexer.kv_block_index.add(
            None, indexer.compute_block_keys(extra, "bench"),
            [entries[i % 4]])

    def score_p50_ns(n=20_000):
        samples = []
        for _ in range(n):
            t0 = time.perf_counter_ns()
            indexer.score_tokens(tokens, "bench")
            samples.append(time.perf_counter_ns() - t0)
        samples.sort()
        return samples[len(samples) // 2]

    class _SeqPool:
        """Just enough pool surface for the manager's snapshot loop."""

        def lag_stats(self):
            return {"pods": {f"pod-{i}": {"last_seq": 1000} for i in range(4)}}

        def index_staleness_s(self):
            return 0.0

    score_p50_ns(n=2_000)  # warm caches so both arms measure steady state
    baseline_ns = score_p50_ns()

    with tempfile.TemporaryDirectory() as tmp:
        mgr = RecoveryManager(
            RecoveryConfig(snapshot_dir=tmp, snapshot_interval_s=0.5,
                           snapshot_keep=2),
            indexer.kv_block_index, _SeqPool())
        t0 = time.perf_counter_ns()
        mgr.snapshot_now("bench")
        one_snapshot_ms = (time.perf_counter_ns() - t0) / 1e6
        # Hot arm: snapshots every 0.5 s while scoring — 60× the default
        # production cadence (30 s) — over a window spanning several
        # snapshot cycles.
        mgr.start()
        hot_ns = score_p50_ns()
        mgr.stop(final_snapshot=False)
        snapshots = mgr.snapshots_written

    regression_pct = 100.0 * (hot_ns - baseline_ns) / baseline_ns
    # The snapshotter must stay invisible on the score hot path.
    assert regression_pct < 1.0, (
        f"snapshotting regressed score p50 by {regression_pct:.2f}% "
        f"({baseline_ns} -> {hot_ns} ns) with {snapshots} snapshots written"
    )

    return {
        "metric": "score-path p50 regression with 0.5 s periodic snapshots "
                  "(Python path, 16-block prompt, 4 pods, ~10k-entry index)",
        "value": round(regression_pct, 4),
        "unit": "% of score p50",
        "vs_baseline": 1.0,
        "score_p50_baseline_us": round(baseline_ns / 1e3, 1),
        "score_p50_snapshotting_us": round(hot_ns / 1e3, 1),
        "snapshot_write_ms": round(one_snapshot_ms, 3),
        "snapshots_during_window": snapshots,
    }


def bench_engine_telemetry() -> dict:
    """Engine-telemetry overhead gate: per-step hook cost as a share of the
    decode-step p50 (<1% asserted — the hooks ride every ``step()``), plus
    informational enabled-vs-disabled step p50s from real engine runs.

    The assertion is analytic (hook-ns / step-p50-ns) like the
    flight-recorder gate: two wall-clock arms of a sub-millisecond CPU
    step differ by more than 1% from scheduler noise alone, so a direct
    A/B assert would flap. Both arms still run and are reported."""
    import time

    import jax

    from llmd_kv_cache_tpu.models import engine as engine_mod
    from llmd_kv_cache_tpu.models.llama import LlamaConfig, init_params
    from llmd_kv_cache_tpu.telemetry.engine_telemetry import (
        EngineTelemetry,
        EngineTelemetryConfig,
    )

    cfg = LlamaConfig(
        vocab_size=8192, hidden_size=256, num_layers=2, num_heads=4,
        num_kv_heads=2, head_dim=64, intermediate_size=704, page_size=16,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, 8000, 64).tolist() for _ in range(4)]
    max_new = 96

    def step_p50_us(telemetry) -> float:
        eng = engine_mod.MiniEngine(
            engine_mod.EngineConfig(
                model=cfg, num_pages=128, max_pages_per_seq=16,
                model_name="bench-telemetry", pod_identifier="p",
                decode_burst=8, telemetry=telemetry,
            ),
            params=params, seed=0,
        )
        for i, p in enumerate(prompts):
            eng.enqueue(f"r{i}", p, max_new_tokens=max_new)
        eng.step()  # compile the prefill/decode programs before timing
        samples = []
        while True:
            t0 = time.perf_counter_ns()
            alive = eng.step()
            samples.append(time.perf_counter_ns() - t0)
            if not alive:
                break
        samples.sort()
        return samples[len(samples) // 2] / 1e3

    off_p50_us = step_p50_us(None)
    on_p50_us = step_p50_us(EngineTelemetryConfig())

    # -- analytic hook cost: the exact per-step call shape ----------------
    tel = EngineTelemetry(EngineTelemetryConfig())
    pool_eng = engine_mod.MiniEngine(
        engine_mod.EngineConfig(
            model=cfg, num_pages=128, max_pages_per_seq=16,
            model_name="bench-telemetry-pool", pod_identifier="p",
        ),
        params=params, seed=0,
    )
    pools = [("full", pool_eng.block_manager)]
    n = 100_000
    start = time.perf_counter_ns()
    for _ in range(n):
        tel.on_step(1e-3, True, pools)  # includes the 1-in-16 pool scrape
    ns_on_step = (time.perf_counter_ns() - start) / n

    tel.on_admitted("r0", 0)
    tel.on_first_token("r0")
    now = time.monotonic()
    start = time.perf_counter_ns()
    for i in range(n):
        tel.on_decode_tokens("r0", 1, now + i * 1e-3)
    ns_on_decode = (time.perf_counter_ns() - start) / n

    # Per step the engine pays one on_step plus one on_decode_tokens per
    # running request (batch of 4 here, matching the wall-clock arms).
    hook_ns_per_step = ns_on_step + len(prompts) * ns_on_decode
    overhead_pct = 100.0 * hook_ns_per_step / (off_p50_us * 1e3)
    # Telemetry must stay invisible on the decode-step path.
    assert overhead_pct < 1.0, (
        f"engine telemetry costs {hook_ns_per_step:.0f} ns/step — "
        f"{overhead_pct:.2f}% of the {off_p50_us:.0f} us decode-step p50"
    )

    return {
        "metric": "engine-telemetry overhead on the decode-step path "
                  "(batch 4, burst 8, pool scrape every 16 steps)",
        "value": round(overhead_pct, 4),
        "unit": "% of decode-step p50",
        "vs_baseline": 1.0,
        "hook_ns_per_step": round(hook_ns_per_step, 1),
        "on_step_ns": round(ns_on_step, 1),
        "on_decode_tokens_ns": round(ns_on_decode, 1),
        "step_p50_off_us": round(off_p50_us, 1),
        "step_p50_on_us": round(on_p50_us, 1),
    }


def bench_shard_fanout(shards: int = 4) -> dict:
    """Sharded control-plane overhead gate (``--shards N``, ISSUE 6).

    Two arms over the real gRPC wire on localhost, both scored through
    :class:`~llmd_kv_cache_tpu.cluster.router.ShardRouter` so the only
    variable is the fan-out width:

    - **baseline** — a single indexer replica (N=1 ring: one LookupBlocks
      RPC per score).
    - **sharded** — ``shards`` replicas holding ``shards``× the baseline
      index size in aggregate (ownership-filtered ingest, rf=2), scored by
      consistent-hash scatter-gather.

    Gate: sharded score p99 must stay within **1.15x** of the baseline —
    parallel fan-out, the ring-plan cache, and chunk early exit must hide
    the partitioning rather than tax the score hot path.

    The workload is the long-context regime sharding exists for (256
    blocks = 4096 tokens per prompt): each shard looks up and serializes
    ~1/N of the keys in parallel, so the big single-response tail the
    baseline pays is split across small messages. Fan-out runs as one
    chunk (``fanoutChunkBlocks: 0``) because every query is a full hit —
    chunked early exit only pays off on misses and has its own unit
    tests (tests/test_cluster_sharding.py).
    """
    from llmd_kv_cache_tpu.cluster.config import ClusterConfig
    from llmd_kv_cache_tpu.core import (
        ChunkedTokenDatabase,
        PodEntry,
        TokenProcessorConfig,
    )
    from llmd_kv_cache_tpu.cluster import ShardRouter
    from llmd_kv_cache_tpu.scoring.indexer import IndexerConfig
    from llmd_kv_cache_tpu.services.indexer_service import (
        IndexerService,
        serve,
    )

    BLOCKS, BSZ = 256, 16  # 4096-token prompts: 256 blocks of 16
    BASE_PROMPTS, QUERIES, WARMUP = 300, 200, 30
    rng = np.random.default_rng(7)

    def run_arm(n_shards: int, n_prompts: int, base_port: int) -> dict:
        addrs = [f"127.0.0.1:{base_port + i}" for i in range(n_shards)]
        rf = min(2, n_shards)
        tp = ChunkedTokenDatabase(TokenProcessorConfig(block_size_tokens=BSZ))
        # Unique leading token → every prompt owns a distinct key chain.
        prompts = [
            [base_port + j * 131071] + list(range(1, BLOCKS * BSZ))
            for j in range(n_prompts)
        ]
        services, servers = [], []
        try:
            for addr in addrs:
                cc = None
                if n_shards > 1:
                    cc = ClusterConfig(
                        shard_addresses=addrs, shard_id=addr,
                        replication_factor=rf,
                    )
                svc = IndexerService(IndexerConfig(
                    token_processor_config=TokenProcessorConfig(
                        block_size_tokens=BSZ),
                    cluster_config=cc,
                ))
                services.append(svc)
                servers.append(serve(addr, svc))
            # Broadcast ingest (the event stream every replica sees);
            # ShardFilterIndex keeps each replica at owned keys only.
            total_keys = 0
            for j, prompt in enumerate(prompts):
                keys = tp.tokens_to_kv_block_keys(0, prompt, MODEL_NAME)
                pod = [PodEntry(pod_identifier=f"pod-{j % 8}",
                                device_tier="gpu")]
                for svc in services:
                    (svc.shard_index or svc.indexer.kv_block_index).add(
                        None, keys, pod)
                total_keys += len(keys)
            router = ShardRouter(
                ClusterConfig(shard_addresses=addrs, replication_factor=rf,
                              fanout_chunk_blocks=0),
                token_processor_config=TokenProcessorConfig(
                    block_size_tokens=BSZ),
            )
            try:
                picks = rng.integers(n_prompts, size=QUERIES + WARMUP)
                lat, rpcs = [], 0
                for i, j in enumerate(picks):
                    t0 = time.perf_counter()
                    res = router.score(prompts[int(j)], MODEL_NAME)
                    dt = time.perf_counter() - t0
                    assert res.hit_blocks == BLOCKS and not res.degraded
                    if i >= WARMUP:
                        lat.append(dt)
                        rpcs += res.rpcs
                plan = router.debug_view()["plan_cache"]
            finally:
                router.close()
            return {
                "index_keys_total": total_keys,
                # Owned (post-filter) writes per replica: shows the ring
                # spreading the 4x population, ~rf/N of the keys each.
                "per_replica_owned_keys": [
                    svc.shard_index.owned_writes for svc in services
                ] if n_shards > 1 else [total_keys],
                "score_p50_us": round(
                    statistics.median(lat) * 1e6, 1),
                "score_p99_us": round(
                    float(np.quantile(lat, 0.99)) * 1e6, 1),
                "rpcs_per_score": round(rpcs / QUERIES, 2),
                "plan_cache_hit_rate": round(
                    plan["hits"] / max(plan["hits"] + plan["misses"], 1), 4),
            }
        finally:
            for server in servers:
                server.stop(grace=0)

    baseline = run_arm(1, BASE_PROMPTS, 15930)
    sharded = run_arm(shards, shards * BASE_PROMPTS, 15940)
    ratio = sharded["score_p99_us"] / max(baseline["score_p99_us"], 1e-9)
    return {
        "metric": f"scatter-gather score p99 vs single shard "
                  f"({shards} shards, {shards}x index size, rf=2)",
        "value": round(ratio, 3),
        "unit": "x single-shard p99",
        "vs_baseline": 1.15,
        "gate_ok": bool(ratio <= 1.15),
        "shards": shards,
        "baseline": baseline,
        "sharded": sharded,
    }


def bench_graytail(shards: int = 4) -> dict:
    """Gray-failure tail-tolerance gate (``--graytail``, PR 16).

    One 4-shard gRPC fleet (rf=2) scored through
    :class:`~llmd_kv_cache_tpu.cluster.router.ShardRouter`, three phases:

    - **healthy** — all shards fast; measures the baseline score p50/p99
      and warms every shard's hedge-trigger latency quantile.
    - **graytail** — ONE shard is delayed 10x the healthy score p50 via a
      seeded ``delay`` failpoint (``services.indexer.lookup.<shard>``):
      slow, not dead — every RPC still succeeds, so breakers must stay
      closed and hedged fan-out to the rf=2 replica owner must keep the
      score p99 within **2x** of the healthy baseline.
    - **deadline** — the same slowed fleet queried under a deliberately
      impossible ambient deadline: every response that overruns it must
      be shed (``DeadlineExceeded``) or flagged degraded — never
      silently late.

    The perf-sentinel headline is the *healthy-path* hedging overhead:
    the per-RPC bookkeeping (latency-quantile observe + trigger read +
    budget refill) the hedging machinery adds to every score even when
    nothing is slow. Gate: < 1% of the healthy score p50.
    """
    from llmd_kv_cache_tpu.cluster.config import ClusterConfig
    from llmd_kv_cache_tpu.cluster import ShardRouter
    from llmd_kv_cache_tpu.core import (
        ChunkedTokenDatabase,
        PodEntry,
        TokenProcessorConfig,
    )
    from llmd_kv_cache_tpu.resilience import Deadline, DeadlineExceeded
    from llmd_kv_cache_tpu.resilience.deadline import deadline_scope
    from llmd_kv_cache_tpu.resilience.failpoints import failpoints
    from llmd_kv_cache_tpu.resilience.hedging import (
        HedgeBudget,
        LatencyQuantileTracker,
    )
    from llmd_kv_cache_tpu.scoring.indexer import IndexerConfig
    from llmd_kv_cache_tpu.services.indexer_service import (
        FP_SHARD_LOOKUP,
        IndexerService,
        serve,
    )

    # Long-context regime (4096-token prompts, as bench_shard_fanout):
    # per-RPC service time must dominate localhost scheduling jitter or
    # the p99 ratio gate measures noise, not tail tolerance. For the same
    # reason the two arms are measured as interleaved time segments
    # (paired sampling): a noisy-neighbor burst lands on both arms
    # instead of flipping the ratio's sign.
    BLOCKS, BSZ = 256, 16
    PROMPTS, WARMUP, SEGS, SEG_Q, DEADLINE_Q = 64, 60, 8, 35, 10
    PACE_S = 0.004  # identical open-loop pacing for both arms
    # Demand is ~1 hedge per fleet-wide fan-out when 1/4 shards is slow
    # (~0.25/primary), so the budget is sized above demand; the bench
    # asserts the *measured* hedge rate stays under it.
    HEDGE_RATE, HEDGE_BURST = 0.35, 16.0
    rng = np.random.default_rng(16)
    base_port = 15960
    addrs = [f"127.0.0.1:{base_port + i}" for i in range(shards)]
    rf = 2
    tp = ChunkedTokenDatabase(TokenProcessorConfig(block_size_tokens=BSZ))
    prompts = [
        [base_port + j * 131071] + list(range(1, BLOCKS * BSZ))
        for j in range(PROMPTS)
    ]

    failpoints.reset(seed=1337)
    services, servers = [], []
    try:
        for addr in addrs:
            svc = IndexerService(IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size_tokens=BSZ),
                cluster_config=ClusterConfig(
                    shard_addresses=addrs, shard_id=addr,
                    replication_factor=rf,
                ),
            ))
            services.append(svc)
            servers.append(serve(addr, svc))
        for j, prompt in enumerate(prompts):
            keys = tp.tokens_to_kv_block_keys(0, prompt, MODEL_NAME)
            pod = [PodEntry(pod_identifier=f"pod-{j % 8}",
                            device_tier="gpu")]
            for svc in services:
                svc.shard_index.add(None, keys, pod)
        router = ShardRouter(
            ClusterConfig(
                shard_addresses=addrs, replication_factor=rf,
                fanout_chunk_blocks=0,
                hedge_budget_rate=HEDGE_RATE,
                hedge_budget_burst=HEDGE_BURST,
            ),
            token_processor_config=TokenProcessorConfig(
                block_size_tokens=BSZ),
        )
        try:
            def run_phase(n: int, record_from: int = 0, pace_s: float = 0.0):
                lat, hedges, rpcs, flagged = [], 0, 0, 0
                picks = rng.integers(PROMPTS, size=n)
                for i, j in enumerate(picks):
                    t0 = time.perf_counter()
                    res = router.score(prompts[int(j)], MODEL_NAME)
                    dt = time.perf_counter() - t0
                    assert res.hit_blocks == BLOCKS
                    if i >= record_from:
                        lat.append(dt)
                        hedges += res.hedges
                        rpcs += res.rpcs
                        flagged += int(res.degraded)
                    if pace_s:
                        time.sleep(pace_s)
                return lat, hedges, rpcs, flagged

            # Warmup: healthy traffic calibrates the slow delay and warms
            # the per-shard hedge quantiles past min_samples so gray
            # segments hedge from their first query.
            w_lat, _, w_rpcs, _ = run_phase(WARMUP, pace_s=PACE_S)
            slow_s = 10.0 * statistics.median(w_lat)
            fp_slow = f"{FP_SHARD_LOOKUP}.{addrs[1]}"

            # Paired measurement: alternate healthy / gray segments.
            h_lat, g_lat = [], []
            h_hedges = h_rpcs = g_hedges = g_rpcs = 0
            for seg in range(SEGS):
                gray = seg % 2 == 1
                if gray:
                    failpoints.arm(fp_slow, mode="delay", delay_s=slow_s)
                # Unrecorded lead-in so both arms measure steady state,
                # not the first queries after a boundary.
                run_phase(3, record_from=3, pace_s=PACE_S)
                lat, hedges, rpcs, _ = run_phase(SEG_Q, pace_s=PACE_S)
                if gray:
                    failpoints.disarm(fp_slow)
                    g_lat += lat
                    g_hedges += hedges
                    g_rpcs += rpcs
                    # Boundary drain: the slow shard's server is still
                    # working off delayed requests after disarm — settle
                    # it so the next healthy segment doesn't inherit the
                    # backlog.
                    time.sleep(2 * slow_s)
                else:
                    h_lat += lat
                    h_hedges += hedges
                    h_rpcs += rpcs
            h_p50 = statistics.median(h_lat)
            h_p99 = float(np.quantile(h_lat, 0.99))
            g_p99 = float(np.quantile(g_lat, 0.99))
            tail_ratio = g_p99 / max(h_p99, 1e-9)
            breakers = {s: b.state for s, b in router.breakers.items()}

            # Healthy-path hedging overhead: per-RPC bookkeeping cost.
            tracker = LatencyQuantileTracker(quantile=0.95)
            budget = HedgeBudget(rate=HEDGE_RATE, burst=HEDGE_BURST)
            N = 20000
            t0 = time.perf_counter()
            for _ in range(N):
                tracker.observe("s0", 0.001)
                tracker.value("s0")
                budget.on_primary()
            per_rpc = (time.perf_counter() - t0) / N
            rpcs_per_score = h_rpcs / max(len(h_lat), 1)
            overhead_pct = per_rpc * rpcs_per_score / h_p50 * 100.0
            assert overhead_pct < 1.0, (
                f"healthy-path hedging overhead {overhead_pct:.3f}% "
                f">= 1% of score p50")
            # Budget compliance: hedge *decisions* per primary, straight
            # from the token bucket — must stay within rate plus the
            # amortized burst credit.
            bstats = router.hedge_budget.stats()
            hedge_rate = bstats["hedges"] / max(bstats["primaries"], 1)
            hedge_rate_cap = HEDGE_RATE + (
                (HEDGE_BURST + 1.0) / max(bstats["primaries"], 1))

            # Phase 3: impossible deadline against the slowed fleet —
            # shed or flagged, never silently late.
            failpoints.arm(fp_slow, mode="delay", delay_s=slow_s)
            shed = late_flagged = late_unflagged = 0
            for j in rng.integers(PROMPTS, size=DEADLINE_Q):
                budget_s = 0.0005
                t0 = time.perf_counter()
                try:
                    with deadline_scope(Deadline.after(budget_s)):
                        res = router.score(prompts[int(j)], MODEL_NAME)
                except DeadlineExceeded:
                    shed += 1
                    continue
                if time.perf_counter() - t0 > budget_s:
                    if res.degraded or res.deadline_expired:
                        late_flagged += 1
                    else:
                        late_unflagged += 1
        finally:
            router.close()
    finally:
        failpoints.reset(seed=1337)
        for server in servers:
            server.stop(grace=0)

    gates = {
        "tail_ok": bool(tail_ratio <= 2.0),
        # Discriminating bound: an unhedged gather pays the full injected
        # delay on every score (all prompts touch the slow shard), so a
        # hedged p99 at half the delay proves hedges actually carried it.
        "tail_vs_injected_ok": bool(g_p99 <= 0.5 * slow_s),
        "overhead_ok": bool(overhead_pct < 1.0),
        "hedge_budget_ok": bool(hedge_rate <= hedge_rate_cap),
        "breakers_ok": all(s == "closed" for s in breakers.values()),
        "deadline_ok": late_unflagged == 0,
        "hedged_at_all": g_hedges > 0,
    }
    return {
        "metric": "healthy-path hedging bookkeeping overhead "
                  f"({shards} shards, rf=2; gray arm: 1 shard 10x slow)",
        "value": round(overhead_pct, 4),
        "unit": "% of score p50",
        "vs_baseline": 1.0,
        "gate_ok": all(gates.values()),
        "gates": gates,
        "healthy": {
            "score_p50_us": round(h_p50 * 1e6, 1),
            "score_p99_us": round(h_p99 * 1e6, 1),
            "rpcs_per_score": round(rpcs_per_score, 2),
            "hedges": h_hedges,
        },
        "graytail": {
            "slow_shard": addrs[1],
            "injected_delay_ms": round(slow_s * 1e3, 2),
            "score_p99_us": round(g_p99 * 1e6, 1),
            "tail_ratio_vs_healthy": round(tail_ratio, 3),
            "tail_gate": 2.0,
            "p99_vs_injected_delay": round(g_p99 / slow_s, 3),
            "hedge_rpcs": g_hedges,
            "hedge_decision_rate": round(hedge_rate, 4),
            "hedge_decision_cap": round(hedge_rate_cap, 4),
            "hedge_budget": bstats,
            "breakers": breakers,
        },
        "deadline": {
            "queries": DEADLINE_Q,
            "shed": shed,
            "late_flagged": late_flagged,
            "late_unflagged": late_unflagged,
        },
    }


def main(queued: bool = True) -> dict:
    """TTFT routing benchmark: service-time replay + open-loop QPS sweep.

    ``queued`` is retained for CLI compatibility; the sweep always runs
    (it reuses the measured service times, so it costs nothing extra).
    """
    import jax

    from llmd_kv_cache_tpu.core import TokenProcessorConfig
    from llmd_kv_cache_tpu.models import engine as engine_mod
    from llmd_kv_cache_tpu.models.llama import LlamaConfig
    from llmd_kv_cache_tpu.scoring import Indexer, IndexerConfig

    rng = np.random.default_rng(42)
    platform = jax.devices()[0].platform
    if platform == "tpu":
        # Production-shaped sizing: a ~0.9B-param model with 4k-token
        # shared prefixes, so a prefix hit skips real MXU work (measured
        # v5e: cold prefill 1.77 s vs 0.14 s on a hit — 12.8×). Tiny
        # models underestimate the routing win on a remote-dispatched
        # device because per-dispatch latency, identical for both arms,
        # buries the prefill compute a hit would skip.
        model_cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, num_layers=16,
            num_heads=16, num_kv_heads=8, head_dim=128,
            intermediate_size=5632, page_size=16,
        )
        wl_kw = dict(n_requests=48, n_prefixes=8, prefix_len=4096,
                     suffix_len=64, vocab=30000)
        # 768 pages/pod = 12k tokens ≈ 3 resident prefixes of the 8 —
        # capacity-constrained per pod (routing matters) while 8 pods fit
        # HBM: 8 × 768 MiB KV + 1.8 GiB params < 16 GiB v5e.
        pod_kw = dict(num_pages=768, max_pages_per_seq=272,
                      max_prefill_tokens=2048)
        # Every prefill bucket a partial prefix hit can produce: the full
        # prompt covers the 128-page chunk + 4-page tail; the shorter
        # lengths cover 8..64-page buckets (a partially evicted prefix
        # leaves a page-aligned remainder ≥ 4 pages). Unwarmed buckets
        # would compile 20-40 s INSIDE an arm's timed window.
        warm_lens = [4096 + 64, 1024, 512, 256, 128]
    else:
        model_cfg = LlamaConfig(
            vocab_size=8192, hidden_size=512, num_layers=4, num_heads=8,
            num_kv_heads=4, head_dim=128, intermediate_size=1408,
            page_size=16,
        )
        wl_kw = {}
        pod_kw = None
        warm_lens = [p * 16 for p in (1, 2, 4, 8, 16, 32)]
    # KVTPU_BENCH_FP8=1: fp8 (e4m3) KV pools at the SAME HBM byte budget
    # — 1-byte elements double num_pages, so each pod holds twice the
    # resident prefixes. This is the fp8 capacity story measured in the
    # benchmark's own unit (hit rate → TTFT), on top of the
    # decode-bandwidth halving the kernel probes measure.
    fp8_pods = os.environ.get("KVTPU_BENCH_FP8") == "1"
    if fp8_pods:
        pod_kw = dict(pod_kw) if pod_kw is not None else dict(DEFAULT_POD_KW)
        pod_kw["num_pages"] *= 2
        pod_kw["kv_cache_dtype"] = "f8_e4m3"
    # 8 pods — the reference's headline fleet size (73-capacity README).
    n_pods = 8
    workload = build_workload(rng, **wl_kw)

    def fresh_indexer():
        return Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size_tokens=model_cfg.page_size
                )
            )
        )

    # Warm the jit cache (prefill buckets + decode) so compile time doesn't
    # pollute TTFT for either arm.
    import sys as _sys
    _t0 = time.perf_counter()
    from llmd_kv_cache_tpu.models.llama import init_params as _init_params
    from llmd_kv_cache_tpu.models.llama import (
        maybe_fuse_params as _maybe_fuse_params)
    # Fused once here when the shape profits (fuse_profitable; the 0.9B
    # bench shape measured faster UNFUSED on the v5e); every fleet
    # shares this single tree (make_pods's fuse and the engines' are
    # no-ops on it).
    shared_params = _maybe_fuse_params(
        _init_params(jax.random.PRNGKey(0), model_cfg), model_cfg)
    warm_indexer = fresh_indexer()
    warm = make_pods(1, model_cfg, engine_mod, warm_indexer,
                     params=shared_params, pod_kw=pod_kw)["pod-0"]
    for wl in warm_lens:
        _tb = time.perf_counter()
        prompt = rng.integers(1, 8000, wl).tolist()
        warm.add_request(f"warm{wl}", prompt, max_new_tokens=1)
        print(f"[bench warm] len {wl}: "
              f"{time.perf_counter() - _tb:.1f}s", file=_sys.stderr, flush=True)
    # Warm the continuous-batching step path too (enqueue-side prefill
    # chunk + the padded batched-decode program the concurrent arms use).
    _tb = time.perf_counter()
    warm.enqueue("warmstep", rng.integers(1, 8000, 128).tolist(),
                 max_new_tokens=3)
    while warm.step():
        pass
    print(f"[bench warm] step path: {time.perf_counter() - _tb:.1f}s",
          file=_sys.stderr, flush=True)
    print(f"[bench warm] total {time.perf_counter() - _t0:.1f}s",
          file=_sys.stderr, flush=True)

    # Calibrate the fleet's all-cold capacity from a measured cold prefill
    # on the warmed pod so arrival rates are platform-honest.
    _tb = time.perf_counter()
    warm.add_request(
        "cal", rng.integers(1, 8000, wl_kw.get("prefix_len", 256)
                            + wl_kw.get("suffix_len", 32)).tolist(),
        max_new_tokens=1)
    d_cold = time.perf_counter() - _tb
    fleet_qps = n_pods / d_cold  # all-cold saturation rate
    print(f"[bench load] cold service {d_cold * 1e3:.0f}ms -> fleet "
          f"capacity {fleet_qps:.1f} req/s", file=_sys.stderr, flush=True)
    del warm

    # Arm 1: round-robin routing.
    rr_indexer = fresh_indexer()
    rr_pods = make_pods(n_pods, model_cfg, engine_mod, rr_indexer,
                        params=shared_params, pod_kw=pod_kw)
    rr_svc, rr_chosen, rr_hit, _ = run_replay(
        rr_pods, workload, router=lambda i, _p, names: names[i % len(names)],
        tag="round-robin",
    )
    del rr_pods

    # Arm 2: KV-cache-aware routing via the Indexer.
    kv_indexer = fresh_indexer()
    kv_pods = make_pods(n_pods, model_cfg, engine_mod, kv_indexer,
                        params=shared_params, pod_kw=pod_kw)
    kv_router = make_kv_router(kv_indexer)
    kv_svc, kv_chosen, kv_hit, _ = run_replay(
        kv_pods, workload, router=kv_router, tag="kv-aware")
    score_path = score_path_stats(kv_router, kv_indexer)
    del kv_pods

    # Arm 3 (storage tier): prefixes live on shared storage (served once by
    # a since-retired pod), HBM cold — admission restores instead of
    # recomputing. The end-value of the L7/L9 offload stack: a storage hit
    # must beat cold prefill. Default-on for the CPU backend; on the
    # tunneled TPU the D2H store pre-phase is tunnel-bound (~0.03 GB/s),
    # so it is opt-in via KVTPU_BENCH_STORAGE=1 until run on-host.
    import os as _os
    st_p50 = st_hit = None
    st_n = 0
    if platform != "tpu" or _os.environ.get("KVTPU_BENCH_STORAGE") == "1":
        st_restore_svc, st_hit, st_fleets = _storage_arm(
            model_cfg, engine_mod, fresh_indexer, shared_params,
            pod_kw, n_pods, wl_kw)
        if st_restore_svc:
            st_p50 = statistics.median(st_restore_svc)
            st_n = len(st_restore_svc)

    # QPS sweep (reference "Summary across QPS"): the measured service
    # times are fixed, so one replay per arm supports the whole open-loop
    # sweep in virtual time. Rates are capacity-relative multipliers.
    sweep = []
    for mult in (0.5, 0.75, 1.0, 1.25, 1.5, 2.0):
        qps = mult * fleet_qps
        arr = np.cumsum(
            np.random.default_rng(7).exponential(1.0 / qps, len(workload)))
        rr_t = queueing_ttfts(rr_svc, rr_chosen, arr)
        kv_t = queueing_ttfts(kv_svc, kv_chosen, arr)
        row = {
            "qps": round(qps, 2), "mult": mult,
            "rr_p50": round(statistics.median(rr_t), 4),
            "rr_p90": round(float(np.quantile(rr_t, 0.9)), 4),
            "kv_p50": round(statistics.median(kv_t), 4),
            "kv_p90": round(float(np.quantile(kv_t, 0.9)), 4),
        }
        row["reduction_pct"] = round(
            100.0 * (1.0 - row["kv_p50"] / row["rr_p50"]), 2)
        sweep.append(row)
        print(f"[bench sweep] {mult:4.2f}x capacity ({qps:6.2f} qps): "
              f"p50 rr {row['rr_p50']:.3f}s kv {row['kv_p50']:.3f}s "
              f"(-{row['reduction_pct']:.1f}%), "
              f"p90 rr {row['rr_p90']:.3f}s kv {row['kv_p90']:.3f}s",
              file=_sys.stderr, flush=True)

    # Concurrent open-loop arms (VERDICT r3 #3): re-serve the workload
    # through the continuous-batching scheduler with arrival-timed
    # admission and real decode load, so TTFTs include batching
    # interference — methodology check on the virtual-time FIFO model
    # above (same arrival seeds; fewer points, each re-serves the fleet).
    conc_sweep = []
    # On the tunneled TPU each concurrent fleet re-serves the workload at
    # real service times (~minutes): run the headline point plus one
    # light- and one over-load point; CPU sweeps three points.
    # KVTPU_BENCH_FULL=1 widens the on-chip sweep to 6 QPS points (the
    # reference capacity tables' grid); default keeps the driver's
    # end-of-round run inside its window.
    if platform == "tpu":
        conc_mults = ((0.5, 0.75, 1.0, 1.25, 1.5, 2.0)
                      if _os.environ.get("KVTPU_BENCH_FULL")
                      else (0.75, 1.25, 1.5))
    else:
        conc_mults = (0.75, 1.25, 2.0)
    for mult in conc_mults:
        qps = mult * fleet_qps
        arr = np.cumsum(
            np.random.default_rng(7).exponential(1.0 / qps, len(workload)))
        crr_indexer = fresh_indexer()
        crr_pods = make_pods(n_pods, model_cfg, engine_mod, crr_indexer,
                             params=shared_params, pod_kw=pod_kw)
        crr_t, crr_hit, crr_tps, _ = run_concurrent(
            crr_pods, workload, make_rr_router(), arr,
            tag=f"conc-rr {mult}x")
        del crr_pods
        ckv_indexer = fresh_indexer()
        ckv_pods = make_pods(n_pods, model_cfg, engine_mod, ckv_indexer,
                             params=shared_params, pod_kw=pod_kw)
        ckv_t, ckv_hit, ckv_tps, _ = run_concurrent(
            ckv_pods, workload, make_kv_router(ckv_indexer), arr,
            tag=f"conc-kv {mult}x")
        del ckv_pods
        crow = {
            "qps": round(qps, 2), "mult": mult,
            "rr_p50": round(statistics.median(crr_t), 4),
            "rr_p90": round(float(np.quantile(crr_t, 0.9)), 4),
            "kv_p50": round(statistics.median(ckv_t), 4),
            "kv_p90": round(float(np.quantile(ckv_t, 0.9)), 4),
            "rr_hit": round(crr_hit, 4), "kv_hit": round(ckv_hit, 4),
            # Sustained output throughput (decoded tok / virtual
            # makespan) — the reference capacity tables' headline unit.
            "rr_out_tok_s": round(crr_tps, 1),
            "kv_out_tok_s": round(ckv_tps, 1),
        }
        crow["reduction_pct"] = round(
            100.0 * (1.0 - crow["kv_p50"] / crow["rr_p50"]), 2)
        conc_sweep.append(crow)
        print(f"[bench conc ] {mult:4.2f}x capacity ({qps:6.2f} qps): "
              f"p50 rr {crow['rr_p50']:.3f}s kv {crow['kv_p50']:.3f}s "
              f"(-{crow['reduction_pct']:.1f}%), "
              f"p90 rr {crow['rr_p90']:.3f}s kv {crow['kv_p90']:.3f}s, "
              f"out tok/s rr {crow['rr_out_tok_s']:.0f} "
              f"kv {crow['kv_out_tok_s']:.0f}",
              file=_sys.stderr, flush=True)

    # Strategy matrix at the headline point — the reference's
    # 37-capacity report compares precise (this indexer) / default /
    # load-aware / random scheduling on one workload; rr and kv already
    # ran above, so two more fleets cover the matrix.
    strategy_comparison = {}
    head_conc = next((r for r in conc_sweep if r["mult"] == 1.25), None)
    if head_conc is not None:
        strategy_comparison["round_robin"] = {
            "p50": head_conc["rr_p50"], "p90": head_conc["rr_p90"],
            "hit": head_conc["rr_hit"],
            "out_tok_s": head_conc["rr_out_tok_s"]}
        strategy_comparison["kv_precise"] = {
            "p50": head_conc["kv_p50"], "p90": head_conc["kv_p90"],
            "hit": head_conc["kv_hit"],
            "out_tok_s": head_conc["kv_out_tok_s"]}
        arr = np.cumsum(np.random.default_rng(7).exponential(
            1.0 / (1.25 * fleet_qps), len(workload)))
        for strat, factory in (("random", make_random_router),
                               ("load_aware", make_load_router)):
            s_indexer = fresh_indexer()
            s_pods = make_pods(n_pods, model_cfg, engine_mod, s_indexer,
                               params=shared_params, pod_kw=pod_kw)
            s_t, s_hit, s_tps, _ = run_concurrent(
                s_pods, workload, factory(s_indexer), arr,
                tag=f"conc-{strat}")
            del s_pods
            strategy_comparison[strat] = {
                "p50": round(statistics.median(s_t), 4),
                "p90": round(float(np.quantile(s_t, 0.9)), 4),
                "hit": round(s_hit, 4), "out_tok_s": round(s_tps, 1)}
            print(f"[bench strat] {strat}: p50 "
                  f"{strategy_comparison[strat]['p50']:.3f}s hit "
                  f"{s_hit:.2f} out {s_tps:.0f} tok/s",
                  file=_sys.stderr, flush=True)

    # Decode-heavy arm (VERDICT r4 #6): the 8-token decodes above make
    # "out tok/s" mostly prefill amortization; the reference capacity
    # tables report ITL mean alongside TTFT (73-capacity README "ITL
    # mean 0.026 s"). Re-serve the headline point with long decodes and
    # report ITL (inter-token gap) and TPOT (per-request mean) per
    # strategy. KVTPU_BENCH_DECODE_TOKENS overrides the depth.
    decode_heavy = {}
    decode_tokens = int(_os.environ.get(
        "KVTPU_BENCH_DECODE_TOKENS", 96 if platform == "tpu" else 24))
    if decode_tokens > 1:
        arr = np.cumsum(np.random.default_rng(7).exponential(
            1.0 / (1.25 * fleet_qps), len(workload)))
        dh_strategies = (("kv_precise", make_kv_router),
                         ("round_robin", make_rr_router),
                         ("load_aware", make_load_router),
                         ("random", make_random_router))
        for strat, factory in dh_strategies:
            d_indexer = fresh_indexer()
            d_pods = make_pods(n_pods, model_cfg, engine_mod, d_indexer,
                               params=shared_params, pod_kw=pod_kw)
            d_t, d_hit, d_tps, d_dec = run_concurrent(
                d_pods, workload, factory(d_indexer), arr,
                max_new_tokens=decode_tokens, tag=f"decode-{strat}")
            del d_pods
            itl, tpot = d_dec["itl"], d_dec["tpot"]
            decode_heavy[strat] = {
                "ttft_p50": round(statistics.median(d_t), 4),
                "itl_p50": round(statistics.median(itl), 5) if itl else None,
                "itl_p90": round(float(np.quantile(itl, 0.9)), 5)
                           if itl else None,
                "tpot_p50": round(statistics.median(tpot), 5)
                            if tpot else None,
                "tpot_p90": round(float(np.quantile(tpot, 0.9)), 5)
                            if tpot else None,
                "hit": round(d_hit, 4), "out_tok_s": round(d_tps, 1)}
            row = decode_heavy[strat]
            print(f"[bench decode] {strat}: ttft p50 {row['ttft_p50']:.3f}s "
                  f"itl p50 {row['itl_p50']}s p90 {row['itl_p90']}s "
                  f"out {row['out_tok_s']:.0f} tok/s",
                  file=_sys.stderr, flush=True)
        decode_heavy["max_new_tokens"] = decode_tokens

    # Headline: the 1.25×-capacity point, from the CONCURRENT
    # continuous-batching arm when it ran — measured TTFTs under real
    # batching interference and decode load, matching how the
    # reference's headline tables are produced (real inference-perf
    # serving, 73-capacity README). The virtual-time FIFO model stays in
    # the payload as the fast methodology-comparison arm; it
    # under-credits routing once prefill is fast (cold prefills cost
    # little when nothing else is running) and over-credits it at
    # saturation, so the served number is the honest one.
    head = next((r for r in conc_sweep if r["mult"] == 1.25), None)
    if head is not None:
        head_tag = "concurrent continuous batching"
        head_kv_hit, head_rr_hit = head["kv_hit"], head["rr_hit"]
    else:
        head = next(r for r in sweep if r["mult"] == 1.25)
        head_tag = "virtual-time replay"
        head_kv_hit, head_rr_hit = kv_hit, rr_hit
    reduction_pct = head["reduction_pct"]
    p50_rr, p50_kv = head["rr_p50"], head["kv_p50"]

    storage = ""
    if st_p50 is not None:
        cold_p50 = statistics.median(rr_svc)
        storage = (f", storage-restore p50 {st_p50:.3f}s vs cold "
                   f"{cold_p50:.3f}s (N={st_n}, {st_fleets} cold fleets, "
                   f"hit-rate {st_hit:.2f})")
    line = {
        "metric": "p50 TTFT reduction, KV-aware routing vs round-robin "
                  f"({n_pods} pods, shared-prefix {head_tag}, Poisson "
                  f"{head['qps']:.1f} req/s open-loop, p50 rr {p50_rr:.2f}s "
                  f"vs kv {p50_kv:.3f}s, hit-rate kv {head_kv_hit:.2f} vs rr "
                  f"{head_rr_hit:.2f}{storage}, "
                  f"{jax.devices()[0].platform}"
                  f"{', fp8 2x-page pools' if fp8_pods else ''})",
        "value": round(reduction_pct, 2),
        "unit": "%",
        "vs_baseline": round(reduction_pct / 40.0, 3),
        # Headline-arm hit rates (match `value`/`metric`); the serial
        # replay arm's are kept under replay_* so consumers never mix
        # measurement arms.
        "hit_rate_kv": round(head_kv_hit, 4),
        "hit_rate_rr": round(head_rr_hit, 4),
        "replay_hit_rate_kv": round(kv_hit, 4),
        "replay_hit_rate_rr": round(rr_hit, 4),
        "qps_sweep": sweep,
        "concurrent_sweep": conc_sweep,
        "strategy_comparison": strategy_comparison,
        # Scheduler-side overhead of the serial replay's KV arm:
        # score_tokens latency and prefix-cache effectiveness.
        "score_path": score_path,
    }
    if decode_heavy:
        line["decode_heavy"] = decode_heavy
    if st_p50 is not None:
        line["storage_restore_p50_s"] = round(st_p50, 4)
        line["storage_hit_rate"] = round(st_hit, 4)
        line["storage_restore_samples"] = st_n
    return line


def _storage_arm(model_cfg, engine_mod, fresh_indexer, shared_params,
                 pod_kw, n_pods, wl_kw, min_restores=50, max_fleets=4):
    """Measure restore-from-shared-storage service times.

    A 'historic' pod serves every unique prefix once with write-through
    offload, flushes, and retires; fresh KV-routed fleets sharing the
    storage root then replay the workload — admissions hit the storage
    tier (`offload/manager.py` lookup → restore) instead of recomputing.
    Mirrors the reference's medium-tier weights
    (`pkg/kvcache/backend.go:19-33`: storage hits are worth routing to).

    Sample-size hardening (VERDICT r3 weak #3): the arm builds its own
    workload with ≥32 unique prefixes and replays it on repeated COLD
    fleets until at least ``min_restores`` genuine restore admissions are
    collected — a p50 over ≥50 points instead of 8.

    Returns ``(restore_services, hit_rate, fleets)`` where
    restore_services covers ONLY the requests actually served by a
    storage restore — the first touch of each prefix on a cold pod.
    Later requests for the same prefix are ordinary HBM hits and would
    dilute the restore number.
    """
    import shutil
    import sys as _sys
    import tempfile

    from llmd_kv_cache_tpu.offload.spec import SharedStorageOffloadSpec

    root = tempfile.mkdtemp(prefix="bench-storage-")

    def spec():
        # The spec dtype must match the pods' KV pool dtype (fingerprint
        # field; the engine refuses a mismatch) — fp8 pods under
        # KVTPU_BENCH_FP8 store 1-byte blocks.
        kv_dtype = {"f8_e4m3": "float8_e4m3fn"}.get(
            (pod_kw or {}).get("kv_cache_dtype"), "bfloat16")
        return SharedStorageOffloadSpec(
            root=root, model_name=MODEL_NAME, page_size=model_cfg.page_size,
            num_layers=model_cfg.num_layers, kv_heads=model_cfg.num_kv_heads,
            head_dim=model_cfg.head_dim, io_threads=4,
            parallel_agnostic=True, dtype=kv_dtype,
        )

    st_kw = dict(wl_kw)
    st_kw["n_prefixes"] = max(32, st_kw.get("n_prefixes", 8))
    workload = build_workload(np.random.default_rng(1234), **st_kw)

    try:
        indexer = fresh_indexer()
        historic = make_pods(1, model_cfg, engine_mod, indexer,
                             params=shared_params, pod_kw=pod_kw,
                             offload_spec_factory=spec)["pod-0"]
        seen = set()
        for i, prompt in enumerate(workload):
            key = tuple(prompt[:64])
            if key in seen:
                continue
            seen.add(key)
            historic.add_request(f"hist{i}", prompt, max_new_tokens=1)
            historic.flush_offload()
        del historic
        print(f"[bench storage] {len(seen)} prefixes stored to {root}",
              file=_sys.stderr, flush=True)

        restore_services: list = []
        fleet_hits: list = []
        fleets = 0
        while len(restore_services) < min_restores and fleets < max_fleets:
            fleets += 1
            st_indexer = fresh_indexer()
            pods = make_pods(n_pods, model_cfg, engine_mod, st_indexer,
                             params=shared_params, pod_kw=pod_kw,
                             offload_spec_factory=spec)
            services, chosen, fleet_hit, cached = run_replay(
                pods, workload, make_kv_router(st_indexer),
                tag=f"storage-restore fleet {fleets}")
            fleet_hits.append(fleet_hit)
            del pods
            # Restore-serving requests: first touch of a prefix on a pod
            # whose HBM cannot hold it yet, with cached tokens at
            # admission — those tokens can only have come from the
            # storage tier.
            touched: set = set()
            for i, prompt in enumerate(workload):
                pair = (chosen[i], tuple(prompt[:64]))
                if pair not in touched and cached[i] > 0:
                    restore_services.append(services[i])
                touched.add(pair)
            print(f"[bench storage] fleet {fleets}: "
                  f"{len(restore_services)} restore admissions so far",
                  file=_sys.stderr, flush=True)
        # Every fleet replays the same workload, so the mean of per-fleet
        # hit-rates is the token-weighted aggregate across all samples.
        hit = sum(fleet_hits) / max(len(fleet_hits), 1)
        return restore_services, hit, fleets
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_fleet_telemetry() -> dict:
    """Fleet-telemetry overhead gate (``--fleet-telemetry``, ISSUE 10).

    Span export rides every traced hot-path operation once a pod enables
    ``fleetTelemetry.spanExport``: each finished span costs one ring
    append (identity stamp + seq + evict-oldest). This gate asserts that
    cost stays <1% of the Python-path score p50 — the per-span microbench
    against the measured score path, like the flight-recorder gate, so
    the number is stable under scheduler noise.

    Also reported (informational): end-to-end score p50 with the
    recording exporter installed, wire-serialization throughput of a
    ``/debug/spans`` pull, and one collector assemble+critical-path round
    over the pulled spans.
    """
    import time

    from llmd_kv_cache_tpu.core.keys import PodEntry
    from llmd_kv_cache_tpu.scoring import Indexer
    from llmd_kv_cache_tpu.services.telemetry_collector import TraceAssembler
    from llmd_kv_cache_tpu.telemetry import (
        InMemorySpanExporter,
        RecordedSpan,
        install_span_exporter,
        set_process_identity,
        uninstall_span_exporter,
    )

    # -- ns/span: the exact export shape (lock + ring append; seq/identity
    # stamping is deferred to pull time). Steady state: the collector's
    # pull keeps the ring below capacity, so the gated cost is the
    # non-evicting append. The ring-full path (drop counter) only runs
    # when the collector has been gone long enough to fill the ring;
    # reported informationally below. ``map`` drives the loop at C level
    # so the interpreter's per-iteration bytecode is not billed to export.
    from collections import deque as _deque

    n_spans = 200_000
    exporter = InMemorySpanExporter(max_spans=n_spans)
    set_process_identity("bench-pod")
    spans = []
    for i in range(n_spans):
        s = RecordedSpan("llm_d.kv_cache.score_tokens",
                         trace_id=i + 1, span_id=i + 1, parent_span_id=None,
                         attributes={"model": "bench", "blocks": 64})
        s.end_time = s.start_time
        spans.append(s)
    sink = _deque(maxlen=0)
    start = time.perf_counter_ns()
    sink.extend(map(exporter.export, spans))
    ns_per_span = (time.perf_counter_ns() - start) / n_spans

    # Ring-full arm: every further export evicts the oldest and counts the
    # drop — the degraded regime with no collector pulling.
    start = time.perf_counter_ns()
    sink.extend(map(exporter.export, spans[:20_000]))
    ns_per_span_full = (time.perf_counter_ns() - start) / 20_000

    # -- score-path baseline (Python path: lookup + prefix scorer) --------
    indexer = Indexer()
    block = indexer.token_processor.block_size
    rng = np.random.default_rng(7)
    tokens = rng.integers(1, 30000, 16 * block).tolist()
    block_keys = indexer.compute_block_keys(tokens, "bench")
    entries = [PodEntry(f"pod-{i}", "gpu") for i in range(4)]
    indexer.kv_block_index.add(None, block_keys, entries)

    def score_p50_ns(n=2_000):
        samples = []
        for _ in range(n):
            t0 = time.perf_counter_ns()
            indexer.score_tokens(tokens, "bench")
            samples.append(time.perf_counter_ns() - t0)
        samples.sort()
        return samples[len(samples) // 2]

    score_p50_ns(n=500)  # warm caches
    baseline_ns = score_p50_ns()
    overhead_pct = 100.0 * ns_per_span / baseline_ns
    # Span export must stay invisible on the score hot path.
    assert overhead_pct < 1.0, (
        f"span export {ns_per_span:.0f} ns/span is "
        f"{overhead_pct:.2f}% of the {baseline_ns} ns score p50"
    )

    # -- informational: e2e recording-mode p50 + pull + assemble ----------
    live = install_span_exporter(InMemorySpanExporter(max_spans=10_000))
    try:
        score_p50_ns(n=500)  # warm the recording arm too
        recording_ns = score_p50_ns()
        t0 = time.perf_counter_ns()
        payload = live.export_since(-1)
        pull_ms = (time.perf_counter_ns() - t0) / 1e6
        assembler = TraceAssembler(idle_s=0.0)
        t0 = time.perf_counter_ns()
        assembler.ingest(payload["spans"])
        assembled = assembler.finalize_idle(force=True)
        assemble_ms = (time.perf_counter_ns() - t0) / 1e6
    finally:
        uninstall_span_exporter()
        set_process_identity(None)

    return {
        "metric": "span-export overhead on the score hot path "
                  "(Python path, 16-block prompt, 4 pods)",
        "value": round(overhead_pct, 4),
        "unit": "% of score p50",
        "vs_baseline": 1.0,
        "span_export_ns_per_span": round(ns_per_span, 1),
        "span_export_ns_per_span_ring_full": round(ns_per_span_full, 1),
        "score_p50_us": round(baseline_ns / 1e3, 1),
        "score_p50_recording_us": round(recording_ns / 1e3, 1),
        "spans_pulled": len(payload["spans"]),
        "debug_spans_pull_ms": round(pull_ms, 3),
        "traces_assembled": len(assembled),
        "assemble_critical_path_ms": round(assemble_ms, 3),
    }


def bench_pyprof_overhead() -> dict:
    """Sampling-profiler overhead gate (``--pyprof-overhead``, ISSUE 11).

    The continuous profiler steals ``pass_cost × hz`` of wall time from
    the program (one GIL-holding stack walk per period), so the expected
    sampler time inside any operation of duration T is ``T × pass_cost ×
    hz`` — its share of the score p50 *is* its CPU fraction. The gate
    asserts that fraction stays <1% from the measured per-pass cost,
    which is stable under scheduler noise (diffing p50 with/without the
    sampler would drown a sub-1% effect in jitter).

    Also reported: score p50 with the sampler actually running
    (informational cross-check) and the span-attributed hot-function
    shares that ``hack/perf_sentinel.py`` diffs against the committed
    baseline manifest.
    """
    import threading
    import time

    from llmd_kv_cache_tpu.core.keys import PodEntry
    from llmd_kv_cache_tpu.scoring import Indexer
    from llmd_kv_cache_tpu.telemetry import (
        InMemorySpanExporter,
        SamplingProfiler,
        SamplingProfilerConfig,
        install_span_exporter,
        merge_folded,
        set_process_identity,
        span_function_shares,
        uninstall_span_exporter,
    )

    cfg = SamplingProfilerConfig(enabled=True, hz=67.0, window_s=3600.0)
    profiler = SamplingProfiler(cfg)

    # Score workload: same shape as the fleet-telemetry gate (16-block
    # prompt, 4 candidate pods, Python scoring path).
    indexer = Indexer()
    block = indexer.token_processor.block_size
    rng = np.random.default_rng(7)
    tokens = rng.integers(1, 30000, 16 * block).tolist()
    block_keys = indexer.compute_block_keys(tokens, "bench")
    entries = [PodEntry(f"pod-{i}", "gpu") for i in range(4)]
    indexer.kv_block_index.add(None, block_keys, entries)

    def score_p50_ns(n=2_000):
        samples = []
        for _ in range(n):
            t0 = time.perf_counter_ns()
            indexer.score_tokens(tokens, "bench")
            samples.append(time.perf_counter_ns() - t0)
        samples.sort()
        return samples[len(samples) // 2]

    score_p50_ns(n=500)  # warm caches
    baseline_ns = score_p50_ns()

    # -- pass cost, measured against a realistically busy process: score
    # traffic runs (traced) in a worker thread while passes are timed
    # here. These samples double as the hot-function profile below.
    set_process_identity("bench-pod")
    install_span_exporter(InMemorySpanExporter(max_spans=50_000))
    stop = threading.Event()

    def drive() -> None:
        while not stop.is_set():
            indexer.score_tokens(tokens, "bench")

    worker = threading.Thread(target=drive, name="bench-score", daemon=True)
    worker.start()
    try:
        costs = sorted(profiler.sample_once() for _ in range(1_000))
    finally:
        stop.set()
        worker.join(timeout=5.0)
    avg_cost_s = sum(costs) / len(costs)
    overhead_pct = avg_cost_s * cfg.hz * 100.0
    # The always-on sampler must stay invisible on the score hot path.
    assert overhead_pct < 1.0, (
        f"sampling pass costs {avg_cost_s * 1e6:.0f} us; at {cfg.hz:g} Hz "
        f"that is {overhead_pct:.2f}% of every second (and of the score "
        "p50)"
    )

    # -- informational: score p50 with the sampler thread live ------------
    profiler.start()
    try:
        sampled_ns = score_p50_ns()
    finally:
        profiler.stop()
        uninstall_span_exporter()
        set_process_identity(None)

    profiler.rotate(force=True)
    windows = profiler.export_since(-1)["windows"]
    shares = span_function_shares(
        merge_folded([w["folded"] for w in windows]))
    hot = {
        span: {
            "samples": entry["samples"],
            "functions": dict(list(entry["functions"].items())[:5]),
        }
        for span, entry in shares.items()
    }

    return {
        "metric": "sampling-profiler overhead on the score hot path "
                  "(pass-cost x hz model, 67 Hz)",
        "value": round(overhead_pct, 4),
        "unit": "% of score p50 (== sampler CPU fraction)",
        "vs_baseline": 1.0,
        "hz": cfg.hz,
        "pass_cost_us_avg": round(avg_cost_s * 1e6, 2),
        "pass_cost_us_p50": round(costs[len(costs) // 2] * 1e6, 2),
        "score_p50_us": round(baseline_ns / 1e3, 1),
        "score_p50_sampled_us": round(sampled_ns / 1e3, 1),
        "profile_samples": sum(w["samples"] for w in windows),
        "hot_functions": hot,
    }


def bench_workingset() -> dict:
    """Working-set sampler gates (``--workingset``, ISSUE 12).

    Two hard gates over ``telemetry/workingset.py``:

    1. **MRC accuracy** — the SHARDS-sampled miss-ratio curve must track
       an exact LRU stack-distance oracle within a bounded error on a
       seeded replay trace (zipf-ish popularity + sequential scan
       segments, the mix that makes naive LRU models lie). The oracle
       replays the same trace through a real most-recent-first stack, so
       the comparison is simulation-vs-estimate, not model-vs-model.
    2. **Overhead** — the hook the indexer runs per score call (a
       single batch enqueue; per-key work drains off the p50) must stay
       <1% of the Python-path score p50, same microbench-vs-p50 model
       as the span-export and pyprof gates.
    """
    import time

    from llmd_kv_cache_tpu.core.keys import PodEntry
    from llmd_kv_cache_tpu.scoring import Indexer
    from llmd_kv_cache_tpu.telemetry import (
        WorkingSetConfig,
        WorkingSetTracker,
        estimate_hit_ratio,
    )

    # -- replay trace: zipf-ish popularity over a warm universe, with
    # periodic sequential scans through one-touch keys (cold traffic that
    # must depress the curve at every capacity, not just the tail).
    # Skew is kept moderate (zipf 0.5 over 4k keys): SHARDS concentrates
    # when no single key owns a macroscopic share of accesses — with a
    # 0.9-exponent zipf the top key alone is ~9% of traffic and whether
    # it hashes into the sample swings the curve by that much.
    rng = np.random.default_rng(12)
    universe = 4096
    ranks = np.arange(1, universe + 1, dtype=np.float64)
    weights = 1.0 / ranks**0.5
    weights /= weights.sum()
    n_accesses = 40_000
    hot = rng.choice(universe, size=n_accesses, p=weights)
    trace: list = []
    scan_key = 1_000_000  # disjoint from the hot universe
    for i, k in enumerate(hot):
        trace.append(int(k))
        if i % 500 == 499:  # a 64-block one-touch scan every 500 accesses
            trace.extend(range(scan_key, scan_key + 64))
            scan_key += 64

    # -- exact oracle: true LRU stack distances (list.index is C-level,
    # so the O(depth) search stays cheap at this trace size).
    stack: list = []
    distances: list = []
    for k in trace:
        try:
            idx = stack.index(k)
        except ValueError:
            distances.append(None)  # cold: misses at every capacity
        else:
            distances.append(idx + 1)
            del stack[idx]
        stack.insert(0, k)
    capacities = (64, 128, 256, 512, 1024, 2048)
    n = len(trace)

    def oracle_hit_ratio(cap: int) -> float:
        return sum(1 for d in distances if d is not None and d <= cap) / n

    # -- estimator arms: the gated sampled tracker plus a rate-1.0 arm
    # that isolates bucket-quantization error from sampling error.
    def estimate_curve(rate: float) -> dict:
        tracker = WorkingSetTracker(WorkingSetConfig(
            enabled=True, sample_rate=rate, window_s=3600.0,
            max_tracked_blocks=4 * universe))
        for i in range(0, n, 64):
            tracker.record_accesses("hbm", trace[i:i + 64])
        tracker.rotate(force=True)
        window = tracker.export_since(-1)["windows"][-1]
        st = window["scopes"]["hbm"]
        return {cap: estimate_hit_ratio(st["hist"], st["cold"], cap)
                for cap in capacities}

    sample_rate = 0.2
    sampled_curve = estimate_curve(sample_rate)
    exact_rate_curve = estimate_curve(1.0)
    oracle_curve = {cap: oracle_hit_ratio(cap) for cap in capacities}
    mrc_err = max(abs(sampled_curve[c] - oracle_curve[c])
                  for c in capacities)
    quant_err = max(abs(exact_rate_curve[c] - oracle_curve[c])
                    for c in capacities)
    # 2^0.25 buckets bound quantization near 0.05 on this trace; the
    # sampling arm gets one more point of estimation noise on top.
    mrc_bound = 0.06
    assert mrc_err <= mrc_bound, (
        f"sampled MRC (rate {sample_rate:g}) is off by {mrc_err:.4f} "
        f"from the exact-simulation oracle (bound {mrc_bound:g}): "
        f"est {sampled_curve} vs oracle {oracle_curve}"
    )

    # -- score-path baseline (same workload as the other telemetry gates:
    # 16-block prompt, 4 candidate pods, Python scoring path).
    indexer = Indexer()
    block = indexer.token_processor.block_size
    trng = np.random.default_rng(7)
    tokens = trng.integers(1, 30000, 16 * block).tolist()
    block_keys = indexer.compute_block_keys(tokens, "bench")
    entries = [PodEntry(f"pod-{i}", "gpu") for i in range(4)]
    indexer.kv_block_index.add(None, block_keys, entries)

    def score_p50_ns(n_iter=2_000):
        samples = []
        for _ in range(n_iter):
            t0 = time.perf_counter_ns()
            indexer.score_tokens(tokens, "bench")
            samples.append(time.perf_counter_ns() - t0)
        samples.sort()
        return samples[len(samples) // 2]

    score_p50_ns(n_iter=500)  # warm caches
    baseline_ns = score_p50_ns()

    # -- per-score hook cost on the p50 path: the exact call the indexer
    # makes per score_tokens (one record_accesses over the prompt's
    # block keys). The hook is a single deque append; the per-key work
    # drains on every 128th call, which lands in the tail, not the p50 —
    # so the gated number is the steady-state enqueue cost, measured
    # with drains forced outside the timed region. The amortized cost
    # including drains is reported (and self-reported at runtime via
    # kvtpu_workingset_overhead_seconds_total).
    hook_tracker = WorkingSetTracker(WorkingSetConfig(
        enabled=True, sample_rate=0.05, window_s=3600.0))
    hook_tracker.record_accesses("index", block_keys)  # warm filter memo
    hook_tracker._drain()
    rounds, per_round = 200, 100  # per_round < the drain threshold
    steady_ns = 0
    for _ in range(rounds):
        t0 = time.perf_counter_ns()
        for _ in range(per_round):
            hook_tracker.record_accesses("index", block_keys)
        steady_ns += time.perf_counter_ns() - t0
        hook_tracker._drain()
    hook_ns = steady_ns / (rounds * per_round)
    n_calls = 20_000
    t0 = time.perf_counter_ns()
    for _ in range(n_calls):
        hook_tracker.record_accesses("index", block_keys)
    amortized_ns = (time.perf_counter_ns() - t0) / n_calls
    overhead_pct = 100.0 * hook_ns / baseline_ns
    # The always-on sampler must stay invisible on the score hot path.
    assert overhead_pct < 1.0, (
        f"workingset hook costs {hook_ns:.0f} ns per {len(block_keys)}-key "
        f"score call — {overhead_pct:.2f}% of the {baseline_ns} ns score "
        "p50"
    )

    # -- informational: e2e score p50 with the tracker actually attached.
    indexer.attach_workingset(hook_tracker)
    try:
        attached_ns = score_p50_ns()
    finally:
        indexer.workingset = None

    return {
        "metric": "working-set sampler: MRC error vs exact oracle + hook "
                  "overhead on the score hot path",
        "value": round(overhead_pct, 4),
        "unit": "% of score p50",
        "vs_baseline": 1.0,
        "sample_rate": sample_rate,
        "trace_accesses": n,
        "mrc_max_abs_error": round(mrc_err, 4),
        "mrc_error_bound": mrc_bound,
        "mrc_quantization_error_rate1": round(quant_err, 4),
        "mrc_sampled": {str(c): round(v, 4)
                        for c, v in sampled_curve.items()},
        "mrc_oracle": {str(c): round(v, 4)
                       for c, v in oracle_curve.items()},
        "hook_ns_per_score": round(hook_ns, 1),
        "hook_ns_per_score_amortized": round(amortized_ns, 1),
        "score_p50_us": round(baseline_ns / 1e3, 1),
        "score_p50_tracked_us": round(attached_ns / 1e3, 1),
    }


def bench_audit() -> dict:
    """Ground-truth audit hook overhead gate (``--audit``, ISSUE 18).

    The audit plane adds exactly one hook to the score hot path: when an
    ``AuditLog`` is attached, ``Indexer._record_score_decision`` appends
    one prediction record (dict build + ring append under a small lock)
    per score call. Same microbench-vs-p50 model as the flight-recorder,
    pyprof, and workingset gates: measure the hook in isolation, gate it
    <1% of the Python-path score p50, and report the e2e attached p50 as
    an informational cross-check. The engine-side outcome hook runs once
    per *request* (at prefill completion), not per score, so it is
    reported but not gated against the score p50.
    """
    import time

    from llmd_kv_cache_tpu.core.keys import PodEntry
    from llmd_kv_cache_tpu.scoring import Indexer
    from llmd_kv_cache_tpu.telemetry.audit import AuditLog

    # -- score-path baseline (same workload as the other telemetry gates:
    # 16-block prompt, 4 candidate pods, Python scoring path).
    indexer = Indexer()
    block = indexer.token_processor.block_size
    trng = np.random.default_rng(7)
    tokens = trng.integers(1, 30000, 16 * block).tolist()
    block_keys = indexer.compute_block_keys(tokens, "bench")
    entries = [PodEntry(f"pod-{i}", "gpu") for i in range(4)]
    indexer.kv_block_index.add(None, block_keys, entries)

    def score_p50_ns(n_iter=2_000):
        samples = []
        for _ in range(n_iter):
            t0 = time.perf_counter_ns()
            indexer.score_tokens(tokens, "bench")
            samples.append(time.perf_counter_ns() - t0)
        samples.sort()
        return samples[len(samples) // 2]

    score_p50_ns(n_iter=500)  # warm caches
    baseline_ns = score_p50_ns()

    # -- the per-score hook in isolation: the exact record_prediction
    # call _record_score_decision makes, with a service-realistic
    # staleness_fn wired (it runs on every append). The ring is sized at
    # the default capacity so steady state exercises eviction, the
    # worst case (append + del of the evicted slice).
    log = AuditLog(staleness_fn=lambda: 0.25)
    scores = {f"pod-{i}": float(4 - i) for i in range(4)}
    traceparent = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    n_calls = 20_000
    log.record_prediction(traceparent, "bench", 16, 4.0, scores, None)
    t0 = time.perf_counter_ns()
    for _ in range(n_calls):
        log.record_prediction(traceparent, "bench", 16, 4.0, scores, None)
    hook_ns = (time.perf_counter_ns() - t0) / n_calls
    overhead_pct = 100.0 * hook_ns / baseline_ns
    # The audit plane must stay invisible on the score hot path.
    assert overhead_pct < 1.0, (
        f"audit prediction hook costs {hook_ns:.0f} ns per score call — "
        f"{overhead_pct:.2f}% of the {baseline_ns} ns score p50"
    )

    # -- informational: the once-per-request outcome append.
    t0 = time.perf_counter_ns()
    for i in range(n_calls):
        log.record_outcome(traceparent, f"r{i}", "pod-0", 16, 12, 2, 2)
    outcome_ns = (time.perf_counter_ns() - t0) / n_calls

    # -- informational: e2e score p50 with the log actually attached.
    indexer.attach_audit(log)
    try:
        attached_ns = score_p50_ns()
    finally:
        indexer.audit = None

    return {
        "metric": "ground-truth audit hook overhead on the score hot path",
        "value": round(overhead_pct, 4),
        "unit": "% of score p50",
        "vs_baseline": 1.0,
        "hook_ns_per_score": round(hook_ns, 1),
        "outcome_ns_per_request": round(outcome_ns, 1),
        "score_p50_us": round(baseline_ns / 1e3, 1),
        "score_p50_audited_us": round(attached_ns / 1e3, 1),
        "ring_dropped": log.debug_view()["dropped"],
    }


def bench_fencing() -> dict:
    """Epoch-fence overhead gate (``--fencing``, ISSUE 19).

    The membership plane adds exactly one check to each serving hot
    path: ``MembershipTable.check_request`` (score/lookup fences — an
    epoch compare under the table lock) and ``check_write`` (event
    ingest — the same plus a lease-validity read). Same
    microbench-vs-p50 model as the audit/flight-recorder/pyprof gates:
    measure the clean-path check in isolation, gate it <1% of the
    Python-path score p50, report the write-fence and the warn-mode
    rejection path as informational.
    """
    import time

    from llmd_kv_cache_tpu.cluster.membership import MembershipTable
    from llmd_kv_cache_tpu.core.keys import PodEntry
    from llmd_kv_cache_tpu.scoring import Indexer

    # -- score-path baseline (same workload as the other telemetry gates:
    # 16-block prompt, 4 candidate pods, Python scoring path).
    indexer = Indexer()
    block = indexer.token_processor.block_size
    trng = np.random.default_rng(7)
    tokens = trng.integers(1, 30000, 16 * block).tolist()
    block_keys = indexer.compute_block_keys(tokens, "bench")
    entries = [PodEntry(f"pod-{i}", "gpu") for i in range(4)]
    indexer.kv_block_index.add(None, block_keys, entries)

    def score_p50_ns(n_iter=2_000):
        samples = []
        for _ in range(n_iter):
            t0 = time.perf_counter_ns()
            indexer.score_tokens(tokens, "bench")
            samples.append(time.perf_counter_ns() - t0)
        samples.sort()
        return samples[len(samples) // 2]

    score_p50_ns(n_iter=500)  # warm caches
    baseline_ns = score_p50_ns()

    # -- the per-request fence in isolation: the exact check the score
    # and lookup RPC handlers make on every request, on the clean path
    # (same-epoch stamp — what every request pays in steady state).
    table = MembershipTable()
    table.grant("pod-0")
    epoch = table.epoch
    n_calls = 20_000
    table.check_request(epoch, "score")
    t0 = time.perf_counter_ns()
    for _ in range(n_calls):
        table.check_request(epoch, "score")
    hook_ns = (time.perf_counter_ns() - t0) / n_calls
    overhead_pct = 100.0 * hook_ns / baseline_ns
    # The fence must stay invisible on the score hot path.
    assert overhead_pct < 1.0, (
        f"epoch fence check costs {hook_ns:.0f} ns per score call — "
        f"{overhead_pct:.2f}% of the {baseline_ns} ns score p50"
    )

    # -- informational: the ingest write fence (lease read + epoch check,
    # once per event batch) and the warn-mode stale-stamp path (metric +
    # flight record + bounded ring — only paid by fenced traffic).
    t0 = time.perf_counter_ns()
    for _ in range(n_calls):
        table.check_write("pod-0", epoch, "events.ingest")
    write_ns = (time.perf_counter_ns() - t0) / n_calls
    table.observe_epoch(epoch + 1, source="bench")
    n_reject = 2_000
    t0 = time.perf_counter_ns()
    for _ in range(n_reject):
        table.check_request(epoch, "score")
    reject_ns = (time.perf_counter_ns() - t0) / n_reject

    return {
        "metric": "epoch-fence check overhead on the score hot path",
        "value": round(overhead_pct, 4),
        "unit": "% of score p50",
        "vs_baseline": 1.0,
        "hook_ns_per_score": round(hook_ns, 1),
        "write_fence_ns_per_batch": round(write_ns, 1),
        "stale_reject_ns": round(reject_ns, 1),
        "score_p50_us": round(baseline_ns / 1e3, 1),
    }


def bench_incident() -> dict:
    """Incident black-box trigger-hook overhead gate (``--incident``,
    ISSUE 20).

    The incident plane touches the serving path in exactly one place:
    every alert/anomaly edge calls ``IncidentManager.maybe_open`` — one
    lock, a cooldown-table read, and (on the rare accepted edge) a
    thread handoff; the evidence fan-out and the bundle write run on the
    detached worker. Same microbench-vs-p50 model as the audit/fencing
    gates: measure the steady-state (cooldown-suppressed) trigger hook
    in isolation, gate it <1% of the Python-path score p50, and prove
    the bundle write is off the hot path by comparing the accepted-edge
    return latency against the full synchronous capture duration.
    """
    import json as _json
    import tempfile
    import time

    from llmd_kv_cache_tpu.core.keys import PodEntry
    from llmd_kv_cache_tpu.scoring import Indexer
    from llmd_kv_cache_tpu.telemetry.incident import (
        IncidentConfig,
        IncidentManager,
        load_bundle,
    )

    # -- score-path baseline (same workload as the other telemetry gates:
    # 16-block prompt, 4 candidate pods, Python scoring path).
    indexer = Indexer()
    block = indexer.token_processor.block_size
    trng = np.random.default_rng(7)
    tokens = trng.integers(1, 30000, 16 * block).tolist()
    block_keys = indexer.compute_block_keys(tokens, "bench")
    entries = [PodEntry(f"pod-{i}", "gpu") for i in range(4)]
    indexer.kv_block_index.add(None, block_keys, entries)

    def score_p50_ns(n_iter=2_000):
        samples = []
        for _ in range(n_iter):
            t0 = time.perf_counter_ns()
            indexer.score_tokens(tokens, "bench")
            samples.append(time.perf_counter_ns() - t0)
        samples.sort()
        return samples[len(samples) // 2]

    score_p50_ns(n_iter=500)  # warm caches
    baseline_ns = score_p50_ns()

    # -- a 4-pod fleet behind a canned in-process transport: evidence
    # payloads sized like a busy pod (full default flight tail, a span
    # window) so the fan-out + bundle-write cost is realistic.
    flight = _json.dumps({
        "records": [{"seq": i, "ts": 1000.0 + i * 0.01, "mono": i * 0.01,
                     "kind": "score", "data": {"i": i}}
                    for i in range(512)],
        "next_seq": 511, "dropped": 0,
    }).encode()
    spans = _json.dumps({
        "spans": [{"name": "llm_d.kv_cache.score_tokens",
                   "start_time": 1000.0 + i * 0.01,
                   "end_time": 1000.001 + i * 0.01}
                  for i in range(256)],
        "next_seq": 255, "dropped": 0,
    }).encode()
    timeb = _json.dumps({"wall": 1000.0, "mono": 50.0, "pid": 1}).encode()

    def fetch(url: str) -> bytes:
        if "flight-recorder" in url:
            return flight
        if "/debug/spans" in url:
            return spans
        if "/debug/time" in url:
            return timeb
        raise OSError("404")  # remaining enrichment legs absent

    with tempfile.TemporaryDirectory() as tmp:
        mgr = IncidentManager(
            IncidentConfig(directory=tmp, cooldown_s=3600.0),
            fetch=fetch,
            targets=lambda: [(f"pod-{i}", f"10.0.0.{i}:9400", None)
                             for i in range(4)],
            local_evidence=lambda: {"rounds": 100},
        )

        # -- the accepted edge: maybe_open hands off to a worker thread
        # and returns. Its latency is what the scrape round actually
        # blocks on when an alert fires.
        t0 = time.perf_counter_ns()
        stub = mgr.maybe_open("slo:bench", {"why": "bench"})
        accept_ns = time.perf_counter_ns() - t0
        assert stub is not None and stub.get("state") == "capturing", stub
        mgr.wait()
        assert accept_ns < 50e6, (
            f"accepted-edge return took {accept_ns / 1e6:.1f} ms"
        )

        # -- the steady-state hook: every further edge inside the
        # cooldown window pays one lock + dict lookup. This is the cost
        # the edge stream pays per scrape round, so it is the gated
        # value.
        n_calls = 20_000
        t0 = time.perf_counter_ns()
        for _ in range(n_calls):
            mgr.maybe_open("slo:bench", {"why": "bench"})
        hook_ns = (time.perf_counter_ns() - t0) / n_calls
        overhead_pct = 100.0 * hook_ns / baseline_ns
        # The trigger hook must stay invisible on the serving path.
        assert overhead_pct < 1.0, (
            f"incident trigger hook costs {hook_ns:.0f} ns per edge — "
            f"{overhead_pct:.2f}% of the {baseline_ns} ns score p50"
        )

        # -- informational: the full fan-out + bundle write, run
        # synchronously so it can be timed, then the bundle verified.
        summary = mgr.maybe_open(
            "slo:bench-sync", {"why": "bench"}, force=True,
            synchronous=True)
        assert summary and summary.get("path"), summary
        doc = load_bundle(summary["path"])
        assert len(doc["pods"]) == 4, sorted(doc["pods"])

        # -- proof the bundle write is off the hot path: a transport
        # stalled 20ms per leg (a realistic cross-pod HTTP fan-out) must
        # not delay the accepted edge's return at all.
        stall_s = 0.02

        def slow_fetch(url: str) -> bytes:
            time.sleep(stall_s)
            return fetch(url)

        slow = IncidentManager(
            IncidentConfig(directory=tmp, cooldown_s=3600.0),
            fetch=slow_fetch,
            targets=lambda: [(f"pod-{i}", f"10.0.0.{i}:9400", None)
                             for i in range(4)],
            local_evidence=lambda: {"rounds": 100},
        )
        t0 = time.perf_counter_ns()
        stub = slow.maybe_open("slo:bench-slow", {"why": "bench"})
        slow_accept_ns = time.perf_counter_ns() - t0
        assert stub is not None and stub.get("state") == "capturing", stub
        slow.wait(timeout=30.0)
        slow_summary = slow.debug_view()["recent"][-1]
        slow_capture_ns = slow_summary["capture_seconds"] * 1e9
        assert slow_capture_ns >= 4 * stall_s * 1e9, slow_summary
        assert slow_accept_ns < slow_capture_ns / 4, (
            f"accepted-edge latency {slow_accept_ns / 1e6:.1f} ms is not "
            f"off the hot path (stalled capture takes "
            f"{slow_capture_ns / 1e6:.1f} ms)"
        )

    return {
        "metric": "incident trigger hook overhead on the serving path",
        "value": round(overhead_pct, 4),
        "unit": "% of score p50",
        "vs_baseline": 1.0,
        "hook_ns_per_edge": round(hook_ns, 1),
        "accept_latency_us": round(accept_ns / 1e3, 1),
        "stalled_accept_latency_us": round(slow_accept_ns / 1e3, 1),
        "stalled_capture_ms": round(slow_capture_ns / 1e6, 3),
        "capture_ms": round(summary["capture_seconds"] * 1e3, 3),
        "bundle_bytes": summary["bytes"],
        "pods_captured": summary["pods_captured"],
        "score_p50_us": round(baseline_ns / 1e3, 1),
    }


def bench_disagg() -> dict:
    """Prefill/decode disaggregation vs a monolithic fleet (decode-heavy).

    Two arms over the same decode-heavy replay (short shared-prefix
    prompts, long generations — the regime where decode batching, not
    prefill compute, bounds throughput):

    - **baseline**: two monolithic (``role="both"``) pods behind the KV
      router, served through ``run_concurrent`` — prefill chunks stall
      the decode batch on every admission.
    - **disagg**: one ``role="prefill"`` pod streaming chunk-granular
      KV commits through a shared storage root, one ``role="decode"``
      pod admitting with ``enqueue(handoff=True)`` — the transferred
      prefix restores while earlier decodes keep batching, and the
      decode pod never runs a full local prefill. Routing goes through
      a real ``IndexerService.get_pod_scores`` call (``role="decode"``,
      residency-aware), whose traceparent threads through
      ``HandoffCoordinator.begin`` and both engines so one trace spans
      GetPodScores → prefill commit → decode first token.

    CPU = correctness smoke (every handoff completes without fallback,
    transferred blocks actually restore, and the score→commit→decode
    trace is a single trace id); TPU = the perf gate from the issue:
    disagg must beat the monolithic baseline on out_tok/s while holding
    TTFT p50 within 1.25x.
    """
    import math
    import shutil
    import sys as _sys
    import tempfile

    import jax

    from llmd_kv_cache_tpu.core import TokenProcessorConfig
    from llmd_kv_cache_tpu.events.model import EventBatch
    from llmd_kv_cache_tpu.models import engine as engine_mod
    from llmd_kv_cache_tpu.models.llama import (LlamaConfig, init_params,
                                                maybe_fuse_params)
    from llmd_kv_cache_tpu.offload.handoff import HandoffCoordinator
    from llmd_kv_cache_tpu.offload.spec import SharedStorageOffloadSpec
    from llmd_kv_cache_tpu.scoring import Indexer, IndexerConfig
    from llmd_kv_cache_tpu.scoring.residency import ResidencyTracker
    from llmd_kv_cache_tpu.services.indexer_service import (IndexerService,
                                                            ScoreRequest)
    from llmd_kv_cache_tpu.telemetry.tracing import recording_tracing

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    if on_tpu:
        model_cfg = LlamaConfig(
            vocab_size=8192, hidden_size=512, num_layers=4, num_heads=8,
            num_kv_heads=4, head_dim=128, intermediate_size=1408,
            page_size=16,
        )
        wl_kw = dict(n_requests=24, n_prefixes=6, prefix_len=256,
                     suffix_len=32, vocab=8000)
        max_new = 64
        pod_kw = dict(num_pages=1024, max_pages_per_seq=48,
                      max_prefill_tokens=128)
    else:
        model_cfg = LlamaConfig.tiny()  # page_size 4
        wl_kw = dict(n_requests=8, n_prefixes=4, prefix_len=8,
                     suffix_len=4, vocab=4000)
        max_new = 16
        # Two prefill chunks per 12-token prompt (chunk cap 8) so the
        # handoff actually streams; pool sized for every request decoding
        # concurrently on the single decode pod.
        pod_kw = dict(num_pages=128, max_pages_per_seq=16,
                      max_prefill_tokens=2 * model_cfg.page_size)
    page = model_cfg.page_size
    workload = build_workload(np.random.default_rng(2026), **wl_kw)
    n = len(workload)
    params = maybe_fuse_params(
        init_params(jax.random.PRNGKey(0), model_cfg), model_cfg)

    def fresh_indexer_cfg():
        return IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size_tokens=page))

    # --- baseline: 2 monolithic pods, KV-routed concurrent replay ---
    base_indexer = Indexer(fresh_indexer_cfg())
    base_pods = make_pods(2, model_cfg, engine_mod, base_indexer,
                          params=params, pod_kw=pod_kw)
    arrivals = [0.0] * n  # burst replay: decode batching under load
    base_t, base_hit, base_tps, _ = run_concurrent(
        base_pods, workload, make_kv_router(base_indexer), arrivals,
        max_new_tokens=max_new, tag="disagg-base")
    del base_pods
    base_p50 = statistics.median(base_t)

    # --- disagg: prefill pod → shared storage root → decode pod ---
    root = tempfile.mkdtemp(prefix="bench-disagg-")

    def spec():
        return SharedStorageOffloadSpec(
            root=root, model_name=MODEL_NAME, page_size=page,
            num_layers=model_cfg.num_layers,
            kv_heads=model_cfg.num_kv_heads,
            head_dim=model_cfg.head_dim, io_threads=4,
            parallel_agnostic=True, dtype="bfloat16",
        )

    try:
        svc = IndexerService(fresh_indexer_cfg())
        tracker = ResidencyTracker()
        svc.indexer.attach_residency(tracker)
        coord = HandoffCoordinator(residency=tracker)

        def pod(name, role):
            def sink(events, pod_name=name):
                svc.pool.process_event_batch(
                    EventBatch(timestamp=time.time(), events=list(events)),
                    pod_name, MODEL_NAME)

            eng = engine_mod.MiniEngine(
                engine_mod.EngineConfig(
                    model=model_cfg, model_name=MODEL_NAME,
                    pod_identifier=name, role=role, handoff_wait_s=60.0,
                    **pod_kw),
                event_sink=sink, params=params, seed=0,
                offload_spec=spec())
            eng.attach_handoff(coord)
            return eng

        prefill, decode = pod("prefill-0", "prefill"), pod("decode-0", "decode")

        # Virtual-time accounting as in run_concurrent: one clock per
        # pod, every enqueue/step's wall time advances it, the pod at
        # the minimum clock acts next. An admission lands on BOTH pods
        # (prefill bootstraps and commits; decode waits on the handoff).
        clocks = {"p": 0.0, "d": 0.0}
        reqs: dict = {}
        arr_of: dict = {}
        ttfts: dict = {}
        first_emit: dict = {}
        last_emit: dict = {}
        n_emitted: dict = {}
        out_tokens = 0
        i = 0
        arm_start = time.perf_counter()

        def p_busy():
            return bool(prefill._running) or bool(prefill._pending_store_jobs)

        def d_busy():
            return bool(decode._running)

        with recording_tracing() as exporter:
            while i < n or p_busy() or d_busy():
                t_arr = arrivals[i] if i < n else math.inf
                t_pod, pick = math.inf, None
                if p_busy():
                    t_pod, pick = clocks["p"], "p"
                if d_busy() and clocks["d"] < t_pod:
                    t_pod, pick = clocks["d"], "d"
                if t_arr <= t_pod:
                    rid, prompt = f"r{i}", workload[i]
                    # Score with the decode role: residency-aware ranks,
                    # and the response traceparent threads the whole
                    # handoff under the GetPodScores span.
                    resp = svc.get_pod_scores(ScoreRequest(
                        tokens=list(prompt), model_name=MODEL_NAME,
                        pod_identifiers=["decode-0"], role="decode"))
                    tp = resp.traceparent or None
                    _, dpod = HandoffCoordinator.pick_pair(
                        ["prefill-0"], ["decode-0"],
                        decode_scores=resp.scores)
                    coord.begin(rid, "prefill-0", dpod,
                                total_blocks=len(prompt) // page,
                                traceparent=tp)
                    if not p_busy():
                        clocks["p"] = max(clocks["p"], t_arr)
                    t0 = time.perf_counter()
                    prefill.enqueue(rid, prompt, max_new_tokens=1,
                                    traceparent=tp)
                    clocks["p"] += time.perf_counter() - t0
                    if not d_busy():
                        clocks["d"] = max(clocks["d"], t_arr)
                    t0 = time.perf_counter()
                    reqs[rid] = decode.enqueue(rid, prompt,
                                               max_new_tokens=max_new,
                                               traceparent=tp, handoff=True)
                    clocks["d"] += time.perf_counter() - t0
                    arr_of[rid] = t_arr
                    i += 1
                    continue
                if pick == "p":
                    t0 = time.perf_counter()
                    if prefill._running:
                        prefill.step()  # bootstrap tokens are discarded
                    prefill.poll_offload()
                    clocks["p"] += time.perf_counter() - t0
                    continue
                t0 = time.perf_counter()
                emitted = decode.step()
                clocks["d"] += time.perf_counter() - t0
                out_tokens += len(emitted)
                for rid in emitted:
                    if rid not in first_emit:
                        ttfts[rid] = clocks["d"] - arr_of[rid]
                        first_emit[rid] = clocks["d"]
                        n_emitted[rid] = 1
                        if len(first_emit) % 8 == 0:
                            print(f"[bench disagg] {len(first_emit)}/{n} "
                                  f"first tokens, "
                                  f"{time.perf_counter() - arm_start:.1f}s",
                                  file=_sys.stderr, flush=True)
                    else:
                        n_emitted[rid] += 1
                    last_emit[rid] = clocks["d"]

        assert len(ttfts) == n, f"decoded {len(ttfts)} of {n}"
        dbg = coord.debug()
        restored = sum(min(r.cached_len, len(workload[int(rid[1:])]))
                       for rid, r in reqs.items())
        # Score→serve trace continuity: one trace id must cover the
        # scorer's span, a prefill commit, and a decode step.
        def trace_ids(name):
            return {sp.trace_id for sp in exporter.find(name)}
        joint = (trace_ids("llm_d.kv_cache.indexer.GetPodScores")
                 & trace_ids("llm_d.kv_cache.handoff.prefill_commit")
                 & trace_ids("llm_d.kv_cache.engine.decode_step"))
        disagg_tps = out_tokens / max(max(clocks.values()), 1e-9)
        disagg_p50 = statistics.median(ttfts.values())
    finally:
        shutil.rmtree(root, ignore_errors=True)

    ratio = disagg_tps / max(base_tps, 1e-9)
    ttft_ratio = disagg_p50 / max(base_p50, 1e-9)
    completed = int(dbg["completed"])
    disagg_detail = {
        "ttft_p50_s": round(disagg_p50, 4),
        "out_tok_s": round(disagg_tps, 1),
        "out_tok_s_ratio": round(ratio, 3),
        "ttft_p50_ratio": round(ttft_ratio, 3),
        "handoffs_completed": completed,
        "handoff_fallbacks": int(dbg["failed"]),
        "restored_tokens": int(restored),
        "trace_continuity": bool(joint),
    }
    baseline_detail = {
        "ttft_p50_s": round(base_p50, 4),
        "out_tok_s": round(base_tps, 1),
        "hit_rate": round(base_hit, 4),
    }
    if on_tpu:
        # The issue's gate: more sustained decode throughput at fixed
        # (within 1.25x) TTFT p50.
        return {
            "metric": "disaggregated handoff out_tok/s vs monolithic "
                      "(decode-heavy, TTFT p50 held within 1.25x)",
            "value": round(ratio, 3),
            "unit": "x monolithic out_tok/s",
            "vs_baseline": 1.0,
            "gate_ok": bool(ratio > 1.0 and ttft_ratio <= 1.25),
            "platform": platform,
            "baseline": baseline_detail,
            "disagg": disagg_detail,
        }
    # CPU smoke: the perf claim is TPU-only; here the gate is the
    # correctness of the handoff plane end to end.
    return {
        "metric": "disaggregated handoff CPU smoke "
                  "(completed handoffs, no fallbacks)",
        "value": completed,
        "unit": "handoffs",
        "vs_baseline": n,
        "gate_ok": bool(completed == n and dbg["failed"] == 0
                        and restored > 0 and joint),
        "platform": platform,
        "baseline": baseline_detail,
        "disagg": disagg_detail,
    }


def bench_controller() -> dict:
    """Fleet-controller chaos arm (``--controller``, ISSUE 13).

    Three deterministic scenarios drive a REAL control stack — SLORegistry
    burn-rate alerting, HandoffCoordinator mix EMA, HashRing membership,
    FleetController with hysteresis/cooldown/budget policy — against a
    modeled fleet (pod service times are analytic functions of topology,
    so the arm is fast and bit-stable):

    1. **re-role chaos**: traffic flips balanced → prefill-heavy mid-run;
       the controller must flip a decode pod to prefill with zero manual
       intervention and bring modeled TTFT p90 back inside the SLO.
    2. **shard ramp**: the index grows 4x; the controller must scale the
       ring up (each join moving < 2/N of partitions) and hold modeled
       score p99 at the threshold.
    3. **flap injection**: the burn rate oscillates around the act band
       every round for 40 rounds; hysteresis must bound executed actions
       (the perf-sentinel value — lower is better, baseline 1).

    Every executed action must carry a ``llm_d.kv_cache.control.action``
    span with the causing signal attached (part of the gate).
    """
    from llmd_kv_cache_tpu.cluster.ring import HashRing, moved_partitions
    from llmd_kv_cache_tpu.control import (
        CollectorSignalSource,
        ControllerConfig,
        FleetController,
        InProcessActuator,
    )
    from llmd_kv_cache_tpu.offload.handoff import HandoffCoordinator
    from llmd_kv_cache_tpu.telemetry.slo import SLOConfig, SLORegistry
    from llmd_kv_cache_tpu.telemetry.tracing import recording_tracing

    clk = [0.0]

    def clock():
        return clk[0]

    def p90(values):
        xs = sorted(values)
        return xs[min(len(xs) - 1, int(0.9 * len(xs)))] if xs else 0.0

    with recording_tracing() as exporter:
        # -- scenario 1: prefill-heavy flip → re-role ----------------------
        roles = {"pod-0": "prefill", "pod-1": "prefill",
                 "pod-2": "decode", "pod-3": "decode"}
        reg = SLORegistry(clock=clock)
        ttft_slo = reg.add(SLOConfig(
            name="ttft", objective=0.99,
            fast_windows=(5.0, 10.0), slow_window=20.0))
        handoff = HandoffCoordinator()
        handoff.mix_alpha = 0.5  # fast EMA so the flip lands in a few rounds
        src = CollectorSignalSource(
            slo_registry=reg, handoff=handoff,
            shards=lambda: ["shard-0"], roles=lambda: dict(roles),
            clock=clock)
        act = InProcessActuator(
            set_role=lambda t, r: roles.__setitem__(t, r),
            drain_pod=lambda t: {"ok": True})
        ctl = FleetController(
            src, act,
            config=ControllerConfig(
                confirm_rounds=2, role_cooldown_s=3.0,
                role_imbalance_act=0.2, role_imbalance_rearm=0.1),
            clock=clock)
        TTFT_BASE, TTFT_SLO_S = 1.4, 2.0
        ttfts = []
        for rnd in range(40):
            mix = 0.5 if rnd < 10 else 0.85  # the chaos flip
            handoff.observe_mix(int(mix * 100), 100 - int(mix * 100))
            prefill_frac = (
                sum(1 for r in roles.values() if r == "prefill")
                / max(len(roles), 1))
            ttft_s = TTFT_BASE * max(1.0, mix / max(prefill_frac, 1e-9))
            ttfts.append(ttft_s)
            ttft_slo.record(*((100, 0) if ttft_s <= TTFT_SLO_S else (0, 100)))
            reg.evaluate_all()
            ctl.reconcile_once()
            clk[0] += 1.0
        reroles = [a for a in act.applied if a[0] == "set_role"]
        ttft_p90_after = p90(ttfts[-10:])
        reroles_ok = (len(reroles) >= 1 and ttft_p90_after <= TTFT_SLO_S
                      and ttft_slo.alert_severity is None)

        # -- scenario 2: 4x index ramp → shard scale-up --------------------
        clk[0] += 100.0
        shards = ["shard-0"]
        reg2 = SLORegistry(clock=clock)
        score_slo = reg2.add(SLOConfig(
            name="score_latency", objective=0.99,
            fast_windows=(5.0, 10.0), slow_window=15.0))
        src2 = CollectorSignalSource(
            slo_registry=reg2, shards=lambda: list(shards),
            roles=lambda: {}, clock=clock)
        move_fracs = []

        def add_shard(target):
            old = HashRing(shards)
            shards.append(target)
            new = HashRing(shards)
            frac = moved_partitions(old, new) / new.partitions
            move_fracs.append(frac)
            return {"joined": target, "moved_fraction": round(frac, 4)}

        act2 = InProcessActuator(
            add_shard=add_shard,
            remove_shard=lambda t: shards.remove(t),
            drain_pod=lambda t: {"ok": True})
        ctl2 = FleetController(
            src2, act2,
            config=ControllerConfig(confirm_rounds=2, shard_cooldown_s=4.0,
                                    max_shards=8),
            clock=clock)
        SCORE_MS_PER_X, SCORE_SLO_MS = 2.0, 4.0
        score_p99 = 0.0
        for rnd in range(50):
            index_x = 1.0 + 3.0 * min(1.0, rnd / 20.0)  # 1x → 4x ramp
            score_p99 = SCORE_MS_PER_X * index_x / max(len(shards), 1)
            score_slo.record(
                *((100, 0) if score_p99 <= SCORE_SLO_MS else (0, 100)))
            reg2.evaluate_all()
            ctl2.reconcile_once()
            clk[0] += 1.0
        scaleup_ok = (len(shards) >= 2 and score_p99 <= SCORE_SLO_MS
                      and all(f <= 2.0 / len(shards) for f in move_fracs))

        # -- scenario 3: flap injection → bounded actions ------------------
        clk[0] += 100.0
        shards3 = ["shard-0"]
        reg3 = SLORegistry(clock=clock)
        flap_slo = reg3.add(SLOConfig(
            name="score_latency", objective=0.99,
            fast_windows=(3.0, 6.0), slow_window=10.0))
        src3 = CollectorSignalSource(
            slo_registry=reg3, shards=lambda: list(shards3),
            roles=lambda: {}, clock=clock)
        act3 = InProcessActuator(
            add_shard=lambda t: shards3.append(t),
            remove_shard=lambda t: shards3.remove(t),
            drain_pod=lambda t: {"ok": True})
        ctl3 = FleetController(
            src3, act3,
            config=ControllerConfig(confirm_rounds=1, shard_cooldown_s=5.0,
                                    max_shards=8),
            clock=clock)
        for rnd in range(40):
            # Oscillate the instantaneous burn around the act band (1.0):
            # 1.5x on even rounds, 0.8x on odd — without hysteresis this
            # would act every other round.
            bad = 15 if rnd % 2 == 0 else 8
            flap_slo.record(1000 - bad, bad)
            reg3.evaluate_all()
            ctl3.reconcile_once()
            clk[0] += 1.0
        flap_actions = len(act3.applied)
        flap_ok = flap_actions <= 2

        executed_total = len(act.applied) + len(act2.applied) + len(act3.applied)
        action_spans = exporter.find("llm_d.kv_cache.control.action")
        spans_ok = (
            len([s for s in action_spans if s.attributes.get("signal")])
            >= executed_total > 0)

    detail = {
        "reroles": {
            "actions": len(reroles),
            "ttft_p90_after_s": round(ttft_p90_after, 3),
            "ttft_slo_s": TTFT_SLO_S,
            "alert_cleared": ttft_slo.alert_severity is None,
            "ok": reroles_ok,
        },
        "scaleup": {
            "final_shards": len(shards),
            "score_p99_ms": round(score_p99, 3),
            "score_slo_ms": SCORE_SLO_MS,
            "max_moved_fraction": round(max(move_fracs), 4) if move_fracs else 0.0,
            "ok": scaleup_ok,
        },
        "flap": {
            "executed_actions": flap_actions,
            "rounds": 40,
            "ok": flap_ok,
        },
        "action_spans_with_signal": spans_ok,
    }
    return {
        "metric": "fleet controller chaos arm "
                  "(flap-injection executed actions; re-role + shard-ramp "
                  "gates)",
        "value": flap_actions,
        "unit": "actions",
        "vs_baseline": 1,
        "gate_ok": bool(reroles_ok and scaleup_ok and flap_ok and spans_ok),
        "detail": detail,
    }


def _run_ttft_subprocess(env=None, timeout=2400):
    """Run the TTFT arm in a watchdogged subprocess; returns the JSON
    result line or None. The budget covers the replay arms, the hardened
    multi-fleet storage arm, AND the concurrent open-loop sweep (which
    re-serves cold fleets per QPS point) at tunneled-TPU service times —
    a too-tight watchdog here silently downgrades a TPU headline to the
    CPU fallback."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, __file__, "--ttft"],
            capture_output=True, text=True, timeout=timeout, env=env,
        )
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    json.loads(line)
                except json.JSONDecodeError:
                    continue
                return line
    except subprocess.TimeoutExpired:
        pass
    return None


def _accelerator_healthy(timeout=90) -> bool:
    """Quick tunnel probe in a subprocess (a wedged device transport hangs
    any jax init in-process, so probe out-of-process)."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; "
             "(jnp.ones((64,64))@jnp.ones((64,64))).block_until_ready(); "
             "print('KVTPU_PROBE_OK')"],
            capture_output=True, text=True, timeout=timeout,
        )
        return (proc.returncode == 0
                and proc.stdout.strip().endswith("KVTPU_PROBE_OK"))
    except subprocess.TimeoutExpired:
        return False


def guarded_main() -> str:
    """The driver entry: returns exactly one JSON result line.

    Ladder: (1) accelerator healthy → TTFT routing benchmark on the real
    device; (2) tunnel down → the SAME headline routing metric on the CPU
    backend (platform is recorded in the metric string) — the routing win
    is prefill-skip-ratio-driven and backend-independent; (3) anything
    else → the index micro-benchmark.
    """
    import os

    if _accelerator_healthy():
        line = _run_ttft_subprocess()
        if line is not None:
            return line
    # CPU fallback: strip the accelerator plugin (PYTHONPATH sitecustomize)
    # so jax cannot touch the wedged transport.
    cpu_env = dict(os.environ)
    cpu_env.pop("PYTHONPATH", None)
    cpu_env["JAX_PLATFORMS"] = "cpu"
    line = _run_ttft_subprocess(env=cpu_env)
    if line is not None:
        return line
    try:
        return json.dumps(bench_index_add())
    except Exception:
        # Toolchain-less host: fall back to the pure-Python backend so a
        # result line is always emitted.
        return json.dumps(bench_index_add(native=False))


def _dispatch(argv: list) -> object:
    """CLI mode → result (a dict, or an already-encoded JSON line)."""
    if "--ttft-load" in argv:
        return main(queued=True)
    if "--ttft" in argv:
        return main()
    if "--index" in argv:
        return bench_index_add()
    if "--offload" in argv:
        return bench_offload_throughput()
    if "--decode-hybrid" in argv:
        return bench_decode_throughput(hybrid=True)
    if "--decode" in argv:
        return bench_decode_throughput()
    if "--ragged" in argv:
        return bench_ragged()
    if "--fp8-bandwidth" in argv:
        return bench_fp8_bandwidth()
    if "--events" in argv:
        return bench_event_ingestion()
    if "--fleet-telemetry" in argv:
        return bench_fleet_telemetry()
    if "--pyprof-overhead" in argv:
        return bench_pyprof_overhead()
    if "--workingset" in argv:
        return bench_workingset()
    if "--audit" in argv:
        return bench_audit()
    if "--fencing" in argv:
        return bench_fencing()
    if "--incident" in argv:
        return bench_incident()
    if "--flight-recorder" in argv:
        return bench_flight_recorder()
    if "--snapshot-overhead" in argv:
        return bench_snapshot_overhead()
    if "--engine-telemetry" in argv:
        return bench_engine_telemetry()
    if "--disagg" in argv:
        return bench_disagg()
    if "--controller" in argv:
        return bench_controller()
    if "--graytail" in argv:
        return bench_graytail()
    if "--shards" in argv:
        i = argv.index("--shards")
        n = 4
        if i + 1 < len(argv):
            try:
                n = int(argv[i + 1])
            except ValueError:
                pass
        return bench_shard_fanout(shards=n)
    return guarded_main()


if __name__ == "__main__":
    import contextlib
    import sys

    # The driver contract (VERDICT #5): the result JSON must be the single
    # LAST stdout line, with nothing after it. Benchmark code and the
    # libraries it imports occasionally write to stdout, so the whole run
    # executes with stdout aliased to stderr; only the final line touches
    # the real stream. (The --ttft subprocess path is unaffected: the
    # parent scans the child's stdout for the last JSON line, which is now
    # the only one.)
    _real_stdout = sys.stdout
    with contextlib.redirect_stdout(sys.stderr):
        _result = _dispatch(sys.argv)
    _line = _result if isinstance(_result, str) else json.dumps(_result)
    print(_line, file=_real_stdout, flush=True)
