#!/usr/bin/env python
"""Headline benchmark: KV-cache-aware routing vs round-robin TTFT.

Mirrors the reference's benchmark design (``benchmarking/*/README.md``:
"precise" scheduling = Indexer-routed vs random/load baselines) scaled to
one host: N in-process engine pods share a workload with heavy shared-prefix
reuse; requests are routed either round-robin or by
``Indexer.score_tokens``, and TTFT (admission+prefill wall time) is
compared. Prefix-cache hits skip prefill compute, so routing quality shows
up directly as p50 TTFT.

Prints ONE JSON line:
  {"metric": "p50 TTFT reduction, KV-aware routing vs round-robin",
   "value": <percent>, "unit": "%", "vs_baseline": <value/40>}

vs_baseline is measured against the north-star target of a >=40% p50 TTFT
reduction (BASELINE.md). Runs on whatever backend JAX selects (the real
TPU chip under the driver; CPU elsewhere).
"""

from __future__ import annotations

import json
import statistics
import time

import numpy as np


def build_workload(rng, n_requests=64, n_prefixes=8, prefix_len=256, suffix_len=32,
                   vocab=8000):
    """Shared-prefix replay: most requests reuse one of a few system prompts."""
    prefixes = [
        rng.integers(1, vocab, prefix_len).tolist() for _ in range(n_prefixes)
    ]
    workload = []
    for i in range(n_requests):
        prefix = prefixes[rng.integers(0, n_prefixes)]
        suffix = rng.integers(1, vocab, suffix_len).tolist()
        workload.append(prefix + suffix)
    return workload


def make_pods(n_pods, model_cfg, engine_mod, indexer, params=None,
              pod_kw=None):
    """Fresh engine pods wired to feed the indexer's index via events.

    All pods share one parameter tree (same seed anyway — the engines
    never donate params); per-pod init costs ~minutes of per-op dispatch
    on a remote-tunneled TPU.
    """
    import jax

    from llmd_kv_cache_tpu.events.model import EventBatch
    from llmd_kv_cache_tpu.events.pool import Pool, PoolConfig
    from llmd_kv_cache_tpu.models.llama import init_params

    if params is None:
        params = init_params(jax.random.PRNGKey(0), model_cfg)
    # Capacity-constrained page pool (the regime where routing matters:
    # each pod can hold a few of the workload's shared prefixes, like the
    # reference's 73%-capacity setup). Round-robin thrashes the prefix
    # cache; KV-aware routing lets each pod own a prefix subset.
    pod_kw = dict(pod_kw) if pod_kw is not None else {
        "num_pages": 72, "max_pages_per_seq": 64}
    pool = Pool(PoolConfig(concurrency=1), indexer.kv_block_index,
                indexer.token_processor)
    pods = {}
    for i in range(n_pods):
        name = f"pod-{i}"

        def sink(events, pod_name=name):
            pool.process_event_batch(
                EventBatch(timestamp=time.time(), events=list(events)),
                pod_name, MODEL_NAME,
            )

        pods[name] = engine_mod.MiniEngine(
            engine_mod.EngineConfig(
                model=model_cfg,
                model_name=MODEL_NAME,
                pod_identifier=name,
                **pod_kw,
            ),
            event_sink=sink,
            params=params,
            seed=0,
        )
    return pods


MODEL_NAME = "bench-llama"


def run_replay(pods, workload, router, tag="", arrivals=None):
    """Admit each request on the routed pod; returns per-request TTFT (s).

    With ``arrivals`` (a nondecreasing array of open-loop arrival times),
    queueing is simulated in virtual time the way inference-perf's
    saturation runs behave: each pod serves FIFO, service time is the
    MEASURED prefill wall time, and TTFT = queue wait + service. This is
    the regime behind the reference's headline tables — at saturation,
    routing quality compounds through queue depth, not just prefill skip
    (`benchmarking/73-capacity/README.md`: precise 0.542 s vs 92.5 s p90
    is queue-dominated). Without ``arrivals``, TTFT is bare service time.

    Coarse progress goes to stderr (the stdout contract is one JSON line);
    on a tunneled TPU a silent 25-minute run is undebuggable without it.
    """
    import sys

    ttfts = []
    pod_names = list(pods.keys())
    pod_free = {name: 0.0 for name in pod_names}
    arm_start = time.perf_counter()
    for i, prompt in enumerate(workload):
        pod_name = router(i, prompt, pod_names)
        engine = pods[pod_name]
        start = time.perf_counter()
        engine.add_request(f"r{i}", prompt, max_new_tokens=1)
        service = time.perf_counter() - start
        if arrivals is None:
            ttfts.append(service)
        else:
            begin = max(arrivals[i], pod_free[pod_name])
            pod_free[pod_name] = begin + service
            ttfts.append(begin + service - arrivals[i])
        if i % 16 == 15:
            print(f"[bench {tag}] {i + 1}/{len(workload)} requests, "
                  f"{time.perf_counter() - arm_start:.1f}s elapsed",
                  file=sys.stderr, flush=True)
    return ttfts


def bench_index_add(native: bool = True) -> dict:
    """Fallback metric: index Add throughput vs the reference's documented
    Go micro-benchmark (BenchmarkInMemory_Add: 6,086,106 ns/op on the same
    fixed-seed 10k-key workload, tests/profiling/kv_cache_index/README.md)."""
    import time

    from llmd_kv_cache_tpu.core import PodEntry

    if native:
        from llmd_kv_cache_tpu.index.native import NativeIndex as IndexImpl
        from llmd_kv_cache_tpu.index.native import NativeIndexConfig as ConfigImpl
        backend = "native C++ index"
    else:
        from llmd_kv_cache_tpu.index import InMemoryIndex as IndexImpl
        from llmd_kv_cache_tpu.index import InMemoryIndexConfig as ConfigImpl
        backend = "python in-memory index"

    rng = np.random.default_rng(42)
    keys = [int(x) for x in rng.integers(0, 2**63, 10_000, dtype=np.int64)]
    entries = [PodEntry("pod1", "gpu")]
    times = []
    for _ in range(30):
        idx = IndexImpl(ConfigImpl())
        start = time.perf_counter()
        idx.add(keys, keys, entries)
        times.append(time.perf_counter() - start)
    ns_op = min(times) * 1e9
    go_baseline_ns = 6_086_106
    return {
        "metric": f"index Add ns/op (10k-key workload, {backend}; "
                  "reference Go BenchmarkInMemory_Add = 6086106)",
        "value": round(ns_op),
        "unit": "ns/op",
        "vs_baseline": round(go_baseline_ns / ns_op, 3),
    }


def bench_offload_throughput() -> dict:
    """Secondary metric: offload store+load throughput through the full
    stack (device page gather → host slab → native file write, and back).
    Printed by ``--offload``; informational (the reference publishes no
    comparable figure)."""
    import shutil
    import tempfile
    import time

    import jax.numpy as jnp

    from llmd_kv_cache_tpu.offload.spec import SharedStorageOffloadSpec

    root = tempfile.mkdtemp(prefix="kvtpu-bench-offload-")
    try:
        layers, pages, page_size, kvh, hd = 16, 256, 16, 8, 128
        spec = SharedStorageOffloadSpec(
            root=root, model_name="bench", page_size=page_size,
            num_layers=layers, kv_heads=kvh, head_dim=hd, io_threads=4,
            parallel_agnostic=True,
        )
        rng = np.random.default_rng(0)
        shape = (layers, pages, kvh, page_size, hd)
        k = jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
        v = jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
        handlers = spec.get_handlers(k, v)

        # 64 blocks of 2 pages each
        transfers = [(0x1000 + i, [1 + 2 * i, 2 + 2 * i]) for i in range(64)]
        start = time.perf_counter()
        job = handlers.async_store_blocks(transfers)
        result = None
        while result is None:
            for res in handlers.get_finished():
                if res.job_id == job:
                    result = res
            time.sleep(0.001)
        store_s = time.perf_counter() - start
        if not result.success or result.shed_hashes:
            raise RuntimeError(
                f"store leg degraded (success={result.success}, "
                f"shed={len(result.shed_hashes)}): throughput not measurable"
            )
        store_bytes = result.bytes_transferred

        start = time.perf_counter()
        job = handlers.async_load_blocks(transfers)
        result = None
        while result is None:
            for res in handlers.get_finished():
                if res.job_id == job:
                    result = res
            time.sleep(0.001)
        load_s = time.perf_counter() - start
        if not result.success:
            raise RuntimeError("load leg failed: throughput not measurable")
        load_bytes = result.bytes_transferred
        handlers.shutdown()

        return {
            "metric": "offload store/load throughput (64 blocks, "
                      f"{store_bytes / 1e6:.0f} MB, device↔host↔disk)",
            "value": round(store_bytes / store_s / 1e9, 3),
            "unit": "GB/s store "
                    f"({load_bytes / load_s / 1e9:.2f} GB/s load)",
            "vs_baseline": 1.0,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_decode_throughput() -> dict:
    """Secondary metric: steady-state greedy decode tokens/s through the
    engine, single-token stepping vs fused 32-token bursts
    (``forward_decode_steps``). The burst factor is the dispatch-overhead
    amortization — the figure that matters on real deployments where
    per-launch latency competes with per-token compute."""
    import time

    from llmd_kv_cache_tpu.models import engine as engine_mod
    from llmd_kv_cache_tpu.models.llama import LlamaConfig, init_params

    import jax

    cfg = LlamaConfig(
        # head_dim 128: the Mosaic lane-tiling unit, so the real-TPU run
        # exercises the Pallas kernels (sub-128 head dims fall back to XLA)
        # — and the shape real model families (Llama/Qwen) actually use.
        vocab_size=8192, hidden_size=512, num_layers=4, num_heads=8,
        num_kv_heads=4, head_dim=128, intermediate_size=1408, page_size=16,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, 8000, 64).tolist() for _ in range(8)]
    max_new = 128
    rates = {}
    bursts = (1, 32)
    for burst in bursts:
        eng = engine_mod.MiniEngine(
            engine_mod.EngineConfig(
                model=cfg, num_pages=256, max_pages_per_seq=16,
                model_name="bench-decode", pod_identifier="p",
                decode_burst=burst,
            ),
            params=params, seed=0,
        )
        reqs = [eng.add_request(f"r{i}", p, max_new_tokens=max_new)
                for i, p in enumerate(prompts)]
        # one warm step so the decode program is compiled before timing
        eng.step()
        start = time.perf_counter()
        tokens_before = sum(len(r.output) for r in reqs)
        while not all(r.done for r in reqs):
            eng.step()
        elapsed = time.perf_counter() - start
        rates[burst] = (sum(len(r.output) for r in reqs) - tokens_before) / elapsed
    return {
        "metric": f"greedy decode tok/s, batch 8 (burst {bursts[-1]} vs "
                  f"single-step {rates[1]:.0f} tok/s)",
        "value": round(rates[bursts[-1]], 1),
        "unit": f"tok/s (x{rates[bursts[-1]] / rates[1]:.2f} vs single-step)",
        "vs_baseline": 1.0,
    }


def bench_event_ingestion() -> dict:
    """Write-path capacity: raw ZMQ-shaped messages through the sharded
    pool into the (native) index, end to end (msgpack parse → request-key
    recompute → index add). Events/sec across 8 simulated pods."""
    import time

    import msgpack

    from llmd_kv_cache_tpu.core import ChunkedTokenDatabase, TokenProcessorConfig
    from llmd_kv_cache_tpu.events import Pool, PoolConfig, RawMessage
    from llmd_kv_cache_tpu.index.base import create_index

    block = 16
    processor = ChunkedTokenDatabase(TokenProcessorConfig(block_size_tokens=block))
    index = create_index(None)
    pool = Pool(PoolConfig(concurrency=4), index, processor)
    pool.start()

    rng = np.random.default_rng(0)
    n_msgs = 4000
    msgs = []
    for i in range(n_msgs):
        pod = f"pod-{i % 8}"
        tokens = rng.integers(1, 30000, 4 * block).tolist()  # 4 blocks/event
        ev = ["BlockStored", [int(h) for h in rng.integers(1, 2**62, 4)],
              None, tokens, block]
        msgs.append(RawMessage(
            topic=f"kv@{pod}@m", sequence=i,
            payload=msgpack.packb([float(i), [ev]], use_bin_type=True),
        ))

    start = time.perf_counter()
    for m in msgs:
        pool.add_task(m)
    pool.join()
    elapsed = time.perf_counter() - start
    pool.shutdown()

    return {
        "metric": "KV-event ingestion (BlockStored, 4 blocks/event, "
                  "parse+hash+index, 8 pods, 4 shards)",
        "value": round(n_msgs / elapsed),
        "unit": "events/s",
        "vs_baseline": 1.0,
    }


def main(queued: bool = False) -> None:
    import jax

    from llmd_kv_cache_tpu.core import TokenProcessorConfig
    from llmd_kv_cache_tpu.models import engine as engine_mod
    from llmd_kv_cache_tpu.models.llama import LlamaConfig
    from llmd_kv_cache_tpu.scoring import Indexer, IndexerConfig

    rng = np.random.default_rng(42)
    platform = jax.devices()[0].platform
    if platform == "tpu":
        # Production-shaped sizing: a ~0.9B-param model with 4k-token
        # shared prefixes, so a prefix hit skips real MXU work (measured
        # v5e: cold prefill 1.77 s vs 0.14 s on a hit — 12.8×). Tiny
        # models underestimate the routing win on a remote-dispatched
        # device because per-dispatch latency, identical for both arms,
        # buries the prefill compute a hit would skip.
        model_cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, num_layers=16,
            num_heads=16, num_kv_heads=8, head_dim=128,
            intermediate_size=5632, page_size=16,
        )
        wl_kw = dict(n_requests=40, n_prefixes=8, prefix_len=4096,
                     suffix_len=64, vocab=30000)
        # 1024 pages/pod = 16k tokens ≈ 3 resident prefixes of the 8.
        pod_kw = dict(num_pages=1024, max_pages_per_seq=272,
                      max_prefill_tokens=2048)
        # Every prefill bucket a partial prefix hit can produce: the full
        # prompt covers the 128-page chunk + 4-page tail; the shorter
        # lengths cover 8..64-page buckets (a partially evicted prefix
        # leaves a page-aligned remainder ≥ 4 pages). Unwarmed buckets
        # would compile 20-40 s INSIDE an arm's timed window.
        warm_lens = [4096 + 64, 1024, 512, 256, 128]
    else:
        model_cfg = LlamaConfig(
            vocab_size=8192, hidden_size=512, num_layers=4, num_heads=8,
            num_kv_heads=4, head_dim=128, intermediate_size=1408,
            page_size=16,
        )
        wl_kw = {}
        pod_kw = None
        warm_lens = [p * 16 for p in (1, 2, 4, 8, 16, 32)]
    n_pods = 4
    workload = build_workload(rng, **wl_kw)

    def fresh_indexer():
        return Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size_tokens=model_cfg.page_size
                )
            )
        )

    # Warm the jit cache (prefill buckets + decode) so compile time doesn't
    # pollute TTFT for either arm.
    import sys as _sys
    _t0 = time.perf_counter()
    from llmd_kv_cache_tpu.models.llama import init_params as _init_params
    shared_params = _init_params(jax.random.PRNGKey(0), model_cfg)
    warm_indexer = fresh_indexer()
    warm = make_pods(1, model_cfg, engine_mod, warm_indexer,
                     params=shared_params, pod_kw=pod_kw)["pod-0"]
    for wl in warm_lens:
        _tb = time.perf_counter()
        prompt = rng.integers(1, 8000, wl).tolist()
        warm.add_request(f"warm{wl}", prompt, max_new_tokens=1)
        print(f"[bench warm] len {wl}: "
              f"{time.perf_counter() - _tb:.1f}s", file=_sys.stderr, flush=True)
    print(f"[bench warm] total {time.perf_counter() - _t0:.1f}s",
          file=_sys.stderr, flush=True)

    # Saturation mode: open-loop Poisson arrivals at 1.25× the fleet's
    # all-cold service capacity — the round-robin arm (mostly cold)
    # saturates and queues; the kv-aware arm (mostly hits, service far
    # below cold) keeps up. Calibrate from a measured cold prefill on the
    # warmed pod so the rate is platform-honest, then use the SAME
    # arrival times for both arms.
    arrivals = None
    qps = None
    if queued:
        _tb = time.perf_counter()
        warm.add_request(
            "cal", rng.integers(1, 8000, wl_kw.get("prefix_len", 256)
                                + wl_kw.get("suffix_len", 32)).tolist(),
            max_new_tokens=1)
        d_cold = time.perf_counter() - _tb
        qps = 1.25 * n_pods / d_cold
        arrivals = np.cumsum(rng.exponential(1.0 / qps, len(workload)))
        print(f"[bench load] cold service {d_cold * 1e3:.0f}ms -> "
              f"{qps:.1f} req/s open-loop", file=_sys.stderr, flush=True)
    del warm

    # Arm 1: round-robin routing.
    rr_indexer = fresh_indexer()
    rr_pods = make_pods(n_pods, model_cfg, engine_mod, rr_indexer,
                        params=shared_params, pod_kw=pod_kw)
    rr_ttfts = run_replay(
        rr_pods, workload, router=lambda i, _p, names: names[i % len(names)],
        tag="round-robin", arrivals=arrivals,
    )

    # Arm 2: KV-cache-aware routing via the Indexer.
    kv_indexer = fresh_indexer()
    kv_pods = make_pods(n_pods, model_cfg, engine_mod, kv_indexer,
                        params=shared_params, pod_kw=pod_kw)
    rr_counter = [0]

    def kv_router(_i, prompt, names):
        scores = kv_indexer.score_tokens(prompt, MODEL_NAME)
        if scores:
            return max(scores.items(), key=lambda kv: kv[1])[0]
        pick = names[rr_counter[0] % len(names)]
        rr_counter[0] += 1
        return pick

    kv_ttfts = run_replay(kv_pods, workload, router=kv_router,
                          tag="kv-aware", arrivals=arrivals)

    p50_rr = statistics.median(rr_ttfts)
    p50_kv = statistics.median(kv_ttfts)
    reduction_pct = 100.0 * (1.0 - p50_kv / p50_rr) if p50_rr > 0 else 0.0

    load = (f", Poisson {qps:.1f} req/s open-loop, p50 rr {p50_rr:.2f}s "
            f"vs kv {p50_kv:.3f}s" if queued else "")
    print(json.dumps({
        "metric": "p50 TTFT reduction, KV-aware routing vs round-robin "
                  f"({n_pods} pods, shared-prefix replay{load}, "
                  f"{jax.devices()[0].platform})",
        "value": round(reduction_pct, 2),
        "unit": "%",
        "vs_baseline": round(reduction_pct / 40.0, 3),
    }))


def _run_ttft_subprocess(env=None, timeout=900):
    """Run the TTFT arm in a watchdogged subprocess; returns the JSON
    result line or None."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, __file__, "--ttft"],
            capture_output=True, text=True, timeout=timeout, env=env,
        )
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    json.loads(line)
                except json.JSONDecodeError:
                    continue
                return line
    except subprocess.TimeoutExpired:
        pass
    return None


def _accelerator_healthy(timeout=90) -> bool:
    """Quick tunnel probe in a subprocess (a wedged device transport hangs
    any jax init in-process, so probe out-of-process)."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; "
             "(jnp.ones((64,64))@jnp.ones((64,64))).block_until_ready(); "
             "print('KVTPU_PROBE_OK')"],
            capture_output=True, text=True, timeout=timeout,
        )
        return (proc.returncode == 0
                and proc.stdout.strip().endswith("KVTPU_PROBE_OK"))
    except subprocess.TimeoutExpired:
        return False


def guarded_main() -> None:
    """The driver entry: always emits exactly one JSON result line.

    Ladder: (1) accelerator healthy → TTFT routing benchmark on the real
    device; (2) tunnel down → the SAME headline routing metric on the CPU
    backend (platform is recorded in the metric string) — the routing win
    is prefill-skip-ratio-driven and backend-independent; (3) anything
    else → the index micro-benchmark.
    """
    import os

    if _accelerator_healthy():
        line = _run_ttft_subprocess()
        if line is not None:
            print(line)
            return
    # CPU fallback: strip the accelerator plugin (PYTHONPATH sitecustomize)
    # so jax cannot touch the wedged transport.
    cpu_env = dict(os.environ)
    cpu_env.pop("PYTHONPATH", None)
    cpu_env["JAX_PLATFORMS"] = "cpu"
    line = _run_ttft_subprocess(env=cpu_env)
    if line is not None:
        print(line)
        return
    try:
        print(json.dumps(bench_index_add()))
    except Exception:
        # Toolchain-less host: fall back to the pure-Python backend so a
        # result line is always emitted.
        print(json.dumps(bench_index_add(native=False)))


if __name__ == "__main__":
    import sys

    if "--ttft-load" in sys.argv:
        main(queued=True)
    elif "--ttft" in sys.argv:
        main()
    elif "--index" in sys.argv:
        print(json.dumps(bench_index_add()))
    elif "--offload" in sys.argv:
        print(json.dumps(bench_offload_throughput()))
    elif "--decode" in sys.argv:
        print(json.dumps(bench_decode_throughput()))
    elif "--events" in sys.argv:
        print(json.dumps(bench_event_ingestion()))
    else:
        guarded_main()
