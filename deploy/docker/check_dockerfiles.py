#!/usr/bin/env python
"""Static Dockerfile validation for environments without a docker daemon.

`docker build` cannot run in the hermetic build sandbox (no daemon, no
registry egress), so CI and developers run this instead: it parses each
Dockerfile and asserts (a) every COPY source exists in the build context
(repo root), (b) ENTRYPOINT/CMD scripts exist among the copied paths,
(c) stage references in `COPY --from=` resolve, and (d) the chart's
image repositories all have a Dockerfile here or are explicitly
external. Run from anywhere: paths resolve relative to the repo root.
"""

from __future__ import annotations

import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parents[2]
DOCKER_DIR = ROOT / "deploy" / "docker"

# Chart image repositories -> Dockerfile (None = external base image the
# repo does not build: the default engine serving stack and Redis).
CHART_IMAGES = {
    "kvtpu/indexer": "Dockerfile.indexer",
    "kvtpu/tokenizer": "Dockerfile.tokenizer",
    "kvtpu/engine": "Dockerfile.engine",
    "vllm-tpu/vllm-tpu": None,
    "redis": None,
}


def parse(dockerfile: pathlib.Path):
    stages, copies, entry_cmds = [], [], []
    # Join backslash continuations first: a COPY's sources may span
    # physical lines and every one of them must be validated.
    logical, pending = [], ""
    for raw in dockerfile.read_text().splitlines():
        stripped = raw.strip()
        if stripped.endswith("\\"):
            pending += stripped[:-1] + " "
            continue
        logical.append(pending + stripped)
        pending = ""
    if pending:
        logical.append(pending)
    for line in logical:
        if m := re.match(r"FROM\s+\S+(?:\s+AS\s+(\S+))?", line, re.I):
            stages.append(m.group(1))
        elif m := re.match(r"COPY\s+(.*)", line, re.I):
            parts = m.group(1).split()
            from_stage = None
            if parts and parts[0].startswith("--from="):
                from_stage = parts.pop(0)[len("--from="):]
            *srcs, _dst = parts
            copies.append((from_stage, srcs))
        elif m := re.match(r"(?:ENTRYPOINT|CMD)\s+\[(.*)\]", line, re.I):
            entry_cmds.extend(
                p.strip().strip('"') for p in m.group(1).split(","))
    return stages, copies, entry_cmds


def check(dockerfile: pathlib.Path) -> list[str]:
    errors = []
    stages, copies, entry_cmds = parse(dockerfile)
    copied_files = set()
    for from_stage, srcs in copies:
        if from_stage is not None:
            if from_stage not in stages:
                errors.append(f"COPY --from={from_stage}: unknown stage")
            # Built artifacts (e.g. /src/.../libkvio.so) are produced by
            # the builder stage; check the source file that builds them.
            continue
        for src in srcs:
            if not (ROOT / src).exists():
                errors.append(f"COPY source missing in context: {src}")
            copied_files.add(src.rstrip("/"))
    for item in entry_cmds:
        if item.endswith(".py") and not item.startswith("-"):
            # Must be covered by a COPY (exact file, or inside a copied
            # directory) — existing in the repo is NOT enough; it has to
            # actually land in the image.
            covered = any(
                item == c or item.startswith(c + "/")
                for c in copied_files)
            if not covered:
                errors.append(f"entrypoint script not COPY'd into image: "
                              f"{item}")
    return errors


def main() -> int:
    failed = False
    for name, df in CHART_IMAGES.items():
        if df is None:
            print(f"  {name}: external image (not built here)")
            continue
        path = DOCKER_DIR / df
        if not path.exists():
            print(f"FAIL {name}: missing {df}")
            failed = True
            continue
        errors = check(path)
        if errors:
            failed = True
            print(f"FAIL {name} ({df}):")
            for e in errors:
                print(f"    {e}")
        else:
            print(f"  {name}: {df} OK")

    # Every image repository referenced by the chart must be accounted for.
    values = (ROOT / "deploy" / "chart" / "values.yaml").read_text()
    for repo in re.findall(r"repository:\s*(\S+)", values):
        if repo not in CHART_IMAGES:
            print(f"FAIL chart references unaccounted image: {repo}")
            failed = True
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
