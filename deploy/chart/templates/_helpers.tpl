{{/* Common names and labels */}}
{{- define "kvtpu.fullname" -}}
{{- .Release.Name | trunc 52 | trimSuffix "-" -}}
{{- end -}}

{{- define "kvtpu.labels" -}}
app.kubernetes.io/part-of: kvtpu-fleet
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end -}}

{{- define "kvtpu.engine.name" -}}
{{ include "kvtpu.fullname" . }}-engine
{{- end -}}

{{- define "kvtpu.indexer.name" -}}
{{ include "kvtpu.fullname" . }}-indexer
{{- end -}}

{{- define "kvtpu.redis.name" -}}
{{ include "kvtpu.fullname" . }}-redis
{{- end -}}

{{- define "kvtpu.offload.pvc" -}}
{{ include "kvtpu.fullname" . }}-kv-offload
{{- end -}}
