// Native KV-block index + block-hash engine.
//
// Perf-critical counterpart of the Python in-memory index and token hash
// chain (the two hot loops of the scheduler path, SURVEY.md §3.1). Role
// parity with the reference's Go implementations:
//   pkg/kvcache/kvblock/in_memory.go  -> Index (two-level LRU, dual keys)
//   pkg/kvcache/kvblock/token_processor.go -> kvhash_* (FNV-64a over
//       canonical CBOR [parent, chunk, extra]), text-only fast path
//
// Exposed via a C ABI consumed by ctypes (llmd_kv_cache_tpu/index/native.py
// and core/token_processor.py). Strings are interned: Python passes pod and
// tier strings once, then everything crosses the boundary as integer ids.
//
// Concurrency: one engine-wide mutex. Calls arrive with the GIL released;
// operations are short (µs) so a single lock outperforms the reference's
// fine-grained locking at this scale while preserving its semantics
// (including Evict's all-empty mapping prune and empty-key removal).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <deque>
#include <list>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// FNV-64a + canonical CBOR hash chain
// ---------------------------------------------------------------------------

constexpr uint64_t kFnvOffset = 0xcbf29ce484222325ULL;
constexpr uint64_t kFnvPrime = 0x100000001b3ULL;

inline uint64_t FnvUpdate(uint64_t h, const uint8_t* data, size_t n) {
  for (size_t i = 0; i < n; ++i) {
    h ^= data[i];
    h *= kFnvPrime;
  }
  return h;
}

// Append a canonical-CBOR unsigned-int head for `value` with major type.
inline void CborHead(std::vector<uint8_t>& out, uint8_t major, uint64_t value) {
  uint8_t mt = major << 5;
  if (value < 24) {
    out.push_back(mt | static_cast<uint8_t>(value));
  } else if (value <= 0xff) {
    out.push_back(mt | 24);
    out.push_back(static_cast<uint8_t>(value));
  } else if (value <= 0xffff) {
    out.push_back(mt | 25);
    for (int s = 8; s >= 0; s -= 8) out.push_back((value >> s) & 0xff);
  } else if (value <= 0xffffffffULL) {
    out.push_back(mt | 26);
    for (int s = 24; s >= 0; s -= 8) out.push_back((value >> s) & 0xff);
  } else {
    out.push_back(mt | 27);
    for (int s = 56; s >= 0; s -= 8) out.push_back((value >> s) & 0xff);
  }
}

// Hash one block: FNV-64a(CBOR([parent, [tokens...], null])).
uint64_t HashBlock(uint64_t parent, const uint32_t* tokens, int n,
                   std::vector<uint8_t>& scratch) {
  scratch.clear();
  scratch.push_back(0x83);  // array(3)
  CborHead(scratch, 0, parent);
  CborHead(scratch, 4, static_cast<uint64_t>(n));  // array(n)
  for (int i = 0; i < n; ++i) CborHead(scratch, 0, tokens[i]);
  scratch.push_back(0xf6);  // null extra (text-only fast path)
  return FnvUpdate(kFnvOffset, scratch.data(), scratch.size());
}

// ---------------------------------------------------------------------------
// Index
// ---------------------------------------------------------------------------

struct Entry {
  int32_t pod;
  int32_t tier;
  uint8_t flags;  // bit0 speculative, bit1 has_group
  int32_t group;

  bool operator==(const Entry& o) const {
    return pod == o.pod && tier == o.tier && flags == o.flags && group == o.group;
  }
};

struct PodSlot {
  // MRU-first, capacity-bounded (pods_per_key, default 10): linear ops on
  // a tiny vector beat any pointer structure.
  std::vector<Entry> entries;
  std::list<uint64_t>::iterator lru_it;
};

struct MapSlot {
  std::vector<uint64_t> request_keys;
  std::list<uint64_t>::iterator lru_it;
};

class Index {
 public:
  Index(uint64_t capacity, int pods_per_key, uint64_t mapping_capacity)
      : capacity_(capacity ? capacity : 1),
        pods_per_key_(pods_per_key > 0 ? pods_per_key : 10),
        mapping_capacity_(mapping_capacity ? mapping_capacity : 1) {}

  int32_t Intern(const std::string& s) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = intern_.find(s);
    if (it != intern_.end()) return it->second;
    int32_t id = static_cast<int32_t>(strings_.size());
    strings_.push_back(s);
    intern_.emplace(s, id);
    return id;
  }

  int GetString(int32_t id, char* buf, int buf_len) {
    std::lock_guard<std::mutex> lk(mu_);
    if (id < 0 || static_cast<size_t>(id) >= strings_.size()) return -1;
    const std::string& s = strings_[id];
    int n = static_cast<int>(s.size());
    if (n >= buf_len) return -1;
    std::memcpy(buf, s.data(), n);
    buf[n] = '\0';
    return n;
  }

  void Add(const uint64_t* engine_keys, int n_ek, const uint64_t* request_keys,
           int n_rk, const Entry* entries, int n_entries) {
    std::lock_guard<std::mutex> lk(mu_);
    if (n_ek > 0 && n_rk > 0) {
      int n = n_ek > n_rk ? n_ek : n_rk;
      uint64_t prev_ek = 0;
      bool first = true;
      for (int i = 0; i < n; ++i) {
        uint64_t ek = engine_keys[static_cast<int64_t>(i) * n_ek / n];
        uint64_t rk = request_keys[static_cast<int64_t>(i) * n_rk / n];
        MapSlot& slot = TouchMapping(ek, first || ek != prev_ek);
        slot.request_keys.push_back(rk);
        prev_ek = ek;
        first = false;
      }
    }
    for (int k = 0; k < n_rk; ++k) {
      PodSlot& slot = TouchKey(request_keys[k]);
      for (int e = 0; e < n_entries; ++e) InsertEntry(slot, entries[e]);
    }
  }

  // Lookup with early stop on a known-but-empty key. Results packed as
  // 4 ints (pod, tier, flags, group) per entry. Returns total entries, or
  // -1 if out_cap is too small.
  int Lookup(const uint64_t* keys, int n_keys, const int32_t* filter_pods,
             int n_filter, int32_t* out_counts, int32_t* out_entries,
             int out_cap) {
    std::lock_guard<std::mutex> lk(mu_);
    int total = 0;
    for (int k = 0; k < n_keys; ++k) {
      out_counts[k] = 0;
      auto it = data_.find(keys[k]);
      if (it == data_.end()) continue;  // absent key does not break the scan
      PodSlot& slot = it->second;
      if (slot.entries.empty()) break;  // chain broken at a known key
      key_lru_.splice(key_lru_.begin(), key_lru_, slot.lru_it);
      for (const Entry& e : slot.entries) {
        if (n_filter > 0) {
          bool match = false;
          for (int f = 0; f < n_filter; ++f) {
            if (filter_pods[f] == e.pod) { match = true; break; }
          }
          if (!match) continue;
        }
        if ((total + 1) * 4 > out_cap) return -1;
        int32_t* dst = out_entries + total * 4;
        dst[0] = e.pod;
        dst[1] = e.tier;
        dst[2] = e.flags;
        dst[3] = e.group;
        ++total;
        ++out_counts[k];
      }
    }
    return total;
  }

  void Evict(uint64_t key, int is_engine_key, const Entry* entries, int n) {
    std::lock_guard<std::mutex> lk(mu_);
    if (is_engine_key) {
      auto mit = mappings_.find(key);
      if (mit == mappings_.end()) return;
      // Copy: EvictFromRequestKey may erase request keys.
      std::vector<uint64_t> rks = mit->second.request_keys;
      for (uint64_t rk : rks) EvictFromRequestKey(rk, entries, n);
      bool all_empty = true;
      for (uint64_t rk : rks) {
        auto dit = data_.find(rk);
        if (dit != data_.end() && !dit->second.entries.empty()) {
          all_empty = false;
          break;
        }
      }
      if (all_empty) {
        mit = mappings_.find(key);
        if (mit != mappings_.end()) {
          map_lru_.erase(mit->second.lru_it);
          mappings_.erase(mit);
        }
      }
    } else {
      EvictFromRequestKey(key, entries, n);
    }
  }

  uint64_t GetRequestKey(uint64_t engine_key) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = mappings_.find(engine_key);
    if (it == mappings_.end() || it->second.request_keys.empty()) return 0;
    map_lru_.splice(map_lru_.begin(), map_lru_, it->second.lru_it);
    return it->second.request_keys.back();
  }

  void Clear(int32_t pod) {
    std::lock_guard<std::mutex> lk(mu_);
    // Collect first: erasing mutates the LRU list we'd be iterating.
    std::vector<uint64_t> touched;
    for (auto& [key, slot] : data_) {
      for (const Entry& e : slot.entries) {
        if (e.pod == pod) { touched.push_back(key); break; }
      }
    }
    for (uint64_t key : touched) {
      auto it = data_.find(key);
      if (it == data_.end()) continue;
      auto& entries = it->second.entries;
      entries.erase(
          std::remove_if(entries.begin(), entries.end(),
                         [pod](const Entry& e) { return e.pod == pod; }),
          entries.end());
      if (entries.empty()) {
        key_lru_.erase(it->second.lru_it);
        data_.erase(it);
      }
    }
  }

  uint64_t Size() {
    std::lock_guard<std::mutex> lk(mu_);
    return data_.size();
  }

  uint64_t MapSize() {
    std::lock_guard<std::mutex> lk(mu_);
    return mappings_.size();
  }

  // Snapshot dump of the request-key table under one lock hold: per key,
  // its entries packed as 4 ints (pod, tier, flags, group). Returns the
  // number of keys written, or -1 when either cap is too small (the
  // caller sizes from Size() * pods_per_key and retries on growth races).
  int Dump(uint64_t* out_keys, int32_t* out_counts, int key_cap,
           int32_t* out_entries, int entry_cap) {
    std::lock_guard<std::mutex> lk(mu_);
    if (static_cast<int64_t>(data_.size()) > key_cap) return -1;
    int nk = 0;
    int total = 0;
    for (auto& [key, slot] : data_) {
      if (total + static_cast<int>(slot.entries.size()) > entry_cap) return -1;
      out_keys[nk] = key;
      out_counts[nk] = static_cast<int32_t>(slot.entries.size());
      for (const Entry& e : slot.entries) {
        int32_t* dst = out_entries + total * 4;
        dst[0] = e.pod;
        dst[1] = e.tier;
        dst[2] = e.flags;
        dst[3] = e.group;
        ++total;
      }
      ++nk;
    }
    return nk;
  }

  // Snapshot dump of the engine→request mapping table. Returns mapping
  // count, or -1 when a cap is too small.
  int DumpMappings(uint64_t* out_keys, int32_t* out_counts, int key_cap,
                   uint64_t* out_request_keys, int rk_cap) {
    std::lock_guard<std::mutex> lk(mu_);
    if (static_cast<int64_t>(mappings_.size()) > key_cap) return -1;
    int nk = 0;
    int total = 0;
    for (auto& [key, slot] : mappings_) {
      if (total + static_cast<int>(slot.request_keys.size()) > rk_cap) return -1;
      out_keys[nk] = key;
      out_counts[nk] = static_cast<int32_t>(slot.request_keys.size());
      for (uint64_t rk : slot.request_keys) out_request_keys[total++] = rk;
      ++nk;
    }
    return nk;
  }

  // Restore one engine→request mapping without touching the key table.
  // Add with zero entries would TouchKey an empty PodSlot, and Lookup
  // treats a known-but-empty key as a broken prefix chain — snapshot
  // restore must not create those.
  void SetMapping(uint64_t engine_key, const uint64_t* request_keys, int n) {
    std::lock_guard<std::mutex> lk(mu_);
    MapSlot& slot = TouchMapping(engine_key, true);
    slot.request_keys.assign(request_keys, request_keys + n);
  }

  // Fused lookup + longest-prefix tier-weighted scoring (the whole
  // scheduler hot path in one native call; mirrors scoring/scorer.py's
  // LongestPrefixScorer semantics exactly).
  // tier_weights: tier string-id → weight (missing tiers weigh 1.0).
  // out_hits receives the Lookup-equivalent hit count (keys with entries,
  // scan stopping only at a known-but-empty key), preserving both the
  // telemetry semantics and the LRU recency refresh of the lookup path.
  // Returns the number of (pod, score) pairs written, or -needed when
  // out_cap is too small (caller retries with a bigger buffer).
  // early_exit != 0 stops the scan as soon as scoring is over (the prefix
  // chain broke): scores are identical, but trailing resident blocks are
  // neither counted in out_hits nor LRU-refreshed — the scheduler trades
  // that for O(prefix) instead of O(prompt) scans.
  int Score(const uint64_t* keys, int n_keys, const int32_t* filter_pods,
            int n_filter, const int32_t* weight_tiers,
            const double* weight_values, int n_weights, int32_t* out_pods,
            double* out_scores, int out_cap, int32_t* out_hits,
            int early_exit = 0) {
    std::lock_guard<std::mutex> lk(mu_);

    auto tier_weight = [&](int32_t tier) {
      for (int i = 0; i < n_weights; ++i) {
        if (weight_tiers[i] == tier) return weight_values[i];
      }
      return 1.0;
    };
    auto pod_allowed = [&](int32_t pod) {
      if (n_filter == 0) return true;
      for (int i = 0; i < n_filter; ++i) {
        if (filter_pods[i] == pod) return true;
      }
      return false;
    };

    std::unordered_map<int32_t, double> scores;   // accumulated
    std::unordered_map<int32_t, double> current;  // this key's max weights
    std::unordered_map<int32_t, bool> active;     // in the prefix chain

    int hits = 0;
    bool scoring = true;  // false once the prefix chain broke
    bool first = true;
    for (int ki = 0; ki < n_keys; ++ki) {
      if (early_exit && !scoring) break;
      auto it = data_.find(keys[ki]);
      if (it == data_.end()) {
        // Absent key: the active prefix set empties (scoring over), but —
        // like Lookup — the scan continues so later resident blocks still
        // get counted and LRU-refreshed.
        scoring = false;
        continue;
      }
      PodSlot& slot = it->second;
      if (slot.entries.empty()) break;  // known-but-empty: Lookup stops too
      ++hits;
      key_lru_.splice(key_lru_.begin(), key_lru_, slot.lru_it);
      if (!scoring) continue;

      current.clear();
      for (const Entry& e : slot.entries) {
        if (!pod_allowed(e.pod)) continue;
        double w = tier_weight(e.tier);
        auto [cit, inserted] = current.emplace(e.pod, w);
        if (!inserted && w > cit->second) cit->second = w;
      }

      if (first) {
        for (auto& [pod, w] : current) {
          scores[pod] = w;
          active[pod] = true;
        }
        first = false;
      } else {
        for (auto& [pod, is_active] : active) {
          if (!is_active) continue;
          auto cit = current.find(pod);
          if (cit != current.end()) {
            scores[pod] += cit->second;
          } else {
            is_active = false;
          }
        }
        bool any = false;
        for (auto& [pod, is_active] : active) {
          if (is_active) { any = true; break; }
        }
        if (!any) scoring = false;  // keep scanning for hits/LRU only
      }
    }

    *out_hits = hits;
    if (static_cast<int>(scores.size()) > out_cap) {
      return -static_cast<int>(scores.size());
    }
    int n = 0;
    for (auto& [pod, score] : scores) {
      out_pods[n] = pod;
      out_scores[n] = score;
      ++n;
    }
    return n;
  }

  // Chunked fused scoring with transferred-residency fold-in: one native
  // call (and one lock hold) covers the whole data plane of a score —
  // the early-exit chunked lookup AND the per-pod consecutive-from-0
  // residency walk that scoring/residency.py::ResidencyTracker.bonus
  // otherwise runs per key in Python.
  //
  // Chunk semantics mirror the Python ``lookup_chunked`` path: keys are
  // scanned ``chunk_size`` at a time and the scan stops at the first
  // chunk boundary after the prefix chain broke (chunk_size <= 0 scans
  // everything in one chunk). Scores are identical to Score() with
  // early_exit either way — post-break keys never accumulate — but hit
  // telemetry covers the whole breaking chunk, matching the Python
  // chunked path rather than Score's per-key early exit.
  //
  // Residency claims arrive as parallel arrays of (pod id, key index,
  // landed flag). Per pod the walk runs along key indices from 0 and
  // stops at the first index with no claim; landed claims weigh
  // landed_weight, in-flight ones in_flight_discount, and the pod's
  // total is scaled by tier_discount. Only positive totals are emitted
  // (out_res_pods/out_res_bonus, count via out_res_n). Bonuses are NOT
  // folded into out_scores: the Python caller applies liveness weighting
  // to the base scores first, exactly like the unfused path.
  //
  // out_chunks counts chunks entered, out_early_exit is 1 when the scan
  // stopped before the last key. Returns the number of (pod, score)
  // pairs, or -needed when out_cap is too small (retry with a bigger
  // buffer; res_cap is exact-sized by the caller and never grows).
  int ScoreChunked(const uint64_t* keys, int n_keys,
                   const int32_t* filter_pods, int n_filter,
                   const int32_t* weight_tiers, const double* weight_values,
                   int n_weights, int chunk_size, const int32_t* claim_pods,
                   const int32_t* claim_key_idx, const uint8_t* claim_landed,
                   int n_claims, double landed_weight,
                   double in_flight_discount, double tier_discount,
                   int32_t* out_pods, double* out_scores, int out_cap,
                   int32_t* out_hits, int32_t* out_chunks,
                   int32_t* out_early_exit, int32_t* out_res_pods,
                   double* out_res_bonus, int res_cap, int32_t* out_res_n) {
    std::lock_guard<std::mutex> lk(mu_);

    auto tier_weight = [&](int32_t tier) {
      for (int i = 0; i < n_weights; ++i) {
        if (weight_tiers[i] == tier) return weight_values[i];
      }
      return 1.0;
    };
    auto pod_allowed = [&](int32_t pod) {
      if (n_filter == 0) return true;
      for (int i = 0; i < n_filter; ++i) {
        if (filter_pods[i] == pod) return true;
      }
      return false;
    };

    if (chunk_size <= 0 || chunk_size > n_keys) {
      chunk_size = n_keys > 0 ? n_keys : 1;
    }

    std::unordered_map<int32_t, double> scores;   // accumulated
    std::unordered_map<int32_t, double> current;  // this key's max weights
    std::unordered_map<int32_t, bool> active;     // in the prefix chain

    int hits = 0;
    int chunks = 0;
    int scanned = 0;
    bool scoring = true;  // false once the prefix chain broke
    bool first = true;
    bool stopped = false;
    for (int cs = 0; cs < n_keys && !stopped; cs += chunk_size) {
      ++chunks;
      int ce = std::min(cs + chunk_size, n_keys);
      for (int ki = cs; ki < ce; ++ki) {
        ++scanned;
        auto it = data_.find(keys[ki]);
        if (it == data_.end()) {
          scoring = false;  // absent key: scan the rest of the chunk
          continue;
        }
        PodSlot& slot = it->second;
        if (slot.entries.empty()) {  // known-but-empty: Lookup stops too
          stopped = true;
          break;
        }
        ++hits;
        key_lru_.splice(key_lru_.begin(), key_lru_, slot.lru_it);
        if (!scoring) continue;

        current.clear();
        for (const Entry& e : slot.entries) {
          if (!pod_allowed(e.pod)) continue;
          double w = tier_weight(e.tier);
          auto [cit, inserted] = current.emplace(e.pod, w);
          if (!inserted && w > cit->second) cit->second = w;
        }

        if (first) {
          for (auto& [pod, w] : current) {
            scores[pod] = w;
            active[pod] = true;
          }
          first = false;
        } else {
          for (auto& [pod, is_active] : active) {
            if (!is_active) continue;
            auto cit = current.find(pod);
            if (cit != current.end()) {
              scores[pod] += cit->second;
            } else {
              is_active = false;
            }
          }
          bool any = false;
          for (auto& [pod, is_active] : active) {
            if (is_active) { any = true; break; }
          }
          if (!any) scoring = false;
        }
      }
      if (!scoring) stopped = true;  // chunk-boundary early exit
    }

    *out_hits = hits;
    *out_chunks = chunks;
    *out_early_exit = scanned < n_keys ? 1 : 0;

    // Residency fold-in: group sparse claims by pod, then per pod walk
    // the key indices consecutively from 0 (ResidencyTracker.bonus).
    int res_n = 0;
    if (n_claims > 0) {
      std::unordered_map<int32_t, std::unordered_map<int32_t, uint8_t>> by_pod;
      for (int i = 0; i < n_claims; ++i) {
        by_pod[claim_pods[i]].emplace(claim_key_idx[i], claim_landed[i]);
      }
      for (auto& [pod, idx_map] : by_pod) {
        double total = 0.0;
        for (int idx = 0; idx < n_keys; ++idx) {
          auto cit = idx_map.find(idx);
          if (cit == idx_map.end()) break;
          total += cit->second ? landed_weight : in_flight_discount;
        }
        if (total > 0.0 && res_n < res_cap) {
          out_res_pods[res_n] = pod;
          out_res_bonus[res_n] = total * tier_discount;
          ++res_n;
        }
      }
    }
    *out_res_n = res_n;

    if (static_cast<int>(scores.size()) > out_cap) {
      return -static_cast<int>(scores.size());
    }
    int n = 0;
    for (auto& [pod, score] : scores) {
      out_pods[n] = pod;
      out_scores[n] = score;
      ++n;
    }
    return n;
  }

 private:
  PodSlot& TouchKey(uint64_t key) {
    auto it = data_.find(key);
    if (it != data_.end()) {
      key_lru_.splice(key_lru_.begin(), key_lru_, it->second.lru_it);
      return it->second;
    }
    if (data_.size() >= capacity_) {
      uint64_t victim = key_lru_.back();
      key_lru_.pop_back();
      data_.erase(victim);
    }
    key_lru_.push_front(key);
    PodSlot& slot = data_[key];
    slot.lru_it = key_lru_.begin();
    return slot;
  }

  // reset=true replaces the mapping (new Add supersedes), matching the
  // reference where Add overwrites the engine key's request list.
  MapSlot& TouchMapping(uint64_t key, bool reset) {
    auto it = mappings_.find(key);
    if (it != mappings_.end()) {
      map_lru_.splice(map_lru_.begin(), map_lru_, it->second.lru_it);
      if (reset) it->second.request_keys.clear();
      return it->second;
    }
    if (mappings_.size() >= mapping_capacity_) {
      uint64_t victim = map_lru_.back();
      map_lru_.pop_back();
      mappings_.erase(victim);
    }
    map_lru_.push_front(key);
    MapSlot& slot = mappings_[key];
    slot.lru_it = map_lru_.begin();
    return slot;
  }

  void InsertEntry(PodSlot& slot, const Entry& entry) {
    auto& v = slot.entries;
    for (size_t i = 0; i < v.size(); ++i) {
      if (v[i] == entry) {
        // promote to MRU (front)
        Entry tmp = v[i];
        v.erase(v.begin() + i);
        v.insert(v.begin(), tmp);
        return;
      }
    }
    if (static_cast<int>(v.size()) >= pods_per_key_) v.pop_back();
    v.insert(v.begin(), entry);
  }

  void EvictFromRequestKey(uint64_t key, const Entry* entries, int n) {
    auto it = data_.find(key);
    if (it == data_.end()) return;
    auto& v = it->second.entries;
    for (int e = 0; e < n; ++e) {
      for (size_t i = 0; i < v.size(); ++i) {
        if (v[i] == entries[e]) {
          v.erase(v.begin() + i);
          break;
        }
      }
    }
    if (v.empty()) {
      key_lru_.erase(it->second.lru_it);
      data_.erase(it);
    }
  }

  uint64_t capacity_;
  int pods_per_key_;
  uint64_t mapping_capacity_;
  std::mutex mu_;
  std::unordered_map<uint64_t, PodSlot> data_;
  std::unordered_map<uint64_t, MapSlot> mappings_;
  std::list<uint64_t> key_lru_;  // MRU at front
  std::list<uint64_t> map_lru_;
  std::unordered_map<std::string, int32_t> intern_;
  std::deque<std::string> strings_;
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// -- hash chain --

// Initial chain hash: FNV64a(CBOR([FNV64a(seed), null, model])).
uint64_t kvhash_init(const char* seed, const char* model) {
  uint64_t seed_hash = FnvUpdate(
      kFnvOffset, reinterpret_cast<const uint8_t*>(seed), std::strlen(seed));
  std::vector<uint8_t> buf;
  buf.push_back(0x83);
  CborHead(buf, 0, seed_hash);
  buf.push_back(0xf6);  // null tokens
  size_t model_len = std::strlen(model);
  CborHead(buf, 3, model_len);  // text string head
  buf.insert(buf.end(), model, model + model_len);
  return FnvUpdate(kFnvOffset, buf.data(), buf.size());
}

// Chain-hash full blocks of `block_size` tokens (text-only path).
// Returns the number of block hashes written to out (= n_tokens/block_size).
int kvhash_chain(uint64_t parent, const uint32_t* tokens, int n_tokens,
                 int block_size, uint64_t* out) {
  if (block_size <= 0) return 0;
  int n_blocks = n_tokens / block_size;
  std::vector<uint8_t> scratch;
  scratch.reserve(16 + 5 * block_size);
  uint64_t prefix = parent;
  for (int b = 0; b < n_blocks; ++b) {
    prefix = HashBlock(prefix, tokens + b * block_size, block_size, scratch);
    out[b] = prefix;
  }
  return n_blocks;
}

// -- index --

void* kvidx_create(uint64_t capacity, int pods_per_key, uint64_t mapping_capacity) {
  return new Index(capacity, pods_per_key, mapping_capacity);
}

void kvidx_destroy(void* idx) { delete static_cast<Index*>(idx); }

int32_t kvidx_intern(void* idx, const char* s) {
  return static_cast<Index*>(idx)->Intern(s);
}

int kvidx_get_string(void* idx, int32_t id, char* buf, int buf_len) {
  return static_cast<Index*>(idx)->GetString(id, buf, buf_len);
}

void kvidx_add(void* idx, const uint64_t* engine_keys, int n_ek,
               const uint64_t* request_keys, int n_rk, const int32_t* pods,
               const int32_t* tiers, const uint8_t* flags,
               const int32_t* groups, int n_entries) {
  std::vector<Entry> entries(n_entries);
  for (int i = 0; i < n_entries; ++i) {
    entries[i] = Entry{pods[i], tiers[i], flags[i], groups[i]};
  }
  static_cast<Index*>(idx)->Add(engine_keys, n_ek, request_keys, n_rk,
                                entries.data(), n_entries);
}

int kvidx_lookup(void* idx, const uint64_t* keys, int n_keys,
                 const int32_t* filter_pods, int n_filter,
                 int32_t* out_counts, int32_t* out_entries, int out_cap) {
  return static_cast<Index*>(idx)->Lookup(keys, n_keys, filter_pods, n_filter,
                                          out_counts, out_entries, out_cap);
}

void kvidx_evict(void* idx, uint64_t key, int is_engine_key,
                 const int32_t* pods, const int32_t* tiers,
                 const uint8_t* flags, const int32_t* groups, int n) {
  std::vector<Entry> entries(n);
  for (int i = 0; i < n; ++i) {
    entries[i] = Entry{pods[i], tiers[i], flags[i], groups[i]};
  }
  static_cast<Index*>(idx)->Evict(key, is_engine_key, entries.data(), n);
}

uint64_t kvidx_get_request_key(void* idx, uint64_t engine_key) {
  return static_cast<Index*>(idx)->GetRequestKey(engine_key);
}

void kvidx_clear(void* idx, int32_t pod) {
  static_cast<Index*>(idx)->Clear(pod);
}

uint64_t kvidx_len(void* idx) { return static_cast<Index*>(idx)->Size(); }

uint64_t kvidx_map_len(void* idx) { return static_cast<Index*>(idx)->MapSize(); }

int kvidx_dump(void* idx, uint64_t* out_keys, int32_t* out_counts, int key_cap,
               int32_t* out_entries, int entry_cap) {
  return static_cast<Index*>(idx)->Dump(out_keys, out_counts, key_cap,
                                        out_entries, entry_cap);
}

int kvidx_dump_mappings(void* idx, uint64_t* out_keys, int32_t* out_counts,
                        int key_cap, uint64_t* out_request_keys, int rk_cap) {
  return static_cast<Index*>(idx)->DumpMappings(out_keys, out_counts, key_cap,
                                                out_request_keys, rk_cap);
}

void kvidx_set_mapping(void* idx, uint64_t engine_key,
                       const uint64_t* request_keys, int n) {
  static_cast<Index*>(idx)->SetMapping(engine_key, request_keys, n);
}

int kvidx_score(void* idx, const uint64_t* keys, int n_keys,
                const int32_t* filter_pods, int n_filter,
                const int32_t* weight_tiers, const double* weight_values,
                int n_weights, int32_t* out_pods, double* out_scores,
                int out_cap, int32_t* out_hits) {
  return static_cast<Index*>(idx)->Score(keys, n_keys, filter_pods, n_filter,
                                         weight_tiers, weight_values,
                                         n_weights, out_pods, out_scores,
                                         out_cap, out_hits);
}

// kvidx_score with an early-exit flag; kept as a separate symbol so older
// callers of kvidx_score keep their ABI (full-scan semantics).
int kvidx_score_ex(void* idx, const uint64_t* keys, int n_keys,
                   const int32_t* filter_pods, int n_filter,
                   const int32_t* weight_tiers, const double* weight_values,
                   int n_weights, int32_t* out_pods, double* out_scores,
                   int out_cap, int32_t* out_hits, int early_exit) {
  return static_cast<Index*>(idx)->Score(keys, n_keys, filter_pods, n_filter,
                                         weight_tiers, weight_values,
                                         n_weights, out_pods, out_scores,
                                         out_cap, out_hits, early_exit);
}

// Chunked fused scoring + residency fold-in (see Index::ScoreChunked).
// One ctypes crossing per score regardless of prompt length: chunk-
// granular early exit, hit/chunk counters, and the per-pod residency
// walk all happen under one native lock hold.
int kvidx_score_chunked(
    void* idx, const uint64_t* keys, int n_keys, const int32_t* filter_pods,
    int n_filter, const int32_t* weight_tiers, const double* weight_values,
    int n_weights, int chunk_size, const int32_t* claim_pods,
    const int32_t* claim_key_idx, const uint8_t* claim_landed, int n_claims,
    double landed_weight, double in_flight_discount, double tier_discount,
    int32_t* out_pods, double* out_scores, int out_cap, int32_t* out_hits,
    int32_t* out_chunks, int32_t* out_early_exit, int32_t* out_res_pods,
    double* out_res_bonus, int res_cap, int32_t* out_res_n) {
  return static_cast<Index*>(idx)->ScoreChunked(
      keys, n_keys, filter_pods, n_filter, weight_tiers, weight_values,
      n_weights, chunk_size, claim_pods, claim_key_idx, claim_landed, n_claims,
      landed_weight, in_flight_discount, tier_discount, out_pods, out_scores,
      out_cap, out_hits, out_chunks, out_early_exit, out_res_pods,
      out_res_bonus, res_cap, out_res_n);
}
}
