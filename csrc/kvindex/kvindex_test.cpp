// TSAN stress for the native index: concurrent add / lookup / evict /
// score / clear against one instance (the role `go test -race` plays for
// the reference's fine-grained-locking index; ours is coarser-locked, so
// this guards the lock discipline as the implementation evolves).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <random>
#include <thread>
#include <vector>

extern "C" {
void* kvidx_create(uint64_t capacity, int pods_per_key,
                   uint64_t mapping_capacity);
void kvidx_destroy(void* idx);
int32_t kvidx_intern(void* idx, const char* s);
void kvidx_add(void* idx, const uint64_t* engine_keys, int n_ek,
               const uint64_t* request_keys, int n_rk, const int32_t* pods,
               const int32_t* tiers, const uint8_t* flags,
               const int32_t* groups, int n_entries);
int kvidx_lookup(void* idx, const uint64_t* keys, int n_keys,
                 const int32_t* filter_pods, int n_filter,
                 int32_t* out_counts, int32_t* out_entries, int out_cap);
void kvidx_evict(void* idx, uint64_t key, int is_engine_key,
                 const int32_t* pods, const int32_t* tiers,
                 const uint8_t* flags, const int32_t* groups, int n);
uint64_t kvidx_get_request_key(void* idx, uint64_t engine_key);
void kvidx_clear(void* idx, int32_t pod);
uint64_t kvidx_len(void* idx);
}

int main() {
  void* idx = kvidx_create(100000, 4, 100000);
  int32_t pods[4];
  char name[8];
  for (int p = 0; p < 4; ++p) {
    std::snprintf(name, sizeof(name), "pod-%d", p);
    pods[p] = kvidx_intern(idx, name);
  }
  int32_t tier = kvidx_intern(idx, "tpu-hbm");

  constexpr int kThreads = 6;
  constexpr int kOps = 4000;
  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back([&, t] {
      std::mt19937_64 rng(t);
      for (int i = 0; i < kOps; ++i) {
        uint64_t keys[4] = {rng() % 512 + 1, rng() % 512 + 1,
                            rng() % 512 + 1, rng() % 512 + 1};
        int32_t entry_pod = pods[t % 4];
        uint8_t flags = 0;
        int32_t group = 0;
        switch (i % 5) {
          case 0:
          case 1:
            kvidx_add(idx, keys, 4, keys, 4, &entry_pod, &tier, &flags,
                      &group, 1);
            break;
          case 2: {
            int32_t counts[4], out_entries[256];
            kvidx_lookup(idx, keys, 4, nullptr, 0, counts, out_entries, 256);
            break;
          }
          case 3:
            kvidx_evict(idx, keys[0], i % 2, &entry_pod, &tier, &flags,
                        &group, 1);
            kvidx_get_request_key(idx, keys[1]);
            break;
          case 4:
            if (i % 1000 == 999) kvidx_clear(idx, entry_pod);
            kvidx_len(idx);
            break;
        }
      }
    });
  }
  for (auto& t : threads) t.join();
  kvidx_destroy(idx);
  std::printf("kvindex_test OK\n");
  return 0;
}
