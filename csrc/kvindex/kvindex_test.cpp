// Native index test binary: a single-threaded correctness section for
// the fused chunked-scoring entry point (run under ASan/UBSan by `make
// asan`), then the TSAN stress — concurrent add / lookup / evict /
// score / clear against one instance (the role `go test -race` plays for
// the reference's fine-grained-locking index; ours is coarser-locked, so
// this guards the lock discipline as the implementation evolves).

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <random>
#include <thread>
#include <vector>

extern "C" {
void* kvidx_create(uint64_t capacity, int pods_per_key,
                   uint64_t mapping_capacity);
void kvidx_destroy(void* idx);
int32_t kvidx_intern(void* idx, const char* s);
void kvidx_add(void* idx, const uint64_t* engine_keys, int n_ek,
               const uint64_t* request_keys, int n_rk, const int32_t* pods,
               const int32_t* tiers, const uint8_t* flags,
               const int32_t* groups, int n_entries);
int kvidx_lookup(void* idx, const uint64_t* keys, int n_keys,
                 const int32_t* filter_pods, int n_filter,
                 int32_t* out_counts, int32_t* out_entries, int out_cap);
void kvidx_evict(void* idx, uint64_t key, int is_engine_key,
                 const int32_t* pods, const int32_t* tiers,
                 const uint8_t* flags, const int32_t* groups, int n);
uint64_t kvidx_get_request_key(void* idx, uint64_t engine_key);
void kvidx_clear(void* idx, int32_t pod);
uint64_t kvidx_len(void* idx);
int kvidx_score_ex(void* idx, const uint64_t* keys, int n_keys,
                   const int32_t* filter_pods, int n_filter,
                   const int32_t* weight_tiers, const double* weight_values,
                   int n_weights, int32_t* out_pods, double* out_scores,
                   int out_cap, int32_t* out_hits, int early_exit);
int kvidx_score_chunked(
    void* idx, const uint64_t* keys, int n_keys, const int32_t* filter_pods,
    int n_filter, const int32_t* weight_tiers, const double* weight_values,
    int n_weights, int chunk_size, const int32_t* claim_pods,
    const int32_t* claim_key_idx, const uint8_t* claim_landed, int n_claims,
    double landed_weight, double in_flight_discount, double tier_discount,
    int32_t* out_pods, double* out_scores, int out_cap, int32_t* out_hits,
    int32_t* out_chunks, int32_t* out_early_exit, int32_t* out_res_pods,
    double* out_res_bonus, int res_cap, int32_t* out_res_n);
}

#define CHECK(cond)                                                      \
  do {                                                                   \
    if (!(cond)) {                                                       \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,      \
                   __LINE__, #cond);                                     \
      std::abort();                                                      \
    }                                                                    \
  } while (0)

namespace {

// kvidx_score_chunked correctness: chunk-granular early exit must be
// score-equivalent to kvidx_score_ex, the residency walk must match the
// Python tracker's consecutive-from-0 rule, and degenerate chunk sizes
// (0, oversized) must behave as one full-array chunk.
void TestScoreChunked() {
  void* idx = kvidx_create(10000, 8, 10000);
  int32_t pods[3];
  pods[0] = kvidx_intern(idx, "pod-0");
  pods[1] = kvidx_intern(idx, "pod-1");
  pods[2] = kvidx_intern(idx, "pod-2");
  int32_t hbm = kvidx_intern(idx, "tpu-hbm");
  int32_t cpu = kvidx_intern(idx, "cpu");

  constexpr int kKeys = 32;
  uint64_t keys[kKeys];
  for (int i = 0; i < kKeys; ++i) keys[i] = 1000 + i;
  uint8_t zero_flag = 0;
  int32_t zero_group = 0;
  // pod-0 holds keys 0..8 in HBM, pod-1 holds 0..19 in cpu, pod-2 holds
  // nothing; the chain breaks globally at key 20.
  for (int i = 0; i < 9; ++i) {
    kvidx_add(idx, nullptr, 0, &keys[i], 1, &pods[0], &hbm, &zero_flag,
              &zero_group, 1);
  }
  for (int i = 0; i < 20; ++i) {
    kvidx_add(idx, nullptr, 0, &keys[i], 1, &pods[1], &cpu, &zero_flag,
              &zero_group, 1);
  }
  // resident island past the break: must never score
  for (int i = 25; i < kKeys; ++i) {
    kvidx_add(idx, nullptr, 0, &keys[i], 1, &pods[0], &hbm, &zero_flag,
              &zero_group, 1);
  }

  int32_t wt[2] = {hbm, cpu};
  double wv[2] = {2.0, 1.0};

  int32_t ref_pods[16], chunk_pods[16], res_pods[16];
  double ref_scores[16], chunk_scores[16], res_bonus[16];
  int32_t ref_hits = 0, hits = 0, chunks = 0, early = 0, res_n = 0;

  int ref_n = kvidx_score_ex(idx, keys, kKeys, nullptr, 0, wt, wv, 2,
                             ref_pods, ref_scores, 16, &ref_hits, 1);
  CHECK(ref_n == 2);

  for (int chunk_size : {1, 4, 7, 32, 64, 0}) {
    int n = kvidx_score_chunked(
        idx, keys, kKeys, nullptr, 0, wt, wv, 2, chunk_size, nullptr, nullptr,
        nullptr, 0, 1.0, 0.5, 1.0, chunk_pods, chunk_scores, 16, &hits,
        &chunks, &early, res_pods, res_bonus, 16, &res_n);
    CHECK(n == ref_n);
    // same (pod, score) pairs regardless of chunk granularity
    for (int i = 0; i < n; ++i) {
      bool found = false;
      for (int j = 0; j < ref_n; ++j) {
        if (chunk_pods[i] == ref_pods[j]) {
          CHECK(chunk_scores[i] == ref_scores[j]);
          found = true;
        }
      }
      CHECK(found);
    }
    CHECK(res_n == 0);
    if (chunk_size <= 0 || chunk_size >= kKeys) {
      // one full-array chunk: no early exit possible, every hit counted
      CHECK(chunks == 1);
      CHECK(early == 0);
      CHECK(hits == 20 + 7);
    } else {
      // the chain breaks at key 20: the scan stops at that chunk's end
      int break_chunk = 20 / chunk_size;
      CHECK(chunks == break_chunk + 1);
      CHECK(early == 1);
      CHECK(hits <= 20 + 7);
    }
  }

  // pod-0's score: 9 HBM keys at weight 2; pod-1: 20 cpu keys at 1.
  for (int i = 0; i < ref_n; ++i) {
    if (ref_pods[i] == pods[0]) CHECK(ref_scores[i] == 18.0);
    if (ref_pods[i] == pods[1]) CHECK(ref_scores[i] == 20.0);
  }

  // Residency fold-in: pod-2 has landed claims on indices 0..2 and an
  // in-flight claim on 3 (bonus 3*1.0 + 0.5), pod-0 claims indices 1..2
  // only (no index-0 claim: walk breaks immediately, no bonus), pod-1
  // claims index 0 in-flight (bonus 0.5). tier_discount scales totals.
  int32_t cl_pods[] = {pods[2], pods[2], pods[2], pods[2],
                       pods[0], pods[0], pods[1]};
  int32_t cl_idx[] = {0, 1, 2, 3, 1, 2, 0};
  uint8_t cl_landed[] = {1, 1, 1, 0, 1, 1, 0};
  int n = kvidx_score_chunked(
      idx, keys, kKeys, nullptr, 0, wt, wv, 2, 8, cl_pods, cl_idx, cl_landed,
      7, 1.0, 0.5, 0.25, chunk_pods, chunk_scores, 16, &hits, &chunks, &early,
      res_pods, res_bonus, 16, &res_n);
  CHECK(n == ref_n);  // base scores untouched by claims
  CHECK(res_n == 2);
  for (int i = 0; i < res_n; ++i) {
    if (res_pods[i] == pods[2]) CHECK(res_bonus[i] == 3.5 * 0.25);
    if (res_pods[i] == pods[1]) CHECK(res_bonus[i] == 0.5 * 0.25);
    CHECK(res_pods[i] != pods[0]);
  }

  // Empty key array: zero chunks of work, no early exit.
  n = kvidx_score_chunked(idx, keys, 0, nullptr, 0, wt, wv, 2, 8, nullptr,
                          nullptr, nullptr, 0, 1.0, 0.5, 1.0, chunk_pods,
                          chunk_scores, 16, &hits, &chunks, &early, res_pods,
                          res_bonus, 16, &res_n);
  CHECK(n == 0 && hits == 0 && chunks == 0 && early == 0 && res_n == 0);

  // Buffer-too-small: -needed retry contract matches kvidx_score.
  n = kvidx_score_chunked(idx, keys, kKeys, nullptr, 0, wt, wv, 2, 8, nullptr,
                          nullptr, nullptr, 0, 1.0, 0.5, 1.0, chunk_pods,
                          chunk_scores, 1, &hits, &chunks, &early, res_pods,
                          res_bonus, 16, &res_n);
  CHECK(n == -2);

  kvidx_destroy(idx);
  std::printf("kvidx_score_chunked OK\n");
}

}  // namespace

int main() {
  TestScoreChunked();
  void* idx = kvidx_create(100000, 4, 100000);
  int32_t pods[4];
  char name[8];
  for (int p = 0; p < 4; ++p) {
    std::snprintf(name, sizeof(name), "pod-%d", p);
    pods[p] = kvidx_intern(idx, name);
  }
  int32_t tier = kvidx_intern(idx, "tpu-hbm");

  constexpr int kThreads = 6;
  constexpr int kOps = 4000;
  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back([&, t] {
      std::mt19937_64 rng(t);
      for (int i = 0; i < kOps; ++i) {
        uint64_t keys[4] = {rng() % 512 + 1, rng() % 512 + 1,
                            rng() % 512 + 1, rng() % 512 + 1};
        int32_t entry_pod = pods[t % 4];
        uint8_t flags = 0;
        int32_t group = 0;
        switch (i % 5) {
          case 0:
          case 1:
            kvidx_add(idx, keys, 4, keys, 4, &entry_pod, &tier, &flags,
                      &group, 1);
            break;
          case 2: {
            int32_t counts[4], out_entries[256];
            kvidx_lookup(idx, keys, 4, nullptr, 0, counts, out_entries, 256);
            // fused chunked score under contention (with a claim row so
            // the residency walk also runs inside the lock)
            int32_t wt = tier;
            double wv = 1.0;
            int32_t sp[16], rp[4], claim_idx = 0;
            double ss[16], rb[4];
            int32_t sh = 0, sc = 0, se = 0, rn = 0;
            uint8_t landed = 1;
            kvidx_score_chunked(idx, keys, 4, nullptr, 0, &wt, &wv, 1, 2,
                                &entry_pod, &claim_idx, &landed, 1, 1.0, 0.5,
                                1.0, sp, ss, 16, &sh, &sc, &se, rp, rb, 4,
                                &rn);
            break;
          }
          case 3:
            kvidx_evict(idx, keys[0], i % 2, &entry_pod, &tier, &flags,
                        &group, 1);
            kvidx_get_request_key(idx, keys[1]);
            break;
          case 4:
            if (i % 1000 == 999) kvidx_clear(idx, entry_pod);
            kvidx_len(idx);
            break;
        }
      }
    });
  }
  for (auto& t : threads) t.join();
  kvidx_destroy(idx);
  std::printf("kvindex_test OK\n");
  return 0;
}
