#include "kvio_numa.hpp"

#include <dirent.h>
#include <pthread.h>
#include <sched.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <cstdlib>
#include <cstring>
#include <fstream>

namespace kvio {

namespace {

// Read a small sysfs attribute; empty string on failure.
std::string ReadSysfs(const std::string& path) {
  std::ifstream f(path);
  if (!f.is_open()) return "";
  std::string line;
  std::getline(f, line);
  return line;
}

int ParseIntOr(const std::string& s, int fallback) {
  if (s.empty()) return fallback;
  char* end = nullptr;
  long v = std::strtol(s.c_str(), &end, 0);  // sysfs vendor ids are 0x-prefixed
  if (end == s.c_str()) return fallback;
  return static_cast<int>(v);
}

constexpr int kGoogleVendorId = 0x1ae0;

}  // namespace

int DiscoverAcceleratorNumaNode() {
  if (const char* env = std::getenv("KVIO_NUMA_NODE")) {
    return ParseIntOr(env, -1);
  }
  DIR* dir = opendir("/sys/bus/pci/devices");
  if (dir == nullptr) return -1;
  int found = -1;
  while (struct dirent* ent = readdir(dir)) {
    if (ent->d_name[0] == '.') continue;
    std::string base = std::string("/sys/bus/pci/devices/") + ent->d_name;
    if (ParseIntOr(ReadSysfs(base + "/vendor"), -1) != kGoogleVendorId) {
      continue;
    }
    int node = ParseIntOr(ReadSysfs(base + "/numa_node"), -1);
    if (node >= 0) {
      found = node;
      break;
    }
  }
  closedir(dir);
  return found;
}

std::vector<int> ParseCpuList(const std::string& line) {
  std::vector<int> cpus;
  size_t start = 0;
  while (start < line.size()) {
    size_t comma = line.find(',', start);
    size_t len = (comma == std::string::npos) ? std::string::npos
                                              : comma - start;
    std::string token = line.substr(start, len);
    // Trim whitespace/newline
    while (!token.empty() && std::isspace(static_cast<unsigned char>(token.back()))) {
      token.pop_back();
    }
    if (!token.empty()) {
      size_t dash = token.find('-');
      char* end = nullptr;
      if (dash != std::string::npos) {
        long a = std::strtol(token.c_str(), &end, 10);
        bool a_ok = end != token.c_str();
        const char* bstart = token.c_str() + dash + 1;
        long b = std::strtol(bstart, &end, 10);
        bool b_ok = end != bstart;
        if (a_ok && b_ok && a >= 0 && a <= b) {
          for (long c = a; c <= b; ++c) cpus.push_back(static_cast<int>(c));
        }
      } else {
        long a = std::strtol(token.c_str(), &end, 10);
        if (end != token.c_str() && a >= 0) cpus.push_back(static_cast<int>(a));
      }
    }
    if (comma == std::string::npos) break;
    start = comma + 1;
  }
  return cpus;
}

std::vector<int> CpusInNumaNode(int node) {
  if (node < 0) return {};
  std::string path = "/sys/devices/system/node/node" + std::to_string(node) +
                     "/cpulist";
  std::string line = ReadSysfs(path);
  if (line.empty()) return {};
  return ParseCpuList(line);
}

bool SetPreferredNode(int node) {
#ifdef __NR_set_mempolicy
  if (node < 0) return false;
  // MPOL_PREFERRED = 1; nodemask is a bitmask of nodes.
  constexpr int kMpolPreferred = 1;
  unsigned long mask[16] = {0};
  if (node >= static_cast<int>(sizeof(mask) * 8)) return false;
  mask[node / (8 * sizeof(unsigned long))] |=
      1UL << (node % (8 * sizeof(unsigned long)));
  long rc = syscall(__NR_set_mempolicy, kMpolPreferred, mask,
                    sizeof(mask) * 8);
  return rc == 0;
#else
  (void)node;
  return false;
#endif
}

bool PinThreadToCpu(int cpu) {
  if (cpu < 0) return false;
  cpu_set_t set;
  CPU_ZERO(&set);
  CPU_SET(cpu, &set);
  return pthread_setaffinity_np(pthread_self(), sizeof(set), &set) == 0;
}

}  // namespace kvio
