// Concurrency test driver for the kvio engine, built with
// -fsanitize=thread (see Makefile `tsan` target): the GIL hides C++ data
// races from the Python 3x-rerun tier, so the submit/poll/cancel/shed
// paths get hammered here under TSAN, the role `go test -race` plays for
// the reference's index.
//
// Exits non-zero on any functional failure; TSAN itself aborts the
// process on a detected race.

#include "kvio.hpp"

#include <atomic>
#include <cstdio>
#include <cstring>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

int failures = 0;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,   \
                   __LINE__, #cond);                                  \
      ++failures;                                                     \
    }                                                                 \
  } while (0)

std::string TmpDir() {
  char templ[] = "/tmp/kvio_test_XXXXXX";
  char* dir = mkdtemp(templ);
  return dir != nullptr ? std::string(dir) : std::string("/tmp");
}

// Writers, readers, pollers, and cancellers all racing one engine.
void StressMixedWorkload(const std::string& root) {
  kvio::Engine engine(/*num_threads=*/4, /*read_preferring_workers=*/2,
                      /*max_write_queued_seconds=*/5.0, /*numa_node=*/-2,
                      /*staging_bytes=*/1 << 16, /*direct_io=*/true);

  constexpr int kProducers = 4;
  constexpr int kJobsPerProducer = 40;
  constexpr int kBufBytes = 8192;
  std::atomic<int> finished{0};
  std::atomic<bool> stop_polling{false};

  std::vector<std::thread> producers;
  // Per-producer buffers outlive the jobs (engine holds raw pointers).
  std::vector<std::vector<std::vector<uint8_t>>> buffers(kProducers);
  for (int p = 0; p < kProducers; ++p) {
    buffers[p].resize(kJobsPerProducer * 2,
                      std::vector<uint8_t>(kBufBytes, 0));
  }

  for (int p = 0; p < kProducers; ++p) {
    producers.emplace_back([&, p] {
      std::mt19937 rng(p);
      for (int j = 0; j < kJobsPerProducer; ++j) {
        auto& wbuf = buffers[p][j * 2];
        auto& rbuf = buffers[p][j * 2 + 1];
        std::memset(wbuf.data(), (p * 37 + j) & 0xff, kBufBytes);
        std::string path =
            root + "/p" + std::to_string(p) + "_" + std::to_string(j);

        uint64_t wjob = engine.BeginJob();
        engine.SubmitWrite(wjob, path, path + ".tmp", wbuf.data(), kBufBytes,
                           /*skip_if_exists=*/false);
        engine.SealJob(wjob);
        // Half the producers cancel-and-wait (the preemption path), half
        // let the poller drain the job.
        if (p % 2 == 0) {
          int wstatus = engine.WaitJob(wjob, 10.0);
          // cancel-and-wait may cancel the queued write; only a completed
          // write guarantees the file exists for the read that follows.
          if (wstatus == kvio::kOk) {
            uint64_t rjob = engine.BeginJob();
            engine.SubmitRead(rjob, path, rbuf.data(), kBufBytes, 0);
            engine.SealJob(rjob);
            int status = engine.WaitJob(rjob, 10.0);
            // kOk when finished before the cancel, kCancelled otherwise;
            // both are legal outcomes of cancel-and-wait.
            CHECK(status == kvio::kOk || status == kvio::kCancelled);
          }
        }
      }
      finished.fetch_add(1);
    });
  }

  std::thread poller([&] {
    uint64_t ids[16];
    int statuses[16];
    while (!stop_polling.load()) {
      engine.PollFinished(ids, statuses, 16);
      engine.AvgWriteSeconds();
      engine.QueuedWrites();
    }
  });

  while (finished.load() < kProducers) {
    std::this_thread::yield();
  }
  stop_polling.store(true);
  poller.join();
  for (auto& t : producers) t.join();
  engine.Shutdown();
}

// Shutdown racing in-flight submissions must not crash or deadlock.
void StressShutdownRace(const std::string& root) {
  for (int round = 0; round < 8; ++round) {
    auto* engine = new kvio::Engine(2, 1, 5.0, -2, 0, false);
    std::vector<uint8_t> buf(4096, 7);
    std::atomic<bool> stop{false};
    std::thread submitter([&] {
      int i = 0;
      while (!stop.load()) {
        uint64_t job = engine->BeginJob();
        std::string path = root + "/s" + std::to_string(round) + "_" +
                           std::to_string(i++ % 8);
        engine->SubmitWrite(job, path, path + ".tmp", buf.data(), buf.size(),
                            true);
        engine->SealJob(job);
      }
    });
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
    engine->Shutdown();
    stop.store(true);
    submitter.join();
    delete engine;
  }
}

}  // namespace

int main() {
  std::string root = TmpDir();
  StressMixedWorkload(root);
  StressShutdownRace(root);
  if (failures != 0) {
    std::fprintf(stderr, "%d check(s) failed\n", failures);
    return 1;
  }
  std::printf("kvio_test OK\n");
  return 0;
}
