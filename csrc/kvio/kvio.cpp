// Implementation of the KV offload I/O engine + C ABI for ctypes.
// See kvio.hpp for design notes and reference parity table.

#include "kvio.hpp"

#include <fcntl.h>
#include <sched.h>
#include <sys/stat.h>
#include <sys/time.h>
#include <unistd.h>
#include <utime.h>

#include <cerrno>
#include <chrono>
#include <cstdio>
#include <cstring>

namespace kvio {

namespace {

double NowSeconds() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

bool MakeParentDirs(const std::string& path) {
  std::string dir = path;
  size_t pos = dir.find_last_of('/');
  if (pos == std::string::npos) return true;
  dir.resize(pos);
  std::string partial;
  size_t start = 0;
  if (!dir.empty() && dir[0] == '/') {
    partial = "/";
    start = 1;
  }
  while (start <= dir.size()) {
    size_t next = dir.find('/', start);
    if (next == std::string::npos) next = dir.size();
    partial.append(dir, start, next - start);
    if (!partial.empty() && partial != "/") {
      if (mkdir(partial.c_str(), 0777) != 0 && errno != EEXIST) return false;
    }
    partial.push_back('/');
    start = next + 1;
  }
  return true;
}

// Atomic write: temp file + rename so readers never observe partial files
// (the reference's FileIO discipline, file_io.cpp:44-108).
bool WriteFileAtomic(const std::string& final_path, const std::string& tmp_path,
                     const uint8_t* data, uint64_t len, bool skip_if_exists) {
  if (skip_if_exists) {
    struct stat st;
    if (stat(final_path.c_str(), &st) == 0) {
      // Idempotent store: refresh atime as an eviction-recency signal
      // (storage_offload.cpp:317-320 equivalent).
      utime(final_path.c_str(), nullptr);
      return true;
    }
  }
  if (!MakeParentDirs(final_path)) return false;

  int fd = open(tmp_path.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return false;
  uint64_t written = 0;
  while (written < len) {
    ssize_t n = write(fd, data + written, len - written);
    if (n < 0) {
      if (errno == EINTR) continue;
      close(fd);
      unlink(tmp_path.c_str());
      return false;
    }
    written += static_cast<uint64_t>(n);
  }
  if (close(fd) != 0) {
    unlink(tmp_path.c_str());
    return false;
  }
  if (rename(tmp_path.c_str(), final_path.c_str()) != 0) {
    unlink(tmp_path.c_str());
    return false;
  }
  return true;
}

bool ReadFileRange(const std::string& path, uint8_t* dst, uint64_t len,
                   uint64_t offset) {
  int fd = open(path.c_str(), O_RDONLY);
  if (fd < 0) return false;
  uint64_t done = 0;
  while (done < len) {
    ssize_t n = pread(fd, dst + done, len - done,
                      static_cast<off_t>(offset + done));
    if (n < 0) {
      if (errno == EINTR) continue;
      close(fd);
      return false;
    }
    if (n == 0) break;  // short file
    done += static_cast<uint64_t>(n);
  }
  close(fd);
  // Refresh atime so the evictor's recency scan sees the hit.
  utime(path.c_str(), nullptr);
  return done == len;
}

}  // namespace

Engine::Engine(int num_threads, int read_preferring_workers,
               double max_write_queued_seconds)
    : num_threads_(num_threads > 0 ? num_threads : 1),
      read_preferring_workers_(read_preferring_workers),
      max_write_queued_seconds_(max_write_queued_seconds) {
  workers_.reserve(num_threads_);
  for (int i = 0; i < num_threads_; ++i) {
    workers_.emplace_back([this, i] { WorkerLoop(i); });
  }
}

Engine::~Engine() { Shutdown(); }

void Engine::Shutdown() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (shutdown_) return;
    shutdown_ = true;
  }
  cv_.notify_all();
  for (auto& t : workers_) {
    if (t.joinable()) t.join();
  }
  std::lock_guard<std::mutex> jl(jobs_mu_);
  for (auto& [id, job] : jobs_) delete job;
  jobs_.clear();
}

uint64_t Engine::BeginJob() {
  uint64_t id = next_job_id_.fetch_add(1);
  auto* job = new JobState();
  job->id = id;
  std::lock_guard<std::mutex> lk(jobs_mu_);
  jobs_[id] = job;
  return id;
}

void Engine::SealJob(uint64_t job_id) {
  std::lock_guard<std::mutex> lk(jobs_mu_);
  auto it = jobs_.find(job_id);
  if (it == jobs_.end()) return;
  it->second->sealed.store(true);
  JobState* job = it->second;
  if (job->completed.load() + job->failed.load() == job->total.load()) {
    finished_ready_.push_back(job_id);
    jobs_cv_.notify_all();
  }
}

int Engine::QueuedWrites() const {
  std::lock_guard<std::mutex> lk(mu_);
  return static_cast<int>(normal_queue_.size());
}

int Engine::SubmitWrite(uint64_t job_id, const std::string& path,
                        const std::string& tmp_path, const void* data,
                        uint64_t len, bool skip_if_exists) {
  // Dynamic write-queue limit: don't queue more write-seconds than the
  // pool can retire within max_write_queued_seconds (the reference's
  // EMA shedding, storage_offload.cpp:80-108,283-299). Dropped writes
  // degrade to cache misses later, never to data loss.
  double avg = avg_write_seconds_.load();
  if (avg > 0 && max_write_queued_seconds_ > 0) {
    double limit = num_threads_ * max_write_queued_seconds_ / avg;
    // Never shed below one queued write: a single pathological slow write
    // would otherwise truncate the limit to 0 and starve (and since the
    // EMA only updates on executed writes, never recover).
    int limit_i = limit < 1.0 ? 1 : static_cast<int>(limit);
    if (QueuedWrites() >= limit_i) {
      return 0;
    }
  }

  Task task;
  task.kind = TaskKind::kWrite;
  task.job_id = job_id;
  task.path = path;
  task.tmp_path = tmp_path;
  task.src = static_cast<const uint8_t*>(data);
  task.len = len;
  task.skip_if_exists = skip_if_exists;

  {
    std::lock_guard<std::mutex> jl(jobs_mu_);
    auto it = jobs_.find(job_id);
    if (it != jobs_.end()) it->second->total.fetch_add(1);
  }
  {
    std::lock_guard<std::mutex> lk(mu_);
    normal_queue_.push_back(std::move(task));
  }
  cv_.notify_one();
  return 1;
}

void Engine::SubmitRead(uint64_t job_id, const std::string& path, void* dst,
                        uint64_t len, uint64_t offset) {
  Task task;
  task.kind = TaskKind::kRead;
  task.job_id = job_id;
  task.path = path;
  task.dst = static_cast<uint8_t*>(dst);
  task.len = len;
  task.offset = offset;

  {
    std::lock_guard<std::mutex> jl(jobs_mu_);
    auto it = jobs_.find(job_id);
    if (it != jobs_.end()) it->second->total.fetch_add(1);
  }
  {
    std::lock_guard<std::mutex> lk(mu_);
    high_queue_.push_back(std::move(task));
  }
  cv_.notify_one();
}

void Engine::WorkerLoop(int worker_index) {
  // The first read_preferring_workers_ drain the high (read) queue first;
  // the rest prefer writes but steal reads when idle (thread_pool.cpp:44-61
  // equivalent).
  const bool prefer_reads = worker_index < read_preferring_workers_;
  for (;;) {
    Task task;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [this] {
        return shutdown_ || !high_queue_.empty() || !normal_queue_.empty();
      });
      if (shutdown_ && high_queue_.empty() && normal_queue_.empty()) return;
      std::deque<Task>* first = prefer_reads ? &high_queue_ : &normal_queue_;
      std::deque<Task>* second = prefer_reads ? &normal_queue_ : &high_queue_;
      std::deque<Task>* src_q = !first->empty() ? first : second;
      task = std::move(src_q->front());
      src_q->pop_front();
    }

    bool cancelled = false;
    {
      std::lock_guard<std::mutex> jl(jobs_mu_);
      auto it = jobs_.find(task.job_id);
      if (it != jobs_.end() && it->second->cancelled.load()) cancelled = true;
    }
    bool ok = cancelled ? false : RunTask(task);
    FinishTask(task, ok);
  }
}

bool Engine::RunTask(Task& task) {
  double start = NowSeconds();
  bool ok;
  if (task.kind == TaskKind::kWrite) {
    ok = WriteFileAtomic(task.path, task.tmp_path, task.src, task.len,
                         task.skip_if_exists);
    double dur = NowSeconds() - start;
    double prev = avg_write_seconds_.load();
    avg_write_seconds_.store(prev == 0.0 ? dur : 0.8 * prev + 0.2 * dur);
  } else {
    ok = ReadFileRange(task.path, task.dst, task.len, task.offset);
  }
  return ok;
}

void Engine::FinishTask(const Task& task, bool ok) {
  std::lock_guard<std::mutex> jl(jobs_mu_);
  auto it = jobs_.find(task.job_id);
  if (it == jobs_.end()) return;
  JobState* job = it->second;
  if (ok) {
    job->completed.fetch_add(1);
    job->bytes.fetch_add(task.len);
  } else {
    job->failed.fetch_add(1);
  }
  if (job->sealed.load() &&
      job->completed.load() + job->failed.load() == job->total.load()) {
    finished_ready_.push_back(job->id);
    jobs_cv_.notify_all();
  }
}

int Engine::PollFinished(uint64_t* ids, int* statuses, int max_items) {
  std::lock_guard<std::mutex> jl(jobs_mu_);
  int n = 0;
  while (n < max_items && !finished_ready_.empty()) {
    uint64_t id = finished_ready_.back();
    finished_ready_.pop_back();
    auto it = jobs_.find(id);
    if (it == jobs_.end()) continue;
    JobState* job = it->second;
    ids[n] = id;
    if (job->cancelled.load()) {
      statuses[n] = kCancelled;
    } else {
      statuses[n] = job->failed.load() > 0 ? kIoError : kOk;
    }
    delete job;
    jobs_.erase(it);
    ++n;
  }
  return n;
}

int Engine::WaitJob(uint64_t job_id, double timeout_seconds) {
  // Cancellation-for-preemption: mark cancelled so queued tasks are skipped,
  // then wait for in-flight ones (storage_offload.cpp:217-236 equivalent).
  {
    std::lock_guard<std::mutex> jl(jobs_mu_);
    auto it = jobs_.find(job_id);
    if (it == jobs_.end()) return kOk;  // already finished+polled
    JobState* job = it->second;
    if (job->sealed.load() &&
        job->completed.load() + job->failed.load() == job->total.load()) {
      // Finished before the wait: report the real outcome, don't cancel.
      int status = job->failed.load() > 0 ? kIoError : kOk;
      delete job;
      jobs_.erase(it);
      for (auto fit = finished_ready_.begin(); fit != finished_ready_.end();
           ++fit) {
        if (*fit == job_id) {
          finished_ready_.erase(fit);
          break;
        }
      }
      return status;
    }
    job->cancelled.store(true);
    job->sealed.store(true);
  }
  std::unique_lock<std::mutex> jl(jobs_mu_);
  bool done = jobs_cv_.wait_for(
      jl, std::chrono::duration<double>(timeout_seconds), [&] {
        auto it = jobs_.find(job_id);
        if (it == jobs_.end()) return true;
        JobState* job = it->second;
        return job->completed.load() + job->failed.load() == job->total.load();
      });
  if (!done) return kPending;
  auto it = jobs_.find(job_id);
  if (it == jobs_.end()) return kOk;
  int status = kCancelled;
  delete it->second;
  jobs_.erase(it);
  // Also drop from finished_ready_ if it landed there.
  for (auto fit = finished_ready_.begin(); fit != finished_ready_.end(); ++fit) {
    if (*fit == job_id) {
      finished_ready_.erase(fit);
      break;
    }
  }
  return status;
}

}  // namespace kvio

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

void* kvio_create(int num_threads, int read_preferring_workers,
                  double max_write_queued_seconds) {
  return new kvio::Engine(num_threads, read_preferring_workers,
                          max_write_queued_seconds);
}

void kvio_destroy(void* engine) { delete static_cast<kvio::Engine*>(engine); }

uint64_t kvio_begin_job(void* engine) {
  return static_cast<kvio::Engine*>(engine)->BeginJob();
}

void kvio_seal_job(void* engine, uint64_t job_id) {
  static_cast<kvio::Engine*>(engine)->SealJob(job_id);
}

int kvio_submit_write(void* engine, uint64_t job_id, const char* path,
                      const char* tmp_path, const void* data, uint64_t len,
                      int skip_if_exists) {
  return static_cast<kvio::Engine*>(engine)->SubmitWrite(
      job_id, path, tmp_path, data, len, skip_if_exists != 0);
}

void kvio_submit_read(void* engine, uint64_t job_id, const char* path,
                      void* dst, uint64_t len, uint64_t offset) {
  static_cast<kvio::Engine*>(engine)->SubmitRead(job_id, path, dst, len,
                                                 offset);
}

int kvio_poll_finished(void* engine, uint64_t* ids, int* statuses,
                       int max_items) {
  return static_cast<kvio::Engine*>(engine)->PollFinished(ids, statuses,
                                                          max_items);
}

int kvio_wait_job(void* engine, uint64_t job_id, double timeout_seconds) {
  return static_cast<kvio::Engine*>(engine)->WaitJob(job_id, timeout_seconds);
}

double kvio_avg_write_seconds(void* engine) {
  return static_cast<kvio::Engine*>(engine)->AvgWriteSeconds();
}

int kvio_queued_writes(void* engine) {
  return static_cast<kvio::Engine*>(engine)->QueuedWrites();
}

int kvio_file_exists(const char* path, int touch_atime) {
  struct stat st;
  if (stat(path, &st) != 0) return 0;
  if (touch_atime) utime(path, nullptr);
  return 1;
}
}
