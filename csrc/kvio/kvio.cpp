// Implementation of the KV offload I/O engine + C ABI for ctypes.
// See kvio.hpp for design notes and reference parity table.

#ifndef _GNU_SOURCE
#define _GNU_SOURCE  // O_DIRECT
#endif

#include "kvio.hpp"
#include "kvio_numa.hpp"

#include <fcntl.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/time.h>
#include <unistd.h>
#include <utime.h>

#include <cstdlib>

#include <algorithm>
#include <cerrno>
#include <chrono>
#include <cstdio>
#include <cstring>

namespace kvio {

namespace {

double NowSeconds() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

bool MakeParentDirs(const std::string& path) {
  std::string dir = path;
  size_t pos = dir.find_last_of('/');
  if (pos == std::string::npos) return true;
  dir.resize(pos);
  std::string partial;
  size_t start = 0;
  if (!dir.empty() && dir[0] == '/') {
    partial = "/";
    start = 1;
  }
  while (start <= dir.size()) {
    size_t next = dir.find('/', start);
    if (next == std::string::npos) next = dir.size();
    partial.append(dir, start, next - start);
    if (!partial.empty() && partial != "/") {
      if (mkdir(partial.c_str(), 0777) != 0 && errno != EEXIST) return false;
    }
    partial.push_back('/');
    start = next + 1;
  }
  return true;
}

// --- Atomic-write discipline, shared by the buffered and O_DIRECT paths
// (the reference's FileIO discipline, file_io.cpp:44-108): dedup+atime,
// parent dirs, write to temp, publish via rename, unlink temp on error. ---

// Idempotent-store dedup: true if the final file already exists (atime
// refreshed as an eviction-recency signal, storage_offload.cpp:317-320).
bool ExistingFileReused(const std::string& final_path) {
  struct stat st;
  if (stat(final_path.c_str(), &st) != 0) return false;
  utime(final_path.c_str(), nullptr);
  return true;
}

// close + rename-to-publish; unlinks the temp on any failure.
bool PublishTmpFile(int fd, const std::string& final_path,
                    const std::string& tmp_path) {
  if (close(fd) != 0) {
    unlink(tmp_path.c_str());
    return false;
  }
  if (rename(tmp_path.c_str(), final_path.c_str()) != 0) {
    unlink(tmp_path.c_str());
    return false;
  }
  return true;
}

// Abort a half-written temp file.
bool AbortTmpFile(int fd, const std::string& tmp_path) {
  close(fd);
  unlink(tmp_path.c_str());
  return false;
}

bool WriteFileAtomic(const std::string& final_path, const std::string& tmp_path,
                     const uint8_t* data, uint64_t len, bool skip_if_exists) {
  if (skip_if_exists && ExistingFileReused(final_path)) return true;
  if (!MakeParentDirs(final_path)) return false;

  int fd = open(tmp_path.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return false;
  uint64_t written = 0;
  while (written < len) {
    ssize_t n = write(fd, data + written, len - written);
    if (n < 0) {
      if (errno == EINTR) continue;
      return AbortTmpFile(fd, tmp_path);
    }
    written += static_cast<uint64_t>(n);
  }
  return PublishTmpFile(fd, final_path, tmp_path);
}

// In-place partial write for multi-block files: provision the file to its
// full size (sparse beyond written slots), then pwrite the slot bytes.
// Deliberately not atomic — the tmp+rename discipline only fits whole-file
// publishes; slot updates mirror the reference's in-place partial-file
// writes (worker.py head_offsets + file_io write path).
bool WriteFileRangeAt(const std::string& path, const uint8_t* data,
                      uint64_t len, uint64_t offset, uint64_t file_size) {
  if (!MakeParentDirs(path)) return false;
  int fd = open(path.c_str(), O_RDWR | O_CREAT, 0644);
  if (fd < 0) return false;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return false;
  }
  if (static_cast<uint64_t>(st.st_size) < file_size &&
      ftruncate(fd, static_cast<off_t>(file_size)) != 0) {
    close(fd);
    return false;
  }
  uint64_t done = 0;
  while (done < len) {
    ssize_t n = pwrite(fd, data + done, len - done,
                       static_cast<off_t>(offset + done));
    if (n <= 0) {  // n==0 would spin forever; treat as failure
      if (n < 0 && errno == EINTR) continue;
      close(fd);
      return false;
    }
    done += static_cast<uint64_t>(n);
  }
  bool ok = close(fd) == 0;
  if (ok) utime(path.c_str(), nullptr);
  return ok;
}

bool ReadFileRange(const std::string& path, uint8_t* dst, uint64_t len,
                   uint64_t offset) {
  int fd = open(path.c_str(), O_RDONLY);
  if (fd < 0) return false;
  uint64_t done = 0;
  while (done < len) {
    ssize_t n = pread(fd, dst + done, len - done,
                      static_cast<off_t>(offset + done));
    if (n < 0) {
      if (errno == EINTR) continue;
      close(fd);
      return false;
    }
    if (n == 0) break;  // short file
    done += static_cast<uint64_t>(n);
  }
  close(fd);
  // Refresh atime so the evictor's recency scan sees the hit.
  utime(path.c_str(), nullptr);
  return done == len;
}

}  // namespace

Engine::Engine(int num_threads, int read_preferring_workers,
               double max_write_queued_seconds, int numa_node,
               uint64_t staging_bytes, bool direct_io)
    : num_threads_(num_threads > 0 ? num_threads : 1),
      read_preferring_workers_(read_preferring_workers),
      max_write_queued_seconds_(max_write_queued_seconds),
      staging_bytes_(staging_bytes),
      direct_io_(direct_io) {
  // Resolve placement: explicit node, auto-discovered accelerator host
  // node, or disabled (-2). Round-robin workers over the node's CPUs
  // (thread_pool.cpp:110-127 semantics). When no node resolves (non-NUMA
  // VM, no accelerator visible) workers stay UNPINNED — pinning to an
  // arbitrary all-CPU fallback would stack every engine instance onto the
  // same first N cores.
  std::vector<int> cpus;
  if (numa_node != -2) {
    numa_node_ = numa_node >= 0 ? numa_node : DiscoverAcceleratorNumaNode();
    if (numa_node_ >= 0) cpus = CpusInNumaNode(numa_node_);
  }
  worker_cpus_.assign(num_threads_, -1);
  if (!cpus.empty()) {
    for (int i = 0; i < num_threads_; ++i) {
      worker_cpus_[i] = cpus[i % cpus.size()];
    }
  }
  workers_.reserve(num_threads_);
  for (int i = 0; i < num_threads_; ++i) {
    workers_.emplace_back([this, i] { WorkerLoop(i); });
  }
}

Engine::~Engine() {
  Shutdown();
  // A BeginJob racing Shutdown can insert into jobs_ after the shutdown
  // sweep (BeginJob deliberately holds only jobs_mu_, never mu_). By the
  // time the destructor runs no callers remain, so sweep once more to
  // reclaim those stragglers.
  std::lock_guard<std::mutex> jl(jobs_mu_);
  for (auto& [id, job] : jobs_) delete job;
  jobs_.clear();
}

void Engine::Shutdown() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (shutdown_) return;
    shutdown_ = true;
  }
  cv_.notify_all();
  for (auto& t : workers_) {
    if (t.joinable()) t.join();
  }
  std::lock_guard<std::mutex> jl(jobs_mu_);
  for (auto& [id, job] : jobs_) delete job;
  jobs_.clear();
}

uint64_t Engine::BeginJob() {
  uint64_t id = next_job_id_.fetch_add(1);
  auto* job = new JobState();
  job->id = id;
  std::lock_guard<std::mutex> lk(jobs_mu_);
  jobs_[id] = job;
  return id;
}

void Engine::SealJob(uint64_t job_id) {
  std::lock_guard<std::mutex> lk(jobs_mu_);
  auto it = jobs_.find(job_id);
  if (it == jobs_.end()) return;
  it->second->sealed.store(true);
  JobState* job = it->second;
  if (job->completed.load() + job->failed.load() == job->total.load()) {
    finished_ready_.push_back(job_id);
    jobs_cv_.notify_all();
  }
}

int Engine::QueuedWrites() const {
  std::lock_guard<std::mutex> lk(mu_);
  return static_cast<int>(normal_queue_.size());
}

bool Engine::ShouldShedWrite() {
  // Dynamic write-queue limit: don't queue more write-seconds than the
  // pool can retire within max_write_queued_seconds (the reference's
  // EMA shedding, storage_offload.cpp:80-108,283-299). Dropped writes
  // degrade to cache misses later, never to data loss.
  double avg = avg_write_seconds_.load();
  if (avg > 0 && max_write_queued_seconds_ > 0) {
    double limit = num_threads_ * max_write_queued_seconds_ / avg;
    // Never shed below one queued write: a single pathological slow write
    // would otherwise truncate the limit to 0 and starve (and since the
    // EMA only updates on executed writes, never recover).
    int limit_i = limit < 1.0 ? 1 : static_cast<int>(limit);
    if (QueuedWrites() >= limit_i) return true;
  }
  return false;
}

void Engine::EnqueueWrite(Task&& task) {
  {
    std::lock_guard<std::mutex> jl(jobs_mu_);
    auto it = jobs_.find(task.job_id);
    if (it != jobs_.end()) it->second->total.fetch_add(1);
  }
  {
    std::lock_guard<std::mutex> lk(mu_);
    normal_queue_.push_back(std::move(task));
  }
  cv_.notify_one();
}

int Engine::SubmitWrite(uint64_t job_id, const std::string& path,
                        const std::string& tmp_path, const void* data,
                        uint64_t len, bool skip_if_exists) {
  if (ShouldShedWrite()) return 0;

  Task task;
  task.kind = TaskKind::kWrite;
  task.job_id = job_id;
  task.path = path;
  task.tmp_path = tmp_path;
  task.src = static_cast<const uint8_t*>(data);
  task.len = len;
  task.skip_if_exists = skip_if_exists;
  EnqueueWrite(std::move(task));
  return 1;
}

int Engine::SubmitWriteAt(uint64_t job_id, const std::string& path,
                          const void* data, uint64_t len, uint64_t offset,
                          uint64_t file_size) {
  if (ShouldShedWrite()) return 0;

  Task task;
  task.kind = TaskKind::kWriteAt;
  task.job_id = job_id;
  task.path = path;
  task.src = static_cast<const uint8_t*>(data);
  task.len = len;
  task.offset = offset;
  task.file_size = file_size;
  EnqueueWrite(std::move(task));
  return 1;
}

void Engine::SubmitRead(uint64_t job_id, const std::string& path, void* dst,
                        uint64_t len, uint64_t offset) {
  Task task;
  task.kind = TaskKind::kRead;
  task.job_id = job_id;
  task.path = path;
  task.dst = static_cast<uint8_t*>(dst);
  task.len = len;
  task.offset = offset;

  {
    std::lock_guard<std::mutex> jl(jobs_mu_);
    auto it = jobs_.find(job_id);
    if (it != jobs_.end()) it->second->total.fetch_add(1);
  }
  {
    std::lock_guard<std::mutex> lk(mu_);
    high_queue_.push_back(std::move(task));
  }
  cv_.notify_one();
}

void Engine::WorkerLoop(int worker_index) {
  // On-thread placement, in order: bind the CPU, then prefer the node for
  // allocations, then first-touch the staging buffer so its pages land on
  // the accelerator's host node (matches the reference worker prologue,
  // thread_pool.cpp:110-144; "pinned" = mlock instead of cudaHostAlloc).
  PinThreadToCpu(worker_cpus_[worker_index]);
  if (numa_node_ >= 0) SetPreferredNode(numa_node_);
  StagingBuffer staging;
  if (direct_io_ && staging_bytes_ > 0) {  // staging only backs O_DIRECT
    uint64_t size = (staging_bytes_ + 4095) & ~uint64_t{4095};
    void* p = std::aligned_alloc(4096, size);
    if (p != nullptr) {
      std::memset(p, 0, size);  // first-touch on this thread
      staging.data = static_cast<uint8_t*>(p);
      staging.size = size;
      staging.locked = mlock(p, size) == 0;
      if (staging.locked) pinned_staging_.fetch_add(1);
    }
  }
  workers_ready_.fetch_add(1);

  // The first read_preferring_workers_ drain the high (read) queue first;
  // the rest prefer writes but steal reads when idle (thread_pool.cpp:44-61
  // equivalent).
  const bool prefer_reads = worker_index < read_preferring_workers_;
  for (;;) {
    Task task;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [this] {
        return shutdown_ || !high_queue_.empty() || !normal_queue_.empty();
      });
      if (shutdown_ && high_queue_.empty() && normal_queue_.empty()) break;
      std::deque<Task>* first = prefer_reads ? &high_queue_ : &normal_queue_;
      std::deque<Task>* second = prefer_reads ? &normal_queue_ : &high_queue_;
      std::deque<Task>* src_q = !first->empty() ? first : second;
      task = std::move(src_q->front());
      src_q->pop_front();
    }

    bool cancelled = false;
    {
      std::lock_guard<std::mutex> jl(jobs_mu_);
      auto it = jobs_.find(task.job_id);
      if (it != jobs_.end() && it->second->cancelled.load()) cancelled = true;
    }
    bool ok = cancelled ? false : RunTask(task, staging);
    FinishTask(task, ok);
  }

  if (staging.data != nullptr) {
    if (staging.locked) munlock(staging.data, staging.size);
    std::free(staging.data);
  }
}

bool Engine::RunTask(Task& task, StagingBuffer& staging) {
  const bool use_staged =
      direct_io_ && staging.data != nullptr && task.len >= 4096;
  double start = NowSeconds();
  bool ok;
  if (task.kind == TaskKind::kWrite || task.kind == TaskKind::kWriteAt) {
    if (task.kind == TaskKind::kWriteAt) {
      ok = WriteFileRangeAt(task.path, task.src, task.len, task.offset,
                            task.file_size);
    } else {
      ok = use_staged ? WriteStaged(task, staging)
                      : WriteFileAtomic(task.path, task.tmp_path, task.src,
                                        task.len, task.skip_if_exists);
    }
    double dur = NowSeconds() - start;
    double prev = avg_write_seconds_.load();
    avg_write_seconds_.store(prev == 0.0 ? dur : 0.8 * prev + 0.2 * dur);
  } else {
    ok = use_staged ? ReadStaged(task, staging)
                    : ReadFileRange(task.path, task.dst, task.len, task.offset);
  }
  return ok;
}

// O_DIRECT atomic write: stream src through the page-aligned staging buffer
// into the temp file (page-cache bypass — KV files are written once and
// rarely re-read on the same host), unaligned tail via buffered I/O after
// clearing O_DIRECT, then rename. Falls back to the buffered path when the
// filesystem rejects O_DIRECT (e.g. tmpfs).
bool Engine::WriteStaged(const Task& task, StagingBuffer& staging) {
  if (task.skip_if_exists && ExistingFileReused(task.path)) return true;
  if (!MakeParentDirs(task.path)) return false;
  int fd = open(task.tmp_path.c_str(),
                O_WRONLY | O_CREAT | O_TRUNC | O_DIRECT, 0644);
  if (fd < 0) {
    // Filesystem refuses O_DIRECT (e.g. tmpfs): buffered path.
    return WriteFileAtomic(task.path, task.tmp_path, task.src, task.len,
                           task.skip_if_exists);
  }
  direct_transfers_.fetch_add(1);
  const uint64_t aligned_len = task.len & ~uint64_t{4095};
  uint64_t done = 0;
  while (done < aligned_len) {
    uint64_t chunk = std::min(staging.size, aligned_len - done);
    std::memcpy(staging.data, task.src + done, chunk);
    uint64_t off = 0;
    while (off < chunk) {
      ssize_t n = write(fd, staging.data + off, chunk - off);
      if (n < 0) {
        if (errno == EINTR) continue;
        return AbortTmpFile(fd, task.tmp_path);
      }
      // O_DIRECT writes stay 4096-multiples as long as the kernel doesn't
      // short-write mid-chunk; a misaligned residue would fail the next
      // write() and funnel into the error path above.
      off += static_cast<uint64_t>(n);
    }
    done += chunk;
  }
  if (task.len > aligned_len) {
    // Unaligned tail: drop O_DIRECT for the final partial page.
    int flags = fcntl(fd, F_GETFL);
    if (flags < 0 || fcntl(fd, F_SETFL, flags & ~O_DIRECT) != 0) {
      return AbortTmpFile(fd, task.tmp_path);
    }
    uint64_t tail = task.len - aligned_len;
    uint64_t off = 0;
    while (off < tail) {
      ssize_t n = pwrite(fd, task.src + aligned_len + off, tail - off,
                         static_cast<off_t>(aligned_len + off));
      if (n < 0) {
        if (errno == EINTR) continue;
        return AbortTmpFile(fd, task.tmp_path);
      }
      off += static_cast<uint64_t>(n);
    }
  }
  return PublishTmpFile(fd, task.path, task.tmp_path);
}

// O_DIRECT read: page-aligned reads into staging, memcpy the requested
// window out (handles arbitrary task.offset). Buffered fallback as above.
bool Engine::ReadStaged(const Task& task, StagingBuffer& staging) {
  int fd = open(task.path.c_str(), O_RDONLY | O_DIRECT);
  if (fd < 0) {
    return ReadFileRange(task.path, task.dst, task.len, task.offset);
  }
  direct_transfers_.fetch_add(1);
  uint64_t done = 0;
  bool ok = true;
  while (done < task.len) {
    uint64_t want_off = task.offset + done;
    uint64_t aligned_off = want_off & ~uint64_t{4095};
    uint64_t skip = want_off - aligned_off;
    uint64_t want = std::min(task.len - done, staging.size - skip);
    // Read enough aligned bytes to cover [want_off, want_off+want).
    uint64_t need = (skip + want + 4095) & ~uint64_t{4095};
    uint64_t got = 0;
    while (got < need) {
      ssize_t n = pread(fd, staging.data + got, need - got,
                        static_cast<off_t>(aligned_off + got));
      if (n < 0) {
        if (errno == EINTR) continue;
        ok = false;
        break;
      }
      if (n == 0) break;  // EOF
      got += static_cast<uint64_t>(n);
    }
    if (!ok) break;
    uint64_t avail = got > skip ? std::min(want, got - skip) : 0;
    if (avail == 0) break;  // EOF before the requested window
    std::memcpy(task.dst + done, staging.data + skip, avail);
    done += avail;
    if (avail < want) break;  // short file
  }
  close(fd);
  utime(task.path.c_str(), nullptr);
  return ok && done == task.len;
}

void Engine::FinishTask(const Task& task, bool ok) {
  std::lock_guard<std::mutex> jl(jobs_mu_);
  auto it = jobs_.find(task.job_id);
  if (it == jobs_.end()) return;
  JobState* job = it->second;
  if (ok) {
    job->completed.fetch_add(1);
    job->bytes.fetch_add(task.len);
  } else {
    job->failed.fetch_add(1);
  }
  if (job->sealed.load() &&
      job->completed.load() + job->failed.load() == job->total.load()) {
    finished_ready_.push_back(job->id);
    jobs_cv_.notify_all();
  }
}

int Engine::PollFinished(uint64_t* ids, int* statuses, int max_items) {
  std::lock_guard<std::mutex> jl(jobs_mu_);
  int n = 0;
  while (n < max_items && !finished_ready_.empty()) {
    uint64_t id = finished_ready_.back();
    finished_ready_.pop_back();
    auto it = jobs_.find(id);
    if (it == jobs_.end()) continue;
    JobState* job = it->second;
    ids[n] = id;
    if (job->cancelled.load()) {
      statuses[n] = kCancelled;
    } else {
      statuses[n] = job->failed.load() > 0 ? kIoError : kOk;
    }
    delete job;
    jobs_.erase(it);
    ++n;
  }
  return n;
}

int Engine::WaitJob(uint64_t job_id, double timeout_seconds) {
  // Cancellation-for-preemption: mark cancelled so queued tasks are skipped,
  // then wait for in-flight ones (storage_offload.cpp:217-236 equivalent).
  {
    std::lock_guard<std::mutex> jl(jobs_mu_);
    auto it = jobs_.find(job_id);
    if (it == jobs_.end()) return kOk;  // already finished+polled
    JobState* job = it->second;
    if (job->sealed.load() &&
        job->completed.load() + job->failed.load() == job->total.load()) {
      // Finished before the wait: report the real outcome, don't cancel.
      int status = job->failed.load() > 0 ? kIoError : kOk;
      delete job;
      jobs_.erase(it);
      for (auto fit = finished_ready_.begin(); fit != finished_ready_.end();
           ++fit) {
        if (*fit == job_id) {
          finished_ready_.erase(fit);
          break;
        }
      }
      return status;
    }
    job->cancelled.store(true);
    job->sealed.store(true);
  }
  std::unique_lock<std::mutex> jl(jobs_mu_);
  // Wait against system_clock: a steady_clock wait_for lowers to
  // pthread_cond_clockwait, which the gcc-10 libtsan does not intercept,
  // so under TSAN the internal unlock/relock of jobs_mu_ goes unseen and
  // the tool's mutex model corrupts (bogus double-lock + phantom races
  // throughout the tsan tier). pthread_cond_timedwait is intercepted.
  const auto deadline =
      std::chrono::system_clock::now() +
      std::chrono::duration_cast<std::chrono::system_clock::duration>(
          std::chrono::duration<double>(timeout_seconds));
  bool done = jobs_cv_.wait_until(jl, deadline, [&] {
        auto it = jobs_.find(job_id);
        if (it == jobs_.end()) return true;
        JobState* job = it->second;
        return job->completed.load() + job->failed.load() == job->total.load();
      });
  if (!done) return kPending;
  auto it = jobs_.find(job_id);
  if (it == jobs_.end()) return kOk;
  int status = kCancelled;
  delete it->second;
  jobs_.erase(it);
  // Also drop from finished_ready_ if it landed there.
  for (auto fit = finished_ready_.begin(); fit != finished_ready_.end(); ++fit) {
    if (*fit == job_id) {
      finished_ready_.erase(fit);
      break;
    }
  }
  return status;
}

}  // namespace kvio

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

void* kvio_create(int num_threads, int read_preferring_workers,
                  double max_write_queued_seconds, int numa_node,
                  uint64_t staging_bytes, int direct_io) {
  return new kvio::Engine(num_threads, read_preferring_workers,
                          max_write_queued_seconds, numa_node, staging_bytes,
                          direct_io != 0);
}

void kvio_destroy(void* engine) { delete static_cast<kvio::Engine*>(engine); }

uint64_t kvio_begin_job(void* engine) {
  return static_cast<kvio::Engine*>(engine)->BeginJob();
}

void kvio_seal_job(void* engine, uint64_t job_id) {
  static_cast<kvio::Engine*>(engine)->SealJob(job_id);
}

int kvio_submit_write(void* engine, uint64_t job_id, const char* path,
                      const char* tmp_path, const void* data, uint64_t len,
                      int skip_if_exists) {
  return static_cast<kvio::Engine*>(engine)->SubmitWrite(
      job_id, path, tmp_path, data, len, skip_if_exists != 0);
}

int kvio_submit_write_at(void* engine, uint64_t job_id, const char* path,
                         const void* data, uint64_t len, uint64_t offset,
                         uint64_t file_size) {
  return static_cast<kvio::Engine*>(engine)->SubmitWriteAt(
      job_id, path, data, len, offset, file_size);
}

void kvio_submit_read(void* engine, uint64_t job_id, const char* path,
                      void* dst, uint64_t len, uint64_t offset) {
  static_cast<kvio::Engine*>(engine)->SubmitRead(job_id, path, dst, len,
                                                 offset);
}

int kvio_poll_finished(void* engine, uint64_t* ids, int* statuses,
                       int max_items) {
  return static_cast<kvio::Engine*>(engine)->PollFinished(ids, statuses,
                                                          max_items);
}

int kvio_wait_job(void* engine, uint64_t job_id, double timeout_seconds) {
  return static_cast<kvio::Engine*>(engine)->WaitJob(job_id, timeout_seconds);
}

double kvio_avg_write_seconds(void* engine) {
  return static_cast<kvio::Engine*>(engine)->AvgWriteSeconds();
}

int kvio_queued_writes(void* engine) {
  return static_cast<kvio::Engine*>(engine)->QueuedWrites();
}

int kvio_file_exists(const char* path, int touch_atime) {
  struct stat st;
  if (stat(path, &st) != 0) return 0;
  if (touch_atime) utime(path, nullptr);
  return 1;
}

// -- placement visibility --

int kvio_numa_node(void* engine) {
  return static_cast<kvio::Engine*>(engine)->NumaNode();
}

int kvio_worker_cpu(void* engine, int worker) {
  return static_cast<kvio::Engine*>(engine)->WorkerCpu(worker);
}

int kvio_workers_ready(void* engine) {
  return static_cast<kvio::Engine*>(engine)->WorkersReady() ? 1 : 0;
}

int kvio_pinned_staging_workers(void* engine) {
  return static_cast<kvio::Engine*>(engine)->PinnedStagingWorkers();
}

uint64_t kvio_direct_transfers(void* engine) {
  return static_cast<kvio::Engine*>(engine)->DirectTransfers();
}

// -- topology helpers (standalone, for tests and Python-side sizing) --

int kvio_discover_numa_node() { return kvio::DiscoverAcceleratorNumaNode(); }

int kvio_cpus_in_node(int node, int* out, int max_items) {
  auto cpus = kvio::CpusInNumaNode(node);
  int n = std::min<int>(max_items, static_cast<int>(cpus.size()));
  for (int i = 0; i < n; ++i) out[i] = cpus[i];
  return static_cast<int>(cpus.size());
}

int kvio_parse_cpulist(const char* s, int* out, int max_items) {
  auto cpus = kvio::ParseCpuList(s ? s : "");
  int n = std::min<int>(max_items, static_cast<int>(cpus.size()));
  for (int i = 0; i < n; ++i) out[i] = cpus[i];
  return static_cast<int>(cpus.size());
}
}
