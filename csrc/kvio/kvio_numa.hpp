// NUMA topology discovery + thread placement for the kvio pool.
//
// TPU-host analog of the reference's GPU NUMA plumbing
// (numa_utils.cpp:33-117, thread_pool.cpp:71-127): where the reference asks
// CUDA for the GPU's host NUMA node, a TPU host exposes its accelerator
// complex only through sysfs — we scan PCI devices for Google (0x1ae0)
// accelerators and read their numa_node attribute. CPU sets come from the
// kernel's per-node cpulist. Memory policy uses the raw set_mempolicy
// syscall so no libnuma link dependency is needed.

#pragma once

#include <string>
#include <vector>

namespace kvio {

// Host NUMA node of the accelerator complex.
// Resolution order:
//   1. KVIO_NUMA_NODE env var (explicit operator override; also the only
//      option in VMs that hide PCI topology),
//   2. sysfs scan: first PCI device with vendor 0x1ae0 (Google, i.e. a TPU)
//      that reports numa_node >= 0,
//   3. -1 (unknown; callers fall back to all CPUs, no memory policy).
int DiscoverAcceleratorNumaNode();

// CPUs belonging to a NUMA node, from
// /sys/devices/system/node/node<N>/cpulist. Empty on failure.
std::vector<int> CpusInNumaNode(int node);

// Parse a kernel cpulist string ("0-13,84-97"); malformed tokens are
// skipped. Exposed separately for unit tests.
std::vector<int> ParseCpuList(const std::string& line);

// Best-effort MPOL_PREFERRED for the calling thread's future allocations
// (first-touch pages land on `node`). Returns false if the syscall is
// unavailable or rejected.
bool SetPreferredNode(int node);

// Pin the calling thread to a single CPU.
bool PinThreadToCpu(int cpu);

}  // namespace kvio
