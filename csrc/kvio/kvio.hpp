// TPU-host KV offload I/O engine.
//
// Native runtime for the offload data plane: a NUMA/affinity-aware I/O
// thread pool with two priority queues (reads preferred by a configurable
// subset of workers), per-job completion tracking with cancellation, atomic
// tmp+rename file writes, and EMA-based write shedding.
//
// Role parity with the reference's csrc (SURVEY.md §2.2):
//   StorageOffloadEngine  -> kvio::Engine (job lifecycle, shedding, polling)
//   ThreadPool            -> kvio::Engine's worker pool + priority queues
//   FileIO                -> write_file_atomic / read_file_range
//   TensorCopier (CUDA)   -> NOT here: the TPU HBM->host gather runs in
//                            JAX/XLA (ops/kv_pages.py); this engine takes
//                            host buffers.
//
// Exposed to Python through a C ABI (kvio.cpp) loaded via ctypes; all file
// I/O happens off the GIL on the pool threads.

#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace kvio {

enum class TaskKind { kWrite, kRead, kWriteAt };

// Completion status codes surfaced to Python.
enum Status : int {
  kPending = -1,
  kOk = 0,
  kIoError = 1,
  kCancelled = 2,
  kShed = 3,
};

struct Task {
  TaskKind kind;
  uint64_t job_id;
  std::string path;
  std::string tmp_path;       // writes: unique temp path for atomic rename
  const uint8_t* src = nullptr;  // writes: caller-owned buffer
  uint8_t* dst = nullptr;        // reads: caller-owned buffer
  uint64_t len = 0;
  uint64_t offset = 0;           // reads/kWriteAt: byte offset into the file
  uint64_t file_size = 0;        // kWriteAt: full file size to provision
  bool skip_if_exists = true;    // writes: dedup against existing files
};

struct JobState {
  uint64_t id = 0;
  std::atomic<int> total{0};
  std::atomic<int> completed{0};
  std::atomic<int> failed{0};
  std::atomic<bool> sealed{false};
  std::atomic<bool> cancelled{false};
  std::atomic<uint64_t> bytes{0};
};

// Per-worker pinned staging buffer: page-aligned, mlock'd (best effort),
// first-touched after the worker binds its CPU + memory policy so pages
// land on the accelerator's host NUMA node. Backs O_DIRECT transfers
// (page-cache bypass for write-once/read-rarely KV files — the TPU-side
// answer to the reference's GDS bounce buffers).
struct StagingBuffer {
  uint8_t* data = nullptr;
  uint64_t size = 0;
  bool locked = false;  // mlock succeeded ("pinned")
};

class Engine {
 public:
  // numa_node: >=0 pins workers to that node's CPUs; -1 auto-discovers the
  // accelerator's host node (kvio_numa.hpp); -2 disables placement.
  // staging_bytes: per-worker staging size (0 disables staging+direct I/O).
  // direct_io: stage transfers through O_DIRECT when the filesystem
  // supports it (falls back to buffered I/O per file otherwise).
  Engine(int num_threads, int read_preferring_workers,
         double max_write_queued_seconds, int numa_node = -1,
         uint64_t staging_bytes = 0, bool direct_io = false);
  ~Engine();

  uint64_t BeginJob();
  // Seal after all submissions; completion requires sealed && completed+failed == total.
  void SealJob(uint64_t job_id);

  // Returns 1 if queued, 0 if shed by the dynamic write-queue limit.
  int SubmitWrite(uint64_t job_id, const std::string& path,
                  const std::string& tmp_path, const void* data, uint64_t len,
                  bool skip_if_exists);
  // Partial in-place write at a byte offset into a (possibly pre-existing)
  // multi-block file provisioned to file_size. NOT atomic — used for
  // head/tail-partial slots of multi-block files, where the enclosing
  // file already exists or is being filled slot-by-slot. Same shedding as
  // SubmitWrite.
  int SubmitWriteAt(uint64_t job_id, const std::string& path, const void* data,
                    uint64_t len, uint64_t offset, uint64_t file_size);
  // Reads are never shed; they enqueue at high priority.
  void SubmitRead(uint64_t job_id, const std::string& path, void* dst,
                  uint64_t len, uint64_t offset);

  // Drain finished jobs (sealed + all tasks done). Returns count; for each,
  // ids[i] and statuses[i] (kOk or kIoError if any task failed).
  int PollFinished(uint64_t* ids, int* statuses, int max_items);

  // Cancel outstanding queued tasks of a job and wait for in-flight ones.
  // Returns the job's final status.
  int WaitJob(uint64_t job_id, double timeout_seconds);

  double AvgWriteSeconds() const { return avg_write_seconds_.load(); }
  int QueuedWrites() const;

  // Placement visibility (tests + metrics).
  int NumaNode() const { return numa_node_; }
  int WorkerCpu(int worker) const {
    return (worker >= 0 && worker < static_cast<int>(worker_cpus_.size()))
               ? worker_cpus_[worker]
               : -1;
  }
  // True once every worker finished CPU/mempolicy/staging setup.
  bool WorkersReady() const {
    return workers_ready_.load() == num_threads_;
  }
  // Count of workers whose staging buffer is mlock'd.
  int PinnedStagingWorkers() const { return pinned_staging_.load(); }
  // Transfers that actually took the O_DIRECT staged path (not the
  // buffered fallback) — lets callers/tests verify direct I/O engaged.
  uint64_t DirectTransfers() const { return direct_transfers_.load(); }

  void Shutdown();

 private:
  void WorkerLoop(int worker_index);
  bool RunTask(Task& task, StagingBuffer& staging);
  void FinishTask(const Task& task, bool ok);
  bool ShouldShedWrite();
  void EnqueueWrite(Task&& task);
  bool WriteStaged(const Task& task, StagingBuffer& staging);
  bool ReadStaged(const Task& task, StagingBuffer& staging);

  int num_threads_;
  int read_preferring_workers_;
  double max_write_queued_seconds_;
  int numa_node_ = -1;                 // resolved node (-1 unknown/disabled)
  uint64_t staging_bytes_ = 0;
  bool direct_io_ = false;
  std::vector<int> worker_cpus_;       // assigned CPU per worker (-1 none)
  std::atomic<int> workers_ready_{0};
  std::atomic<int> pinned_staging_{0};
  std::atomic<uint64_t> direct_transfers_{0};

  mutable std::mutex mu_;
  std::condition_variable cv_;
  std::deque<Task> high_queue_;   // reads
  std::deque<Task> normal_queue_; // writes
  bool shutdown_ = false;

  std::mutex jobs_mu_;
  std::unordered_map<uint64_t, JobState*> jobs_;
  std::vector<uint64_t> finished_ready_;
  std::condition_variable jobs_cv_;
  std::atomic<uint64_t> next_job_id_{1};

  std::atomic<double> avg_write_seconds_{0.0};  // EMA, alpha=0.2

  std::vector<std::thread> workers_;
};

}  // namespace kvio
