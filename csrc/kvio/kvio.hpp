// TPU-host KV offload I/O engine.
//
// Native runtime for the offload data plane: a NUMA/affinity-aware I/O
// thread pool with two priority queues (reads preferred by a configurable
// subset of workers), per-job completion tracking with cancellation, atomic
// tmp+rename file writes, and EMA-based write shedding.
//
// Role parity with the reference's csrc (SURVEY.md §2.2):
//   StorageOffloadEngine  -> kvio::Engine (job lifecycle, shedding, polling)
//   ThreadPool            -> kvio::Engine's worker pool + priority queues
//   FileIO                -> write_file_atomic / read_file_range
//   TensorCopier (CUDA)   -> NOT here: the TPU HBM->host gather runs in
//                            JAX/XLA (ops/kv_pages.py); this engine takes
//                            host buffers.
//
// Exposed to Python through a C ABI (kvio.cpp) loaded via ctypes; all file
// I/O happens off the GIL on the pool threads.

#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace kvio {

enum class TaskKind { kWrite, kRead };

// Completion status codes surfaced to Python.
enum Status : int {
  kPending = -1,
  kOk = 0,
  kIoError = 1,
  kCancelled = 2,
  kShed = 3,
};

struct Task {
  TaskKind kind;
  uint64_t job_id;
  std::string path;
  std::string tmp_path;       // writes: unique temp path for atomic rename
  const uint8_t* src = nullptr;  // writes: caller-owned buffer
  uint8_t* dst = nullptr;        // reads: caller-owned buffer
  uint64_t len = 0;
  uint64_t offset = 0;           // reads: byte offset into the file
  bool skip_if_exists = true;    // writes: dedup against existing files
};

struct JobState {
  uint64_t id = 0;
  std::atomic<int> total{0};
  std::atomic<int> completed{0};
  std::atomic<int> failed{0};
  std::atomic<bool> sealed{false};
  std::atomic<bool> cancelled{false};
  std::atomic<uint64_t> bytes{0};
};

class Engine {
 public:
  Engine(int num_threads, int read_preferring_workers,
         double max_write_queued_seconds);
  ~Engine();

  uint64_t BeginJob();
  // Seal after all submissions; completion requires sealed && completed+failed == total.
  void SealJob(uint64_t job_id);

  // Returns 1 if queued, 0 if shed by the dynamic write-queue limit.
  int SubmitWrite(uint64_t job_id, const std::string& path,
                  const std::string& tmp_path, const void* data, uint64_t len,
                  bool skip_if_exists);
  // Reads are never shed; they enqueue at high priority.
  void SubmitRead(uint64_t job_id, const std::string& path, void* dst,
                  uint64_t len, uint64_t offset);

  // Drain finished jobs (sealed + all tasks done). Returns count; for each,
  // ids[i] and statuses[i] (kOk or kIoError if any task failed).
  int PollFinished(uint64_t* ids, int* statuses, int max_items);

  // Cancel outstanding queued tasks of a job and wait for in-flight ones.
  // Returns the job's final status.
  int WaitJob(uint64_t job_id, double timeout_seconds);

  double AvgWriteSeconds() const { return avg_write_seconds_.load(); }
  int QueuedWrites() const;

  void Shutdown();

 private:
  void WorkerLoop(int worker_index);
  bool RunTask(Task& task);
  void FinishTask(const Task& task, bool ok);

  int num_threads_;
  int read_preferring_workers_;
  double max_write_queued_seconds_;

  mutable std::mutex mu_;
  std::condition_variable cv_;
  std::deque<Task> high_queue_;   // reads
  std::deque<Task> normal_queue_; // writes
  bool shutdown_ = false;

  std::mutex jobs_mu_;
  std::unordered_map<uint64_t, JobState*> jobs_;
  std::vector<uint64_t> finished_ready_;
  std::condition_variable jobs_cv_;
  std::atomic<uint64_t> next_job_id_{1};

  std::atomic<double> avg_write_seconds_{0.0};  // EMA, alpha=0.2

  std::vector<std::thread> workers_;
};

}  // namespace kvio
