"""Fleet-grade resilience primitives.

This package concentrates the cross-cutting machinery that keeps the
serving path alive when individual components misbehave:

- :mod:`failpoints` — a deterministic, seeded fault-injection registry
  with named hooks planted at every I/O boundary (offload, Redis index,
  ZMQ events, tokenizer RPCs).
- :mod:`policy` — jittered exponential backoff with deadlines and a
  per-target circuit breaker.
- :mod:`integrity` — the per-slot CRC32 footer appended to offload
  files, verified on load.
- :mod:`failover` — an Index wrapper that trips Redis ops over to the
  in-memory index when the primary's breaker opens.
- :mod:`liveness` — per-pod last-event tracking feeding degraded-mode
  scoring (stale pods demoted, then dropped), plus latency-EMA demotion
  for pods that are slow rather than dead.
- :mod:`deadline` — end-to-end request deadlines carried as tolerant
  wire metadata and consumed at every blocking site.
- :mod:`hedging` — per-target latency-quantile tracking and the hedge
  budget behind the router's tail-tolerant scatter-gather.
- :mod:`shedding` — CoDel-style queue-delay-controlled overload
  shedding (brownout before blackout, priority-ordered).

See docs/resilience.md for the failpoint catalog and defaults.
"""

from .failpoints import (  # noqa: F401
    FailpointRegistry,
    FaultInjected,
    failpoints,
)
from .policy import (  # noqa: F401
    CircuitBreaker,
    CircuitOpenError,
    RetryExhausted,
    RetryPolicy,
    call_with_retry,
)
from .integrity import (  # noqa: F401
    FOOTER_MAGIC,
    IntegrityError,
    build_footer,
    footer_size,
    parse_footer,
    slot_crcs,
)
from .failover import FailoverIndex  # noqa: F401
from .liveness import PodLivenessTracker  # noqa: F401
from .deadline import (  # noqa: F401
    Deadline,
    DeadlineExceeded,
    current_deadline,
    deadline_scope,
    effective_timeout,
)
from .hedging import HedgeBudget, LatencyQuantileTracker  # noqa: F401
from .shedding import (  # noqa: F401
    ADMIT,
    BROWNOUT,
    PRIORITY_CRITICAL,
    PRIORITY_LOW,
    PRIORITY_NORMAL,
    SHED,
    CoDelShedder,
    OverloadShedError,
)
