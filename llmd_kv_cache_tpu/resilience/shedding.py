"""Adaptive overload shedding: a CoDel-style queue-delay controller.

Fixed queue-length caps misfire in both directions: too small sheds
bursts a healthy server would absorb, too large lets latency build into
standing-queue collapse. CoDel (Nichols & Jacobson) controls on *delay*
instead: overload is declared only when the observed queueing/processing
delay stays above ``target_delay_s`` for a full ``interval_s`` — a burst
that clears inside one interval never sheds — and once overloaded the
shedder ramps pressure with the classic inverse-sqrt control law
(re-evaluation intervals shrink as ``interval / sqrt(n)`` while the
overload persists, so pressure grows smoothly rather than oscillating).

Pressure maps to *brownout before blackout* via request priorities:

- ``PRIORITY_LOW`` (0) — speculative/optional work (prefetch, offload
  restore extensions, background repair). Shed first.
- ``PRIORITY_NORMAL`` (1) — ordinary request-path work. Degraded
  (brownout: skip enrichment, serve a cheaper answer flagged
  ``degraded``) under moderate pressure, shed only when pressure is
  sustained.
- ``PRIORITY_CRITICAL`` (2) — never shed (health checks, drain,
  control-plane actions).

Callers ask :meth:`CoDelShedder.admit` per unit of work and feed
:meth:`observe_delay` with the measured sojourn/processing delay. All
state is one lock; the clock is injectable for tests.
"""

from __future__ import annotations

import math
import time
from typing import Callable, Optional

from ..utils.lockdep import new_lock

PRIORITY_LOW = 0
PRIORITY_NORMAL = 1
PRIORITY_CRITICAL = 2

# Decision outcomes (also the flight-recorder / metric label values).
ADMIT = "admit"
BROWNOUT = "brownout"
SHED = "shed"

# Consecutive shed-law firings before PRIORITY_NORMAL work sheds too
# (low-priority work sheds from the first firing; brownout starts at
# overload entry).
_NORMAL_SHED_AFTER = 4


class OverloadShedError(RuntimeError):
    """Raised by call sites that fail fast on shed (engine admission)."""

    def __init__(self, site: str, queue_delay_s: float):
        super().__init__(
            f"overload shed at {site} "
            f"(queue delay {queue_delay_s * 1e3:.1f} ms)"
        )
        self.site = site
        self.queue_delay_s = queue_delay_s


class CoDelShedder:
    """CoDel-style delay-controlled admission for one service site."""

    def __init__(
        self,
        site: str,
        target_delay_s: float = 0.005,
        interval_s: float = 0.1,
        clock: Callable[[], float] = time.monotonic,
    ):
        if target_delay_s <= 0 or interval_s <= 0:
            raise ValueError("target_delay_s and interval_s must be > 0")
        self.site = site
        self.target_delay_s = target_delay_s
        self.interval_s = interval_s
        self._clock = clock
        self._mu = new_lock()
        # CoDel state: when delay first exceeded target (None = under
        # target), whether we are in the shedding regime, the next time
        # the control law fires, and the firing count driving sqrt decay.
        self._first_above: Optional[float] = None
        self._overloaded = False
        self._next_fire = 0.0
        self._fire_count = 0
        self._last_delay = 0.0
        # Accounting.
        self._admitted = 0
        self._brownouts = 0
        self._sheds = 0
        self._listeners: list = []

    # -- observation ------------------------------------------------------

    def observe_delay(self, delay_s: float) -> None:
        """Feed one measured queueing/processing delay."""
        now = self._clock()
        transition = None
        with self._mu:
            self._last_delay = delay_s
            if delay_s < self.target_delay_s:
                # Below target: leave overload immediately (CoDel resets
                # its decay once the standing queue drains).
                if self._overloaded:
                    transition = ("clear", delay_s)
                self._first_above = None
                self._overloaded = False
                self._fire_count = 0
            else:
                if self._first_above is None:
                    self._first_above = now
                if (not self._overloaded
                        and now - self._first_above >= self.interval_s):
                    # Sustained above target for a full interval: enter
                    # the shedding regime.
                    self._overloaded = True
                    self._fire_count = 1
                    self._next_fire = now + self.interval_s / math.sqrt(
                        self._fire_count + 1)
                    transition = ("overload", delay_s)
                elif self._overloaded and now >= self._next_fire:
                    # Still above target at the control-law cadence: ramp.
                    self._fire_count += 1
                    self._next_fire = now + self.interval_s / math.sqrt(
                        self._fire_count + 1)
        if transition is not None:
            self._notify(*transition)

    # -- admission --------------------------------------------------------

    def admit(self, priority: int = PRIORITY_NORMAL) -> str:
        """Decide for one unit of work: ADMIT, BROWNOUT, or SHED."""
        with self._mu:
            if not self._overloaded or priority >= PRIORITY_CRITICAL:
                self._admitted += 1
                return ADMIT
            if priority <= PRIORITY_LOW:
                self._sheds += 1
                return SHED
            if self._fire_count >= _NORMAL_SHED_AFTER:
                self._sheds += 1
                return SHED
            self._brownouts += 1
            return BROWNOUT

    @property
    def overloaded(self) -> bool:
        with self._mu:
            return self._overloaded

    @property
    def last_delay_s(self) -> float:
        """Most recently observed delay (for shed error messages)."""
        with self._mu:
            return self._last_delay

    @property
    def pressure(self) -> int:
        """0 = healthy; >= 1 = overloaded, growing with persistence."""
        with self._mu:
            return self._fire_count if self._overloaded else 0

    def shed_rate(self) -> float:
        """Shed decisions / total decisions (the controller signal)."""
        with self._mu:
            total = self._admitted + self._brownouts + self._sheds
            return self._sheds / total if total else 0.0

    def stats(self) -> dict:
        with self._mu:
            total = self._admitted + self._brownouts + self._sheds
            return {
                "site": self.site,
                "overloaded": self._overloaded,
                "pressure": self._fire_count if self._overloaded else 0,
                "last_delay_ms": round(self._last_delay * 1e3, 3),
                "admitted": self._admitted,
                "brownouts": self._brownouts,
                "sheds": self._sheds,
                "shed_rate": round(self._sheds / total, 4) if total else 0.0,
            }

    # -- observers --------------------------------------------------------

    def add_listener(self, fn) -> None:
        """``fn(event, delay_s)`` on overload/clear transitions (flight
        recorder, tests). Called outside the lock; a raising listener is
        ignored."""
        with self._mu:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def _notify(self, event: str, delay_s: float) -> None:
        with self._mu:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(event, delay_s)
            except Exception:  # lint: allow-swallow (observers never break shedding)
                pass
