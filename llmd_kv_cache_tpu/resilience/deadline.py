"""End-to-end request deadlines that travel with the request.

A :class:`Deadline` is an absolute point on the *local* monotonic clock.
It crosses process boundaries as a **relative budget** (milliseconds
remaining at send time) — never as an absolute timestamp — so clock skew
between pods cannot inflate or collapse the budget; each hop re-anchors
the remaining time on its own clock. The cost is that network transit
time is invisible to the receiver (the budget is slightly optimistic by
one one-way latency), which errs on the side of doing work rather than
shedding it.

Wire conventions (all tolerant — absent means "no deadline", exactly the
``traceparent`` arrival pattern):

- ``ScoreRequest.deadline_ms`` / shard-RPC frame key ``"deadline_ms"`` —
  msgpack int, remaining budget at send time, 0/absent = none.
- gRPC metadata key ``kvtpu-deadline-ms`` — same value for surfaces that
  only speak metadata (the tokenizer sidecar).

Ambient propagation mirrors ``telemetry.current_traceparent()``: a
service handler enters :func:`deadline_scope` once at the top of the
request, and every blocking site below — router fan-out, index lookup,
tokenizer RPC, engine admission, offload restore — reads
:func:`current_deadline` without threading a parameter through every
signature.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Iterator, Optional

GRPC_DEADLINE_KEY = "kvtpu-deadline-ms"
WIRE_DEADLINE_KEY = "deadline_ms"


class DeadlineExceeded(TimeoutError):
    """A blocking site found the request's deadline already spent."""

    def __init__(self, site: str, overrun_s: float = 0.0):
        super().__init__(
            f"deadline exceeded at {site}"
            + (f" ({overrun_s * 1e3:.1f} ms past)" if overrun_s > 0 else "")
        )
        self.site = site
        self.overrun_s = overrun_s


class Deadline:
    """An absolute monotonic expiry with skew-free wire encoding."""

    __slots__ = ("expires_at", "_clock")

    def __init__(self, expires_at: float,
                 clock: Callable[[], float] = time.monotonic):
        self.expires_at = float(expires_at)
        self._clock = clock

    @classmethod
    def after(cls, budget_s: float,
              clock: Callable[[], float] = time.monotonic) -> "Deadline":
        if budget_s < 0:
            raise ValueError(f"deadline budget must be >= 0, got {budget_s}")
        return cls(clock() + budget_s, clock=clock)

    @classmethod
    def from_wire_ms(cls, ms, clock: Callable[[], float] = time.monotonic
                     ) -> Optional["Deadline"]:
        """Decode a relative wire budget; 0/None/absent/garbage → None
        (a peer that sends nonsense must not crash scoring)."""
        try:
            ms = int(ms)
        except (TypeError, ValueError):
            return None
        if ms <= 0:
            return None
        return cls(clock() + ms / 1e3, clock=clock)

    def remaining_s(self) -> float:
        """Seconds of budget left; negative once expired."""
        return self.expires_at - self._clock()

    def expired(self) -> bool:
        return self._clock() >= self.expires_at

    def to_wire_ms(self) -> int:
        """Remaining budget as the wire int (>= 1 while any budget is
        left, so a nearly-spent deadline never encodes as "none")."""
        remaining = self.remaining_s()
        if remaining <= 0:
            return 0
        return max(1, int(remaining * 1e3))

    def cap_timeout(self, timeout_s: Optional[float]) -> float:
        """The stricter of ``timeout_s`` and this deadline (floor 0)."""
        remaining = max(0.0, self.remaining_s())
        if timeout_s is None:
            return remaining
        return min(float(timeout_s), remaining)

    def check(self, site: str) -> None:
        """Raise :class:`DeadlineExceeded` if already spent."""
        remaining = self.remaining_s()
        if remaining <= 0:
            raise DeadlineExceeded(site, overrun_s=-remaining)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Deadline(remaining={self.remaining_s() * 1e3:.1f}ms)"


# -- ambient propagation ---------------------------------------------------

_ambient = threading.local()


def current_deadline() -> Optional[Deadline]:
    """The innermost active :func:`deadline_scope` deadline, or None."""
    return getattr(_ambient, "deadline", None)


@contextlib.contextmanager
def deadline_scope(deadline: Optional[Deadline]) -> Iterator[Optional[Deadline]]:
    """Make ``deadline`` ambient for the current thread. ``None`` is
    accepted and simply clears the scope (callers need no branching for
    deadline-less requests). Nested scopes keep the *stricter* deadline —
    an inner hop can shrink the budget but never extend it."""
    prev = getattr(_ambient, "deadline", None)
    eff = deadline
    if prev is not None and (eff is None or prev.expires_at < eff.expires_at):
        eff = prev
    _ambient.deadline = eff
    try:
        yield eff
    finally:
        _ambient.deadline = prev


def effective_timeout(timeout_s: Optional[float],
                      deadline: Optional[Deadline] = None) -> Optional[float]:
    """Cap ``timeout_s`` by the explicit or ambient deadline. Returns
    ``timeout_s`` unchanged when no deadline is active; never negative."""
    dl = deadline if deadline is not None else current_deadline()
    if dl is None:
        return timeout_s
    return dl.cap_timeout(timeout_s)


def deadline_metadata(deadline: Optional[Deadline] = None):
    """``((kvtpu-deadline-ms, "<n>"),)`` for gRPC metadata, or ``()``."""
    dl = deadline if deadline is not None else current_deadline()
    if dl is None:
        return ()
    return ((GRPC_DEADLINE_KEY, str(dl.to_wire_ms())),)


def extract_deadline(context) -> Optional[Deadline]:
    """Read ``kvtpu-deadline-ms`` from a gRPC ServicerContext (tolerant:
    absent, unparsable, or a None context all yield None)."""
    if context is None:
        return None
    try:
        metadata = context.invocation_metadata()
    except Exception:  # lint: allow-swallow (non-gRPC test doubles)
        return None
    if not metadata:
        return None
    for key, value in metadata:
        if key == GRPC_DEADLINE_KEY:
            return Deadline.from_wire_ms(value)
    return None
