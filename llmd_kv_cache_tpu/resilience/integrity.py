"""Per-slot CRC32 footer for offload block files.

Layout (little-endian), appended after the raw KV payload:

    +----------------+--------------------------+
    | payload        | slot 0 | slot 1 | ...    |   <- existing format
    +----------------+--------------------------+
    | u32 crc32 per slot  (4 * num_slots bytes) |
    | magic "KVCK" | u16 version | u16 slots    |   <- 8-byte trailer
    +-------------------------------------------+

A *slot* is one contiguous cache-slice write (one layer's K or V run
for the block), matching the units ``assemble_file_buffers`` emits, so
a torn write is localised to the slot granularity.  The trailer lives
at the very end of the file so a reader only needs the file tail plus
the slot count it already knows from the mapper geometry.

The footer is covered by the offload fingerprint (``integrity`` field
of ``FileMapperConfig``), so files with and without footers never share
a directory.
"""

from __future__ import annotations

import struct
import zlib
from typing import Sequence

FOOTER_MAGIC = b"KVCK"
FOOTER_VERSION = 1
_TRAILER = struct.Struct("<4sHH")  # magic, version, slot count


class IntegrityError(Exception):
    """Checksum footer missing, malformed, or mismatched."""


def footer_size(num_slots: int) -> int:
    return 4 * num_slots + _TRAILER.size


def slot_crcs(buffers: Sequence) -> list[int]:
    """CRC32 of each slot buffer (accepts anything memoryview-able)."""
    return [zlib.crc32(memoryview(b).cast("B")) & 0xFFFFFFFF for b in buffers]


def build_footer(crcs: Sequence[int]) -> bytes:
    body = struct.pack(f"<{len(crcs)}I", *crcs)
    return body + _TRAILER.pack(FOOTER_MAGIC, FOOTER_VERSION, len(crcs))


def parse_footer(footer: bytes, expected_slots: int) -> list[int]:
    """Decode a footer blob; raise :class:`IntegrityError` on any defect."""
    if len(footer) != footer_size(expected_slots):
        raise IntegrityError(
            f"footer is {len(footer)} bytes, expected {footer_size(expected_slots)}"
        )
    magic, version, slots = _TRAILER.unpack_from(footer, 4 * expected_slots)
    if magic != FOOTER_MAGIC:
        raise IntegrityError(f"bad footer magic {magic!r}")
    if version != FOOTER_VERSION:
        raise IntegrityError(f"unsupported footer version {version}")
    if slots != expected_slots:
        raise IntegrityError(f"footer has {slots} slot(s), expected {expected_slots}")
    return list(struct.unpack_from(f"<{expected_slots}I", footer, 0))


def verify_slots(buffers: Sequence, footer: bytes) -> None:
    """Check every slot buffer against the footer; raise on first mismatch."""
    expected = parse_footer(footer, len(buffers))
    actual = slot_crcs(buffers)
    for i, (want, got) in enumerate(zip(expected, actual)):
        if want != got:
            raise IntegrityError(
                f"slot {i} crc mismatch: footer={want:#010x} data={got:#010x}"
            )
