"""Tail-tolerant hedging primitives: latency quantiles + a hedge budget.

Gray failures — a pod that is *slow* rather than dead — defeat breakers
(calls succeed) and liveness (events keep flowing). The classic answer
("The Tail at Scale") is a *hedged request*: if the primary hasn't
answered by the p-th latency percentile, issue the same request to a
replica and take the first response. Two pieces make that safe:

- :class:`LatencyQuantileTracker` — a per-target streaming quantile
  estimate (EMA-stepped stochastic approximation, O(1) memory per
  target) that adapts the hedge trigger to each shard's *current*
  latency distribution, so a uniformly slow fleet doesn't hedge at all
  while one slow shard trips hedges immediately.
- :class:`HedgeBudget` — a token bucket refilled by primary-request
  volume, capping hedges at a configured fraction of real traffic so a
  melting-down fleet cannot double its own load (hedge-storm).

Both are lock-protected (lockdep factories), clock-injectable, and
dependency-free, like the rest of ``resilience``.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from ..utils.lockdep import new_lock


class _QuantileEstimate:
    """Streaming quantile via stochastic approximation.

    Classic Robbins-Monro update: the estimate moves up by ``step * q``
    on a sample above it and down by ``step * (1 - q)`` on one below, so
    it converges where the exceed-rate is ``1 - q``. The step adapts to
    the value scale through an EMA of |sample - estimate| — no window,
    no histogram, a handful of floats per target.

    Samples are winsorized at ``3x`` the current estimate (once warmed):
    at high quantiles the up/down steps are deliberately asymmetric
    (``q`` vs ``1 - q``), so a single wild outlier would otherwise
    ratchet the estimate up and take hundreds of samples to decay — a
    hedge trigger stuck high is a hedge that never fires. A genuinely
    shifted distribution still grows the estimate exponentially (3x per
    sample), just not in one jump.
    """

    __slots__ = ("q", "estimate", "scale", "count")

    WINSOR_FACTOR = 3.0
    WINSOR_AFTER = 8  # leave the first samples unclamped to find scale

    def __init__(self, q: float):
        self.q = q
        self.estimate = 0.0
        self.scale = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        if self.count == 0:
            self.estimate = value
            self.scale = max(abs(value), 1e-9)
            self.count = 1
            return
        self.count += 1
        if (self.count > self.WINSOR_AFTER and self.estimate > 0.0
                and value > self.WINSOR_FACTOR * self.estimate):
            value = self.WINSOR_FACTOR * self.estimate
        # Scale EMA first so early, wildly-off estimates correct fast.
        self.scale += 0.05 * (abs(value - self.estimate) - self.scale)
        step = max(self.scale, 1e-9) * 0.2
        if value > self.estimate:
            self.estimate += step * self.q
        else:
            self.estimate -= step * (1.0 - self.q)
        if self.estimate < 0.0:
            self.estimate = 0.0


class LatencyQuantileTracker:
    """Per-target latency quantile estimates for hedge triggering."""

    def __init__(self, quantile: float = 0.95, min_samples: int = 8):
        if not 0.5 <= quantile < 1.0:
            raise ValueError(f"quantile must be in [0.5, 1), got {quantile}")
        self.quantile = quantile
        self.min_samples = min_samples
        self._mu = new_lock()
        self._targets: Dict[str, _QuantileEstimate] = {}

    def observe(self, target: str, seconds: float) -> None:
        with self._mu:
            est = self._targets.get(target)
            if est is None:
                est = self._targets[target] = _QuantileEstimate(self.quantile)
            est.observe(max(0.0, seconds))

    def value(self, target: str) -> Optional[float]:
        """Current quantile estimate, or None until ``min_samples`` have
        been observed (hedging on a cold estimate is worse than waiting)."""
        with self._mu:
            est = self._targets.get(target)
            if est is None or est.count < self.min_samples:
                return None
            return est.estimate

    def snapshot(self) -> Dict[str, float]:
        with self._mu:
            return {
                t: est.estimate for t, est in self._targets.items()
                if est.count >= self.min_samples
            }


class HedgeBudget:
    """Token bucket capping hedges at a fraction of primary traffic.

    Every primary attempt deposits ``rate`` tokens (so budget is a
    *fraction of real load*, self-scaling with traffic); a hedge spends
    one token. ``burst`` bounds the accumulated credit so an idle hour
    cannot bankroll a hedge storm. ``spend()`` is the only consumer-facing
    call: True = hedge admitted.
    """

    def __init__(self, rate: float = 0.1, burst: float = 8.0,
                 clock: Callable[[], float] = time.monotonic):
        if rate < 0:
            raise ValueError(f"hedge budget rate must be >= 0, got {rate}")
        self.rate = rate
        self.burst = max(1.0, burst)
        self._clock = clock  # retained for debug views / future decay
        self._mu = new_lock()
        self._tokens = min(1.0, self.burst)
        self._primaries = 0
        self._hedges = 0
        self._denied = 0

    def on_primary(self, n: int = 1) -> None:
        with self._mu:
            self._primaries += n
            self._tokens = min(self.burst, self._tokens + self.rate * n)

    def spend(self) -> bool:
        with self._mu:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self._hedges += 1
                return True
            self._denied += 1
            return False

    def hedge_rate(self) -> float:
        """Hedges issued per primary attempt (the bench/SLO signal)."""
        with self._mu:
            return self._hedges / self._primaries if self._primaries else 0.0

    def stats(self) -> dict:
        with self._mu:
            return {
                "primaries": self._primaries,
                "hedges": self._hedges,
                "denied": self._denied,
                "tokens": round(self._tokens, 3),
                "rate": self.rate,
            }
