"""Pod liveness tracking for degraded-mode scoring.

The event :class:`~llmd_kv_cache_tpu.events.pool.Pool` touches a pod
every time it processes one of its events; scorers multiply each pod's
score by :meth:`PodLivenessTracker.factor`.  A pod that stops emitting
events (crashed, partitioned, wedged publisher) decays linearly from
full weight at ``stale_after_s`` to zero at ``drop_after_s``, so the
router shifts traffic away gradually and finally falls back to
round-robin rather than routing to a corpse with a stale index view.

Gray failures — a pod that is *slow* rather than dead — never trip the
staleness decay (its events keep flowing). When serving-latency samples
are fed via :meth:`observe_latency`, a second, independent demotion
kicks in: each pod keeps a latency EMA, and a pod whose EMA exceeds
``latency_demote_after_s`` decays linearly to ``latency_floor`` at
``latency_drop_after_s`` — demoted, never fully zeroed, because a slow
pod still serves (unlike a dead one) and zero-weighting the whole fleet
during a global slowdown would leave nothing to route to. The two
factors multiply. Latency demotion is off (factor 1.0) until
``latency_demote_after_s > 0`` and at least ``_MIN_LATENCY_SAMPLES``
samples arrived, so existing deployments see no behavior change.

Pods the tracker has never seen score at full weight: a fresh indexer
(or one tracking pods discovered out-of-band) must not zero the fleet.
"""

from __future__ import annotations

import time
from typing import Callable, Dict

from ..utils.lockdep import new_lock

# EMA smoothing for latency samples (~20-sample memory) and the minimum
# evidence before a pod can be demoted for slowness.
_LATENCY_ALPHA = 0.1
_MIN_LATENCY_SAMPLES = 5


class PodLivenessTracker:
    def __init__(
        self,
        stale_after_s: float = 30.0,
        drop_after_s: float = 120.0,
        latency_demote_after_s: float = 0.0,
        latency_drop_after_s: float = 0.0,
        latency_floor: float = 0.1,
        clock: Callable[[], float] = time.monotonic,
    ):
        if drop_after_s <= stale_after_s:
            raise ValueError(
                f"drop_after_s ({drop_after_s}) must exceed stale_after_s ({stale_after_s})"
            )
        if latency_demote_after_s > 0:
            if latency_drop_after_s <= latency_demote_after_s:
                raise ValueError(
                    f"latency_drop_after_s ({latency_drop_after_s}) must "
                    f"exceed latency_demote_after_s ({latency_demote_after_s})"
                )
            if not 0.0 <= latency_floor <= 1.0:
                raise ValueError(
                    f"latency_floor must be in [0, 1], got {latency_floor}"
                )
        self.stale_after_s = stale_after_s
        self.drop_after_s = drop_after_s
        self.latency_demote_after_s = latency_demote_after_s
        self.latency_drop_after_s = latency_drop_after_s
        self.latency_floor = latency_floor
        self._clock = clock
        self._lock = new_lock()
        self._last_seen: Dict[str, float] = {}
        # pod -> (ema_seconds, sample_count)
        self._latency: Dict[str, tuple[float, int]] = {}

    def touch(self, pod: str) -> None:
        with self._lock:
            self._last_seen[pod] = self._clock()

    def observe_latency(self, pod: str, seconds: float) -> None:
        """Feed one serving-latency sample (e.g. a shard RPC or a pod's
        TTFT) for gray-failure demotion. Cheap: one lock, two floats."""
        seconds = max(0.0, seconds)
        with self._lock:
            prev = self._latency.get(pod)
            if prev is None:
                self._latency[pod] = (seconds, 1)
            else:
                ema, n = prev
                self._latency[pod] = (
                    ema + _LATENCY_ALPHA * (seconds - ema), n + 1
                )

    def mark_removed(self, pod: str) -> None:
        with self._lock:
            self._last_seen.pop(pod, None)
            self._latency.pop(pod, None)

    def last_seen(self, pod: str) -> float | None:
        with self._lock:
            return self._last_seen.get(pod)

    def staleness(self, pod: str) -> float | None:
        """Seconds since the pod's last event, or None if never seen."""
        with self._lock:
            ts = self._last_seen.get(pod)
        return None if ts is None else max(0.0, self._clock() - ts)

    def latency_ema(self, pod: str) -> float | None:
        """Current latency EMA in seconds, or None without samples."""
        with self._lock:
            entry = self._latency.get(pod)
            return entry[0] if entry is not None else None

    def _latency_factor_locked(self, pod: str) -> float:
        if self.latency_demote_after_s <= 0:
            return 1.0
        entry = self._latency.get(pod)
        if entry is None or entry[1] < _MIN_LATENCY_SAMPLES:
            return 1.0
        ema = entry[0]
        if ema <= self.latency_demote_after_s:
            return 1.0
        if ema >= self.latency_drop_after_s:
            return self.latency_floor
        span = self.latency_drop_after_s - self.latency_demote_after_s
        frac = (ema - self.latency_demote_after_s) / span
        return 1.0 - (1.0 - self.latency_floor) * frac

    def latency_factor(self, pod: str) -> float:
        """Gray-failure multiplier in [latency_floor, 1]."""
        with self._lock:
            return self._latency_factor_locked(pod)

    def factor(self, pod: str) -> float:
        """Score multiplier in [0, 1]: staleness decay x latency demotion."""
        age = self.staleness(pod)
        if age is None or age <= self.stale_after_s:
            staleness_factor = 1.0
        elif age >= self.drop_after_s:
            return 0.0
        else:
            span = self.drop_after_s - self.stale_after_s
            staleness_factor = 1.0 - (age - self.stale_after_s) / span
        return staleness_factor * self.latency_factor(pod)

    def snapshot(self) -> Dict[str, float]:
        """Current factor per tracked pod (observability hook)."""
        with self._lock:
            pods = set(self._last_seen) | set(self._latency)
        return {p: self.factor(p) for p in pods}
