"""Pod liveness tracking for degraded-mode scoring.

The event :class:`~llmd_kv_cache_tpu.events.pool.Pool` touches a pod
every time it processes one of its events; scorers multiply each pod's
score by :meth:`PodLivenessTracker.factor`.  A pod that stops emitting
events (crashed, partitioned, wedged publisher) decays linearly from
full weight at ``stale_after_s`` to zero at ``drop_after_s``, so the
router shifts traffic away gradually and finally falls back to
round-robin rather than routing to a corpse with a stale index view.

Pods the tracker has never seen score at full weight: a fresh indexer
(or one tracking pods discovered out-of-band) must not zero the fleet.
"""

from __future__ import annotations

import time
from typing import Callable, Dict

from ..utils.lockdep import new_lock


class PodLivenessTracker:
    def __init__(
        self,
        stale_after_s: float = 30.0,
        drop_after_s: float = 120.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if drop_after_s <= stale_after_s:
            raise ValueError(
                f"drop_after_s ({drop_after_s}) must exceed stale_after_s ({stale_after_s})"
            )
        self.stale_after_s = stale_after_s
        self.drop_after_s = drop_after_s
        self._clock = clock
        self._lock = new_lock()
        self._last_seen: Dict[str, float] = {}

    def touch(self, pod: str) -> None:
        with self._lock:
            self._last_seen[pod] = self._clock()

    def mark_removed(self, pod: str) -> None:
        with self._lock:
            self._last_seen.pop(pod, None)

    def last_seen(self, pod: str) -> float | None:
        with self._lock:
            return self._last_seen.get(pod)

    def staleness(self, pod: str) -> float | None:
        """Seconds since the pod's last event, or None if never seen."""
        with self._lock:
            ts = self._last_seen.get(pod)
        return None if ts is None else max(0.0, self._clock() - ts)

    def factor(self, pod: str) -> float:
        """Score multiplier in [0, 1]: 1 fresh, linear decay, 0 dead."""
        age = self.staleness(pod)
        if age is None or age <= self.stale_after_s:
            return 1.0
        if age >= self.drop_after_s:
            return 0.0
        span = self.drop_after_s - self.stale_after_s
        return 1.0 - (age - self.stale_after_s) / span

    def snapshot(self) -> Dict[str, float]:
        """Current factor per tracked pod (observability hook)."""
        with self._lock:
            pods = list(self._last_seen)
        return {p: self.factor(p) for p in pods}
