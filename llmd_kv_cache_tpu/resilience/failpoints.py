"""Named, deterministic fault-injection points.

A *failpoint* is a named hook planted at an I/O boundary.  Production
code calls :meth:`FailpointRegistry.hit` (or :meth:`should_fire` for
custom corruption-style faults); when the failpoint is disarmed this is
a single dict lookup, so hooks are safe to leave in hot-ish paths.

Failpoints are armed programmatically (chaos tests) or from the
environment:

    KVTPU_FAILPOINTS="offload.load.io_error=error:p=1:times=2,index.redis.op=error"
    KVTPU_FAILPOINT_SEED=1234

Spec grammar per failpoint:
``name=mode[:p=<prob>][:times=<n>][:delay=<s>|delay_ms=<n>][:jitter=<s>|jitter_ms=<n>][:pause=<s>|pause_ms=<n>]``
with modes ``error`` (raise :class:`FaultInjected`), ``delay`` (sleep),
``custom`` (``should_fire`` returns True; the call site decides what
the fault looks like — e.g. flipping bytes to tear a file), and
``pause`` (a *virtual* stop-the-world stall: :meth:`pause_seconds`
returns the armed duration without ever sleeping, so chaos tests
simulate a GC-paused zombie by aging its lease/clock deterministically
instead of stalling the test for real). ``jitter`` adds a uniform
``[0, jitter]`` extension to each sleep — and to each virtual pause —
modeling the wandering latency of a gray-failing pod rather than a
fixed stall.

Determinism: probabilistic firing draws from a registry-owned
``random.Random`` seeded at construction (``KVTPU_FAILPOINT_SEED``,
default 0), so a chaos run replays exactly. Jitter draws come from a
*per-failpoint* RNG seeded from ``(registry seed, failpoint name)``, so
one point's delay schedule replays identically regardless of how other
points' firings interleave with it across threads.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field

from ..utils.lockdep import new_lock

logger = logging.getLogger(__name__)

ENV_FAILPOINTS = "KVTPU_FAILPOINTS"
ENV_SEED = "KVTPU_FAILPOINT_SEED"

MODE_ERROR = "error"
MODE_DELAY = "delay"
MODE_CUSTOM = "custom"
MODE_PAUSE = "pause"

_MODES = (MODE_ERROR, MODE_DELAY, MODE_CUSTOM, MODE_PAUSE)


class FaultInjected(RuntimeError):
    """Raised by an armed ``error``-mode failpoint.

    Carries the failpoint name so retry policies can treat injected
    faults like the real failures they stand in for.
    """

    def __init__(self, name: str):
        super().__init__(f"fault injected at failpoint '{name}'")
        self.failpoint = name


@dataclass
class _Failpoint:
    name: str
    mode: str = MODE_ERROR
    probability: float = 1.0
    times: int | None = None  # remaining firings; None = unlimited
    delay_s: float = 0.0
    jitter_s: float = 0.0  # uniform [0, jitter_s) added to each sleep
    pause_s: float = 0.0  # virtual stall length for MODE_PAUSE (never slept)
    rng: random.Random | None = None  # per-point RNG for jitter draws
    hits: int = 0  # times the hook was reached
    fired: int = 0  # times the fault actually triggered
    lock: threading.Lock = field(default_factory=lambda: new_lock(), repr=False)


class FailpointRegistry:
    """Thread-safe registry of named failpoints with a seeded RNG."""

    def __init__(self, seed: int = 0):
        self._lock = new_lock()
        self._points: dict[str, _Failpoint] = {}
        self._rng = random.Random(seed)
        self._seed = seed
        # Fired-failpoint observers (flight recorder, tests). Called outside
        # the registry lock with just the failpoint name; a listener that
        # raises is dropped from the notification (never breaks injection).
        self._listeners: list = []

    def add_listener(self, fn) -> None:
        """Register ``fn(name)`` to run on every fired failpoint (idempotent
        by identity)."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def _notify(self, name: str) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(name)
            except Exception:  # pragma: no cover - observers must not break injection  # lint: allow-swallow
                pass

    # -- configuration ----------------------------------------------------

    def arm(
        self,
        name: str,
        mode: str = MODE_ERROR,
        probability: float = 1.0,
        times: int | None = None,
        delay_s: float = 0.0,
        jitter_s: float = 0.0,
        pause_s: float = 0.0,
    ) -> None:
        if mode not in _MODES:
            raise ValueError(f"unknown failpoint mode {mode!r}; expected one of {_MODES}")
        if not 0.0 <= probability <= 1.0:
            raise ValueError(f"probability must be in [0, 1], got {probability}")
        if jitter_s < 0.0:
            raise ValueError(f"jitter_s must be >= 0, got {jitter_s}")
        if pause_s < 0.0:
            raise ValueError(f"pause_s must be >= 0, got {pause_s}")
        with self._lock:
            # Per-point RNG keyed off (seed, name): jitter schedules replay
            # per-point regardless of cross-point thread interleaving.
            rng = random.Random(f"{self._seed}:{name}") if jitter_s > 0 else None
            self._points[name] = _Failpoint(
                name=name, mode=mode, probability=probability,
                times=times, delay_s=delay_s, jitter_s=jitter_s,
                pause_s=pause_s, rng=rng,
            )
        logger.debug("armed failpoint %s mode=%s p=%s times=%s", name, mode, probability, times)

    def disarm(self, name: str) -> None:
        with self._lock:
            self._points.pop(name, None)

    def reset(self, seed: int | None = None) -> None:
        """Disarm everything and reseed the RNG (chaos-test fixture hook)."""
        with self._lock:
            self._points.clear()
            self._rng = random.Random(self._seed if seed is None else seed)
            if seed is not None:
                self._seed = seed

    def configure_from_env(self, env: dict[str, str] | None = None) -> None:
        env = os.environ if env is None else env
        seed = env.get(ENV_SEED)
        if seed is not None:
            self.reset(seed=int(seed))
        spec = env.get(ENV_FAILPOINTS, "")
        for part in filter(None, (p.strip() for p in spec.split(","))):
            self._arm_from_spec(part)

    def _arm_from_spec(self, spec: str) -> None:
        name, _, rest = spec.partition("=")
        mode, probability, times = MODE_ERROR, 1.0, None
        delay_s, jitter_s, pause_s = 0.0, 0.0, 0.0
        for tok in filter(None, rest.split(":")):
            if tok in _MODES:
                mode = tok
            elif tok.startswith("p="):
                probability = float(tok[2:])
            elif tok.startswith("times="):
                times = int(tok[6:])
            elif tok.startswith("delay_ms="):
                delay_s = float(tok[9:]) / 1e3
            elif tok.startswith("delay="):
                delay_s = float(tok[6:])
            elif tok.startswith("jitter_ms="):
                jitter_s = float(tok[10:]) / 1e3
            elif tok.startswith("jitter="):
                jitter_s = float(tok[7:])
            elif tok.startswith("pause_ms="):
                mode, pause_s = MODE_PAUSE, float(tok[9:]) / 1e3
            elif tok.startswith("pause="):
                # A duration implies the mode: ``name=pause=12`` and
                # ``name=pause:pause=12`` both arm a 12 s virtual stall.
                mode, pause_s = MODE_PAUSE, float(tok[6:])
            else:
                raise ValueError(f"bad failpoint spec token {tok!r} in {spec!r}")
        self.arm(name, mode=mode, probability=probability, times=times,
                 delay_s=delay_s, jitter_s=jitter_s, pause_s=pause_s)

    # -- introspection ----------------------------------------------------

    def is_armed(self, name: str) -> bool:
        with self._lock:
            return name in self._points

    def stats(self, name: str) -> tuple[int, int]:
        """Return ``(hits, fired)`` for a failpoint (0, 0 if never armed)."""
        with self._lock:
            fp = self._points.get(name)
            return (fp.hits, fp.fired) if fp is not None else (0, 0)

    # -- firing -----------------------------------------------------------

    def _roll(self, name: str) -> _Failpoint | None:
        """Decide whether the named failpoint fires; returns it if so."""
        with self._lock:
            fp = self._points.get(name)
            if fp is None:
                return None
            fp.hits += 1
            if fp.times is not None and fp.times <= 0:
                return None
            if fp.probability < 1.0 and self._rng.random() >= fp.probability:
                return None
            if fp.times is not None:
                fp.times -= 1
            fp.fired += 1
            return fp

    def should_fire(self, name: str) -> bool:
        """Custom-mode check: True when the call site should inject its fault."""
        fired = self._roll(name) is not None
        if fired:
            self._notify(name)
        return fired

    def pause_seconds(self, name: str) -> float:
        """Pause-mode check: the virtual stall to apply, 0.0 when quiet.

        Never sleeps — the call site ages its own clock (a lease's last
        renewal, a liveness stamp) by the returned seconds, exactly what a
        stop-the-world GC pause of that length would have done to it.
        Seeded jitter extends the stall the same way it extends delay-mode
        sleeps, so a chaos run's pause schedule replays identically.
        """
        fp = self._roll(name)
        if fp is None or fp.mode != MODE_PAUSE:
            return 0.0
        self._notify(name)
        logger.warning("failpoint %s fired (mode=%s, count=%d)", name, fp.mode, fp.fired)
        stall = fp.pause_s
        if fp.jitter_s > 0.0 and fp.rng is not None:
            with fp.lock:
                stall += fp.rng.uniform(0.0, fp.jitter_s)
        return stall

    def hit(self, name: str) -> None:
        """Standard hook: raise/sleep per the armed mode, no-op otherwise."""
        fp = self._roll(name)
        if fp is None:
            return
        self._notify(name)
        logger.warning("failpoint %s fired (mode=%s, count=%d)", name, fp.mode, fp.fired)
        sleep_s = fp.delay_s
        if fp.jitter_s > 0.0 and fp.rng is not None:
            with fp.lock:
                sleep_s += fp.rng.uniform(0.0, fp.jitter_s)
        if sleep_s > 0.0:
            time.sleep(sleep_s)
        if fp.mode == MODE_ERROR:
            raise FaultInjected(name)


# Process-wide registry; chaos tests arm/reset it, prod leaves it empty.
failpoints = FailpointRegistry(seed=int(os.environ.get(ENV_SEED, "0")))
if os.environ.get(ENV_FAILPOINTS):
    failpoints.configure_from_env()
