"""Retry/backoff policies and per-target circuit breakers.

These are deliberately dependency-free and clock-injectable so unit
tests can drive them without sleeping.  The defaults are tuned for the
in-cluster failure profile: short first retry (transient fs/network
blips resolve in tens of milliseconds), exponential growth with full
jitter to avoid thundering herds, and a hard deadline so callers on the
request path never wait unboundedly.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from ..utils.lockdep import new_lock

logger = logging.getLogger(__name__)


class RetryExhausted(Exception):
    """All attempts failed; ``__cause__`` is the last underlying error."""


@dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff with an overall deadline.

    ``delay(attempt)`` for attempt ``n`` (0-based, i.e. delay before
    retry ``n+1``) is uniform in ``[0, min(max_delay_s, base_delay_s *
    multiplier**n)]`` when ``jitter`` is set ("full jitter"), else the
    deterministic cap value.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: bool = True
    deadline_s: float | None = None

    def delay(self, attempt: int, rng: random.Random | None = None) -> float:
        cap = min(self.max_delay_s, self.base_delay_s * (self.multiplier ** attempt))
        if not self.jitter:
            return cap
        return (rng.random() if rng is not None else random.random()) * cap


def call_with_retry(
    fn: Callable,
    policy: RetryPolicy,
    *,
    retryable: Callable[[BaseException], bool] | None = None,
    on_retry: Callable[[int, BaseException], None] | None = None,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    rng: random.Random | None = None,
):
    """Invoke ``fn()`` under ``policy``; raise :class:`RetryExhausted` when spent.

    ``retryable`` filters which exceptions are worth retrying (default:
    every ``Exception``); a non-retryable error propagates immediately.
    """
    start = clock()
    last_exc: BaseException | None = None
    for attempt in range(policy.max_attempts):
        try:
            return fn()
        except Exception as exc:
            if retryable is not None and not retryable(exc):
                raise
            last_exc = exc
            if attempt + 1 >= policy.max_attempts:
                break
            pause = policy.delay(attempt, rng)
            if policy.deadline_s is not None and clock() - start + pause > policy.deadline_s:
                break
            if on_retry is not None:
                on_retry(attempt, exc)
            logger.debug("retry %d after %s: sleeping %.3fs", attempt + 1, exc, pause)
            sleep(pause)
    raise RetryExhausted(
        f"{policy.max_attempts} attempt(s) failed: {last_exc}"
    ) from last_exc


class CircuitOpenError(Exception):
    """The breaker is open; the protected target is being shed."""

    def __init__(self, target: str, retry_after_s: float):
        super().__init__(f"circuit for '{target}' is open (retry in {retry_after_s:.1f}s)")
        self.target = target
        self.retry_after_s = retry_after_s


_CLOSED, _OPEN, _HALF_OPEN = "closed", "open", "half_open"


@dataclass
class CircuitBreaker:
    """Classic three-state breaker guarding one target.

    CLOSED → OPEN after ``failure_threshold`` consecutive failures;
    OPEN → HALF_OPEN after ``reset_timeout_s``; one probe call is then
    admitted — success closes the breaker, failure re-opens it.

    The probe slot is a *lease*, not a latch: if the prober never reports
    back (its thread died mid-call, its process was killed, an exception
    path swallowed the outcome), the lease expires after
    ``probe_timeout_s`` and the next ``allow()`` claims it. Without the
    lease a single dead prober wedges the breaker in half-open forever —
    every caller rejected, no probe ever running (a gray failure of the
    breaker itself).
    """

    target: str = "unnamed"
    failure_threshold: int = 5
    reset_timeout_s: float = 10.0
    # Probe lease: how long a claimed half-open probe slot stays reserved
    # before another caller may reclaim it. Must comfortably exceed the
    # slowest legitimate probe RPC.
    probe_timeout_s: float = 30.0
    clock: Callable[[], float] = time.monotonic

    _state: str = field(default=_CLOSED, init=False)
    _failures: int = field(default=0, init=False)
    _opened_at: float = field(default=0.0, init=False)
    _probing: bool = field(default=False, init=False)
    _probe_started_at: float = field(default=0.0, init=False)
    _lock: threading.Lock = field(default_factory=lambda: new_lock(), init=False, repr=False)

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        if self._state == _OPEN and self.clock() - self._opened_at >= self.reset_timeout_s:
            self._state = _HALF_OPEN
            self._probing = False

    def allow(self) -> bool:
        """True if a call may proceed (claims the probe slot in half-open)."""
        with self._lock:
            self._maybe_half_open()
            if self._state == _CLOSED:
                return True
            if self._state == _HALF_OPEN:
                now = self.clock()
                if (self._probing
                        and now - self._probe_started_at >= self.probe_timeout_s):
                    # Probe lease expired: the prober went quiet without
                    # reporting an outcome. Reclaim so the breaker can
                    # still make progress (a late report from the stale
                    # prober is harmless — it just records an outcome).
                    logger.warning(
                        "circuit for '%s': probe lease expired after %.1fs; "
                        "reclaiming", self.target, self.probe_timeout_s,
                    )
                    self._probing = False
                if not self._probing:
                    self._probing = True
                    self._probe_started_at = now
                    return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._state = _CLOSED
            self._failures = 0
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == _HALF_OPEN or self._failures >= self.failure_threshold:
                if self._state != _OPEN:
                    logger.warning(
                        "circuit for '%s' opened after %d failure(s)",
                        self.target, self._failures,
                    )
                self._state = _OPEN
                self._opened_at = self.clock()
                self._probing = False

    def call(self, fn: Callable):
        """Run ``fn`` through the breaker, recording the outcome."""
        if not self.allow():
            with self._lock:
                remaining = max(0.0, self.reset_timeout_s - (self.clock() - self._opened_at))
            raise CircuitOpenError(self.target, remaining)
        try:
            result = fn()
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result
