"""Degraded-mode index: primary backend with in-memory failover.

Wraps a primary :class:`~llmd_kv_cache_tpu.index.base.Index` (typically
Redis) behind a retry policy and a circuit breaker.  Every write is
mirrored into the fallback index first, so the fallback holds a warm
(LRU-bounded) replica of everything this process has learned; when the
primary's breaker opens, reads are served from the fallback until the
breaker's probe succeeds.  The index is soft state rebuilt from the
event stream, so a temporarily narrower fallback view only costs some
routing quality — never correctness.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..core.keys import BlockHash, KeyType, PodEntry
from ..index.base import Index
from ..telemetry import flight_recorder, tracer
from ..telemetry.flight_recorder import KIND_FAILOVER
from ..utils.logging import get_logger
from .policy import CircuitBreaker, CircuitOpenError, RetryPolicy, call_with_retry

logger = get_logger("resilience.failover")


class FailoverIndex(Index):
    """Index wrapper: primary under breaker+retry, in-memory fallback."""

    def __init__(
        self,
        primary,
        fallback,
        retry_policy: Optional[RetryPolicy] = None,
        breaker: Optional[CircuitBreaker] = None,
    ):
        self.primary = primary
        self.fallback = fallback
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=2, base_delay_s=0.02, max_delay_s=0.2, deadline_s=1.0
        )
        self.breaker = breaker or CircuitBreaker(
            target="index.primary", failure_threshold=3, reset_timeout_s=5.0
        )
        self.failovers = 0  # reads served by the fallback

    # -- internals --------------------------------------------------------

    def _primary_call(self, fn):
        """Run a primary op through breaker + retry; raise on failure."""
        return self.breaker.call(
            lambda: call_with_retry(fn, self.retry_policy)
        )

    def _record_failover(self, op_name: str, reason: str) -> None:
        """Flight-record + span the failover decision so post-hoc debugging
        can see when (and why) routing quality degraded to the fallback."""
        flight_recorder().record(
            KIND_FAILOVER,
            {
                "op": op_name,
                "reason": reason,
                "breaker_state": self.breaker.state,
                "failovers": self.failovers,
            },
        )
        with tracer().span(
            "llm_d.kv_cache.resilience.failover",
            op=op_name,
            reason=reason,
            breaker_state=self.breaker.state,
        ):
            pass

    def _read(self, op_name: str, primary_fn, fallback_fn):
        try:
            return self._primary_call(primary_fn)
        except CircuitOpenError:
            self.failovers += 1
            self._record_failover(op_name, "breaker_open")
            return fallback_fn()
        except Exception as exc:
            self.failovers += 1
            self._record_failover(op_name, f"error: {exc}")
            logger.warning("primary index %s failed (%s); serving fallback", op_name, exc)
            return fallback_fn()

    def _write(self, op_name: str, primary_fn) -> None:
        # Fallback is written by the caller before this; primary write
        # failures are absorbed (the breaker counts them) because the
        # event stream will converge the primary once it heals.
        try:
            self._primary_call(primary_fn)
        except CircuitOpenError:  # lint: allow-swallow (breaker open; fallback already holds the write)
            pass
        except Exception as exc:
            logger.warning("primary index %s failed (%s); fallback retains write", op_name, exc)

    # -- Index contract ---------------------------------------------------

    def lookup(
        self,
        request_keys: Sequence[BlockHash],
        pod_identifier_set: Optional[set[str]] = None,
    ) -> dict[BlockHash, list[PodEntry]]:
        return self._read(
            "lookup",
            lambda: self.primary.lookup(request_keys, pod_identifier_set),
            lambda: self.fallback.lookup(request_keys, pod_identifier_set),
        )

    def add(
        self,
        engine_keys: Optional[Sequence[BlockHash]],
        request_keys: Sequence[BlockHash],
        entries: Sequence[PodEntry],
    ) -> None:
        self.fallback.add(engine_keys, request_keys, entries)
        self._write("add", lambda: self.primary.add(engine_keys, request_keys, entries))

    def evict(
        self,
        key: BlockHash,
        key_type: KeyType,
        entries: Sequence[PodEntry],
    ) -> None:
        self.fallback.evict(key, key_type, entries)
        self._write("evict", lambda: self.primary.evict(key, key_type, entries))

    def evict_batch(
        self,
        keys: Sequence[BlockHash],
        key_type: KeyType,
        entries: Sequence[PodEntry],
    ) -> None:
        # One mirrored batch instead of N wrapped evicts: the primary's
        # pipelined implementation stays engaged and the breaker counts
        # one op per digest.
        self.fallback.evict_batch(keys, key_type, entries)
        self._write(
            "evict_batch", lambda: self.primary.evict_batch(keys, key_type, entries)
        )

    def get_request_key(self, engine_key: BlockHash) -> Optional[BlockHash]:
        return self._read(
            "get_request_key",
            lambda: self.primary.get_request_key(engine_key),
            lambda: self.fallback.get_request_key(engine_key),
        )

    def clear(self, pod_identifier: str) -> None:
        self.fallback.clear(pod_identifier)
        self._write("clear", lambda: self.primary.clear(pod_identifier))

    def dump_state(self):
        # The fallback mirrors every write this process made; the primary
        # (Redis) is durable on its own, so the warm replica is the right
        # thing to snapshot — and it works even while the breaker is open.
        return self.fallback.dump_state()

    def restore_state(self, state: dict) -> int:
        restored = self.fallback.restore_state(state)
        self._write("restore_state", lambda: self.primary.restore_state(state))
        return restored
