"""Sharded training step for the paged-Llama model family.

Full training loop piece used by fine-tuning flows and the multi-chip
dry-run: causal-LM loss, AdamW, one jitted ``train_step`` whose inputs are
sharded over a named mesh — ``dp`` on the batch, ``tp`` inside the matmuls
(Megatron layout from ``mesh.param_pspecs``), and ``sp`` on the sequence
dimension for the norm/MLP segments (Megatron-style sequence parallelism:
XLA inserts the gather before attention and the reduce-scatter after, all
derived from sharding constraints — no explicit collectives).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.llama import LlamaConfig, Params, _mlp, _rms_norm, _rope
from .mesh import param_shardings


def forward_train(
    params: Params,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [batch, seq]
    mesh_axes: tuple[Optional[str], Optional[str]] = (None, None),
    attention_fn=None,
    aux_out=None,
) -> jax.Array:
    """Causal-LM forward without KV cache (training path).

    ``mesh_axes = (dp_axis, sp_axis)`` adds sharding constraints on the
    activations; pass ``(None, None)`` for single-device runs.
    ``attention_fn(q, k, v) -> out`` overrides the attention backend — pass
    a ``ring_attention.make_ring_attention(mesh)`` fn for true sequence
    parallelism on long contexts (K/V rotate over ICI; no all-gather).
    """
    dp, sp = mesh_axes
    batch, seq = tokens.shape
    positions = jnp.arange(seq)[None, :].repeat(batch, axis=0)

    def constrain(x):
        if dp is None and sp is None:
            return x
        return jax.lax.with_sharding_constraint(x, P(dp, sp, None))

    x = constrain(params["embed"][tokens])

    for layer in params["layers"]:
        x = constrain(x + attention_block(x, layer, cfg, positions, attention_fn))
        mlp_in = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        x = constrain(x + _mlp(mlp_in, layer, cfg, aux_out=aux_out))

    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    return (x @ params["lm_head"]).astype(jnp.float32)


def attention_block(x, layer, cfg, positions, attention_fn=None):
    """One training-path attention block (shared by the python-loop and
    pipeline-scan formulations so they cannot drift).

    ``attention_fn(q, k, v) -> out`` overrides the dense causal backend
    (e.g. ring attention); the dense path builds its causal mask here (the
    override path never traces the O(S^2) mask).
    """
    batch, seq = x.shape[0], x.shape[1]
    attn_in = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = (attn_in @ layer["wq"]).reshape(batch, seq, cfg.num_heads, cfg.head_dim)
    k = (attn_in @ layer["wk"]).reshape(batch, seq, cfg.num_kv_heads, cfg.head_dim)
    v = (attn_in @ layer["wv"]).reshape(batch, seq, cfg.num_kv_heads, cfg.head_dim)
    if cfg.qk_norm:  # Qwen3: per-head RMS over head_dim, pre-RoPE
        q = _rms_norm(q, layer["q_norm"], cfg.norm_eps)
        k = _rms_norm(k, layer["k_norm"], cfg.norm_eps)
    q = _rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
    k = _rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
    if cfg.num_heads != cfg.num_kv_heads:
        rep = cfg.num_heads // cfg.num_kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    if attention_fn is not None:
        attn = attention_fn(q, k, v)
    else:
        causal = jnp.tril(jnp.ones((seq, seq), bool))
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
        ) * (cfg.head_dim ** -0.5)
        scores = jnp.where(causal[None, None], scores, -1e30)
        attn = jnp.einsum(
            "bhqk,bkhd->bqhd", jax.nn.softmax(scores, axis=-1),
            v.astype(jnp.float32),
        ).astype(x.dtype)
    return attn.reshape(batch, seq, -1) @ layer["wo"]


MOE_AUX_LOSS_WEIGHT = 0.01  # Switch-Transformer convention


def loss_fn(params: Params, cfg: LlamaConfig, tokens: jax.Array, mesh_axes,
            attention_fn=None) -> jax.Array:
    """Next-token cross-entropy over shifted tokens.

    MoE configs add the Switch load-balancing auxiliary term so the router
    cannot collapse onto a few experts (dead-expert failure mode)."""
    aux: list = [] if cfg.num_experts > 0 else None
    logits = forward_train(params, cfg, tokens, mesh_axes, attention_fn,
                           aux_out=aux)
    targets = tokens[:, 1:]
    logprobs = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    loss = nll.mean()
    if aux:
        loss = loss + MOE_AUX_LOSS_WEIGHT * sum(aux) / len(aux)
    return loss


def make_train_state(
    params: Params, learning_rate: float = 1e-3
) -> tuple[optax.GradientTransformation, Any]:
    opt = optax.adamw(learning_rate)
    return opt, opt.init(params)


def train_step(
    params: Params,
    opt_state: Any,
    cfg: LlamaConfig,
    opt: optax.GradientTransformation,
    tokens: jax.Array,
    mesh_axes: tuple[Optional[str], Optional[str]] = (None, None),
    attention_fn=None,
):
    """One full training step: loss, grads, AdamW update.

    Under a mesh, gradient reduction across ``dp`` falls out of the
    sharding annotations (XLA emits the reduce-scatter/all-reduce over
    ICI); with ``attention_fn`` = ring attention, the sequence axis scales
    by neighbor exchanges instead of gathers.

    (The ``accum_steps=1`` case of ``train_step_accum`` — one grad/update
    implementation, no drift.)
    """
    return train_step_accum(params, opt_state, cfg, opt, tokens, mesh_axes,
                            attention_fn, 1)


@partial(jax.jit, static_argnames=("cfg", "opt", "mesh_axes", "attention_fn",
                                   "accum_steps"))
def train_step_accum(
    params: Params,
    opt_state: Any,
    cfg: LlamaConfig,
    opt: optax.GradientTransformation,
    tokens: jax.Array,  # [accum_steps * micro_batch, seq]
    mesh_axes: tuple[Optional[str], Optional[str]] = (None, None),
    attention_fn=None,
    accum_steps: int = 1,
):
    """Training step with microbatch gradient accumulation.

    The global batch splits into ``accum_steps`` equal microbatches scanned
    sequentially (bounding activation memory); gradients accumulate in
    float32 and average before a single optimizer update — numerically the
    full-batch step. Microbatches are strided (row ``m`` of microbatch j is
    global row ``m*accum_steps + j``) so each microbatch stays balanced
    across a dp-sharded batch dimension instead of clustering on a shard
    subset.
    """
    batch, seq = tokens.shape
    if accum_steps < 1 or batch % accum_steps != 0:
        raise ValueError(
            f"batch size ({batch}) must divide by accum_steps ({accum_steps})"
        )
    micro = batch // accum_steps

    if accum_steps == 1:
        loss, grads = jax.value_and_grad(loss_fn)(
            params, cfg, tokens, mesh_axes, attention_fn
        )
    else:
        micro_tokens = tokens.reshape(micro, accum_steps, seq).transpose(1, 0, 2)

        def micro_step(carry, mb):
            loss_sum, grad_sum = carry
            mloss, mgrads = jax.value_and_grad(loss_fn)(
                params, cfg, mb, mesh_axes, attention_fn
            )
            grad_sum = jax.tree.map(
                lambda acc, g: acc + g.astype(jnp.float32), grad_sum, mgrads
            )
            return (loss_sum + mloss, grad_sum), None

        # f32 accumulators: bf16 sums round away microbatch contributions
        # exactly when accumulation is most needed.
        zero_grads = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        (loss_sum, grad_sum), _ = jax.lax.scan(
            micro_step, (jnp.zeros((), jnp.float32), zero_grads), micro_tokens
        )
        grads = jax.tree.map(
            lambda g, p: (g / accum_steps).astype(p.dtype), grad_sum, params
        )
        loss = loss_sum / accum_steps

    updates, opt_state = opt.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


def make_sharded_train_step(mesh: Mesh, cfg: LlamaConfig, params: Params, opt,
                            use_ring_attention: bool = False,
                            accum_steps: int = 1):
    """Prepare a mesh-sharded training setup.

    Returns ``(step_fn, sharded_params, opt_state, data_sharding)``. The
    parameters are placed with the Megatron TP layout; the optimizer state
    inherits their shardings (``zeros_like`` preserves placement); jit then
    propagates shardings from the inputs — the idiomatic
    annotate-and-let-XLA-insert-collectives flow.

    ``use_ring_attention=True`` (requires an ``sp`` axis) replaces the
    attention gather with ring K/V rotation for long sequences.
    """
    dp = "dp" if "dp" in mesh.axis_names else None
    sp = "sp" if "sp" in mesh.axis_names else None
    sharded_params = jax.device_put(params, param_shardings(mesh, params))
    opt_state = opt.init(sharded_params)
    data_sharding = NamedSharding(mesh, P(dp, sp))

    attention_fn = None
    if use_ring_attention:
        if sp is None:
            raise ValueError("ring attention requires an 'sp' mesh axis")
        from .ring_attention import make_ring_attention

        tp = "tp" if "tp" in mesh.axis_names else None
        attention_fn = make_ring_attention(
            mesh, sp, batch_axis=dp, head_axis=tp
        )

    def step(p, s, tokens):
        return train_step_accum(p, s, cfg, opt, tokens, (dp, sp),
                                attention_fn, accum_steps)

    return jax.jit(step), sharded_params, opt_state, data_sharding
