"""Distributed execution: device meshes, shardings, parallel train/infer steps."""

from .mesh import (
    make_mesh,
    mesh_fingerprint_fields,
    param_pspecs,
    shard_params,
)
from .train import make_train_state, train_step

__all__ = [
    "make_mesh",
    "mesh_fingerprint_fields",
    "param_pspecs",
    "shard_params",
    "make_train_state",
    "train_step",
]
