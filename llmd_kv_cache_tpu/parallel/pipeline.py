"""Pipeline parallelism: layer-sharded training over a ``pp`` mesh axis.

Two schedules:

1. **Sequential stacked scan** (``make_pp_train_step``): layers stacked
   into leading-axis arrays, scanned, the layer axis sharded over ``pp``.
   Distributes parameters + optimizer state across stages; the whole
   batch flows through the stages one layer-block at a time, so the
   bubble fraction is (P-1)/P. Composes with dp AND tp.

2. **Microbatched rotating-buffer pipeline**
   (``make_pp_pipelined_train_step``): an explicit shard_map schedule —
   the batch splits into M microbatches that stream through the stages,
   activations hopping stage→stage via ``ppermute`` each tick, so up to P
   microbatches are in flight at once and the bubble fraction drops to
   (P-1)/(M+P-1) (``pipeline_bubble_fraction``). This is the SPMD
   formulation of pipelined microbatching on TPU (collectives ride ICI;
   the autodiff transpose replays the schedule in reverse, so memory is
   GPipe-shaped: all forwards live until backwards drain —
   ``remat=True`` rematerializes each tick's forward in the backward
   pass, bounding live activations to the rotating buffer at the cost of
   one extra forward). Composes with dp AND tp: inside shard_map, XLA
   cannot derive collectives from sharding annotations, so the tp path is
   hand-written Megatron — column-parallel wq/wk/wv/w_gate/w_up on local
   heads/columns, ``psum`` after the row-parallel wo/w_down, a
   vocab-parallel embedding (mask + psum) and a vocab-parallel
   cross-entropy (``pmax``/``psum`` log-sum-exp) over the tp-sharded
   lm_head.

Dense layers only (MoE layers scale across ``ep`` instead).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.llama import LlamaConfig, Params, _mlp, _rms_norm


def stack_layer_params(params: Params) -> dict:
    """Convert the per-layer list tree into stacked [L, ...] arrays."""
    layers = params["layers"]
    stacked = {
        key: jnp.stack([layer[key] for layer in layers])
        for key in layers[0]
    }
    return {
        "embed": params["embed"],
        "layers_stacked": stacked,
        "final_norm": params["final_norm"],
        "lm_head": params["lm_head"],
    }


def unstack_layer_params(stacked_params: dict) -> Params:
    """Inverse of ``stack_layer_params`` (checkpoint interop)."""
    stacked = stacked_params["layers_stacked"]
    num_layers = next(iter(stacked.values())).shape[0]
    layers = [
        {key: stacked[key][i] for key in stacked} for i in range(num_layers)
    ]
    return {
        "embed": stacked_params["embed"],
        "layers": layers,
        "final_norm": stacked_params["final_norm"],
        "lm_head": stacked_params["lm_head"],
    }


def stacked_param_pspecs(has_tp: bool, pp_axis: Optional[str],
                         qk_norm: bool = False) -> dict:
    """PartitionSpecs for the stacked tree: layer axis over ``pp``, the
    Megatron tp layout within each layer."""
    tp = "tp" if has_tp else None
    qk = ({"q_norm": P(pp_axis, None), "k_norm": P(pp_axis, None)}
          if qk_norm else {})
    return {
        "embed": P(tp, None),
        "layers_stacked": {
            **qk,
            "attn_norm": P(pp_axis, None),
            "wq": P(pp_axis, None, tp),
            "wk": P(pp_axis, None, tp),
            "wv": P(pp_axis, None, tp),
            "wo": P(pp_axis, tp, None),
            "mlp_norm": P(pp_axis, None),
            "w_gate": P(pp_axis, None, tp),
            "w_up": P(pp_axis, None, tp),
            "w_down": P(pp_axis, tp, None),
        },
        "final_norm": P(),
        "lm_head": P(None, tp),
    }


def forward_train_pp(stacked_params: dict, cfg: LlamaConfig,
                     tokens: jax.Array) -> jax.Array:
    """Causal-LM forward scanning stacked (pipeline-sharded) layers.

    The per-layer body is ``_scan_layers`` (``train.attention_block`` +
    ``_mlp``) — shared with the pipelined schedule and the python-loop
    formulation so the paths cannot drift.
    """
    batch, seq = tokens.shape
    positions = jnp.arange(seq)[None, :].repeat(batch, axis=0)
    x = stacked_params["embed"][tokens]
    x = _scan_layers(stacked_params["layers_stacked"], cfg, x, positions)
    x = _rms_norm(x, stacked_params["final_norm"], cfg.norm_eps)
    return (x @ stacked_params["lm_head"]).astype(jnp.float32)


def pp_loss_fn(stacked_params, cfg, tokens):
    logits = forward_train_pp(stacked_params, cfg, tokens)
    targets = tokens[:, 1:]
    logprobs = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


@partial(jax.jit, static_argnames=("cfg", "opt"))
def pp_train_step(stacked_params, opt_state, cfg: LlamaConfig,
                  opt: optax.GradientTransformation, tokens: jax.Array):
    loss, grads = jax.value_and_grad(pp_loss_fn)(stacked_params, cfg, tokens)
    updates, opt_state = opt.update(grads, opt_state, stacked_params)
    stacked_params = optax.apply_updates(stacked_params, updates)
    return stacked_params, opt_state, loss


def pipeline_bubble_fraction(pp_size: int, num_microbatches: int) -> float:
    """Idle fraction of the microbatched schedule: (P-1)/(M+P-1). The
    sequential stacked scan is the M=1 case, (P-1)/P."""
    return (pp_size - 1) / (num_microbatches + pp_size - 1)


def _scan_layers(layers_stacked, cfg: LlamaConfig, x: jax.Array,
                 positions: jax.Array) -> jax.Array:
    """Scan a stacked layer slab over activations ``x`` — the ONE per-layer
    body shared by the sequential and pipelined schedules (and built from
    ``train.attention_block`` + ``_mlp`` so the python-loop formulation
    cannot drift either)."""
    from .train import attention_block

    def layer_step(x, layer):
        x = x + attention_block(x, layer, cfg, positions)
        mlp_in = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        x = x + _mlp(mlp_in, layer, cfg)
        return x, None

    x, _ = jax.lax.scan(layer_step, x, layers_stacked)
    return x


def _tp_embed(embed_local: jax.Array, token_ids: jax.Array,
              tp_axis: str) -> jax.Array:
    """Vocab-parallel embedding lookup: each tp shard holds a contiguous
    row slice; out-of-slice ids contribute zero and the ``psum`` assembles
    the full vectors (Megatron VocabParallelEmbedding)."""
    rows = embed_local.shape[0]
    shard = jax.lax.axis_index(tp_axis)
    local_ids = token_ids - shard * rows
    ok = (local_ids >= 0) & (local_ids < rows)
    e = embed_local[jnp.clip(local_ids, 0, rows - 1)]
    return jax.lax.psum(jnp.where(ok[..., None], e, 0), tp_axis)


def _tp_layer_step(x: jax.Array, layer: dict, cfg: LlamaConfig,
                   positions: jax.Array, tp_axis: str) -> jax.Array:
    """One dense layer on tp-local weight shards with explicit collectives.

    Column-parallel wq/wk/wv give each shard ``num_heads/tp`` query heads
    (heads are attention-independent, so no collective until the output
    projection); the row-parallel wo/w_down products are partial sums over
    the hidden/intermediate slices, fixed by one ``psum`` each — the
    hand-written form of what XLA derives from sharding annotations in the
    sequential schedule.
    """
    from ..models.llama import _rope

    batch, seq = x.shape[0], x.shape[1]
    attn_in = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = (attn_in @ layer["wq"]).reshape(batch, seq, -1, cfg.head_dim)
    k = (attn_in @ layer["wk"]).reshape(batch, seq, -1, cfg.head_dim)
    v = (attn_in @ layer["wv"]).reshape(batch, seq, -1, cfg.head_dim)
    if cfg.qk_norm:  # Qwen3: per-head RMS over head_dim, pre-RoPE
        q = _rms_norm(q, layer["q_norm"], cfg.norm_eps)
        k = _rms_norm(k, layer["k_norm"], cfg.norm_eps)
    q = _rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
    k = _rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
    if cfg.num_heads != cfg.num_kv_heads:
        rep = cfg.num_heads // cfg.num_kv_heads  # per-shard ratio unchanged
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    causal = jnp.tril(jnp.ones((seq, seq), bool))
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * (cfg.head_dim ** -0.5)
    scores = jnp.where(causal[None, None], scores, -1e30)
    attn = jnp.einsum(
        "bhqk,bkhd->bqhd", jax.nn.softmax(scores, axis=-1),
        v.astype(jnp.float32),
    ).astype(x.dtype)
    attn_out = attn.reshape(batch, seq, -1) @ layer["wo"]
    x = x + jax.lax.psum(attn_out, tp_axis)

    mlp_in = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    h = jax.nn.silu(mlp_in @ layer["w_gate"]) * (mlp_in @ layer["w_up"])
    return x + jax.lax.psum(h @ layer["w_down"], tp_axis)


def _tp_vocab_parallel_nll(h: jax.Array, lm_head_local: jax.Array,
                           targets: jax.Array, tp_axis: str) -> jax.Array:
    """Cross-entropy over a vocab-sharded head without materializing the
    full logits on any shard: a ``pmax``/``psum`` log-sum-exp plus a
    masked ``psum`` gather of each target's logit (Megatron
    vocab-parallel cross-entropy). ``h`` is [b, s, hidden] (positions
    already shifted); ``targets`` is [b, s]."""
    logits = (h @ lm_head_local).astype(jnp.float32)  # [b, s, vocab/tp]
    v_local = logits.shape[-1]
    # The stability shift is gradient-free (it cancels in lse - tgt), and
    # pmax has no differentiation rule — detach before the collective.
    m = jax.lax.pmax(
        jnp.max(jax.lax.stop_gradient(logits), axis=-1), tp_axis)  # [b, s]
    lse = jnp.log(jax.lax.psum(
        jnp.sum(jnp.exp(logits - m[..., None]), axis=-1), tp_axis)) + m
    shard = jax.lax.axis_index(tp_axis)
    local_t = targets - shard * v_local
    ok = (local_t >= 0) & (local_t < v_local)
    tgt = jnp.take_along_axis(
        logits, jnp.clip(local_t, 0, v_local - 1)[..., None], axis=-1
    )[..., 0]
    tgt = jax.lax.psum(jnp.where(ok, tgt, 0.0), tp_axis)
    return lse - tgt  # [b, s] per-token NLL


def make_pp_pipelined_train_step(mesh: Mesh, cfg: LlamaConfig, params: Params,
                                 opt, num_microbatches: int,
                                 remat: bool = False):
    """Microbatched rotating-buffer pipeline over ``mesh``'s ``pp`` axis
    (× optional ``dp``).

    Returns ``(step_fn, stacked_params, opt_state, data_sharding)`` like
    ``make_pp_train_step``; the two produce identical losses/gradients for
    the same params (the schedule changes wall-clock shape, not math).
    """
    from .ring_attention import shard_map  # jax-version compat shim

    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if "pp" not in axis_sizes:
        raise ValueError("pipelined training requires a 'pp' mesh axis")
    if cfg.num_experts > 0:
        raise ValueError("pipeline path supports dense layers (MoE uses ep)")
    P_size = axis_sizes["pp"]
    M = num_microbatches
    if cfg.num_layers % P_size != 0:
        raise ValueError(
            f"num_layers ({cfg.num_layers}) must divide by pp size ({P_size})")
    dp = "dp" if "dp" in axis_sizes else None
    tp_size = axis_sizes.get("tp", 1)
    tp = "tp" if tp_size > 1 else None
    if tp is not None:
        if cfg.num_kv_heads % tp_size or cfg.vocab_size % tp_size:
            raise ValueError(
                f"tp={tp_size} must divide num_kv_heads "
                f"({cfg.num_kv_heads}) and vocab_size ({cfg.vocab_size})")

    stacked = stack_layer_params(params)
    # has_tp=True already places the embedding vocab-parallel (P(tp, None))
    # and lm_head column-parallel — the Megatron layout the hand-written
    # collectives below assume.
    param_specs = stacked_param_pspecs(tp is not None, "pp",
                                       qk_norm=cfg.qk_norm)
    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        param_specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    stacked = jax.device_put(stacked, shardings)
    opt_state = opt.init(stacked)
    data_sharding = NamedSharding(mesh, P(dp, None))

    perm = [(i, i + 1) for i in range(P_size - 1)]

    def pipeline_loss(sp, tokens):
        # tokens: microbatch-local [b_mb, S] per (dp shard); split into M
        # microbatches along batch.
        b, S = tokens.shape
        if b % M != 0:
            raise ValueError(f"local batch {b} must divide by M={M}")
        mbs = tokens.reshape(M, b // M, S)
        positions = jnp.arange(S)[None, :].repeat(b // M, axis=0)
        stage = jax.lax.axis_index("pp")
        layers_local = sp["layers_stacked"]

        def embed(ids):
            if tp is not None:
                return _tp_embed(sp["embed"], ids, tp)
            return sp["embed"][ids]

        def run_layers(x):
            if tp is not None:
                def layer_step(x, layer):
                    return _tp_layer_step(x, layer, cfg, positions, tp), None

                x, _ = jax.lax.scan(layer_step, x, layers_local)
                return x
            return _scan_layers(layers_local, cfg, x, positions)

        def head_nll(y, mb_out):
            h = _rms_norm(y, sp["final_norm"], cfg.norm_eps)
            if tp is not None:
                return _tp_vocab_parallel_nll(
                    h[:, :-1], sp["lm_head"], mb_out[:, 1:], tp)
            logits = (h @ sp["lm_head"]).astype(jnp.float32)
            logprobs = jax.nn.log_softmax(logits[:, :-1], axis=-1)
            return -jnp.take_along_axis(
                logprobs, mb_out[:, 1:][..., None], axis=-1)[..., 0]

        # Streams padded to M+P-1 ticks: stage 0 consumes microbatch t;
        # the last stage emits microbatch t-(P-1), so its target stream is
        # pre-shifted by P-1.
        pad = jnp.zeros((P_size - 1,) + mbs.shape[1:], mbs.dtype)
        in_stream = jnp.concatenate([mbs, pad], axis=0)           # [T,...]
        out_stream = jnp.concatenate([pad, mbs], axis=0)          # [T,...]

        def tick(carry, xs):
            x_prev, loss_acc = carry
            t, mb_in, mb_out = xs
            # Activations hop one stage forward; stage 0's slot is then
            # replaced by the fresh microbatch's embedding.
            recv = jax.lax.ppermute(x_prev, "pp", perm)
            injected = embed(mb_in)
            x_in = jnp.where(stage == 0, injected, recv)
            y = run_layers(x_in)
            # Last stage: head + NLL for the microbatch leaving the pipe.
            nll = head_nll(y, mb_out)
            # Count only drain ticks (t >= P-1): earlier ticks see the
            # zero-initialized buffer, not a real microbatch.
            valid = jnp.logical_and(stage == P_size - 1, t >= P_size - 1)
            loss_acc = loss_acc + jnp.where(valid, nll.mean(), 0.0)
            return (y, loss_acc), None

        if remat:
            # Bound activation memory to the rotating buffer: the backward
            # pass replays each tick's forward instead of keeping all
            # M+P-1 tick activations live (GPipe memory → ~1F1B memory,
            # paid with one extra forward).
            tick = jax.checkpoint(tick)

        x0 = jnp.zeros((b // M, S, cfg.hidden_size),
                       sp["embed"].dtype)
        ticks = jnp.arange(M + P_size - 1)
        # The loss accumulator rides the scan carry as shape (1,), not a
        # scalar: under value_and_grad, shard_map's partial-eval saves the
        # carry output as a residual, and this jax release's scalar-residual
        # promotion misses forwarded scan outputs — a float32[] residual
        # then fails the {0: axes} out-spec rank check (_SpecError).
        (_, loss_sum), _ = jax.lax.scan(
            tick, (x0, jnp.zeros((1,), jnp.float32)),
            (ticks, in_stream, out_stream))
        # Valid losses accumulated on the last stage only, for ticks
        # t >= P-1 … M+P-2 → exactly M microbatches. Average over M, then
        # across the pipeline (sum picks up the last stage's value) and
        # data shards.
        loss = jax.lax.psum(loss_sum[0] / M, "pp")
        # (Already replicated across tp: every shard computed the same
        # post-psum NLL, so no tp collective is needed here.)
        if dp is not None:
            loss = jax.lax.pmean(loss, dp)
        return loss

    mapped = shard_map(
        pipeline_loss,
        mesh=mesh,
        in_specs=(param_specs, P(dp, None)),
        out_specs=P(),
        check_vma=False,
    )

    def train_step(sp, opt_state, tokens):
        loss, grads = jax.value_and_grad(mapped)(sp, tokens)
        updates, opt_state = opt.update(grads, opt_state, sp)
        sp = optax.apply_updates(sp, updates)
        return sp, opt_state, loss

    return jax.jit(train_step), stacked, opt_state, data_sharding


def make_pp_train_step(mesh: Mesh, cfg: LlamaConfig, params: Params, opt):
    """Prepare pipeline-sharded training over ``mesh``'s ``pp`` axis.

    Returns ``(step_fn, stacked_params, opt_state, data_sharding)``.
    ``num_layers`` must divide evenly by the pp axis size.
    """
    if "pp" not in mesh.axis_names:
        raise ValueError("pipeline training requires a 'pp' mesh axis")
    if cfg.num_experts > 0:
        raise ValueError("pipeline path supports dense layers (MoE uses ep)")
    pp_size = dict(zip(mesh.axis_names, mesh.devices.shape))["pp"]
    if cfg.num_layers % pp_size != 0:
        raise ValueError(
            f"num_layers ({cfg.num_layers}) must divide by pp size ({pp_size})"
        )
    dp = "dp" if "dp" in mesh.axis_names else None
    has_tp = "tp" in mesh.axis_names

    stacked = stack_layer_params(params)
    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        stacked_param_pspecs(has_tp, "pp", qk_norm=cfg.qk_norm),
        is_leaf=lambda x: isinstance(x, P),
    )
    stacked = jax.device_put(stacked, shardings)
    opt_state = opt.init(stacked)
    data_sharding = NamedSharding(mesh, P(dp, None))

    def step(p, s, tokens):
        return pp_train_step(p, s, cfg, opt, tokens)

    return jax.jit(step), stacked, opt_state, data_sharding
