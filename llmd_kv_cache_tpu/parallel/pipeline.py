"""Pipeline parallelism: layer-sharded training over a ``pp`` mesh axis.

The dense transformer's layers are stacked into leading-axis arrays and
scanned; sharding that leading axis over ``pp`` distributes the parameters
(and their optimizer state) across pipeline stages — the memory-scaling
half of pipeline parallelism, with XLA moving activations between stages
at the scan steps. The schedule is sequential (GPipe-style microbatch
interleaving / 1F1B is the round-2 follow-up); composes with dp/tp on the
other axes.

Dense layers only (MoE layers scale across ``ep`` instead).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.llama import LlamaConfig, Params, _mlp, _rms_norm


def stack_layer_params(params: Params) -> dict:
    """Convert the per-layer list tree into stacked [L, ...] arrays."""
    layers = params["layers"]
    stacked = {
        key: jnp.stack([layer[key] for layer in layers])
        for key in layers[0]
    }
    return {
        "embed": params["embed"],
        "layers_stacked": stacked,
        "final_norm": params["final_norm"],
        "lm_head": params["lm_head"],
    }


def unstack_layer_params(stacked_params: dict) -> Params:
    """Inverse of ``stack_layer_params`` (checkpoint interop)."""
    stacked = stacked_params["layers_stacked"]
    num_layers = next(iter(stacked.values())).shape[0]
    layers = [
        {key: stacked[key][i] for key in stacked} for i in range(num_layers)
    ]
    return {
        "embed": stacked_params["embed"],
        "layers": layers,
        "final_norm": stacked_params["final_norm"],
        "lm_head": stacked_params["lm_head"],
    }


def stacked_param_pspecs(has_tp: bool, pp_axis: Optional[str]) -> dict:
    """PartitionSpecs for the stacked tree: layer axis over ``pp``, the
    Megatron tp layout within each layer."""
    tp = "tp" if has_tp else None
    return {
        "embed": P(tp, None),
        "layers_stacked": {
            "attn_norm": P(pp_axis, None),
            "wq": P(pp_axis, None, tp),
            "wk": P(pp_axis, None, tp),
            "wv": P(pp_axis, None, tp),
            "wo": P(pp_axis, tp, None),
            "mlp_norm": P(pp_axis, None),
            "w_gate": P(pp_axis, None, tp),
            "w_up": P(pp_axis, None, tp),
            "w_down": P(pp_axis, tp, None),
        },
        "final_norm": P(),
        "lm_head": P(None, tp),
    }


def forward_train_pp(stacked_params: dict, cfg: LlamaConfig,
                     tokens: jax.Array) -> jax.Array:
    """Causal-LM forward scanning stacked (pipeline-sharded) layers.

    The per-layer body is ``train.attention_block`` + ``_mlp`` — shared
    with the python-loop formulation so the two paths cannot drift.
    """
    from .train import attention_block

    batch, seq = tokens.shape
    positions = jnp.arange(seq)[None, :].repeat(batch, axis=0)

    x = stacked_params["embed"][tokens]

    def layer_step(x, layer):
        x = x + attention_block(x, layer, cfg, positions)
        mlp_in = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        x = x + _mlp(mlp_in, layer, cfg)
        return x, None

    x, _ = jax.lax.scan(layer_step, x, stacked_params["layers_stacked"])
    x = _rms_norm(x, stacked_params["final_norm"], cfg.norm_eps)
    return (x @ stacked_params["lm_head"]).astype(jnp.float32)


def pp_loss_fn(stacked_params, cfg, tokens):
    logits = forward_train_pp(stacked_params, cfg, tokens)
    targets = tokens[:, 1:]
    logprobs = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


@partial(jax.jit, static_argnames=("cfg", "opt"))
def pp_train_step(stacked_params, opt_state, cfg: LlamaConfig,
                  opt: optax.GradientTransformation, tokens: jax.Array):
    loss, grads = jax.value_and_grad(pp_loss_fn)(stacked_params, cfg, tokens)
    updates, opt_state = opt.update(grads, opt_state, stacked_params)
    stacked_params = optax.apply_updates(stacked_params, updates)
    return stacked_params, opt_state, loss


def make_pp_train_step(mesh: Mesh, cfg: LlamaConfig, params: Params, opt):
    """Prepare pipeline-sharded training over ``mesh``'s ``pp`` axis.

    Returns ``(step_fn, stacked_params, opt_state, data_sharding)``.
    ``num_layers`` must divide evenly by the pp axis size.
    """
    if "pp" not in mesh.axis_names:
        raise ValueError("pipeline training requires a 'pp' mesh axis")
    if cfg.num_experts > 0:
        raise ValueError("pipeline path supports dense layers (MoE uses ep)")
    pp_size = dict(zip(mesh.axis_names, mesh.devices.shape))["pp"]
    if cfg.num_layers % pp_size != 0:
        raise ValueError(
            f"num_layers ({cfg.num_layers}) must divide by pp size ({pp_size})"
        )
    dp = "dp" if "dp" in mesh.axis_names else None
    has_tp = "tp" in mesh.axis_names

    stacked = stack_layer_params(params)
    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        stacked_param_pspecs(has_tp, "pp"),
        is_leaf=lambda x: isinstance(x, P),
    )
    stacked = jax.device_put(stacked, shardings)
    opt_state = opt.init(stacked)
    data_sharding = NamedSharding(mesh, P(dp, None))

    def step(p, s, tokens):
        return pp_train_step(p, s, cfg, opt, tokens)

    return jax.jit(step), stacked, opt_state, data_sharding
