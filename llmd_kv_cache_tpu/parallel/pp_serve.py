"""Pipeline-parallel SERVING over a ``pp`` mesh axis.

The last parallelism mode the serving engine lacked (VERDICT r4 weak
#7). Training pp exists in two schedules (``parallel.pipeline``); this
module adds the inference counterpart: layer blocks sharded across
stages, **paged KV caches sharded on their layer axis** (each stage owns
the cache slabs for its layers — the memory reason pp exists: a model +
cache too big for one chip), and a GPipe rotating-buffer schedule where
M microbatches of the serving batch stream through the stages with
activations hopping stage→stage via ``ppermute``.

Reference counterpart: the reference fingerprints pp topology into its
offload store layout (``file_mapper.py`` keys files by parallel rank)
but delegates the engines to vLLM; here the engine is in-tree, so pp
serving is implemented, not just fingerprinted.

TPU-first design notes:
- The tick loop is a PYTHON unroll, not ``lax.scan``: the carries would
  include each stage's cache slab, and XLA TPU copies large scan
  carries every iteration (measured ~300 GB/s r+w — the round-4
  burst-tail finding). Unrolled straight-line code lets XLA update the
  donated cache slabs in place. M + P - 1 ticks with L/P layers each
  keep the program ~(M+P-1)/M × one model forward.
- Collectives are explicit (``ppermute`` for the activation hop, one
  final ``psum`` to replicate the departing logits) because inside
  ``shard_map`` XLA does not derive collectives from shardings.
- Scope: dense uniform-attention models (incl. uniform SWA + sinks and
  Qwen-bias families), XLA attention backend, single-token decode.
  Composes with ``tp`` on the same mesh (Megatron column/row shards +
  kv-head-sharded cache slabs within each stage, explicit psums) and
  with ``dp`` outside; ``sp`` is not composed yet.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.llama import LlamaConfig, Params, _mlp, _rms_norm, _rope
from ..ops.kv_pages import scatter_kv_pages
from ..ops.paged_attention import paged_attention
from .pipeline import stack_layer_params
from .ring_attention import shard_map  # jax-version compat shim


def pp_size_of(mesh: Optional[Mesh]) -> int:
    if mesh is None:
        return 1
    return mesh.shape.get("pp", 1)


def _uniform_window(cfg: LlamaConfig):
    """The single per-layer window of a uniform config (None = full
    attention everywhere). Mixed layouts raise — that's the hybrid
    family, which pp v1 does not cover."""
    windows = {cfg.layer_window(li) for li in range(cfg.num_layers)}
    if len(windows) > 1:
        raise ValueError(
            "pp serving v1 needs a uniform attention layout (mixed "
            "full/SWA layers are the hybrid family)")
    return next(iter(windows))


def validate_pp_serve_config(cfg: LlamaConfig, mesh: Mesh,
                             microbatches: int, max_batch: int) -> None:
    pp = pp_size_of(mesh)
    if cfg.num_layers % pp != 0:
        raise ValueError(
            f"num_layers ({cfg.num_layers}) must divide by pp ({pp})")
    if cfg.num_experts > 0 or cfg.is_mla or cfg.is_hybrid:
        raise ValueError(
            "pp serving v1 covers dense non-hybrid attention models "
            "(MoE scales over ep; MLA/hybrid compose with tp/sp)")
    _uniform_window(cfg)
    if max_batch % microbatches != 0:
        raise ValueError(
            f"max_batch ({max_batch}) must divide by microbatches "
            f"({microbatches}) — every tick moves one microbatch")


# Megatron placement within each stage when a ``tp`` axis is present:
# column-parallel in-projections (their biases follow the columns),
# row-parallel out-projections (one psum each in _pp_layer).
_TP_COL = {"wq", "wk", "wv", "w_gate", "w_up", "bq", "bk", "bv"}
_TP_ROW = {"wo", "w_down"}


def pp_param_pspecs(stacked: dict, tp: bool = False) -> dict:
    """Stacked-tree specs DERIVED from the tree itself: every stacked
    layer leaf shards its leading (layer) axis over ``pp``, whatever the
    key — qk norms, Qwen2 QKV biases, future additions — so the spec
    tree can never drift from the parameter tree (review r5). With
    ``tp``, the known Megatron keys additionally shard within the stage.
    Embed and head replicate: stage 0 embeds, the last stage projects,
    which keeps the schedule collective-free at the ends for one matrix
    copy each."""
    def leaf_spec(path, a):
        key = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        rest = [None] * (a.ndim - 1)
        if tp and key in _TP_COL:
            rest[-1] = "tp"  # biases are 1-D: their only axis follows
        elif tp and key in _TP_ROW:
            rest[0] = "tp"
        return P("pp", *rest)

    return {
        "embed": P(),
        "layers_stacked": jax.tree_util.tree_map_with_path(
            leaf_spec, stacked["layers_stacked"]),
        "final_norm": P(),
        "lm_head": P(),
    }


def kv_pp_axes(tp: bool = False) -> P:
    """[layers, pages, kvh, ps, hd]: layer axis over pp, kv heads over
    tp when present (each tp shard owns whole kv heads, like
    parallel.serve.shard_kv_pool)."""
    return P("pp", None, "tp" if tp else None, None, None)


def shard_pp_state(mesh: Mesh, cfg: LlamaConfig, params: Params,
                   k_cache: jax.Array, v_cache: jax.Array):
    """(stacked_params, k, v) placed for pp serving: stacked layer trees
    with the layer axis over ``pp``; cache slabs likewise (+ the kv-head
    axis over ``tp`` when the mesh has one)."""
    tp = mesh.shape.get("tp", 1) > 1
    stacked = stack_layer_params(params)
    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        pp_param_pspecs(stacked, tp),
        is_leaf=lambda x: isinstance(x, P),
    )
    stacked = jax.device_put(stacked, shardings)
    kv_sharding = NamedSharding(mesh, kv_pp_axes(tp))
    return (stacked, jax.device_put(k_cache, kv_sharding),
            jax.device_put(v_cache, kv_sharding))


def _pp_layer(x, layer, cfg, k_layer, v_layer, table, positions,
              total_lens, valid, window, tp_axis=None):
    """One dense layer with paged attention over this stage's cache slab.

    Scatters the microbatch's K/V into the LOCAL layer cache (functional
    update — straight-line code, so XLA keeps it in place), then runs the
    XLA paged-attention reference over cached prefix + the new tokens.
    Mirrors the per-layer body of ``models.llama._forward_impl_grouped``
    for the dense path: qk-norm, GQA, QKV biases, uniform SWA windows,
    and StreamingLLM sinks.

    ``tp_axis``: Megatron within the stage — the projections are local
    column/row shards (head counts derive from the LOCAL weight shapes,
    the GQA group ratio is shard-invariant), attention runs on local
    heads over the kv-head-sharded cache slab, and the row-parallel
    wo/w_down partial sums are fixed by one ``psum`` each (the explicit
    form ``parallel.pipeline._tp_layer_step`` uses for training —
    inside shard_map XLA does not derive collectives).
    """
    batch, seq = x.shape[0], x.shape[1]
    attn_in = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = attn_in @ layer["wq"]
    k = attn_in @ layer["wk"]
    v = attn_in @ layer["wv"]
    if "bq" in layer:  # Qwen2-lineage QKV projection biases
        q = q + layer["bq"]
        k = k + layer["bk"]
        v = v + layer["bv"]
    q = q.reshape(batch, seq, -1, cfg.head_dim)
    k = k.reshape(batch, seq, -1, cfg.head_dim)
    v = v.reshape(batch, seq, -1, cfg.head_dim)
    if cfg.qk_norm:
        q = _rms_norm(q, layer["q_norm"], cfg.norm_eps)
        k = _rms_norm(k, layer["k_norm"], cfg.norm_eps)
    q = _rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
    k = _rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
    k_layer = scatter_kv_pages(k_layer, k, table, positions, valid)
    v_layer = scatter_kv_pages(v_layer, v, table, positions, valid)
    attn = paged_attention(q, k_layer, v_layer, table, positions,
                           total_lens, sliding_window=window,
                           attention_sinks=cfg.attention_sinks or None)
    attn_out = attn.reshape(batch, seq, -1) @ layer["wo"]
    if tp_axis is not None:
        attn_out = jax.lax.psum(attn_out, tp_axis)
    x = x + attn_out
    mlp_in = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    # _mlp's dense branch on the local column shards; the row-parallel
    # w_down product is a partial sum under tp, fixed by one psum.
    down = _mlp(mlp_in, layer, cfg)
    if tp_axis is not None:
        down = jax.lax.psum(down, tp_axis)
    return x + down, k_layer, v_layer


def make_pp_serve_forward(mesh: Mesh, cfg: LlamaConfig,
                          stacked_params: dict,
                          microbatches: Optional[int] = None):
    """Jitted pp forward: ``fn(sp, k, v, tokens, table, ctx, new) ->
    (last_logits [b, vocab], k, v)``.

    One call serves a prefill chunk (seq > 1) or a decode step (seq == 1)
    for the whole batch; the batch is split into ``microbatches`` (default
    = pp size) row groups that stream through the stages. Logits are each
    sequence's LAST valid position (``new - 1``), replicated on every
    stage by the final psum — the only logits serving ever needs.
    ``stacked_params`` supplies the tree structure the shard_map specs
    derive from (the call passes the same tree).
    """
    P_size = pp_size_of(mesh)
    M = microbatches or P_size
    local_layers = cfg.num_layers // P_size
    perm = [(i, i + 1) for i in range(P_size - 1)]
    window = _uniform_window(cfg)
    tp = mesh.shape.get("tp", 1) > 1
    tp_axis = "tp" if tp else None
    param_specs = pp_param_pspecs(stacked_params, tp)
    kv_axes = kv_pp_axes(tp)

    def staged(sp, k_all, v_all, tokens, table, ctx_lens, new_lens):
        # Everything except the cache slabs and layer stack is replicated.
        b, seq = tokens.shape
        mb = b // M
        stage = jax.lax.axis_index("pp")
        layers = sp["layers_stacked"]  # [local_layers, ...] on this stage

        positions_all = ctx_lens[:, None] + jnp.arange(seq)[None, :]
        valid_all = jnp.arange(seq)[None, :] < new_lens[:, None]
        total_all = ctx_lens + new_lens

        def mb_slice(a, m):
            return jax.lax.dynamic_slice_in_dim(a, m * mb, mb, axis=0)

        x_buf = jnp.zeros((mb, seq, cfg.hidden_size), sp["embed"].dtype)
        out = jnp.zeros((b, cfg.vocab_size), jnp.float32)
        k_all = k_all  # [local_layers, pages, kvh, ps, hd] local slab
        v_all = v_all

        for t in range(M + P_size - 1):
            inject = min(t, M - 1)      # microbatch entering stage 0
            depart = max(t - P_size + 1, 0)  # microbatch leaving the end
            recv = jax.lax.ppermute(x_buf, "pp", perm)
            injected = sp["embed"][mb_slice(tokens, inject)]
            x_in = jnp.where(stage == 0, injected, recv)
            # Every stage processes the microbatch resident in its slot
            # this tick: stage s holds microbatch t - s. Slices of the
            # control tensors are picked per stage.
            mine = jnp.clip(t - stage, 0, M - 1)
            tab = mb_slice(table, mine)
            pos = mb_slice(positions_all, mine)
            val = mb_slice(valid_all, mine)
            tot = mb_slice(total_all, mine)
            # Ticks where this stage holds no real microbatch (t < s or
            # t - s >= M) write via a garbage-masked valid.
            live = jnp.logical_and(t - stage >= 0, t - stage < M)
            val = jnp.logical_and(val, live)
            x = x_in
            for j in range(local_layers):
                layer = jax.tree.map(lambda a: a[j], layers)
                x, k_j, v_j = _pp_layer(
                    x, layer, cfg, k_all[j], v_all[j], tab, pos, tot, val,
                    window, tp_axis=tp_axis)
                k_all = k_all.at[j].set(k_j)
                v_all = v_all.at[j].set(v_j)
            x_buf = x
            # Departing microbatch: last-token logits on the last stage.
            h = _rms_norm(x, sp["final_norm"], cfg.norm_eps)
            last_idx = jnp.clip(mb_slice(new_lens, depart) - 1, 0, seq - 1)
            h_last = jnp.take_along_axis(
                h, last_idx[:, None, None].repeat(cfg.hidden_size, -1),
                axis=1)[:, 0]
            logits = (h_last @ sp["lm_head"]).astype(jnp.float32)
            emit = jnp.logical_and(stage == P_size - 1, t >= P_size - 1)
            out = jax.lax.dynamic_update_slice_in_dim(
                out, jnp.where(emit, logits, mb_slice(out, depart)),
                depart * mb, axis=0)

        # Replicate the assembled logits (only the last stage wrote real
        # values; other stages hold zeros at emitted rows).
        out = jax.lax.psum(
            jnp.where(stage == P_size - 1, out, jnp.zeros_like(out)), "pp")
        return out, k_all, v_all

    mapped = shard_map(
        staged,
        mesh=mesh,
        in_specs=(param_specs, kv_axes, kv_axes,
                  P(), P(), P(), P()),
        out_specs=(P(), kv_axes, kv_axes),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(1, 2))
    def fn(sp, k, v, tokens, table, ctx_lens, new_lens):
        return mapped(sp, k, v, tokens, table,
                      ctx_lens.astype(jnp.int32), new_lens.astype(jnp.int32))

    return fn
