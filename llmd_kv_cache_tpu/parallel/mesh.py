"""Device meshes and parameter shardings.

TPU-native parallelism: a named ``jax.sharding.Mesh`` with explicit axes —
``dp`` (data), ``tp`` (tensor, rides ICI), ``sp`` (sequence/context) — and
PartitionSpecs per parameter. XLA inserts the collectives (psum /
all-gather / reduce-scatter) from the sharding annotations; nothing here
issues explicit NCCL-style calls.

``mesh_fingerprint_fields`` feeds the offload FileMapper: the reference
fingerprints ``tp/pp/pcp/dcp`` world sizes (``file_mapper.py:63-74``) so
on-disk KV blocks are only shared between identically-sharded deployments;
ours fingerprints the mesh axis layout the same way.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.llama import Params


def make_mesh(
    axes: Optional[dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh, e.g. ``make_mesh({"dp": 2, "tp": 4})``.

    With no axes, the full device set becomes a 1-D ``dp`` mesh.
    """
    devices = list(devices if devices is not None else jax.devices())
    if not axes:
        axes = {"dp": len(devices)}
    sizes = list(axes.values())
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(
            f"mesh axes {axes} need {int(np.prod(sizes))} devices, have {len(devices)}"
        )
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(axes.keys()))


def param_pspecs(has_tp: bool = True, has_ep: bool = False,
                 moe_layer: bool = False, qk_norm: bool = False,
                 mla_layer: bool = False, qkv_bias: bool = False,
                 latent_norm: bool = False, q_lora: bool = False,
                 shared_expert: bool = False,
                 router_bias: bool = False,
                 fused: bool = False) -> dict:
    """PartitionSpecs for one Llama layer family.

    Column-parallel QKV/gate/up (output features over ``tp``),
    row-parallel wo/down (input features over ``tp``), vocab-sharded
    embed/lm_head — the standard Megatron-style layout that keeps matmuls
    large on the MXU and puts one all-reduce per block on ICI. MoE expert
    tensors additionally shard their leading expert dim over ``ep``.

    MLA layers shard on the *head* axis instead of kv-heads: ``wq`` stays
    column-parallel (its flat output dim is head-major, so a contiguous
    ``tp`` split assigns whole heads), the absorbed up-projections
    ``w_uk``/``w_uv`` shard their leading head dim, and the latent
    down-projections ``w_dkv``/``w_kr`` replicate — the latent is one
    shared head by construction (DeepSeek-V2 §2.1), so every shard
    recomputes the tiny rank-wide projection rather than paying a
    collective for it.
    """
    tp = "tp" if has_tp else None
    ep = "ep" if has_ep else None
    # Shared attention/norm layout; only the MLP family differs.
    layer = {
        "attn_norm": P(),
        "wq": P(None, tp),
        "wo": P(tp, None),
        "mlp_norm": P(),
    }
    if mla_layer:
        layer.update({
            "w_dkv": P(),
            "w_kr": P(),
            "w_uk": P(tp, None, None),
            "w_uv": P(tp, None, None),
        })
        if latent_norm:  # DeepSeek kv_a_layernorm: replicated like w_dkv
            layer["latent_norm"] = P()
        if q_lora:  # DeepSeek q-LoRA: compressed-q path, replicated
            layer.update({"w_dq": P(), "q_latent_norm": P()})
    elif fused:
        # Fused serving layout (llama.fuse_params with the per-rank
        # interleaved column order, fused_interleave = tp): one fused
        # leaf replaces the three projections; a uniform column split
        # hands each shard its local [q_i|k_i|v_i] block, so the fused
        # leaf shards column-parallel exactly like its parts did.
        del layer["wq"]
        layer["w_qkv"] = P(None, tp)
        if qkv_bias:
            layer["b_qkv"] = P(tp)
    else:
        layer.update({"wk": P(None, tp), "wv": P(None, tp)})
        if qkv_bias:  # column-parallel bias shards with its output dim
            layer.update({"bq": P(tp), "bk": P(tp), "bv": P(tp)})
    if qk_norm:
        layer.update({"q_norm": P(), "k_norm": P()})
    if moe_layer:
        layer.update({
            "router": P(),
            "w_gate": P(ep, None, tp),
            "w_up": P(ep, None, tp),
            "w_down": P(ep, tp, None),
        })
        if router_bias:  # DeepSeek e_score_correction: replicated vector
            layer["router_bias"] = P()
        if shared_expert:  # always-on shared expert: dense Megatron layout
            if fused:
                layer.update({
                    "w_gate_up_sh": P(None, tp),
                    "w_down_sh": P(tp, None),
                })
            else:
                layer.update({
                    "w_gate_sh": P(None, tp),
                    "w_up_sh": P(None, tp),
                    "w_down_sh": P(tp, None),
                })
    elif fused:
        layer.update({
            "w_gate_up": P(None, tp),
            "w_down": P(tp, None),
        })
    else:
        layer.update({
            "w_gate": P(None, tp),
            "w_up": P(None, tp),
            "w_down": P(tp, None),
        })
    return {
        "embed": P(tp, None),
        "layers": layer,  # broadcast over the list of layers
        "final_norm": P(),
        "lm_head": P(None, tp),
    }


def _layer_flags(layer: dict) -> dict:
    """Derive the pspec-family flags from one layer's parameter keys —
    per LAYER, because DeepSeek layouts mix dense and MoE layers in one
    model (first_k_dense_replace)."""
    return dict(
        moe_layer="router" in layer,
        qk_norm="q_norm" in layer,
        mla_layer="w_uk" in layer,
        qkv_bias="bq" in layer or "b_qkv" in layer,
        latent_norm="latent_norm" in layer,
        q_lora="w_dq" in layer,
        shared_expert="w_gate_sh" in layer or "w_gate_up_sh" in layer,
        router_bias="router_bias" in layer,
        fused="w_qkv" in layer,
    )


def param_shardings(mesh: Mesh, params: Params) -> dict:
    """NamedShardings matching the parameter tree structure (per-layer
    spec derivation — layer kinds may differ within one model)."""
    has_tp = "tp" in mesh.axis_names
    has_ep = "ep" in mesh.axis_names
    base = param_pspecs(has_tp, has_ep)
    specs = dict(base)
    specs["layers"] = [
        param_pspecs(has_tp, has_ep, **_layer_flags(layer))["layers"]
        for layer in params["layers"]
    ]
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(mesh: Mesh, params: Params) -> Params:
    """Place a parameter tree onto the mesh with TP shardings.

    The ``fused_interleave`` layout marker (llama.fuse_params) is a plain
    int, not a weight: it is lifted out before device_put (the sharding
    tree has no slot for it) and re-attached unchanged."""
    marker = None
    if "fused_interleave" in params:
        params = dict(params)
        marker = params.pop("fused_interleave")
    out = jax.device_put(params, param_shardings(mesh, params))
    if marker is not None:
        out = dict(out)
        out["fused_interleave"] = marker
    return out


def mesh_fingerprint_fields(mesh: Optional[Mesh]) -> dict[str, int]:
    """Mesh-axis world sizes for the offload cache fingerprint.

    Maps our axes onto the reference's fingerprint fields: ``tp`` → tensor
    parallel, ``dp`` → data parallel, ``sp`` → context parallel (covers the
    reference's pcp/dcp), ``pp`` → pipeline parallel.
    """
    if mesh is None:
        return {"tp_size": 1, "pp_size": 1, "dp_size": 1, "sp_size": 1}
    sizes = mesh.shape
    return {
        "tp_size": sizes.get("tp", 1),
        "pp_size": sizes.get("pp", 1),
        "dp_size": sizes.get("dp", 1),
        "sp_size": sizes.get("sp", 1),
    }
