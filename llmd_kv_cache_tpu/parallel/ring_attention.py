"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context sequence/context parallelism: Q, K, V are sharded along the
sequence dimension across the ``sp`` mesh axis; each device keeps its Q
shard resident and the K/V shards rotate around the ring via
``lax.ppermute`` (ICI neighbor exchanges), with flash-style online-softmax
accumulation so the full [S, S] score matrix never materializes. Exact
(not approximate) causal attention with O(S/n) memory per device and
communication fully overlappable with compute by XLA.

Implemented with ``shard_map`` — the collective schedule is explicit here
because the rotation pattern (not a sharding annotation) IS the algorithm;
everything around it stays in the annotate-and-let-XLA-partition style.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.shard_map_compat import shard_map  # re-export (pipeline.py uses it)

_NEG_INF = -1e30


def _flash_block(q, k, v, mask, m_prev, l_prev, acc_prev, scale):
    """Fold one K/V block into the online-softmax state.

    q: [B, Sq, H, D]; k, v: [B, Sk, H, D]; mask: [Sq, Sk] bool.
    State: m, l [B, H, Sq, 1]; acc [B, H, Sq, D].
    """
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32)
    )
    scores = jnp.where(mask[None, None], scores, _NEG_INF)

    m_cur = jnp.max(scores, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(scores - m_new)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc_prev * alpha + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32)
    )
    return m_new, l_new, acc_new


def _ring_attention_sharded(q, k, v, axis_name):
    """Per-device body under shard_map. q/k/v: [B, S_local, H, D] shards."""
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    batch, s_local, heads, dim = q.shape
    scale = dim ** -0.5

    q_pos = my_idx * s_local + jnp.arange(s_local)  # global query positions

    m0 = jnp.full((batch, heads, s_local, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((batch, heads, s_local, 1), jnp.float32)
    acc0 = jnp.zeros((batch, heads, s_local, dim), jnp.float32)

    def step(i, carry):
        m, l, acc, k_blk, v_blk = carry
        # Block i holds the K/V shard originally on device (my_idx - i) mod n.
        src_idx = (my_idx - i) % n
        k_pos = src_idx * s_local + jnp.arange(s_local)
        mask = q_pos[:, None] >= k_pos[None, :]  # causal on global positions

        m, l, acc = _flash_block(q, k_blk, v_blk, mask, m, l, acc, scale)

        # Rotate K/V to the next device (receive from the previous ring
        # neighbor). The final rotation is harmless and keeps the loop
        # uniform; XLA overlaps the permute with the next block's compute.
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return m, l, acc, k_blk, v_blk

    m, l, acc, _k, _v = jax.lax.fori_loop(0, n, step, (m0, l0, acc0, k, v))

    out = acc / jnp.maximum(l, 1e-30)  # [B, H, Sq, D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, Sq, H, D]


def make_ring_attention(mesh: Mesh, axis_name: str = "sp",
                        batch_axis: str | None = None,
                        head_axis: str | None = None):
    """Build a jitted ring-attention fn for ``mesh``.

    Returns ``fn(q, k, v) -> out`` where all tensors are [B, S, H, D] with
    S sharded over ``axis_name``. ``batch_axis``/``head_axis`` name the
    mesh axes sharding B and H so ring attention composes with dp/tp
    (those axes stay data-local; only K/V shards rotate over ``axis_name``).
    S must divide evenly by the axis size.
    """
    spec = P(batch_axis, axis_name, head_axis, None)
    # check_vma off: the fori_loop carry mixes axis-varying K/V blocks with
    # locally-created accumulators, which the varying-axis checker can't
    # unify even though the program is correct.
    sharded = shard_map(
        partial(_ring_attention_sharded, axis_name=axis_name),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return jax.jit(sharded)


def ring_attention_reference(q, k, v):
    """Dense causal reference for testing: same math, no sharding."""
    b, s, h, d = q.shape
    scale = d ** -0.5
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32)
    )
    mask = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(mask[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
