"""Tensor-parallel serving: mesh shardings for MiniEngine state.

The reference only *fingerprints* TP topology (``file_mapper.py:63-74``
keys the offload store by ``tp_size`` and per-rank ``_r<rank>`` folders);
the engines themselves are vLLM's. Here the serving engine is in-tree, so
TP is first-class: parameters take the Megatron layout
(``mesh.param_pspecs``), both paged KV pools shard their kv-heads axis
over ``tp``, and the unchanged jitted forwards run SPMD — XLA derives the
per-block all-reduces from the shardings (no explicit collectives).

Requirements: ``num_kv_heads % tp == 0`` for standard/GQA attention (each
shard owns whole kv heads, so GQA groups never straddle shards) and
``num_heads % num_kv_heads == 0`` (already a model invariant). MLA models
instead require ``num_heads % tp == 0``: they shard the *head* axis
(wq/w_uk/w_uv/wo) and replicate the single shared latent cache head, so
each shard runs absorbed multi-query attention locally. Page tables and
token blocks stay replicated host-side — paging is control plane,
identical on every shard, which is what makes the per-shard KV pools line
up with the reference's per-rank offload folders.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.llama import LlamaConfig, Params
from .mesh import shard_params

KV_CACHE_AXES = P(None, None, "tp", None, None)  # [layers, pages, kvh, ps, hd]


def mesh_tp_size(mesh: Optional[Mesh]) -> int:
    if mesh is None:
        return 1
    return mesh.shape.get("tp", 1)


def validate_tp_config(cfg: LlamaConfig, mesh: Mesh) -> None:
    tp = mesh_tp_size(mesh)
    if cfg.is_mla:
        # MLA shards the *head* axis (wq/w_uk/w_uv/wo); the single shared
        # latent head replicates, so kv-head divisibility does not apply.
        if cfg.num_heads % tp != 0:
            raise ValueError(
                f"num_heads ({cfg.num_heads}) must divide by the tp axis "
                f"({tp}) so every shard owns whole query heads (MLA "
                f"shards the absorbed up-projections per head)")
    elif cfg.num_kv_heads % tp != 0:
        raise ValueError(
            f"num_kv_heads ({cfg.num_kv_heads}) must divide by the tp axis "
            f"({tp}) so every shard owns whole kv heads")
    ep = mesh.shape.get("ep", 1)
    if cfg.num_experts > 0 and cfg.num_experts % ep != 0:
        raise ValueError(
            f"num_experts ({cfg.num_experts}) must divide by the ep "
            f"axis ({ep})")
    # Width divisibility: the Megatron column/row splits place exact
    # uniform shards (jax.device_put refuses uneven NamedShardings with
    # a cryptic late error), so surface the constraint here. The fused
    # interleaved layout needs the same divisibility — no extra
    # constraint beyond the unfused one.
    if tp > 1:
        widths = {"intermediate_size": cfg.intermediate_size}
        if cfg.moe_intermediate_size:
            widths["moe_intermediate_size"] = cfg.moe_intermediate_size
        if not cfg.is_mla:
            widths["num_heads*head_dim"] = cfg.num_heads * cfg.head_dim
            widths["num_kv_heads*head_dim"] = (
                cfg.num_kv_heads * cfg.head_dim)
        for name, width in widths.items():
            if width % tp:
                raise ValueError(
                    f"{name} ({width}) must divide by the tp axis "
                    f"({tp}): Megatron shards are uniform")


def shard_engine_params(mesh: Mesh, params: Params) -> Params:
    """Megatron-place the parameter tree (same layout as training)."""
    return shard_params(mesh, params)


def shard_kv_pool(mesh: Mesh, k_cache: jax.Array, v_cache: jax.Array):
    """Place one paged KV pool with its kv-heads axis over ``tp``.

    On a mesh without a ``tp`` axis (e.g. a dp-only fleet mesh) the pool
    is placed replicated — a PartitionSpec naming an absent axis is
    rejected by NamedSharding. An MLA latent pool (single shared cache
    head, ``kv_cache_heads == 1``) also places replicated: the latent is
    shared across heads by construction, and replicating it is what lets
    every shard run absorbed multi-query attention with no collective in
    the attention core."""
    shardable = "tp" in mesh.axis_names and k_cache.shape[2] > 1
    axes = KV_CACHE_AXES if shardable else P()
    sharding = NamedSharding(mesh, axes)
    return jax.device_put(k_cache, sharding), jax.device_put(v_cache, sharding)
