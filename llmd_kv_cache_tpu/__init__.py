"""llmd_kv_cache_tpu — a TPU-native KV-cache management framework.

A from-scratch rebuild of the capabilities of llm-d/llm-d-kv-cache for TPU
fleets (vLLM-TPU / JAX engines), with three pillars:

1. **KV-Cache Indexer** (`core/`, `index/`, `scoring/`) — a library keeping a
   near-real-time global view of which KV-cache blocks live on which model
   server, on which device tier (``tpu-hbm`` / ``cpu`` / ``shared-storage``),
   and scoring candidate pods for a prompt by longest cached prefix.
2. **KV offload data plane** (`offload/`, `ops/`) — moves paged KV blocks
   between TPU HBM and shared storage through JAX/XLA host offload (device →
   pinned-host transfers) and a native C++ I/O thread pool, replacing the
   reference's CUDA D2H/H2D path (`kv_connectors/llmd_fs_backend/csrc/`).
3. **Event plane & services** (`events/`, `services/`, `evictor/`) — ZMQ
   KV-event ingestion with per-pod ordering, a gRPC-over-UDS tokenizer
   sidecar, and a storage-lifecycle evictor.

The `models/`, `ops/` and `parallel/` packages additionally ship a compact
TPU-native paged-KV serving engine (JAX/Flax/Pallas) used as the in-tree
stand-in for vLLM-TPU in end-to-end tests and benchmarks.

Reference layer map: /root/reference — see SURVEY.md §1-2 for the component
inventory this package mirrors.
"""

__version__ = "0.1.0"
