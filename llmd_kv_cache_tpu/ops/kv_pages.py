"""Paged KV-cache page scatter/gather.

The paged cache is the TPU-native analogue of vLLM's block tables: one
physical pool of pages per layer, shape ``[num_pages, kv_heads, page_size,
head_dim]``, addressed through per-sequence page tables. Everything here is
shape-static and jit-safe: padded positions are routed to a reserved
garbage page (page 0) instead of branching.

Layout note (TPU-deliberate): ``page_size`` and ``head_dim`` are the two
minor dimensions, so a page of one kv head is exactly one Mosaic-tileable
``[page_size, head_dim]`` block — the Pallas kernels DMA ``cache[page, h]``
HBM→VMEM without slicing inside a tiled dimension (slicing one head out of
a ``[.., page_size, kv_heads, ..]`` layout violates the (8/16,128) tiling
and fails to lower). Verified on v5e.

These ops are also the heart of the offload data plane: ``gather_pages_flat``
assembles the contiguous slab that gets DMA'd to pinned host memory (the
role ``tensor_copier.cu`` plays in the reference — see SURVEY.md §2.2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Physical page 0 is reserved as the write target for padded/invalid
# positions so scatters need no data-dependent control flow.
GARBAGE_PAGE = 0


def scatter_kv_pages(
    cache: jax.Array,  # [num_pages, kv_heads, page_size, head_dim]
    new_kv: jax.Array,  # [batch, seq, kv_heads, head_dim]
    page_table: jax.Array,  # [batch, pages_per_seq] int32 (physical page ids)
    positions: jax.Array,  # [batch, seq] int32 logical positions
    valid: jax.Array,  # [batch, seq] bool
) -> jax.Array:
    """Write new K or V vectors into their pages; returns the updated cache.

    Invalid slots scatter into the garbage page. Donate ``cache`` under jit
    for an in-place update.
    """
    num_pages, kv_heads, page_size, head_dim = cache.shape
    batch, seq = positions.shape
    # Clamp: padded positions can point past the page table (their writes
    # are redirected to the garbage page below anyway).
    logical_page = jnp.minimum(positions // page_size, page_table.shape[1] - 1)
    slot = positions % page_size
    phys_page = jnp.take_along_axis(page_table, logical_page, axis=1)
    phys_page = jnp.where(valid, phys_page, GARBAGE_PAGE)
    slot = jnp.where(valid, slot, 0)

    flat_page = phys_page.reshape(batch * seq)
    flat_slot = slot.reshape(batch * seq)
    # [batch*seq, kv_heads, head_dim] values scattered on dims (0, 2).
    vals = new_kv.astype(cache.dtype).reshape(batch * seq, kv_heads, head_dim)
    return cache.at[flat_page, :, flat_slot, :].set(
        vals, mode="drop", unique_indices=False
    )


def scatter_kv_pages_ragged(
    cache: jax.Array,  # [num_pages, kv_heads, page_size, head_dim]
    new_kv: jax.Array,  # [total_q, kv_heads, head_dim] flat mixed batch
    page_table: jax.Array,  # [rows, pages_per_seq] int32
    row_of: jax.Array,  # [total_q] int32 owning row per flat token
    positions: jax.Array,  # [total_q] int32 logical positions
    valid: jax.Array,  # [total_q] bool
) -> jax.Array:
    """`scatter_kv_pages` over a ragged flat token axis.

    The mixed prefill+decode batch is one flat axis where each token knows
    its owning row (``row_of``) and logical position; the page lookup is
    a 2-D gather on ``(row, logical_page)`` instead of a per-row
    take_along_axis. Padded slots route to the garbage page exactly like
    the padded scatter.
    """
    page_size = cache.shape[2]
    logical_page = jnp.minimum(positions // page_size, page_table.shape[1] - 1)
    slot = positions % page_size
    row = jnp.clip(row_of, 0, page_table.shape[0] - 1)
    phys_page = page_table[row, logical_page]
    phys_page = jnp.where(valid, phys_page, GARBAGE_PAGE)
    slot = jnp.where(valid, slot, 0)
    vals = new_kv.astype(cache.dtype)
    return cache.at[phys_page, :, slot, :].set(
        vals, mode="drop", unique_indices=False
    )


def gather_kv_pages(
    cache: jax.Array,  # [num_pages, kv_heads, page_size, head_dim]
    page_table: jax.Array,  # [batch, pages_per_seq] int32
) -> jax.Array:
    """Gather each sequence's pages into logical order.

    Returns ``[batch, pages_per_seq * page_size, kv_heads, head_dim]``.
    """
    batch, pages_per_seq = page_table.shape
    _, kv_heads, page_size, head_dim = cache.shape
    gathered = cache[page_table]  # [batch, pages_per_seq, kv, page_size, hd]
    return gathered.transpose(0, 1, 3, 2, 4).reshape(
        batch, pages_per_seq * page_size, kv_heads, head_dim
    )


def gather_pages_flat(
    cache: jax.Array,  # [num_pages, kv_heads, page_size, head_dim]
    page_ids: jax.Array,  # [n] int32 physical page ids
) -> jax.Array:
    """Gather arbitrary physical pages into one contiguous block.

    The offload store path: selected pages → a contiguous
    ``[n, kv_heads, page_size, head_dim]`` slab ready for a device→host
    transfer.
    """
    return cache[page_ids]
