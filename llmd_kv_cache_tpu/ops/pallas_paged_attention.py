"""Pallas TPU kernels: flash attention over a paged KV cache.

Instead of materializing each sequence's gathered KV **and the fp32
attention probs** in HBM (which ``ops.paged_attention`` does — the
dominant excess HBM traffic of the XLA prefill path, see
benchmarking/r4-mfu/README.md), each (batch, kv_head[, q_tile]) program
streams the sequence's pages HBM→VMEM with double-buffered async DMA and
folds them into an online softmax — the ragged-paged-attention recipe.

Pages stream in **superblocks** of ``pages_per_block`` pages (default
targets 128 keys): each online-softmax round is then a full-width MXU
matmul and a 64 KB-class DMA batch, instead of one page_size-wide sliver
per round. Matmul operands stay in the cache dtype (bf16×bf16, fp32
accumulate — the MXU fast path) with the softmax scale applied to the
fp32 scores, matching the XLA reference's numerics.

Grid: ``(batch, kv_heads)`` for decode, ``(batch, kv_heads, q_blocks)``
for prefill. Scalar-prefetched page table + context lengths drive the DMA
indices (``PrefetchScalarGridSpec``). GQA: each program serves its kv
head's whole query group; absorbed MLA is the kv_heads=1 multi-query
case. SWA skips out-of-window pages; StreamingLLM sinks stream the first
pages too via a loop-counter→page-index remap.

The jnp reference path remains the fallback (CPU tests run these kernels
in interpreter mode against it).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def head_dim_supported(head_dim: int) -> bool:
    """Whether these kernels can compile on real TPU for this head size.

    Mosaic requires per-(page, head) DMA slices to be 128-aligned along
    the lane (head_dim) axis; sub-128 head dims fail to compile (measured
    v5e: "Slice shape along dimension 3 must be aligned to tiling (128)").
    Interpreter mode has no such restriction — this predicate gates the
    compiled path only (the engine's backend selection and the kernels'
    own guard both use it, so the rule cannot drift between them)."""
    return head_dim % 128 == 0


def _check_head_dim_alignment(head_dim: int, interpret: bool) -> None:
    if not interpret and not head_dim_supported(head_dim) and (
            jax.devices()[0].platform == "tpu"):
        raise ValueError(
            f"Pallas paged attention needs head_dim % 128 == 0 on TPU "
            f"(got {head_dim}); use the XLA paged-attention fallback "
            f"(ops.paged_attention) for this model")


def _superblock_streamer(page_table_ref, b, h, k_hbm, v_hbm, k_scratch,
                         v_scratch, sem, *, kpb, num_iters, first_window,
                         sink_pages, sinks, shared_kv=False,
                         layer_idx=None, row=None):
    """Shared page remap + superblock DMA for the decode/prefill kernels.

    ``page_for`` (internal) maps a loop counter to a page-table index —
    sink pages ([0, sink_pages)) first, then window pages
    ([first_window, …)) — with DMA-safe clamping for sub-pages past
    ``num_iters`` (their garbage loads are masked out by position).
    Returns ``(positions, sb_dma)``: the flat-lane key-position builder
    for the mask and the double-buffered DMA batch. One definition for
    both kernels so the clamp/remap subtleties cannot drift between
    them.

    ``shared_kv`` (absorbed MLA: values ARE the latent keys) streams each
    page ONCE into the K scratch and skips the V stream entirely —
    halving the attention's HBM traffic, which is the point of caching
    only the latent.

    ``row``: multi-row decode programs (``batch_rows > 1``) stage each
    batch row in its own scratch slice ``[slot, row, t]`` / semaphore
    plane; ``None`` keeps the single-row ``[slot, t]`` layout."""
    pp_seq = page_table_ref.shape[1]

    def dst(buf, slot, t):
        return buf.at[slot, t] if row is None else buf.at[slot, row, t]

    def dsem(slot, t, s):
        return (sem.at[slot, t, s] if row is None
                else sem.at[slot, row, t, s])

    def page_for(j):
        j = jnp.minimum(j, jnp.maximum(num_iters - 1, 0))  # DMA-safe clamp
        if not sinks:
            idx = first_window + j
        else:
            idx = jnp.where(j < sink_pages, j,
                            first_window + (j - sink_pages))
        return jnp.minimum(idx, pp_seq - 1)

    def page_src(hbm, page):
        # layer_idx: the operand is the engine's full [layers, pages, …]
        # stack and the kernel indexes the layer itself — slicing the
        # stack OUTSIDE a pallas_call materializes a full per-layer copy
        # at the custom-call boundary (XLA cannot fuse a producer slice
        # into a custom call; measured ~0.9 ms/layer/step in the decode
        # burst). h=None: merged-heads mode — one whole-page copy
        # carries every kv head, cutting the DMA count by kv_heads×.
        src = hbm if layer_idx is None else hbm.at[layer_idx]
        return src.at[page] if h is None else src.at[page, h]

    def sb_dma(slot, sb):
        copies = []
        for t in range(kpb):
            page = page_table_ref[b, page_for(sb * kpb + t)]
            copies.append(pltpu.make_async_copy(
                page_src(k_hbm, page), dst(k_scratch, slot, t),
                dsem(slot, t, 0)
            ))
            if not shared_kv:
                copies.append(pltpu.make_async_copy(
                    page_src(v_hbm, page), dst(v_scratch, slot, t),
                    dsem(slot, t, 1)
                ))
        return copies

    def positions(sb, park, page_size):
        """Key positions for superblock ``sb`` as [1, kpb*page_size] i32.

        Built directly in the flat lane layout — Mosaic's
        infer-vector-layout rejects the (kpb, page_size) →
        (1, kpb*page_size) shape cast (sublane→lane collapse) — by
        deriving sub-page index and in-page offset from one lane iota.
        Sub-pages past ``num_iters`` park at ``park`` (a position every
        mask term rejects: ctx_len for decode, total_len for prefill).
        """
        j = jax.lax.broadcasted_iota(jnp.int32, (1, kpb * page_size), 1)
        jp = j // page_size
        sub = sb * kpb + jp
        pos = page_for(sub) * page_size + (j - jp * page_size)
        return jnp.where(sub < num_iters, pos, park)

    return positions, sb_dma


def _decode_stream_bounds(ctx_len, q_end, page_size, sliding_window, sinks):
    """(first_window, sink_pages, num_iters) for a decode stream over
    keys [0, ctx_len). One definition for the per-head and merged decode
    kernels so the window/sink page arithmetic cannot drift between
    them (same rationale as ``_superblock_streamer``). SWA skips pages
    wholly before q_end - window (``q_end`` is the exclusive query
    position bound — ctx_len without a burst tail, ctx_len + tail_len
    with one); sinks keep the first ceil(S/page_size) pages streamed via
    the loop-counter remap."""
    num_pages = (ctx_len + page_size - 1) // page_size
    if sliding_window is not None:
        first_window = jnp.minimum(
            jnp.maximum(q_end - sliding_window, 0) // page_size, num_pages)
    else:
        first_window = jnp.int32(0)
    if sinks:
        sink_pages = jnp.minimum(
            (sinks + page_size - 1) // page_size, num_pages)
        first_window = jnp.maximum(first_window, sink_pages)
    else:
        sink_pages = jnp.int32(0)
    num_iters = sink_pages + num_pages - first_window
    return first_window, sink_pages, num_iters


def _decode_mask(positions, ctx_len, q_end, sliding_window, sinks):
    """Attendability of decode key ``positions``: in-bounds (< ctx_len),
    and inside the sliding window of the query at position ``q_end - 1``
    unless a sink position. Shared between the per-head and merged
    decode kernels."""
    in_bounds = positions < ctx_len
    if sliding_window is not None:
        in_window = positions >= q_end - sliding_window
        if sinks:
            in_window = in_window | (positions < sinks)
        in_bounds = in_bounds & in_window
    return in_bounds


def _tail_fold(q_h, k_t, v_t, tail_len, ctx_len, m, l, acc, *,
               scale, sliding_window, sinks):
    """Fold the dense burst-local KV tail (one extra online-softmax
    round) into one head's state. ``k_t``/``v_t`` are that head's tail
    keys/values [T, head_dim]. Tail slot ``j`` holds the key at logical
    position ctx_len + j, attendable while ``j < tail_len``; the query
    sits at ctx_len + tail_len - 1, so the window condition is
    ``tail_len - 1 - j < W`` — except sink positions (absolute position
    ctx_len + j < S), which stay attendable past the window like any
    other sink key (reachable only when ctx_len < S and the burst
    outruns the window, but the XLA reference keeps them and the mask
    must not drift). Shared by the merged and per-head decode kernels.

    The fold computes in explicit fp32: Mosaic miscompiles
    mixed-precision dots with tiny contraction/result dims (T ≤ burst —
    loud 'vector.broadcast' verifier failure at T=1, silently wrong
    values at T=8 with 384-wide MLA operands on a real v5e). bf16→fp32
    upcast is exact, so the scores match the bf16-operand/fp32-accum
    MXU path up to summation order, and the tail is tiny so fp32 VPU
    compute costs nothing."""
    t = k_t.shape[0]
    scores = jax.lax.dot_general(
        q_h.astype(jnp.float32), k_t.astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [group, T]
    jt = jax.lax.broadcasted_iota(jnp.int32, (1, t), 1)
    ok = jt < tail_len
    if sliding_window is not None:
        in_window = tail_len - 1 - jt < sliding_window
        if sinks:
            in_window = in_window | (ctx_len + jt < sinks)
        ok = ok & in_window
    scores = jnp.where(ok, scores, _NEG_INF)

    m_cur = jnp.max(scores, axis=1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    p = jnp.exp(scores - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_new = acc * alpha + jax.lax.dot_general(
        p, v_t.astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_new


def _decode_kernel(
    # scalar prefetch
    page_table_ref,  # [batch, pages_per_seq] int32 (SMEM)
    ctx_lens_ref,  # [batch] int32 (SMEM)
    tail_lens_ref,  # [batch] int32 (SMEM; zeros when has_tail=False)
    # inputs
    q_ref,  # [1, 1, group, head_dim] VMEM block for (b, h)
    k_hbm,  # [num_pages, kv_heads, page_size, head_dim] (ANY/HBM)
    v_hbm,  # same
    tail_k_ref,  # [1, 1, T, head_dim] VMEM block for (b, h); dummy if no tail
    tail_v_ref,  # same (placeholder when shared_kv)
    # output
    o_ref,  # [1, 1, group, head_dim] VMEM block
    # scratch
    k_scratch,  # [2, pages_per_block, page_size, head_dim] VMEM
    v_scratch,  # same
    sem,  # DMA semaphores [2, pages_per_block, 2]
    *,
    page_size: int,
    scale: float,
    sliding_window: int | None,
    sinks: int,
    pages_per_block: int,
    shared_kv: bool,
    shared_copy: bool,
    has_tail: bool,
    layer_idx: int | None,
):
    b = pl.program_id(0)
    h = pl.program_id(1)
    group, head_dim = q_ref.shape[2], q_ref.shape[3]
    kpb = pages_per_block

    ctx_len = ctx_lens_ref[b]
    tail_len = tail_lens_ref[b] if has_tail else jnp.int32(0)
    q_end = ctx_len + tail_len
    # SWA: pages entirely outside the window are skipped, so long contexts
    # stream only ~window/page_size pages. Attention sinks (StreamingLLM,
    # reference events.go:40 sink_full_attention) additionally stream the
    # first ceil(S/page_size) pages: the loop counter j is remapped to a
    # page index — sink pages [0, sink_pages) first, then window pages
    # [first_window, num_pages) — so the double-buffered DMA pipeline is
    # unchanged and the skipped middle costs nothing.
    first_window, sink_pages, num_iters = _decode_stream_bounds(
        ctx_len, q_end, page_size, sliding_window, sinks)
    # Pages stream in superblocks of ``kpb``: each round waits on one
    # batch of kpb in-flight DMAs (4 KB single-page transfers underuse
    # HBM bandwidth; a 128-key superblock moves 64 KB per K/V round) and
    # feeds the MXU a [head_dim, kpb·page_size] operand instead of a
    # page_size-wide sliver. A superblock may straddle the sink→window
    # jump; per-sub-page positions keep the mask exact.
    num_sb = (num_iters + kpb - 1) // kpb

    sb_positions, sb_dma = _superblock_streamer(
        page_table_ref, b, h, k_hbm, v_hbm, k_scratch, v_scratch, sem,
        kpb=kpb, num_iters=num_iters, first_window=first_window,
        sink_pages=sink_pages, sinks=sinks, shared_kv=shared_kv,
        layer_idx=layer_idx)

    @pl.when(num_sb > 0)
    def _():
        for c in sb_dma(0, 0):
            c.start()

    # Cache-dtype q, scale applied to the fp32 scores after the matmul:
    # bf16×bf16 + fp32 accumulate is the MXU fast path and matches the
    # XLA reference's numerics.
    q = q_ref[0, 0]  # [group, head_dim]

    def body(sb, carry):
        m_prev, l_prev, acc_prev = carry
        slot = sb % 2
        next_slot = (sb + 1) % 2

        @pl.when(sb + 1 < num_sb)
        def _():
            for c in sb_dma(next_slot, sb + 1):
                c.start()

        for c in sb_dma(slot, sb):
            c.wait()

        k = k_scratch[slot].reshape(kpb * page_size, head_dim)
        if shared_kv and shared_copy:
            # Absorbed MLA measured 2x SLOWER with v aliased to k at
            # b8/ctx4k (benchmarking/r5-tpu, --mla probe): one buffer
            # feeding both matmuls — head_dim-contraction for scores,
            # key-contraction for the output — forces Mosaic into
            # per-round relayouts. A local VMEM->VMEM copy gives each
            # matmul its own buffer while HBM still sees ONE latent
            # read (the point of caching only the latent).
            cp = pltpu.make_async_copy(
                k_scratch.at[slot], v_scratch.at[slot], sem.at[slot, 0, 1])
            cp.start()
            cp.wait()
            v = v_scratch[slot].reshape(kpb * page_size, head_dim)
        else:
            v = k if shared_kv else v_scratch[slot].reshape(
                kpb * page_size, head_dim)

        scores = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [group, kpb*page_size]

        # mask slots beyond the context length on the last page (and, for
        # SWA, positions that fell out of the window — unless they are
        # sink positions, which stay attendable forever); sub-pages past
        # num_iters park at ctx_len so every mask term rejects them.
        positions = sb_positions(sb, ctx_len, page_size)
        in_bounds = _decode_mask(positions, ctx_len, q_end, sliding_window,
                                 sinks)
        scores = jnp.where(in_bounds, scores, _NEG_INF)

        m_cur = jnp.max(scores, axis=1, keepdims=True)  # [group, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(scores - m_new)  # [group, kpb*page_size]
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc_prev * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((group, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((group, 1), jnp.float32)
    acc0 = jnp.zeros((group, head_dim), jnp.float32)
    m_fin, l_fin, acc = jax.lax.fori_loop(0, num_sb, body, (m0, l0, acc0))
    if has_tail:
        k_t = tail_k_ref[0, 0]  # [T, head_dim]; head picked by the block
        v_t = k_t if shared_kv else tail_v_ref[0, 0]
        m_fin, l_fin, acc = _tail_fold(
            q, k_t, v_t, tail_len, ctx_len, m_fin, l_fin, acc,
            scale=scale, sliding_window=sliding_window, sinks=sinks)

    out = acc / jnp.maximum(l_fin, 1e-30)
    o_ref[0, 0] = out.astype(o_ref.dtype)


def _decode_kernel_merged(
    # scalar prefetch
    page_table_ref,  # [batch, pages_per_seq] int32 (SMEM)
    ctx_lens_ref,  # [batch] int32 (SMEM)
    tail_lens_ref,  # [batch] int32 (SMEM; zeros when has_tail=False)
    # inputs
    q_ref,  # [1, kv_heads, group, head_dim] VMEM block for (b,)
    k_hbm,  # [num_pages, kv_heads, page_size, head_dim] (ANY/HBM)
    v_hbm,  # same
    tail_k_ref,  # [1, T, kv_heads, head_dim] VMEM block; dummy if no tail
    tail_v_ref,  # same (placeholder when shared_kv)
    # output
    o_ref,  # [1, kv_heads, group, head_dim] VMEM block
    # scratch
    k_scratch,  # [2, pages_per_block, kv_heads, page_size, head_dim] VMEM
    v_scratch,  # same
    sem,  # DMA semaphores [2, pages_per_block, 2]
    *,
    page_size: int,
    scale: float,
    sliding_window: int | None,
    sinks: int,
    pages_per_block: int,
    shared_kv: bool,
    shared_copy: bool,
    has_tail: bool,
    layer_idx: int | None,
    quant: bool = False,
):
    """Decode with every kv head — and up to ``batch_rows`` batch items —
    in ONE program.

    ``quant``: the cache operands/scratch hold 1-byte (fp8 e4m3) pages
    in the flat whole-page layout ``[.., kv_heads*page_size, head_dim]``
    (see the wrapper's quant arm); each round upcasts the staged
    superblock to the query dtype once and the head loop slices the
    upcast value — HBM moved half the bytes, the MXU still sees bf16.

    The per-head grid (``_decode_kernel``) pays pipeline fill/drain and
    per-page 4 KB DMAs once per (batch, head) program — measured on a
    real v5e at batch 8 / ctx 4k it sustains only ~105 GB/s of the
    chip's 819 (benchmarking/r4-mfu, "decode" table). Merging heads
    makes each sub-page copy one whole-page transfer carrying all kv
    heads (DMA count ÷ kv_heads), computes the position mask once per
    round instead of per head, and amortizes the program overhead over
    kv_heads× more work. The head loop is a static Python unroll of
    per-head [group, head_dim]×[head_dim, keys] matmuls over the shared
    streamed superblock.

    ``batch_rows > 1`` additionally co-schedules several batch items per
    program: each round issues every row's superblock DMAs together
    (more copies in flight against the same HBM latency) and the
    pipeline fills/drains once per program instead of once per batch
    item. Rows already out of rounds skip their DMAs and carry their
    state through unchanged; ragged contexts therefore cost bandwidth
    only up to each row's own length. VMEM budgeting in the wrapper
    divides the superblock across rows, so keys-per-round shrinks as
    rows grow — the on-chip sweep picks the operating point.
    """
    b0 = pl.program_id(0)
    rows, kv_heads, group = q_ref.shape[0], q_ref.shape[1], q_ref.shape[2]
    head_dim = q_ref.shape[3]
    kpb = pages_per_block

    ctx_len, tail_len, q_end = [], [], []
    num_iters, num_sb_r, streamers = [], [], []
    for r in range(rows):
        b = b0 * rows + r
        cl = ctx_lens_ref[b]
        tl = tail_lens_ref[b] if has_tail else jnp.int32(0)
        qe = cl + tl
        fw, sp, ni = _decode_stream_bounds(
            cl, qe, page_size, sliding_window, sinks)
        ctx_len.append(cl)
        tail_len.append(tl)
        q_end.append(qe)
        num_iters.append(ni)
        num_sb_r.append((ni + kpb - 1) // kpb)
        streamers.append(_superblock_streamer(
            page_table_ref, b, None, k_hbm, v_hbm, k_scratch, v_scratch,
            sem, kpb=kpb, num_iters=ni, first_window=fw, sink_pages=sp,
            sinks=sinks, shared_kv=shared_kv, layer_idx=layer_idx,
            row=r if rows > 1 else None))
    num_sb = num_sb_r[0]
    for r in range(1, rows):
        num_sb = jnp.maximum(num_sb, num_sb_r[r])

    def start_round(slot, sb):
        # Per-row guard: a row past its rounds neither starts nor waits
        # its copies (the same predicate gates both, below).
        for r in range(rows):
            @pl.when(sb < num_sb_r[r])
            def _(r=r):
                for c in streamers[r][1](slot, sb):
                    c.start()

    @pl.when(num_sb > 0)
    def _():
        start_round(0, 0)

    # qs[r][h]: [group, head_dim]
    qs = [[q_ref[r, h] for h in range(kv_heads)] for r in range(rows)]

    def body(sb, carry):
        ms, ls, accs = carry
        slot = sb % 2
        next_slot = (sb + 1) % 2

        @pl.when(sb + 1 < num_sb)
        def _():
            start_round(next_slot, sb + 1)

        new_ms = [list(row_m) for row_m in ms]
        new_ls = [list(row_l) for row_l in ls]
        new_accs = [list(row_a) for row_a in accs]
        for r in range(rows):
            @pl.when(sb < num_sb_r[r])
            def _(r=r):
                for c in streamers[r][1](slot, sb):
                    c.wait()
                if shared_copy:
                    # Same rationale as _decode_kernel: mirror the row's
                    # K superblock into the V scratch locally so each
                    # matmul gets its own buffer (one HBM read).
                    cp = pltpu.make_async_copy(
                        k_scratch.at[slot] if rows == 1
                        else k_scratch.at[slot, r],
                        v_scratch.at[slot] if rows == 1
                        else v_scratch.at[slot, r],
                        sem.at[slot, 0, 1] if rows == 1
                        else sem.at[slot, r, 0, 1])
                    cp.start()
                    cp.wait()

            # Shared mask for every head: positions depend only on the
            # row's pages — the per-head grid recomputed this kv_heads×.
            positions = streamers[r][0](sb, ctx_len[r], page_size)
            in_bounds = _decode_mask(positions, ctx_len[r], q_end[r],
                                     sliding_window, sinks)
            # Row liveness: past its last round the row's state must pass
            # through untouched (an all-masked round with m still at
            # -inf would turn exp(scores - m) into exp(0) garbage).
            live = sb * kpb < num_iters[r]

            if quant:
                # One upcast of the whole staged superblock (the fp8→bf16
                # convert is exact); every head slices the same value.
                kq = (k_scratch[slot] if rows == 1
                      else k_scratch[slot, r]).astype(q_ref.dtype)
                vq = (v_scratch[slot] if rows == 1
                      else v_scratch[slot, r]).astype(q_ref.dtype)

            for h in range(kv_heads):
                # [kpb, page_size, head_dim] slice of this head's keys →
                # leading-collapse reshape (lane dim unchanged).
                if quant:
                    k = kq[:, h * page_size:(h + 1) * page_size, :].reshape(
                        kpb * page_size, head_dim)
                    v = vq[:, h * page_size:(h + 1) * page_size, :].reshape(
                        kpb * page_size, head_dim)
                elif shared_kv and not shared_copy:
                    ks = k_scratch[slot, :, h] if rows == 1 else \
                        k_scratch[slot, r, :, h]
                    k = v = ks.reshape(kpb * page_size, head_dim)
                else:
                    ks = k_scratch[slot, :, h] if rows == 1 else \
                        k_scratch[slot, r, :, h]
                    k = ks.reshape(kpb * page_size, head_dim)
                    vs = v_scratch[slot, :, h] if rows == 1 else \
                        v_scratch[slot, r, :, h]
                    v = vs.reshape(kpb * page_size, head_dim)
                scores = jax.lax.dot_general(
                    qs[r][h], k,
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * scale  # [group, kpb*page_size]
                scores = jnp.where(in_bounds, scores, _NEG_INF)

                m_cur = jnp.max(scores, axis=1, keepdims=True)
                m_new = jnp.maximum(ms[r][h], m_cur)
                p = jnp.exp(scores - m_new)
                alpha = jnp.exp(ms[r][h] - m_new)
                l_new = ls[r][h] * alpha + jnp.sum(p, axis=1, keepdims=True)
                acc_new = accs[r][h] * alpha + jax.lax.dot_general(
                    p.astype(v.dtype), v,
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                new_ms[r][h] = jnp.where(live, m_new, ms[r][h])
                new_ls[r][h] = jnp.where(live, l_new, ls[r][h])
                new_accs[r][h] = jnp.where(live, acc_new, accs[r][h])
        to_t = lambda rows_list: tuple(tuple(x) for x in rows_list)
        return to_t(new_ms), to_t(new_ls), to_t(new_accs)

    m0 = tuple(tuple(jnp.full((group, 1), _NEG_INF, jnp.float32)
                     for _ in range(kv_heads)) for _ in range(rows))
    l0 = tuple(tuple(jnp.zeros((group, 1), jnp.float32)
                     for _ in range(kv_heads)) for _ in range(rows))
    acc0 = tuple(tuple(jnp.zeros((group, head_dim), jnp.float32)
                       for _ in range(kv_heads)) for _ in range(rows))
    ms, l_fin, accs = jax.lax.fori_loop(0, num_sb, body, (m0, l0, acc0))
    ms = [list(x) for x in ms]
    l_fin = [list(x) for x in l_fin]
    accs = [list(x) for x in accs]

    if has_tail:
        for r in range(rows):
            for h in range(kv_heads):
                ms[r][h], l_fin[r][h], accs[r][h] = _tail_fold(
                    qs[r][h], tail_k_ref[r, :, h],
                    tail_k_ref[r, :, h] if shared_kv
                    else tail_v_ref[r, :, h],
                    tail_len[r], ctx_len[r], ms[r][h], l_fin[r][h],
                    accs[r][h], scale=scale, sliding_window=sliding_window,
                    sinks=sinks)

    for r in range(rows):
        for h in range(kv_heads):
            out = accs[r][h] / jnp.maximum(l_fin[r][h], 1e-30)
            o_ref[r, h] = out.astype(o_ref.dtype)


def _prefill_kernel(
    # scalar prefetch
    page_table_ref,  # [batch, pages_per_seq] int32
    ctx_lens_ref,  # [batch] int32 (tokens already cached BEFORE the new ones)
    total_lens_ref,  # [batch] int32 (ctx + new)
    # inputs
    q_ref,  # [1, q_tile, heads_group, head_dim] block for (b, h, qt)
    k_hbm,
    v_hbm,
    # output
    o_ref,
    # scratch
    k_scratch,  # [2, pages_per_block, page_size, head_dim]
    v_scratch,
    sem,  # [2, pages_per_block, 2]
    *,
    page_size: int,
    q_tile: int,
    scale: float,
    sliding_window: int | None,
    sinks: int,
    pages_per_block: int,
    shared_kv: bool,
    layer_idx: int | None,
):
    b = pl.program_id(0)
    h = pl.program_id(1)
    qt = pl.program_id(2)
    # q_ref block: [1, 1, q_tile, 1, group, head_dim]
    group, head_dim = q_ref.shape[4], q_ref.shape[5]
    kpb = pages_per_block

    ctx_len = ctx_lens_ref[b]
    total_len = total_lens_ref[b]
    # Query rows in this tile sit at logical positions ctx_len + qt*q_tile + i.
    q_start = ctx_len + qt * q_tile
    # Causality: this tile needs keys up to position q_start + q_tile - 1.
    max_key = jnp.minimum(q_start + q_tile, total_len)
    num_pages = (max_key + page_size - 1) // page_size
    # SWA: the earliest key any query in this tile can see is
    # q_start - W + 1 (XLA convention: q_pos - k_pos < W), so pages wholly
    # before it are never streamed — long contexts cost ~W/page_size pages
    # per tile, matching the decode kernel's page skipping. Sinks keep the
    # first ceil(S/page_size) pages streamed too, via the same loop-counter
    # → page-index remap as the decode kernel.
    if sliding_window is not None:
        first_window = jnp.maximum(q_start - sliding_window + 1, 0) // page_size
    else:
        first_window = jnp.int32(0)
    if sinks:
        sink_pages = jnp.minimum(
            (sinks + page_size - 1) // page_size, num_pages)
        first_window = jnp.maximum(first_window, sink_pages)
    else:
        sink_pages = jnp.int32(0)
    num_iters = sink_pages + num_pages - jnp.minimum(first_window, num_pages)
    # MXU utilization: pages stream in superblocks of ``kpb`` pages, so
    # each online-softmax round multiplies [group·q_tile, head_dim] by
    # [head_dim, kpb·page_size] — full 128-wide MXU tiles instead of one
    # page_size-wide sliver per round (the round-2 kernel's 12×-slower
    # root cause; see benchmarking/r4-mfu/README.md). A superblock may
    # straddle the sink→window jump: each sub-page's positions come from
    # its own remapped index, so masking stays exact.
    num_sb = (num_iters + kpb - 1) // kpb

    sb_positions, sb_dma = _superblock_streamer(
        page_table_ref, b, h, k_hbm, v_hbm, k_scratch, v_scratch, sem,
        kpb=kpb, num_iters=num_iters, first_window=first_window,
        sink_pages=sink_pages, sinks=sinks, shared_kv=shared_kv,
        layer_idx=layer_idx)

    @pl.when(num_sb > 0)
    def _():
        for c in sb_dma(0, 0):
            c.start()

    # Keep q in the cache dtype and scale AFTER the QK^T matmul (fp32
    # scores): bf16×bf16 with fp32 accumulation is the MXU fast path, and
    # it matches the XLA reference's numerics (paged_attention scales the
    # fp32 einsum output).
    q = q_ref[0, 0, :, 0]  # [q_tile, group, head_dim]
    q2d = q.transpose(1, 0, 2)  # [group, q_tile, head_dim]
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (q_tile, 1), 0)

    def body(sb, carry):
        m_prev, l_prev, acc_prev = carry
        slot = sb % 2
        next_slot = (sb + 1) % 2

        @pl.when(sb + 1 < num_sb)
        def _():
            for c in sb_dma(next_slot, sb + 1):
                c.start()

        for c in sb_dma(slot, sb):
            c.wait()

        k = k_scratch[slot].reshape(kpb * page_size, head_dim)
        v = k if shared_kv else v_scratch[slot].reshape(
            kpb * page_size, head_dim)

        # [group, q_tile, kpb*page_size], fp32 accumulate off bf16 operands
        scores = jax.lax.dot_general(
            q2d, k, dimension_numbers=(((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        # Per-sub-page key positions (each from its own remapped page
        # index); sub-pages past num_iters park at total_len so every
        # mask term rejects them.
        k_pos = sb_positions(sb, total_len, page_size)
        mask = (k_pos <= q_pos) & (k_pos < total_len)  # [q_tile, kpb*ps]
        if sliding_window is not None:
            in_window = q_pos - k_pos < sliding_window
            if sinks:
                in_window = in_window | (k_pos < sinks)
            mask = mask & in_window
        scores = jnp.where(mask[None], scores, _NEG_INF)

        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc_prev * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((group, q_tile, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((group, q_tile, 1), jnp.float32)
    acc0 = jnp.zeros((group, q_tile, head_dim), jnp.float32)
    _m, l_fin, acc = jax.lax.fori_loop(0, num_sb, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l_fin, 1e-30)  # [group, q_tile, head_dim]
    o_ref[0, 0, :, 0] = out.transpose(1, 0, 2).astype(o_ref.dtype)


def _ragged_kernel(
    # scalar prefetch
    page_table_ref,  # [rows, pages_per_seq] int32 (SMEM)
    row_starts_ref,  # [rows+1] int32: per-row flat-token prefix sums
    ctx_lens_ref,  # [rows] int32 (tokens cached BEFORE each row's new ones)
    block_first_ref,  # [num_q_blocks] int32: first row touching each block
    block_rows_ref,  # [num_q_blocks] int32: rows touching each block
    tail_lens_ref,  # [rows] int32 (zeros when has_tail=False)
    # inputs
    q_ref,  # [1, q_tile, kv_heads, group, head_dim] VMEM block for (g,)
    k_hbm,  # [num_pages, kv_heads, page_size, head_dim] (ANY/HBM)
    v_hbm,  # same
    tail_k_ref,  # [rows, T, kv_heads, head_dim] whole-array VMEM; dummy if no tail
    tail_v_ref,  # same (placeholder when shared_kv)
    # output
    o_ref,  # [1, q_tile, kv_heads, group, head_dim] VMEM block
    # scratch
    k_scratch,  # [2, pages_per_block, kv_heads, page_size, head_dim] VMEM
    v_scratch,  # same
    sem,  # DMA semaphores [2, pages_per_block, 2]
    *,
    page_size: int,
    scale: float,
    q_tile: int,
    sliding_window: int | None,
    sinks: int,
    pages_per_block: int,
    shared_kv: bool,
    shared_copy: bool,
    has_tail: bool,
    layer_idx: int | None,
    quant: bool = False,
):
    """One grid over a ragged mixed prefill+decode batch.

    The batch is a FLAT token axis: row r's new tokens occupy flat slots
    ``[row_starts[r], row_starts[r+1])`` at logical positions
    ``ctx_lens[r] + i`` — a 1-token decode row and a 512-token prefill
    chunk are just rows of different lengths, with zero per-sequence
    padding (only the axis tail pads to a ``q_tile`` multiple). The grid
    is BLOCK-centric — one program per aligned q block, all kv heads
    merged (whole-page DMAs carry every head, as in
    ``_decode_kernel_merged``) — so a block's output is owned by exactly
    one program and rows straddling a block boundary cannot race. Rows
    intersecting the block are walked by a dynamic ``fori_loop`` off the
    prefix-summed metadata; each row streams its own page window through
    ``_superblock_streamer`` with ``_decode_stream_bounds`` arithmetic
    (``q_end`` = its first in-block query position + 1 reproduces the
    prefill kernel's ``max(q_first - W + 1, 0) // page_size`` window
    start), and its q rows are committed into the block state with a
    per-row liveness select — the ragged analogue of the merged decode
    kernel's live guard (a foreign row's all-masked scores would
    otherwise poison m/l/acc).

    ``quant``: fp8 (1-byte) pages in the flat whole-page layout with a
    per-round upcast, exactly the merged decode kernel's operand mode.
    ``has_tail``: burst-local dense KV tails folded per row via
    ``_tail_fold`` — its mask puts every query at ``ctx + tail_len - 1``,
    so tails are only valid for single-token (decode) rows; multi-token
    rows must carry ``tail_lens == 0``.
    """
    g = pl.program_id(0)
    kv_heads, group = q_ref.shape[2], q_ref.shape[3]
    head_dim = q_ref.shape[4]
    kpb = pages_per_block
    blk_start = g * q_tile

    first_row = block_first_ref[g]
    n_rows = block_rows_ref[g]

    # qs[h]: [group, q_tile, head_dim] (cache dtype; fp32 scores after the
    # matmul — the MXU fast path, same numerics as the other kernels).
    qs = [q_ref[0, :, h].transpose(1, 0, 2) for h in range(kv_heads)]
    qi = jax.lax.broadcasted_iota(jnp.int32, (q_tile, 1), 0)

    def row_body(ri, state):
        r = first_row + ri
        row_start = row_starts_ref[r]
        row_end = row_starts_ref[r + 1]
        ctx_len = ctx_lens_ref[r]

        flat = blk_start + qi  # [q_tile, 1] flat token index of each q row
        q_live = (flat >= row_start) & (flat < row_end)
        # Logical query positions as if every q row belonged to row r —
        # garbage for foreign rows, discarded by the liveness select.
        q_pos = ctx_len + flat - row_start
        # Keys this block needs from row r: up to its last in-block query
        # (causal; the new tokens' KV is already scattered, so kv_limit
        # includes them), starting from the first in-block query's window.
        kv_limit = (ctx_len - row_start
                    + jnp.minimum(row_end, blk_start + q_tile))
        q_first = ctx_len + jnp.maximum(row_start, blk_start) - row_start
        q_end = q_first + 1
        tail_len = tail_lens_ref[r] if has_tail else jnp.int32(0)
        if has_tail:
            # A tail row (tail_len > 0 — a 1-token row by contract) keeps
            # its new KV in the dense tail, not the pages: the paged scan
            # covers [0, ctx_len) and the query sits at
            # ctx_len + tail_len - 1 (the decode kernels' tail contract).
            is_tail_row = tail_len > 0
            kv_limit = jnp.where(is_tail_row, ctx_len, kv_limit)
            q_end = jnp.where(is_tail_row, ctx_len + tail_len, q_end)
            q_pos = jnp.where(is_tail_row, q_end - 1, q_pos)
        fw, sp, ni = _decode_stream_bounds(
            kv_limit, q_end, page_size, sliding_window, sinks)
        num_sb = (ni + kpb - 1) // kpb
        sb_positions, sb_dma = _superblock_streamer(
            page_table_ref, r, None, k_hbm, v_hbm, k_scratch, v_scratch,
            sem, kpb=kpb, num_iters=ni, first_window=fw, sink_pages=sp,
            sinks=sinks, shared_kv=shared_kv, layer_idx=layer_idx)

        @pl.when(num_sb > 0)
        def _():
            for c in sb_dma(0, 0):
                c.start()

        def body(sb, carry):
            ms, ls, accs = carry
            slot = sb % 2
            next_slot = (sb + 1) % 2

            @pl.when(sb + 1 < num_sb)
            def _():
                for c in sb_dma(next_slot, sb + 1):
                    c.start()

            for c in sb_dma(slot, sb):
                c.wait()
            if shared_copy:
                # Same rationale as the decode kernels: mirror the K
                # superblock into the V scratch locally so each matmul
                # gets its own buffer (one HBM read).
                cp = pltpu.make_async_copy(
                    k_scratch.at[slot], v_scratch.at[slot],
                    sem.at[slot, 0, 1])
                cp.start()
                cp.wait()

            # Shared mask for every head; park at kv_limit so parked
            # sub-pages are rejected by the in-bounds term.
            k_pos = sb_positions(sb, kv_limit, page_size)  # [1, kpb*ps]
            mask = (k_pos <= q_pos) & (k_pos < kv_limit)  # [q_tile, K]
            if sliding_window is not None:
                in_window = q_pos - k_pos < sliding_window
                if sinks:
                    in_window = in_window | (k_pos < sinks)
                mask = mask & in_window

            if quant:
                # One upcast of the staged superblock (fp8→bf16 exact);
                # every head slices the same value.
                kq = k_scratch[slot].astype(q_ref.dtype)
                vq = v_scratch[slot].astype(q_ref.dtype)

            new_ms, new_ls, new_accs = [], [], []
            for h in range(kv_heads):
                if quant:
                    k = kq[:, h * page_size:(h + 1) * page_size, :].reshape(
                        kpb * page_size, head_dim)
                    v = vq[:, h * page_size:(h + 1) * page_size, :].reshape(
                        kpb * page_size, head_dim)
                else:
                    k = k_scratch[slot, :, h].reshape(
                        kpb * page_size, head_dim)
                    if shared_kv:
                        v = (v_scratch[slot, :, h].reshape(
                            kpb * page_size, head_dim) if shared_copy else k)
                    else:
                        v = v_scratch[slot, :, h].reshape(
                            kpb * page_size, head_dim)
                scores = jax.lax.dot_general(
                    qs[h], k, dimension_numbers=(((2,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * scale  # [group, q_tile, kpb*page_size]
                scores = jnp.where(mask[None], scores, _NEG_INF)

                m_cur = jnp.max(scores, axis=-1, keepdims=True)
                m_new = jnp.maximum(ms[h], m_cur)
                p = jnp.exp(scores - m_new)
                alpha = jnp.exp(ms[h] - m_new)
                l_new = ls[h] * alpha + jnp.sum(p, axis=-1, keepdims=True)
                acc_new = accs[h] * alpha + jax.lax.dot_general(
                    p.astype(v.dtype), v,
                    dimension_numbers=(((2,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                new_ms.append(m_new)
                new_ls.append(l_new)
                new_accs.append(acc_new)
            return tuple(new_ms), tuple(new_ls), tuple(new_accs)

        m0 = tuple(jnp.full((group, q_tile, 1), _NEG_INF, jnp.float32)
                   for _ in range(kv_heads))
        l0 = tuple(jnp.zeros((group, q_tile, 1), jnp.float32)
                   for _ in range(kv_heads))
        a0 = tuple(jnp.zeros((group, q_tile, head_dim), jnp.float32)
                   for _ in range(kv_heads))
        m_r, l_r, acc_r = jax.lax.fori_loop(0, num_sb, body, (m0, l0, a0))
        m_r, l_r, acc_r = list(m_r), list(l_r), list(acc_r)

        if has_tail:
            # _tail_fold's mask assumes every query sits at the tail end
            # (ctx + tail_len - 1) — true for this row's single query when
            # tail_lens[r] > 0 only on 1-token rows (the documented
            # contract); the garbage it computes for foreign q rows is
            # discarded by the liveness select below. The fold is
            # row-wise over its leading axis, so the [group, q_tile, …]
            # state folds as [group·q_tile, …].
            for h in range(kv_heads):
                k_t = tail_k_ref[r, :, h]  # [T, head_dim]
                v_t = k_t if shared_kv else tail_v_ref[r, :, h]
                mf, lf, af = _tail_fold(
                    qs[h].reshape(group * q_tile, head_dim), k_t, v_t,
                    tail_len, ctx_len,
                    m_r[h].reshape(group * q_tile, 1),
                    l_r[h].reshape(group * q_tile, 1),
                    acc_r[h].reshape(group * q_tile, head_dim),
                    scale=scale, sliding_window=sliding_window, sinks=sinks)
                m_r[h] = mf.reshape(group, q_tile, 1)
                l_r[h] = lf.reshape(group, q_tile, 1)
                acc_r[h] = af.reshape(group, q_tile, head_dim)

        # Commit row r's q rows into the block state; foreign rows keep
        # theirs (the merged decode kernel's live guard, per q row).
        ms, ls, accs = state
        sel = q_live[None]  # [1, q_tile, 1] broadcasts over group/head_dim
        return (
            tuple(jnp.where(sel, m_r[h], ms[h]) for h in range(kv_heads)),
            tuple(jnp.where(sel, l_r[h], ls[h]) for h in range(kv_heads)),
            tuple(jnp.where(sel, acc_r[h], accs[h])
                  for h in range(kv_heads)),
        )

    m0 = tuple(jnp.full((group, q_tile, 1), _NEG_INF, jnp.float32)
               for _ in range(kv_heads))
    l0 = tuple(jnp.zeros((group, q_tile, 1), jnp.float32)
               for _ in range(kv_heads))
    a0 = tuple(jnp.zeros((group, q_tile, head_dim), jnp.float32)
               for _ in range(kv_heads))
    ms, ls, accs = jax.lax.fori_loop(0, n_rows, row_body, (m0, l0, a0))
    for h in range(kv_heads):
        # Pure-padding blocks (n_rows == 0) write zeros (l stays 0).
        out = accs[h] / jnp.maximum(ls[h], 1e-30)  # [group, q_tile, hd]
        o_ref[0, :, h] = out.transpose(1, 0, 2).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("q_tile", "sliding_window", "sinks",
                                    "pages_per_block", "shared_kv",
                                    "shared_stream", "layer_idx",
                                    "interpret"))
def pallas_paged_ragged_attention(
    q: jax.Array,  # [total_q, q_heads, head_dim] flat mixed batch
    k_cache: jax.Array,  # [num_pages, kv_heads, page_size, head_dim]
    v_cache: jax.Array,
    page_table: jax.Array,  # [rows, pages_per_seq] int32
    row_starts: jax.Array,  # [rows+1] int32 flat-token prefix sums
    ctx_lens: jax.Array,  # [rows] cached tokens before each row's new ones
    *,
    q_tile: int = 8,
    sliding_window: int | None = None,
    sinks: int | None = None,
    pages_per_block: int | None = None,
    shared_kv: bool = False,
    shared_stream: str = "copy",
    tail_k: jax.Array | None = None,  # [rows, T, kv_heads, head_dim]
    tail_v: jax.Array | None = None,
    tail_lens: jax.Array | None = None,  # [rows] valid tail tokens
    layer_idx: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Single-kernel flash attention over a ragged mixed batch.

    Row r's new tokens occupy flat q slots ``[row_starts[r],
    row_starts[r+1])`` at logical positions ``ctx_lens[r] + i`` and attend
    causally over that row's paged KV (the new tokens' KV already
    scattered, as in the prefill wrapper). Decode rows are length-1 rows;
    prefill chunks are longer rows — one dispatch serves both with no
    per-sequence padding (``total_q`` pads only to a ``q_tile`` multiple;
    slots at and past ``row_starts[-1]`` return unspecified values).
    Returns ``[total_q, q_heads, head_dim]``.

    ``sliding_window``/``sinks`` follow the prefill wrapper's semantics;
    ``shared_kv``/``shared_stream`` the decode wrapper's (absorbed MLA).
    A 1-byte (fp8 e4m3) cache takes the merged decode kernel's quantized
    operand mode: whole flat pages DMA'd at 1 byte/element and upcast
    once per round (needs ``kv_heads * page_size % 32 == 0`` on real
    TPU, merged layout only — same rules as decode). ``tail_*`` fold a
    dense burst-local tail per row via ``_tail_fold``; its mask pins
    every query to the tail end, so only 1-token rows may carry
    ``tail_lens > 0``.
    """
    total_q, q_heads, head_dim = q.shape
    # layer_idx: stacked caches, in-kernel layer indexing (see the other
    # wrappers — no per-layer slice copy at the custom-call boundary).
    cache_dims = k_cache.shape[1:] if layer_idx is not None else k_cache.shape
    _, kv_heads, page_size, _ = cache_dims
    group = q_heads // kv_heads
    rows = page_table.shape[0]
    assert total_q % q_tile == 0, "pad total_q to a q_tile multiple"
    if sliding_window is None:
        sinks = None  # no-op without a window (see the prefill wrapper)
    _check_head_dim_alignment(head_dim, interpret)
    if shared_stream not in ("copy", "reuse"):
        raise ValueError(
            f"shared_stream must be 'copy' or 'reuse', got {shared_stream!r}")

    num_blocks = total_q // q_tile
    row_starts = row_starts.astype(jnp.int32)
    ctx_lens = ctx_lens.astype(jnp.int32)

    # Block→row intersection metadata, prefix-sum arithmetic on the traced
    # row_starts (searchsorted 'right' minus one lands on the covering row
    # and naturally skips empty rows). Pure-padding blocks (start at or
    # past row_starts[-1]) get zero rows; the kernel writes zeros there.
    blk_starts = jnp.arange(num_blocks, dtype=jnp.int32) * q_tile
    total_real = row_starts[-1]
    first = jnp.clip(
        jnp.searchsorted(row_starts, blk_starts, side="right") - 1,
        0, rows - 1)
    last_tok = jnp.minimum(blk_starts + q_tile, total_real) - 1
    last = jnp.clip(
        jnp.searchsorted(row_starts, last_tok, side="right") - 1,
        0, rows - 1)
    block_first = first.astype(jnp.int32)
    block_rows = jnp.where(blk_starts < total_real,
                           last - first + 1, 0).astype(jnp.int32)

    if pages_per_block is None:
        # Merged-heads VMEM budget (see the decode wrapper) combined with
        # the prefill wrapper's fp32-scores clamp [group, q_tile, keys].
        kv_streams = 1 if shared_kv else 2
        budget = (8 * 2 ** 20) // (
            2 * kv_heads * head_dim
            * max(k_cache.dtype.itemsize, 2) * kv_streams)
        max_keys = max(128, (4 * 2 ** 20) // (4 * group * q_tile))
        keys = min(1024, max_keys, max(page_size, budget))
        pages_per_block = max(1, min(keys // page_size,
                                     page_table.shape[1]))

    has_tail = tail_k is not None
    if has_tail:
        if tail_lens is None:
            raise ValueError(
                "tail_k requires tail_lens [rows] int32 (valid tail "
                "tokens per row)")
        if tail_v is None and not shared_kv:
            raise ValueError(
                "tail_k requires tail_v [rows, T, kv_heads, head_dim] "
                "unless shared_kv=True (single-stream MLA)")
    else:
        # Structural placeholders (see the decode wrapper): the kernel
        # always takes tail refs; has_tail=False makes the fold dead code.
        tail_k = jnp.zeros((rows, 1, kv_heads, head_dim), q.dtype)
        tail_lens = jnp.zeros((rows,), jnp.int32)
    if shared_kv or not has_tail:
        tail_v = jnp.zeros((rows, 1, kv_heads, head_dim), q.dtype)
    t_len = tail_k.shape[1]

    # Quantized (fp8 e4m3) cache arm — the merged decode kernel's operand
    # mode carried over verbatim: flat whole-page view, 1-byte DMAs,
    # per-round upcast; tails ride in the query dtype (their values were
    # quantized through the cache when written, so the upcast is exact).
    quant = k_cache.dtype.itemsize == 1
    if quant:
        if shared_kv:
            raise ValueError(
                "quantized (fp8) caches are not supported for shared-kv "
                "(MLA latent) pools")
        if (kv_heads * page_size) % 32 and not interpret:
            raise ValueError(
                f"fp8 pages need kv_heads*page_size % 32 == 0 for "
                f"Mosaic's 8-bit tiling (got {kv_heads}*{page_size})")
        flat = (kv_heads * page_size, head_dim)
        k_cache = k_cache.reshape(k_cache.shape[:-3] + flat)
        v_cache = v_cache.reshape(v_cache.shape[:-3] + flat)

    q_blocked = q.reshape(num_blocks, q_tile, kv_heads, group, head_dim)

    kernel = functools.partial(
        _ragged_kernel, page_size=page_size, scale=head_dim ** -0.5,
        q_tile=q_tile, sliding_window=sliding_window, sinks=int(sinks or 0),
        pages_per_block=pages_per_block, shared_kv=shared_kv,
        shared_copy=shared_kv and shared_stream == "copy",
        has_tail=has_tail, layer_idx=layer_idx, quant=quant,
    )

    if quant:
        k_scr = (2, pages_per_block, kv_heads * page_size, head_dim)
    else:
        k_scr = (2, pages_per_block, kv_heads, page_size, head_dim)
    v_scr = (((1,) * len(k_scr))
             if shared_kv and shared_stream != "copy" else k_scr)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(num_blocks,),
        in_specs=[
            pl.BlockSpec(
                (1, q_tile, kv_heads, group, head_dim),
                lambda g, *_prefetch: (g, 0, 0, 0, 0),
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            # Tails ride as whole-array blocks: a q block can span several
            # rows, so no per-row BlockSpec fits — the kernel indexes rows
            # dynamically. Tail buffers are burst-sized (rows × steps).
            pl.BlockSpec(
                (rows, t_len, kv_heads, head_dim),
                lambda g, *_prefetch: (0, 0, 0, 0),
            ),
            pl.BlockSpec(
                (rows, tail_v.shape[1], kv_heads, head_dim),
                lambda g, *_prefetch: (0, 0, 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, q_tile, kv_heads, group, head_dim),
            lambda g, *_prefetch: (g, 0, 0, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM(k_scr, k_cache.dtype),
            pltpu.VMEM(v_scr, k_cache.dtype),
            pltpu.SemaphoreType.DMA((2, pages_per_block, 2)),
        ],
    )

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(
            (num_blocks, q_tile, kv_heads, group, head_dim), q.dtype
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table.astype(jnp.int32), row_starts, ctx_lens,
      block_first, block_rows, tail_lens.astype(jnp.int32),
      q_blocked, k_cache, v_cache,
      tail_k.astype(q.dtype), tail_v.astype(q.dtype))

    return out.reshape(total_q, q_heads, head_dim)


@functools.partial(jax.jit,
                   static_argnames=("q_tile", "sliding_window", "sinks",
                                    "pages_per_block", "shared_kv",
                                    "layer_idx", "interpret"))
def pallas_paged_prefill_attention(
    q: jax.Array,  # [batch, q_seq, q_heads, head_dim] (new tokens, padded)
    k_cache: jax.Array,  # [num_pages, kv_heads, page_size, head_dim]
    v_cache: jax.Array,
    page_table: jax.Array,  # [batch, pages_per_seq] int32
    ctx_lens: jax.Array,  # [batch] cached tokens before the new ones
    total_lens: jax.Array,  # [batch] ctx + valid new tokens
    *,
    q_tile: int = 16,
    sliding_window: int | None = None,
    sinks: int | None = None,
    pages_per_block: int | None = None,
    shared_kv: bool = False,
    layer_idx: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Flash prefill over paged KV (new tokens' KV already scattered).

    Queries attend causally over cached prefix + themselves, streaming
    page superblocks HBM→VMEM per (batch, kv_head, q_tile) program.
    Returns ``[batch, q_seq, q_heads, head_dim]``. ``q_seq`` must divide
    by ``q_tile`` (callers pad; padded rows are masked out by
    total_lens). ``sliding_window=W`` restricts each query to the last W
    keys and skips pages wholly out of window; ``sinks=S`` keeps the
    first S positions attendable past the window (StreamingLLM; needs a
    window). ``pages_per_block`` sets the keys per online-softmax round
    (``pages_per_block * page_size``); the default targets 1024 keys per
    round — measured on a real v5e (hack/mfu_probe.py, in-jit sweep at
    the bench's 2048-token chunks) round width beyond one MXU tile keeps
    paying until ~1024: 128-key rounds ran 3.0 ms/layer vs 1.9 ms at
    1024 keys — clamped so the fp32 scores tile [group, q_tile, keys]
    stays within a few MB of VMEM.
    """
    batch, q_seq, q_heads, head_dim = q.shape
    # layer_idx: caches are the engine's full [layers, pages, …] stack and
    # the kernel DMAs from [layer_idx, page, …] directly — slicing the
    # stack outside the pallas_call would materialize a per-layer copy at
    # the custom-call boundary.
    cache_dims = k_cache.shape[1:] if layer_idx is not None else k_cache.shape
    _, kv_heads, page_size, _ = cache_dims
    group = q_heads // kv_heads
    assert q_seq % q_tile == 0, "pad q_seq to a q_tile multiple"
    if sliding_window is None:
        # Without a window every position is causally attendable anyway —
        # the sink mask is a semantic no-op, so callers can pass a model's
        # sinks unconditionally (full-attention layers included).
        sinks = None
    _check_head_dim_alignment(head_dim, interpret)
    if pages_per_block is None:
        max_keys = max(128, (4 * 2 ** 20) // (4 * group * q_tile))
        pages_per_block = max(1, min(min(1024, max_keys) // page_size,
                                     page_table.shape[1]))

    # [batch, q_blocks, q_tile, kv_heads, group, head_dim] view via reshape:
    q_blocked = q.reshape(batch, q_seq // q_tile, q_tile, kv_heads, group, head_dim)

    kernel = functools.partial(
        _prefill_kernel, page_size=page_size, q_tile=q_tile,
        scale=head_dim ** -0.5, sliding_window=sliding_window,
        sinks=int(sinks or 0), pages_per_block=pages_per_block,
        shared_kv=shared_kv, layer_idx=layer_idx,
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(batch, kv_heads, q_seq // q_tile),
        in_specs=[
            pl.BlockSpec(
                (1, 1, q_tile, 1, group, head_dim),
                lambda b, h, qt, *_p: (b, qt, 0, h, 0, 0),
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, q_tile, 1, group, head_dim),
            lambda b, h, qt, *_p: (b, qt, 0, h, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, pages_per_block, page_size, head_dim),
                       k_cache.dtype),
            # shared_kv (absorbed MLA): the V stream is skipped, so its
            # scratch shrinks to a placeholder allocation.
            pltpu.VMEM((1, 1, 1, 1) if shared_kv else
                       (2, pages_per_block, page_size, head_dim),
                       k_cache.dtype),
            pltpu.SemaphoreType.DMA((2, pages_per_block, 2)),
        ],
    )

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(
            (batch, q_seq // q_tile, q_tile, kv_heads, group, head_dim), q.dtype
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table.astype(jnp.int32), ctx_lens.astype(jnp.int32),
      total_lens.astype(jnp.int32), q_blocked, k_cache, v_cache)

    return out.reshape(batch, q_seq, q_heads, head_dim)


@functools.partial(jax.jit,
                   static_argnames=("interpret", "sliding_window", "sinks",
                                    "pages_per_block", "shared_kv",
                                    "shared_stream", "merge_heads",
                                    "layer_idx", "batch_rows"))
def pallas_paged_decode_attention(
    q: jax.Array,  # [batch, q_heads, head_dim]
    k_cache: jax.Array,  # [num_pages, kv_heads, page_size, head_dim]
    v_cache: jax.Array,  # same
    page_table: jax.Array,  # [batch, pages_per_seq] int32
    ctx_lens: jax.Array,  # [batch] int32 (keys to attend per sequence)
    *,
    sliding_window: int | None = None,
    sinks: int | None = None,
    pages_per_block: int | None = None,
    shared_kv: bool = False,
    shared_stream: str = "copy",
    merge_heads: bool | None = None,
    tail_k: jax.Array | None = None,  # [batch, T, kv_heads, head_dim]
    tail_v: jax.Array | None = None,
    tail_lens: jax.Array | None = None,  # [batch] valid tail tokens
    layer_idx: int | None = None,
    batch_rows: int = 1,
    interpret: bool = False,
) -> jax.Array:
    """Flash-decode over paged KV. Returns ``[batch, q_heads, head_dim]``.

    The page size is the cache's native page dimension — the DMA tiles and
    mask arithmetic are derived from it, so no override is offered.
    ``sinks=S`` (StreamingLLM) keeps the first S positions attendable past
    the sliding window; their pages are streamed in addition to the
    window's. MLA's absorbed multi-query form is the ``kv_heads == 1``
    case: one shared latent 'head' serves every query head as one group.

    ``shared_stream`` picks how the ``shared_kv`` latent feeds the two
    matmuls: ``"copy"`` (default) DMAs each page from HBM once and
    locally mirrors it into the V scratch — HBM traffic stays halved
    but each matmul gets its own buffer; ``"reuse"`` aliases V to the K
    scratch (no copy, but the one buffer serves a head_dim-contraction
    and a key-contraction, which measured 2x slower at b8/ctx4k on a
    real v5e — see benchmarking/r5-tpu). Ignored without ``shared_kv``.

    ``merge_heads`` (default: on when ``kv_heads > 1``) runs every kv
    head of a batch item in one program — whole-page DMAs carry all
    heads, the position mask is computed once per round, and program
    count drops kv_heads× (see ``_decode_kernel_merged``). The per-head
    grid remains for kv_heads == 1 (identical work) and as an escape
    hatch.

    ``batch_rows`` (merged path only) co-schedules that many batch items
    per program: per-round DMAs issue for every row together (more
    copies in flight) and pipeline fill/drain amortizes across rows.
    The VMEM superblock budget is divided across rows, so keys-per-round
    shrinks accordingly; the batch is zero-padded to a multiple (padded
    rows stream nothing and their outputs are sliced off).
    """
    batch, q_heads, head_dim = q.shape
    # layer_idx: see the prefill wrapper — stacked caches, in-kernel
    # layer indexing, no per-layer slice copy at the custom-call boundary.
    cache_dims = k_cache.shape[1:] if layer_idx is not None else k_cache.shape
    num_pages_total, kv_heads, page_size, _ = cache_dims
    group = q_heads // kv_heads
    if sliding_window is None:
        sinks = None  # no-op without a window (see the prefill wrapper)
    _check_head_dim_alignment(head_dim, interpret)
    if merge_heads is None:
        merge_heads = kv_heads > 1
    if shared_stream not in ("copy", "reuse"):
        raise ValueError(
            f"shared_stream must be 'copy' or 'reuse', got {shared_stream!r}")
    if batch_rows > 1 and not merge_heads:
        raise ValueError("batch_rows > 1 requires the merged-heads kernel")
    batch_rows = max(1, min(batch_rows, batch))
    if pages_per_block is None:
        # ~1024 keys per online-softmax round: measured on a real v5e at
        # batch 8 / ctx 4k (hack/mfu_probe.py), widening rounds from 128
        # to 1024-2048 keys cut the step from 2.5 ms to ~1.3 ms — fewer
        # DMA waits and per-round fixed costs against the same bytes.
        # The decode scores tile [group, keys] is small; the merged
        # kernel's scratch carries every head per key, so its keys/round
        # are clamped to keep the double-buffered K+V staging ≤ ~8 MB of
        # VMEM. Clamped to the table's static page capacity so
        # short-context configs don't pay for redundant clamped copies.
        keys = 1024
        if merge_heads:
            kv_streams = 1 if shared_kv else 2
            # Quantized caches stage 1-byte pages but the per-round
            # upcast materializes bf16 values of the same superblock, so
            # budget as if 2-byte — the explicit pages_per_block knob
            # (and the on-chip sweep) can still push wider.
            budget = (8 * 2 ** 20) // (
                2 * batch_rows * kv_heads * head_dim
                * max(k_cache.dtype.itemsize, 2) * kv_streams)
            keys = min(keys, max(page_size, budget))
        pages_per_block = max(1, min(keys // page_size,
                                     page_table.shape[1]))

    q_blocked = q.reshape(batch, kv_heads, group, head_dim)

    has_tail = tail_k is not None
    if has_tail:
        # The tail arguments travel as a set: a tail without its valid
        # lengths (or, for separate K/V caches, without its values) would
        # surface much later as an opaque shape/attribute error.
        if tail_lens is None:
            raise ValueError(
                "tail_k requires tail_lens [batch] int32 (valid tail "
                "tokens per sequence)")
        if tail_v is None and not shared_kv:
            raise ValueError(
                "tail_k requires tail_v [batch, T, kv_heads, head_dim] "
                "unless shared_kv=True (single-stream MLA)")
    if not has_tail:
        # Structural placeholders: the kernels always take tail refs so
        # the two arities share one code path; has_tail=False makes the
        # fold dead code and the 2 KB dummy blocks are never read.
        tail_k = jnp.zeros((batch, 1, kv_heads, head_dim), k_cache.dtype)
        tail_lens = jnp.zeros((batch,), jnp.int32)
    if shared_kv or not has_tail:
        tail_v = jnp.zeros((batch, 1, kv_heads, head_dim), k_cache.dtype)
    t_len = tail_k.shape[1]

    # Multi-row programs: zero-pad the batch to a row multiple. Padded
    # rows have ctx_len 0 → no rounds, no DMAs; their outputs are 0 and
    # sliced off below.
    out_batch = batch
    if batch % batch_rows:
        pad = batch_rows - batch % batch_rows
        bpad = [(0, pad)] + [(0, 0)] * 3
        q_blocked = jnp.pad(q_blocked, bpad)
        tail_k = jnp.pad(tail_k, bpad)
        tail_v = jnp.pad(tail_v, bpad)
        page_table = jnp.pad(page_table, [(0, pad), (0, 0)])
        ctx_lens = jnp.pad(ctx_lens, (0, pad))
        tail_lens = jnp.pad(tail_lens, (0, pad))
        batch += pad

    # Quantized (fp8 e4m3) cache arm: DMA the 1-byte pages — the whole
    # point, half the HBM read bytes — and upcast in VMEM before the
    # matmuls. Mosaic's 8-bit tiling is (32, 128), so the per-head
    # [page_size, head_dim] sub-slices the bf16 path copies are
    # misaligned at page_size 16; instead the cache is viewed as
    # contiguous whole pages [.., kv_heads*page_size, head_dim] (a free
    # reshape) and each DMA moves one full page for every head, which is
    # aligned whenever kv_heads*page_size % 32 == 0. Merged-heads only
    # (the per-head grid would need the misaligned sub-slice), and the
    # burst tail rides as bf16 — its values were already quantized
    # through the cache dtype when written, so the upcast is exact.
    quant = k_cache.dtype.itemsize == 1
    if quant:
        if shared_kv:
            raise ValueError(
                "quantized (fp8) caches are not supported for shared-kv "
                "(MLA latent) pools")
        if not merge_heads:
            raise ValueError(
                "quantized (fp8) caches need the merged-heads decode "
                "kernel (merge_heads=True)")
        if (kv_heads * page_size) % 32 and not interpret:
            raise ValueError(
                f"fp8 pages need kv_heads*page_size % 32 == 0 for "
                f"Mosaic's 8-bit tiling (got {kv_heads}*{page_size})")
        flat = (kv_heads * page_size, head_dim)
        k_cache = k_cache.reshape(k_cache.shape[:-3] + flat)
        v_cache = v_cache.reshape(v_cache.shape[:-3] + flat)
        tail_k = tail_k.astype(q.dtype)
        tail_v = tail_v.astype(q.dtype)

    if merge_heads:
        rr = batch_rows
        kernel = functools.partial(
            _decode_kernel_merged, page_size=page_size,
            scale=head_dim ** -0.5, sliding_window=sliding_window,
            sinks=int(sinks or 0), pages_per_block=pages_per_block,
            shared_kv=shared_kv,
            shared_copy=shared_kv and shared_stream == "copy",
            has_tail=has_tail, layer_idx=layer_idx, quant=quant,
        )
        if quant:
            k_scr = ((2, pages_per_block, kv_heads * page_size, head_dim)
                     if rr == 1 else
                     (2, rr, pages_per_block, kv_heads * page_size,
                      head_dim))
        else:
            k_scr = ((2, pages_per_block, kv_heads, page_size, head_dim)
                     if rr == 1 else
                     (2, rr, pages_per_block, kv_heads, page_size,
                      head_dim))
        v_scr = (((1,) * len(k_scr))
                 if shared_kv and shared_stream != "copy" else k_scr)
        sem_shape = ((2, pages_per_block, 2) if rr == 1
                     else (2, rr, pages_per_block, 2))
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(batch // rr,),
            in_specs=[
                pl.BlockSpec(
                    (rr, kv_heads, group, head_dim),
                    lambda b, *_prefetch: (b, 0, 0, 0),
                ),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(
                    (rr, t_len, kv_heads, head_dim),
                    lambda b, *_prefetch: (b, 0, 0, 0),
                ),
                pl.BlockSpec(
                    (rr, tail_v.shape[1], kv_heads, head_dim),
                    lambda b, *_prefetch: (b, 0, 0, 0),
                ),
            ],
            out_specs=pl.BlockSpec(
                (rr, kv_heads, group, head_dim),
                lambda b, *_prefetch: (b, 0, 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM(k_scr, k_cache.dtype),
                pltpu.VMEM(v_scr, k_cache.dtype),
                pltpu.SemaphoreType.DMA(sem_shape),
            ],
        )
    else:
        # Tail transposed to [batch, kvh, T, hd] for this path: Mosaic
        # requires the last two block dims to divide (8, 128) or equal
        # the array dims — a size-1 block on a kvh>1 second-to-last axis
        # is rejected, so the head axis moves out of the blocked pair
        # and is picked by the index map.
        tail_k = tail_k.transpose(0, 2, 1, 3)
        tail_v = tail_v.transpose(0, 2, 1, 3)
        kernel = functools.partial(
            _decode_kernel, page_size=page_size, scale=head_dim ** -0.5,
            sliding_window=sliding_window, sinks=int(sinks or 0),
            pages_per_block=pages_per_block, shared_kv=shared_kv,
            shared_copy=shared_kv and shared_stream == "copy",
            has_tail=has_tail, layer_idx=layer_idx,
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(batch, kv_heads),
            in_specs=[
                pl.BlockSpec(
                    (1, 1, group, head_dim),
                    # scalar-prefetch refs are appended to index_map args
                    lambda b, h, *_prefetch: (b, h, 0, 0),
                ),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(
                    (1, 1, t_len, head_dim),
                    lambda b, h, *_prefetch: (b, h, 0, 0),
                ),
                pl.BlockSpec(
                    (1, 1, tail_v.shape[2], head_dim),
                    lambda b, h, *_prefetch: (b, h, 0, 0),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, group, head_dim),
                lambda b, h, *_prefetch: (b, h, 0, 0),
            ),
            scratch_shapes=[
                # DMA staging must match the cache dtype; upcast after load.
                pltpu.VMEM((2, pages_per_block, page_size, head_dim),
                           k_cache.dtype),
                # shared_kv: V stream skipped. "copy" mirrors K into a
                # full V scratch locally (one HBM read, two buffers);
                # "reuse" needs only a placeholder.
                pltpu.VMEM((1, 1, 1, 1)
                           if shared_kv and shared_stream != "copy" else
                           (2, pages_per_block, page_size, head_dim),
                           k_cache.dtype),
                pltpu.SemaphoreType.DMA((2, pages_per_block, 2)),
            ],
        )

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(
            (batch, kv_heads, group, head_dim), q.dtype
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table.astype(jnp.int32), ctx_lens.astype(jnp.int32),
      tail_lens.astype(jnp.int32),
      q_blocked, k_cache, v_cache, tail_k.astype(k_cache.dtype),
      tail_v.astype(k_cache.dtype))

    return out.reshape(batch, q_heads, head_dim)[:out_batch]


def _kv_pool_spec(k_cache, stacked=False):
    """Cache PartitionSpec under tp: kv-heads axis sharded, except the
    single-shared-head (MQA/absorbed-MLA) pool, which replicates — a
    width-1 axis cannot shard, and replicating the latent is what lets
    each shard attend its local query heads with zero cross-shard traffic
    (matches ``parallel.serve.shard_kv_pool`` placement). ``stacked``:
    the operand is the full [layers, pages, kvh, ps, hd] stack (kernel
    indexes the layer in-DMA)."""
    from jax.sharding import PartitionSpec as P

    kvh_axis = 2 if stacked else 1
    if k_cache.shape[kvh_axis] == 1:
        return P()
    if stacked:
        return P(None, None, "tp", None, None)
    return P(None, "tp", None, None)


def sharded_paged_decode_attention(
    mesh, q, k_cache, v_cache, page_table, ctx_lens, *,
    sliding_window=None, sinks=None, pages_per_block=None, shared_kv=False,
    shared_stream="copy", merge_heads=None, tail_k=None, tail_v=None,
    tail_lens=None, layer_idx=None, interpret=False,
):
    """Flash-decode over a tp-sharded paged KV cache.

    ``pallas_call`` cannot consume sharded operands directly, so each tp
    shard runs the kernel on its local kv heads under ``shard_map``.
    Heads stay shard-local either way the local kernel grids them (one
    program per (batch, local head), or the merged-heads default's one
    program per batch item covering every local head — kv_heads× larger
    scratch per program), so sharding the kv-heads axis needs no
    cross-shard communication at all (the per-block all-reduce happens
    later, at the wo projection). Page tables and lengths are replicated
    control state.

    Shapes are global: q [batch, q_heads, hd] (heads sharded over tp),
    caches [pages, kv_heads, ps, hd] (kv heads sharded over tp; a
    single-head MQA/MLA pool replicates and each shard runs its local
    query heads as one group against the full pool).
    """
    from ..utils.shard_map_compat import shard_map
    from jax.sharding import PartitionSpec as P

    has_tail = tail_k is not None

    def local(q_, k_, v_, t_, l_, tk_, tv_, tl_):
        return pallas_paged_decode_attention(
            q_, k_, v_, t_, l_, sliding_window=sliding_window, sinks=sinks,
            pages_per_block=pages_per_block, shared_kv=shared_kv,
            shared_stream=shared_stream, merge_heads=merge_heads,
            tail_k=tk_ if has_tail else None,
            tail_v=tv_ if has_tail else None,
            tail_lens=tl_ if has_tail else None,
            layer_idx=layer_idx, interpret=interpret,
        )

    kv_spec = _kv_pool_spec(k_cache, stacked=layer_idx is not None)
    # Tail buffers shard on their kv-heads axis alongside the pool (a
    # replicated single-head MLA pool replicates its tail too).
    kvh_axis = 2 if layer_idx is not None else 1
    tail_spec = (P() if k_cache.shape[kvh_axis] == 1
                 else P(None, None, "tp", None))
    if not has_tail:
        # Zero-size placeholders keep the shard_map arity fixed.
        batch = q.shape[0]
        tail_k = jnp.zeros(
            (batch, 1, k_cache.shape[kvh_axis], k_cache.shape[-1]),
            k_cache.dtype)
        tail_v = tail_k
        tail_lens = jnp.zeros((batch,), jnp.int32)
    elif tail_v is None:  # shared_kv callers pass only the latent tail
        tail_v = tail_k
    return shard_map(
        local, mesh=mesh,
        in_specs=(P(None, "tp", None), kv_spec, kv_spec,
                  P(None, None), P(None), tail_spec, tail_spec, P(None)),
        out_specs=P(None, "tp", None),
        check_vma=False,
    )(q, k_cache, v_cache, page_table, ctx_lens, tail_k, tail_v, tail_lens)


def sharded_paged_prefill_attention(
    mesh, q, k_cache, v_cache, page_table, ctx_lens, total_lens, *,
    q_tile=16, sliding_window=None, sinks=None, pages_per_block=None,
    shared_kv=False, layer_idx=None, interpret=False,
):
    """Flash-prefill over a tp-sharded paged KV cache (see the decode
    wrapper's rationale). q: [batch, q_seq, q_heads, hd], heads sharded."""
    from ..utils.shard_map_compat import shard_map
    from jax.sharding import PartitionSpec as P

    def local(q_, k_, v_, t_, cl_, tl_):
        return pallas_paged_prefill_attention(
            q_, k_, v_, t_, cl_, tl_, q_tile=q_tile,
            sliding_window=sliding_window, sinks=sinks,
            pages_per_block=pages_per_block, shared_kv=shared_kv,
            layer_idx=layer_idx, interpret=interpret,
        )

    kv_spec = _kv_pool_spec(k_cache, stacked=layer_idx is not None)
    return shard_map(
        local, mesh=mesh,
        in_specs=(P(None, None, "tp", None), kv_spec, kv_spec,
                  P(None, None), P(None), P(None)),
        out_specs=P(None, None, "tp", None),
        check_vma=False,
    )(q, k_cache, v_cache, page_table, ctx_lens, total_lens)
