"""Pallas TPU kernel: flash-decode attention over a paged KV cache.

The decode-step fast path (seq == 1): instead of materializing each
sequence's gathered KV ([batch, pages*page_size, heads, dim] in HBM, which
``ops.paged_attention`` does and which wastes HBM bandwidth on long
contexts), each (batch, kv_head) program streams the sequence's pages
HBM→VMEM with double-buffered async DMA and folds them into an online
softmax — the ragged-paged-attention recipe specialized to decode.

Grid: ``(batch, kv_heads)``. Scalar-prefetched page table + context lengths
drive the DMA indices (``PrefetchScalarGridSpec``). GQA: each program
serves its kv head's whole query group.

The jnp reference path remains the fallback (CPU tests run this kernel in
interpreter mode against it).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def head_dim_supported(head_dim: int) -> bool:
    """Whether these kernels can compile on real TPU for this head size.

    Mosaic requires per-(page, head) DMA slices to be 128-aligned along
    the lane (head_dim) axis; sub-128 head dims fail to compile (measured
    v5e: "Slice shape along dimension 3 must be aligned to tiling (128)").
    Interpreter mode has no such restriction — this predicate gates the
    compiled path only (the engine's backend selection and the kernels'
    own guard both use it, so the rule cannot drift between them)."""
    return head_dim % 128 == 0


def _check_head_dim_alignment(head_dim: int, interpret: bool) -> None:
    if not interpret and not head_dim_supported(head_dim) and (
            jax.devices()[0].platform == "tpu"):
        raise ValueError(
            f"Pallas paged attention needs head_dim % 128 == 0 on TPU "
            f"(got {head_dim}); use the XLA paged-attention fallback "
            f"(ops.paged_attention) for this model")


def _decode_kernel(
    # scalar prefetch
    page_table_ref,  # [batch, pages_per_seq] int32 (SMEM)
    ctx_lens_ref,  # [batch] int32 (SMEM)
    # inputs
    q_ref,  # [1, 1, group, head_dim] VMEM block for (b, h)
    k_hbm,  # [num_pages, kv_heads, page_size, head_dim] (ANY/HBM)
    v_hbm,  # same
    # output
    o_ref,  # [1, 1, group, head_dim] VMEM block
    # scratch
    k_scratch,  # [2, page_size, head_dim] VMEM
    v_scratch,  # [2, page_size, head_dim] VMEM
    sem,  # DMA semaphores [2, 2]
    *,
    page_size: int,
    scale: float,
    sliding_window: int | None,
    sinks: int,
):
    b = pl.program_id(0)
    h = pl.program_id(1)
    group, head_dim = q_ref.shape[2], q_ref.shape[3]

    ctx_len = ctx_lens_ref[b]
    num_pages = (ctx_len + page_size - 1) // page_size
    # SWA: pages entirely outside the window are skipped, so long contexts
    # stream only ~window/page_size pages. Attention sinks (StreamingLLM,
    # reference events.go:40 sink_full_attention) additionally stream the
    # first ceil(S/page_size) pages: the loop counter j is remapped to a
    # page index — sink pages [0, sink_pages) first, then window pages
    # [first_window, num_pages) — so the double-buffered DMA pipeline is
    # unchanged and the skipped middle costs nothing.
    if sliding_window is not None:
        first_window = jnp.maximum(ctx_len - sliding_window, 0) // page_size
    else:
        first_window = jnp.int32(0)
    if sinks:
        sink_pages = jnp.minimum(
            (sinks + page_size - 1) // page_size, num_pages)
        first_window = jnp.maximum(first_window, sink_pages)
    else:
        sink_pages = jnp.int32(0)
    num_iters = sink_pages + num_pages - first_window

    def page_for(j):
        if not sinks:
            return first_window + j
        return jnp.where(j < sink_pages, j, first_window + (j - sink_pages))

    def page_dma(slot, page_idx):
        page = page_table_ref[b, page_idx]
        k_copy = pltpu.make_async_copy(
            k_hbm.at[page, h], k_scratch.at[slot], sem.at[slot, 0]
        )
        v_copy = pltpu.make_async_copy(
            v_hbm.at[page, h], v_scratch.at[slot], sem.at[slot, 1]
        )
        return k_copy, v_copy

    @pl.when(num_iters > 0)
    def _():
        for c in page_dma(0, page_for(0)):
            c.start()

    q = q_ref[0, 0].astype(jnp.float32) * scale  # [group, head_dim]

    def body(j, carry):
        m_prev, l_prev, acc_prev = carry
        slot = j % 2
        next_slot = (j + 1) % 2

        @pl.when(j + 1 < num_iters)
        def _():
            for c in page_dma(next_slot, page_for(j + 1)):
                c.start()

        for c in page_dma(slot, page_for(j)):
            c.wait()

        k = k_scratch[slot].astype(jnp.float32)  # [page_size, head_dim]
        v = v_scratch[slot].astype(jnp.float32)

        scores = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [group, page_size]

        # mask slots beyond the context length on the last page (and, for
        # SWA, positions that fell out of the window — unless they are
        # sink positions, which stay attendable forever)
        positions = page_for(j) * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1
        )
        in_bounds = positions < ctx_len
        if sliding_window is not None:
            in_window = positions >= ctx_len - sliding_window
            if sinks:
                in_window = in_window | (positions < sinks)
            in_bounds = in_bounds & in_window
        scores = jnp.where(in_bounds, scores, _NEG_INF)

        m_cur = jnp.max(scores, axis=1, keepdims=True)  # [group, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(scores - m_new)  # [group, page_size]
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc_prev * alpha + jax.lax.dot_general(
            p, v, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((group, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((group, 1), jnp.float32)
    acc0 = jnp.zeros((group, head_dim), jnp.float32)
    _m, l_fin, acc = jax.lax.fori_loop(0, num_iters, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l_fin, 1e-30)
    o_ref[0, 0] = out.astype(o_ref.dtype)


def _prefill_kernel(
    # scalar prefetch
    page_table_ref,  # [batch, pages_per_seq] int32
    ctx_lens_ref,  # [batch] int32 (tokens already cached BEFORE the new ones)
    total_lens_ref,  # [batch] int32 (ctx + new)
    # inputs
    q_ref,  # [1, q_tile, heads_group, head_dim] block for (b, h, qt)
    k_hbm,
    v_hbm,
    # output
    o_ref,
    # scratch
    k_scratch,
    v_scratch,
    sem,
    *,
    page_size: int,
    q_tile: int,
    scale: float,
    sliding_window: int | None,
    sinks: int,
):
    b = pl.program_id(0)
    h = pl.program_id(1)
    qt = pl.program_id(2)
    # q_ref block: [1, 1, q_tile, 1, group, head_dim]
    group, head_dim = q_ref.shape[4], q_ref.shape[5]

    ctx_len = ctx_lens_ref[b]
    total_len = total_lens_ref[b]
    # Query rows in this tile sit at logical positions ctx_len + qt*q_tile + i.
    q_start = ctx_len + qt * q_tile
    # Causality: this tile needs keys up to position q_start + q_tile - 1.
    max_key = jnp.minimum(q_start + q_tile, total_len)
    num_pages = (max_key + page_size - 1) // page_size
    # SWA: the earliest key any query in this tile can see is
    # q_start - W + 1 (XLA convention: q_pos - k_pos < W), so pages wholly
    # before it are never streamed — long contexts cost ~W/page_size pages
    # per tile, matching the decode kernel's page skipping. Sinks keep the
    # first ceil(S/page_size) pages streamed too, via the same loop-counter
    # → page-index remap as the decode kernel.
    if sliding_window is not None:
        first_window = jnp.maximum(q_start - sliding_window + 1, 0) // page_size
    else:
        first_window = jnp.int32(0)
    if sinks:
        sink_pages = jnp.minimum(
            (sinks + page_size - 1) // page_size, num_pages)
        first_window = jnp.maximum(first_window, sink_pages)
    else:
        sink_pages = jnp.int32(0)
    num_iters = sink_pages + num_pages - jnp.minimum(first_window, num_pages)

    def page_for(j):
        if not sinks:
            return first_window + j
        return jnp.where(j < sink_pages, j, first_window + (j - sink_pages))

    def page_dma(slot, page_idx):
        page = page_table_ref[b, page_idx]
        return (
            pltpu.make_async_copy(
                k_hbm.at[page, h], k_scratch.at[slot], sem.at[slot, 0]
            ),
            pltpu.make_async_copy(
                v_hbm.at[page, h], v_scratch.at[slot], sem.at[slot, 1]
            ),
        )

    @pl.when(num_iters > 0)
    def _():
        for c in page_dma(0, page_for(0)):
            c.start()

    q = q_ref[0, 0, :, 0].astype(jnp.float32) * scale  # [q_tile, group, hd]
    q2d = q.transpose(1, 0, 2)  # [group, q_tile, head_dim]
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (q_tile, 1), 0)

    def body(j, carry):
        m_prev, l_prev, acc_prev = carry
        slot = j % 2
        next_slot = (j + 1) % 2

        @pl.when(j + 1 < num_iters)
        def _():
            for c in page_dma(next_slot, page_for(j + 1)):
                c.start()

        for c in page_dma(slot, page_for(j)):
            c.wait()

        k = k_scratch[slot].astype(jnp.float32)  # [page_size, head_dim]
        v = v_scratch[slot].astype(jnp.float32)

        # [group, q_tile, page_size]
        scores = jax.lax.dot_general(
            q2d, k, dimension_numbers=(((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        k_pos = page_for(j) * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1
        )
        mask = (k_pos <= q_pos) & (k_pos < total_len)  # [q_tile, page_size]
        if sliding_window is not None:
            in_window = q_pos - k_pos < sliding_window
            if sinks:
                in_window = in_window | (k_pos < sinks)
            mask = mask & in_window
        scores = jnp.where(mask[None], scores, _NEG_INF)

        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc_prev * alpha + jax.lax.dot_general(
            p, v, dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((group, q_tile, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((group, q_tile, 1), jnp.float32)
    acc0 = jnp.zeros((group, q_tile, head_dim), jnp.float32)
    _m, l_fin, acc = jax.lax.fori_loop(0, num_iters, body,
                                       (m0, l0, acc0))

    out = acc / jnp.maximum(l_fin, 1e-30)  # [group, q_tile, head_dim]
    o_ref[0, 0, :, 0] = out.transpose(1, 0, 2).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("q_tile", "sliding_window", "sinks",
                                    "interpret"))
def pallas_paged_prefill_attention(
    q: jax.Array,  # [batch, q_seq, q_heads, head_dim] (new tokens, padded)
    k_cache: jax.Array,  # [num_pages, kv_heads, page_size, head_dim]
    v_cache: jax.Array,
    page_table: jax.Array,  # [batch, pages_per_seq] int32
    ctx_lens: jax.Array,  # [batch] cached tokens before the new ones
    total_lens: jax.Array,  # [batch] ctx + valid new tokens
    *,
    q_tile: int = 16,
    sliding_window: int | None = None,
    sinks: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Flash prefill over paged KV (new tokens' KV already scattered).

    Queries attend causally over cached prefix + themselves, streaming
    pages HBM→VMEM per (batch, kv_head, q_tile) program. Returns
    ``[batch, q_seq, q_heads, head_dim]``. ``q_seq`` must divide by
    ``q_tile`` (callers pad; padded rows are masked out by total_lens).
    ``sliding_window=W`` restricts each query to the last W keys and skips
    pages wholly out of window; ``sinks=S`` keeps the first S positions
    attendable past the window (StreamingLLM; needs a window).
    """
    batch, q_seq, q_heads, head_dim = q.shape
    _, kv_heads, page_size, _ = k_cache.shape
    group = q_heads // kv_heads
    assert q_seq % q_tile == 0, "pad q_seq to a q_tile multiple"
    if sliding_window is None:
        # Without a window every position is causally attendable anyway —
        # the sink mask is a semantic no-op, so callers can pass a model's
        # sinks unconditionally (full-attention layers included).
        sinks = None
    _check_head_dim_alignment(head_dim, interpret)

    # [batch, q_blocks, q_tile, kv_heads, group, head_dim] view via reshape:
    q_blocked = q.reshape(batch, q_seq // q_tile, q_tile, kv_heads, group, head_dim)

    kernel = functools.partial(
        _prefill_kernel, page_size=page_size, q_tile=q_tile,
        scale=head_dim ** -0.5, sliding_window=sliding_window,
        sinks=int(sinks or 0),
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(batch, kv_heads, q_seq // q_tile),
        in_specs=[
            pl.BlockSpec(
                (1, 1, q_tile, 1, group, head_dim),
                lambda b, h, qt, *_p: (b, qt, 0, h, 0, 0),
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, q_tile, 1, group, head_dim),
            lambda b, h, qt, *_p: (b, qt, 0, h, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, page_size, head_dim), k_cache.dtype),
            pltpu.VMEM((2, page_size, head_dim), k_cache.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(
            (batch, q_seq // q_tile, q_tile, kv_heads, group, head_dim), q.dtype
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table.astype(jnp.int32), ctx_lens.astype(jnp.int32),
      total_lens.astype(jnp.int32), q_blocked, k_cache, v_cache)

    return out.reshape(batch, q_seq, q_heads, head_dim)


@functools.partial(jax.jit,
                   static_argnames=("interpret", "sliding_window", "sinks"))
def pallas_paged_decode_attention(
    q: jax.Array,  # [batch, q_heads, head_dim]
    k_cache: jax.Array,  # [num_pages, kv_heads, page_size, head_dim]
    v_cache: jax.Array,  # same
    page_table: jax.Array,  # [batch, pages_per_seq] int32
    ctx_lens: jax.Array,  # [batch] int32 (keys to attend per sequence)
    *,
    sliding_window: int | None = None,
    sinks: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Flash-decode over paged KV. Returns ``[batch, q_heads, head_dim]``.

    The page size is the cache's native page dimension — the DMA tiles and
    mask arithmetic are derived from it, so no override is offered.
    ``sinks=S`` (StreamingLLM) keeps the first S positions attendable past
    the sliding window; their pages are streamed in addition to the
    window's. MLA's absorbed multi-query form is the ``kv_heads == 1``
    case: one shared latent 'head' serves every query head as one group.
    """
    batch, q_heads, head_dim = q.shape
    num_pages_total, kv_heads, page_size, _ = k_cache.shape
    group = q_heads // kv_heads
    if sliding_window is None:
        sinks = None  # no-op without a window (see the prefill wrapper)
    _check_head_dim_alignment(head_dim, interpret)

    q_blocked = q.reshape(batch, kv_heads, group, head_dim)

    kernel = functools.partial(
        _decode_kernel, page_size=page_size, scale=head_dim ** -0.5,
        sliding_window=sliding_window, sinks=int(sinks or 0),
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, kv_heads),
        in_specs=[
            pl.BlockSpec(
                (1, 1, group, head_dim),
                # scalar-prefetch refs are appended to index_map args
                lambda b, h, *_prefetch: (b, h, 0, 0),
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group, head_dim),
            lambda b, h, *_prefetch: (b, h, 0, 0),
        ),
        scratch_shapes=[
            # DMA staging must match the cache dtype; upcast after load.
            pltpu.VMEM((2, page_size, head_dim), k_cache.dtype),
            pltpu.VMEM((2, page_size, head_dim), k_cache.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(
            (batch, kv_heads, group, head_dim), q.dtype
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table.astype(jnp.int32), ctx_lens.astype(jnp.int32),
      q_blocked, k_cache, v_cache)

    return out.reshape(batch, q_heads, head_dim)


def _kv_pool_spec(k_cache):
    """Cache PartitionSpec under tp: kv-heads axis sharded, except the
    single-shared-head (MQA/absorbed-MLA) pool, which replicates — a
    width-1 axis cannot shard, and replicating the latent is what lets
    each shard attend its local query heads with zero cross-shard traffic
    (matches ``parallel.serve.shard_kv_pool`` placement)."""
    from jax.sharding import PartitionSpec as P

    if k_cache.shape[1] == 1:
        return P()
    return P(None, "tp", None, None)


def sharded_paged_decode_attention(
    mesh, q, k_cache, v_cache, page_table, ctx_lens, *,
    sliding_window=None, sinks=None, interpret=False,
):
    """Flash-decode over a tp-sharded paged KV cache.

    ``pallas_call`` cannot consume sharded operands directly, so each tp
    shard runs the kernel on its local kv heads under ``shard_map`` — the
    decode grid is (batch, kv_head)-independent, so sharding the kv-heads
    axis needs no cross-shard communication at all (the per-block
    all-reduce happens later, at the wo projection). Page tables and
    lengths are replicated control state.

    Shapes are global: q [batch, q_heads, hd] (heads sharded over tp),
    caches [pages, kv_heads, ps, hd] (kv heads sharded over tp; a
    single-head MQA/MLA pool replicates and each shard runs its local
    query heads as one group against the full pool).
    """
    from ..utils.shard_map_compat import shard_map
    from jax.sharding import PartitionSpec as P

    def local(q_, k_, v_, t_, l_):
        return pallas_paged_decode_attention(
            q_, k_, v_, t_, l_, sliding_window=sliding_window, sinks=sinks,
            interpret=interpret,
        )

    kv_spec = _kv_pool_spec(k_cache)
    return shard_map(
        local, mesh=mesh,
        in_specs=(P(None, "tp", None), kv_spec, kv_spec,
                  P(None, None), P(None)),
        out_specs=P(None, "tp", None),
        check_vma=False,
    )(q, k_cache, v_cache, page_table, ctx_lens)


def sharded_paged_prefill_attention(
    mesh, q, k_cache, v_cache, page_table, ctx_lens, total_lens, *,
    q_tile=16, sliding_window=None, sinks=None, interpret=False,
):
    """Flash-prefill over a tp-sharded paged KV cache (see the decode
    wrapper's rationale). q: [batch, q_seq, q_heads, hd], heads sharded."""
    from ..utils.shard_map_compat import shard_map
    from jax.sharding import PartitionSpec as P

    def local(q_, k_, v_, t_, cl_, tl_):
        return pallas_paged_prefill_attention(
            q_, k_, v_, t_, cl_, tl_, q_tile=q_tile,
            sliding_window=sliding_window, sinks=sinks, interpret=interpret,
        )

    kv_spec = _kv_pool_spec(k_cache)
    return shard_map(
        local, mesh=mesh,
        in_specs=(P(None, None, "tp", None), kv_spec, kv_spec,
                  P(None, None), P(None), P(None)),
        out_specs=P(None, None, "tp", None),
        check_vma=False,
    )(q, k_cache, v_cache, page_table, ctx_lens, total_lens)
