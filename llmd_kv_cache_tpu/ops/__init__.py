"""TPU compute ops: paged attention and KV-page gather/scatter.

The XLA-level (jnp) implementations are the portable reference path (they
run on the CPU backend in tests); Pallas kernels provide the TPU fast path.
"""

from .paged_attention import paged_attention
from .kv_pages import (
    gather_kv_pages,
    scatter_kv_pages,
    scatter_kv_pages_ragged,
)

__all__ = [
    "paged_attention",
    "gather_kv_pages",
    "scatter_kv_pages",
    "scatter_kv_pages_ragged",
]
