"""Paged attention over a page-table-addressed KV cache.

XLA-level implementation: gathers each sequence's pages into logical order
and runs masked multi-head attention. Shapes are static; ragged sequence
lengths are handled with masks, so the whole op stays inside one jit and
XLA tiles the matmuls onto the MXU. Works for both prefill (seq > 1,
queries appended after a cached prefix) and decode (seq == 1).

A Pallas flash-decode kernel (``pallas_paged_attention``, double-buffered
page DMA + online softmax) is the TPU fast path for long contexts where
materializing the gathered KV would be HBM-wasteful.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .kv_pages import gather_kv_pages

_NEG_INF = -1e30


def paged_attention(
    q: jax.Array,  # [batch, q_seq, q_heads, head_dim]
    k_cache: jax.Array,  # [num_pages, kv_heads, page_size, head_dim]
    v_cache: jax.Array,  # [num_pages, kv_heads, page_size, head_dim]
    page_table: jax.Array,  # [batch, pages_per_seq] int32
    q_positions: jax.Array,  # [batch, q_seq] logical position of each query
    total_lens: jax.Array,  # [batch] total tokens (context + new) per sequence
    scale: float | None = None,
    sliding_window: int | None = None,
    attention_sinks: int | None = None,
    tail_k: jax.Array | None = None,  # [batch, T, kv_heads, head_dim]
    tail_v: jax.Array | None = None,
    tail_lens: jax.Array | None = None,  # [batch] valid tail tokens
) -> jax.Array:
    """Causal attention of new queries against paged KV (cached + new).

    The KV for the new tokens must already be scattered into the cache.
    ``sliding_window=W`` restricts each query to the last W keys (SWA
    layers of hybrid-attention models); ``attention_sinks=S`` additionally
    keeps the first S positions attendable past the window (StreamingLLM
    sinks — the reference's ``sink_full_attention`` spec kind,
    ``events.go:40``). Returns ``[batch, q_seq, q_heads, head_dim]`` in
    the query dtype.

    ``tail_k/tail_v/tail_lens`` append a dense burst-local KV tail after
    the paged keys: tail slot ``j`` sits at logical position
    ``total_lens + j`` and is attendable while ``j < tail_lens``. This is
    the fused-decode-burst path — the paged cache stays a read-only scan
    constant (XLA copies large scan carries every iteration, see
    ``forward_decode_steps``) and only the ≤steps-token tail is carried.
    With a tail, ``total_lens`` is the FROZEN base length and queries sit
    at ``q_positions ≥ total_lens``.
    """
    batch, q_seq, q_heads, head_dim = q.shape
    _, kv_heads, page_size, _ = k_cache.shape
    if scale is None:
        scale = head_dim ** -0.5
    group = q_heads // kv_heads

    k = gather_kv_pages(k_cache, page_table)  # [b, kv_len, kvh, hd]
    v = gather_kv_pages(v_cache, page_table)
    if k.dtype.itemsize == 1:
        # Quantized (fp8 e4m3) cache: the HBM read above moved 1-byte
        # elements — the bandwidth/capacity win — and the upcast to the
        # query dtype happens on the gathered values so the matmuls run
        # the same bf16 MXU path as an unquantized cache. (bf16 caches
        # deliberately skip this: see the numerics note below.)
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)
    kv_len = k.shape[1]

    k_pos = jnp.broadcast_to(jnp.arange(kv_len)[None], (batch, kv_len))
    k_valid = k_pos < total_lens[:, None]
    if tail_k is not None:
        t = tail_k.shape[1]
        k = jnp.concatenate([k, tail_k.astype(k.dtype)], axis=1)
        v = jnp.concatenate([v, tail_v.astype(v.dtype)], axis=1)
        tail_pos = total_lens[:, None] + jnp.arange(t)[None]
        k_pos = jnp.concatenate([k_pos, tail_pos], axis=1)
        k_valid = jnp.concatenate(
            [k_valid, jnp.arange(t)[None] < tail_lens[:, None]], axis=1)

    # MXU-friendly numerics: feed the matmuls bf16 operands with fp32
    # accumulation (bf16·bf16 products are exact in fp32) instead of
    # upcasting K/V first — upcasting halves MXU throughput and doubles
    # the HBM traffic of the gathered KV. Softmax stays fp32. GQA is a
    # grouped einsum over [b, q, kvh, group, hd] so KV heads are never
    # materialized ``group``× (the repeat would burn HBM bandwidth).
    qg = q.reshape(batch, q_seq, kv_heads, group, head_dim)
    # [b, kvh, group, q_seq, kv_len(+T)], fp32
    logits = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32
    ) * scale

    k_pos = k_pos[:, None, None, None, :]
    q_pos = q_positions[:, None, None, :, None]
    mask = (k_pos <= q_pos) & k_valid[:, None, None, None, :]
    if sliding_window is not None:
        in_window = q_pos - k_pos < sliding_window
        if attention_sinks:
            in_window = in_window | (k_pos < attention_sinks)
        mask = mask & in_window
    logits = jnp.where(mask, logits, _NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhgqk,bkhd->bqhgd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(batch, q_seq, q_heads, head_dim).astype(q.dtype)
