"""Storage-side disk-space manager for the offload store.

Counterpart of reference ``kv_connectors/pvc_evictor``: keeps the shared
KV store below a capacity threshold by deleting the least-recently-used
block files, publishing ``BlockRemoved`` storage events so the global
index stays consistent.
"""

from .config import EvictorConfig
from .evictor import Evictor

__all__ = ["EvictorConfig", "Evictor"]
