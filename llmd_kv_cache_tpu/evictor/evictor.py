"""Evictor pipeline: activator → crawlers → deleter → folder cleaner.

Counterpart of reference ``pvc_evictor/evictor.py`` + ``processes/``:

- **activator**: polls disk usage; deletion switches ON above
  ``cleanup_threshold`` and OFF below ``target_threshold`` (hysteresis)
- **crawlers**: partition the 16 first-hex buckets across workers
  (``crawler.py:49-79`` equivalent) and stream candidate files oldest-atime
  first, skipping anything accessed within ``min_idle_seconds``
- **deleter**: deletes in batches, parses ``(block_hash, group)`` from the
  path via ``FileMapper.parse_block_path`` and publishes ``BlockRemoved``
  storage events so the global index drops the storage-tier entries
- **folder cleaner**: prunes empty bucket directories with a TTL guard
  against racing writers

The reference runs these as N+2 supervised OS processes; here they are
supervised daemon threads (the work is I/O-bound and the index events are
the shared state, so threads suffice; the supervisor restarts dead
workers the same way, ``evictor.py:135+``). Each stage is also exposed as
a plain function for deterministic tests.
"""

from __future__ import annotations

import heapq
import os
import shutil
import threading
import time
from typing import Callable, Iterator, Optional, Sequence

from ..utils.lockdep import new_lock
from ..events.publisher import StorageEventPublisher
from ..offload.file_mapper import FileMapper
from ..utils.logging import get_logger
from .config import EvictorConfig

logger = get_logger("evictor")

_HEX = "0123456789abcdef"


def disk_usage_fraction(path: str) -> float:
    usage = shutil.disk_usage(path)
    return usage.used / usage.total if usage.total else 0.0


def crawler_buckets(crawler_idx: int, num_crawlers: int) -> list[str]:
    """Partition the 16 top-level hex buckets across crawlers."""
    return [h for i, h in enumerate(_HEX) if i % num_crawlers == crawler_idx]


def crawl_candidates(
    store_root: str,
    buckets: Sequence[str],
    min_idle_seconds: float,
    now: Optional[float] = None,
    max_candidates: Optional[int] = None,
) -> Iterator[tuple[float, str]]:
    """Yield ``(atime, path)`` of deletable files in the crawler's buckets,
    oldest first.

    Deletable = block files (``*.bin``) idle for at least
    ``min_idle_seconds``, plus orphaned atomic-write temp files
    (``*.tmp.*`` from crashed writers) past the same idle window — those
    would otherwise leak disk forever since no reader ever touches them.

    ``max_candidates`` bounds memory with a heap (O(N log K) instead of a
    full sort): each pass only deletes a few batches, so collecting every
    candidate on a multi-million-file store would hammer metadata for
    nothing.
    """
    now = now if now is not None else time.time()
    candidates: list[tuple[float, str]] = []
    try:
        model_dirs = [
            os.path.join(store_root, d)
            for d in os.listdir(store_root)
            if os.path.isdir(os.path.join(store_root, d))
        ]
    except FileNotFoundError:
        return

    def scan() -> Iterator[tuple[float, str]]:
        for model_dir in model_dirs:
            for bucket in buckets:
                # top-level bucket dirs are 3 hex chars; partition by char 0
                try:
                    tops = [
                        t for t in os.listdir(model_dir)
                        if len(t) == 3 and t[0] == bucket
                    ]
                except FileNotFoundError:
                    continue
                for top in tops:
                    top_path = os.path.join(model_dir, top)
                    for dirpath, _dirs, files in os.walk(top_path):
                        for name in files:
                            # Live blocks, orphaned tmp files from crashed
                            # writers, and checksum-quarantined files (held
                            # briefly for post-mortem, reclaimed by the same
                            # age sweep) are all evictable.
                            if not (name.endswith(".bin") or ".tmp." in name
                                    or name.endswith(".quarantine")):
                                continue
                            path = os.path.join(dirpath, name)
                            try:
                                atime = os.stat(path).st_atime
                            except FileNotFoundError:
                                continue
                            if now - atime < min_idle_seconds:
                                continue
                            yield (atime, path)

    if max_candidates is not None:
        candidates = heapq.nsmallest(max_candidates, scan())
    else:
        candidates = sorted(scan())
    yield from candidates


def delete_batch(
    paths: Sequence[str],
    publish: Optional[Callable[[list[int]], None]] = None,
) -> int:
    """Delete files and publish BlockRemoved for the parsed hashes.

    Returns the number of files actually deleted.
    """
    deleted = 0
    hashes: list[int] = []
    for path in paths:
        try:
            os.unlink(path)
            deleted += 1
        except FileNotFoundError:
            continue
        parsed = FileMapper.parse_block_path(path)
        if parsed is not None:
            hashes.append(parsed[0])
    if publish is not None and hashes:
        publish(hashes)
    return deleted


def clean_empty_dirs(store_root: str, ttl_seconds: float,
                     now: Optional[float] = None) -> int:
    """Remove empty bucket dirs whose mtime is older than the TTL.

    The TTL guards against deleting a directory a writer just created but
    hasn't populated yet (reference ``folder_cleaner.py``).
    """
    now = now if now is not None else time.time()
    removed = 0
    for dirpath, dirs, files in os.walk(store_root, topdown=False):
        if dirpath == store_root or files or dirs:
            continue
        try:
            if now - os.stat(dirpath).st_mtime < ttl_seconds:
                continue
            os.rmdir(dirpath)
            removed += 1
        except OSError:
            continue
    return removed


class Evictor:
    """Supervised evictor pipeline."""

    def __init__(
        self,
        cfg: EvictorConfig,
        publisher: Optional[StorageEventPublisher] = None,
        usage_fn: Optional[Callable[[], float]] = None,
    ):
        self.cfg = cfg
        self._usage_fn = usage_fn or (lambda: disk_usage_fraction(cfg.store_root))
        self._publisher = publisher
        if publisher is None and cfg.storage_events_endpoint:
            self._publisher = StorageEventPublisher(
                cfg.storage_events_endpoint, cfg.model_name, bind=False
            )
        self.deletion_active = threading.Event()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.total_deleted = 0
        self._deleted_lock = new_lock()

    # -- single-pass stages (deterministic, used by tests and the loops) --

    def activator_pass(self) -> bool:
        """Update the deletion flag from disk usage; returns the flag."""
        usage = self._usage_fn()
        if usage >= self.cfg.cleanup_threshold:
            if not self.deletion_active.is_set():
                logger.info("disk usage %.1f%% >= %.1f%%: deletion ON",
                            100 * usage, 100 * self.cfg.cleanup_threshold)
            self.deletion_active.set()
        elif usage <= self.cfg.target_threshold:
            if self.deletion_active.is_set():
                logger.info("disk usage %.1f%% <= %.1f%%: deletion OFF",
                            100 * usage, 100 * self.cfg.target_threshold)
            self.deletion_active.clear()
        return self.deletion_active.is_set()

    def crawl_and_delete_pass(self, crawler_idx: int = 0,
                              max_batches: int = 1) -> int:
        """One crawler pass: delete up to ``max_batches`` batches of the
        oldest idle files in this crawler's buckets. Stops early when the
        activator turns deletion off. Returns files deleted."""
        if not self.deletion_active.is_set():
            return 0
        buckets = crawler_buckets(crawler_idx, self.cfg.num_crawlers)
        publish = (
            self._publisher.publish_block_removed if self._publisher else None
        )
        deleted = 0
        batch: list[str] = []
        batches_done = 0
        for _atime, path in crawl_candidates(
            self.cfg.store_root, buckets, self.cfg.min_idle_seconds,
            max_candidates=self.cfg.delete_batch_size * max_batches,
        ):
            if not self.deletion_active.is_set():
                break
            batch.append(path)
            if len(batch) >= self.cfg.delete_batch_size:
                deleted += delete_batch(batch, publish)
                batch = []
                batches_done += 1
                self.activator_pass()  # re-check usage between batches
                if batches_done >= max_batches:
                    break
        if batch and self.deletion_active.is_set():
            deleted += delete_batch(batch, publish)
        with self._deleted_lock:
            self.total_deleted += deleted
        return deleted

    def folder_cleaner_pass(self) -> int:
        return clean_empty_dirs(self.cfg.store_root, self.cfg.empty_dir_ttl_s)

    # -- supervised loops --

    def start(self) -> None:
        """Start the supervised worker threads (idempotent)."""
        if self._threads:
            return
        self._stop.clear()

        def supervise(name: str, loop_fn: Callable[[], None]):
            def run():
                while not self._stop.is_set():
                    try:
                        loop_fn()
                    except Exception:
                        logger.exception("%s crashed; restarting", name)
                        self._stop.wait(1.0)
            t = threading.Thread(target=run, name=f"evictor-{name}", daemon=True)
            t.start()
            self._threads.append(t)

        def activator_loop():
            self.activator_pass()
            self._stop.wait(self.cfg.poll_interval_s)

        def make_crawler_loop(idx: int):
            def crawler_loop():
                if self.deletion_active.is_set():
                    self.crawl_and_delete_pass(idx, max_batches=4)
                self._stop.wait(self.cfg.poll_interval_s)
            return crawler_loop

        def cleaner_loop():
            self.folder_cleaner_pass()
            self._stop.wait(max(self.cfg.poll_interval_s * 6, 30.0))

        supervise("activator", activator_loop)
        for i in range(self.cfg.num_crawlers):
            supervise(f"crawler-{i}", make_crawler_loop(i))
        supervise("folder-cleaner", cleaner_loop)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads.clear()
