"""Evictor configuration (env-var driven, like reference ``config.py:26-73``)."""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass
class EvictorConfig:
    # Root of the offload store (the FileMapper root).
    store_root: str = "/mnt/kv-store"
    # Deletion turns ON when disk usage crosses this fraction...
    cleanup_threshold: float = 0.85
    # ...and OFF once usage falls below this fraction (hysteresis,
    # reference config.py:32-34).
    target_threshold: float = 0.70
    # Files accessed within this window are never deleted (seconds).
    min_idle_seconds: float = 3600.0
    # Crawler parallelism: the 16 hex buckets are partitioned across crawlers.
    num_crawlers: int = 2
    # Files deleted per batch (reference deleter.py batch of 100).
    delete_batch_size: int = 100
    # Disk-usage poll interval.
    poll_interval_s: float = 5.0
    # Empty bucket directories older than this are removed (folder cleaner).
    empty_dir_ttl_s: float = 600.0
    # ZMQ endpoint for storage BlockRemoved events (None disables).
    storage_events_endpoint: str | None = None
    # Model name used in the event topic.
    model_name: str = "unknown"

    @classmethod
    def from_env(cls, env: dict | None = None) -> "EvictorConfig":
        e = env if env is not None else os.environ
        return cls(
            store_root=e.get("KVTPU_EVICTOR_STORE_ROOT", "/mnt/kv-store"),
            cleanup_threshold=float(e.get("KVTPU_EVICTOR_CLEANUP_THRESHOLD", "0.85")),
            target_threshold=float(e.get("KVTPU_EVICTOR_TARGET_THRESHOLD", "0.70")),
            min_idle_seconds=float(e.get("KVTPU_EVICTOR_MIN_IDLE_SECONDS", "3600")),
            num_crawlers=int(e.get("KVTPU_EVICTOR_NUM_CRAWLERS", "2")),
            delete_batch_size=int(e.get("KVTPU_EVICTOR_DELETE_BATCH_SIZE", "100")),
            poll_interval_s=float(e.get("KVTPU_EVICTOR_POLL_INTERVAL_S", "5")),
            empty_dir_ttl_s=float(e.get("KVTPU_EVICTOR_EMPTY_DIR_TTL_S", "600")),
            storage_events_endpoint=e.get("KVTPU_EVICTOR_EVENTS_ENDPOINT"),
            model_name=e.get("KVTPU_EVICTOR_MODEL_NAME", "unknown"),
        )
