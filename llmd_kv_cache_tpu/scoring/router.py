"""KV-cache-aware router: the scheduler-side scoring plugin.

Counterpart of reference ``examples/kv_cache_aware_scorer`` (the EPP
``PrecisePrefixCacheScorer``): wraps the Indexer into a routing decision
and, crucially, inserts **speculative** index entries for the blocks the
routed request will create — so identical prompts arriving before the
engine's KV events confirm residency still converge onto the same pod
instead of fanning out. Speculative entries carry a TTL and are dropped if
unconfirmed (the real event stream overwrites them with authoritative
entries; both coexist as distinct PodEntry values).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional, Sequence

from ..utils.lockdep import new_lock
from ..core.keys import TIER_TPU_HBM, KeyType, PodEntry
from ..utils.logging import get_logger
from .indexer import Indexer

logger = get_logger("scoring.router")


@dataclass
class RouterConfig:
    # Weight multiplier applied to the KV score when combining with external
    # signals (the reference's "precise" config uses weight 3.0 inside EPP).
    kv_score_weight: float = 3.0
    # Speculative entries expire after this many seconds if no KV event
    # confirmed the blocks.
    speculative_ttl_s: float = 30.0
    # Minimum score advantage (in blocks) required to override round-robin.
    min_score_to_prefer: float = 1.0


class KVAwareRouter:
    """Routes requests to the pod holding the longest cached prefix."""

    def __init__(self, indexer: Indexer, pods: Sequence[str],
                 config: Optional[RouterConfig] = None):
        self.indexer = indexer
        self.pods = list(pods)
        self.config = config or RouterConfig()
        self._rr_counter = 0
        self._lock = new_lock()
        # (pod, block-key) → expiry of outstanding speculative inserts;
        # keyed per block (not per chain) so overlapping prompts sharing a
        # prefix refresh the shared keys' TTLs — a shorter prompt's expiry
        # must never evict keys still covered by a longer prompt's record.
        self._speculative: dict[tuple[str, int], float] = {}

    def set_pods(self, pods: Sequence[str]) -> None:
        with self._lock:
            self.pods = list(pods)

    def route(self, tokens: Sequence[int], model_name: str) -> str:
        """Pick the pod for a request and record speculative residency."""
        if not self.pods:
            # Must fail loudly: an empty filter set means "all pods" to the
            # index, which would happily route to a drained pod.
            raise RuntimeError("no candidate pods")
        self._expire_speculative()
        # Hash once; reuse the key chain for lookup, scoring, and the
        # speculative insert.
        keys = self.indexer.compute_block_keys(tokens, model_name)
        scores: dict[str, float] = {}
        if keys:
            key_to_pods = self.indexer.kv_block_index.lookup(keys, set(self.pods))
            scores = self.indexer.scorer.score(keys, key_to_pods)
        pod = self._pick(scores)
        self._add_speculative(keys, pod)
        return pod

    def scores(self, tokens: Sequence[int], model_name: str) -> dict[str, float]:
        """Weighted scores for external scheduler composition."""
        raw = self.indexer.score_tokens(tokens, model_name, set(self.pods))
        return {p: s * self.config.kv_score_weight for p, s in raw.items()}

    def _pick(self, scores: dict[str, float]) -> str:
        with self._lock:
            if scores:
                best_pod, best = max(scores.items(), key=lambda kv: kv[1])
                if best >= self.config.min_score_to_prefer:
                    return best_pod
            if not self.pods:
                raise RuntimeError("no candidate pods")
            pod = self.pods[self._rr_counter % len(self.pods)]
            self._rr_counter += 1
            return pod

    def _add_speculative(self, keys: Sequence[int], pod: str) -> None:
        if not keys:
            return
        entry = PodEntry(pod_identifier=pod, device_tier=TIER_TPU_HBM,
                         speculative=True)
        try:
            self.indexer.kv_block_index.add(None, list(keys), [entry])
        except Exception:
            logger.exception("speculative add failed")
            return
        expiry = time.monotonic() + self.config.speculative_ttl_s
        with self._lock:
            for key in keys:
                self._speculative[(pod, key)] = expiry

    def _expire_speculative(self) -> None:
        now = time.monotonic()
        with self._lock:
            expired = [k for k, expiry in self._speculative.items() if expiry <= now]
            for k in expired:
                del self._speculative[k]
        for pod, key in expired:
            entry = PodEntry(pod_identifier=pod, device_tier=TIER_TPU_HBM,
                             speculative=True)
            try:
                self.indexer.kv_block_index.evict(key, KeyType.REQUEST, [entry])
            except Exception:
                logger.debug("speculative evict failed for key %d", key)
