"""KV-block scorers.

Counterpart of reference ``pkg/kvcache/kvblock_scorer.go`` +
``pkg/kvcache/backend.go``. Scores candidate pods by the longest consecutive
run of cached blocks from block 0, weighting each hit by the device tier it
lives on. Default tier weights are TPU-first: ``tpu-hbm`` (1.0) is the fast
tier (the reference's ``gpu``), ``cpu`` host memory 0.8, shared storage 0.5.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..core.keys import (
    TIER_CPU,
    TIER_OBJECT_STORE,
    TIER_SHARED_STORAGE,
    TIER_TPU_HBM,
    BlockHash,
    PodEntry,
)

LONGEST_PREFIX_MATCH = "LongestPrefix"


@dataclass
class KVCacheBackendConfig:
    """A device tier/medium and its scoring weight (``backend.go:19-24``)."""

    name: str
    weight: float


def default_backend_configs() -> list[KVCacheBackendConfig]:
    """TPU-first tier weights.

    ``gpu`` kept as an alias tier for interop with engines that emit GPU
    mediums (weight equal to HBM).
    """
    return [
        KVCacheBackendConfig(name=TIER_TPU_HBM, weight=1.0),
        KVCacheBackendConfig(name="gpu", weight=1.0),
        KVCacheBackendConfig(name=TIER_CPU, weight=0.8),
        KVCacheBackendConfig(name=TIER_SHARED_STORAGE, weight=0.5),
        KVCacheBackendConfig(name=TIER_OBJECT_STORE, weight=0.5),
    ]


@dataclass
class KVBlockScorerConfig:
    scoring_strategy: str = LONGEST_PREFIX_MATCH
    backend_configs: list[KVCacheBackendConfig] = field(default_factory=default_backend_configs)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "KVBlockScorerConfig":
        if not d:
            return cls()
        backends = d.get("backendConfigs", d.get("backend_configs"))
        cfg = cls(scoring_strategy=d.get("scoringStrategy", d.get("scoring_strategy", LONGEST_PREFIX_MATCH)))
        if backends:
            cfg.backend_configs = [
                KVCacheBackendConfig(name=b["name"], weight=float(b["weight"])) for b in backends
            ]
        return cfg


class LongestPrefixScorer:
    """Longest-consecutive-prefix scorer with tier weighting.

    Mirrors reference ``kvblock_scorer.go:106-154``: per key, each pod takes
    the max weight across its tiers holding the block; pods drop out of the
    active set at their first gap; scores accumulate while active.
    """

    def __init__(self, medium_weights: Optional[dict[str, float]] = None):
        self.medium_weights = (
            medium_weights
            if medium_weights is not None
            else {b.name: b.weight for b in default_backend_configs()}
        )

    @property
    def strategy(self) -> str:
        return LONGEST_PREFIX_MATCH

    def _fill_max_weights(
        self, entries: Sequence[PodEntry]
    ) -> dict[str, float]:
        weights: dict[str, float] = {}
        for entry in entries:
            w = self.medium_weights.get(entry.device_tier, 1.0)
            cur = weights.get(entry.pod_identifier)
            if cur is None or w > cur:
                weights[entry.pod_identifier] = w
        return weights

    def score(
        self,
        keys: Sequence[BlockHash],
        key_to_pods: dict[BlockHash, list[PodEntry]],
    ) -> dict[str, float]:
        if not keys:
            return {}

        cur_weights = self._fill_max_weights(key_to_pods.get(keys[0], []))
        pod_scores = dict(cur_weights)
        active = set(cur_weights)

        for key in keys[1:]:
            if not active:
                break
            cur_weights = self._fill_max_weights(key_to_pods.get(key, []))
            for pod in list(active):
                w = cur_weights.get(pod)
                if w is not None:
                    pod_scores[pod] += w
                else:
                    active.discard(pod)

        return pod_scores


def create_scorer(config: Optional[KVBlockScorerConfig] = None) -> LongestPrefixScorer:
    config = config or KVBlockScorerConfig()
    if config.scoring_strategy != LONGEST_PREFIX_MATCH:
        raise ValueError(f"unsupported scoring strategy: {config.scoring_strategy}")
    return LongestPrefixScorer({b.name: b.weight for b in config.backend_configs})
