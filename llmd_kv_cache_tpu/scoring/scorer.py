"""KV-block scorers.

Counterpart of reference ``pkg/kvcache/kvblock_scorer.go`` +
``pkg/kvcache/backend.go``. Scores candidate pods by the longest consecutive
run of cached blocks from block 0, weighting each hit by the device tier it
lives on. Default tier weights are TPU-first: ``tpu-hbm`` (1.0) is the fast
tier (the reference's ``gpu``), ``cpu`` host memory 0.8, shared storage 0.5.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..core.hma import SPEC_SINK_FULL
from ..core.keys import (
    TIER_CPU,
    TIER_OBJECT_STORE,
    TIER_SHARED_STORAGE,
    TIER_TPU_HBM,
    BlockHash,
    PodEntry,
)

LONGEST_PREFIX_MATCH = "LongestPrefix"
HYBRID_AWARE = "HybridAware"


@dataclass
class KVCacheBackendConfig:
    """A device tier/medium and its scoring weight (``backend.go:19-24``)."""

    name: str
    weight: float


def default_backend_configs() -> list[KVCacheBackendConfig]:
    """TPU-first tier weights.

    ``gpu`` kept as an alias tier for interop with engines that emit GPU
    mediums (weight equal to HBM).
    """
    return [
        KVCacheBackendConfig(name=TIER_TPU_HBM, weight=1.0),
        KVCacheBackendConfig(name="gpu", weight=1.0),
        KVCacheBackendConfig(name=TIER_CPU, weight=0.8),
        KVCacheBackendConfig(name=TIER_SHARED_STORAGE, weight=0.5),
        KVCacheBackendConfig(name=TIER_OBJECT_STORE, weight=0.5),
    ]


@dataclass
class KVBlockScorerConfig:
    scoring_strategy: str = LONGEST_PREFIX_MATCH
    backend_configs: list[KVCacheBackendConfig] = field(default_factory=default_backend_configs)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "KVBlockScorerConfig":
        if not d:
            return cls()
        backends = d.get("backendConfigs", d.get("backend_configs"))
        cfg = cls(scoring_strategy=d.get("scoringStrategy", d.get("scoring_strategy", LONGEST_PREFIX_MATCH)))
        if backends:
            cfg.backend_configs = [
                KVCacheBackendConfig(name=b["name"], weight=float(b["weight"])) for b in backends
            ]
        return cfg


class LongestPrefixScorer:
    """Longest-consecutive-prefix scorer with tier weighting.

    Mirrors reference ``kvblock_scorer.go:106-154``: per key, each pod takes
    the max weight across its tiers holding the block; pods drop out of the
    active set at their first gap; scores accumulate while active.
    """

    def __init__(self, medium_weights: Optional[dict[str, float]] = None):
        self.medium_weights = (
            medium_weights
            if medium_weights is not None
            else {b.name: b.weight for b in default_backend_configs()}
        )
        # Optional PodLivenessTracker (resilience.liveness), attached by
        # the host (Indexer.attach_liveness): demotes pods whose event
        # stream — and therefore whose index view — has gone stale.
        self.liveness = None

    @property
    def strategy(self) -> str:
        return LONGEST_PREFIX_MATCH

    def _apply_liveness(self, scores: dict[str, float]) -> dict[str, float]:
        """Degraded-mode weighting: multiply each pod's score by its
        liveness factor (1 fresh → 0 dead) and drop zeroed pods. With every
        pod stale, scores empty out and the router falls back to
        round-robin — degrading toward fairness, never toward a corpse."""
        if self.liveness is None or not scores:
            return scores
        out = {}
        for pod, s in scores.items():
            f = self.liveness.factor(pod)
            if s * f > 0.0:
                out[pod] = s * f
        return out

    def _fill_max_weights(
        self, entries: Sequence[PodEntry]
    ) -> dict[str, float]:
        weights: dict[str, float] = {}
        for entry in entries:
            w = self.medium_weights.get(entry.device_tier, 1.0)
            cur = weights.get(entry.pod_identifier)
            if cur is None or w > cur:
                weights[entry.pod_identifier] = w
        return weights

    def score(
        self,
        keys: Sequence[BlockHash],
        key_to_pods: dict[BlockHash, list[PodEntry]],
    ) -> dict[str, float]:
        if not keys:
            return {}

        cur_weights = self._fill_max_weights(key_to_pods.get(keys[0], []))
        pod_scores = dict(cur_weights)
        active = set(cur_weights)

        for key in keys[1:]:
            if not active:
                break
            cur_weights = self._fill_max_weights(key_to_pods.get(key, []))
            for pod in list(active):
                w = cur_weights.get(pod)
                if w is not None:
                    pod_scores[pod] += w
                else:
                    active.discard(pod)

        return self._apply_liveness(pod_scores)


class HybridAwareScorer(LongestPrefixScorer):
    """Sliding-window-aware scoring (the reference's documented WIP,
    ``docs/architecture.md`` "Hybrid attention").

    For a full-attention pod, a cached prefix of L blocks saves L blocks of
    prefill — the longest-prefix rule. For a pod whose cache group is
    ``sliding_window`` with window W, resuming at length L only requires
    the blocks covering the last W tokens of L: **early blocks falling out
    of the window don't matter**, so the usable prefix is the deepest L
    whose trailing window of blocks is fully present, and the saving is
    capped at the window itself.

    Per pod: score = tier-weighted count of present blocks inside the best
    usable trailing window (full-attention pods fall back to the exact
    longest-prefix accumulation). Requires the pool's ``GroupCatalog`` to
    know the pod's group spec; unknown pods score as full attention.
    """

    def __init__(self, medium_weights=None, group_catalog=None,
                 block_size_tokens: int = 16):
        super().__init__(medium_weights)
        self.group_catalog = group_catalog
        self.block_size_tokens = block_size_tokens

    def _window_blocks(self, pod: str, group_idx) -> Optional[int]:
        """A group's sliding window in blocks; None = full attention.

        ``sink_full_attention`` groups also return None: their mask keeps
        the sink prefix attendable past the window, and the producing
        engines resume by longest prefix over a non-reclaiming pool — so
        a trailing window without block 0 is worthless there, and valuing
        it like plain SWA would systematically overscore sink pods that
        lost early blocks to eviction.
        """
        if group_idx is None or self.group_catalog is None:
            return None
        meta = self.group_catalog.get(pod, group_idx)
        if (meta is not None and meta.sliding_window_size
                and meta.kind != SPEC_SINK_FULL):
            return max(1, -(-meta.sliding_window_size // self.block_size_tokens))
        return None

    @staticmethod
    def _merge_max(dst: dict[int, float], src: dict[int, float]) -> None:
        """Fold ``src`` into ``dst`` keeping the per-index max weight."""
        for i, w in src.items():
            if w > dst.get(i, 0.0):
                dst[i] = w

    @staticmethod
    def _prefix_value(blocks: dict[int, float]) -> float:
        """Longest-consecutive-from-0 weighted value."""
        total = 0.0
        i = 0
        while i in blocks:
            total += blocks[i]
            i += 1
        return total

    def _window_value(self, blocks: dict[int, float], n_keys: int,
                      wb: int) -> float:
        """Deepest resume length whose trailing min(wb, L) blocks are all
        present; value = their weights (capped at the window).

        Single forward pass (O(n_keys)): track the consecutive-present run
        ending at each position plus a weight prefix sum; end L is usable
        iff the run covers min(wb, L) blocks.
        """
        run = 0
        best_end = 0
        prefix = [0.0] * (n_keys + 1)
        for i in range(n_keys):
            w = blocks.get(i)
            prefix[i + 1] = prefix[i] + (w or 0.0)
            run = run + 1 if w is not None else 0
            if run >= min(wb, i + 1):
                best_end = i + 1
        if best_end == 0:
            return 0.0
        start = max(0, best_end - wb)
        return prefix[best_end] - prefix[start]

    def score(self, keys, key_to_pods):
        if not keys:
            return {}
        if self.group_catalog is None:
            return super().score(keys, key_to_pods)

        # One pass: per-pod {group: presence map} for tagged entries, plus
        # a per-pod map for untagged entries (tokenless tier updates carry
        # no group; they assert residency for every group).
        tagged: dict[str, dict[int, dict[int, float]]] = {}
        untagged: dict[str, dict[int, float]] = {}
        for i, key in enumerate(keys):
            for e in key_to_pods.get(key, []):
                w = self.medium_weights.get(e.device_tier, 1.0)
                slot = (
                    tagged.setdefault(e.pod_identifier, {}).setdefault(e.group_idx, {})
                    if e.has_group
                    else untagged.setdefault(e.pod_identifier, {})
                )
                if w > slot.get(i, 0.0):
                    slot[i] = w

        # A resume needs EVERY group of the pod to supply its share: score
        # = min across all cataloged groups (full-attention: longest
        # prefix; SWA: trailing window) — conservative for hybrid pods. A
        # cataloged group with no residency zeroes the pod. Pods with no
        # cataloged groups score by the plain longest-prefix rule; tagged
        # entries whose group the catalog doesn't know (e.g. a persistent
        # index surviving an indexer restart, before a new BlockStored
        # re-teaches the spec) still assert residency and fold into that
        # full-attention fallback instead of being dropped.
        pods = set(tagged) | set(untagged)
        scores: dict[str, float] = {}
        for pod in pods:
            pod_groups = tagged.get(pod, {})
            cataloged = self.group_catalog.groups(pod)
            extra = dict(untagged.get(pod, {}))
            for g, presence in pod_groups.items():
                if g not in cataloged:
                    self._merge_max(extra, presence)
            if not cataloged:
                scores[pod] = self._prefix_value(extra) if extra else 0.0
                continue
            value = None
            for g in cataloged:
                blocks = dict(extra)
                self._merge_max(blocks, pod_groups.get(g, {}))
                wb = self._window_blocks(pod, g)
                if wb is None:
                    gv = self._prefix_value(blocks)
                else:
                    gv = self._window_value(blocks, len(keys), wb)
                value = gv if value is None else min(value, gv)
            scores[pod] = value or 0.0
        return self._apply_liveness(
            {p: v for p, v in scores.items() if v > 0.0})

    @property
    def strategy(self) -> str:
        return HYBRID_AWARE


def create_scorer(config: Optional[KVBlockScorerConfig] = None,
                  block_size_tokens: int = 16):
    config = config or KVBlockScorerConfig()
    weights = {b.name: b.weight for b in config.backend_configs}
    if config.scoring_strategy == LONGEST_PREFIX_MATCH:
        return LongestPrefixScorer(weights)
    if config.scoring_strategy == HYBRID_AWARE:
        # The GroupCatalog is wired post-construction by the host
        # (Indexer.attach_group_catalog), since it lives on the event pool.
        return HybridAwareScorer(weights, None, block_size_tokens)
    raise ValueError(f"unsupported scoring strategy: {config.scoring_strategy}")
