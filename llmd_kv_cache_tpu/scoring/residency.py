"""Transferred-prefix residency for role-aware decode-pod scoring.

Disaggregated serving (offload.handoff) moves a request's prefill KV to a
decode pod through the shared transfer tier. While that transfer is in
flight the global index knows nothing yet — the storage tier's tokenless
BlockStored only lands when a store completes, and it names no *decode*
pod at all. This tracker is the scorer-side view of that gap: the handoff
coordinator registers which blocks are headed to (in flight) or already
pullable by (landed) each decode pod, and ``bonus`` converts that into a
consecutive-from-0 prefix score the indexer adds for ``role="decode"``
requests — landed blocks at full weight, in-flight blocks discounted
(they may still shed or fail), the whole bonus scaled by the transfer
tier's restore-latency discount when the index exposes one
(``index.cost_aware.CostAwareMemoryIndex.tier_discount``).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

from ..utils.lockdep import new_lock


class ResidencyTracker:
    """Per-decode-pod transferred-block residency, with in-flight discount.

    Claims are released when the handoff settles
    (:meth:`release_pod_claims`): from then on the storage tier's own
    BlockStored advertisements carry the residency signal through the
    normal index path, and keeping stale claims would double-count it.
    """

    def __init__(self, landed_weight: float = 1.0,
                 in_flight_discount: float = 0.5):
        self.landed_weight = landed_weight
        self.in_flight_discount = in_flight_discount
        self._mu = new_lock()
        # block hash → {decode pod → landed?}
        self._claims: dict[int, dict[str, bool]] = {}
        self._pod_blocks: dict[str, set[int]] = {}
        # Optional transfer-tier restore-latency discount, wired by
        # Indexer.attach_residency when the index has a tier_discount
        # hook. Applied only here — i.e. only when residency scoring is
        # on — never to the base prefix scores.
        self.tier_discount_fn: Optional[Callable[[], float]] = None

    # -- coordinator-side updates ---------------------------------------

    def on_transfer_started(self, pod: str,
                            block_hashes: Sequence[int]) -> None:
        with self._mu:
            blocks = self._pod_blocks.setdefault(pod, set())
            for h in block_hashes:
                self._claims.setdefault(h, {}).setdefault(pod, False)
                blocks.add(h)

    def on_landed(self, pod: str, block_hashes: Sequence[int]) -> None:
        with self._mu:
            blocks = self._pod_blocks.setdefault(pod, set())
            for h in block_hashes:
                self._claims.setdefault(h, {})[pod] = True
                blocks.add(h)

    def on_released(self, pod: str, block_hashes: Sequence[int]) -> None:
        """Drop specific claims (a shed/failed chunk never lands)."""
        with self._mu:
            blocks = self._pod_blocks.get(pod)
            for h in block_hashes:
                pods = self._claims.get(h)
                if pods is not None:
                    pods.pop(pod, None)
                    if not pods:
                        del self._claims[h]
                if blocks is not None:
                    blocks.discard(h)

    def release_pod_claims(self, pod: str) -> None:
        """Drop every claim for ``pod`` (its handoff settled)."""
        with self._mu:
            blocks = self._pod_blocks.pop(pod, set())
            for h in blocks:
                pods = self._claims.get(h)
                if pods is not None:
                    pods.pop(pod, None)
                    if not pods:
                        del self._claims[h]

    # -- scorer-side read ------------------------------------------------

    def bonus(
        self,
        block_keys: Sequence[int],
        pod_identifiers: Optional[set[str]] = None,
    ) -> dict[str, float]:
        """Consecutive-from-0 residency bonus per decode pod.

        Same accumulation rule as the longest-prefix scorer: a pod's
        bonus runs along the key chain until its first unclaimed block.
        """
        with self._mu:
            pods = [
                p for p in self._pod_blocks
                if self._pod_blocks[p]
                and (not pod_identifiers or p in pod_identifiers)
            ]
            if not pods:
                return {}
            claims = {k: dict(self._claims.get(k, {})) for k in block_keys}
        discount = self.discount()
        out: dict[str, float] = {}
        for pod in pods:
            total = 0.0
            for key in block_keys:
                landed = claims.get(key, {}).get(pod)
                if landed is None:
                    break
                total += (self.landed_weight if landed
                          else self.in_flight_discount)
            if total > 0.0:
                out[pod] = total * discount
        return out

    def claim_rows(
        self,
        block_keys: Sequence[int],
        pod_identifiers: Optional[set[str]] = None,
    ) -> list[tuple[str, int, bool]]:
        """Sparse ``(pod, key_index, landed)`` rows for the native fold-in.

        The same claim view :meth:`bonus` walks, flattened positionally so
        ``kvidx_score_chunked`` can run the consecutive-from-0 walk inside
        the index lock: a pod with no row at index 0 accumulates nothing,
        exactly like ``bonus``'s break-at-first-unclaimed rule. Returns
        an empty list when no (allowed) pod holds claims — callers skip
        the native residency arguments entirely then.
        """
        with self._mu:
            pods = {
                p for p in self._pod_blocks
                if self._pod_blocks[p]
                and (not pod_identifiers or p in pod_identifiers)
            }
            if not pods:
                return []
            rows: list[tuple[str, int, bool]] = []
            for idx, key in enumerate(block_keys):
                claimants = self._claims.get(key)
                if not claimants:
                    continue
                for pod, landed in claimants.items():
                    if pod in pods:
                        rows.append((pod, idx, landed))
            return rows

    def discount(self) -> float:
        """Evaluate the transfer-tier restore-latency discount (1.0 when
        absent or failing) — the scalar :meth:`bonus` multiplies in."""
        if self.tier_discount_fn is None:
            return 1.0
        try:
            return float(self.tier_discount_fn())
        except Exception:  # pragma: no cover  # lint: allow-swallow
            return 1.0

    def debug(self) -> dict:
        with self._mu:
            return {
                "claimed_blocks": len(self._claims),
                "pods": {p: len(b) for p, b in self._pod_blocks.items()},
            }
