"""Indexer orchestrator: tokens → block keys → index lookup → pod scores.

Counterpart of reference ``pkg/kvcache/indexer.go``. This is the scheduler
hot path (``ScoreTokens``, ``indexer.go:238-303``): embedded in an endpoint
picker, it answers "which pods hold the longest cached prefix for these
tokens, and how much of it" in a single in-process call.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..utils.lockdep import new_lock
from ..core.extra_keys import BlockExtraFeatures
from ..core.keys import BlockHash
from ..core.token_processor import ChunkedTokenDatabase, TokenProcessorConfig
from ..index.base import Index, IndexConfig, create_index
from ..telemetry import flight_recorder, tracer
from ..telemetry.flight_recorder import KIND_SCORE
from ..utils.logging import get_logger
from .scorer import KVBlockScorerConfig, LongestPrefixScorer, create_scorer

logger = get_logger("indexer")


class CacheEfficiencyLedger:
    """Per-pod cache-efficiency attribution (ISSUE 3).

    Answers "which pods actually earn their cache footprint?" after the
    fact: per pod, how often it appeared in score results (and won), how
    much weighted prefix score it accumulated, and how many blocks the
    event stream stored/evicted on it. Misses are global per lookup —
    a block no pod holds cannot be attributed to any one of them.

    One small lock-guarded dict update per score call / ingest event;
    cheap enough to stay always-on (bench.py budgets the whole
    observability overhead at < 1% of the score hot path).
    """

    def __init__(self):
        self._mu = new_lock()
        self._pods: dict[str, dict] = {}
        self.score_calls = 0
        self.lookup_blocks = 0
        self.lookup_hit_blocks = 0

    def _pod(self, pod: str) -> dict:
        st = self._pods.get(pod)
        if st is None:
            st = self._pods[pod] = {
                "appearances": 0,
                "wins": 0,
                "score_total": 0.0,
                "stored_blocks": 0,
                "evicted_blocks": 0,
                "clears": 0,
            }
        return st

    def record_score(
        self, scores: dict[str, float], total_blocks: int, hit_blocks: int
    ) -> None:
        winner = max(scores, key=scores.get) if scores else None
        with self._mu:
            self.score_calls += 1
            self.lookup_blocks += total_blocks
            self.lookup_hit_blocks += hit_blocks
            for pod, score in scores.items():
                st = self._pod(pod)
                st["appearances"] += 1
                st["score_total"] += score
            if winner is not None:
                self._pods[winner]["wins"] += 1

    def record_store(self, pod: str, blocks: int) -> None:
        with self._mu:
            self._pod(pod)["stored_blocks"] += blocks

    def record_evict(self, pod: str, blocks: int) -> None:
        with self._mu:
            self._pod(pod)["evicted_blocks"] += blocks

    def record_clear(self, pod: str) -> None:
        with self._mu:
            self._pod(pod)["clears"] += 1

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "score_calls": self.score_calls,
                "lookup_blocks": self.lookup_blocks,
                "lookup_hit_blocks": self.lookup_hit_blocks,
                "lookup_miss_blocks": self.lookup_blocks - self.lookup_hit_blocks,
                "pods": {pod: dict(st) for pod, st in self._pods.items()},
            }


@dataclass
class IndexerConfig:
    """Top-level config (reference ``indexer.go:39-61``): nested configs with
    nil-tolerance — every field defaults sensibly when omitted."""

    token_processor_config: TokenProcessorConfig = field(default_factory=TokenProcessorConfig)
    index_config: Optional[IndexConfig] = None
    scorer_config: KVBlockScorerConfig = field(default_factory=KVBlockScorerConfig)
    # Early-exit chunked lookup: score_tokens looks blocks up in chunks of
    # this many keys and stops at the first chunk that breaks the prefix
    # chain (0 disables — single full lookup / full native scan). Only
    # engaged for the LongestPrefix strategy; hybrid-aware scoring values
    # blocks at any position.
    lookup_chunk_size: int = 128
    # Observability endpoints (services.admin): 0 = disabled (default).
    # metrics_port serves /metrics + /healthz only; admin_port additionally
    # exposes the /debug/* surfaces (flight recorder, lag, ledger).
    metrics_port: int = 0
    admin_port: int = 0
    # Bind address for both endpoints; localhost by default because the
    # debug surface exposes pod names and score internals.
    admin_host: str = "127.0.0.1"
    # Crash-tolerant state (recovery/): None or snapshot_dir="" disables
    # snapshots, journaled warm restart, and the warmup readiness gate.
    recovery_config: Optional["RecoveryConfig"] = None
    # Sharded control plane (cluster/): None disables. With shardId set,
    # a service built from this config ingests as one shard replica
    # (ShardFilterIndex); routers use the same config to fan out.
    cluster_config: Optional["ClusterConfig"] = None
    # Fleet observability (telemetry/fleet.py): None disables span export;
    # with spanExport set, the admin endpoint serves /debug/spans for the
    # fleet telemetry collector.
    fleet_telemetry: Optional["FleetTelemetryConfig"] = None
    # Adaptive overload shedding at the scoring service (resilience.
    # shedding.CoDelShedder): when serving delay stays above this target
    # for a full interval, low-priority requests shed and normal-priority
    # ones brown out (residency fold-in skipped, response flagged
    # degraded). 0 disables (the default).
    shed_target_delay_s: float = 0.0
    shed_interval_s: float = 0.1

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "IndexerConfig":
        if not d:
            return cls()
        chunk = d.get("lookupChunkSize", d.get("lookup_chunk_size"))
        cfg = cls(
            token_processor_config=TokenProcessorConfig.from_dict(
                d.get("tokenProcessorConfig", d.get("token_processor_config"))
            ),
            scorer_config=KVBlockScorerConfig.from_dict(
                d.get("kvBlockScorerConfig", d.get("scorer_config"))
            ),
            lookup_chunk_size=128 if chunk is None else chunk,
            metrics_port=d.get("metricsPort", d.get("metrics_port", 0)) or 0,
            admin_port=d.get("adminPort", d.get("admin_port", 0)) or 0,
            admin_host=d.get("adminHost", d.get("admin_host", "127.0.0.1"))
            or "127.0.0.1",
            shed_target_delay_s=d.get(
                "shedTargetDelayS", d.get("shed_target_delay_s", 0.0)
            ) or 0.0,
            shed_interval_s=d.get(
                "shedIntervalS", d.get("shed_interval_s", 0.1)
            ) or 0.1,
        )
        recovery_dict = d.get("recoveryConfig", d.get("recovery_config"))
        if recovery_dict:
            from ..recovery.config import RecoveryConfig

            cfg.recovery_config = RecoveryConfig.from_dict(recovery_dict)
        cluster_dict = d.get("clusterConfig", d.get("cluster_config"))
        if cluster_dict:
            from ..cluster.config import ClusterConfig

            cfg.cluster_config = ClusterConfig.from_dict(cluster_dict)
        fleet_dict = d.get("fleetTelemetry", d.get("fleet_telemetry"))
        if fleet_dict:
            from ..telemetry.fleet import FleetTelemetryConfig

            cfg.fleet_telemetry = FleetTelemetryConfig.from_dict(fleet_dict)
        index_dict = d.get("kvBlockIndexConfig", d.get("index_config"))
        if index_dict:
            from ..index.cost_aware import CostAwareMemoryIndexConfig
            from ..index.in_memory import InMemoryIndexConfig

            # Valkey is wire-compatible with Redis (reference index.go:74-79
            # keeps a distinct config slot); fold it into the redis backend
            # with the valkey backend type.
            redis_cfg = index_dict.get("redisConfig")
            valkey_cfg = index_dict.get("valkeyConfig")
            if redis_cfg is None and valkey_cfg is not None:
                redis_cfg = dict(valkey_cfg)
                redis_cfg.setdefault("backendType", "valkey")

            native_dict = index_dict.get("nativeConfig")
            native_cfg = None
            if native_dict is not None:
                from ..index.native import NativeIndexConfig

                native_cfg = NativeIndexConfig.from_dict(native_dict)

            cfg.index_config = IndexConfig(
                in_memory_config=InMemoryIndexConfig.from_dict(index_dict.get("inMemoryConfig"))
                if index_dict.get("inMemoryConfig") is not None
                else None,
                cost_aware_memory_config=CostAwareMemoryIndexConfig.from_dict(
                    index_dict.get("costAwareMemoryConfig")
                )
                if index_dict.get("costAwareMemoryConfig") is not None
                else None,
                redis_config=redis_cfg,
                native_config=native_cfg,
                enable_metrics=index_dict.get("enableMetrics", False),
                enable_tracing=index_dict.get("enableTracing", False),
                metrics_logging_interval_s=index_dict.get("metricsLoggingInterval", 0.0),
            )
        return cfg


class Indexer:
    """KV-cache indexer: the library's main entry point."""

    def __init__(
        self,
        config: Optional[IndexerConfig] = None,
        index: Optional[Index] = None,
    ):
        self.config = config or IndexerConfig()
        self.token_processor = ChunkedTokenDatabase(self.config.token_processor_config)
        self.kv_block_index: Index = (
            index if index is not None else create_index(self.config.index_config)
        )
        self.scorer: LongestPrefixScorer = create_scorer(
            self.config.scorer_config,
            block_size_tokens=self.token_processor.block_size,
        )
        self._tracer = tracer()
        # Score-path latency histogram, exemplar-linked to the request's
        # trace so a slow bucket on /metrics points at a retained trace in
        # the fleet collector (docs/observability.md "Fleet observability").
        from ..metrics.collector import bucket_histogram

        self._score_latency = bucket_histogram(
            "kvcache_score_latency_seconds",
            "score_tokens wall time (keys to merged pod scores)",
            (1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5, 1.0),
        )
        # Fused native lookup+score fast path (NativeIndex only): the whole
        # scheduler hot loop stays in C++. Only the LongestPrefix strategy
        # has a native twin; other strategies take the Python path.
        from .scorer import LONGEST_PREFIX_MATCH

        self._native_score = (
            getattr(self.kv_block_index, "score", None)
            if self.scorer.strategy == LONGEST_PREFIX_MATCH
            else None
        )
        # Chunked native data plane (NativeIndex.score_chunked): early-exit
        # chunked lookup + residency fold-in in ONE ctypes crossing. When
        # present it supersedes the plain fused path below.
        self._native_score_chunked = (
            getattr(self.kv_block_index, "score_chunked", None)
            if self.scorer.strategy == LONGEST_PREFIX_MATCH
            else None
        )
        # Native data-plane counters (kvdiag `data_plane` section). Plain
        # int bumps on the hot path — diagnostic reads tolerate the odd
        # lost increment, a lock per score call would not pay for itself.
        self._dp_native_calls = 0
        self._dp_chunks = 0
        self._dp_early_exits = 0
        # Early-exit is only sound for consecutive-from-0 prefix scoring.
        self._early_exit = (
            self.config.lookup_chunk_size > 0
            and self.scorer.strategy == LONGEST_PREFIX_MATCH
        )
        # Last-published prefix-cache snapshot, so each score_tokens call
        # records only its delta into the Prometheus counters.
        self._pc_hit_snapshot = 0
        self._pc_miss_snapshot = 0
        # Per-pod cache-efficiency attribution + score-decision flight
        # records; the event pool shares this ledger (IndexerService wires
        # ``pool.ledger = indexer.ledger``) so evict/store attribution and
        # score attribution land in one place.
        self.ledger = CacheEfficiencyLedger()
        self._recorder = flight_recorder()
        # Residency-aware decode-pod scoring (prefill/decode
        # disaggregation): None until attach_residency wires a
        # scoring.residency.ResidencyTracker.
        self.residency = None
        # Working-set analytics: None until attach_workingset wires a
        # telemetry.workingset.WorkingSetTracker into the lookup path.
        self.workingset = None
        # Ground-truth audit: None until attach_audit wires a
        # telemetry.audit.AuditLog into the score path.
        self.audit = None

    def prefix_cache_stats(self) -> Optional[dict]:
        """Token-processor prefix-cache counters (None when disabled)."""
        return self.token_processor.prefix_cache_stats()

    def _record_prefix_cache_metrics(self) -> None:
        stats = self.token_processor.prefix_cache_stats()
        if stats is None:
            return
        hit_d = stats["hit_blocks"] - self._pc_hit_snapshot
        miss_d = stats["miss_blocks"] - self._pc_miss_snapshot
        self._pc_hit_snapshot = stats["hit_blocks"]
        self._pc_miss_snapshot = stats["miss_blocks"]
        try:
            from ..metrics.collector import record_prefix_cache_delta

            record_prefix_cache_delta(hit_d, miss_d)
        except Exception:  # pragma: no cover - metrics must never break scoring  # lint: allow-swallow
            pass

    def attach_group_catalog(self, group_catalog) -> None:
        """Wire the event pool's GroupCatalog into hybrid-aware scoring
        (no-op for the default strategy)."""
        if hasattr(self.scorer, "group_catalog"):
            self.scorer.group_catalog = group_catalog

    def attach_residency(self, tracker) -> None:
        """Wire a scoring.residency.ResidencyTracker into role-aware
        scoring: ``score_tokens(..., role="decode")`` adds each decode
        pod's transferred-prefix residency bonus (landed blocks full
        weight, in-flight discounted) on top of the base prefix score.
        When the index exposes the cost-aware tier-discount hook, the
        bonus is additionally scaled by the transfer tier's observed
        restore latency — the discount engages ONLY through this path.
        """
        self.residency = tracker
        fn = getattr(self.kv_block_index, "tier_discount", None)
        if fn is not None and tracker.tier_discount_fn is None:
            from ..core.keys import TIER_SHARED_STORAGE

            tracker.tier_discount_fn = lambda: fn(TIER_SHARED_STORAGE)

    def attach_workingset(self, tracker) -> None:
        """Wire a telemetry.workingset.WorkingSetTracker into the score
        path: every lookup's block keys feed the global "index" reuse
        stream (the fleet MRC), and — on the Python scoring path, where
        the per-key pod map exists — the cross-pod duplication estimator.
        Unsampled keys cost one dict hit each; the whole hook is gated
        <1% of score p50 by ``bench.py --workingset``."""
        self.workingset = tracker

    def attach_audit(self, audit_log) -> None:
        """Wire a telemetry.audit.AuditLog into the score path: every
        score decision records its prediction (per-pod scores, residency
        bonuses, and — when the log's ``staleness_fn`` is wired — the
        index staleness at score time) so the fleet collector can join
        it against the serving engine's realized outcome. One ring
        append per score call, gated <1% of score p50 by
        ``bench.py --audit``."""
        self.audit = audit_log

    def attach_liveness(self, liveness) -> None:
        """Wire the event pool's PodLivenessTracker into scoring: pods whose
        event stream went silent are demoted (stale index views overstate
        what the pod still holds) and eventually dropped, so routing decays
        toward the picker's round-robin fallback instead of pinning traffic
        on a corpse. Applied inside the Python scorers and post-hoc on the
        native fused fast path."""
        self.scorer.liveness = liveness

    def compute_block_keys(
        self,
        tokens: Sequence[int],
        model_name: str,
        extra_features: Optional[Sequence[Optional[BlockExtraFeatures]]] = None,
    ) -> list[BlockHash]:
        """Content-address tokens at the canonical block size
        (reference ``indexer.go:178-195``)."""
        return self.token_processor.tokens_to_kv_block_keys(
            0, tokens, model_name, extra_features
        )

    def score_tokens(
        self,
        tokens: Sequence[int],
        model_name: str,
        pod_identifiers: Optional[set[str]] = None,
        extra_features: Optional[Sequence[Optional[BlockExtraFeatures]]] = None,
        role: str = "",
        detail: Optional[dict] = None,
    ) -> dict[str, float]:
        """Score candidate pods for the given tokens
        (reference ``indexer.go:238-303``).

        Returns pod → tier-weighted consecutive-prefix score. Pods in
        ``pod_identifiers`` that hold nothing simply do not appear.

        ``role`` is the requesting scheduler's target pod role ("" =
        role-agnostic, the legacy behavior). For ``role="decode"`` with a
        residency tracker attached, each pod's transferred-prefix
        residency bonus is added on top; when ``detail`` is a dict, the
        per-pod bonus is written into ``detail["residency"]`` so service
        responses can surface it.
        """
        t0 = time.perf_counter()
        trace_ref: list = [None]
        try:
            return self._score_tokens_traced(
                tokens, model_name, pod_identifiers, extra_features,
                role, detail, trace_ref,
            )
        finally:
            tp = trace_ref[0]
            self._score_latency.observe(
                time.perf_counter() - t0,
                trace_id=None if tp is None else tp[3:35],
            )

    def _score_tokens_traced(
        self,
        tokens: Sequence[int],
        model_name: str,
        pod_identifiers: Optional[set[str]],
        extra_features: Optional[Sequence[Optional[BlockExtraFeatures]]],
        role: str,
        detail: Optional[dict],
        trace_ref: list,
    ) -> dict[str, float]:
        with self._tracer.span(
            "llm_d.kv_cache.score_tokens",
            model=model_name,
            token_count=len(tokens),
            pod_count=len(pod_identifiers) if pod_identifiers else 0,
            role=role,
        ) as span:
            # RecordedSpan exposes .traceparent; the no-op/otel spans do
            # not — no exemplar in those modes (documented caveat).
            trace_ref[0] = getattr(span, "traceparent", None)
            block_keys, keys_arr = (
                self.token_processor.tokens_to_kv_block_keys_with_array(
                    0, tokens, model_name, extra_features))
            span.set_attribute("block_count", len(block_keys))
            self._record_prefix_cache_metrics()
            if not block_keys:
                return {}

            # End-to-end deadline: the index lookup is the one blocking
            # site on this path — check the ambient budget before paying
            # for it (resilience.deadline; no-op without a deadline_scope).
            from ..resilience.deadline import current_deadline

            dl = current_deadline()
            if dl is not None:
                dl.check("scoring.index_lookup")

            if self._native_score_chunked is not None:
                return self._score_native_chunked(
                    keys_arr if keys_arr is not None else block_keys,
                    block_keys, model_name, pod_identifiers, role, detail,
                    span,
                )

            if self._native_score is not None:
                scores, hit_count = self._native_score(
                    keys_arr if keys_arr is not None else block_keys,
                    self.scorer.medium_weights, pod_identifiers,
                    early_exit=self._early_exit,
                )
                span.set_attribute("block_hit_count", hit_count)
                span.set_attribute("block_hit_ratio", hit_count / len(block_keys))
                # The C++ fused path knows nothing about liveness; apply the
                # same degraded-mode weighting the Python scorers use.
                scores = self.scorer._apply_liveness(scores)
                scores = self._apply_residency(
                    scores, block_keys, pod_identifiers, role, detail
                )
                self._record_score_decision(
                    model_name, len(block_keys), hit_count, scores,
                    traceparent=trace_ref[0],
                    residency=None if detail is None else detail.get("residency"),
                )
                if self.workingset is not None:
                    # The fused C++ path returns no per-key pod map; the
                    # reuse stream still gets every key (dup estimation
                    # just rides the Python path only).
                    self.workingset.record_index_lookup(
                        block_keys, None, hits=hit_count)
                return scores

            if self._early_exit:
                key_to_pods = self.kv_block_index.lookup_chunked(
                    block_keys, pod_identifiers,
                    chunk_size=self.config.lookup_chunk_size,
                )
            else:
                key_to_pods = self.kv_block_index.lookup(block_keys, pod_identifiers)
            span.set_attribute("block_hit_count", len(key_to_pods))
            span.set_attribute("block_hit_ratio", len(key_to_pods) / len(block_keys))

            scores = self.scorer.score(block_keys, key_to_pods)
            scores = self._apply_residency(
                scores, block_keys, pod_identifiers, role, detail
            )
            self._record_score_decision(
                model_name, len(block_keys), len(key_to_pods), scores,
                traceparent=trace_ref[0],
                residency=None if detail is None else detail.get("residency"),
            )
            if self.workingset is not None:
                self.workingset.record_index_lookup(
                    block_keys, key_to_pods, hits=len(key_to_pods))
            return scores

    def _score_native_chunked(
        self,
        keys,
        block_keys: Sequence[BlockHash],
        model_name: str,
        pod_identifiers: Optional[set[str]],
        role: str,
        detail: Optional[dict],
        span,
    ) -> dict[str, float]:
        """Native chunked data plane: one C++ pass runs the early-exit
        chunked lookup AND the residency-bonus walk; Python only folds —
        liveness weighting applies to the base scores first, then the
        bonus lands on top, exactly like the unfused path."""
        apply_res = role == "decode" and self.residency is not None
        claims = (
            self.residency.claim_rows(block_keys, pod_identifiers)
            if apply_res else []
        )
        scores, hit_count, res_bonus, dp = self._native_score_chunked(
            keys, self.scorer.medium_weights, pod_identifiers,
            chunk_size=(
                self.config.lookup_chunk_size if self._early_exit else 0
            ),
            claims=claims,
            landed_weight=(
                self.residency.landed_weight if apply_res else 1.0
            ),
            in_flight_discount=(
                self.residency.in_flight_discount if apply_res else 0.5
            ),
            tier_discount=(
                self.residency.discount() if claims else 1.0
            ),
        )
        span.set_attribute("block_hit_count", hit_count)
        span.set_attribute("block_hit_ratio", hit_count / len(block_keys))
        span.set_attribute("native_chunks", dp["chunks"])
        self._dp_native_calls += 1
        self._dp_chunks += dp["chunks"]
        self._dp_early_exits += dp["early_exited"]
        try:
            from ..metrics.collector import record_native_score

            record_native_score(dp["chunks"], dp["early_exited"])
        except Exception:  # pragma: no cover - metrics must never break scoring  # lint: allow-swallow
            pass
        scores = self.scorer._apply_liveness(scores)
        if res_bonus:
            for pod, b in res_bonus.items():
                scores[pod] = scores.get(pod, 0.0) + b
        if apply_res and detail is not None:
            detail["residency"] = res_bonus
        self._record_score_decision(
            model_name, len(block_keys), hit_count, scores,
            traceparent=getattr(span, "traceparent", None),
            residency=res_bonus if apply_res else None,
        )
        if self.workingset is not None:
            self.workingset.record_index_lookup(
                block_keys, None, hits=hit_count)
        return scores

    def data_plane_debug(self) -> dict:
        """Native score data-plane counters (kvdiag `data_plane`)."""
        return {
            "native_score_calls": self._dp_native_calls,
            "native_score_chunks": self._dp_chunks,
            "native_score_early_exits": self._dp_early_exits,
        }

    def _apply_residency(
        self,
        scores: dict[str, float],
        block_keys: Sequence[BlockHash],
        pod_identifiers: Optional[set[str]],
        role: str,
        detail: Optional[dict],
    ) -> dict[str, float]:
        """Add transferred-prefix residency bonuses for decode-role scoring.

        No-op (and zero-cost) unless the request targets decode pods and a
        residency tracker is attached; block keys are the same canonical
        chunk keys the index uses, so the tracker's claims line up 1:1.
        """
        if role != "decode" or self.residency is None:
            return scores
        bonus = self.residency.bonus(block_keys, pod_identifiers)
        if bonus:
            scores = dict(scores)
            for pod, b in bonus.items():
                scores[pod] = scores.get(pod, 0.0) + b
        if detail is not None:
            detail["residency"] = bonus
        return scores

    def _record_score_decision(
        self,
        model_name: str,
        total_blocks: int,
        hit_blocks: int,
        scores: dict[str, float],
        traceparent: Optional[str] = None,
        residency: Optional[dict] = None,
    ) -> None:
        """Ledger + flight-recorder + audit attribution for one score call.

        Kept lean — one ledger lock, one ring store (plus one audit ring
        append when an AuditLog is attached); ``scores`` is handed to the
        recorder and the audit log by reference (diagnostic surfaces,
        treated as frozen), so the hot-path cost is the dict literal
        below.
        """
        self.ledger.record_score(scores, total_blocks, hit_blocks)
        self._recorder.record(
            KIND_SCORE,
            {
                "model": model_name,
                "blocks": total_blocks,
                "hits": hit_blocks,
                "scores": scores,
            },
        )
        if self.audit is not None:
            winner = max(scores, key=scores.get) if scores else None
            self.audit.record_prediction(
                traceparent, model_name, total_blocks,
                scores[winner] if winner is not None else 0.0,
                scores, residency,
            )
