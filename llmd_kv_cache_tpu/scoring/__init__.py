"""Pod scoring and the indexer orchestrator."""

from .scorer import (
    KVCacheBackendConfig,
    KVBlockScorerConfig,
    LongestPrefixScorer,
    create_scorer,
    default_backend_configs,
)
from .indexer import Indexer, IndexerConfig

__all__ = [
    "KVCacheBackendConfig",
    "KVBlockScorerConfig",
    "LongestPrefixScorer",
    "create_scorer",
    "default_backend_configs",
    "Indexer",
    "IndexerConfig",
]
