"""SLO tracking with multi-window burn-rate alerts (fleet collector).

Implements the Google-SRE multiwindow, multi-burn-rate pattern over
threshold SLIs ("TTFT ≤ 2s", "scrape target reachable"): each
:class:`SLOTracker` ingests (good, bad) event counts, maintains sliding
windows, and converts the windowed bad fraction into a **burn rate** —
the multiple of the error budget being consumed:

    burn = bad_fraction(window) / (1 - objective)

Two alert severities:

- **fast_burn** — the short window AND its confirmation window both
  exceed ``fast_threshold`` (default 14.4× ≈ 2% of a 30-day budget in
  1h). The confirmation window suppresses blips; the short window makes
  reset fast once the incident ends.
- **slow_burn** — the long window exceeds ``slow_threshold`` (default
  6× ≈ 5% of a 30-day budget in 6h): a simmering regression.

Everything is clock-injectable and window lengths are constructor
arguments, so unit tests (and the toy-cluster chaos test) drive hours of
"budget history" in milliseconds. Alert state is exported both through
:meth:`debug_view` (the collector's ``/debug/slo``) and the
``kvtpu_slo_*`` metric families.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from prometheus_client import Counter, Gauge

from ..utils.lockdep import new_lock

SLO_BURN_RATE = Gauge(
    "kvtpu_slo_burn_rate",
    "Error-budget burn rate per SLO and window",
    ["slo", "window"],
)
SLO_ALERT_ACTIVE = Gauge(
    "kvtpu_slo_alert_active",
    "1 while the SLO's burn-rate alert is firing (by severity)",
    ["slo", "severity"],  # severity: fast_burn|slow_burn
)
SLO_ALERTS = Counter(
    "kvtpu_slo_alerts_total",
    "Burn-rate alert transitions (fired only, not clears)",
    ["slo", "severity"],
)
SLO_BUDGET_REMAINING = Gauge(
    "kvtpu_slo_error_budget_remaining",
    "Fraction of the error budget left over the slow window (1 = untouched)",
    ["slo"],
)


@dataclass(frozen=True)
class SLOConfig:
    """One service-level objective over a threshold SLI."""

    name: str
    objective: float = 0.99  # target good fraction, e.g. 0.99 = 1% budget
    description: str = ""
    # Window lengths in seconds: (short, confirmation) for the fast alert,
    # one long window for the slow alert. Defaults: 5m/1h fast, 6h slow.
    fast_windows: tuple = (300.0, 3600.0)
    slow_window: float = 21600.0
    fast_threshold: float = 14.4
    slow_threshold: float = 6.0

    @property
    def error_budget(self) -> float:
        return max(1.0 - self.objective, 1e-9)


@dataclass
class _AlertState:
    severity: Optional[str] = None  # None|fast_burn|slow_burn
    fired_at: Optional[float] = None
    fires: int = 0


class SLOTracker:
    """Sliding-window burn-rate evaluation for one SLO."""

    def __init__(
        self,
        config: SLOConfig,
        clock: Callable[[], float] = time.monotonic,
        on_edge: Optional[Callable[[dict], None]] = None,
    ):
        self.config = config
        self._clock = clock
        self._lock = new_lock()
        # (ts, good, bad) event-count samples, pruned past the slow window.
        self._samples: deque = deque()
        self._alert = _AlertState()
        # Called once per alert transition (fire/clear/severity change)
        # with an edge record — the registry's edge history feed, and the
        # fleet controller's trigger.
        self._on_edge = on_edge

    # -- ingestion ---------------------------------------------------------

    def record(self, good: int, bad: int) -> None:
        """Ingest an SLI observation batch (e.g. one scrape round's delta)."""
        if good <= 0 and bad <= 0:
            return
        now = self._clock()
        with self._lock:
            self._samples.append((now, max(good, 0), max(bad, 0)))
            self._prune(now)

    def _prune(self, now: float) -> None:
        horizon = now - max(self.config.slow_window, *self.config.fast_windows)
        while self._samples and self._samples[0][0] < horizon:
            self._samples.popleft()

    # -- readback ----------------------------------------------------------

    def _window_counts(self, window_s: float, now: float) -> tuple:
        lo = now - window_s
        good = bad = 0
        for ts, g, b in self._samples:
            if ts >= lo:
                good += g
                bad += b
        return good, bad

    def burn_rate(self, window_s: float) -> float:
        """bad_fraction(window) / error_budget; 0.0 with no traffic."""
        now = self._clock()
        with self._lock:
            self._prune(now)
            good, bad = self._window_counts(window_s, now)
        total = good + bad
        if total <= 0:
            return 0.0
        return (bad / total) / self.config.error_budget

    def evaluate(self) -> dict:
        """Re-evaluate alert state; returns :meth:`debug_view`.

        Fires/clears are edge-triggered: ``kvtpu_slo_alerts_total`` counts
        transitions into an alert, ``kvtpu_slo_alert_active`` mirrors the
        level. fast_burn outranks slow_burn when both conditions hold.
        """
        cfg = self.config
        short, confirm = cfg.fast_windows
        burns = {
            "short": self.burn_rate(short),
            "confirm": self.burn_rate(confirm),
            "slow": self.burn_rate(cfg.slow_window),
        }
        severity: Optional[str] = None
        if burns["short"] >= cfg.fast_threshold and burns["confirm"] >= cfg.fast_threshold:
            severity = "fast_burn"
        elif burns["slow"] >= cfg.slow_threshold:
            severity = "slow_burn"
        edge: Optional[dict] = None
        with self._lock:
            prev = self._alert.severity
            if severity != prev:
                if severity is not None:
                    self._alert.fires += 1
                    self._alert.fired_at = self._clock()
                    SLO_ALERTS.labels(cfg.name, severity).inc()
                self._alert.severity = severity
                if severity is None:
                    self._alert.fired_at = None
                edge = {
                    "ts": self._clock(),
                    "slo": cfg.name,
                    "edge": "fire" if severity is not None else "clear",
                    "severity": severity if severity is not None else prev,
                    "prev_severity": prev,
                    "burns": {k: round(v, 3) for k, v in burns.items()},
                }
        if edge is not None and self._on_edge is not None:
            # Outside the lock: the sink may re-enter tracker readbacks.
            self._on_edge(edge)
        for sev in ("fast_burn", "slow_burn"):
            SLO_ALERT_ACTIVE.labels(cfg.name, sev).set(1.0 if severity == sev else 0.0)
        SLO_BURN_RATE.labels(cfg.name, f"{int(short)}s").set(burns["short"])
        SLO_BURN_RATE.labels(cfg.name, f"{int(confirm)}s").set(burns["confirm"])
        SLO_BURN_RATE.labels(cfg.name, f"{int(cfg.slow_window)}s").set(burns["slow"])
        budget_left = max(0.0, 1.0 - self._budget_spent_fraction())
        SLO_BUDGET_REMAINING.labels(cfg.name).set(budget_left)
        return self.debug_view(burns=burns, budget_remaining=budget_left)

    def _budget_spent_fraction(self) -> float:
        """Fraction of the slow-window error budget already consumed."""
        now = self._clock()
        with self._lock:
            good, bad = self._window_counts(self.config.slow_window, now)
        total = good + bad
        if total <= 0:
            return 0.0
        return min(1.0, (bad / total) / self.config.error_budget)

    @property
    def alert_severity(self) -> Optional[str]:
        with self._lock:
            return self._alert.severity

    def debug_view(
        self, burns: Optional[dict] = None, budget_remaining: Optional[float] = None
    ) -> dict:
        cfg = self.config
        if burns is None:
            short, confirm = cfg.fast_windows
            burns = {
                "short": self.burn_rate(short),
                "confirm": self.burn_rate(confirm),
                "slow": self.burn_rate(cfg.slow_window),
            }
        if budget_remaining is None:
            budget_remaining = max(0.0, 1.0 - self._budget_spent_fraction())
        with self._lock:
            alert = {
                "severity": self._alert.severity,
                "fired_at": self._alert.fired_at,
                "fires": self._alert.fires,
            }
        return {
            "slo": cfg.name,
            "objective": cfg.objective,
            "description": cfg.description,
            "burn_rates": {
                f"{int(cfg.fast_windows[0])}s": round(burns["short"], 3),
                f"{int(cfg.fast_windows[1])}s": round(burns["confirm"], 3),
                f"{int(cfg.slow_window)}s": round(burns["slow"], 3),
            },
            "thresholds": {
                "fast": cfg.fast_threshold,
                "slow": cfg.slow_threshold,
            },
            "error_budget_remaining": round(budget_remaining, 4),
            "alert": alert,
        }


@dataclass
class SLORegistry:
    """The collector's set of trackers, evaluated as one unit.

    Besides level state (:meth:`debug_view`), the registry keeps a
    bounded, seq-stamped **edge history** of alert transitions so remote
    consumers — the fleet controller, ``/debug/slo?since=`` pullers —
    can react to each fire/clear exactly once, with the same cursor
    semantics as ``/debug/spans`` (``seq > since``; ``next_seq`` is the
    last stamped seq; ring-bounded with a drop counter).
    """

    clock: Callable[[], float] = time.monotonic
    trackers: Dict[str, SLOTracker] = field(default_factory=dict)
    max_edges: int = 512
    _edges: deque = field(default_factory=deque, repr=False)
    _edge_lock: threading.Lock = field(
        default_factory=lambda: new_lock(), repr=False)
    _edge_seq: int = field(default=0, repr=False)
    edges_dropped: int = 0

    def add(self, config: SLOConfig) -> SLOTracker:
        tracker = SLOTracker(
            config, clock=self.clock, on_edge=self._record_edge)
        self.trackers[config.name] = tracker
        return tracker

    def get(self, name: str) -> Optional[SLOTracker]:
        return self.trackers.get(name)

    def evaluate_all(self) -> dict:
        return {name: t.evaluate() for name, t in self.trackers.items()}

    def debug_view(self) -> dict:
        return {name: t.debug_view() for name, t in self.trackers.items()}

    # -- alert edge history ------------------------------------------------

    def _record_edge(self, edge: dict) -> None:
        with self._edge_lock:
            edge = dict(edge)
            edge["seq"] = self._edge_seq
            self._edge_seq += 1
            self._edges.append(edge)
            while len(self._edges) > self.max_edges:
                self._edges.popleft()
                self.edges_dropped += 1

    def export_edges_since(self, since: int = -1) -> dict:
        """Alert edges with ``seq > since`` plus the resume cursor
        (``/debug/slo?since=`` payload; non-destructive, per-puller)."""
        with self._edge_lock:
            edges = [dict(e) for e in self._edges if e["seq"] > since]
            return {
                "edges": edges,
                "next_seq": self._edge_seq - 1,
                "dropped": self.edges_dropped,
            }
