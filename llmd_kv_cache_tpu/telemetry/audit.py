"""Ground-truth audit plane: score-vs-reality calibration (ISSUE 18).

The indexer routes every prompt on a *predicted* residency view; nothing
before this module ever checked whether the prediction was true when the
request reached the engine. Two record streams close that loop:

- **predictions** — written by the scorer at score time (``Indexer.
  attach_audit``): the trace id, per-pod scores, residency bonuses, and
  the index staleness (PR 3 event-lag) at the moment of the decision.
- **outcomes** — written by the engine at prefill completion
  (``MiniEngine.attach_audit``): the realized prefix decomposition —
  blocks served straight from HBM, blocks restored from a lower tier,
  blocks recomputed — plus the :class:`ScoreFeedback` the request was
  routed on (``services.indexer_service.ScoreFeedback``).

Both land in a process-local :class:`AuditLog` ring exported over
``/debug/audit?since=SEQ`` with the same cursor semantics as
``/debug/spans`` (non-destructive per-puller cursor, drop counter). The
fleet :class:`~..services.telemetry_collector.TelemetryCollector` pulls
every target's ring and hands the records to an :class:`AuditJoiner`,
which joins predictions to outcomes per trace and emits:

- calibration curves (predicted vs realized hit blocks, exemplar-linked
  ``BucketHistogram`` families),
- per-pod mispredicted-block counters attributed by index staleness at
  score time (``stale`` vs ``fresh``),
- a **routing-regret** counterfactual: requests where another scored
  pod's *calibrated* prediction (its raw score scaled by that pod's
  realized/predicted EMA ratio) beat the chosen pod's realized hit.
  Other pods' realized residency is unobservable — the request only ran
  in one place — so regret is an estimate by construction; the EMA
  calibration keeps a consistently over-advertising pod from winning
  counterfactuals it would have lost (docs/observability.md, "Divergence
  triage").

Hot-path budget: one clock read + one atomic ring append per score
call (no lock — CPython's GIL makes ``deque.append`` and
``itertools.count`` atomic; the dict build and trace-id parse are
deferred to export time), gated < 1% of score p50 by
``bench.py --audit``.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from typing import Callable, Optional

from ..utils.lockdep import new_lock
from ..utils.logging import get_logger

logger = get_logger("telemetry.audit")

DEFAULT_CAPACITY = 2048
# The joiner holds unmatched predictions this long at most (bounded by
# count, too); a prediction whose request never reached an audited engine
# (old peer, shed, abort before prefill) must not leak.
DEFAULT_PENDING_LIMIT = 4096


def trace_id_of(traceparent: str) -> str:
    """32-hex trace id of a W3C traceparent ('' when absent/malformed)."""
    if not traceparent:
        return ""
    parts = traceparent.split("-")
    if len(parts) >= 2 and len(parts[1]) == 32:
        return parts[1]
    return ""


class AuditLog:
    """Fixed-capacity ring of prediction/outcome audit records.

    Same cursor shape as the span ring exporter: pullers read
    ``export_since(cursor)`` non-destructively and advance their own
    cursor from ``next_seq``; records older than the ring are counted in
    ``dropped`` so a slow puller knows what it missed. One ring serves
    any number of pullers.

    The write side is lock-free (the score hot path cannot afford a
    lock + eviction bookkeeping per call): sequence numbers come from an
    atomic ``itertools.count`` and the ring is a ``deque(maxlen=...)``
    whose append-with-evict is one atomic C call under the GIL. Drops
    are *derived* at export time (``max seq + 1 - retained``), so a
    contended writer never pays for drop accounting. Two benign races
    follow: a record whose append is preempted between seq issue and
    ring insert can land behind a faster writer (exports filter by seq,
    not position, so at worst one record is seen a pull late), and the
    derived drop count can transiently miscount in-flight appends.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 staleness_fn: Optional[Callable[[], float]] = None):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self._capacity = capacity
        self._seq = itertools.count()
        self._records: deque = deque(maxlen=capacity)
        # Export-side bookkeeping only (never touched by writers): the
        # kvtpu_audit_dropped_records_total delta emitted per export.
        self._mu = new_lock()
        self._reported_drops = 0
        # Index staleness at score time (events.pool.Pool.index_staleness_s
        # when service-wired); predictions stamp it so the collector can
        # attribute calibration error to event lag. The probe is cached
        # for _STALE_TTL_S: attribution only needs ~1 s resolution
        # (stale_threshold_s), and the pool probe is too expensive to pay
        # per score call.
        self.staleness_fn = staleness_fn
        self._stale_cache = 0.0
        self._stale_ts = -1.0

    _STALE_TTL_S = 0.05

    def _append(self, record) -> None:
        """Ring-append one record: an outcome dict, or a prediction
        tuple (hot path — inflated to a dict only at export). Atomic:
        the maxlen deque evicts the oldest entry in the same C call."""
        self._records.append((next(self._seq), record))

    def _snapshot(self) -> tuple:
        """(records copy, last issued seq, derived drop count).

        ``deque.copy`` is one C call (atomic under the GIL) — iterating
        the live deque while writers append would raise. Seqs are
        dense, so everything not retained was evicted.
        """
        snap = self._records.copy()
        if not snap:
            return snap, -1, 0
        last = max(seq for seq, _ in snap)
        return snap, last, max(last + 1 - len(snap), 0)

    @staticmethod
    def _inflate(seq: int, record) -> dict:
        """Export-time record shape (the deferred half of the hot path)."""
        if isinstance(record, dict):
            out = dict(record)
            out["seq"] = seq
            return out
        ts, traceparent, model, total, hit, scores, residency, stale = record
        return {
            "kind": "prediction",
            "ts": ts,
            "trace_id": trace_id_of(traceparent),
            "traceparent": traceparent,
            "model": model,
            "total_blocks": int(total),
            "hit_blocks": float(hit),
            "scores": scores,
            "residency": residency or {},
            "staleness_s": stale,
            "seq": seq,
        }

    def _flush_drop_metric(self, dropped: int) -> None:
        """Emit the kvtpu_audit_dropped_records_total delta since the
        last export — writers never pay for drop accounting, so the
        metric advances when a puller (or debug view) looks."""
        with self._mu:
            delta = dropped - self._reported_drops
            if delta <= 0:
                return
            self._reported_drops = dropped
        try:
            from ..metrics.collector import record_audit_dropped

            record_audit_dropped(delta)
        except Exception:  # pragma: no cover - metrics must never break audit  # lint: allow-swallow
            pass

    def record_prediction(
        self,
        traceparent: Optional[str],
        model: str,
        total_blocks: int,
        hit_blocks: float,
        scores: dict,
        residency: Optional[dict] = None,
    ) -> None:
        """One score decision, stamped with the index staleness *now*.

        ``hit_blocks`` is the winner's predicted prefix score in block
        units (tier-weighted, so fractional); ``scores`` is kept by
        reference — the score path treats the result dict as frozen once
        returned, same contract as the flight recorder.
        """
        ts = time.time()
        fn = self.staleness_fn
        if fn is None:
            staleness = 0.0
        elif ts - self._stale_ts >= self._STALE_TTL_S:
            try:
                staleness = float(fn() or 0.0)
            except Exception:  # staleness is enrichment, never score-fatal  # lint: allow-swallow
                staleness = 0.0
            self._stale_cache = staleness
            self._stale_ts = ts
        else:
            staleness = self._stale_cache
        # Predictions ride the score hot path, so the stored form is a
        # flat tuple: the dict build and trace-id parse are deferred to
        # export time (_inflate), keeping the per-score cost to one
        # timestamp + one atomic ring append (bench.py --audit gates it
        # <1% of score p50).
        self._records.append((next(self._seq), (
            ts, traceparent or "", model, total_blocks, hit_blocks,
            scores, residency, staleness)))

    def record_outcome(
        self,
        traceparent: Optional[str],
        request_id: str,
        pod: str,
        total_blocks: int,
        hbm_blocks: int,
        restored_blocks: int,
        recomputed_blocks: int,
        feedback=None,
    ) -> None:
        """The realized prefix outcome of one admitted request.

        ``feedback`` is the (duck-typed) ``ScoreFeedback`` the request
        was routed on, when the scheduler passed one to ``enqueue`` —
        its predicted scores ride along so the collector can join even
        when the prediction record itself was dropped from the scorer's
        ring.
        """
        realized = int(hbm_blocks) + int(restored_blocks)
        rec: dict = {
            "kind": "outcome",
            "ts": time.time(),
            "trace_id": trace_id_of(traceparent or ""),
            "traceparent": traceparent or "",
            "request_id": request_id,
            "pod": pod,
            "total_blocks": int(total_blocks),
            "hbm_blocks": int(hbm_blocks),
            "restored_blocks": int(restored_blocks),
            "recomputed_blocks": int(recomputed_blocks),
            "realized_blocks": realized,
        }
        if feedback is not None:
            rec["predicted_blocks"] = float(
                getattr(feedback, "predicted_blocks", 0.0) or 0.0)
            rec["scores"] = dict(getattr(feedback, "scores", {}) or {})
            rec["residency"] = dict(getattr(feedback, "residency", {}) or {})
            rec["staleness_s"] = float(
                getattr(feedback, "staleness_s", 0.0) or 0.0)
        self._append(rec)

    def export_since(self, since: int) -> dict:
        """Records with ``seq > since`` — the ``/debug/audit`` payload,
        cursor semantics identical to ``/debug/spans``."""
        snap, last, dropped = self._snapshot()
        self._flush_drop_metric(dropped)
        return {
            "records": [self._inflate(seq, r)
                        for seq, r in snap if seq > since],
            "next_seq": last,
            "dropped": dropped,
        }

    def debug_view(self) -> dict:
        snap, last, dropped = self._snapshot()
        self._flush_drop_metric(dropped)
        kinds: dict[str, int] = {}
        for _seq, r in snap:
            kind = r["kind"] if isinstance(r, dict) else "prediction"
            kinds[kind] = kinds.get(kind, 0) + 1
        return {
            "capacity": self._capacity,
            "retained": len(snap),
            "next_seq": last + 1,
            "dropped": dropped,
            "kinds": kinds,
        }


class _PodCalibration:
    """Per-pod running calibration state inside the joiner."""

    __slots__ = ("joins", "abs_error_blocks", "ratio_ema", "regrets",
                 "regret_blocks", "stale_mispredicted_blocks",
                 "fresh_mispredicted_blocks")

    def __init__(self):
        self.joins = 0
        self.abs_error_blocks = 0.0
        # realized/predicted EMA; 1.0 = perfectly calibrated. Only
        # observable for pods that actually served requests.
        self.ratio_ema = 1.0
        self.regrets = 0
        self.regret_blocks = 0.0
        self.stale_mispredicted_blocks = 0.0
        self.fresh_mispredicted_blocks = 0.0


class AuditJoiner:
    """Collector-side join of predictions to outcomes per trace.

    ``ingest(records)`` accepts one target's ``/debug/audit`` pull.
    Predictions park (bounded) until the matching outcome arrives from
    the serving engine's ring — usually a different target — then the
    pair is scored: calibration histograms, staleness-attributed
    mispredicted-block counters, and the routing-regret counterfactual.
    Outcomes that carry their own ``ScoreFeedback`` fields join even
    when the prediction record was never seen.
    """

    def __init__(
        self,
        stale_threshold_s: float = 1.0,
        regret_margin_blocks: float = 0.5,
        ema_alpha: float = 0.2,
        calibration_buckets: tuple = (0.5, 1, 2, 4, 8, 16, 32, 64, 128),
        pending_limit: int = DEFAULT_PENDING_LIMIT,
    ):
        self._mu = new_lock()
        self.stale_threshold_s = stale_threshold_s
        self.regret_margin_blocks = regret_margin_blocks
        self.ema_alpha = ema_alpha
        self._pending_limit = pending_limit
        # trace_id -> prediction record, insertion-ordered for eviction.
        self._pending: dict[str, dict] = {}
        self._pods: dict[str, _PodCalibration] = {}
        self.joined = 0
        self.unjoined_outcomes = 0
        self.abs_error_blocks = 0.0
        self.regrets = 0
        from ..metrics.collector import bucket_histogram

        self._predicted_hist = bucket_histogram(
            "kvtpu_audit_predicted_hit_blocks",
            "predicted prefix-hit length (blocks) of joined requests",
            calibration_buckets,
        )
        self._realized_hist = bucket_histogram(
            "kvtpu_audit_realized_hit_blocks",
            "realized prefix-hit length (blocks) of joined requests",
            calibration_buckets,
        )
        self._error_hist = bucket_histogram(
            "kvtpu_audit_calibration_error_blocks",
            "abs(predicted - realized) hit length (blocks) per joined request",
            calibration_buckets,
        )

    def _pod(self, pod: str) -> _PodCalibration:
        st = self._pods.get(pod)
        if st is None:
            st = self._pods[pod] = _PodCalibration()
        return st

    def ingest(self, records: list) -> int:
        """Feed one pull's records; returns the number of joins made."""
        joins = 0
        for rec in records or ():
            try:
                kind = rec.get("kind")
                if kind == "prediction":
                    self._ingest_prediction(rec)
                elif kind == "outcome":
                    joins += 1 if self._ingest_outcome(rec) else 0
            except Exception:  # one bad record must not poison the pull  # lint: allow-swallow
                logger.debug("audit join failed for record %r", rec,
                             exc_info=True)
        return joins

    def _ingest_prediction(self, rec: dict) -> None:
        tid = rec.get("trace_id") or ""
        if not tid:
            return
        with self._mu:
            self._pending[tid] = rec
            while len(self._pending) > self._pending_limit:
                self._pending.pop(next(iter(self._pending)))

    def _ingest_outcome(self, rec: dict) -> bool:
        tid = rec.get("trace_id") or ""
        with self._mu:
            pred = self._pending.pop(tid, None) if tid else None
        scores = dict(rec.get("scores") or {})
        staleness = rec.get("staleness_s")
        if pred is not None:
            scores = scores or dict(pred.get("scores") or {})
            if staleness is None:
                staleness = pred.get("staleness_s", 0.0)
        pod = rec.get("pod") or ""
        predicted = rec.get("predicted_blocks")
        if predicted is None:
            predicted = scores.get(pod) if pred is not None or scores else None
        if predicted is None:
            # No feedback and no parked prediction: nothing to calibrate
            # against (old peer, or the scorer ring dropped it).
            with self._mu:
                self.unjoined_outcomes += 1
            return False
        predicted = float(predicted)
        realized = float(rec.get("realized_blocks", 0))
        staleness = float(staleness or 0.0)
        tid_or_none = tid or None
        self._predicted_hist.observe(predicted, trace_id=tid_or_none)
        self._realized_hist.observe(realized, trace_id=tid_or_none)
        error = abs(predicted - realized)
        self._error_hist.observe(error, trace_id=tid_or_none)
        cause = "stale" if staleness > self.stale_threshold_s else "fresh"
        with self._mu:
            self.joined += 1
            self.abs_error_blocks += error
            st = self._pod(pod)
            st.joins += 1
            st.abs_error_blocks += error
            if cause == "stale":
                st.stale_mispredicted_blocks += error
            else:
                st.fresh_mispredicted_blocks += error
            if predicted > 0:
                a = self.ema_alpha
                st.ratio_ema += a * (realized / predicted - st.ratio_ema)
            regret_pod, regret_blocks = self._regret_locked(
                pod, realized, scores)
            if regret_pod is not None:
                self.regrets += 1
                st.regrets += 1
                st.regret_blocks += regret_blocks
        try:
            from ..metrics.collector import (record_audit_join,
                                             record_audit_regret)

            record_audit_join(pod, error, cause)
            if regret_pod is not None:
                record_audit_regret(pod, regret_blocks)
        except Exception:  # pragma: no cover - metrics never break the join  # lint: allow-swallow
            pass
        return True

    def _regret_locked(self, chosen: str, realized: float,
                       scores: dict) -> tuple[Optional[str], float]:
        """Best calibrated counterfactual among the losing pods, or None.

        A losing pod's estimated realized hit is its predicted score
        scaled by its own realized/predicted EMA (1.0 until observed) —
        an estimate, since the request only ran on ``chosen``.
        """
        best_pod, best_est = None, realized + self.regret_margin_blocks
        for pod, score in scores.items():
            if pod == chosen:
                continue
            st = self._pods.get(pod)
            est = float(score) * (st.ratio_ema if st is not None else 1.0)
            if est > best_est:
                best_pod, best_est = pod, est
        if best_pod is None:
            return None, 0.0
        return best_pod, best_est - realized

    def view(self) -> dict:
        """JSON-able calibration/regret summary (``/debug/audit`` provider
        on the collector, ``kvdiag --fleet`` audit section)."""
        with self._mu:
            joined = self.joined
            return {
                "joined": joined,
                "unjoined_outcomes": self.unjoined_outcomes,
                "pending_predictions": len(self._pending),
                "mean_abs_error_blocks": (
                    self.abs_error_blocks / joined if joined else 0.0),
                "regrets": self.regrets,
                "regret_rate": self.regrets / joined if joined else 0.0,
                "pods": {
                    pod: {
                        "joins": st.joins,
                        "mean_abs_error_blocks": (
                            st.abs_error_blocks / st.joins
                            if st.joins else 0.0),
                        "calibration_ratio": round(st.ratio_ema, 4),
                        "regrets": st.regrets,
                        "regret_blocks": round(st.regret_blocks, 3),
                        "stale_mispredicted_blocks": round(
                            st.stale_mispredicted_blocks, 3),
                        "fresh_mispredicted_blocks": round(
                            st.fresh_mispredicted_blocks, 3),
                    }
                    for pod, st in self._pods.items()
                },
            }
