"""Fleet metric rollup: parse per-pod expositions, merge type-correctly.

The collector scrapes every pod's ``/metrics`` (Prometheus text format) and
needs fleet-level answers — "TTFT p99 across all decode pods", "total
handoff chunks landed" — which requires merging *by metric type*:

- **counter** samples sum across pods (monotonic totals are additive);
- **gauge** samples report sum, max, and avg (occupancy gauges are
  additive, watermark gauges are not — the reader picks);
- **histogram** families merge bucket-by-bucket (cumulative counts and
  sums are additive when bucket bounds agree, which they do fleet-wide
  because every pod runs the same config), giving true fleet percentiles
  rather than an average-of-percentiles.

This is the package-internal sibling of ``hack/kvdiag.py``'s parser:
kvdiag stays stdlib-only and standalone by design, so the two do not
share code. Everything here is pure parsing/arithmetic — no network —
so the unit suite drives it with literal exposition text.
"""

from __future__ import annotations

import math
import re
from typing import Dict, Iterable, List, Optional, Tuple

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^ ]+)"
    r"(?:\s+(?P<ts>[0-9.+-eE]+))?$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_labels(raw: Optional[str]) -> Tuple[Tuple[str, str], ...]:
    if not raw:
        return ()
    out = []
    for key, val in _LABEL_RE.findall(raw):
        out.append((key, val.replace(r"\"", '"').replace(r"\\", "\\").replace(r"\n", "\n")))
    return tuple(sorted(out))


class MetricFamily:
    """One parsed family: name, TYPE, and ``{labelset: value}`` samples.

    Histogram families keep their ``_bucket``/``_sum``/``_count`` samples
    under the family name; :func:`merge_families` reassembles them.
    """

    __slots__ = ("name", "type", "samples")

    def __init__(self, name: str, type_: str = "untyped"):
        self.name = name
        self.type = type_
        # {(sample_suffix, labelset): value}
        self.samples: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"MetricFamily({self.name!r}, {self.type!r}, {len(self.samples)} samples)"


_HIST_SUFFIXES = ("_bucket", "_sum", "_count")
_COUNTER_SUFFIX = "_total"


def _family_name(sample_name: str, types: Dict[str, str]) -> Tuple[str, str]:
    """Map a sample name back to its family name + sample suffix."""
    for suffix in _HIST_SUFFIXES:
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            if base in types:
                return base, suffix
    if sample_name.endswith(_COUNTER_SUFFIX) and sample_name not in types:
        # prometheus_client registers Counter("x_total") under family "x".
        base = sample_name[: -len(_COUNTER_SUFFIX)]
        if base in types:
            return base, _COUNTER_SUFFIX
    return sample_name, ""


def parse_exposition(text: str) -> Dict[str, MetricFamily]:
    """Parse Prometheus text exposition into ``{family_name: MetricFamily}``.

    ``# TYPE`` lines are retained (this is the whole point — a merger must
    know counters from gauges); other comments are skipped; malformed lines
    are dropped rather than raised, because one bad pod must not take down
    the fleet view.
    """
    types: Dict[str, str] = {}
    families: Dict[str, MetricFamily] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3].strip()
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        fam_name, suffix = _family_name(m.group("name"), types)
        fam = families.get(fam_name)
        if fam is None:
            fam = MetricFamily(fam_name, types.get(fam_name, "untyped"))
            families[fam_name] = fam
        labels = _parse_labels(m.group("labels"))
        fam.samples[(suffix, labels)] = value
    return families


def merge_families(
    expositions: Iterable[Dict[str, MetricFamily]],
    conflicts: Optional[List[str]] = None,
) -> Dict[str, dict]:
    """Type-correct merge of several pods' parsed expositions.

    Returns ``{family: {"type": t, "samples": {labels: merged}}}`` where a
    merged counter/histogram sample is the cross-pod **sum** and a merged
    gauge sample is ``{"sum": s, "max": m, "avg": a, "pods": n}``. Histogram
    families come back as ``{"buckets": {le: cum}, "sum": s, "count": n}``
    per labelset so :func:`histogram_percentile` can read them directly.

    Pods that disagree on a family's TYPE line (a counter on one pod, a
    gauge on another — version skew, or a name collision) cannot be
    merged meaningfully: summing a gauge into a counter silently corrupts
    the fleet number. Such a family is dropped from the result with
    ``{"type": "conflict", "samples": {}}`` and its name appended to
    ``conflicts`` (when given) so callers can count/warn. An ``untyped``
    exposition never conflicts — it upgrades to the first typed peer.
    """
    merged: Dict[str, dict] = {}
    gauge_acc: Dict[Tuple[str, Tuple], List[float]] = {}
    for families in expositions:
        for name, fam in families.items():
            out = merged.setdefault(name, {"type": fam.type, "samples": {}})
            if out["type"] == "conflict":
                continue
            if out["type"] == "untyped" and fam.type != "untyped":
                out["type"] = fam.type
            elif fam.type not in ("untyped", out["type"]):
                out["type"] = "conflict"
                out["samples"] = {}
                for key in [k for k in gauge_acc if k[0] == name]:
                    del gauge_acc[key]
                if conflicts is not None:
                    conflicts.append(name)
                continue
            if fam.type == "histogram":
                for (suffix, labels), value in fam.samples.items():
                    if suffix == "_bucket":
                        le = dict(labels).get("le", "+Inf")
                        rest = tuple(kv for kv in labels if kv[0] != "le")
                        hist = out["samples"].setdefault(
                            rest, {"buckets": {}, "sum": 0.0, "count": 0.0}
                        )
                        hist["buckets"][le] = hist["buckets"].get(le, 0.0) + value
                    elif suffix in ("_sum", "_count"):
                        hist = out["samples"].setdefault(
                            labels, {"buckets": {}, "sum": 0.0, "count": 0.0}
                        )
                        hist[suffix[1:]] += value
            elif fam.type == "gauge":
                for (_suffix, labels), value in fam.samples.items():
                    gauge_acc.setdefault((name, labels), []).append(value)
            else:  # counter / untyped: additive; the _total suffix is
                # implied by the counter type, so keys are just labelsets.
                for (_suffix, labels), value in fam.samples.items():
                    out["samples"][labels] = out["samples"].get(labels, 0.0) + value
    for (name, labels), values in gauge_acc.items():
        merged[name]["samples"][labels] = {
            "sum": sum(values),
            "max": max(values),
            "avg": sum(values) / len(values),
            "pods": len(values),
        }
    return merged


def _le_key(le: str) -> float:
    return math.inf if le in ("+Inf", "inf") else float(le)


def histogram_percentile(hist: dict, q: float) -> float:
    """q-quantile (q in [0,1]) from a merged ``{"buckets": {le: cum}}``.

    Linear interpolation within the containing bucket, mirroring
    ``BucketHistogram.percentile`` so fleet and per-pod readbacks agree.
    Returns 0.0 for an empty histogram.
    """
    buckets = sorted(hist.get("buckets", {}).items(), key=lambda kv: _le_key(kv[0]))
    if not buckets:
        return 0.0
    total = buckets[-1][1]
    if total <= 0:
        return 0.0
    target = max(q, 0.0) * total
    prev_le, prev_cum = 0.0, 0.0
    finite = [_le_key(le) for le, _ in buckets if _le_key(le) != math.inf]
    top = finite[-1] if finite else 0.0
    for le, cum in buckets:
        bound = _le_key(le)
        if cum >= target:
            if bound == math.inf:
                return top
            in_bucket = cum - prev_cum
            if in_bucket <= 0:
                return bound
            frac = (target - prev_cum) / in_bucket
            return prev_le + (bound - prev_le) * min(max(frac, 0.0), 1.0)
        prev_le, prev_cum = (0.0 if bound == math.inf else bound), cum
    return top


def rollup_percentiles(
    merged: Dict[str, dict],
    family: str,
    quantiles: Tuple[float, ...] = (0.5, 0.9, 0.99),
) -> Dict[str, float]:
    """Fleet percentiles for one merged histogram family (empty if absent)."""
    fam = merged.get(family)
    if fam is None or fam["type"] != "histogram" or not fam["samples"]:
        return {}
    # Merge across labelsets too: the fleet answer ignores per-pod labels.
    combined: dict = {"buckets": {}, "sum": 0.0, "count": 0.0}
    for hist in fam["samples"].values():
        for le, cum in hist["buckets"].items():
            combined["buckets"][le] = combined["buckets"].get(le, 0.0) + cum
        combined["sum"] += hist["sum"]
        combined["count"] += hist["count"]
    out = {f"p{int(q * 100)}": histogram_percentile(combined, q) for q in quantiles}
    out["count"] = combined["count"]
    return out
