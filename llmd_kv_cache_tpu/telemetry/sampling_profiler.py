"""Always-on span-attributed CPU sampling profiler (stdlib only).

Fleet tracing (PR 10) answers *where a request waited*; this module
answers *where CPU time goes* — continuously and per pod, so ROADMAP
item 4's "Python-side score/ingest overhead" claim is measurable in
production instead of asserted from a one-off local profile.

Design:

- A daemon thread wakes at a configurable rate (default ~67 Hz, a prime
  period of ~15 ms so the sampler cannot alias with 10/100 ms pollers)
  and walks ``sys._current_frames()``.
- Each thread's stack is folded leaf-up into a **bounded trie**
  (``max_nodes`` interned frames; overflow collapses into a synthetic
  ``(trie-full)`` frame so memory is hard-capped), with per-thread
  sample counts kept alongside.
- Each sample is tagged with the sampled thread's **currently-active
  span name** read from the tracer's cross-thread registry
  (:func:`telemetry.tracing.active_span_names`) — span-attributed
  profiling: the fleet collector joins these tags against critical-path
  segments to report *dominant segment × dominant function*.
- Every ``window_s`` the live trie is sealed into a window and pushed
  onto an evict-oldest ring of ``max_windows``; windows export as
  Brendan-Gregg folded-stack text over ``/debug/pyprof?since=seq`` with
  the same cursor semantics as ``/debug/spans`` (non-destructive,
  monotonic seq, drop counting).
- The sampler self-measures: wall time spent inside each sampling pass
  is accumulated per window and exported as ``overhead_frac`` (plus the
  ``kvtpu_pyprof_*`` metric families), and ``bench.py --pyprof-overhead``
  gates that cost under 1% of the score-path p50.

Folded line format (one stack per line, count last)::

    span:<name-or-(nospan)>;thread:<name>;file.py:func;file.py:func 42

Root-first frames after the two tag frames; ``flamegraph.pl`` or
speedscope render it directly (docs/observability.md "Continuous
profiling").
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..utils.lockdep import new_lock
from ..utils.logging import get_logger
from .tracing import active_span_names, process_identity

logger = get_logger("telemetry.sampling_profiler")

# Tag frame for samples whose thread is not inside any span.
NO_SPAN = "(nospan)"
# Synthetic frame charged once the trie hits max_nodes.
TRIE_FULL = "(trie-full)"


class CaptureInProgress(RuntimeError):
    """A burst ``/debug/pyprof/capture`` is already running (→ HTTP 409)."""


MAX_CAPTURE_SECONDS = 60.0


def _metrics():
    """Lazy metric handles: the profiler must stay importable (and usable
    by kvdiag deep-debug) without the metrics stack."""
    try:
        from ..metrics.collector import (
            PYPROF_OVERHEAD_SECONDS,
            PYPROF_SAMPLES,
            PYPROF_TRIE_NODES,
            PYPROF_WINDOWS_DROPPED,
        )

        return (PYPROF_SAMPLES, PYPROF_OVERHEAD_SECONDS,
                PYPROF_WINDOWS_DROPPED, PYPROF_TRIE_NODES)
    except Exception:  # pragma: no cover - metrics stack absent
        return None


@dataclass(frozen=True)
class SamplingProfilerConfig:
    """``fleetTelemetry.pyprof`` knobs (camelCase in config files)."""

    enabled: bool = False
    # Sampling rate. 67 Hz ≈ a 14.9 ms period: prime-ish so periodic
    # 10/100 ms work cannot hide between samples, and low enough that a
    # <150 µs pass stays under the 1% CPU budget.
    hz: float = 67.0
    # Windowing: seal the live trie every window_s; keep max_windows
    # sealed windows in the evict-oldest export ring.
    window_s: float = 10.0
    max_windows: int = 30
    # Bounded-trie caps: total interned stack nodes per window and frames
    # kept per stack (deepest frames beyond max_depth are dropped,
    # keeping the leaf).
    max_nodes: int = 8192
    max_depth: int = 64

    @classmethod
    def from_dict(cls, data: Optional[dict]) -> "SamplingProfilerConfig":
        if not data:
            return cls()

        def k(camel: str, snake: str, default):
            if camel in data:
                return data[camel]
            if snake in data:
                return data[snake]
            return default

        d = cls()
        return cls(
            enabled=bool(k("enabled", "enabled", d.enabled)),
            hz=float(k("hz", "hz", d.hz)),
            window_s=float(k("windowS", "window_s", d.window_s)),
            max_windows=int(k("maxWindows", "max_windows", d.max_windows)),
            max_nodes=int(k("maxNodes", "max_nodes", d.max_nodes)),
            max_depth=int(k("maxDepth", "max_depth", d.max_depth)),
        )


class _StackTrie:
    """Bounded trie of folded stacks with per-leaf sample counts.

    Nodes are interned as ``(parent_id, frame) → node_id``; counts land
    on the node where a sampled stack terminates. ``max_nodes`` caps
    interning: once full, unseen frames collapse into one shared
    ``(trie-full)`` child per parent-or-root so hot (already-interned)
    paths keep full resolution while the long tail degrades gracefully.
    """

    __slots__ = ("_nodes", "_frames", "_parents", "_counts", "_max_nodes",
                 "_tf_cap", "truncations")

    def __init__(self, max_nodes: int):
        self._nodes: Dict[tuple, int] = {}
        self._frames: List[str] = []
        self._parents: List[int] = []
        self._counts: Dict[int, int] = {}
        self._max_nodes = max(16, int(max_nodes))
        # Overflow ``(trie-full)`` children intern into a small slack
        # beyond max_nodes so truncation stays *visible* in the folded
        # output; the slack itself is the hard cap.
        self._tf_cap = self._max_nodes + max(16, self._max_nodes // 16)
        self.truncations = 0

    def __len__(self) -> int:
        return len(self._frames)

    def _child(self, parent: int, frame: str) -> int:
        key = (parent, frame)
        node = self._nodes.get(key)
        if node is not None:
            return node
        if frame == TRIE_FULL:
            if len(self._frames) >= self._tf_cap:  # even the slack is full
                return parent
        elif len(self._frames) >= self._max_nodes:
            self.truncations += 1
            return self._child(parent, TRIE_FULL)
        node = len(self._frames)
        self._nodes[key] = node
        self._frames.append(frame)
        self._parents.append(parent)
        return node

    def add(self, frames: List[str], count: int = 1) -> None:
        """Record one root-first folded stack."""
        node = -1
        for frame in frames:
            node = self._child(node, frame)
        if node >= 0:
            self._counts[node] = self._counts.get(node, 0) + count

    def folded_lines(self) -> List[str]:
        """Render ``frame;frame;... count`` lines, deterministic order."""
        out = []
        for node, count in self._counts.items():
            frames = []
            cur = node
            while cur >= 0:
                frames.append(self._frames[cur])
                cur = self._parents[cur]
            frames.reverse()
            out.append(f"{';'.join(frames)} {count}")
        out.sort()
        return out


def _frame_label(frame) -> str:
    """``file.py:func`` — short, stable across pods, merge-friendly."""
    code = frame.f_code
    filename = code.co_filename
    slash = filename.rfind("/")
    if slash >= 0:
        filename = filename[slash + 1:]
    return f"{filename}:{code.co_name}"


class SamplingProfiler:
    """The always-on sampler + windowed folded-stack exporter."""

    def __init__(
        self,
        config: Optional[SamplingProfilerConfig] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cfg = config or SamplingProfilerConfig(enabled=True)
        self._clock = clock
        self._lock = new_lock()
        self._trie = _StackTrie(self.cfg.max_nodes)
        self._window_started = clock()
        self._window_samples = 0
        self._window_overhead_s = 0.0
        self._window_threads: Dict[str, int] = {}
        self._window_spans: Dict[str, int] = {}
        self._windows: deque = deque(maxlen=max(1, self.cfg.max_windows))
        self._next_seq = 0
        self.dropped = 0
        self.samples_total = 0
        self.overhead_s_total = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._capture_lock = new_lock()

    # -- sampling ----------------------------------------------------------

    def sample_once(self) -> float:
        """One sampling pass over every thread; returns its own cost (s).

        Public so the overhead bench and tests can drive passes without
        the timer thread.
        """
        t0 = time.perf_counter()
        own_ident = threading.get_ident()
        span_by_ident = active_span_names()
        names = {t.ident: t.name for t in threading.enumerate()}
        frames_by_ident = sys._current_frames()
        max_depth = self.cfg.max_depth
        stacks = []
        for ident, frame in frames_by_ident.items():
            if ident == own_ident:
                continue  # never bill the sampler to the program
            stack: List[str] = []
            depth = 0
            while frame is not None and depth < max_depth:
                stack.append(_frame_label(frame))
                frame = frame.f_back
                depth += 1
            stack.reverse()  # root first
            span = span_by_ident.get(ident, NO_SPAN)
            thread_name = names.get(ident, str(ident))
            stacks.append((span, thread_name, stack))
        elapsed = time.perf_counter() - t0
        with self._lock:
            for span, thread_name, stack in stacks:
                self._trie.add(
                    [f"span:{span}", f"thread:{thread_name}"] + stack)
                self._window_threads[thread_name] = \
                    self._window_threads.get(thread_name, 0) + 1
                self._window_spans[span] = self._window_spans.get(span, 0) + 1
            self._window_samples += len(stacks)
            self.samples_total += len(stacks)
            self._window_overhead_s += elapsed
            self.overhead_s_total += elapsed
        m = _metrics()
        if m is not None:
            samples, overhead, _dropped, nodes = m
            samples.inc(len(stacks))
            overhead.inc(elapsed)
            nodes.set(len(self._trie))
        return elapsed

    def _rotate_locked(self, now: float) -> None:
        wall = max(now - self._window_started, 1e-9)
        window = {
            "seq": self._next_seq,
            "process": process_identity() or "",
            "start_unix": time.time() - wall,
            "duration_s": round(wall, 3),
            "hz": self.cfg.hz,
            "samples": self._window_samples,
            "threads": dict(self._window_threads),
            "spans": dict(self._window_spans),
            "truncations": self._trie.truncations,
            "overhead_frac": round(self._window_overhead_s / wall, 6),
            "folded": "\n".join(self._trie.folded_lines()),
        }
        self._next_seq += 1
        if len(self._windows) == self._windows.maxlen:
            self.dropped += 1
            m = _metrics()
            if m is not None:
                m[2].inc()
        self._windows.append(window)
        self._trie = _StackTrie(self.cfg.max_nodes)
        self._window_started = now
        self._window_samples = 0
        self._window_overhead_s = 0.0
        self._window_threads = {}
        self._window_spans = {}

    def rotate(self, force: bool = False) -> None:
        """Seal the live window when due (or unconditionally with force).

        Empty windows are sealed too: a flat profile ("nothing ran") is
        itself evidence, and the collector's cursor math stays uniform.
        """
        with self._lock:
            now = self._clock()
            if force or now - self._window_started >= self.cfg.window_s:
                self._rotate_locked(now)

    # -- export ------------------------------------------------------------

    def export_since(self, since: int = -1) -> dict:
        """``/debug/pyprof`` payload, mirroring ``/debug/spans`` cursors:
        sealed windows with ``seq > since`` (oldest first), the next
        cursor, and the evict-before-pull drop count."""
        with self._lock:
            windows = [w for w in self._windows if w["seq"] > since]
            return {
                "windows": windows,
                "next_seq": self._next_seq - 1,
                "dropped": self.dropped,
                "live_samples": self._window_samples,
            }

    def capture(self, seconds: float) -> dict:
        """Burst mode (``/debug/pyprof/capture?seconds=N``): sample the
        process at the configured rate for ``seconds`` on the caller's
        thread and return the folded profile directly — one capture at a
        time, same guard shape as the jax profiler endpoint."""
        if not (0.0 < seconds <= MAX_CAPTURE_SECONDS):
            raise ValueError(
                f"seconds must be in (0, {MAX_CAPTURE_SECONDS:g}], "
                f"got {seconds}")
        if not self._capture_lock.acquire(blocking=False):
            raise CaptureInProgress("a pyprof capture is already running")
        try:
            trie = _StackTrie(self.cfg.max_nodes)
            period = 1.0 / max(self.cfg.hz, 1e-3)
            deadline = time.perf_counter() + seconds
            samples = 0
            overhead = 0.0
            own_ident = threading.get_ident()
            while time.perf_counter() < deadline:
                t0 = time.perf_counter()
                span_by_ident = active_span_names()
                names = {t.ident: t.name for t in threading.enumerate()}
                for ident, frame in sys._current_frames().items():
                    if ident == own_ident:
                        continue
                    stack: List[str] = []
                    depth = 0
                    while frame is not None and depth < self.cfg.max_depth:
                        stack.append(_frame_label(frame))
                        frame = frame.f_back
                        depth += 1
                    stack.reverse()
                    trie.add([f"span:{span_by_ident.get(ident, NO_SPAN)}",
                              f"thread:{names.get(ident, str(ident))}"]
                             + stack)
                    samples += 1
                overhead += time.perf_counter() - t0
                time.sleep(max(0.0, period - (time.perf_counter() - t0)))
            return {
                "seconds": seconds,
                "hz": self.cfg.hz,
                "samples": samples,
                "process": process_identity() or "",
                "overhead_frac": round(overhead / max(seconds, 1e-9), 6),
                "folded": "\n".join(trie.folded_lines()),
            }
        finally:
            self._capture_lock.release()

    def debug_view(self) -> dict:
        with self._lock:
            return {
                "running": self._thread is not None,
                "hz": self.cfg.hz,
                "window_s": self.cfg.window_s,
                "windows_sealed": self._next_seq,
                "windows_buffered": len(self._windows),
                "windows_dropped": self.dropped,
                "samples_total": self.samples_total,
                "overhead_s_total": round(self.overhead_s_total, 6),
                "live_samples": self._window_samples,
                "trie_nodes": len(self._trie),
            }

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Start the daemon sampling thread (idempotent)."""
        if self._thread is not None:
            return
        self._stop.clear()
        period = 1.0 / max(self.cfg.hz, 1e-3)

        def loop() -> None:
            while not self._stop.wait(period):
                try:
                    self.sample_once()
                    self.rotate()
                except Exception:  # sampling must never kill the pod
                    logger.exception("sampling pass failed")

        self._thread = threading.Thread(
            target=loop, name="kvtpu-pyprof-sampler", daemon=True)
        self._thread.start()
        logger.info(
            "sampling profiler on: %.0f Hz, %ss windows x %d",
            self.cfg.hz, self.cfg.window_s, self.cfg.max_windows)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# -- process-global wiring (mirrors install_span_exporter) -------------------

_active_profiler: Optional[SamplingProfiler] = None


def install_sampling_profiler(
    profiler: Optional[SamplingProfiler] = None,
) -> SamplingProfiler:
    """Install (or create) the process's profiler; does not start it."""
    global _active_profiler
    if profiler is None:
        profiler = SamplingProfiler()
    _active_profiler = profiler
    return profiler


def active_sampling_profiler() -> Optional[SamplingProfiler]:
    return _active_profiler


def uninstall_sampling_profiler() -> None:
    global _active_profiler
    if _active_profiler is not None:
        _active_profiler.stop()
    _active_profiler = None


# -- fleet-merge helpers (collector + kvdiag side) ---------------------------


def merge_folded(folded_texts: List[str]) -> Dict[str, int]:
    """Merge folded-stack texts into one ``stack → count`` dict."""
    merged: Dict[str, int] = {}
    for text in folded_texts:
        for line in text.splitlines():
            stack, _, count = line.rpartition(" ")
            if not stack:
                continue
            try:
                merged[stack] = merged.get(stack, 0) + int(count)
            except ValueError:
                continue
    return merged


def span_function_shares(merged: Dict[str, int]) -> Dict[str, dict]:
    """Per-span leaf-function attribution from a merged folded profile.

    Returns ``{span_name: {"samples": n, "functions": {leaf_frame:
    share}}}`` where share is the fraction of that span's samples whose
    leaf (on-CPU) frame is ``leaf_frame`` — the join key for "dominant
    segment × dominant function" in ``kvdiag --fleet``.
    """
    by_span: Dict[str, dict] = {}
    for stack, count in merged.items():
        frames = stack.split(";")
        span = NO_SPAN
        if frames and frames[0].startswith("span:"):
            span = frames[0][len("span:"):]
        leaf = frames[-1] if frames else ""
        entry = by_span.setdefault(span, {"samples": 0, "functions": {}})
        entry["samples"] += count
        entry["functions"][leaf] = entry["functions"].get(leaf, 0) + count
    for entry in by_span.values():
        total = max(entry["samples"], 1)
        entry["functions"] = {
            fn: round(c / total, 4)
            for fn, c in sorted(entry["functions"].items(),
                                key=lambda kv: -kv[1])
        }
    return by_span
