"""Incident black-box: alert-triggered fleet evidence capture (ISSUE 20).

When an alert or anomaly sentinel fires, the evidence an operator needs
— each pod's flight-recorder ring, recent spans, profiler window, audit
records, membership view, the controller journal — is scattered across
per-pod rings that keep rotating while the human is still getting paged.
The :class:`IncidentManager` captures all of it *at the edge*: one
fan-out over the fleet's admin endpoints (with the PR 1 retry/breaker
semantics the collector already applies to scrapes), snapshotted into a
single self-contained **bundle** file:

    +----------------------+----------------------+------------------+
    | magic "KVTPUINC1\\n"  | canonical CBOR doc   | CRC footer (1    |
    | (10 bytes)           | (the evidence)       | slot, integrity) |
    +----------------------+----------------------+------------------+

— the PR 4 snapshot format with its own magic, written via
``utils.atomic_io`` so a torn write can never publish a half bundle.
Per-trigger cooldowns and a keep-N retention cap bound the disk cost of
a flapping alert; capture runs on a detached worker thread so the
trigger edge itself costs microseconds (bench.py ``--incident`` gates
it).

Cross-pod timelines need one clock. ``/debug/time`` (services/admin.py)
echoes each pod's wall + monotonic clocks; :class:`ClockSkewEstimator`
brackets the echo between two local readings and halves the RTT —
the NTP offset estimate ``remote_wall - (t0 + rtt/2)``, whose error is
bounded by ``rtt/2`` under asymmetric routing. Bundles carry the offset
table so ``kvdiag --incident`` can merge flight records, span edges and
controller actions from every pod onto one corrected timeline offline
(:func:`merged_timeline`, :func:`first_anomalous_pod`).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from prometheus_client import Counter, Gauge

from ..resilience.integrity import (
    IntegrityError,
    build_footer,
    footer_size,
    parse_footer,
)
from ..utils.atomic_io import atomic_write_bytes
from ..utils.cbor import CBORDecodeError, canonical_cbor_decode, canonical_cbor_encode
from ..utils.lockdep import new_lock
from ..utils.logging import get_logger
from .anomaly import robust_z
from .flight_recorder import KIND_INCIDENT, flight_recorder

logger = get_logger("telemetry.incident")

INCIDENT_OPENED = Counter(
    "kvtpu_incident_opened_total",
    "Incident captures started, by trigger",
    ["trigger"],
)
INCIDENT_SUPPRESSED = Counter(
    "kvtpu_incident_suppressed_total",
    "Incident triggers suppressed before capture, by reason",
    ["reason"],  # cooldown|disabled|inflight
)
INCIDENT_BUNDLE_BYTES = Gauge(
    "kvtpu_incident_bundle_bytes",
    "Size of the most recently written incident bundle",
)
INCIDENT_CAPTURE_SECONDS = Gauge(
    "kvtpu_incident_capture_seconds",
    "Wall duration of the most recent evidence capture fan-out",
)
INCIDENT_PODS_CAPTURED = Gauge(
    "kvtpu_incident_pods_captured",
    "Pods that contributed evidence to the most recent bundle",
)

BUNDLE_MAGIC = b"KVTPUINC1\n"
BUNDLE_VERSION = 1
_NAME_RE = re.compile(r"^incident-(\d{8})(?:-[A-Za-z0-9_.]+)?\.inc$")
_TRIGGER_SAFE_RE = re.compile(r"[^A-Za-z0-9_.]+")


class IncidentBundleError(Exception):
    """Bundle file malformed or failed verification."""


def encode_bundle(doc: dict) -> bytes:
    """Serialize an evidence document to the on-disk bundle format."""
    body = canonical_cbor_encode(doc)
    crc = zlib.crc32(body) & 0xFFFFFFFF
    return BUNDLE_MAGIC + body + build_footer([crc])


def decode_bundle(blob: bytes) -> dict:
    """Parse + verify one bundle; raise :class:`IncidentBundleError`."""
    if not blob.startswith(BUNDLE_MAGIC):
        raise IncidentBundleError(
            "bad magic (not an incident bundle, or truncated head)")
    tail = footer_size(1)
    if len(blob) < len(BUNDLE_MAGIC) + tail:
        raise IncidentBundleError("truncated bundle (magic + footer missing)")
    body = blob[len(BUNDLE_MAGIC):-tail]
    try:
        (want,) = parse_footer(blob[-tail:], 1)
    except IntegrityError as e:
        raise IncidentBundleError(f"bad checksum footer: {e}") from e
    got = zlib.crc32(body) & 0xFFFFFFFF
    if got != want:
        raise IncidentBundleError(
            f"body crc mismatch: footer={want:#010x} data={got:#010x}")
    try:
        doc = canonical_cbor_decode(body)
    except CBORDecodeError as e:
        raise IncidentBundleError(f"undecodable bundle body: {e}") from e
    if not isinstance(doc, dict):
        raise IncidentBundleError(
            f"bundle body is {type(doc).__name__}, expected map")
    return doc


def load_bundle(path: str) -> dict:
    with open(path, "rb") as fh:
        return decode_bundle(fh.read())


# -- clock-skew estimation ---------------------------------------------------


def estimate_offset(
    t0_wall: float, rtt_s: float, remote_wall: float
) -> float:
    """NTP-style RTT-halved offset: ``remote_wall - local_wall`` at the
    instant the remote stamped its clock, assuming the request and the
    response each took half the round trip. Under asymmetric routing
    (request a, response b, rtt = a + b) the error is ``(b - a) / 2``,
    always bounded by ``rtt / 2``."""
    return remote_wall - (t0_wall + rtt_s / 2.0)


@dataclass
class _OffsetState:
    offset_s: float = 0.0
    rtt_s: float = float("inf")
    updated_mono: float = 0.0
    samples: int = 0


class ClockSkewEstimator:
    """Per-pod clock offsets from ``/debug/time`` echoes.

    Plain NTP filtering: a new sample replaces the stored estimate when
    its RTT is comparable to (or better than) the stored one — a
    congested round trip widens the error bound, so it must not clobber
    a tight estimate — **unless** the stored estimate has aged past
    ``max_age_s``, because clocks drift and a stale tight estimate is
    eventually worse than a fresh loose one.
    """

    def __init__(
        self,
        mono: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
        rtt_slack: float = 1.5,
        max_age_s: float = 120.0,
    ):
        self._mono = mono
        self._wall = wall
        self._rtt_slack = rtt_slack
        self._max_age_s = max_age_s
        self._lock = new_lock()
        self._pods: Dict[str, _OffsetState] = {}

    def update(self, pod: str, fetch_time: Callable[[], dict]) -> Optional[float]:
        """One echo round against ``pod``; returns the accepted offset
        (or None when the sample was rejected or the fetch failed)."""
        t0_mono = self._mono()
        t0_wall = self._wall()
        try:
            payload = fetch_time()
            remote_wall = float(payload["wall"])
        except Exception as exc:
            logger.debug("time echo from %s failed: %s", pod, exc)
            return None
        t1_mono = self._mono()
        rtt = max(0.0, t1_mono - t0_mono)
        offset = estimate_offset(t0_wall, rtt, remote_wall)
        with self._lock:
            state = self._pods.setdefault(pod, _OffsetState())
            age = t0_mono - state.updated_mono
            accept = (
                state.samples == 0
                or rtt <= state.rtt_s * self._rtt_slack
                or age >= self._max_age_s
            )
            state.samples += 1
            if not accept:
                return None
            state.offset_s = offset
            state.rtt_s = rtt
            state.updated_mono = t1_mono
            return offset

    def offsets(self) -> Dict[str, dict]:
        """The bundle's offset table: ``pod -> {offset_s, rtt_s, age_s}``.
        ``offset_s`` is *pod wall minus local wall*; subtract it from a
        pod timestamp to land on the local (collector) timeline."""
        now = self._mono()
        with self._lock:
            return {
                pod: {
                    "offset_s": round(st.offset_s, 6),
                    "rtt_s": round(st.rtt_s, 6),
                    "age_s": round(max(0.0, now - st.updated_mono), 3),
                    "samples": st.samples,
                }
                for pod, st in self._pods.items()
                if st.samples > 0 and st.rtt_s != float("inf")
            }


# -- the incident manager ----------------------------------------------------


@dataclass(frozen=True)
class IncidentConfig:
    """``fleetTelemetry.collector.incident`` config block."""

    enabled: bool = True
    # Bundle directory; empty disables capture entirely (triggers are
    # counted as suppressed so the silence is visible).
    directory: str = ""
    # A trigger that fired within cooldown_s of its previous capture is
    # suppressed — a flapping alert must not spam the disk.
    cooldown_s: float = 300.0
    # Keep-N retention over bundle files (oldest deleted first).
    max_bundles: int = 16
    # Evidence caps per pod (entries, newest kept).
    flight_tail: int = 512
    spans_tail: int = 256
    journal_tail: int = 64

    @classmethod
    def from_dict(cls, data: Optional[dict]) -> "IncidentConfig":
        if not data:
            return cls()

        def k(camel: str, snake: str, default):
            if camel in data:
                return data[camel]
            if snake in data:
                return data[snake]
            return default

        d = cls()
        return cls(
            enabled=bool(k("enabled", "enabled", d.enabled)),
            directory=str(k("directory", "directory", d.directory)),
            cooldown_s=float(k("cooldownS", "cooldown_s", d.cooldown_s)),
            max_bundles=int(k("maxBundles", "max_bundles", d.max_bundles)),
            flight_tail=int(k("flightTail", "flight_tail", d.flight_tail)),
            spans_tail=int(k("spansTail", "spans_tail", d.spans_tail)),
            journal_tail=int(k("journalTail", "journal_tail", d.journal_tail)),
        )


class IncidentManager:
    """Edge-triggered black-box capture over the fleet admin plane.

    ``targets()`` yields ``(name, address, breaker)`` triples (the
    collector's scrape targets and their PR 1 breakers); ``fetch(url)``
    is the collector's retrying transport. ``local_evidence()`` returns
    the collector-side snapshot (alert/anomaly state, per-pod SLI
    history, retained traces) embedded in every bundle.
    """

    # Per-pod evidence legs: (key, path). The flight recorder is the
    # required leg — a pod that cannot even serve its ring is recorded
    # unreachable (and its breaker charged); everything else is
    # enrichment, 404-tolerated exactly like the collector's scrape legs.
    _REQUIRED_LEG = ("flight_recorder", "/debug/flight-recorder?since=-1")
    _ENRICHMENT_LEGS = (
        ("time", "/debug/time"),
        ("spans", "/debug/spans?since=-1"),
        ("pyprof", "/debug/pyprof?since=-1"),
        ("audit", "/debug/audit?since=-1"),
        ("membership", "/debug/membership"),
        ("controller", "/debug/controller"),
    )

    def __init__(
        self,
        config: IncidentConfig,
        fetch: Callable[[str], bytes],
        targets: Callable[[], List[Tuple[str, str, object]]],
        local_evidence: Optional[Callable[[], dict]] = None,
        skew: Optional[ClockSkewEstimator] = None,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
        max_recent: int = 32,
    ):
        self.cfg = config
        self._fetch = fetch
        self._targets = targets
        self._local_evidence = local_evidence or (lambda: {})
        self.skew = skew if skew is not None else ClockSkewEstimator()
        self._clock = clock
        self._wall = wall
        self._lock = new_lock()
        self._last_open: Dict[str, float] = {}
        self._recent: deque = deque(maxlen=max_recent)
        self._suppressed: Dict[str, int] = {}
        # Exported lazily: maybe_open rides the collector's edge stream
        # (bench.py --incident gates it <1% of the score p50) and one
        # prometheus child.inc() alone costs most of that budget. The
        # Python-side counts above are exact and always visible in
        # /debug/incident; the prometheus counters catch up at every
        # accepted trigger and debug_view()/offsets scrape.
        self._suppress_counters = {
            reason: INCIDENT_SUPPRESSED.labels(reason)
            for reason in ("disabled", "cooldown", "inflight")
        }
        self._suppress_published: Dict[str, int] = {}
        self._inflight: Optional[threading.Thread] = None
        self._seq = 0
        self.opened = 0

    # -- triggering --------------------------------------------------------

    def maybe_open(
        self,
        trigger: str,
        reason: Optional[dict] = None,
        force: bool = False,
        synchronous: bool = False,
    ) -> Optional[dict]:
        """Open an incident for ``trigger`` unless suppressed.

        This is the edge-stream hook and must stay cheap: it takes one
        lock, checks the cooldown table, and hands the fan-out to a
        detached worker thread (``synchronous=True`` — tests, the manual
        admin action — captures inline and returns the summary).
        Returns the accepted-trigger stub (or the finished summary when
        synchronous), ``None`` when suppressed.
        """
        now = self._clock()
        if not force:
            # Lock-free steady-state fast path: a trigger still inside
            # its cooldown window is what every edge of a flapping alert
            # pays. The dict read is GIL-atomic, and a racing capture
            # can only have stamped a *newer* ``last`` — which still
            # suppresses — so the check never wrongly accepts; a miss
            # falls through to the locked re-check below.
            last = self._last_open.get(trigger)
            if last is not None and now - last < self.cfg.cooldown_s:
                self._suppress("cooldown")
                return None
        with self._lock:
            if not self.cfg.enabled or not self.cfg.directory:
                self._suppress("disabled")
                return None
            last = self._last_open.get(trigger)
            if not force and last is not None \
                    and now - last < self.cfg.cooldown_s:
                self._suppress("cooldown")
                return None
            if self._inflight is not None and self._inflight.is_alive():
                self._suppress("inflight")
                return None
            self._last_open[trigger] = now
            self._seq += 1
            seq = self._seq
            self.opened += 1
        INCIDENT_OPENED.labels(trigger).inc()
        self._sync_suppressed()
        if synchronous:
            return self._capture(seq, trigger, reason or {})
        worker = threading.Thread(
            target=self._capture,
            args=(seq, trigger, reason or {}),
            name=f"kvtpu-incident-{seq}",
            daemon=True,
        )
        with self._lock:
            self._inflight = worker
        worker.start()
        return {"seq": seq, "trigger": trigger, "state": "capturing"}

    def _suppress(self, why: str) -> None:
        # Unlocked read-modify-write: callers on the fast path hold no
        # lock, so a concurrent bump can lose one count in the *local*
        # dict — acceptable for a suppression tally, and in practice the
        # edge stream is the collector's single scrape thread.
        self._suppressed[why] = self._suppressed.get(why, 0) + 1

    def _sync_suppressed(self) -> None:
        """Catch the prometheus counters up to the exact local tally."""
        for why, n in list(self._suppressed.items()):
            delta = n - self._suppress_published.get(why, 0)
            if delta > 0:
                self._suppress_counters[why].inc(delta)
                self._suppress_published[why] = n

    def wait(self, timeout: float = 10.0) -> None:
        """Join any in-flight capture (tests, orderly shutdown)."""
        with self._lock:
            worker = self._inflight
        if worker is not None and worker.is_alive():
            worker.join(timeout=timeout)

    # -- capture -----------------------------------------------------------

    def _capture_pod(self, name: str, address: str, breaker) -> dict:
        evidence: dict = {"reachable": False}
        if breaker is not None and not breaker.allow():
            evidence["error"] = "breaker open"
            return evidence
        base = f"http://{address}"
        key, path = self._REQUIRED_LEG
        try:
            payload = json.loads(self._fetch(base + path))
            records = payload.get("records")
            if isinstance(records, list) \
                    and len(records) > self.cfg.flight_tail:
                payload["records"] = records[-self.cfg.flight_tail:]
                payload["truncated"] = len(records) - self.cfg.flight_tail
            evidence[key] = payload
            evidence["reachable"] = True
            if breaker is not None:
                breaker.record_success()
        except Exception as exc:
            evidence["error"] = str(exc)
            if breaker is not None:
                breaker.record_failure()
            return evidence
        for key, path in self._ENRICHMENT_LEGS:
            try:
                payload = json.loads(self._fetch(base + path))
            except Exception:  # enrichment leg, 404/timeout tolerated  # lint: allow-swallow
                continue
            if key == "spans":
                spans = payload.get("spans")
                if isinstance(spans, list) \
                        and len(spans) > self.cfg.spans_tail:
                    payload["spans"] = spans[-self.cfg.spans_tail:]
                    payload["truncated"] = len(spans) - self.cfg.spans_tail
            evidence[key] = payload
        return evidence

    def _capture(self, seq: int, trigger: str, reason: dict) -> dict:
        start = self._clock()
        pods: Dict[str, dict] = {}
        captured = 0
        for name, address, breaker in self._targets():
            evidence = self._capture_pod(name, address, breaker)
            pods[name] = evidence
            captured += int(bool(evidence.get("reachable")))
        try:
            local = self._local_evidence()
        except Exception as exc:  # evidence, never capture-fatal
            local = {"error": str(exc)}
        journal = local.get("controller_journal")
        if isinstance(journal, list) and len(journal) > self.cfg.journal_tail:
            local["controller_journal"] = journal[-self.cfg.journal_tail:]
        doc = {
            "version": BUNDLE_VERSION,
            "seq": seq,
            "trigger": trigger,
            "reason": reason,
            "opened_wall": self._wall(),
            "opened_mono": self._clock(),
            "offsets": self.skew.offsets(),
            "collector": local,
            "pods": pods,
        }
        duration = self._clock() - start
        doc["capture_seconds"] = round(duration, 6)
        summary = {
            "seq": seq,
            "trigger": trigger,
            "opened_wall": doc["opened_wall"],
            "pods_captured": captured,
            "pods_total": len(pods),
            "capture_seconds": doc["capture_seconds"],
            "path": "",
            "bytes": 0,
        }
        try:
            summary["path"], summary["bytes"] = self._write(seq, trigger, doc)
        except Exception as exc:
            summary["error"] = str(exc)
            logger.error("incident bundle write failed: %s", exc)
        INCIDENT_CAPTURE_SECONDS.set(duration)
        INCIDENT_PODS_CAPTURED.set(captured)
        with self._lock:
            self._recent.append(summary)
        flight_recorder().record(KIND_INCIDENT, {
            "trigger": trigger,
            "pods": captured,
            "path": summary["path"],
        })
        logger.warning(
            "incident %d (%s): %d/%d pod(s) captured in %.3fs -> %s",
            seq, trigger, captured, len(pods), duration,
            summary["path"] or summary.get("error", "<unwritten>"))
        return summary

    def _write(self, seq: int, trigger: str, doc: dict) -> Tuple[str, int]:
        safe = _TRIGGER_SAFE_RE.sub("_", trigger).strip("_") or "manual"
        path = os.path.join(
            self.cfg.directory, f"incident-{seq:08d}-{safe}.inc")
        os.makedirs(self.cfg.directory, exist_ok=True)
        blob = encode_bundle(doc)
        atomic_write_bytes(path, blob)
        INCIDENT_BUNDLE_BYTES.set(len(blob))
        self._prune()
        return path, len(blob)

    def _prune(self) -> None:
        try:
            names = os.listdir(self.cfg.directory)
        except OSError:
            return
        bundles = sorted(
            (int(m.group(1)), n)
            for n in names
            if (m := _NAME_RE.match(n)) is not None
        )
        excess = len(bundles) - max(1, self.cfg.max_bundles)
        for _seq, name in bundles[:max(0, excess)]:
            try:
                os.unlink(os.path.join(self.cfg.directory, name))
            except OSError:  # racing another pruner  # lint: allow-swallow
                pass

    # -- read surface ------------------------------------------------------

    def debug_view(self) -> dict:
        """The collector's ``/debug/incident`` payload (and the
        ``incidents`` section of ``kvdiag --fleet``)."""
        self._sync_suppressed()
        with self._lock:
            recent = list(self._recent)
            suppressed = dict(self._suppressed)
            inflight = self._inflight is not None and self._inflight.is_alive()
        return {
            "enabled": bool(self.cfg.enabled and self.cfg.directory),
            "directory": self.cfg.directory,
            "cooldown_s": self.cfg.cooldown_s,
            "max_bundles": self.cfg.max_bundles,
            "opened_total": self.opened,
            "capturing": inflight,
            "suppressed": suppressed,
            "recent": recent,
            "offsets": self.skew.offsets(),
        }


# -- offline bundle analysis (kvdiag --incident) -----------------------------


def merged_timeline(doc: dict, limit: int = 0) -> List[dict]:
    """Skew-corrected cross-pod event list, oldest first.

    Every event timestamp is mapped onto the *collector's* wall clock by
    subtracting the source pod's estimated offset (``offsets`` table in
    the bundle; pods without an estimate merge uncorrected). Sources:
    flight-recorder records, span start/end edges, and controller journal
    records from the collector evidence.
    """
    offsets = doc.get("offsets") or {}
    events: List[dict] = []

    def off(pod: str) -> float:
        return float((offsets.get(pod) or {}).get("offset_s", 0.0))

    for pod, evidence in (doc.get("pods") or {}).items():
        shift = off(pod)
        flight = (evidence.get("flight_recorder") or {}).get("records") or ()
        for rec in flight:
            events.append({
                "ts": float(rec.get("ts", 0.0)) - shift,
                "pod": pod,
                "source": "flight",
                "label": str(rec.get("kind", "")),
                "detail": rec.get("data"),
            })
        spans = (evidence.get("spans") or {}).get("spans") or ()
        for span in spans:
            name = str(span.get("name", ""))
            start = span.get("start_time")
            end = span.get("end_time")
            if start is not None:
                events.append({
                    "ts": float(start) - shift, "pod": pod,
                    "source": "span", "label": f"{name} start",
                    "detail": None,
                })
            if end is not None:
                events.append({
                    "ts": float(end) - shift, "pod": pod,
                    "source": "span", "label": f"{name} end",
                    "detail": None,
                })
    journal = (doc.get("collector") or {}).get("controller_journal") or ()
    for rec in journal:
        events.append({
            "ts": float(rec.get("ts", 0.0)),
            "pod": "controller",
            "source": "controller",
            "label": f"{rec.get('action', rec.get('kind', 'action'))} "
                     f"{rec.get('phase', '')}".strip(),
            "detail": {k: rec[k] for k in ("action_id", "epoch")
                       if k in rec},
        })
    events.sort(key=lambda e: e["ts"])
    if limit > 0 and len(events) > limit:
        events = events[-limit:]
    return events


def firing_alerts(doc: dict) -> List[dict]:
    """Alerts + anomalies that were firing at capture time."""
    out: List[dict] = []
    collector = doc.get("collector") or {}
    for name, state in (collector.get("slo") or {}).items():
        severity = (state.get("alert") or {}).get("severity")
        if severity:
            out.append({"kind": "slo", "name": name, "severity": severity})
    for name, state in (collector.get("anomalies") or {}).items():
        if state.get("firing"):
            out.append({
                "kind": "anomaly", "name": name,
                "z": state.get("last_z"), "value": state.get("last_value"),
            })
    return out


def dominant_segment(doc: dict) -> dict:
    """The largest critical-path self-time segment across the bundle's
    retained traces (the 'where was the time going' one-liner)."""
    best: dict = {}
    traces = ((doc.get("collector") or {}).get("traces") or {})
    for summary in traces.get("retained") or ():
        for seg in summary.get("critical_path") or ():
            if seg.get("self_time_s", 0.0) > best.get("self_time_s", 0.0):
                best = dict(seg)
                best["trace_id"] = summary.get("trace_id", "")
    return best


def first_anomalous_pod(
    doc: dict,
    z_threshold: float = 4.0,
    min_samples: int = 6,
) -> Optional[dict]:
    """Name the pod whose SLI series went anomalous first.

    The bundle carries each pod's recent per-sentinel sample series
    (``collector.sli_history``: pod -> sentinel -> [values]). For every
    series, walk forward scoring each sample against the samples before
    it (the same robust z the live sentinels use) and note the earliest
    round that crossed ``z_threshold``; the pod with the earliest
    crossing — ties broken by the larger score — is the primary suspect.
    """
    history = ((doc.get("collector") or {}).get("sli_history") or {})
    best: Optional[dict] = None
    for pod, series_by_sentinel in history.items():
        for sentinel, series in (series_by_sentinel or {}).items():
            values = [float(v) for v in series]
            for i in range(min_samples, len(values)):
                z = robust_z(values[i], values[:i])
                if abs(z) < z_threshold:
                    continue
                candidate = {
                    "pod": pod,
                    "sentinel": sentinel,
                    "round": i,
                    "z": round(min(abs(z), 1e9), 3),
                    "value": round(values[i], 6),
                }
                if best is None or candidate["round"] < best["round"] or (
                        candidate["round"] == best["round"]
                        and candidate["z"] > best["z"]):
                    best = candidate
                break  # first crossing of this series is the one that counts
    return best
