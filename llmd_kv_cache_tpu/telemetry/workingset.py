"""Online working-set analytics: reuse distances + miss-ratio curves.

The fleet can trace, profile, and alert on itself (PRs 10-11), but
capacity questions — "would 2x HBM double the hit ratio?", "which
offloaded blocks are written and never read back?", "how much cross-pod
duplication exists?" — need *reuse* measurements, not latency ones.
This module is that measurement substrate (stdlib only), feeding the
SSD-admission and cross-tenant-dedup ROADMAP items.

Design (SHARDS-style spatial hash sampling):

- A block key is **sampled** iff ``mix64(key) < rate * 2^64`` — a fixed
  spatial filter, so every process that sees a key makes the *same*
  sampling decision (no coordination, no PYTHONHASHSEED dependence) and
  the sampled stream is an unbiased 1-in-``1/rate`` subset of distinct
  blocks. The recording hooks themselves are a single batch enqueue
  (they ride the score p50); per-key work drains amortized.
- For sampled keys, an exact LRU **stack distance** is computed among
  sampled keys (OrderedDict recency list + Fenwick tree over logical
  access timestamps, periodically renumbered), then scaled by
  ``1/rate``: the SHARDS estimator. Distances land in a geometric
  (ratio 2^0.25) histogram, from which the **miss-ratio curve** — estimated
  hit ratio as a function of cache capacity — is evaluated at any
  capacity grid (``estimate_hit_ratio``). Cold (first-touch) accesses
  are counted separately; they miss at every capacity.
- Tracked state is bounded: at most ``max_tracked_blocks`` sampled keys
  per scope; beyond that the coldest sampled key is forgotten (its next
  access counts as cold — the estimator degrades toward pessimism, not
  bias explosion).
- A **written-never-read ledger** on the offload admission path (sampled
  stored keys vs. sampled restored keys), an **eviction-age histogram**
  fed from ``BlockManager`` evictions, and a **duplication estimator**
  (fraction of sampled index keys resident on >= 2 pods) ride along in
  the same windows.
- Every ``window_s`` the live state is sealed into a window on an
  evict-oldest ring and exported at ``/debug/workingset?since=`` with
  the same cursor semantics as ``/debug/spans`` / ``/debug/pyprof``;
  the fleet collector merges windows sample-weighted
  (:func:`merge_workingset_windows`) into the ``kvdiag --fleet``
  what-if capacity table.
- The tracker self-measures: wall time inside record calls accumulates
  into ``overhead_frac`` per window (plus ``kvtpu_workingset_*``
  families), and ``bench.py --workingset`` gates it < 1% of the
  score-path p50 *and* validates the sampled MRC against an
  exact-simulation oracle.

Scopes are tiers within one process ("hbm", "storage", "index"); the
per-pod dimension comes from the window's ``process`` identity, exactly
like pyprof windows.
"""

from __future__ import annotations

import time
import zlib
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence

from ..utils.lockdep import new_lock
from ..utils.logging import get_logger
from .tracing import process_identity

logger = get_logger("telemetry.workingset")

_MASK64 = (1 << 64) - 1

# Tier scope names (window["scopes"] keys). Per-pod curves come from the
# window's process identity, so scopes stay tier-only.
SCOPE_HBM = "hbm"
SCOPE_CPU = "cpu"
SCOPE_STORAGE = "storage"
SCOPE_INDEX = "index"


def _metrics():
    """Lazy metric handles so the module (and kvdiag, which imports the
    merge helpers) stays importable without the metrics stack."""
    try:
        from ..metrics.collector import (
            WORKINGSET_OVERHEAD_SECONDS,
            WORKINGSET_SAMPLED_TOTAL,
            WORKINGSET_TRACKED_BLOCKS,
            WORKINGSET_WINDOWS_DROPPED,
        )

        return (WORKINGSET_SAMPLED_TOTAL, WORKINGSET_OVERHEAD_SECONDS,
                WORKINGSET_TRACKED_BLOCKS, WORKINGSET_WINDOWS_DROPPED)
    except Exception:  # pragma: no cover - metrics stack absent
        return None


def mix64(x: int) -> int:
    """splitmix64 finalizer: a deterministic 64-bit avalanche.

    Block keys are usually content hashes already, but admission paths
    also see small test keys (0, 1, 2, ...); the mix makes the spatial
    filter uniform for both without any per-process state.
    """
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & _MASK64
    x = (x ^ (x >> 27)) * 0x94D049BB133111EB & _MASK64
    return x ^ (x >> 31)


def key64(key) -> int:
    """64-bit spatial-sampling hash for a block key (int/str/bytes)."""
    if isinstance(key, int):
        return mix64(key & _MASK64)
    if isinstance(key, str):
        key = key.encode("utf-8", "surrogatepass")
    # Two salted crc32 halves: cheap, stdlib, process-independent.
    return mix64((zlib.crc32(key) << 32) | zlib.crc32(key, 0x9E3779B9))


@dataclass(frozen=True)
class WorkingSetConfig:
    """``fleetTelemetry.workingset`` knobs (camelCase in config files)."""

    enabled: bool = False
    # Spatial sampling rate R: a key is tracked iff hash(key) < R * 2^64.
    # Estimates are unbiased in R; cost is linear in R. SHARDS reports
    # ~1% MRC error at R=0.01 on real traces; the toy fleet's traces are
    # short, so default higher for tighter small-sample error.
    sample_rate: float = 0.05
    # Windowing: seal live state every window_s; keep max_windows sealed
    # windows on the evict-oldest export ring.
    window_s: float = 10.0
    max_windows: int = 30
    # Hard cap on tracked sampled keys per scope (LRU forget beyond it)
    # and on the never-read / duplication key sets.
    max_tracked_blocks: int = 4096

    @classmethod
    def from_dict(cls, data: Optional[dict]) -> "WorkingSetConfig":
        if not data:
            return cls()

        def k(camel: str, snake: str, default):
            if camel in data:
                return data[camel]
            if snake in data:
                return data[snake]
            return default

        d = cls()
        return cls(
            enabled=bool(k("enabled", "enabled", d.enabled)),
            sample_rate=float(k("sampleRate", "sample_rate", d.sample_rate)),
            window_s=float(k("windowS", "window_s", d.window_s)),
            max_windows=int(k("maxWindows", "max_windows", d.max_windows)),
            max_tracked_blocks=int(
                k("maxTrackedBlocks", "max_tracked_blocks",
                  d.max_tracked_blocks)),
        )


# Geometric distance buckets: ~2^(1/4) ratio. Bucket i holds scaled
# distances in (UPPER[i-1], UPPER[i]]; hit_ratio(C) sums buckets with
# upper bound <= C, so the MRC capacity resolution is the bucket ratio
# (a ≤19% capacity quantization, conservative direction).
_BUCKET_UPPERS: List[int] = []
_v = 1
while _v < 1 << 40:
    _BUCKET_UPPERS.append(_v)
    nxt = max(_v + 1, int(_v * 1.189207115002721))
    _v = nxt


def distance_bucket(scaled_distance: float) -> int:
    """Upper bound of the geometric bucket holding ``scaled_distance``."""
    lo, hi = 0, len(_BUCKET_UPPERS) - 1
    if scaled_distance <= 1:
        return 1
    while lo < hi:
        mid = (lo + hi) // 2
        if _BUCKET_UPPERS[mid] >= scaled_distance:
            hi = mid
        else:
            lo = mid + 1
    return _BUCKET_UPPERS[lo]


class _Fenwick:
    """Fenwick/BIT over logical access timestamps (1-based)."""

    __slots__ = ("n", "tree")

    def __init__(self, n: int):
        self.n = n
        self.tree = [0] * (n + 1)

    def add(self, i: int, delta: int) -> None:
        i += 1
        while i <= self.n:
            self.tree[i] += delta
            i += i & (-i)

    def prefix(self, i: int) -> int:
        """Sum of [0, i]."""
        i += 1
        s = 0
        while i > 0:
            s += self.tree[i]
            i -= i & (-i)
        return s


class _ScopeState:
    """Exact LRU stack distances among sampled keys for one scope.

    ``last`` is an OrderedDict key -> logical timestamp in recency order
    (oldest first); a Fenwick tree marks each tracked key's most recent
    timestamp so the distinct-keys-since-last-access count is two prefix
    sums. Timestamps are renumbered in-place when the logical clock
    reaches the tree size (amortized O(log n) per sampled access).
    """

    __slots__ = ("last", "bit", "clock", "cap", "tree_size",
                 "accesses", "sampled", "cold", "hits", "hist",
                 "capacity_blocks")

    def __init__(self, cap: int):
        self.cap = max(16, cap)
        self.tree_size = 8 * self.cap
        self.last: OrderedDict = OrderedDict()
        self.bit = _Fenwick(self.tree_size)
        self.clock = 0
        # Window-delta counters (reset at seal).
        self.accesses = 0
        self.sampled = 0
        self.cold = 0
        self.hits = 0
        self.hist: Dict[int, int] = {}
        self.capacity_blocks = 0

    def _renumber(self) -> None:
        self.bit = _Fenwick(self.tree_size)
        for i, key in enumerate(self.last):
            self.last[key] = i
            self.bit.add(i, 1)
        self.clock = len(self.last)

    def touch(self, key) -> Optional[int]:
        """Record a sampled access; returns the raw (unscaled) stack
        distance among sampled keys, or None for a cold first touch."""
        if self.clock >= self.tree_size:
            self._renumber()
        prev = self.last.get(key)
        t = self.clock
        self.clock += 1
        if prev is None:
            distance = None
            if len(self.last) >= self.cap:
                _, old_ts = self.last.popitem(last=False)
                self.bit.add(old_ts, -1)
        else:
            # Distinct sampled keys touched strictly after prev: each
            # tracked key's latest access is a marked timestamp.
            distance = self.bit.prefix(t - 1) - self.bit.prefix(prev)
            self.bit.add(prev, -1)
            self.last.move_to_end(key)
        self.last[key] = t
        self.bit.add(t, 1)
        return distance


class WorkingSetTracker:
    """The per-process working-set sampler + windowed exporter."""

    def __init__(
        self,
        config: Optional[WorkingSetConfig] = None,
        clock=time.monotonic,
    ):
        self.cfg = config or WorkingSetConfig(enabled=True)
        rate = min(max(self.cfg.sample_rate, 1e-6), 1.0)
        self.sample_rate = rate
        self._threshold = int(rate * (1 << 64))
        self._clock = clock
        self._lock = new_lock()
        self._scopes: Dict[str, _ScopeState] = {}
        # Spatial-filter memo: key -> bool(sampled). Steady-state cost of
        # an unsampled access is this one dict hit; cleared (cheaply
        # recomputed) when it outgrows the tracked-key budget.
        self._filter: Dict[object, bool] = {}
        self._filter_cap = 8 * max(16, self.cfg.max_tracked_blocks)
        # Written-never-read ledger over sampled offloaded keys
        # (cumulative; snapshot per window).
        self._offload_written: Dict[object, bool] = {}  # key -> read yet?
        self._offload_read_count = 0
        # Duplication estimator over sampled index keys: key -> pod count
        # seen in the latest lookup that resolved it.
        self._dup: OrderedDict = OrderedDict()
        # Eviction-age histogram (seconds, window delta).
        self._evict_hist: Dict[float, int] = {}
        self._window_started = clock()
        self._window_overhead_s = 0.0
        self._windows: deque = deque(maxlen=max(1, self.cfg.max_windows))
        self._next_seq = 0
        self.dropped = 0
        self.sampled_total = 0
        self.overhead_s_total = 0.0
        # Deferred-processing queue: the recording hooks ride latency-
        # critical paths (one per score call), so they only append the
        # batch here — one C-level deque op — and the per-key work
        # (filter, stack distance, histograms) runs in :meth:`_drain`,
        # amortized over every ``_drain_every``-th call and forced on
        # rotate/export. deque.append is GIL-atomic, so the enqueue
        # needs no lock.
        self._pending: deque = deque()
        self._drain_every = 128

    # -- spatial filter ----------------------------------------------------

    def _is_sampled(self, key) -> bool:
        f = self._filter
        v = f.get(key)
        if v is None:
            v = key64(key) < self._threshold
            if len(f) >= self._filter_cap:
                f.clear()
            f[key] = v
        return v

    def _scope(self, scope: str) -> _ScopeState:
        st = self._scopes.get(scope)
        if st is None:
            st = self._scopes[scope] = _ScopeState(self.cfg.max_tracked_blocks)
        return st

    # -- recording hooks ---------------------------------------------------

    def record_accesses(self, scope: str, keys: Sequence, hits: int = 0) -> None:
        """Record one access per key against ``scope``'s reuse stream.

        ``hits`` is how many of these accesses actually hit in the real
        cache behind this scope (measured, not modeled) — reported next
        to the MRC so operators can sanity-check the model.

        Hot-path contract: this is one deque append plus a length check.
        The per-key work happens in :meth:`_drain`, which runs inline on
        every ``_drain_every``-th call (off the p50; the self-reported
        overhead metric bills the full drain cost) and on every
        rotate/export. Callers must not mutate ``keys`` afterwards.
        """
        q = self._pending
        if len(q) >= self._drain_every:
            self._drain()
        q.append((scope, keys, hits, None))

    def record_index_lookup(
        self,
        keys: Sequence,
        key_to_pods: Optional[dict],
        hits: int = 0,
    ) -> None:
        """Index-lookup hook (scoring hot path): feeds the global "index"
        reuse stream and, when the per-key pod map is available (Python
        scoring path), the cross-pod duplication estimator. Same
        single-append hot-path contract as :meth:`record_accesses`."""
        q = self._pending
        if len(q) >= self._drain_every:
            self._drain()
        q.append((SCOPE_INDEX, keys, hits, key_to_pods or None))

    def _drain(self) -> None:
        """Process every queued access batch (filter → stack distance →
        histograms → dup ledger). Amortized onto one in every
        ``_drain_every`` recording calls, and forced before any seal or
        export so readers always see a fully-applied stream."""
        q = self._pending
        if not q:
            return
        t0 = time.perf_counter()
        threshold = self._threshold
        filter_cap = self._filter_cap
        f = self._filter
        drained_sampled = 0
        with self._lock:
            inv = 1.0 / self.sample_rate
            dup = self._dup
            dup_cap = self.cfg.max_tracked_blocks
            while True:
                try:
                    scope, keys, hits, key_to_pods = q.popleft()
                except IndexError:
                    break
                st = self._scope(scope)
                st.accesses += len(keys)
                st.hits += hits
                sampled = []
                for k in keys:
                    v = f.get(k)
                    if v is None:
                        v = key64(k) < threshold
                        if len(f) >= filter_cap:
                            f.clear()
                        f[k] = v
                    if v:
                        sampled.append(k)
                if sampled:
                    st.sampled += len(sampled)
                    drained_sampled += len(sampled)
                    touch = st.touch
                    hist = st.hist
                    for k in sampled:
                        d = touch(k)
                        if d is None:
                            st.cold += 1
                        else:
                            b = distance_bucket((d + 1) * inv)
                            hist[b] = hist.get(b, 0) + 1
                if key_to_pods:
                    for k, pods in key_to_pods.items():
                        v = f.get(k)
                        if v is None:
                            v = key64(k) < threshold
                            f[k] = v
                        if not v:
                            continue
                        if k in dup:
                            dup.move_to_end(k)
                        elif len(dup) >= dup_cap:
                            dup.popitem(last=False)
                        dup[k] = len(pods)
            self.sampled_total += drained_sampled
            elapsed = time.perf_counter() - t0
            self._window_overhead_s += elapsed
            self.overhead_s_total += elapsed
        if drained_sampled:
            m = _metrics()
            if m is not None:
                m[0].inc(drained_sampled)
                m[1].inc(elapsed)
                m[2].set(sum(len(s.last) for s in self._scopes.values()))

    def record_offload_write(self, keys: Sequence) -> None:
        """Offload-store admission hook: sampled keys enter the
        written-never-read ledger as unread."""
        t0 = time.perf_counter()
        is_sampled = self._is_sampled
        sampled = [k for k in keys if is_sampled(k)]
        if not sampled:
            return
        with self._lock:
            written = self._offload_written
            cap = self.cfg.max_tracked_blocks
            for k in sampled:
                if k not in written and len(written) >= cap:
                    evicted_read = written.pop(next(iter(written)))
                    if evicted_read:
                        self._offload_read_count -= 1
                if not written.get(k, False):
                    written[k] = False
            elapsed = time.perf_counter() - t0
            self._window_overhead_s += elapsed
            self.overhead_s_total += elapsed

    def record_offload_read(self, keys: Sequence, hits: int = 0) -> None:
        """Offload-restore hook: storage-tier reuse stream + marks the
        hit prefix as read in the never-read ledger."""
        self.record_accesses(SCOPE_STORAGE, keys, hits=hits)
        t0 = time.perf_counter()
        is_sampled = self._is_sampled
        sampled = [k for k in keys[:hits] if is_sampled(k)]
        if not sampled:
            return
        with self._lock:
            written = self._offload_written
            for k in sampled:
                if k in written and not written[k]:
                    written[k] = True
                    self._offload_read_count += 1
            elapsed = time.perf_counter() - t0
            self._window_overhead_s += elapsed
            self.overhead_s_total += elapsed

    def record_eviction_age(self, age_s: float) -> None:
        """BlockManager eviction hook: time from last use to eviction."""
        with self._lock:
            b = float(distance_bucket(max(age_s, 0.0) * 16.0)) / 16.0
            self._evict_hist[b] = self._evict_hist.get(b, 0) + 1

    def set_capacity(self, scope: str, blocks: int) -> None:
        """Declare the real capacity (in blocks) behind a scope; the
        what-if table is evaluated at multiples of it."""
        with self._lock:
            self._scope(scope).capacity_blocks = int(blocks)

    # -- windowing / export ------------------------------------------------

    def _seal_locked(self, now: float) -> None:
        wall = max(now - self._window_started, 1e-9)
        written = len(self._offload_written)
        read = self._offload_read_count
        multi = sum(1 for c in self._dup.values() if c >= 2)
        tracked = len(self._dup)
        window = {
            "seq": self._next_seq,
            "process": process_identity() or "",
            "start_unix": time.time() - wall,
            "duration_s": round(wall, 3),
            "sample_rate": self.sample_rate,
            "scopes": {
                scope: {
                    "accesses": st.accesses,
                    "sampled": st.sampled,
                    "cold": st.cold,
                    "hits": st.hits,
                    "capacity_blocks": st.capacity_blocks,
                    "tracked": len(st.last),
                    "hist": {str(b): c for b, c in sorted(st.hist.items())},
                }
                for scope, st in self._scopes.items()
            },
            "never_read": {
                "written": written,
                "read": read,
                "fraction": round((written - read) / written, 4)
                if written else 0.0,
            },
            "duplication": {
                "tracked": tracked,
                "multi_pod": multi,
                "share": round(multi / tracked, 4) if tracked else 0.0,
            },
            "eviction_age": {
                str(b): c for b, c in sorted(self._evict_hist.items())
            },
            "overhead_s": round(self._window_overhead_s, 6),
            "overhead_frac": round(self._window_overhead_s / wall, 6),
        }
        self._next_seq += 1
        if len(self._windows) == self._windows.maxlen:
            self.dropped += 1
            m = _metrics()
            if m is not None:
                m[3].inc()
        self._windows.append(window)
        # Reuse state (last-access maps, never-read ledger, dup keys)
        # carries across windows — reuse has no window boundary; only the
        # delta counters reset.
        for st in self._scopes.values():
            st.accesses = st.sampled = st.cold = st.hits = 0
            st.hist = {}
        self._evict_hist = {}
        self._window_started = now
        self._window_overhead_s = 0.0

    def rotate(self, force: bool = False) -> None:
        """Seal the live window when due (or unconditionally with force).
        Empty windows seal too: cursor math stays uniform."""
        self._drain()
        with self._lock:
            now = self._clock()
            if force or now - self._window_started >= self.cfg.window_s:
                self._seal_locked(now)

    def export_since(self, since: int = -1) -> dict:
        """``/debug/workingset`` payload, mirroring ``/debug/spans`` and
        ``/debug/pyprof`` cursors: sealed windows with ``seq > since``
        (oldest first), the next cursor, and the drop count."""
        self.rotate()
        with self._lock:
            windows = [w for w in self._windows if w["seq"] > since]
            return {
                "windows": windows,
                "next_seq": self._next_seq - 1,
                "dropped": self.dropped,
                "sample_rate": self.sample_rate,
            }

    def debug_view(self) -> dict:
        self._drain()
        with self._lock:
            return {
                "sample_rate": self.sample_rate,
                "window_s": self.cfg.window_s,
                "windows_sealed": self._next_seq,
                "windows_buffered": len(self._windows),
                "windows_dropped": self.dropped,
                "sampled_total": self.sampled_total,
                "overhead_s_total": round(self.overhead_s_total, 6),
                "scopes": {
                    scope: {
                        "tracked": len(st.last),
                        "capacity_blocks": st.capacity_blocks,
                    }
                    for scope, st in self._scopes.items()
                },
            }


# -- process-global wiring (mirrors install_span_exporter) -------------------

_active_tracker: Optional[WorkingSetTracker] = None


def install_workingset_tracker(
    tracker: Optional[WorkingSetTracker] = None,
) -> WorkingSetTracker:
    """Install (or create) the process's working-set tracker."""
    global _active_tracker
    if tracker is None:
        tracker = WorkingSetTracker()
    _active_tracker = tracker
    return tracker


def active_workingset_tracker() -> Optional[WorkingSetTracker]:
    return _active_tracker


def uninstall_workingset_tracker() -> None:
    global _active_tracker
    _active_tracker = None


# -- fleet-merge helpers (collector + kvdiag side) ---------------------------


def estimate_hit_ratio(
    hist: Dict[str, int], cold: int, capacity_blocks: float
) -> float:
    """SHARDS MRC point estimate: fraction of sampled accesses whose
    scaled reuse distance fits in ``capacity_blocks`` (cold accesses
    miss at every capacity)."""
    total = cold + sum(hist.values())
    if total <= 0:
        return 0.0
    hits = sum(c for b, c in hist.items() if float(b) <= capacity_blocks)
    return hits / total


def merge_workingset_windows(windows: Iterable[dict]) -> dict:
    """Sample-weighted fleet merge of per-pod workingset windows.

    Histogram counts estimate ``count / rate`` real accesses, so windows
    from pods running different sample rates merge by weighting each
    window's counts with ``1/rate``; the merged hit-ratio estimates stay
    unbiased. Never-read and duplication ledgers merge the same way.
    Returns per-scope merged histograms plus fleet HBM capacity — the
    input to :func:`whatif_table`.
    """
    scopes: Dict[str, dict] = {}
    never = {"written": 0.0, "read": 0.0}
    dup = {"tracked": 0.0, "multi_pod": 0.0}
    evict: Dict[str, float] = {}
    capacity_by_proc: Dict[str, int] = {}
    processes = set()
    for w in windows:
        inv = 1.0 / max(w.get("sample_rate", 1.0), 1e-9)
        processes.add(w.get("process", ""))
        for scope, st in (w.get("scopes") or {}).items():
            agg = scopes.setdefault(scope, {
                "accesses": 0, "sampled": 0.0, "cold": 0.0, "hits": 0,
                "hist": {},
            })
            agg["accesses"] += st.get("accesses", 0)
            agg["sampled"] += st.get("sampled", 0) * inv
            agg["cold"] += st.get("cold", 0) * inv
            agg["hits"] += st.get("hits", 0)
            hist = agg["hist"]
            for b, c in (st.get("hist") or {}).items():
                hist[b] = hist.get(b, 0.0) + c * inv
            if scope == SCOPE_HBM and st.get("capacity_blocks"):
                capacity_by_proc[w.get("process", "")] = \
                    st["capacity_blocks"]
        nr = w.get("never_read") or {}
        never["written"] += nr.get("written", 0) * inv
        never["read"] += nr.get("read", 0) * inv
        d = w.get("duplication") or {}
        dup["tracked"] += d.get("tracked", 0) * inv
        dup["multi_pod"] += d.get("multi_pod", 0) * inv
        for b, c in (w.get("eviction_age") or {}).items():
            evict[b] = evict.get(b, 0.0) + c
    never["fraction"] = (
        round((never["written"] - never["read"]) / never["written"], 4)
        if never["written"] else 0.0)
    dup["share"] = (round(dup["multi_pod"] / dup["tracked"], 4)
                    if dup["tracked"] else 0.0)
    return {
        "processes": sorted(p for p in processes if p),
        "scopes": scopes,
        "never_read": never,
        "duplication": dup,
        "eviction_age": evict,
        "hbm_capacity_blocks": sum(capacity_by_proc.values()),
        "hbm_capacity_by_process": capacity_by_proc,
    }


def whatif_table(
    merged: dict,
    factors: Sequence[float] = (0.5, 1.0, 2.0, 4.0),
    scope: str = SCOPE_HBM,
) -> List[dict]:
    """Evaluate the merged MRC at multiples of current capacity.

    Falls back to the "index" scope's reuse stream when the requested
    scope saw no traffic (an indexer-only fleet still has a global
    reuse curve worth printing).
    """
    st = (merged.get("scopes") or {}).get(scope)
    if not st or not (st.get("cold") or st.get("hist")):
        st = (merged.get("scopes") or {}).get(SCOPE_INDEX)
    capacity = merged.get("hbm_capacity_blocks") or 0
    rows = []
    for f in factors:
        cap = capacity * f
        ratio = (estimate_hit_ratio(st["hist"], st["cold"], cap)
                 if st and capacity else 0.0)
        rows.append({
            "factor": f,
            "capacity_blocks": int(cap),
            "est_hit_ratio": round(ratio, 4),
        })
    return rows
