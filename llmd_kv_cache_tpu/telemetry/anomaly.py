"""Robust anomaly sentinels over fleet SLI series (ISSUE 20).

The burn-rate alerts (telemetry/slo.py) answer "is the error budget on
fire?" — but they only see SLIs with an explicit objective, and a slow
gray failure (one pod's ingest lag creeping up, hedge spend doubling,
fence rejections trickling in) can simmer for a long time without
touching a budget. The sentinels watch the *shape* of each series
instead: every scrape round the collector feeds one sample per sentinel,
and the detector compares it against the series' own recent history with
a **robust z-score**:

    z = 0.6745 * (x - median) / MAD

where MAD is the median absolute deviation of the window — median/MAD
instead of mean/stddev so the baseline is not dragged by the very
outliers being hunted (a single 100x spike barely moves the median). A
sentinel *fires* after ``min_consecutive`` samples beyond
``z_threshold`` (one blip is noise) and *clears* once the score falls
back under ``clear_threshold`` (hysteresis, so a value hovering at the
threshold cannot flap the edge stream).

Edges are seq-stamped into a bounded ring with the exact cursor contract
of ``SLORegistry.export_edges_since`` so the fleet controller and the
incident manager consume one uniform edge stream; level state folds into
``FleetSignals.anomalies`` (control/signals.py).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from prometheus_client import Counter, Gauge

from ..utils.lockdep import new_lock

ANOMALY_ACTIVE = Gauge(
    "kvtpu_anomaly_active",
    "1 while the sentinel's robust-z anomaly is firing",
    ["sentinel"],
)
ANOMALY_EDGES = Counter(
    "kvtpu_anomaly_edges_total",
    "Sentinel anomaly transitions by edge (fire/clear)",
    ["sentinel", "edge"],
)
ANOMALY_SCORE = Gauge(
    "kvtpu_anomaly_score",
    "Latest robust z-score of the sentinel's series",
    ["sentinel"],
)

# 0.6745 ~= Phi^-1(0.75): scales MAD to the stddev of a normal series so
# z thresholds read in familiar sigma units.
_MAD_TO_SIGMA = 0.6745


def robust_z(value: float, history: List[float]) -> float:
    """Robust z-score of ``value`` against ``history`` (median/MAD).

    A zero MAD (constant history — the common case for a healthy counter
    rate of 0) falls back to the mean absolute deviation, and when that
    is zero too, any deviation at all is scored infinite: a series that
    has literally never moved and suddenly does *is* the anomaly.
    """
    if not history:
        return 0.0
    ordered = sorted(history)
    n = len(ordered)
    median = (ordered[n // 2] if n % 2
              else 0.5 * (ordered[n // 2 - 1] + ordered[n // 2]))
    deviations = sorted(abs(x - median) for x in ordered)
    mad = (deviations[n // 2] if n % 2
           else 0.5 * (deviations[n // 2 - 1] + deviations[n // 2]))
    if mad <= 0.0:
        mad = sum(deviations) / n / _MAD_TO_SIGMA
    if mad <= 0.0:
        return float("inf") if value != median else 0.0
    return _MAD_TO_SIGMA * (value - median) / mad


@dataclass(frozen=True)
class SentinelConfig:
    """One watched SLI series."""

    name: str
    description: str = ""
    # Samples of history kept (and required before any verdict).
    window: int = 64
    min_samples: int = 8
    # Fire above z_threshold, clear below clear_threshold (hysteresis).
    z_threshold: float = 6.0
    clear_threshold: float = 3.0
    # Consecutive anomalous samples before the fire edge (blip filter).
    min_consecutive: int = 2
    # |value - median| must also exceed this before firing — keeps a
    # microsecond-scale wiggle on an all-but-constant series from scoring
    # "infinite sigma" (units of the series itself).
    absolute_floor: float = 0.0


class AnomalySentinel:
    """Edge-triggered robust-z detector over one scalar series."""

    def __init__(
        self,
        config: SentinelConfig,
        clock: Callable[[], float] = time.monotonic,
        on_edge: Optional[Callable[[dict], None]] = None,
    ):
        self.config = config
        self._clock = clock
        self._on_edge = on_edge
        self._lock = new_lock()
        self._history: deque = deque(maxlen=max(2, config.window))
        self._streak = 0
        self.firing = False
        self.last_value = 0.0
        self.last_z = 0.0
        self.fires = 0

    def observe(self, value: float) -> Optional[dict]:
        """Ingest one sample; returns the edge record when one fired."""
        cfg = self.config
        value = float(value)
        edge: Optional[dict] = None
        with self._lock:
            history = list(self._history)
            z = robust_z(value, history) if len(history) >= cfg.min_samples \
                else 0.0
            ordered = sorted(history)
            median = (0.0 if not ordered else
                      ordered[len(ordered) // 2] if len(ordered) % 2 else
                      0.5 * (ordered[len(ordered) // 2 - 1]
                             + ordered[len(ordered) // 2]))
            anomalous = (abs(z) >= cfg.z_threshold
                         and abs(value - median) >= cfg.absolute_floor)
            self._streak = self._streak + 1 if anomalous else 0
            prev = self.firing
            if not prev and self._streak >= max(1, cfg.min_consecutive):
                self.firing = True
                self.fires += 1
            elif prev and abs(z) < cfg.clear_threshold:
                self.firing = False
                self._streak = 0
            if self.firing != prev:
                edge = {
                    "ts": self._clock(),
                    "sentinel": cfg.name,
                    "edge": "fire" if self.firing else "clear",
                    "value": round(value, 6),
                    "median": round(median, 6),
                    "z": round(min(z, 1e9), 3),
                }
            # Anomalous samples never feed the baseline — neither while
            # firing (a long incident cannot launder itself into
            # "normal") nor during the pre-fire streak: on a tight
            # series the first outlier would inflate the MAD fallback
            # enough that the second consecutive sample scores back
            # under threshold and min_consecutive could never be met.
            if not self.firing and not anomalous:
                self._history.append(value)
            self.last_value = value
            self.last_z = z if z != float("inf") else 1e9
        ANOMALY_SCORE.labels(cfg.name).set(round(min(z, 1e9), 3))
        ANOMALY_ACTIVE.labels(cfg.name).set(1.0 if self.firing else 0.0)
        if edge is not None:
            ANOMALY_EDGES.labels(cfg.name, edge["edge"]).inc()
            if self._on_edge is not None:
                # Outside the lock's critical work: the sink may re-enter.
                self._on_edge(edge)
        return edge

    def debug_view(self) -> dict:
        with self._lock:
            return {
                "sentinel": self.config.name,
                "description": self.config.description,
                "firing": self.firing,
                "fires": self.fires,
                "last_value": round(self.last_value, 6),
                "last_z": round(self.last_z, 3),
                "samples": len(self._history),
            }


class AnomalyRegistry:
    """The collector's sentinels, sharing one seq-stamped edge ring.

    Cursor contract mirrors ``SLORegistry.export_edges_since`` exactly
    (``seq > since``; ``next_seq`` = last stamped seq; bounded ring with
    a drop counter) so ``/debug/slo?since=`` consumers can treat both
    streams identically.
    """

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        max_edges: int = 512,
    ):
        self.clock = clock
        self.sentinels: Dict[str, AnomalySentinel] = {}
        self.max_edges = max_edges
        self._edges: deque = deque()
        self._edge_lock = new_lock()
        self._edge_seq = 0
        self.edges_dropped = 0

    def add(self, config: SentinelConfig) -> AnomalySentinel:
        sentinel = AnomalySentinel(
            config, clock=self.clock, on_edge=self._record_edge)
        self.sentinels[config.name] = sentinel
        return sentinel

    def get(self, name: str) -> Optional[AnomalySentinel]:
        return self.sentinels.get(name)

    def observe(self, name: str, value: float) -> Optional[dict]:
        sentinel = self.sentinels.get(name)
        return sentinel.observe(value) if sentinel is not None else None

    def active(self) -> Dict[str, dict]:
        """Level state per sentinel (the ``FleetSignals.anomalies`` feed)."""
        return {
            name: {
                "firing": s.firing,
                "last_value": round(s.last_value, 6),
                "last_z": round(min(s.last_z, 1e9), 3),
            }
            for name, s in self.sentinels.items()
        }

    def debug_view(self) -> dict:
        return {name: s.debug_view() for name, s in self.sentinels.items()}

    def _record_edge(self, edge: dict) -> None:
        with self._edge_lock:
            edge = dict(edge)
            edge["seq"] = self._edge_seq
            self._edge_seq += 1
            self._edges.append(edge)
            while len(self._edges) > self.max_edges:
                self._edges.popleft()
                self.edges_dropped += 1

    def export_edges_since(self, since: int = -1) -> dict:
        with self._edge_lock:
            return {
                "edges": [dict(e) for e in self._edges if e["seq"] > since],
                "next_seq": self._edge_seq - 1,
                "dropped": self.edges_dropped,
            }
