"""Tracing + flight recorder (counterpart of ``pkg/telemetry/``)."""

from .engine_telemetry import (
    EngineTelemetry,
    EngineTelemetryConfig,
    ProfileInProgress,
    ProfilerCapture,
)
from .flight_recorder import (
    FlightRecorder,
    attach_failpoint_listener,
    flight_recorder,
    install_signal_dump,
    set_flight_recorder,
)
from .tracing import (
    InMemorySpanExporter,
    current_traceparent,
    format_traceparent,
    init_tracing,
    install_span_exporter,
    parse_traceparent,
    recording_tracing,
    tracer,
    uninstall_span_exporter,
)

__all__ = [
    "EngineTelemetry",
    "EngineTelemetryConfig",
    "FlightRecorder",
    "InMemorySpanExporter",
    "ProfileInProgress",
    "ProfilerCapture",
    "attach_failpoint_listener",
    "current_traceparent",
    "flight_recorder",
    "format_traceparent",
    "init_tracing",
    "install_signal_dump",
    "install_span_exporter",
    "parse_traceparent",
    "recording_tracing",
    "set_flight_recorder",
    "tracer",
    "uninstall_span_exporter",
]
