"""Tracing + flight recorder (counterpart of ``pkg/telemetry/``)."""

from .engine_telemetry import (
    EngineTelemetry,
    EngineTelemetryConfig,
    ProfileInProgress,
    ProfilerCapture,
)
from .fleet import FleetTelemetryConfig, enable_span_export
from .flight_recorder import (
    FlightRecorder,
    attach_failpoint_listener,
    flight_recorder,
    install_signal_dump,
    set_flight_recorder,
)
from .slo import SLOConfig, SLORegistry, SLOTracker
from .tracing import (
    InMemorySpanExporter,
    RecordedSpan,
    active_span_exporter,
    current_traceparent,
    format_traceparent,
    init_tracing,
    install_span_exporter,
    parse_traceparent,
    process_identity,
    recording_tracing,
    set_process_identity,
    tracer,
    uninstall_span_exporter,
)

__all__ = [
    "EngineTelemetry",
    "EngineTelemetryConfig",
    "FleetTelemetryConfig",
    "FlightRecorder",
    "InMemorySpanExporter",
    "ProfileInProgress",
    "ProfilerCapture",
    "RecordedSpan",
    "SLOConfig",
    "SLORegistry",
    "SLOTracker",
    "active_span_exporter",
    "attach_failpoint_listener",
    "current_traceparent",
    "enable_span_export",
    "flight_recorder",
    "format_traceparent",
    "init_tracing",
    "install_signal_dump",
    "install_span_exporter",
    "parse_traceparent",
    "process_identity",
    "recording_tracing",
    "set_flight_recorder",
    "set_process_identity",
    "tracer",
    "uninstall_span_exporter",
]
