"""Tracing (counterpart of ``pkg/telemetry/``)."""

from .tracing import init_tracing, tracer

__all__ = ["init_tracing", "tracer"]
