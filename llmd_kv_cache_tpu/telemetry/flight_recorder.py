"""In-process flight recorder: a lock-light ring of decision records.

The aviation analogy is deliberate: when a scorer picks a surprising pod,
a failover flips the index, or an offload job dies, the question is always
"what were the last N decisions leading up to it?" — and by then the
moment is gone. The recorder keeps the last ``capacity`` structured
records (score outcomes with per-pod scores, ingest coalescing stats,
failover transitions, offload results, failpoint trips) in a preallocated
ring that costs well under a microsecond per record on the score hot path
(bench.py asserts < 1%).

Lock-light by construction: writers claim a monotonically increasing
sequence from ``itertools.count()`` (a single C-level call, atomic under
the GIL and safe on free-threaded builds via its internal lock) and store
an immutable tuple into ``slots[seq % capacity]`` — one list item
assignment, no lock, no allocation beyond the tuple. Readers snapshot the
slot list and sort by sequence; a reader racing a writer sees either the
old or the new tuple for a slot, never a torn record.

Dump surfaces: ``SIGUSR2`` (install via :func:`install_signal_dump`),
first trip of each failpoint (:func:`attach_failpoint_listener`), the
admin endpoint's ``/debug/flight-recorder``, and ``hack/kvdiag.py``.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import signal
import tempfile
import threading
import time
from typing import Any, Callable, Optional

logger = logging.getLogger(__name__)

DEFAULT_CAPACITY = 1024

# Record kinds written by the library (a closed set keeps dashboards and
# kvdiag greppable; new subsystems add to it deliberately).
KIND_SCORE = "score"
KIND_INGEST = "ingest"
KIND_FAILOVER = "failover"
KIND_RETRY = "retry"
KIND_OFFLOAD = "offload"
KIND_FAILPOINT = "failpoint"
KIND_RECONNECT = "zmq_reconnect"
KIND_RECOVERY = "recovery"
KIND_DRAIN = "drain"
KIND_OVERFLOW = "queue_overflow"
KIND_ENGINE_REQUEST = "engine_request"
KIND_PROFILE = "profile_capture"
KIND_LOCKDEP = "lockdep"
KIND_HEDGE = "hedge"
KIND_SHED = "shed"
KIND_AUDIT = "audit"
KIND_FENCE = "fence"
KIND_ANOMALY = "anomaly"
KIND_INCIDENT = "incident"


class FlightRecorder:
    """Fixed-capacity ring of ``(seq, ts, mono, kind, data)`` tuples.

    ``ts`` is wall-clock (``time.time()``) so records from different pods
    can be merged onto one fleet timeline (after the collector's per-pod
    skew correction, telemetry/incident.py); ``mono`` is the same pod's
    ``time.monotonic()`` so records align with span start/end stamps and
    survive local wall-clock steps."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self._capacity = capacity
        self._slots: list[Optional[tuple]] = [None] * capacity
        self._count = itertools.count()

    @property
    def capacity(self) -> int:
        return self._capacity

    def record(self, kind: str, data: Optional[dict] = None) -> int:
        """Append one record; returns its sequence number.

        Hot-path budget: one ``next()``, one ``time.time()``, one
        ``time.monotonic()``, one tuple build, one list store. ``data`` is
        kept by reference — treat it as frozen after handoff (callers on
        the hot path pass freshly built dicts they do not mutate
        afterwards).
        """
        seq = next(self._count)
        self._slots[seq % self._capacity] = (
            seq, time.time(), time.monotonic(), kind, data)
        return seq

    def _live(self) -> list[tuple]:
        live = [s for s in list(self._slots) if s is not None]
        live.sort(key=lambda rec: rec[0])
        return live

    def snapshot(self) -> list[dict[str, Any]]:
        """Records currently in the ring, oldest first."""
        return [
            {"seq": seq, "ts": ts, "mono": mono, "kind": kind, "data": data}
            for seq, ts, mono, kind, data in self._live()
        ]

    def export_since(self, since: int = -1) -> dict[str, Any]:
        """Records with ``seq > since`` plus the resume cursor — the
        ``/debug/flight-recorder?since=`` payload, with the same
        non-destructive per-puller cursor semantics as ``/debug/spans``:
        ``next_seq`` is the newest seq present (echo it back next pull)
        and ``dropped`` counts records evicted from the ring so far."""
        live = self._live()
        records = [
            {"seq": seq, "ts": ts, "mono": mono, "kind": kind, "data": data}
            for seq, ts, mono, kind, data in live
            if seq > since
        ]
        next_seq = live[-1][0] if live else since
        dropped = max(0, live[-1][0] + 1 - len(live)) if live else 0
        return {"records": records, "next_seq": next_seq, "dropped": dropped}

    def dump_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(
            {"capacity": self._capacity, "records": self.snapshot()},
            indent=indent,
            default=repr,
        )

    def clear(self) -> None:
        """Drop all records (tests / post-dump reset); writers may race this
        benignly — a record written during clear survives in its slot."""
        for i in range(self._capacity):
            self._slots[i] = None


_global_recorder: Optional[FlightRecorder] = None
_global_mu = threading.Lock()


def flight_recorder() -> FlightRecorder:
    """Process-wide recorder (lazily created at :data:`DEFAULT_CAPACITY`)."""
    global _global_recorder
    rec = _global_recorder
    if rec is None:
        with _global_mu:
            rec = _global_recorder
            if rec is None:
                rec = _global_recorder = FlightRecorder()
    return rec


def set_flight_recorder(recorder: Optional[FlightRecorder]) -> None:
    """Swap the process-wide recorder (tests size it down; None resets)."""
    global _global_recorder
    with _global_mu:
        _global_recorder = recorder


def record(kind: str, data: Optional[dict] = None) -> int:
    """Module-level shorthand for ``flight_recorder().record(...)``."""
    return flight_recorder().record(kind, data)


def install_signal_dump(
    signum: int = signal.SIGUSR2,
    path: Optional[str] = None,
    recorder: Optional[FlightRecorder] = None,
    dump_dir: Optional[str] = None,
) -> Callable:
    """Dump the ring as JSON on ``signum`` (default ``SIGUSR2``).

    Writes to ``path`` when given. Otherwise each signal writes a fresh
    timestamped file under ``dump_dir`` (default: ``$KVTPU_DUMP_DIR``,
    falling back to the system temp dir) and logs the file path — a
    1024-record ring serialized onto a single ``logger.warning`` line
    used to be truncated by every log shipper that touched it, so the
    payload never goes to the log anymore, only its location does.
    Returns the previous handler so callers can restore it. Must be called
    from the main thread (CPython restriction on ``signal.signal``).
    """
    rec = recorder if recorder is not None else flight_recorder()

    def _handler(_signum, _frame):
        payload = rec.dump_json()
        target = path
        if not target:
            directory = (dump_dir or os.environ.get("KVTPU_DUMP_DIR")
                         or tempfile.gettempdir())
            stamp = time.strftime("%Y%m%dT%H%M%S")
            target = os.path.join(
                directory, f"kvtpu-flight-{os.getpid()}-{stamp}.json")
        try:
            with open(target, "w") as fh:
                fh.write(payload)
        except OSError as exc:
            logger.error("flight-recorder dump to %s failed: %s", target, exc)
        else:
            logger.warning(
                "flight-recorder dump (signal %d) written to %s (%d bytes)",
                _signum, target, len(payload))

    return signal.signal(signum, _handler)


# One black-box capture per failpoint name per process: chaos suites fire
# the same failpoint thousands of times and must not flood the log.
_dumped_failpoints: set[str] = set()


def attach_failpoint_listener(registry=None) -> None:
    """Record every failpoint trip; dump the ring once per failpoint name.

    ``registry`` defaults to the global one in ``resilience.failpoints``.
    Idempotent — re-attaching replaces nothing and duplicates nothing
    (the registry de-dupes listeners by identity).
    """
    if registry is None:
        from ..resilience.failpoints import failpoints as registry  # noqa: PLC0415

    registry.add_listener(_on_failpoint_fired)


def _on_failpoint_fired(name: str) -> None:
    rec = flight_recorder()
    rec.record(KIND_FAILPOINT, {"name": name})
    if name not in _dumped_failpoints:
        _dumped_failpoints.add(name)
        logger.warning(
            "failpoint '%s' fired; flight-recorder capture: %s", name, rec.dump_json()
        )
