"""Pod-side fleet-telemetry wiring: the ``fleetTelemetry`` config block.

The *collector-side* configuration lives in
``services.telemetry_collector.CollectorConfig``; this module is the thin
pod-side counterpart: whether this process exports finished spans through
its admin ``/debug/spans`` endpoint, how deep the ring buffer is, and the
logical ``process`` identity stamped on every exported span (what the
collector's critical-path attribution groups by).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from .sampling_profiler import (
    SamplingProfiler,
    SamplingProfilerConfig,
    active_sampling_profiler,
    install_sampling_profiler,
)
from .tracing import (
    InMemorySpanExporter,
    active_span_exporter,
    install_span_exporter,
    set_process_identity,
)
from .workingset import (
    WorkingSetConfig,
    WorkingSetTracker,
    active_workingset_tracker,
    install_workingset_tracker,
)


@dataclass(frozen=True)
class FleetTelemetryConfig:
    """``fleetTelemetry`` block of a pod config (camelCase in files)."""

    # Master switch: install a recording ring exporter and expose
    # /debug/spans on the pod's admin endpoint.
    span_export: bool = False
    # Ring depth; evict-oldest beyond this (drops are counted in
    # kvtpu_trace_dropped_spans_total).
    max_spans: int = 10_000
    # Span attribution identity; defaults to the pod/shard id the owning
    # service already knows.
    process_identity: str = ""
    # The collector's address (host:port), informational for operators /
    # kvdiag --fleet; pods never dial it (the collector pulls).
    collector_address: str = ""
    # Continuous profiling (``pyprof`` sub-block): the always-on sampling
    # profiler exported at /debug/pyprof. Off by default; the sampler's
    # own cost is gated <1% of score p50 by ``bench.py --pyprof-overhead``.
    pyprof: SamplingProfilerConfig = field(
        default_factory=SamplingProfilerConfig)
    # Working-set analytics (``workingset`` sub-block): the SHARDS-style
    # reuse sampler exported at /debug/workingset. Off by default; its
    # cost is gated <1% of score p50 by ``bench.py --workingset``.
    workingset: WorkingSetConfig = field(default_factory=WorkingSetConfig)
    # Ground-truth audit plane (telemetry/audit.py): record score-time
    # predictions (and, on engine pods, realized outcomes) in a ring
    # exported at /debug/audit for the collector's score-vs-reality
    # join. Off by default; the score-path hook is gated <1% of score
    # p50 by ``bench.py --audit``.
    audit: bool = False
    # Audit ring depth; evict-oldest beyond this (drops are counted in
    # kvtpu_audit_dropped_records_total).
    audit_max_records: int = 2048

    @classmethod
    def from_dict(cls, data: Optional[dict]) -> Optional["FleetTelemetryConfig"]:
        if not data:
            return None

        def k(camel: str, snake: str, default):
            if camel in data:
                return data[camel]
            if snake in data:
                return data[snake]
            return default

        d = cls()
        return cls(
            span_export=bool(k("spanExport", "span_export", d.span_export)),
            max_spans=int(k("maxSpans", "max_spans", d.max_spans)),
            process_identity=str(
                k("processIdentity", "process_identity", d.process_identity)),
            collector_address=str(
                k("collectorAddress", "collector_address",
                  d.collector_address)),
            pyprof=SamplingProfilerConfig.from_dict(
                k("pyprof", "pyprof", None)),
            workingset=WorkingSetConfig.from_dict(
                k("workingset", "workingset", None)),
            audit=bool(k("audit", "audit", d.audit)),
            audit_max_records=int(
                k("auditMaxRecords", "audit_max_records",
                  d.audit_max_records)),
        )


def enable_span_export(
    config: FleetTelemetryConfig,
    default_identity: str = "",
) -> Optional[Callable[[int], dict]]:
    """Install (or reuse) the ring exporter per ``config``.

    Returns the ``/debug/spans`` source callable to hand to
    ``AdminServer.register_spans_source``, or None when span export is
    disabled. An exporter already installed (tests, another service in
    the same process) is reused rather than replaced, so every in-process
    service shares one ring and one seq space.
    """
    if not config.span_export:
        return None
    set_process_identity(config.process_identity or default_identity or None)
    exporter = active_span_exporter()
    if exporter is None:
        exporter = install_span_exporter(
            InMemorySpanExporter(max_spans=config.max_spans))

    def source(since: int, _exp=exporter) -> dict:
        payload = _exp.export_since(since)
        try:
            from ..metrics.collector import record_spans_exported

            record_spans_exported(len(payload["spans"]))
        except Exception:  # pragma: no cover  # lint: allow-swallow
            pass
        return payload

    return source


def enable_pyprof(
    config: FleetTelemetryConfig,
    default_identity: str = "",
) -> Optional[tuple]:
    """Install + start the sampling profiler per ``config.pyprof``.

    Returns ``(source, capture)`` callables to hand to
    ``AdminServer.register_pyprof_source`` /
    ``register_pyprof_capture``, or None when continuous profiling is
    disabled. Like :func:`enable_span_export`, a profiler already
    installed in this process is reused (one sampler per process — the
    OS only has one set of thread stacks to walk).
    """
    if not config.pyprof.enabled:
        return None
    set_process_identity(config.process_identity or default_identity or None)
    profiler = active_sampling_profiler()
    if profiler is None:
        profiler = install_sampling_profiler(SamplingProfiler(config.pyprof))
    profiler.start()

    def source(since: int, _p=profiler) -> dict:
        return _p.export_since(since)

    def capture(seconds: float, _p=profiler) -> dict:
        return _p.capture(seconds)

    return source, capture


def enable_workingset(
    config: FleetTelemetryConfig,
    default_identity: str = "",
) -> Optional[WorkingSetTracker]:
    """Install (or reuse) the working-set tracker per ``config.workingset``.

    Returns the tracker — callers attach it to their hot paths
    (``Indexer.attach_workingset``, ``MiniEngine.attach_workingset``) and
    hand ``tracker.export_since`` to
    ``AdminServer.register_workingset_source``. None when disabled. Like
    the span exporter, a tracker already installed in this process is
    reused so co-resident services share one sampled reuse stream.
    """
    if not config.workingset.enabled:
        return None
    set_process_identity(config.process_identity or default_identity or None)
    tracker = active_workingset_tracker()
    if tracker is None:
        tracker = install_workingset_tracker(
            WorkingSetTracker(config.workingset))
    return tracker
