"""OpenTelemetry tracing with a no-op fallback.

Counterpart of reference ``pkg/telemetry/tracing.go``: spans are attached
unconditionally throughout the read/write paths via decorator wrappers and
no-op when no provider is configured (``indexer.go:90-103``). ``init_tracing``
configures an OTLP exporter from the standard ``OTEL_*`` env vars when the
optional exporter packages are importable; in library mode the host process's
global provider is used untouched.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

try:
    from opentelemetry import trace as _otel_trace
except Exception:  # pragma: no cover - otel always present in this image
    _otel_trace = None

_SERVICE_NAME = "llmd-kv-cache-tpu"


class _NoopSpan:
    def set_attribute(self, *_args, **_kwargs) -> None:
        pass

    def record_exception(self, *_args, **_kwargs) -> None:
        pass


class _Tracer:
    """Thin facade: OTel tracer when available, no-op otherwise."""

    def __init__(self) -> None:
        self._otel_tracer = None
        if _otel_trace is not None:
            self._otel_tracer = _otel_trace.get_tracer(_SERVICE_NAME)

    @contextlib.contextmanager
    def span(self, name: str, **attributes) -> Iterator[object]:
        if self._otel_tracer is None:
            yield _NoopSpan()
            return
        with self._otel_tracer.start_as_current_span(name) as sp:
            for k, v in attributes.items():
                sp.set_attribute(k, v)
            yield sp


_tracer: Optional[_Tracer] = None


def tracer() -> _Tracer:
    global _tracer
    if _tracer is None:
        _tracer = _Tracer()
    return _tracer


def init_tracing(service_name: Optional[str] = None) -> bool:
    """Standalone-mode init from OTEL_* env (reference tracing.go:72-141).

    Returns True when an OTLP exporter was installed; False when running in
    library mode (host provider reused) or exporters are unavailable.
    """
    global _tracer
    if _otel_trace is None:
        return False
    exporter_kind = os.environ.get("OTEL_TRACES_EXPORTER", "otlp")
    if exporter_kind in ("none", ""):
        return False
    try:
        from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import OTLPSpanExporter
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor
        from opentelemetry.sdk.trace.sampling import ParentBasedTraceIdRatio
    except Exception:
        return False

    endpoint = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT", "http://localhost:4317")
    ratio = float(os.environ.get("OTEL_TRACES_SAMPLER_ARG", "0.1"))
    provider = TracerProvider(
        resource=Resource.create(
            {"service.name": os.environ.get("OTEL_SERVICE_NAME", service_name or _SERVICE_NAME)}
        ),
        sampler=ParentBasedTraceIdRatio(ratio),
    )
    provider.add_span_processor(BatchSpanProcessor(OTLPSpanExporter(endpoint=endpoint)))
    _otel_trace.set_tracer_provider(provider)
    _tracer = None  # rebuild against the new provider
    return True
