"""OpenTelemetry tracing with a no-op fallback and a built-in recorder.

Counterpart of reference ``pkg/telemetry/tracing.go``: spans are attached
unconditionally throughout the read/write paths via a thin facade and no-op
when no provider is configured (``indexer.go:90-103``). ``init_tracing``
configures an OTLP exporter from the standard ``OTEL_*`` env vars when the
optional exporter packages are importable; in library mode the host
process's global provider is used untouched.

Three operating modes, resolved per ``span()`` call in priority order:

1. **recording** — an in-process :class:`InMemorySpanExporter` installed via
   :func:`install_span_exporter`. Spans are plain Python objects with real
   trace/span ids, parentage via ``contextvars`` plus explicit W3C
   ``traceparent`` strings, and land in the exporter on exit. This needs
   only the stdlib, so cross-hop trace assertions work even on images that
   ship ``opentelemetry-api`` without the SDK.
2. **otel** — a real TracerProvider is installed on the global OTel API
   (either by :func:`init_tracing` or by the host process). Attributes are
   passed at span start; exceptions are recorded with ERROR status.
3. **noop** — neither of the above: a shared zero-allocation span that
   accepts ``set_attribute`` chains and costs one identity check per call.

W3C trace-context helpers (:func:`current_traceparent`,
:func:`parse_traceparent`) are the single source of truth for propagation
across the gRPC tokenizer hop and the ZMQ event wire.
"""

from __future__ import annotations

import contextlib
import os
import random
import re
import threading
import time
from collections import deque
from typing import Iterator, Optional

try:
    from opentelemetry import trace as _otel_trace
except Exception:  # pragma: no cover - otel always present in this image
    _otel_trace = None

import contextvars

from ..utils.lockdep import new_lock

_SERVICE_NAME = "llmd-kv-cache-tpu"

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def format_traceparent(trace_id: int, span_id: int, sampled: bool = True) -> str:
    """Render a W3C ``traceparent`` header value (version 00)."""
    return f"00-{trace_id:032x}-{span_id:016x}-{0x01 if sampled else 0x00:02x}"


def parse_traceparent(value: Optional[str]) -> Optional[tuple[int, int, int]]:
    """Parse ``traceparent`` → ``(trace_id, span_id, flags)``; None if invalid.

    Malformed values are dropped rather than raised: a bad header from a
    remote peer must never break event ingestion or an RPC.
    """
    if not value or not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    trace_id = int(m.group(1), 16)
    span_id = int(m.group(2), 16)
    if trace_id == 0 or span_id == 0:
        return None
    return trace_id, span_id, int(m.group(3), 16)


class _NoopSpan:
    """Shared do-nothing span; every mutator chains so call sites can write
    ``span.set_attribute(...).set_attribute(...)`` without mode checks."""

    __slots__ = ()

    def set_attribute(self, *_args, **_kwargs) -> "_NoopSpan":
        return self

    def set_attributes(self, *_args, **_kwargs) -> "_NoopSpan":
        return self

    def add_event(self, *_args, **_kwargs) -> "_NoopSpan":
        return self

    def record_exception(self, *_args, **_kwargs) -> None:
        pass

    def set_status(self, *_args, **_kwargs) -> None:
        pass


_NOOP_SPAN = _NoopSpan()


class _NoopSpanCM:
    """Reusable, allocation-free context manager for the no-op path."""

    __slots__ = ()

    def __enter__(self) -> _NoopSpan:
        return _NOOP_SPAN

    def __exit__(self, *_exc) -> bool:
        return False


_NOOP_CM = _NoopSpanCM()

# Logical process identity ("engine-pod-0", "shard:127.0.0.1:15920",
# "router", ...) stamped onto every exported span that does not already
# carry an explicit ``process`` attribute. The fleet collector attributes
# critical-path segments to these identities; in production each identity
# also maps to a distinct scrape endpoint.
_PROCESS_IDENTITY: Optional[str] = None


def set_process_identity(identity: Optional[str]) -> None:
    """Set (or clear, with None) this process's span attribution identity."""
    global _PROCESS_IDENTITY
    _PROCESS_IDENTITY = identity


def process_identity() -> Optional[str]:
    return _PROCESS_IDENTITY


_dropped_counter = None


def _count_dropped_span() -> None:
    """Bump ``kvtpu_trace_dropped_spans_total`` (lazy: tracing must stay
    importable without the metrics stack, e.g. under kvdiag deep-debug)."""
    global _dropped_counter
    if _dropped_counter is None:
        try:
            from llmd_kv_cache_tpu.metrics.collector import TRACE_DROPPED_SPANS

            _dropped_counter = TRACE_DROPPED_SPANS
        except Exception:  # pragma: no cover - metrics stack absent
            _dropped_counter = False
    if _dropped_counter:
        try:
            _dropped_counter.inc()
        except Exception:  # pragma: no cover  # lint: allow-swallow
            pass


class RecordedSpan:
    """A finished-or-active span in recording mode.

    Mirrors the slice of the OTel Span API the library uses (set_attribute,
    record_exception, set_status) plus the readback fields tests assert on.
    """

    __slots__ = (
        "name",
        "trace_id",
        "span_id",
        "parent_span_id",
        "attributes",
        "events",
        "status",
        "status_description",
        "start_time",
        "end_time",
        "seq",
    )

    def __init__(
        self,
        name: str,
        trace_id: int,
        span_id: int,
        parent_span_id: Optional[int],
        attributes: Optional[dict] = None,
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id
        self.attributes = dict(attributes) if attributes else {}
        self.events: list[tuple[str, dict]] = []
        self.status = "UNSET"
        self.status_description: Optional[str] = None
        self.start_time = time.time()
        self.end_time: Optional[float] = None
        # Monotonic export sequence number, stamped by the exporter so
        # remote pullers (/debug/spans?since=seq) can resume a cursor.
        self.seq: Optional[int] = None

    def set_attribute(self, key: str, value) -> "RecordedSpan":
        self.attributes[key] = value
        return self

    def set_attributes(self, attributes: dict) -> "RecordedSpan":
        self.attributes.update(attributes)
        return self

    def add_event(self, name: str, attributes: Optional[dict] = None) -> "RecordedSpan":
        self.events.append((name, attributes or {}))
        return self

    def record_exception(self, exc: BaseException) -> None:
        self.events.append(
            ("exception", {"exception.type": type(exc).__name__, "exception.message": str(exc)})
        )

    def set_status(self, status: str, description: Optional[str] = None) -> None:
        self.status = status
        self.status_description = description

    @property
    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id)

    def to_wire(self) -> dict:
        """JSON-safe dict for span export over ``/debug/spans``.

        Ids travel as hex strings (W3C casing), attribute values are
        coerced to JSON scalars so a numpy int at a span site can never
        break the export path.
        """

        def _scalar(v):
            if isinstance(v, (str, bool)) or v is None:
                return v
            if isinstance(v, (int, float)):
                return v
            try:  # numpy scalars and friends
                return v.item()
            except Exception:
                return str(v)

        return {
            "name": self.name,
            "trace_id": f"{self.trace_id:032x}",
            "span_id": f"{self.span_id:016x}",
            "parent_span_id": (
                None if self.parent_span_id is None else f"{self.parent_span_id:016x}"
            ),
            "start_time": self.start_time,
            "end_time": self.end_time,
            "status": self.status,
            "attributes": {str(k): _scalar(v) for k, v in self.attributes.items()},
            "seq": self.seq,
        }

    @classmethod
    def from_wire(cls, data: dict) -> "RecordedSpan":
        """Inverse of :meth:`to_wire` (collector side)."""
        parent = data.get("parent_span_id")
        sp = cls(
            str(data.get("name", "")),
            int(str(data.get("trace_id", "0")) or "0", 16),
            int(str(data.get("span_id", "0")) or "0", 16),
            None if parent in (None, "") else int(str(parent), 16),
            data.get("attributes") or {},
        )
        sp.start_time = float(data.get("start_time") or 0.0)
        end = data.get("end_time")
        sp.end_time = None if end is None else float(end)
        sp.status = str(data.get("status", "UNSET"))
        sp.seq = data.get("seq")
        return sp

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"RecordedSpan({self.name!r}, trace={self.trace_id:032x}, "
            f"span={self.span_id:016x}, parent="
            f"{'-' if self.parent_span_id is None else format(self.parent_span_id, '016x')})"
        )


class InMemorySpanExporter:
    """Collects finished :class:`RecordedSpan` objects for assembly/export.

    Stand-in for ``opentelemetry.sdk``'s in-memory exporter on images where
    only ``opentelemetry-api`` is installed — and the local buffer behind
    the admin ``/debug/spans?since=seq`` pull endpoint.

    The buffer is a ring: when ``max_spans`` is reached the **oldest** span
    is evicted (previously new spans were silently discarded, which meant a
    long-lived pod stopped tracing entirely once warm). Every eviction is
    counted both locally (:attr:`dropped`) and in the
    ``kvtpu_trace_dropped_spans_total`` counter so the collector can see
    export-loss on a lagging cursor.

    Spans are stamped with a monotonically increasing ``seq`` (and, when
    missing, the process identity) lazily — at pull time under the ring
    lock, not on the per-span export hot path — so :meth:`drain_since`
    lets a remote puller resume from its last cursor while ``export``
    itself stays a bare ring append (gated <1% of score p50 by
    ``bench.py --fleet-telemetry``).
    """

    __slots__ = ("_lock", "_spans", "_max_spans", "_next_seq", "dropped")

    def __init__(self, max_spans: int = 10_000):
        self._lock = new_lock()
        self._spans: deque[RecordedSpan] = deque(maxlen=max(1, int(max_spans)))
        self._max_spans = max(1, int(max_spans))
        self._next_seq = 0
        self.dropped = 0

    def export(self, span: RecordedSpan) -> None:
        # Hot path: runs inline at every span end once fleet span export
        # is on. Everything deferrable (seq + identity stamping, wire
        # encoding) happens at pull time instead.
        spans = self._spans
        with self._lock:
            if len(spans) >= self._max_spans:
                self.dropped += 1
                _count_dropped_span()
            spans.append(span)  # at capacity the deque evicts the oldest

    def _stamp_locked(self) -> None:
        """Assign ``seq`` (and process identity) to not-yet-stamped spans.

        Caller holds ``self._lock``. Spans are stamped newest-backwards
        until the first already-stamped one, so the cost is O(new spans)
        per pull, not O(ring).
        """
        fresh = []
        for span in reversed(self._spans):
            if span.seq is not None:
                break
            fresh.append(span)
        identity = _PROCESS_IDENTITY
        for span in reversed(fresh):
            span.seq = self._next_seq
            self._next_seq += 1
            if identity is not None and "process" not in span.attributes:
                span.attributes["process"] = identity

    @property
    def spans(self) -> list[RecordedSpan]:
        with self._lock:
            return list(self._spans)

    @property
    def next_seq(self) -> int:
        with self._lock:
            self._stamp_locked()
            return self._next_seq

    def drain_since(self, since: int = -1) -> tuple[list[RecordedSpan], int]:
        """Spans with ``seq > since`` (oldest first) and the next cursor.

        Non-destructive: the ring keeps its contents so several pullers
        (or a retried pull) each keep their own cursor; the collector
        dedupes by span id anyway.
        """
        with self._lock:
            self._stamp_locked()
            out = [s for s in self._spans if s.seq is not None and s.seq > since]
            return out, self._next_seq - 1

    def export_since(self, since: int = -1) -> dict:
        """JSON-safe ``/debug/spans`` payload: spans + cursor + drop count."""
        spans, cursor = self.drain_since(since)
        return {
            "spans": [s.to_wire() for s in spans if s.end_time is not None],
            "next_seq": cursor,
            "dropped": self.dropped,
        }

    def find(self, name: str) -> list[RecordedSpan]:
        return [s for s in self.spans if s.name == name]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


# Ambient current span for recording mode. contextvars gives correct
# nesting per-thread / per-async-task; cross-thread and cross-process hops
# must pass an explicit traceparent (which is what the wire formats do).
_CURRENT_SPAN: contextvars.ContextVar[Optional[RecordedSpan]] = contextvars.ContextVar(
    "kvtpu_current_span", default=None
)

# Cross-thread view of each thread's innermost active span *name*.
# contextvars are only readable from their own thread, but the sampling
# profiler (telemetry/sampling_profiler.py) walks ``sys._current_frames()``
# from a background thread and must attribute each sampled stack to the
# span the sampled thread is inside. A plain dict keyed by thread ident is
# enough: single-key int reads/writes are atomic under the GIL, so the hot
# path stays two dict stores per span (inside the <1% budget that
# ``bench.py --fleet-telemetry`` gates) and the sampler reads without a
# lock — a momentarily stale name only mis-tags one 15 ms sample.
_THREAD_SPAN_NAMES: dict[int, str] = {}


def active_span_names() -> dict[int, str]:
    """Snapshot of thread ident → innermost active span name.

    Read by the sampling profiler; copies so the caller can iterate while
    spans keep opening/closing.
    """
    return dict(_THREAD_SPAN_NAMES)

_recording_exporter: Optional[InMemorySpanExporter] = None


def _new_trace_id() -> int:
    return random.getrandbits(128) or 1


def _new_span_id() -> int:
    return random.getrandbits(64) or 1


def _otel_provider_configured() -> bool:
    """True when a real (recording) TracerProvider is installed globally.

    The api-only default providers live under ``opentelemetry.trace``; any
    real SDK (or host-supplied) provider comes from another module.
    """
    if _otel_trace is None:
        return False
    provider = _otel_trace.get_tracer_provider()
    return not type(provider).__module__.startswith("opentelemetry.trace")


class _Tracer:
    """Thin facade: recording exporter > OTel provider > no-op."""

    def __init__(self) -> None:
        self._otel_tracer = None
        if _otel_trace is not None and _otel_provider_configured():
            self._otel_tracer = _otel_trace.get_tracer(_SERVICE_NAME)

    def span(
        self,
        name: str,
        parent_traceparent: Optional[str] = None,
        **attributes,
    ):
        """Context manager yielding a span.

        ``parent_traceparent`` (a W3C header value) links this span under a
        remote parent — used on the server side of the gRPC hop and by the
        event-pool ingest loop; when omitted the ambient current span (if
        any) is the parent. Remaining kwargs become span attributes, set at
        span start. On exception exit the exception is recorded on the span
        with ERROR status and re-raised.
        """
        if _recording_exporter is not None:
            return self._recording_span(name, parent_traceparent, attributes)
        if self._otel_tracer is not None:
            return self._otel_span(name, parent_traceparent, attributes)
        return _NOOP_CM

    @contextlib.contextmanager
    def _recording_span(
        self, name: str, parent_traceparent: Optional[str], attributes: dict
    ) -> Iterator[RecordedSpan]:
        exporter = _recording_exporter
        trace_id: Optional[int] = None
        parent_id: Optional[int] = None
        parsed = parse_traceparent(parent_traceparent)
        if parsed is not None:
            trace_id, parent_id, _flags = parsed
        else:
            cur = _CURRENT_SPAN.get()
            if cur is not None:
                trace_id, parent_id = cur.trace_id, cur.span_id
        if trace_id is None:
            trace_id = _new_trace_id()
        sp = RecordedSpan(name, trace_id, _new_span_id(), parent_id, attributes)
        token = _CURRENT_SPAN.set(sp)
        tid = threading.get_ident()
        prev_name = _THREAD_SPAN_NAMES.get(tid)
        _THREAD_SPAN_NAMES[tid] = name
        try:
            yield sp
        except BaseException as exc:
            sp.record_exception(exc)
            sp.set_status("ERROR", str(exc))
            raise
        finally:
            if prev_name is None:
                _THREAD_SPAN_NAMES.pop(tid, None)
            else:
                _THREAD_SPAN_NAMES[tid] = prev_name
            _CURRENT_SPAN.reset(token)
            sp.end_time = time.time()
            if exporter is not None:
                exporter.export(sp)

    @contextlib.contextmanager
    def _otel_span(
        self, name: str, parent_traceparent: Optional[str], attributes: dict
    ) -> Iterator[object]:
        context = None
        parsed = parse_traceparent(parent_traceparent)
        if parsed is not None:
            trace_id, span_id, flags = parsed
            remote = _otel_trace.SpanContext(
                trace_id=trace_id,
                span_id=span_id,
                is_remote=True,
                trace_flags=_otel_trace.TraceFlags(flags),
            )
            context = _otel_trace.set_span_in_context(_otel_trace.NonRecordingSpan(remote))
        tid = threading.get_ident()
        prev_name = _THREAD_SPAN_NAMES.get(tid)
        _THREAD_SPAN_NAMES[tid] = name
        try:
            with self._otel_tracer.start_as_current_span(
                name, context=context, attributes=attributes or None, end_on_exit=True
            ) as sp:
                try:
                    yield sp
                except BaseException as exc:
                    sp.record_exception(exc)
                    try:
                        from opentelemetry.trace import Status, StatusCode

                        sp.set_status(Status(StatusCode.ERROR, str(exc)))
                    except Exception:  # pragma: no cover - api drift  # lint: allow-swallow
                        pass
                    raise
        finally:
            if prev_name is None:
                _THREAD_SPAN_NAMES.pop(tid, None)
            else:
                _THREAD_SPAN_NAMES[tid] = prev_name


_tracer: Optional[_Tracer] = None


def tracer() -> _Tracer:
    global _tracer
    if _tracer is None:
        _tracer = _Tracer()
    return _tracer


def current_traceparent() -> Optional[str]:
    """The ambient span's W3C ``traceparent``, or None when untraced.

    This is what gets injected into outbound gRPC metadata and onto the
    ZMQ event wire.
    """
    if _recording_exporter is not None:
        cur = _CURRENT_SPAN.get()
        if cur is not None:
            return cur.traceparent
        return None
    if _otel_trace is not None:
        ctx = _otel_trace.get_current_span().get_span_context()
        if ctx is not None and ctx.trace_id != 0 and ctx.span_id != 0:
            return format_traceparent(
                ctx.trace_id, ctx.span_id, bool(int(ctx.trace_flags) & 0x01)
            )
    return None


def install_span_exporter(
    exporter: Optional[InMemorySpanExporter] = None,
) -> InMemorySpanExporter:
    """Switch the facade into recording mode (tests, ``kvdiag`` deep-debug).

    Returns the active exporter (created when not supplied). Call
    :func:`uninstall_span_exporter` to restore the previous mode.
    """
    global _recording_exporter, _tracer
    if exporter is None:
        exporter = InMemorySpanExporter()
    _recording_exporter = exporter
    _tracer = None  # rebuild so mode resolution sees the exporter
    return exporter


def uninstall_span_exporter() -> None:
    global _recording_exporter, _tracer
    _recording_exporter = None
    _tracer = None


def active_span_exporter() -> Optional[InMemorySpanExporter]:
    """The currently installed recording exporter, if any (fleet wiring
    reuses an already-installed exporter instead of replacing it)."""
    return _recording_exporter


@contextlib.contextmanager
def recording_tracing(
    exporter: Optional[InMemorySpanExporter] = None,
) -> Iterator[InMemorySpanExporter]:
    """Scoped :func:`install_span_exporter` — the test-fixture form."""
    installed = install_span_exporter(exporter)
    try:
        yield installed
    finally:
        uninstall_span_exporter()


def init_tracing(service_name: Optional[str] = None) -> bool:
    """Standalone-mode init from OTEL_* env (reference tracing.go:72-141).

    Returns True when an OTLP exporter was installed; False when running in
    library mode (host provider reused) or exporters are unavailable.
    """
    global _tracer
    if _otel_trace is None:
        return False
    exporter_kind = os.environ.get("OTEL_TRACES_EXPORTER", "otlp")
    if exporter_kind in ("none", ""):
        return False
    try:
        from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import OTLPSpanExporter
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor
        from opentelemetry.sdk.trace.sampling import ParentBasedTraceIdRatio
    except Exception:
        return False

    endpoint = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT", "http://localhost:4317")
    ratio = float(os.environ.get("OTEL_TRACES_SAMPLER_ARG", "0.1"))
    provider = TracerProvider(
        resource=Resource.create(
            {"service.name": os.environ.get("OTEL_SERVICE_NAME", service_name or _SERVICE_NAME)}
        ),
        sampler=ParentBasedTraceIdRatio(ratio),
    )
    provider.add_span_processor(BatchSpanProcessor(OTLPSpanExporter(endpoint=endpoint)))
    _otel_trace.set_tracer_provider(provider)
    _tracer = None  # rebuild against the new provider
    return True
