"""Engine data-plane telemetry: request lifecycle, KV-pool gauges, profiler.

The serving engine (``models/engine.py``) is the half of the system the
paper's latency claims rest on, and before this module it emitted nothing.
``EngineTelemetry`` turns the engine's lifecycle into the three serving
histograms operators actually watch — TTFT (enqueue to first token), ITL
(inter-token latency), TPOT (time per output token) — plus KV-pool
occupancy gauges and per-request flight-recorder events, without touching
the step path's allocation budget (``bench.py --engine-telemetry`` asserts
the per-step hook cost stays under 1% of the decode-step p50).

Design constraints, in order:

- **Allocation-light on the step path.** Hooks mutate a preallocated
  ``_ReqState`` (``__slots__``), observe into :class:`BucketHistogram`
  (one bisect + three stores), and scrape pool gauges only every
  ``pool_gauge_every`` steps. No dicts are built per decode step.
- **Config-driven buckets.** TTFT on a CPU dev loop and TTFT on a v5e pod
  differ by two orders of magnitude; bucket bounds come from
  :class:`EngineTelemetryConfig` (``engineTelemetry`` in config files),
  not module constants.
- **One trace from score to serve.** The engine itself creates spans
  (gated on a request carrying a ``traceparent``); this module only keeps
  the lifecycle clock. See ``docs/observability.md``.

``ProfilerCapture`` wraps on-demand ``jax.profiler`` xplane captures for
the admin endpoint's ``/debug/profile?duration_s=N`` (guarded: requires a
configured ``profileDir``; one capture at a time).
"""

from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..utils.lockdep import new_lock
from ..metrics import collector
from ..utils.logging import get_logger
from . import flight_recorder as fr
from .tracing import parse_traceparent

logger = get_logger("engine_telemetry")


def trace_id_of(traceparent: Optional[str]) -> Optional[str]:
    """Hex trace id from a W3C traceparent, for histogram exemplars."""
    parsed = parse_traceparent(traceparent)
    return None if parsed is None else f"{parsed[0]:032x}"

# Default bucket bounds span CPU dev loops through TPU pods; deployments
# with tighter SLOs override them via EngineTelemetryConfig.
DEFAULT_TTFT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)
DEFAULT_ITL_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
)
DEFAULT_STEP_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.5, 1.0,
)

MAX_PROFILE_DURATION_S = 60.0


class ProfileInProgress(RuntimeError):
    """A jax.profiler capture is already running (admin maps this to 409)."""


def _as_buckets(value, default: Tuple[float, ...]) -> Tuple[float, ...]:
    if value is None:
        return default
    return tuple(float(v) for v in value)


@dataclass
class EngineTelemetryConfig:
    """Knobs for the engine observability layer (``engineTelemetry``)."""

    enabled: bool = True
    ttft_buckets: Tuple[float, ...] = DEFAULT_TTFT_BUCKETS
    itl_buckets: Tuple[float, ...] = DEFAULT_ITL_BUCKETS
    tpot_buckets: Tuple[float, ...] = DEFAULT_ITL_BUCKETS
    step_buckets: Tuple[float, ...] = DEFAULT_STEP_BUCKETS
    # Pool gauges are scraped once every N steps: gauge label lookups are
    # ~1us each and a tiny-model CPU decode step is sub-millisecond, so an
    # every-step scrape alone could eat the 1% overhead budget.
    pool_gauge_every: int = 16
    # One flight-recorder record per request phase transition (admit,
    # finish); decode steps never write to the ring.
    flight_records: bool = True
    # Directory for on-demand jax.profiler captures; empty disables the
    # /debug/profile endpoint.
    profile_dir: str = ""
    # Ring of per-request lifecycle summaries kept for /debug/vars.
    max_finished: int = 64

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "EngineTelemetryConfig":
        if not d:
            return cls()

        def k(camel, snake, default):
            return d.get(camel, d.get(snake, default))

        return cls(
            enabled=bool(k("enabled", "enabled", True)),
            ttft_buckets=_as_buckets(
                k("ttftBuckets", "ttft_buckets", None), DEFAULT_TTFT_BUCKETS),
            itl_buckets=_as_buckets(
                k("itlBuckets", "itl_buckets", None), DEFAULT_ITL_BUCKETS),
            tpot_buckets=_as_buckets(
                k("tpotBuckets", "tpot_buckets", None), DEFAULT_ITL_BUCKETS),
            step_buckets=_as_buckets(
                k("stepBuckets", "step_buckets", None), DEFAULT_STEP_BUCKETS),
            pool_gauge_every=int(k("poolGaugeEvery", "pool_gauge_every", 16)),
            flight_records=bool(k("flightRecords", "flight_records", True)),
            profile_dir=str(k("profileDir", "profile_dir", "")),
            max_finished=int(k("maxFinished", "max_finished", 64)),
        )


class _ReqState:
    """Per-request lifecycle clock. Preallocated; mutated in place."""

    __slots__ = (
        "request_id", "traceparent", "enqueue_ts", "admit_ts",
        "first_token_ts", "last_token_ts", "tokens", "prefix_hit_blocks",
    )

    def __init__(self, request_id: str, now: float, prefix_hit_blocks: int,
                 traceparent: Optional[str]):
        self.request_id = request_id
        self.traceparent = traceparent
        self.enqueue_ts = now
        self.admit_ts: Optional[float] = None
        self.first_token_ts: Optional[float] = None
        self.last_token_ts: Optional[float] = None
        self.tokens = 0
        self.prefix_hit_blocks = prefix_hit_blocks

    def summary(self, finish_ts: float, outcome: str) -> dict:
        return {
            "request_id": self.request_id,
            "enqueue_ts": self.enqueue_ts,
            "admit_ts": self.admit_ts,
            "first_token_ts": self.first_token_ts,
            "last_token_ts": self.last_token_ts,
            "finish_ts": finish_ts,
            "tokens": self.tokens,
            "prefix_hit_blocks": self.prefix_hit_blocks,
            "traced": self.traceparent is not None,
            "outcome": outcome,
        }


class ProfilerCapture:
    """On-demand ``jax.profiler`` xplane capture, one at a time."""

    def __init__(self, profile_dir: str):
        self.profile_dir = profile_dir
        self._lock = new_lock()
        self.last: Optional[dict] = None

    def capture(self, duration_s: float = 1.0) -> dict:
        """Run a blocking capture; returns ``{"dir", "duration_s", ...}``.

        Raises ``ValueError`` on a bad duration, :class:`ProfileInProgress`
        when a capture is already running, and ``RuntimeError`` when the
        platform/profiler refuses (surfaced as HTTP 400/409/500 by
        ``services/admin.py``).
        """
        duration_s = float(duration_s)
        if not (0.0 < duration_s <= MAX_PROFILE_DURATION_S):
            raise ValueError(
                f"duration_s must be in (0, {MAX_PROFILE_DURATION_S}], "
                f"got {duration_s}")
        if not self.profile_dir:
            raise RuntimeError("profiler capture disabled: no profileDir configured")
        if not self._lock.acquire(blocking=False):
            raise ProfileInProgress("a profiler capture is already running")
        try:
            import jax.profiler  # deferred: telemetry imports stay jax-free

            os.makedirs(self.profile_dir, exist_ok=True)
            started = time.time()
            try:
                jax.profiler.start_trace(self.profile_dir)
                time.sleep(duration_s)
            finally:
                jax.profiler.stop_trace()
        except Exception as exc:
            collector.record_profile_capture("failure")
            fr.record(fr.KIND_PROFILE, {"outcome": "failure", "error": str(exc)})
            raise RuntimeError(f"jax.profiler capture failed: {exc}") from exc
        finally:
            self._lock.release()
        self.last = {
            "dir": self.profile_dir,
            "duration_s": duration_s,
            "started_ts": started,
            "completed_ts": time.time(),
        }
        collector.record_profile_capture("success")
        fr.record(fr.KIND_PROFILE, {"outcome": "success", "dir": self.profile_dir,
                                    "duration_s": duration_s})
        return dict(self.last)


class EngineTelemetry:
    """Request-lifecycle + KV-pool telemetry for one ``MiniEngine``.

    Histograms are process-global (deduped by metric name), so several
    engines in one process aggregate into the same families; per-request
    state is per-instance. The engine calls the ``on_*`` hooks; everything
    else (admin endpoint, kvdiag) reads :meth:`debug_vars`.
    """

    def __init__(self, config: Optional[EngineTelemetryConfig] = None,
                 group: str = "0"):
        self.cfg = config or EngineTelemetryConfig()
        self.group = str(group)
        self.ttft = collector.bucket_histogram(
            "kvtpu_engine_ttft_seconds",
            "Time from enqueue to first output token",
            self.cfg.ttft_buckets)
        self.itl = collector.bucket_histogram(
            "kvtpu_engine_itl_seconds",
            "Inter-token latency between decode emissions",
            self.cfg.itl_buckets)
        self.tpot = collector.bucket_histogram(
            "kvtpu_engine_tpot_seconds",
            "Time per output token after the first",
            self.cfg.tpot_buckets)
        self.step_seconds = collector.bucket_histogram(
            "kvtpu_engine_decode_step_seconds",
            "Engine step() wall time",
            self.cfg.step_buckets)
        self._requests: Dict[str, _ReqState] = {}
        self.finished: deque = deque(maxlen=max(1, self.cfg.max_finished))
        self._step_counter = 0
        self._pool_stats: Dict[str, dict] = {}
        self._pool_evictions_seen: Dict[str, int] = {}
        self.profiler = ProfilerCapture(self.cfg.profile_dir)
        # Label children resolved once; labels() does a dict lookup + tuple
        # build per call, which the scrape path should not pay repeatedly.
        self._gauge_cache: Dict[str, tuple] = {}
        # Padding-waste accumulators (on_dispatch_tokens): real vs padded
        # tokens per device dispatch, fed by the ragged path AND the
        # padded fallback so the waste ratio compares the schedulers.
        self._dispatch_real = 0
        self._dispatch_padded = 0
        self._dispatches = 0
        self._last_waste_ratio = 0.0

    # -- lifecycle hooks (called by MiniEngine) ---------------------------

    def on_admitted(self, request_id: str, prefix_hit_blocks: int,
                    traceparent: Optional[str] = None) -> None:
        now = time.monotonic()
        self._requests[request_id] = _ReqState(
            request_id, now, prefix_hit_blocks, traceparent)
        if prefix_hit_blocks > 0:
            collector.ENGINE_PREFIX_HIT_BLOCKS.inc(prefix_hit_blocks)
        if self.cfg.flight_records:
            fr.record(fr.KIND_ENGINE_REQUEST, {
                "request_id": request_id, "phase": "admit",
                "prefix_hit_blocks": prefix_hit_blocks})

    def set_traceparent(self, request_id: str, traceparent: Optional[str]) -> None:
        st = self._requests.get(request_id)
        if st is not None:
            st.traceparent = traceparent

    def on_first_schedule(self, request_id: str) -> None:
        st = self._requests.get(request_id)
        if st is not None and st.admit_ts is None:
            st.admit_ts = time.monotonic()

    def on_first_token(self, request_id: str) -> None:
        st = self._requests.get(request_id)
        if st is None:
            return
        now = time.monotonic()
        st.first_token_ts = now
        st.last_token_ts = now
        st.tokens = 1
        if st.admit_ts is None:  # synchronous add_request path
            st.admit_ts = st.enqueue_ts
        # The trace-id exemplar links a slow TTFT bucket straight to the
        # retained trace in the fleet collector (OpenMetrics exposition).
        self.ttft.observe(now - st.enqueue_ts,
                          trace_id=trace_id_of(st.traceparent))

    def on_decode_tokens(self, request_id: str, n: int, now: float) -> None:
        st = self._requests.get(request_id)
        if st is None or n <= 0:
            return
        last = st.last_token_ts
        if last is None:  # decode before a recorded first token: treat as first
            st.first_token_ts = now
            st.tokens = n
            st.last_token_ts = now
            return
        gap = (now - last) / n
        observe = self.itl.observe
        for _ in range(n):
            observe(gap)
        st.tokens += n
        st.last_token_ts = now

    def on_finish(self, request_id: str, outcome: str = "finished") -> None:
        st = self._requests.pop(request_id, None)
        if st is None:
            return
        now = time.monotonic()
        if st.tokens > 1 and st.first_token_ts is not None \
                and st.last_token_ts is not None:
            self.tpot.observe(
                (st.last_token_ts - st.first_token_ts) / (st.tokens - 1))
        collector.ENGINE_REQUESTS.labels(outcome).inc()
        summary = st.summary(now, outcome)
        self.finished.append(summary)
        if self.cfg.flight_records:
            fr.record(fr.KIND_ENGINE_REQUEST, {
                "request_id": request_id, "phase": "finish",
                "outcome": outcome, "tokens": st.tokens})

    def on_step(self, duration_s: float, decoded: bool,
                pools: Sequence[Tuple[str, Any]] = ()) -> None:
        """Once per engine ``step()``: step timing + decimated pool scrape.

        ``pools`` is ``[(group_name, block_manager), ...]``; each block
        manager answers :meth:`~models.engine.BlockManager.pool_stats`.
        """
        self.step_seconds.observe(duration_s)
        if decoded:
            collector.ENGINE_DECODE_STEPS.inc()
        self._step_counter += 1
        if self._step_counter % max(1, self.cfg.pool_gauge_every) == 0:
            self.scrape_pools(pools)

    def scrape_pools(self, pools: Sequence[Tuple[str, Any]]) -> None:
        for group, bm in pools:
            stats = bm.pool_stats()
            self._pool_stats[group] = stats
            gauges = self._gauge_cache.get(group)
            if gauges is None:
                gauges = (
                    collector.ENGINE_POOL_FREE_PAGES.labels(group),
                    collector.ENGINE_POOL_CACHED_BLOCKS.labels(group),
                    collector.ENGINE_POOL_ORPHAN_PAGES.labels(group),
                )
                self._gauge_cache[group] = gauges
            free_g, cached_g, orphan_g = gauges
            free_g.set(stats["free_pages"])
            cached_g.set(stats["cached_blocks"])
            orphan_g.set(stats["orphan_pages"])
            seen = self._pool_evictions_seen.get(group, 0)
            delta = stats["evictions"] - seen
            if delta > 0:
                collector.ENGINE_POOL_EVICTIONS.labels(group).inc(delta)
                self._pool_evictions_seen[group] = stats["evictions"]

    def on_restore(self, outcome: str, seconds: Optional[float] = None) -> None:
        collector.record_engine_restore(outcome, seconds)

    def on_dispatch_tokens(self, real: int, dispatched: int) -> None:
        """Padding-waste accounting for one device dispatch.

        ``real`` tokens of actual work rode a ``dispatched``-token padded
        program — the gap is pure padding FLOPs. Both the ragged
        single-kernel path and the padded fallback (prefill buckets,
        pad-to-max_batch decode) report here, so the
        ``kvtpu_engine_ragged_*_tokens_total`` counters directly compare
        the two schedulers' waste.
        """
        if dispatched <= 0:
            return
        collector.record_ragged_dispatch(self.group, real, dispatched)
        self._dispatch_real += real
        self._dispatch_padded += dispatched
        self._dispatches += 1
        self._last_waste_ratio = 1.0 - real / dispatched

    # -- read side --------------------------------------------------------

    def _phase_stats(self, hist) -> dict:
        return {
            "count": hist.count,
            "p50": hist.percentile(0.50),
            "p90": hist.percentile(0.90),
            "p99": hist.percentile(0.99),
        }

    def debug_vars(self) -> dict:
        """The ``engine`` section of ``/debug/vars`` (and kvdiag)."""
        return {
            "group": self.group,
            "pool": {g: dict(s) for g, s in self._pool_stats.items()},
            "requests": {
                "active": len(self._requests),
                "finished_window": len(self.finished),
                "recent": list(self.finished)[-8:],
            },
            "phases": {
                "ttft_seconds": self._phase_stats(self.ttft),
                "itl_seconds": self._phase_stats(self.itl),
                "tpot_seconds": self._phase_stats(self.tpot),
                "step_seconds": self._phase_stats(self.step_seconds),
            },
            "steps": self._step_counter,
            "ragged": {
                "real_tokens_total": self._dispatch_real,
                "padded_tokens_total": self._dispatch_padded,
                "last_waste_ratio": self._last_waste_ratio,
                "dispatches": self._dispatches,
            },
            "last_profile": self.profiler.last,
        }

    def attach_admin(self, server) -> None:
        """Register the debug provider and (if configured) the profiler."""
        server.register_debug("engine", self.debug_vars)
        if self.cfg.profile_dir:
            server.register_profiler(self.profiler.capture)

    def active_requests(self) -> List[str]:
        return list(self._requests)
