"""Whole-program assembly + the four concurrency rules.

Consumes the per-module :class:`~.model.ModuleModel`s and reports:

``CONC-REENTRY``
    A non-reentrant ``threading.Lock`` re-acquired on a call path that
    already holds it — the PR 3 ``_lag_mu`` self-deadlock class. Only
    same-instance (``self.*``) call chains count, so a ``Pool`` calling
    *another* object of the same class is not flagged (different
    instance, different lock).

``CONC-LOCK-ORDER``
    A cycle in the global lock-acquisition-order graph. Edge A→B exists
    when some method acquires B (directly, or through any resolvable
    call, cross-class and cross-module) while holding A. Cycles mean two
    threads can deadlock by taking the locks in opposite orders.

``CONC-BLOCKING``
    A blocking call — ``time.sleep``, socket/ZMQ ``recv*``,
    ``Future.result``, blocking ``queue.get``/``join``, ``Event.wait``,
    file/network IO — inside a lock region. Blocking under a lock turns
    every other acquirer into a convoy (and, with IO, a priority
    inversion). ``Condition.wait`` on the *held* condition is the
    sanctioned pattern and exempt.

``CONC-CALLBACK``
    A user-supplied callable stored on ``self`` (publish hooks, failpoint
    listeners, controller actuators, journal sinks…) invoked while a lock
    is held. The callback can run arbitrary code — including re-entering
    this object — so it must escape the critical section.

Suppression: ``# lint: allow-<rule> (why)`` on the violation line or on
the enclosing ``with`` line. The ``(why)`` is mandatory — a bare marker
is itself a finding (``CONC-BAD-MARKER``).
"""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path
from typing import Optional

from .model import (
    KIND_CONDITION,
    KIND_EVENT,
    KIND_LOCK,
    KIND_QUEUE,
    KIND_THREAD,
    AcqSite,
    CallSite,
    ClassModel,
    LockToken,
    MethodModel,
    ModuleModel,
    extract_module,
    module_name_for,
)

RULE_REENTRY = "CONC-REENTRY"
RULE_LOCK_ORDER = "CONC-LOCK-ORDER"
RULE_BLOCKING = "CONC-BLOCKING"
RULE_CALLBACK = "CONC-CALLBACK"
RULE_BAD_MARKER = "CONC-BAD-MARKER"
RULE_SYNTAX = "CONC-SYNTAX"

# rule code -> marker suffix ("# lint: allow-<suffix> (why)")
MARKER_FOR_RULE = {
    RULE_REENTRY: "reentry",
    RULE_LOCK_ORDER: "lock-order",
    RULE_BLOCKING: "blocking",
    RULE_CALLBACK: "callback",
}
_CONC_MARKERS = frozenset(MARKER_FOR_RULE.values())

# Dotted-name calls that block the calling thread. Matched on the
# resolved name (via imports) so aliases still hit.
_BLOCKING_DOTTED = frozenset({
    "time.sleep",
    "open",
    "os.fsync", "os.replace", "os.rename",
    "select.select",
    "socket.create_connection",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
    "urllib.request.urlopen",
})
# Method names that block regardless of receiver type (sockets, gRPC
# streams, ZMQ sockets, futures — receivers the type pass can't see).
_BLOCKING_METHODS = frozenset({
    "recv", "recv_multipart", "recv_string", "recv_json", "recv_pyobj",
    "result",
})
# Injected pure-value callables that are safe under a lock by contract
# (a clock reads time; it cannot call back into the locking object).
_CALLBACK_EXEMPT_ATTRS = frozenset({"clock", "_clock", "now", "_now"})


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass(frozen=True)
class _Edge:
    src: LockToken
    dst: LockToken
    path: str
    line: int
    region_line: int
    via: str  # human-readable provenance ("Pool.lag_stats → ...")


class Program:
    """All modules, with cross-module class/function resolution."""

    def __init__(self, modules: list[ModuleModel]):
        self.modules = modules
        self.classes: dict[str, ClassModel] = {}
        self.functions: dict[str, MethodModel] = {}
        self._method_module: dict[str, ModuleModel] = {}
        for mm in modules:
            for cls in mm.classes.values():
                self.classes[cls.qualname] = cls
                for m in cls.methods.values():
                    self._method_module[m.qualname] = mm
            for name, fn in mm.functions.items():
                self.functions[f"{mm.module}.{name}"] = fn
                self._method_module[fn.qualname] = mm
        # method qualname -> transitive may-acquire set (filled lazily)
        self._may_acquire: dict[str, frozenset] = {}

    # -- resolution --------------------------------------------------------

    def module_of(self, method: MethodModel) -> ModuleModel:
        return self._method_module[method.qualname]

    def resolve_class(self, dotted: str, from_module: str) -> Optional[ClassModel]:
        cls = self.classes.get(dotted)
        if cls is not None:
            return cls
        if "." not in dotted:  # same-module reference
            return self.classes.get(f"{from_module}.{dotted}")
        return None

    def mro_method(self, cls: ClassModel, name: str) -> Optional[MethodModel]:
        """Method lookup through project-resolvable bases (simple DFS)."""
        seen = set()
        stack = [cls]
        while stack:
            c = stack.pop(0)
            if c.qualname in seen:
                continue
            seen.add(c.qualname)
            if name in c.methods:
                return c.methods[name]
            mod = c.qualname.rsplit(".", 1)[0]
            for base in c.bases:
                b = self.resolve_class(base, mod)
                if b is not None:
                    stack.append(b)
        return None

    def owner_class(self, method: MethodModel) -> Optional[ClassModel]:
        owner = method.qualname.rsplit(".", 2)
        if len(owner) < 2:
            return None
        return self.classes.get(".".join(owner[:-1]))

    def attr_class(self, cls: ClassModel, attr: str) -> Optional[ClassModel]:
        dotted = cls.attr_types.get(attr)
        if not dotted:
            return None
        return self.resolve_class(dotted, cls.qualname.rsplit(".", 1)[0])

    def call_targets(self, caller: MethodModel, site: CallSite) -> list[MethodModel]:
        """Project methods/functions a call site can reach (may be empty)."""
        kind = site.desc[0]
        cls = self.owner_class(caller)
        mod = self.module_of(caller)
        if kind == "self_attr":
            if cls is None:
                return []
            m = self.mro_method(cls, site.desc[1])
            return [m] if m is not None else []
        if kind == "attr_method":
            if cls is None:
                return []
            target_cls = self.attr_class(cls, site.desc[1])
            if target_cls is None:
                return []
            m = self.mro_method(target_cls, site.desc[2])
            return [m] if m is not None else []
        if kind == "name":
            dotted = site.desc[1]
            fn = self.functions.get(dotted)
            if fn is None and "." not in dotted:
                fn = self.functions.get(f"{mod.module}.{dotted}")
            if fn is not None:
                return [fn]
            # Calling a class constructs it: treat as a call to __init__.
            target_cls = self.classes.get(dotted) or (
                self.classes.get(f"{mod.module}.{dotted}")
                if "." not in dotted else None)
            if target_cls is not None:
                init = self.mro_method(target_cls, "__init__")
                return [init] if init is not None else []
        return []

    # -- transitive may-acquire -------------------------------------------

    def may_acquire(self, method: MethodModel) -> frozenset:
        """Lock tokens ``method`` may acquire, transitively (fixpoint)."""
        cached = self._may_acquire.get(method.qualname)
        if cached is not None:
            return cached
        # Iterative DFS with cycle tolerance: start everything reachable
        # at its direct set, then propagate to a fixpoint.
        reach = self._reachable(method)
        direct = {
            m.qualname: {a.token for a in m.acquisitions}
            for m in reach.values()
        }
        edges = {
            m.qualname: [t.qualname for site in m.calls
                         for t in self.call_targets(m, site)]
            for m in reach.values()
        }
        changed = True
        while changed:
            changed = False
            for q, callees in edges.items():
                for c in callees:
                    if c in direct and not direct[c] <= direct[q]:
                        direct[q] |= direct[c]
                        changed = True
        for q, toks in direct.items():
            self._may_acquire[q] = frozenset(toks)
        return self._may_acquire[method.qualname]

    def _reachable(self, method: MethodModel) -> dict:
        out = {}
        stack = [method]
        while stack:
            m = stack.pop()
            if m.qualname in out:
                continue
            out[m.qualname] = m
            for site in m.calls:
                stack.extend(self.call_targets(m, site))
        return out

    # -- same-instance reentry closure ------------------------------------

    def self_reacquire(self, cls: ClassModel, method: MethodModel) -> frozenset:
        """Self-lock tokens reachable through ``self.*`` calls only."""
        out: set = set()
        seen: set = set()
        stack = [method]
        while stack:
            m = stack.pop()
            if m.qualname in seen:
                continue
            seen.add(m.qualname)
            out.update(a.token for a in m.acquisitions
                       if a.token.cls == cls.qualname)
            for site in m.calls:
                if site.desc[0] != "self_attr":
                    continue
                target = self.mro_method(cls, site.desc[1])
                if target is not None:
                    stack.append(target)
        return frozenset(out)


# -- suppression --------------------------------------------------------------


class _Suppressor:
    """Marker lookup + bad-marker findings for one module."""

    def __init__(self, mm: ModuleModel):
        self.mm = mm
        self.path = str(mm.path)

    def allows(self, rule: str, *lines: int) -> bool:
        suffix = MARKER_FOR_RULE.get(rule)
        if suffix is None:
            return False
        for line in lines:
            for marker in self.mm.markers.get(line, ()):
                if marker.rule == suffix and marker.reason:
                    return True
        return False

    def bad_marker_findings(self) -> list[Finding]:
        out = []
        for line, markers in sorted(self.mm.markers.items()):
            for marker in markers:
                if marker.rule in _CONC_MARKERS and not marker.reason:
                    out.append(Finding(
                        self.path, line, RULE_BAD_MARKER,
                        f"suppression `allow-{marker.rule}` without a "
                        "reason — write `# lint: allow-"
                        f"{marker.rule} (why)`",
                    ))
        return out


# -- rule evaluation ----------------------------------------------------------


def _is_blocking(prog: Program, cls: Optional[ClassModel],
                 site: CallSite) -> Optional[str]:
    """Short description when the call blocks the thread (else None)."""
    kind = site.desc[0]
    if kind == "name":
        dotted = site.desc[1]
        if dotted in _BLOCKING_DOTTED:
            return f"`{dotted}()`"
        return None
    if kind != "attr_method" or cls is None:
        if kind == "attr_method":  # no class context → only method-name hits
            m = site.desc[2]
            if m in _BLOCKING_METHODS:
                return f"`.{m}()`"
        return None
    attr, m = site.desc[1], site.desc[2]
    attr_kind = cls.attr_kinds.get(attr, "")
    if attr in cls.lock_attrs:
        lk = cls.lock_attrs[attr]
        if m == "wait" and lk == KIND_CONDITION:
            # Condition.wait releases the lock — the sanctioned pattern
            # when the condition itself is the held lock.
            held_attrs = {t.attr for t in site.held if t.cls == cls.qualname}
            if attr in held_attrs:
                return None
            return f"`self.{attr}.wait()` (condition not held here)"
        if m == "acquire" and "blocking" not in site.kwargs \
                and "timeout" not in site.kwargs:
            return f"blocking `self.{attr}.acquire()`"
        return None
    if m in _BLOCKING_METHODS:
        return f"`self.{attr}.{m}()`"
    if m == "get" and attr_kind == KIND_QUEUE:
        if "block" in site.kwargs or "timeout" in site.kwargs:
            return f"`self.{attr}.get(...)`"
        return f"blocking `self.{attr}.get()`"
    if m == "join" and attr_kind in (KIND_QUEUE, KIND_THREAD):
        return f"`self.{attr}.join()`"
    if m == "wait" and attr_kind == KIND_EVENT:
        return f"`self.{attr}.wait()`"
    return None


def _check_method(prog: Program, mm: ModuleModel, cls: Optional[ClassModel],
                  method: MethodModel, sup: _Suppressor,
                  findings: list, edges: list) -> None:
    path = str(mm.path)
    short = method.qualname.split(".", mm.module.count(".") + 1)[-1]

    # direct re-acquisition + ordering edges from nested `with`s
    for acq in method.acquisitions:
        if acq.token in acq.held_before and acq.token.kind == KIND_LOCK:
            if not sup.allows(RULE_REENTRY, acq.line, acq.region_line):
                findings.append(Finding(
                    path, acq.line, RULE_REENTRY,
                    f"`{short}` re-acquires non-reentrant `self."
                    f"{acq.token.attr}` already held — self-deadlock",
                ))
        for held in acq.held_before:
            if held != acq.token:
                edges.append(_Edge(held, acq.token, path, acq.line,
                                   acq.region_line, short))

    for site in method.calls:
        if not site.held:
            continue
        # CONC-BLOCKING
        desc = _is_blocking(prog, cls, site)
        if desc is not None:
            held = ", ".join(f"self.{t.attr}" for t in site.held)
            if not sup.allows(RULE_BLOCKING, site.line, site.region_line):
                findings.append(Finding(
                    path, site.line, RULE_BLOCKING,
                    f"{desc} blocks while holding {held} in `{short}` — "
                    "move the blocking work outside the critical section",
                ))
            continue
        # CONC-CALLBACK: stored-callable invocation under a lock
        if site.desc[0] in ("self_attr", "attr_value") and cls is not None:
            attr = site.desc[1]
            is_method = prog.mro_method(cls, attr) is not None \
                and site.desc[0] == "self_attr"
            known_attr = attr in cls.lock_attrs or attr in cls.attr_kinds \
                or attr in cls.attr_types
            if not is_method and not known_attr \
                    and attr not in _CALLBACK_EXEMPT_ATTRS:
                held = ", ".join(f"self.{t.attr}" for t in site.held)
                if not sup.allows(RULE_CALLBACK, site.line, site.region_line):
                    findings.append(Finding(
                        path, site.line, RULE_CALLBACK,
                        f"callback `self.{attr}(...)` invoked while holding "
                        f"{held} in `{short}` — escaping hooks must run "
                        "outside the lock",
                    ))
                continue
        # CONC-REENTRY through same-instance call chains
        if site.desc[0] == "self_attr" and cls is not None:
            target = prog.mro_method(cls, site.desc[1])
            if target is not None:
                reacq = prog.self_reacquire(cls, target)
                hit = next(
                    (t for t in site.held
                     if t.kind == KIND_LOCK and t in reacq), None)
                if hit is not None and not sup.allows(
                        RULE_REENTRY, site.line, site.region_line):
                    findings.append(Finding(
                        path, site.line, RULE_REENTRY,
                        f"`{short}` calls `self.{site.desc[1]}()` while "
                        f"holding non-reentrant `self.{hit.attr}`, which "
                        "that call path re-acquires — self-deadlock",
                    ))
        # CONC-LOCK-ORDER edges through any resolvable call
        for target in prog.call_targets(method, site):
            for tok in prog.may_acquire(target):
                for held in site.held:
                    if held != tok:
                        edges.append(_Edge(
                            held, tok, path, site.line, site.region_line,
                            f"{short} → {target.qualname.rsplit('.', 2)[-2]}."
                            f"{target.qualname.rsplit('.', 1)[-1]}"))


def _cycle_findings(edges: list, suppressors: dict) -> list:
    """Cycle detection over the lock-order graph (marker-pruned edges)."""
    live: list[_Edge] = []
    for e in edges:
        sup = suppressors.get(e.path)
        if sup is not None and sup.allows(RULE_LOCK_ORDER, e.line, e.region_line):
            continue
        live.append(e)
    graph: dict[LockToken, set] = {}
    for e in live:
        graph.setdefault(e.src, set()).add(e.dst)

    # The lock graph is tiny (one node per lock *role*), so plain
    # transitive closure + mutual-reachability grouping is the simplest
    # correct SCC computation — no recursion limits, no index juggling.
    nodes = set(graph) | {d for dsts in graph.values() for d in dsts}
    reach: dict[LockToken, set] = {n: set(graph.get(n, ())) for n in nodes}
    changed = True
    while changed:
        changed = False
        for n in nodes:
            add: set = set()
            for m in reach[n]:
                add |= reach.get(m, set())
            if not add <= reach[n]:
                reach[n] |= add
                changed = True

    sccs: list[set] = []
    assigned: set = set()
    for n in nodes:
        if n in assigned:
            continue
        comp = {n} | {m for m in reach[n] if n in reach.get(m, set())}
        assigned |= comp
        if len(comp) > 1:
            sccs.append(comp)

    findings = []
    for comp_set in sccs:
        cyc_edges = [e for e in live
                     if e.src in comp_set and e.dst in comp_set]
        cyc_edges.sort(key=lambda e: (e.path, e.line))
        names = " ↔ ".join(sorted({str(t) for t in comp_set}))
        sites = "; ".join(
            f"{e.src}→{e.dst} at {e.path}:{e.line} (via {e.via})"
            for e in cyc_edges[:4])
        anchor = cyc_edges[0]
        findings.append(Finding(
            anchor.path, anchor.line, RULE_LOCK_ORDER,
            f"lock-order cycle {names}: {sites} — acquire these locks in "
            "one global order (or break an edge with "
            "`# lint: allow-lock-order (why)`)",
        ))
    return findings


# -- entry points -------------------------------------------------------------


def load_program(roots: list) -> tuple:
    """Parse every .py under the roots → (Program, [syntax Findings])."""
    modules: list[ModuleModel] = []
    findings: list[Finding] = []
    for root in roots:
        root = Path(root)
        files = [root] if root.is_file() else sorted(root.rglob("*.py"))
        pkg_root = root if root.is_dir() else root.parent
        for f in files:
            try:
                module = module_name_for(f, pkg_root)
            except ValueError:
                module = f.stem
            mm = extract_module(f, module)
            if mm.syntax_error:
                findings.append(Finding(
                    str(f), 0, RULE_SYNTAX, mm.syntax_error))
                continue
            modules.append(mm)
    return Program(modules), findings


def analyze(roots: list) -> list:
    """Run all rules over the given roots; returns sorted Findings."""
    prog, findings = load_program(roots)
    suppressors = {str(mm.path): _Suppressor(mm) for mm in prog.modules}
    edges: list[_Edge] = []
    for mm in prog.modules:
        sup = suppressors[str(mm.path)]
        findings.extend(sup.bad_marker_findings())
        for cls in mm.classes.values():
            for method in cls.methods.values():
                _check_method(prog, mm, cls, method, sup, findings, edges)
        for fn in mm.functions.values():
            _check_method(prog, mm, None, fn, sup, findings, edges)
    findings.extend(_cycle_findings(edges, suppressors))
    return sorted(set(findings), key=lambda f: (f.path, f.line, f.rule))
