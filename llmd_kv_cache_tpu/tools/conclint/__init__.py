"""Whole-program concurrency analyzer (the ``make lint`` concurrency pass).

Public surface:

- :func:`analyze` — run the four rules over package roots, returning
  :class:`Finding`s (``path:line: RULE message`` via ``Finding.format()``)
- the ``RULE_*`` codes and :data:`MARKER_FOR_RULE` marker grammar

Entry points: ``hack/lint_concurrency.py`` (standalone) and
``hack/kvlint.py`` (the unified lint driver). The runtime counterpart —
the lockdep witness that validates this static model under
``make unit-test-race`` — lives in ``llmd_kv_cache_tpu/utils/lockdep.py``.
See docs/testing.md "Concurrency analysis" for the rule catalog.
"""

from .analysis import (
    MARKER_FOR_RULE,
    RULE_BAD_MARKER,
    RULE_BLOCKING,
    RULE_CALLBACK,
    RULE_LOCK_ORDER,
    RULE_REENTRY,
    RULE_SYNTAX,
    Finding,
    analyze,
    load_program,
)

__all__ = [
    "analyze",
    "load_program",
    "Finding",
    "MARKER_FOR_RULE",
    "RULE_REENTRY",
    "RULE_LOCK_ORDER",
    "RULE_BLOCKING",
    "RULE_CALLBACK",
    "RULE_BAD_MARKER",
    "RULE_SYNTAX",
]
