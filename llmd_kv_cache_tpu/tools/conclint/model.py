"""Per-module AST extraction for the concurrency analyzer.

This layer turns one Python source file into a :class:`ModuleModel`:
which classes exist, which ``self.<attr>`` attributes are locks (or
queues / events / threads / other project classes), and — per method —
every lock acquisition and every call *with the set of locks held at
that point*. The cross-module assembly and the actual rules live in
:mod:`.analysis`; nothing here decides what is a violation.

Lock identity is ``(class qualname, attribute name)``: the analyzer
reasons about lock *roles*, not instances (the same abstraction the
runtime witness in ``utils/lockdep.py`` uses, which keys locks by
construction site). Two instances of the same class share a lock token —
strict, like kernel lockdep, and exactly what makes whole-program
ordering checkable.

Recognized lock constructors: ``threading.Lock/RLock/Condition`` and the
project's own ``utils.lockdep.new_lock/new_rlock/new_condition``
factories (the production spelling after this PR).

Known limitations, by design (kept conservative to avoid false
positives; the runtime witness covers the residue):

- instance identity is erased — ``self.helper.method()`` where helper is
  the *same* class is treated as a different instance's lock for the
  reentry rule (only ``self.*`` call chains count);
- nested function / lambda bodies are not attributed to the enclosing
  lock region (they usually run later, on other threads);
- ``lock.acquire()`` / ``release()`` pairs are recorded as acquisition
  *events* for ordering, but do not open a held region (extent is not
  statically obvious); ``acquire(blocking=False)`` try-locks are ignored.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

# attr kinds the analyzer distinguishes (beyond project-class types)
KIND_LOCK = "lock"            # threading.Lock / lockdep.new_lock
KIND_RLOCK = "rlock"          # threading.RLock / lockdep.new_rlock
KIND_CONDITION = "condition"  # threading.Condition / lockdep.new_condition
KIND_EVENT = "event"          # threading.Event
KIND_THREAD = "thread"        # threading.Thread
KIND_QUEUE = "queue"          # queue.Queue / LifoQueue / PriorityQueue / SimpleQueue
KIND_SEMAPHORE = "semaphore"  # threading.(Bounded)Semaphore

LOCK_KINDS = (KIND_LOCK, KIND_RLOCK, KIND_CONDITION)

# Constructor dotted-name → attr kind. ``new_*`` factories are matched by
# suffix so both absolute and package-relative resolutions hit.
_CTOR_KINDS = {
    "threading.Lock": KIND_LOCK,
    "threading.RLock": KIND_RLOCK,
    "threading.Condition": KIND_CONDITION,
    "threading.Event": KIND_EVENT,
    "threading.Thread": KIND_THREAD,
    "threading.Semaphore": KIND_SEMAPHORE,
    "threading.BoundedSemaphore": KIND_SEMAPHORE,
    "queue.Queue": KIND_QUEUE,
    "queue.LifoQueue": KIND_QUEUE,
    "queue.PriorityQueue": KIND_QUEUE,
    "queue.SimpleQueue": KIND_QUEUE,
}
_FACTORY_SUFFIXES = {
    "lockdep.new_lock": KIND_LOCK,
    "lockdep.new_rlock": KIND_RLOCK,
    "lockdep.new_condition": KIND_CONDITION,
}

# ``# lint: allow-<rule> (why)`` — same grammar as lint_resilience's
# allow-swallow, but the reason is mandatory for concurrency rules.
MARKER_RE = re.compile(r"#\s*lint:\s*allow-([a-z][a-z0-9-]*)\s*(\(([^)]*)\))?")


@dataclass(frozen=True)
class LockToken:
    """One lock *role*: the ``self._mu`` of a specific class."""

    cls: str   # class qualname ("pkg.mod.Class")
    attr: str  # attribute name ("_mu")
    kind: str  # KIND_LOCK | KIND_RLOCK | KIND_CONDITION

    def __str__(self) -> str:  # pragma: no cover - repr convenience
        return f"{self.cls.rsplit('.', 1)[-1]}.{self.attr}"


@dataclass(frozen=True)
class CallSite:
    """One call expression and the lock context it runs under.

    ``desc`` is the resolution-ready descriptor:

    - ``("self_attr", name)``         — ``self.name(...)``; the analysis
      phase decides whether ``name`` is a method (call edge) or a stored
      callable (escaping-callback rule)
    - ``("attr_method", attr, m)``    — ``self.attr.m(...)`` (or via a
      local alias of ``self.attr`` / an element of ``self.attr``)
    - ``("attr_value", attr)``        — call of a *value read from*
      ``self.attr`` through a local alias (``fn = self.hooks[0]; fn()``)
    - ``("name", dotted)``            — import-resolved dotted call
      ("time.sleep", "pkg.mod.fn", "open")
    """

    desc: tuple
    line: int
    held: tuple  # LockTokens held at the call, outermost first
    region_line: int  # line of the innermost `with` that holds a lock (0 = none)
    kwargs: tuple = ()  # keyword-argument names (blocking-rule heuristics)


@dataclass(frozen=True)
class AcqSite:
    """One lock acquisition (``with self._mu:`` entry or ``.acquire()``)."""

    token: LockToken
    line: int
    held_before: tuple  # LockTokens already held, outermost first
    region_line: int


@dataclass
class MethodModel:
    qualname: str  # "pkg.mod.Class.method" or "pkg.mod.func"
    calls: list = field(default_factory=list)  # [CallSite]
    acquisitions: list = field(default_factory=list)  # [AcqSite]


@dataclass
class ClassModel:
    qualname: str
    bases: list = field(default_factory=list)  # dotted base-class names
    lock_attrs: dict = field(default_factory=dict)  # attr -> kind
    attr_kinds: dict = field(default_factory=dict)  # attr -> KIND_* (queue/event/...)
    attr_types: dict = field(default_factory=dict)  # attr -> dotted class name
    methods: dict = field(default_factory=dict)  # name -> MethodModel


@dataclass
class Marker:
    rule: str          # "reentry" / "lock-order" / ...
    line: int
    reason: str        # "" when the (why) is missing


@dataclass
class ModuleModel:
    path: Path
    module: str  # dotted module name
    classes: dict = field(default_factory=dict)  # name -> ClassModel
    functions: dict = field(default_factory=dict)  # name -> MethodModel
    markers: dict = field(default_factory=dict)  # line -> [Marker]
    syntax_error: Optional[str] = None


# -- import resolution --------------------------------------------------------


def _resolve_imports(tree: ast.Module, module: str) -> dict:
    """Local name → dotted path, for modules and imported symbols."""
    pkg_parts = module.split(".")[:-1]
    table: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                table[local] = alias.name.split(".")[0] if alias.asname is None \
                    else alias.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                src = ".".join(base + ([node.module] if node.module else []))
            else:
                src = node.module or ""
            for alias in node.names:
                local = alias.asname or alias.name
                table[local] = f"{src}.{alias.name}" if src else alias.name
    return table


def _dotted(expr: ast.AST, imports: dict) -> str:
    """Best-effort dotted name of an expression (``""`` when dynamic)."""
    parts: list[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return ""
    head = imports.get(node.id, node.id)
    parts.append(head)
    return ".".join(reversed(parts))


def _ctor_kind(dotted: str) -> str:
    """Attr kind for a constructor dotted name ("" = not a known ctor)."""
    kind = _CTOR_KINDS.get(dotted, "")
    if kind:
        return kind
    for suffix, k in _FACTORY_SUFFIXES.items():
        if dotted == suffix or dotted.endswith("." + suffix) \
                or dotted.endswith("." + suffix.split(".")[-1]):
            # "new_lock" imported bare still counts: the name is unique
            # enough in this codebase to key on.
            if dotted.rsplit(".", 1)[-1] == suffix.rsplit(".", 1)[-1]:
                return k
    return ""


def _annotation_class(ann: ast.AST, imports: dict) -> str:
    """Dotted class from an annotation, unwrapping Optional[...] etc."""
    if isinstance(ann, ast.Subscript):  # Optional[X], list[X], "ClassVar[X]"
        return _annotation_class(ann.slice, imports)
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return imports.get(ann.value, ann.value)
    name = _dotted(ann, imports)
    return name


# -- per-function walking -----------------------------------------------------


class _FnWalker:
    """Walks one function body tracking the stack of held self-locks."""

    def __init__(self, cls: Optional[ClassModel], imports: dict,
                 method: MethodModel):
        self.cls = cls
        self.imports = imports
        self.method = method
        self.held: list[LockToken] = []
        self.region_lines: list[int] = []
        self.aliases: dict[str, tuple] = {}  # name -> ("attr"|"attr_ele", attr)

    # - lock bookkeeping -

    def _self_attr(self, expr: ast.AST) -> str:
        """attr name iff ``expr`` is ``self.<attr>`` (else "")."""
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return expr.attr
        return ""

    def _lock_token(self, expr: ast.AST) -> Optional[LockToken]:
        if self.cls is None:
            return None
        attr = self._self_attr(expr)
        if attr and attr in self.cls.lock_attrs:
            return LockToken(self.cls.qualname, attr, self.cls.lock_attrs[attr])
        return None

    def _region_line(self) -> int:
        return self.region_lines[-1] if self.region_lines else 0

    # - traversal -

    def walk_body(self, stmts: list) -> None:
        for stmt in stmts:
            self.walk_stmt(stmt)

    def walk_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.With):
            self._walk_with(stmt)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs execute later, not under this region
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._track_alias(stmt)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._track_loop_alias(stmt)
        # Expressions hanging off this statement run under the current
        # region; child *statements* recurse so nested withs are handled.
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self.walk_stmt(child)
            elif isinstance(child, ast.ExceptHandler):
                self.walk_body(child.body)
            elif isinstance(child, ast.expr):
                self.walk_expr(child)
            # arguments/keywords/etc fall out of iter_child_nodes as
            # non-stmt non-expr nodes only for defs, skipped above

    def _walk_with(self, stmt: ast.With) -> None:
        acquired: list[LockToken] = []
        pushed_region = False
        for item in stmt.items:
            self.walk_expr(item.context_expr)
            tok = self._lock_token(item.context_expr)
            if tok is not None:
                self.method.acquisitions.append(AcqSite(
                    token=tok,
                    line=item.context_expr.lineno,
                    held_before=tuple(self.held),
                    region_line=stmt.lineno,
                ))
                self.held.append(tok)
                acquired.append(tok)
                if not pushed_region:
                    self.region_lines.append(stmt.lineno)
                    pushed_region = True
        self.walk_body(stmt.body)
        for _ in acquired:
            self.held.pop()
        if pushed_region:
            self.region_lines.pop()

    def walk_expr(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if isinstance(node, (ast.Lambda,)):
                continue  # body runs later
            if isinstance(node, ast.Call):
                self._record_call(node)

    # - aliases -

    def _track_alias(self, stmt: ast.stmt) -> None:
        target = None
        value = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            target, value = stmt.targets[0].id, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            target, value = stmt.target.id, stmt.value
        if target is None:
            return
        self.aliases.pop(target, None)
        if value is None:
            return
        attr = self._self_attr(value)
        if attr:
            self.aliases[target] = ("attr", attr)
        elif isinstance(value, ast.Subscript):
            attr = self._self_attr(value.value)
            if attr:
                self.aliases[target] = ("attr_ele", attr)

    def _track_loop_alias(self, stmt) -> None:
        if not isinstance(stmt.target, ast.Name):
            return
        it = stmt.iter
        # for x in self.attr / self.attr.values() / list(self.attr)
        attr = self._self_attr(it)
        if not attr and isinstance(it, ast.Call):
            if isinstance(it.func, ast.Attribute):
                attr = self._self_attr(it.func.value)
            elif isinstance(it.func, ast.Name) and it.args:
                attr = self._self_attr(it.args[0])
        if attr:
            self.aliases[stmt.target.id] = ("attr_ele", attr)

    # - call recording -

    def _record_call(self, call: ast.Call) -> None:
        desc = self._describe(call)
        if desc is None:
            return
        self.method.calls.append(CallSite(
            desc=desc,
            line=call.lineno,
            held=tuple(self.held),
            region_line=self._region_line(),
            kwargs=tuple(kw.arg for kw in call.keywords if kw.arg),
        ))

    def _describe(self, call: ast.Call) -> Optional[tuple]:
        fn = call.func
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if isinstance(base, ast.Name) and base.id == "self":
                # self.x(...): method call or stored-callable invocation —
                # the analysis phase decides which, once methods are known.
                return ("self_attr", fn.attr)
            attr = self._self_attr(base)
            if attr:  # self.attr.m(...)
                return ("attr_method", attr, fn.attr)
            if isinstance(base, ast.Name):
                alias = self.aliases.get(base.id)
                if alias is not None:  # q.get() where q = self._queues[i]
                    return ("attr_method", alias[1], fn.attr)
                dotted = _dotted(fn, self.imports)
                if dotted:
                    return ("name", dotted)
                return None
            if isinstance(base, ast.Subscript):
                attr = self._self_attr(base.value)
                if attr:  # self._queues[i].get()
                    return ("attr_method", attr, fn.attr)
            dotted = _dotted(fn, self.imports)
            if dotted:
                return ("name", dotted)
            return None
        if isinstance(fn, ast.Name):
            if fn.id == "self":
                return None
            alias = self.aliases.get(fn.id)
            if alias is not None:  # fn() where fn = self.publish / iter ele
                return ("attr_value", alias[1])
            return ("name", self.imports.get(fn.id, fn.id))
        # self.something(...) arrives as Attribute(value=Name self)
        return None


# -- class / module extraction ------------------------------------------------


def _extract_class(node: ast.ClassDef, module: str, imports: dict) -> ClassModel:
    cls = ClassModel(qualname=f"{module}.{node.name}")
    for base in node.bases:
        dotted = _dotted(base, imports)
        if dotted:
            cls.bases.append(dotted)
    # Class-body annotated fields (dataclasses): pick up lock kinds from
    # `field(default_factory=new_lock)` and attr types from annotations.
    for stmt in node.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            attr = stmt.target.id
            ann_cls = _annotation_class(stmt.annotation, imports)
            kind = _ctor_kind(ann_cls)
            if isinstance(stmt.value, ast.Call):
                factory = next(
                    (kw.value for kw in stmt.value.keywords
                     if kw.arg == "default_factory"), None)
                if factory is not None:
                    fkind = _ctor_kind(_dotted(factory, imports))
                    if fkind:
                        kind = fkind
            if kind in LOCK_KINDS:
                cls.lock_attrs[attr] = kind
            elif kind:
                cls.attr_kinds[attr] = kind
            elif ann_cls and ann_cls.rsplit(".", 1)[-1][:1].isupper():
                cls.attr_types.setdefault(attr, ann_cls)

    # First pass over methods: find self.<attr> assignments/annotations.
    for fn in node.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for sub in ast.walk(fn):
            attr = None
            value = None
            ann = None
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                t = sub.targets[0]
                if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    attr, value = t.attr, sub.value
            elif isinstance(sub, ast.AnnAssign):
                t = sub.target
                if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    attr, value, ann = t.attr, sub.value, sub.annotation
            if attr is None:
                continue
            kind = ""
            type_name = ""
            if isinstance(value, ast.Call):
                dotted = _dotted(value.func, imports)
                kind = _ctor_kind(dotted)
                if not kind and dotted and dotted.rsplit(".", 1)[-1][:1].isupper():
                    type_name = dotted
            elif isinstance(value, (ast.List, ast.ListComp)):
                # self._queues = [queue.Queue(...) for ...] — element kind
                elt = value.elts[0] if isinstance(value, ast.List) and value.elts \
                    else getattr(value, "elt", None)
                if isinstance(elt, ast.Call):
                    ekind = _ctor_kind(_dotted(elt.func, imports))
                    if ekind:
                        kind = ekind  # list-of-<kind>: element calls resolve
            if not kind and ann is not None:
                ann_cls = _annotation_class(ann, imports)
                akind = _ctor_kind(ann_cls)
                if akind:
                    kind = akind
                elif ann_cls and "." in ann_cls:
                    type_name = ann_cls
            if kind in LOCK_KINDS:
                cls.lock_attrs.setdefault(attr, kind)
            elif kind:
                cls.attr_kinds.setdefault(attr, kind)
            elif type_name:
                cls.attr_types.setdefault(attr, type_name)

    # Second pass: walk each method with lock-region tracking.
    for fn in node.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        method = MethodModel(qualname=f"{cls.qualname}.{fn.name}")
        walker = _FnWalker(cls, imports, method)
        walker.walk_body(fn.body)
        cls.methods[fn.name] = method
    return cls


def extract_markers(src: str) -> dict:
    markers: dict[int, list[Marker]] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        if "lint:" not in line:
            continue
        for m in MARKER_RE.finditer(line):
            markers.setdefault(i, []).append(
                Marker(rule=m.group(1), line=i,
                       reason=(m.group(3) or "").strip()))
    return markers


def extract_module(path: Path, module: str) -> ModuleModel:
    src = path.read_text()
    mm = ModuleModel(path=path, module=module)
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        mm.syntax_error = f"line {e.lineno}: {e.msg}"
        return mm
    imports = _resolve_imports(tree, module)
    mm.markers = extract_markers(src)
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            mm.classes[node.name] = _extract_class(node, module, imports)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            method = MethodModel(qualname=f"{module}.{node.name}")
            walker = _FnWalker(None, imports, method)
            walker.walk_body(node.body)
            mm.functions[node.name] = method
    return mm


def module_name_for(path: Path, root: Path) -> str:
    """Dotted module name of ``path`` relative to package root ``root``."""
    rel = path.relative_to(root.parent)
    parts = list(rel.with_suffix("").parts)
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)
