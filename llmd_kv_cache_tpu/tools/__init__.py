"""Developer tooling shipped inside the package (static analyzers).

Nothing under ``tools/`` is imported by the serving library; the hack/
entry points (``hack/lint_concurrency.py``, ``hack/kvlint.py``) import it
directly, and keeping it in-package lets the analyzers dogfood the same
conventions (docstrings, lint passes) as the code they check.
"""
