"""Controller actions and the pluggable actuator interface.

An :class:`Action` is a *concrete, idempotent* topology mutation — the
policy's output and the journal's subject. Actuators turn actions into
effects; two ship in-tree:

- :class:`InProcessActuator` — callables wired at construction (the
  bench's simulated fleet, single-process deployments, tests).
- :class:`AdminPlaneActuator` — drives *remote* pods over the stdlib
  admin plane: POST ``/debug/role?set=`` re-roles an engine pod, POST
  ``/debug/drain`` triggers the PR 4 graceful drain. Shard membership
  changes stay with the deployment layer (the ring is rebuilt from the
  membership list), so add/remove-shard calls go through an injected
  callback there too.

Actuators raise on failure; the controller journals the failure and the
cooldown prevents an immediate retry storm.
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from ..utils.logging import get_logger

logger = get_logger("control.actions")

ACTION_ADD_SHARD = "add_shard"
ACTION_REMOVE_SHARD = "remove_shard"
ACTION_SET_ROLE = "set_role"
ACTION_DRAIN_POD = "drain_pod"

ACTION_KINDS = (
    ACTION_ADD_SHARD,
    ACTION_REMOVE_SHARD,
    ACTION_SET_ROLE,
    ACTION_DRAIN_POD,
)

# Kinds that change ring membership and therefore mint a new topology
# epoch (two-phase propose→commit in the controller). Re-roles and
# drains ride the *current* epoch — they do not move partitions.
TOPOLOGY_KINDS = (
    ACTION_ADD_SHARD,
    ACTION_REMOVE_SHARD,
)


@dataclass(frozen=True)
class Action:
    """One concrete topology mutation, with its causing signal attached."""

    kind: str  # one of ACTION_KINDS
    target: str  # shard id / pod id
    params: dict = field(default_factory=dict)  # e.g. {"role": "decode"}
    reason: str = ""  # one-line operator-readable cause
    signal: dict = field(default_factory=dict)  # alert/stat snapshot

    def action_id(self, seq: int) -> str:
        return f"{self.kind}:{self.target}:{seq}"

    def describe(self) -> dict:
        return {
            "kind": self.kind,
            "target": self.target,
            "params": dict(self.params),
            "reason": self.reason,
            "signal": dict(self.signal),
        }


class Actuator:
    """The controller's hands. ``apply`` returns a JSON-able result dict
    and raises on failure."""

    def apply(self, action: Action) -> dict:
        raise NotImplementedError


class InProcessActuator(Actuator):
    """Callable-backed actuator (tests, bench sim, single-process runs)."""

    def __init__(
        self,
        add_shard: Optional[Callable[[str], object]] = None,
        remove_shard: Optional[Callable[[str], object]] = None,
        set_role: Optional[Callable[[str, str], object]] = None,
        drain_pod: Optional[Callable[[str], object]] = None,
    ):
        self._add_shard = add_shard
        self._remove_shard = remove_shard
        self._set_role = set_role
        self._drain_pod = drain_pod
        self.applied: list = []  # (kind, target, params) audit trail

    def apply(self, action: Action) -> dict:
        handler = {
            ACTION_ADD_SHARD: self._add_shard,
            ACTION_REMOVE_SHARD: self._remove_shard,
            ACTION_SET_ROLE: self._set_role,
            ACTION_DRAIN_POD: self._drain_pod,
        }.get(action.kind)
        if handler is None:
            raise ValueError(f"no handler wired for action {action.kind!r}")
        if action.kind == ACTION_SET_ROLE:
            result = handler(action.target, str(action.params.get("role", "")))
        else:
            result = handler(action.target)
        self.applied.append((action.kind, action.target, dict(action.params)))
        if isinstance(result, dict):
            return result
        return {"ok": True, "result": repr(result) if result is not None else ""}


class AdminPlaneActuator(Actuator):
    """Acts on remote pods through their admin endpoints.

    ``pod_addresses`` maps pod/target id → ``host:port`` of the pod's
    admin server. Re-role and drain go over HTTP POST (the guarded
    endpoints of ``services/admin.py``); shard membership changes call
    the injected deployment hooks — the controller cannot conjure a new
    shard process itself, but it *can* tell the deployment layer to.
    """

    def __init__(
        self,
        pod_addresses: Dict[str, str],
        add_shard: Optional[Callable[[str], object]] = None,
        remove_shard: Optional[Callable[[str], object]] = None,
        timeout_s: float = 5.0,
    ):
        self.pod_addresses = dict(pod_addresses)
        self._add_shard = add_shard
        self._remove_shard = remove_shard
        self.timeout_s = timeout_s

    def _post(self, address: str, path: str, params: dict) -> dict:
        query = urllib.parse.urlencode(params)
        url = f"http://{address}{path}"
        if query:
            url += f"?{query}"
        req = urllib.request.Request(url, data=b"", method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            payload = json.loads(resp.read() or b"{}")
        return payload if isinstance(payload, dict) else {"result": payload}

    def apply(self, action: Action) -> dict:
        if action.kind == ACTION_SET_ROLE:
            address = self.pod_addresses.get(action.target)
            if not address:
                raise ValueError(f"no admin address for pod {action.target!r}")
            return self._post(address, "/debug/role",
                              {"set": str(action.params.get("role", ""))})
        if action.kind == ACTION_DRAIN_POD:
            address = self.pod_addresses.get(action.target)
            if not address:
                raise ValueError(f"no admin address for pod {action.target!r}")
            return self._post(address, "/debug/drain", {})
        if action.kind == ACTION_ADD_SHARD:
            if self._add_shard is None:
                raise ValueError("add_shard deployment hook not wired")
            result = self._add_shard(action.target)
            return result if isinstance(result, dict) else {"ok": True}
        if action.kind == ACTION_REMOVE_SHARD:
            if self._remove_shard is None:
                raise ValueError("remove_shard deployment hook not wired")
            result = self._remove_shard(action.target)
            return result if isinstance(result, dict) else {"ok": True}
        raise ValueError(f"unknown action kind {action.kind!r}")
