"""The reconciliation loop: sense → decide → act, journaled and traced.

Each round takes one :class:`FleetSignals` snapshot, first *resolves*
any in-flight actions a predecessor journaled but never settled (verify
against observed topology; only re-execute when the world does not
already reflect the action — never repeat, never reverse), then asks the
policy for new actions and pushes them through the actuator under the
global action budget.

Crash safety is the journal's write ordering: ``planned`` lands on disk
*before* the actuator runs, ``executed``/``failed`` after it settles, so
every controller state is reconstructible from the journal alone. Every
executed (or dry-run) action gets a ``llm_d.kv_cache.control.action``
span whose attributes carry the causing alert/signal snapshot — the
audit trail from "SLO burned" to "topology changed" is one trace query.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from prometheus_client import Counter, Gauge

from ..utils.lockdep import new_lock
from ..utils.logging import get_logger
from ..telemetry.tracing import tracer
from .actions import (
    ACTION_ADD_SHARD,
    ACTION_DRAIN_POD,
    ACTION_REMOVE_SHARD,
    ACTION_SET_ROLE,
    Action,
    Actuator,
)
from .config import ControllerConfig
from .journal import (
    PHASE_EXECUTED,
    PHASE_FAILED,
    PHASE_PLANNED,
    PHASE_WOULD_ACT,
    ActionJournal,
    ActionRecord,
    last_settlement_ts,
    unresolved_actions,
)
from .policy import ControlPolicy
from .signals import FleetSignals

logger = get_logger("control.controller")

CTRL_ROUNDS = Counter(
    "kvtpu_ctrl_reconcile_rounds_total",
    "Fleet-controller reconcile rounds completed",
)
CTRL_ACTIONS = Counter(
    "kvtpu_ctrl_actions_total",
    "Fleet-controller actions by kind and settlement phase",
    ["kind", "phase"],
)
CTRL_BUDGET_DEFERRED = Counter(
    "kvtpu_ctrl_budget_deferred_total",
    "Actions the policy wanted but the global budget deferred",
)
CTRL_INFLIGHT = Gauge(
    "kvtpu_ctrl_inflight_actions",
    "Journaled planned actions not yet settled",
)

SPAN_RECONCILE = "llm_d.kv_cache.control.reconcile"
SPAN_ACTION = "llm_d.kv_cache.control.action"


class FleetController:
    """Sense → decide → act loop over a signal source and an actuator."""

    def __init__(
        self,
        signal_source,
        actuator: Actuator,
        config: Optional[ControllerConfig] = None,
        journal: Optional[ActionJournal] = None,
        clock: Callable[[], float] = time.time,
    ):
        self.cfg = config or ControllerConfig()
        self.source = signal_source
        self.actuator = actuator
        # Wall clock on purpose: journal timestamps must stay comparable
        # across restarts for cooldown/budget restoration.
        self._clock = clock
        self.policy = ControlPolicy(self.cfg, clock)
        if journal is None and self.cfg.journal_path:
            journal = ActionJournal(self.cfg.journal_path)
        self.journal = journal
        self._mu = new_lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.rounds = 0
        self.budget_deferred = 0
        # Executed-action timestamps inside the sliding budget window.
        self._budget_ts: deque = deque()
        # Bounded histories for kvdiag / /debug/controller.
        self._history: deque = deque(maxlen=self.cfg.history)
        self._would_act: deque = deque(maxlen=self.cfg.history)
        # In-flight (planned, unsettled) records to resolve next round.
        self._pending: List[ActionRecord] = []
        # Monotonic action-id counter, assigned BEFORE the planned record
        # is journaled so the on-disk planned and settled records carry
        # the same action_id (unresolved_actions matches by id).
        self._action_counter = 0
        self.resumed_records = 0
        if self.journal is not None:
            self._restore()

    # -- warm restart ------------------------------------------------------

    def _restore(self) -> None:
        records = list(self.journal.replay())
        self.resumed_records = len(records)
        if not records:
            return
        # Resume past the highest journal seq: action ids embed the
        # counter, so reuse across restarts would alias distinct actions.
        self._action_counter = max(r.seq for r in records)
        for kind, ts in last_settlement_ts(records).items():
            self.policy.notify_action(kind, ts)
        now = self._clock()
        for rec in records:
            if rec.phase == PHASE_EXECUTED \
                    and now - rec.ts <= self.cfg.budget_window_s:
                self._budget_ts.append(rec.ts)
            if rec.phase in (PHASE_EXECUTED, PHASE_FAILED):
                self._history.append(rec.to_wire())
            elif rec.phase == PHASE_WOULD_ACT:
                self._would_act.append(rec.to_wire())
        self._pending = unresolved_actions(records)
        CTRL_INFLIGHT.set(len(self._pending))
        if self._pending:
            logger.info(
                "restored %d journal records, %d in-flight action(s) to "
                "re-verify: %s", len(records), len(self._pending),
                [r.action_id for r in self._pending])
        else:
            logger.info("restored %d journal records, no in-flight actions",
                        len(records))

    # -- budget ------------------------------------------------------------

    def _budget_ok(self) -> bool:
        now = self._clock()
        while self._budget_ts and now - self._budget_ts[0] > self.cfg.budget_window_s:
            self._budget_ts.popleft()
        return len(self._budget_ts) < self.cfg.action_budget

    def _charge_budget(self) -> None:
        self._budget_ts.append(self._clock())

    # -- journaling helpers ------------------------------------------------

    def _journal(self, record: ActionRecord) -> ActionRecord:
        if self.journal is not None:
            return self.journal.append(record)
        # No persistence configured: still assign seqs so action ids and
        # histories stay well-formed.
        self._seq = getattr(self, "_seq", 0) + 1
        record.seq = self._seq
        return record

    def _record(self, action: Action, phase: str,
                result: Optional[dict] = None) -> ActionRecord:
        self._action_counter += 1
        rec = ActionRecord(
            action_id=action.action_id(self._action_counter),
            seq=0,
            ts=self._clock(),
            phase=phase,
            kind=action.kind,
            target=action.target,
            params=dict(action.params),
            reason=action.reason,
            signal=dict(action.signal),
            result=dict(result or {}),
        )
        return self._journal(rec)

    # -- action execution --------------------------------------------------

    def _execute(self, action: Action) -> ActionRecord:
        """planned → actuate → executed/failed, traced and journaled."""
        planned = self._record(action, PHASE_PLANNED)
        CTRL_ACTIONS.labels(action.kind, PHASE_PLANNED).inc()
        self._pending.append(planned)
        CTRL_INFLIGHT.set(len(self._pending))
        try:
            with tracer().span(
                SPAN_ACTION,
                action_id=planned.action_id,
                action_kind=action.kind,
                action_target=action.target,
                reason=action.reason,
                signal=json.dumps(action.signal, sort_keys=True,
                                  default=repr),
                dry_run=False,
            ):
                result = self.actuator.apply(action)
            phase, payload = PHASE_EXECUTED, {"ok": True, **(result or {})}
            self._charge_budget()
        except Exception as exc:
            phase, payload = PHASE_FAILED, {"ok": False, "error": repr(exc)}
            logger.warning("action %s failed: %r", planned.action_id, exc)
        settled = ActionRecord(
            action_id=planned.action_id,
            seq=0,
            ts=self._clock(),
            phase=phase,
            kind=action.kind,
            target=action.target,
            params=dict(action.params),
            reason=action.reason,
            signal=dict(action.signal),
            result=payload,
        )
        settled = self._journal(settled)
        CTRL_ACTIONS.labels(action.kind, phase).inc()
        self._pending = [p for p in self._pending
                         if p.action_id != planned.action_id]
        CTRL_INFLIGHT.set(len(self._pending))
        self._history.append(settled.to_wire())
        return settled

    def _dry_run(self, action: Action) -> ActionRecord:
        with tracer().span(
            SPAN_ACTION,
            action_kind=action.kind,
            action_target=action.target,
            reason=action.reason,
            signal=json.dumps(action.signal, sort_keys=True, default=repr),
            dry_run=True,
        ):
            rec = self._record(action, PHASE_WOULD_ACT,
                               result={"dry_run": True})
        CTRL_ACTIONS.labels(action.kind, PHASE_WOULD_ACT).inc()
        self._would_act.append(rec.to_wire())
        return rec

    # -- in-flight resolution ----------------------------------------------

    def _world_reflects(self, rec: ActionRecord,
                        signals: FleetSignals) -> bool:
        """Does observed topology already show this action's effect?"""
        if rec.kind == ACTION_SET_ROLE:
            return signals.roles.get(rec.target) == rec.params.get("role")
        if rec.kind == ACTION_ADD_SHARD:
            return rec.target in signals.shards
        if rec.kind == ACTION_REMOVE_SHARD:
            return rec.target not in signals.shards
        if rec.kind == ACTION_DRAIN_POD:
            # Drain leaves no durable topology mark; once its pod is gone
            # from the ring the paired scale-down clearly went through.
            return rec.target not in signals.shards
        return False

    def _resolve_pending(self, signals: FleetSignals) -> None:
        pending, self._pending = self._pending, []
        for rec in pending:
            action = Action(kind=rec.kind, target=rec.target,
                            params=dict(rec.params),
                            reason=f"resume in-flight: {rec.reason}",
                            signal=dict(rec.signal))
            if self._world_reflects(rec, signals):
                settled = ActionRecord(
                    action_id=rec.action_id, seq=0, ts=self._clock(),
                    phase=PHASE_EXECUTED, kind=rec.kind, target=rec.target,
                    params=dict(rec.params), reason=rec.reason,
                    signal=dict(rec.signal),
                    result={"ok": True, "resumed": True,
                            "already_applied": True},
                )
                settled = self._journal(settled)
                CTRL_ACTIONS.labels(rec.kind, PHASE_EXECUTED).inc()
                self._history.append(settled.to_wire())
                logger.info("in-flight action %s already applied; settled "
                            "without re-executing", rec.action_id)
                continue
            if self.cfg.dry_run:
                self._dry_run(action)
                continue
            if not self._budget_ok():
                self.budget_deferred += 1
                CTRL_BUDGET_DEFERRED.inc()
                self._pending.append(rec)
                continue
            logger.info("re-executing in-flight action %s", rec.action_id)
            self._execute(action)
        CTRL_INFLIGHT.set(len(self._pending))

    # -- the loop ----------------------------------------------------------

    def reconcile_once(self) -> Dict[str, object]:
        """One sense→decide→act round; returns a round summary."""
        with self._mu:
            with tracer().span(SPAN_RECONCILE, dry_run=self.cfg.dry_run):
                signals = self.source.poll()
                self._resolve_pending(signals)
                proposed = self.policy.decide(signals)
                executed: List[str] = []
                deferred = 0
                for action in proposed:
                    if self.cfg.dry_run:
                        rec = self._dry_run(action)
                        executed.append(rec.action_id)
                        continue
                    if not self._budget_ok():
                        self.budget_deferred += 1
                        deferred += 1
                        CTRL_BUDGET_DEFERRED.inc()
                        logger.warning(
                            "budget exhausted (%d actions in %.0fs window); "
                            "deferring %s", self.cfg.action_budget,
                            self.cfg.budget_window_s, action.describe())
                        continue
                    rec = self._execute(action)
                    executed.append(rec.action_id)
                self.rounds += 1
                CTRL_ROUNDS.inc()
                return {
                    "ts": signals.ts,
                    "proposed": len(proposed),
                    "settled": executed,
                    "budget_deferred": deferred,
                    "pending": [r.action_id for r in self._pending],
                    "dry_run": self.cfg.dry_run,
                }

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="fleet-controller", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.reconcile_once()
            except Exception:  # loop survives a bad round  # lint: allow-swallow
                logger.exception("reconcile round failed")
            self._stop.wait(self.cfg.loop_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self.journal is not None:
            self.journal.close()

    # -- introspection -----------------------------------------------------

    def debug_view(self) -> dict:
        with self._mu:
            now = self._clock()
            window = [t for t in self._budget_ts
                      if now - t <= self.cfg.budget_window_s]
            return {
                "dry_run": self.cfg.dry_run,
                "rounds": self.rounds,
                "resumed_records": self.resumed_records,
                "budget": {
                    "limit": self.cfg.action_budget,
                    "window_s": self.cfg.budget_window_s,
                    "used": len(window),
                    "deferred_total": self.budget_deferred,
                },
                "policy": self.policy.debug_view(),
                "pending": [r.to_wire() for r in self._pending],
                "actions": list(self._history),
                "would_act": list(self._would_act),
            }
