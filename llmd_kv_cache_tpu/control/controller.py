"""The reconciliation loop: sense → decide → act, journaled and traced.

Each round takes one :class:`FleetSignals` snapshot, first *resolves*
any in-flight actions a predecessor journaled but never settled (verify
against observed topology; only re-execute when the world does not
already reflect the action — never repeat, never reverse), then asks the
policy for new actions and pushes them through the actuator under the
global action budget.

Crash safety is the journal's write ordering: ``planned`` lands on disk
*before* the actuator runs, ``executed``/``failed`` after it settles, so
every controller state is reconstructible from the journal alone. Every
executed (or dry-run) action gets a ``llm_d.kv_cache.control.action``
span whose attributes carry the causing alert/signal snapshot — the
audit trail from "SLO burned" to "topology changed" is one trace query.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from prometheus_client import Counter, Gauge

from ..metrics.collector import record_fence_rejection
from ..resilience.failpoints import failpoints
from ..telemetry.flight_recorder import KIND_FENCE
from ..telemetry.flight_recorder import record as fr_record
from ..utils.lockdep import new_lock
from ..utils.logging import get_logger
from ..telemetry.tracing import tracer
from .actions import (
    ACTION_ADD_SHARD,
    ACTION_DRAIN_POD,
    ACTION_REMOVE_SHARD,
    ACTION_SET_ROLE,
    TOPOLOGY_KINDS,
    Action,
    Actuator,
)
from .config import ControllerConfig
from .journal import (
    PHASE_EXECUTED,
    PHASE_FAILED,
    PHASE_FENCED,
    PHASE_PLANNED,
    PHASE_WOULD_ACT,
    ActionJournal,
    ActionRecord,
    last_settlement_ts,
    unresolved_actions,
)
from .policy import ControlPolicy
from .signals import FleetSignals

logger = get_logger("control.controller")

CTRL_ROUNDS = Counter(
    "kvtpu_ctrl_reconcile_rounds_total",
    "Fleet-controller reconcile rounds completed",
)
CTRL_ACTIONS = Counter(
    "kvtpu_ctrl_actions_total",
    "Fleet-controller actions by kind and settlement phase",
    ["kind", "phase"],
)
CTRL_BUDGET_DEFERRED = Counter(
    "kvtpu_ctrl_budget_deferred_total",
    "Actions the policy wanted but the global budget deferred",
)
CTRL_INFLIGHT = Gauge(
    "kvtpu_ctrl_inflight_actions",
    "Journaled planned actions not yet settled",
)

SPAN_RECONCILE = "llm_d.kv_cache.control.reconcile"
SPAN_ACTION = "llm_d.kv_cache.control.action"

# Failpoint fired between a topology action's propose (``planned``
# journal record) and its commit fence check — ``pause`` mode here
# simulates a controller that stalled mid-mutation while a rival
# committed the contested epoch (the split-brain chaos suite's seam).
FP_COMMIT_PREFIX = "controller.commit."


class FleetController:
    """Sense → decide → act loop over a signal source and an actuator."""

    def __init__(
        self,
        signal_source,
        actuator: Actuator,
        config: Optional[ControllerConfig] = None,
        journal: Optional[ActionJournal] = None,
        clock: Callable[[], float] = time.time,
        membership=None,
    ):
        self.cfg = config or ControllerConfig()
        self.source = signal_source
        self.actuator = actuator
        # Optional cluster.membership.MembershipTable — the fleet epoch
        # authority topology commits publish to (and fence against).
        self.membership = membership
        # Wall clock on purpose: journal timestamps must stay comparable
        # across restarts for cooldown/budget restoration.
        self._clock = clock
        self.policy = ControlPolicy(self.cfg, clock)
        if journal is None and self.cfg.journal_path:
            journal = ActionJournal(self.cfg.journal_path)
        self.journal = journal
        self._mu = new_lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.rounds = 0
        self.budget_deferred = 0
        # Executed-action timestamps inside the sliding budget window.
        self._budget_ts: deque = deque()
        # Bounded histories for kvdiag / /debug/controller.
        self._history: deque = deque(maxlen=self.cfg.history)
        self._would_act: deque = deque(maxlen=self.cfg.history)
        # In-flight (planned, unsettled) records to resolve next round.
        self._pending: List[ActionRecord] = []
        # Monotonic action-id counter, assigned BEFORE the planned record
        # is journaled so the on-disk planned and settled records carry
        # the same action_id (unresolved_actions matches by id).
        self._action_counter = 0
        # Highest topology epoch this controller has committed or
        # observed (journal replay + signal polls + membership). Topology
        # mutations propose epoch+1 and fence the commit against it.
        self._epoch = 0
        self._signals_epoch = 0
        # Latched once this controller loses an epoch race: a fenced
        # controller stops mutating and defers to the winner until it is
        # restarted (re-admission re-reads the committed fleet epoch).
        self.fenced = False
        self.fence_events = 0
        self.resumed_records = 0
        if self.journal is not None:
            self._restore()

    # -- warm restart ------------------------------------------------------

    def _restore(self) -> None:
        records = list(self.journal.replay())
        self.resumed_records = len(records)
        if not records:
            return
        # Resume past the highest journal seq: action ids embed the
        # counter, so reuse across restarts would alias distinct actions.
        self._action_counter = max(r.seq for r in records)
        # Resume at the highest epoch the journal ever saw — committed or
        # merely proposed. A proposed-but-unsettled epoch must not be
        # re-minted blindly: _resolve_pending fences it if the fleet
        # moved past it while this controller was down.
        self._epoch = max(self._epoch, max(r.epoch for r in records))
        for kind, ts in last_settlement_ts(records).items():
            self.policy.notify_action(kind, ts)
        now = self._clock()
        for rec in records:
            if rec.phase == PHASE_EXECUTED \
                    and now - rec.ts <= self.cfg.budget_window_s:
                self._budget_ts.append(rec.ts)
            if rec.phase in (PHASE_EXECUTED, PHASE_FAILED):
                self._history.append(rec.to_wire())
            elif rec.phase == PHASE_WOULD_ACT:
                self._would_act.append(rec.to_wire())
        self._pending = unresolved_actions(records)
        CTRL_INFLIGHT.set(len(self._pending))
        if self._pending:
            logger.info(
                "restored %d journal records, %d in-flight action(s) to "
                "re-verify: %s", len(records), len(self._pending),
                [r.action_id for r in self._pending])
        else:
            logger.info("restored %d journal records, no in-flight actions",
                        len(records))

    # -- budget ------------------------------------------------------------

    def _budget_ok(self) -> bool:
        now = self._clock()
        while self._budget_ts and now - self._budget_ts[0] > self.cfg.budget_window_s:
            self._budget_ts.popleft()
        return len(self._budget_ts) < self.cfg.action_budget

    def _charge_budget(self) -> None:
        self._budget_ts.append(self._clock())

    # -- journaling helpers ------------------------------------------------

    def _journal(self, record: ActionRecord) -> ActionRecord:
        if self.journal is not None:
            return self.journal.append(record)
        # No persistence configured: still assign seqs so action ids and
        # histories stay well-formed.
        self._seq = getattr(self, "_seq", 0) + 1
        record.seq = self._seq
        return record

    def _record(self, action: Action, phase: str,
                result: Optional[dict] = None,
                epoch: Optional[int] = None) -> ActionRecord:
        self._action_counter += 1
        rec = ActionRecord(
            action_id=action.action_id(self._action_counter),
            seq=0,
            ts=self._clock(),
            phase=phase,
            kind=action.kind,
            target=action.target,
            params=dict(action.params),
            reason=action.reason,
            signal=dict(action.signal),
            result=dict(result or {}),
            epoch=int(self._epoch if epoch is None else epoch),
        )
        return self._journal(rec)

    # -- epoch fencing -----------------------------------------------------

    def _fleet_epoch(self) -> int:
        """Highest committed topology epoch this controller can see:
        its own commits, the membership table, the last signal poll."""
        epoch = max(self._epoch, self._signals_epoch)
        if self.membership is not None:
            epoch = max(epoch, int(self.membership.epoch))
        return epoch

    def _fence(self, planned: ActionRecord, action: Action,
               fleet_epoch: int) -> ActionRecord:
        """Journal the loss of an epoch race and latch self-fencing."""
        self.fenced = True
        self.fence_events += 1
        self._epoch = max(self._epoch, fleet_epoch)
        fenced = ActionRecord(
            action_id=planned.action_id, seq=0, ts=self._clock(),
            phase=PHASE_FENCED, kind=action.kind, target=action.target,
            params=dict(action.params), reason=action.reason,
            signal=dict(action.signal),
            result={"ok": False, "fenced": True,
                    "proposed_epoch": int(planned.epoch),
                    "fleet_epoch": int(fleet_epoch)},
            epoch=planned.epoch,
        )
        fenced = self._journal(fenced)
        CTRL_ACTIONS.labels(action.kind, PHASE_FENCED).inc()
        record_fence_rejection("controller.commit", "stale_epoch")
        fr_record(KIND_FENCE, {
            "site": "controller.commit", "reason": "stale_epoch",
            "action_id": planned.action_id,
            "proposed_epoch": int(planned.epoch),
            "fleet_epoch": int(fleet_epoch),
        })
        self._pending = [p for p in self._pending
                         if p.action_id != planned.action_id]
        CTRL_INFLIGHT.set(len(self._pending))
        self._history.append(fenced.to_wire())
        logger.warning(
            "action %s fenced: proposed epoch %d but fleet already "
            "committed %d — another controller won the race; this "
            "controller self-fences until restart",
            planned.action_id, planned.epoch, fleet_epoch)
        return fenced

    # -- action execution --------------------------------------------------

    def _execute(self, action: Action) -> ActionRecord:
        """planned → actuate → executed/failed, traced and journaled.

        Topology mutations are two-phase: *propose* journals ``planned``
        with epoch ``fleet+1``; *commit* re-reads the fleet epoch right
        before actuating and abandons the action (``fenced`` record,
        self-fence latch) if a rival controller committed the contested
        epoch in between — at most one controller's mutation lands per
        epoch, no matter how many believe they are the leader.
        """
        topology = action.kind in TOPOLOGY_KINDS
        proposed = self._fleet_epoch() + 1 if topology else None
        planned = self._record(action, PHASE_PLANNED, epoch=proposed)
        CTRL_ACTIONS.labels(action.kind, PHASE_PLANNED).inc()
        self._pending.append(planned)
        CTRL_INFLIGHT.set(len(self._pending))
        if topology:
            stall = failpoints.pause_seconds(FP_COMMIT_PREFIX + action.target)
            if stall:
                logger.warning(
                    "action %s stalled %.3fs between propose and commit "
                    "(failpoint)", planned.action_id, stall)
            fleet = max(self._signals_epoch,
                        int(self.membership.epoch)
                        if self.membership is not None else 0)
            if fleet >= proposed:
                return self._fence(planned, action, fleet)
        try:
            with tracer().span(
                SPAN_ACTION,
                action_id=planned.action_id,
                action_kind=action.kind,
                action_target=action.target,
                reason=action.reason,
                signal=json.dumps(action.signal, sort_keys=True,
                                  default=repr),
                dry_run=False,
            ):
                result = self.actuator.apply(action)
            phase, payload = PHASE_EXECUTED, {"ok": True, **(result or {})}
            self._charge_budget()
            if topology:
                # Commit: the new epoch becomes the fleet's, and every
                # peer learns it by piggyback on the next RPC it sees.
                self._epoch = proposed
                if self.membership is not None:
                    self.membership.observe_epoch(
                        proposed, source="controller.commit")
        except Exception as exc:
            phase, payload = PHASE_FAILED, {"ok": False, "error": repr(exc)}
            logger.warning("action %s failed: %r", planned.action_id, exc)
        settled = ActionRecord(
            action_id=planned.action_id,
            seq=0,
            ts=self._clock(),
            phase=phase,
            kind=action.kind,
            target=action.target,
            params=dict(action.params),
            reason=action.reason,
            signal=dict(action.signal),
            result=payload,
            epoch=planned.epoch,
        )
        settled = self._journal(settled)
        CTRL_ACTIONS.labels(action.kind, phase).inc()
        self._pending = [p for p in self._pending
                         if p.action_id != planned.action_id]
        CTRL_INFLIGHT.set(len(self._pending))
        self._history.append(settled.to_wire())
        return settled

    def _dry_run(self, action: Action) -> ActionRecord:
        with tracer().span(
            SPAN_ACTION,
            action_kind=action.kind,
            action_target=action.target,
            reason=action.reason,
            signal=json.dumps(action.signal, sort_keys=True, default=repr),
            dry_run=True,
        ):
            rec = self._record(action, PHASE_WOULD_ACT,
                               result={"dry_run": True})
        CTRL_ACTIONS.labels(action.kind, PHASE_WOULD_ACT).inc()
        self._would_act.append(rec.to_wire())
        return rec

    # -- in-flight resolution ----------------------------------------------

    def _world_reflects(self, rec: ActionRecord,
                        signals: FleetSignals) -> bool:
        """Does observed topology already show this action's effect?"""
        if rec.kind == ACTION_SET_ROLE:
            return signals.roles.get(rec.target) == rec.params.get("role")
        if rec.kind == ACTION_ADD_SHARD:
            return rec.target in signals.shards
        if rec.kind == ACTION_REMOVE_SHARD:
            return rec.target not in signals.shards
        if rec.kind == ACTION_DRAIN_POD:
            # Drain leaves no durable topology mark; once its pod is gone
            # from the ring the paired scale-down clearly went through.
            return rec.target not in signals.shards
        return False

    def _resolve_pending(self, signals: FleetSignals) -> None:
        pending, self._pending = self._pending, []
        for rec in pending:
            if self.fenced:
                # Lost an epoch race earlier in this resolution pass:
                # keep the rest in-flight for the winner (or a restart)
                # to verify — a fenced controller executes nothing.
                self._pending.append(rec)
                continue
            action = Action(kind=rec.kind, target=rec.target,
                            params=dict(rec.params),
                            reason=f"resume in-flight: {rec.reason}",
                            signal=dict(rec.signal))
            if self._world_reflects(rec, signals):
                settled = ActionRecord(
                    action_id=rec.action_id, seq=0, ts=self._clock(),
                    phase=PHASE_EXECUTED, kind=rec.kind, target=rec.target,
                    params=dict(rec.params), reason=rec.reason,
                    signal=dict(rec.signal),
                    result={"ok": True, "resumed": True,
                            "already_applied": True},
                    epoch=rec.epoch,
                )
                settled = self._journal(settled)
                CTRL_ACTIONS.labels(rec.kind, PHASE_EXECUTED).inc()
                self._history.append(settled.to_wire())
                logger.info("in-flight action %s already applied; settled "
                            "without re-executing", rec.action_id)
                continue
            if rec.kind in TOPOLOGY_KINDS and rec.epoch:
                # Warm-restart split-brain check: this controller died
                # between propose and commit. If the fleet meanwhile
                # committed the proposed epoch (or beyond) — and the
                # world does *not* reflect our plan — a rival won it;
                # re-executing now would mutate topology under a stale
                # epoch. Fence instead.
                fleet = max(self._signals_epoch,
                            int(self.membership.epoch)
                            if self.membership is not None else 0)
                if fleet >= rec.epoch:
                    self._fence(rec, Action(
                        kind=rec.kind, target=rec.target,
                        params=dict(rec.params), reason=rec.reason,
                        signal=dict(rec.signal)), fleet)
                    continue
            if self.cfg.dry_run:
                self._dry_run(action)
                continue
            if not self._budget_ok():
                self.budget_deferred += 1
                CTRL_BUDGET_DEFERRED.inc()
                self._pending.append(rec)
                continue
            logger.info("re-executing in-flight action %s", rec.action_id)
            self._execute(action)
        CTRL_INFLIGHT.set(len(self._pending))

    # -- the loop ----------------------------------------------------------

    def reconcile_once(self) -> Dict[str, object]:
        """One sense→decide→act round; returns a round summary."""
        with self._mu:
            with tracer().span(SPAN_RECONCILE, dry_run=self.cfg.dry_run):
                signals = self.source.poll()
                self._signals_epoch = max(self._signals_epoch,
                                          int(getattr(signals, "epoch", 0)))
                if self.membership is not None and self._signals_epoch:
                    self.membership.observe_epoch(
                        self._signals_epoch, source="controller.poll")
                if self.fenced:
                    # A fenced controller observes but never mutates: the
                    # epoch race proved a rival is actuating, and two
                    # hands on the same topology is the failure mode this
                    # plane exists to prevent. Restart to re-admit.
                    self.rounds += 1
                    CTRL_ROUNDS.inc()
                    return {
                        "ts": signals.ts,
                        "proposed": 0,
                        "settled": [],
                        "budget_deferred": 0,
                        "pending": [r.action_id for r in self._pending],
                        "dry_run": self.cfg.dry_run,
                        "fenced": True,
                    }
                self._resolve_pending(signals)
                proposed = self.policy.decide(signals)
                executed: List[str] = []
                deferred = 0
                for action in proposed:
                    if self.fenced:
                        break
                    if self.cfg.dry_run:
                        rec = self._dry_run(action)
                        executed.append(rec.action_id)
                        continue
                    if not self._budget_ok():
                        self.budget_deferred += 1
                        deferred += 1
                        CTRL_BUDGET_DEFERRED.inc()
                        logger.warning(
                            "budget exhausted (%d actions in %.0fs window); "
                            "deferring %s", self.cfg.action_budget,
                            self.cfg.budget_window_s, action.describe())
                        continue
                    rec = self._execute(action)
                    if rec.phase != PHASE_FENCED:
                        # A fenced action never landed — it lost the epoch
                        # race, so it is settled in the journal but not a
                        # mutation this round performed.
                        executed.append(rec.action_id)
                self.rounds += 1
                CTRL_ROUNDS.inc()
                return {
                    "ts": signals.ts,
                    "proposed": len(proposed),
                    "settled": executed,
                    "budget_deferred": deferred,
                    "pending": [r.action_id for r in self._pending],
                    "dry_run": self.cfg.dry_run,
                    "fenced": self.fenced,
                }

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="fleet-controller", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.reconcile_once()
            except Exception:  # loop survives a bad round  # lint: allow-swallow
                logger.exception("reconcile round failed")
            self._stop.wait(self.cfg.loop_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self.journal is not None:
            self.journal.close()

    # -- introspection -----------------------------------------------------

    def debug_view(self) -> dict:
        with self._mu:
            now = self._clock()
            window = [t for t in self._budget_ts
                      if now - t <= self.cfg.budget_window_s]
            return {
                "dry_run": self.cfg.dry_run,
                "rounds": self.rounds,
                "resumed_records": self.resumed_records,
                "epoch": {
                    "current": self._epoch,
                    "fleet": self._fleet_epoch(),
                    "fenced": self.fenced,
                    "fence_events": self.fence_events,
                },
                "budget": {
                    "limit": self.cfg.action_budget,
                    "window_s": self.cfg.budget_window_s,
                    "used": len(window),
                    "deferred_total": self.budget_deferred,
                },
                "policy": self.policy.debug_view(),
                "pending": [r.to_wire() for r in self._pending],
                "actions": list(self._history),
                "would_act": list(self._would_act),
            }
