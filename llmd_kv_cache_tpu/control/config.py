"""``controllerConfig`` block: the fleet controller's knobs.

Every threshold here is a *pair* (act / re-arm) or a timer, because the
controller's contract is "never flap": a signal must cross the act band,
stay there for ``confirmRounds`` consecutive reconcile rounds, survive
the per-action cooldown, and fit inside the global action budget before
anything touches the cluster. Crossing back matters too — hysteresis
only re-arms once the signal falls through the (lower) re-arm band, so a
value oscillating around one threshold produces exactly one action.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class ControllerConfig:
    """Fleet-controller policy + loop knobs (camelCase in config files)."""

    # Reconcile loop.
    loop_interval_s: float = 5.0
    # Emit would-have-acted journal records instead of touching the
    # cluster (safe-rollout mode; kvdiag shows the records).
    dry_run: bool = False
    # Append-only action journal (PR 4 framed format). Empty = no
    # persistence (the controller still works, but a restart forgets
    # cooldowns/in-flight actions).
    journal_path: str = ""
    # Global action budget: at most this many *executed* actions per
    # budget window, across every action kind. The last backstop against
    # a confused policy thrashing the fleet.
    action_budget: int = 8
    budget_window_s: float = 600.0
    # A decision must hold for this many consecutive reconcile rounds
    # before the action fires (blip suppression ahead of hysteresis).
    confirm_rounds: int = 2

    # -- indexer shard scaling (HashRing join/leave) ----------------------
    # Act when the score_latency SLO's slow-window burn rate crosses
    # scale_up; re-arm / scale down only once it falls under scale_down.
    score_burn_scale_up: float = 1.0
    score_burn_scale_down: float = 0.25
    min_shards: int = 1
    max_shards: int = 16
    shard_cooldown_s: float = 120.0

    # -- engine pod re-roling (prefill <-> decode) ------------------------
    # Act when the offered traffic mix (handoff coordinator's EMA of the
    # prefill-token fraction) diverges from the provisioned role split by
    # more than role_imbalance_act; re-arm under role_imbalance_rearm.
    role_imbalance_act: float = 0.20
    role_imbalance_rearm: float = 0.10
    min_prefill_pods: int = 1
    min_decode_pods: int = 1
    role_cooldown_s: float = 60.0

    # -- scale-down safety ------------------------------------------------
    # Pods are drained (PR 4 graceful drain) before shard removal; the
    # drain itself is also cooldown-guarded.
    drain_cooldown_s: float = 120.0
    drain_deadline_s: float = 10.0

    # Bound on remembered dry-run / executed action history (kvdiag).
    history: int = 64

    @classmethod
    def from_dict(cls, data: Optional[dict]) -> "ControllerConfig":
        if not data:
            return cls()

        def k(camel: str, snake: str, default):
            if camel in data:
                return data[camel]
            if snake in data:
                return data[snake]
            return default

        d = cls()
        return cls(
            loop_interval_s=float(
                k("loopIntervalS", "loop_interval_s", d.loop_interval_s)),
            dry_run=bool(k("dryRun", "dry_run", d.dry_run)),
            journal_path=str(k("journalPath", "journal_path", d.journal_path)),
            action_budget=int(
                k("actionBudget", "action_budget", d.action_budget)),
            budget_window_s=float(
                k("budgetWindowS", "budget_window_s", d.budget_window_s)),
            confirm_rounds=int(
                k("confirmRounds", "confirm_rounds", d.confirm_rounds)),
            score_burn_scale_up=float(
                k("scoreBurnScaleUp", "score_burn_scale_up",
                  d.score_burn_scale_up)),
            score_burn_scale_down=float(
                k("scoreBurnScaleDown", "score_burn_scale_down",
                  d.score_burn_scale_down)),
            min_shards=int(k("minShards", "min_shards", d.min_shards)),
            max_shards=int(k("maxShards", "max_shards", d.max_shards)),
            shard_cooldown_s=float(
                k("shardCooldownS", "shard_cooldown_s", d.shard_cooldown_s)),
            role_imbalance_act=float(
                k("roleImbalanceAct", "role_imbalance_act",
                  d.role_imbalance_act)),
            role_imbalance_rearm=float(
                k("roleImbalanceRearm", "role_imbalance_rearm",
                  d.role_imbalance_rearm)),
            min_prefill_pods=int(
                k("minPrefillPods", "min_prefill_pods", d.min_prefill_pods)),
            min_decode_pods=int(
                k("minDecodePods", "min_decode_pods", d.min_decode_pods)),
            role_cooldown_s=float(
                k("roleCooldownS", "role_cooldown_s", d.role_cooldown_s)),
            drain_cooldown_s=float(
                k("drainCooldownS", "drain_cooldown_s", d.drain_cooldown_s)),
            drain_deadline_s=float(
                k("drainDeadlineS", "drain_deadline_s", d.drain_deadline_s)),
            history=int(k("history", "history", d.history)),
        )
