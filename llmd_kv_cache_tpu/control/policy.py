"""Decision policy: hysteresis bands, cooldown timers, starved-side math.

The policy is a pure-ish function of one :class:`FleetSignals` snapshot
plus its own small anti-flap state. Three rules:

- **indexer shard scale-up/down** — driven by the ``score_latency``
  SLO's slow-window burn rate (and its firing alert). The up band
  (``score_burn_scale_up``) and down band (``score_burn_scale_down``)
  form the hysteresis gap: between them the policy holds still, so a
  burn rate oscillating around one threshold cannot flap the ring.
  Scale-down emits a graceful drain of the victim *before* the
  membership change (PR 4 drain → PR 6 leave, < 2/N key movement).
- **engine re-role** — the handoff coordinator's traffic-mix EMA
  (prefill-token fraction) vs the provisioned role split. When offered
  mix diverges from capacity past ``role_imbalance_act``, one pod flips
  from the over-provisioned role to the starved one; the rule re-arms
  only once the imbalance falls under ``role_imbalance_rearm``.
- **confirmation + cooldown** — every rule must hold for
  ``confirm_rounds`` consecutive polls and respect a per-action-kind
  cooldown. The *global* action budget is the controller's job (it also
  covers actuator failures and restarts), not the policy's.

All state is reconstructible: the controller replays journal timestamps
into :meth:`notify_action` after a restart so cooldowns survive crashes.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from .actions import (
    ACTION_ADD_SHARD,
    ACTION_DRAIN_POD,
    ACTION_REMOVE_SHARD,
    ACTION_SET_ROLE,
    Action,
)
from .config import ControllerConfig
from .signals import FleetSignals

ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"


class Hysteresis:
    """Two-band trigger with consecutive-round confirmation.

    ``direction="above"``: fires once ``value >= act`` held for
    ``confirm_rounds`` polls; stays disarmed until ``value <= rearm``.
    ``direction="below"`` mirrors it (fires at/under ``act``, re-arms
    at/over ``rearm``). The gap between the bands is the no-flap zone.
    """

    def __init__(self, act: float, rearm: float, confirm_rounds: int = 1,
                 direction: str = "above"):
        if direction not in ("above", "below"):
            raise ValueError(f"bad hysteresis direction {direction!r}")
        if direction == "above" and rearm > act:
            raise ValueError("above-band hysteresis needs rearm <= act")
        if direction == "below" and rearm < act:
            raise ValueError("below-band hysteresis needs rearm >= act")
        self.act = act
        self.rearm = rearm
        self.confirm_rounds = max(1, confirm_rounds)
        self.direction = direction
        self.armed = True
        self.streak = 0

    def _past_act(self, value: float) -> bool:
        return value >= self.act if self.direction == "above" \
            else value <= self.act

    def _past_rearm(self, value: float) -> bool:
        return value <= self.rearm if self.direction == "above" \
            else value >= self.rearm

    def update(self, value: float) -> bool:
        """Feed one poll's value; True exactly when the trigger fires."""
        if not self.armed:
            if self._past_rearm(value):
                self.armed = True
                self.streak = 0
            return False
        if self._past_act(value):
            self.streak += 1
            if self.streak >= self.confirm_rounds:
                self.armed = False
                self.streak = 0
                return True
            return False
        self.streak = 0
        return False

    def debug(self) -> dict:
        return {
            "act": self.act,
            "rearm": self.rearm,
            "direction": self.direction,
            "armed": self.armed,
            "streak": self.streak,
            "confirm_rounds": self.confirm_rounds,
        }


class Cooldown:
    """Per-key minimum spacing between actions."""

    def __init__(self, period_s: float,
                 clock: Callable[[], float] = time.time):
        self.period_s = period_s
        self._clock = clock
        self._last: Dict[str, float] = {}

    def ready(self, key: str = "") -> bool:
        return self.remaining(key) <= 0.0

    def remaining(self, key: str = "") -> float:
        last = self._last.get(key)
        if last is None:
            return 0.0
        return max(0.0, self.period_s - (self._clock() - last))

    def stamp(self, key: str = "", ts: Optional[float] = None) -> None:
        ts = self._clock() if ts is None else ts
        self._last[key] = max(self._last.get(key, 0.0), ts)

    def debug(self) -> dict:
        return {
            "period_s": self.period_s,
            "remaining_s": {k: round(self.remaining(k), 2)
                            for k in self._last},
        }


def next_shard_name(shards) -> str:
    """Deterministic fresh shard id: numeric-suffix max + 1."""
    best = -1
    for shard in shards:
        tail = shard.rsplit("-", 1)[-1]
        if tail.isdigit():
            best = max(best, int(tail))
    return f"shard-{best + 1 if best >= 0 else len(list(shards))}"


class ControlPolicy:
    """Signals → zero or more actions, with anti-flap state."""

    def __init__(self, config: ControllerConfig,
                 clock: Callable[[], float] = time.time):
        self.cfg = config
        self._clock = clock
        self._scale_up = Hysteresis(
            act=config.score_burn_scale_up,
            rearm=config.score_burn_scale_down,
            confirm_rounds=config.confirm_rounds,
            direction="above",
        )
        self._scale_down = Hysteresis(
            act=config.score_burn_scale_down,
            rearm=config.score_burn_scale_up,
            confirm_rounds=max(config.confirm_rounds, 2),
            direction="below",
        )
        # Directional re-role triggers on the signed mix-vs-capacity
        # imbalance: positive = prefill starved, negative = decode starved.
        self._role_prefill = Hysteresis(
            act=config.role_imbalance_act,
            rearm=config.role_imbalance_rearm,
            confirm_rounds=config.confirm_rounds,
            direction="above",
        )
        self._role_decode = Hysteresis(
            act=-config.role_imbalance_act,
            rearm=-config.role_imbalance_rearm,
            confirm_rounds=config.confirm_rounds,
            direction="below",
        )
        self._cooldowns = {
            ACTION_ADD_SHARD: Cooldown(config.shard_cooldown_s, clock),
            ACTION_REMOVE_SHARD: Cooldown(config.shard_cooldown_s, clock),
            ACTION_SET_ROLE: Cooldown(config.role_cooldown_s, clock),
            ACTION_DRAIN_POD: Cooldown(config.drain_cooldown_s, clock),
        }

    # -- state restoration -------------------------------------------------

    def notify_action(self, kind: str, ts: Optional[float] = None) -> None:
        """Stamp a cooldown (at decision time, and from journal replay)."""
        cd = self._cooldowns.get(kind)
        if cd is not None:
            cd.stamp("", ts)

    def cooldown_ready(self, kind: str) -> bool:
        cd = self._cooldowns.get(kind)
        return cd is None or cd.ready()

    # -- the decision ------------------------------------------------------

    def decide(self, signals: FleetSignals) -> List[Action]:
        actions: List[Action] = []
        actions.extend(self._decide_shards(signals))
        actions.extend(self._decide_roles(signals))
        return actions

    def _score_signal(self, signals: FleetSignals) -> dict:
        return {
            "slo": "score_latency",
            "severity": signals.severity("score_latency"),
            "burn_slow": round(signals.burn("score_latency"), 3),
            "alert_edges": [e for e in signals.alert_edges
                            if e.get("slo") == "score_latency"],
            "dominant_segment": dict(signals.dominant_segment),
            "whatif": list(signals.whatif),
        }

    def _decide_shards(self, signals: FleetSignals) -> List[Action]:
        burn = signals.burn("score_latency")
        # A firing alert counts as a saturated burn signal even when the
        # slow window hasn't caught up yet (fast_burn fires first).
        effective = burn
        if signals.firing("score_latency"):
            effective = max(effective, self.cfg.score_burn_scale_up)
        out: List[Action] = []
        up = self._scale_up.update(effective)
        down = self._scale_down.update(effective)
        n = len(signals.shards)
        if up and n and n < self.cfg.max_shards \
                and self.cooldown_ready(ACTION_ADD_SHARD):
            target = next_shard_name(signals.shards)
            self.notify_action(ACTION_ADD_SHARD)
            out.append(Action(
                kind=ACTION_ADD_SHARD,
                target=target,
                params={"bootstrap": "snapshot"},
                reason=(f"score_latency burn {burn:.2f} >= "
                        f"{self.cfg.score_burn_scale_up:.2f} "
                        f"({n} -> {n + 1} shards)"),
                signal=self._score_signal(signals),
            ))
        elif down and n > self.cfg.min_shards \
                and not signals.firing("score_latency") \
                and self.cooldown_ready(ACTION_REMOVE_SHARD):
            victim = sorted(signals.shards)[-1]
            self.notify_action(ACTION_REMOVE_SHARD)
            self.notify_action(ACTION_DRAIN_POD)
            signal = self._score_signal(signals)
            out.append(Action(
                kind=ACTION_DRAIN_POD,
                target=victim,
                params={"deadline_s": self.cfg.drain_deadline_s},
                reason=(f"drain ahead of scale-down: score_latency burn "
                        f"{burn:.2f} <= {self.cfg.score_burn_scale_down:.2f}"),
                signal=signal,
            ))
            out.append(Action(
                kind=ACTION_REMOVE_SHARD,
                target=victim,
                reason=(f"score_latency burn {burn:.2f} <= "
                        f"{self.cfg.score_burn_scale_down:.2f} "
                        f"({n} -> {n - 1} shards)"),
                signal=signal,
            ))
        return out

    def _decide_roles(self, signals: FleetSignals) -> List[Action]:
        mix = (signals.handoff.get("mix") or {})
        offered = mix.get("prefill_fraction")
        prefill = signals.pods_with_role(ROLE_PREFILL)
        decode = signals.pods_with_role(ROLE_DECODE)
        total = len(prefill) + len(decode)
        if offered is None or total == 0:
            return []
        provisioned = len(prefill) / total
        imbalance = float(offered) - provisioned
        prefill_starved = self._role_prefill.update(imbalance)
        decode_starved = self._role_decode.update(imbalance)
        if not self.cooldown_ready(ACTION_SET_ROLE):
            return []
        signal = {
            "slo": "ttft",
            "severity": signals.severity("ttft"),
            "burn_slow": round(signals.burn("ttft"), 3),
            "alert_edges": [e for e in signals.alert_edges
                            if e.get("slo") == "ttft"],
            "handoff": dict(signals.handoff),
            "offered_prefill_fraction": round(float(offered), 3),
            "provisioned_prefill_fraction": round(provisioned, 3),
            "imbalance": round(imbalance, 3),
        }
        if prefill_starved and len(decode) > self.cfg.min_decode_pods:
            donor = decode[-1]
            self.notify_action(ACTION_SET_ROLE)
            return [Action(
                kind=ACTION_SET_ROLE,
                target=donor,
                params={"role": ROLE_PREFILL},
                reason=(f"prefill starved: offered mix {offered:.2f} vs "
                        f"provisioned {provisioned:.2f} "
                        f"(imbalance {imbalance:+.2f})"),
                signal=signal,
            )]
        if decode_starved and len(prefill) > self.cfg.min_prefill_pods:
            donor = prefill[-1]
            self.notify_action(ACTION_SET_ROLE)
            return [Action(
                kind=ACTION_SET_ROLE,
                target=donor,
                params={"role": ROLE_DECODE},
                reason=(f"decode starved: offered mix {offered:.2f} vs "
                        f"provisioned {provisioned:.2f} "
                        f"(imbalance {imbalance:+.2f})"),
                signal=signal,
            )]
        return []

    # -- introspection -----------------------------------------------------

    def debug_view(self) -> dict:
        return {
            "hysteresis": {
                "shard_scale_up": self._scale_up.debug(),
                "shard_scale_down": self._scale_down.debug(),
                "role_prefill_starved": self._role_prefill.debug(),
                "role_decode_starved": self._role_decode.debug(),
            },
            "cooldowns": {
                kind: cd.debug() for kind, cd in self._cooldowns.items()
            },
        }
