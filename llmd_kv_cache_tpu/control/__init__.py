"""Self-driving fleet controller: close the loop from SLO burn to topology.

The observability planes (PRs 10-12) are the fleet's *senses* — burn-rate
alerts, critical-path attribution, working-set what-if tables — and the
cluster/recovery planes (PRs 4/6/8) are its *actuators* — ``HashRing``
join/leave with snapshot bootstrap, ``EngineConfig.role`` re-roling, and
graceful drain. This package is the loop between them: a reconciliation
controller that polls fleet signals, runs them through a hysteresis/
cooldown/budget policy, and emits concrete topology actions through a
pluggable actuator interface — every action journaled (crash-safe),
traced (``llm_d.kv_cache.control.*``), and dry-runnable.
"""

from .actions import (  # noqa: F401
    ACTION_ADD_SHARD,
    ACTION_DRAIN_POD,
    ACTION_REMOVE_SHARD,
    ACTION_SET_ROLE,
    Action,
    Actuator,
    AdminPlaneActuator,
    InProcessActuator,
)
from .config import ControllerConfig  # noqa: F401
from .controller import FleetController  # noqa: F401
from .journal import (  # noqa: F401
    ActionJournal,
    ActionRecord,
    last_settlement_ts,
    unresolved_actions,
)
from .policy import (  # noqa: F401
    ControlPolicy,
    Cooldown,
    Hysteresis,
    next_shard_name,
)
from .signals import CollectorSignalSource, FleetSignals  # noqa: F401
