"""Fleet signal snapshot: everything the policy reads, in one struct.

The controller is deliberately *pull*-shaped: each reconcile round takes
one immutable :class:`FleetSignals` snapshot and decides from it alone,
so a decision is always attributable to a concrete, journalable signal
state (the acceptance criterion: every action's span carries the alert/
signal that caused it).

Sources:

- **SLO state + alert edges** — ``telemetry/slo.py``'s registry; edges
  (fire/clear transitions) rather than level state, so the policy can
  react to a fire exactly once and the journal names the alert.
- **critical-path dominant segment** — the collector's retained traces:
  *where* the request time goes steers *which* actuator helps (score
  fan-out dominant → shard scale-up; decode/admission dominant →
  re-role).
- **handoff residency/starvation stats** — the coordinator's traffic-mix
  EMA + transfer-pressure counters name the starved side.
- **what-if capacity table** — PR 12's working-set plane; journaled with
  scale decisions so capacity actions are auditable against the MRC.
- **topology** — current shard membership and pod→role map (what the
  actions mutate; also how a restarted controller verifies in-flight
  actions against reality).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple


@dataclass(frozen=True)
class FleetSignals:
    """One reconcile round's immutable input snapshot."""

    ts: float = 0.0
    # slo name -> {"severity": None|fast_burn|slow_burn, "burn_slow": x}
    slo: Dict[str, dict] = field(default_factory=dict)
    # New alert edges since the previous round:
    # {"slo", "severity", "edge": fire|clear, "ts", "seq"}
    alert_edges: Tuple[dict, ...] = ()
    # Dominant critical-path segment across retained traces
    # ({"name", "process", "self_time_s"}) or {}.
    dominant_segment: dict = field(default_factory=dict)
    # Handoff coordinator starvation/residency stats (see
    # offload.handoff.HandoffCoordinator.starvation()).
    handoff: dict = field(default_factory=dict)
    # PR 12 what-if capacity rows ({"factor", "est_hit_ratio", ...}).
    whatif: Tuple[dict, ...] = ()
    # Overload-shed state per site ({"indexer.score": {"shed_rate": x,
    # "overloaded": bool, "pressure": n}, ...}): a sustained shed rate is
    # the earliest capacity signal the controller gets — requests are
    # already being turned away before any SLO window fills.
    shed: Dict[str, dict] = field(default_factory=dict)
    # Ground-truth audit plane (collector.audit_view()): per-pod
    # phantom/ghost divergence now, calibration error, and the
    # routing-regret rate — lets the policy distinguish "the index is
    # lying about pod X" (divergence → reconcile/demote) from "capacity
    # is short" (shed/SLO burn → scale).
    audit: dict = field(default_factory=dict)
    # Anomaly sentinel level state (telemetry/anomaly.py:
    # AnomalyRegistry.active()): sentinel name -> {"firing", "last_z",
    # "last_value"}. Robust-z detectors over SLI *shape* fire well
    # before a burn-rate window fills, so they are the policy's earliest
    # gray-failure signal (and each fire edge also opens an incident
    # black-box capture).
    anomalies: Dict[str, dict] = field(default_factory=dict)
    # Topology.
    shards: Tuple[str, ...] = ()
    roles: Dict[str, str] = field(default_factory=dict)
    # Highest committed topology epoch observed across the fleet
    # (cluster.membership). 0 when the deployment predates the epoch
    # plane. The controller fences its own proposals against this: a
    # proposal whose epoch the fleet already reached lost the race.
    epoch: int = 0

    def burn(self, slo_name: str) -> float:
        return float((self.slo.get(slo_name) or {}).get("burn_slow", 0.0))

    def severity(self, slo_name: str) -> Optional[str]:
        sev = (self.slo.get(slo_name) or {}).get("severity")
        return str(sev) if sev else None

    def firing(self, slo_name: str) -> bool:
        return self.severity(slo_name) is not None

    def pods_with_role(self, role: str) -> List[str]:
        return sorted(p for p, r in self.roles.items() if r == role)

    def shed_rate(self, site: str) -> float:
        return float((self.shed.get(site) or {}).get("shed_rate", 0.0))

    def anomaly_firing(self, sentinel: str) -> bool:
        return bool((self.anomalies.get(sentinel) or {}).get("firing"))

    def firing_anomalies(self) -> List[str]:
        return sorted(name for name, st in self.anomalies.items()
                      if st.get("firing"))

    def divergent_pods(self) -> List[str]:
        """Pods the divergence audit currently finds out of sync
        (advertising phantom blocks or hiding ghost ones)."""
        return sorted((self.audit.get("divergence") or {}).keys())

    def regret_rate(self) -> float:
        return float(self.audit.get("regret_rate", 0.0))

    def describe(self) -> dict:
        """Compact JSON-able summary (journal/span payloads)."""
        return {
            "ts": self.ts,
            "slo": {
                name: {"severity": st.get("severity"),
                       "burn_slow": round(float(st.get("burn_slow", 0.0)), 3)}
                for name, st in self.slo.items()
            },
            "alert_edges": list(self.alert_edges),
            "dominant_segment": dict(self.dominant_segment),
            "handoff": dict(self.handoff),
            "shed": {site: dict(st) for site, st in self.shed.items()},
            "anomalies": {
                name: dict(st) for name, st in self.anomalies.items()},
            "audit": {
                "divergence": dict(self.audit.get("divergence") or {}),
                "regret_rate": round(self.regret_rate(), 4),
                "mean_abs_error_blocks": round(float(
                    self.audit.get("mean_abs_error_blocks", 0.0)), 3),
            } if self.audit else {},
            "shards": list(self.shards),
            "roles": dict(self.roles),
            "epoch": int(self.epoch),
        }


class CollectorSignalSource:
    """In-process signal source: a live :class:`TelemetryCollector` plus
    topology/handoff hooks (the bench and single-process deployments; the
    HTTP counterpart lives in ``services/fleet_controller.py``)."""

    def __init__(
        self,
        collector=None,
        slo_registry=None,
        handoff=None,
        shards: Optional[Callable[[], List[str]]] = None,
        roles: Optional[Callable[[], Dict[str, str]]] = None,
        shedders: Optional[Callable[[], Dict[str, dict]]] = None,
        membership=None,
        clock: Callable[[], float] = time.time,
    ):
        if collector is None and slo_registry is None:
            raise ValueError(
                "CollectorSignalSource needs a collector or an SLO registry")
        self._collector = collector
        self._slos = slo_registry if slo_registry is not None else collector.slos
        self._handoff = handoff
        self._shards = shards or (lambda: [])
        self._roles = roles or (lambda: {})
        # site -> CoDelShedder.stats() dict; typically
        # ``lambda: {s.site: s.stats() for s in shedders}``.
        self._shedders = shedders or (lambda: {})
        # Optional cluster.membership.MembershipTable (the local fleet
        # epoch authority) so polls carry the committed topology epoch.
        self._membership = membership
        self._clock = clock
        self._edge_cursor = -1

    def poll(self) -> FleetSignals:
        slo_state: Dict[str, dict] = {}
        for name, tracker in self._slos.trackers.items():
            cfg = tracker.config
            slo_state[name] = {
                "severity": tracker.alert_severity,
                "burn_slow": tracker.burn_rate(cfg.slow_window),
            }
        edges_payload = self._slos.export_edges_since(self._edge_cursor)
        self._edge_cursor = int(edges_payload.get("next_seq",
                                                  self._edge_cursor))
        dominant: dict = {}
        whatif: Tuple[dict, ...] = ()
        audit: dict = {}
        anomalies: Dict[str, dict] = {}
        if self._collector is not None:
            best = 0.0
            for summary in self._collector.assembler.retained():
                for seg in summary.get("critical_path") or ():
                    if seg.get("self_time_s", 0.0) > best:
                        best = seg["self_time_s"]
                        dominant = {
                            "name": seg.get("name"),
                            "process": seg.get("process"),
                            "self_time_s": seg.get("self_time_s"),
                            "trace_id": summary.get("trace_id"),
                        }
            try:
                whatif = tuple(
                    self._collector.workingset_view().get("whatif") or ())
            except Exception:  # enrichment, never round-fatal  # lint: allow-swallow
                whatif = ()
            try:
                audit = dict(self._collector.audit_view())
            except Exception:  # enrichment, never round-fatal  # lint: allow-swallow
                audit = {}
            registry = getattr(self._collector, "anomalies", None)
            if registry is not None:
                try:
                    anomalies = dict(registry.active())
                except Exception:  # enrichment, never round-fatal  # lint: allow-swallow
                    anomalies = {}
        handoff = {}
        if self._handoff is not None:
            handoff = self._handoff.starvation()
        try:
            shed = dict(self._shedders())
        except Exception:  # enrichment, never round-fatal  # lint: allow-swallow
            shed = {}
        return FleetSignals(
            ts=self._clock(),
            slo=slo_state,
            alert_edges=tuple(edges_payload.get("edges") or ()),
            dominant_segment=dominant,
            handoff=handoff,
            whatif=whatif,
            shed=shed,
            audit=audit,
            anomalies=anomalies,
            shards=tuple(self._shards()),
            roles=dict(self._roles()),
            epoch=(int(self._membership.epoch)
                   if self._membership is not None else 0),
        )
