"""Append-only, checksummed action journal (PR 4 framed-record format).

Every controller decision is journaled *before* the actuator runs and
again after it settles, so a controller restart can tell three cases
apart:

- ``executed``/``failed`` after ``planned`` — the action settled; replay
  only restores its cooldown/budget accounting.
- ``planned`` with no settlement — the controller died mid-action. The
  action is **in flight**: the successor re-verifies it against observed
  topology instead of repeating it blindly, and the restored cooldown
  prevents an immediate reversal.
- ``would_act`` — dry-run mode; replay restores the record history only.

Record framing is exactly the event journal's (``recovery/journal.py``)::

    +-----------+-----------+------------------------------+
    | u32 length| u32 crc32 | canonical CBOR               |
    | (of body) | (of body) | {action_id, seq, ts, phase,  |
    |           |           |  kind, target, params,       |
    |           |           |  reason, signal, result}     |
    +-----------+-----------+------------------------------+

Appends flush per record and fsync every ``sync_every`` records; a torn
tail (crash mid-append) stops replay cleanly at the last good record.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from dataclasses import dataclass, field
from typing import Iterator, List, Optional

from ..utils.lockdep import new_lock
from ..utils.atomic_io import fsync_dir
from ..utils.cbor import CBORDecodeError, canonical_cbor_decode, canonical_cbor_encode
from ..utils.logging import get_logger

logger = get_logger("control.journal")

_HEADER = struct.Struct("<II")  # body length, body crc32

PHASE_PLANNED = "planned"
PHASE_EXECUTED = "executed"
PHASE_FAILED = "failed"
PHASE_WOULD_ACT = "would_act"
# The proposer lost the epoch race: a newer committed topology epoch was
# observed between propose (``planned``) and commit, so the action was
# abandoned and this controller self-fenced (split-brain loser's record).
PHASE_FENCED = "fenced"


def _jsonable(obj) -> object:
    """CBOR-encodable deep copy of an arbitrary signal payload (anything
    exotic goes through its JSON repr rather than poisoning the append)."""
    try:
        return json.loads(json.dumps(obj, default=repr))
    except (TypeError, ValueError):
        return repr(obj)


@dataclass
class ActionRecord:
    """One journaled phase transition of one action."""

    action_id: str
    seq: int
    ts: float
    phase: str  # planned|executed|failed|would_act|fenced
    kind: str
    target: str
    params: dict = field(default_factory=dict)
    reason: str = ""
    signal: dict = field(default_factory=dict)
    result: dict = field(default_factory=dict)
    # Topology epoch the action proposes/committed (two-phase controller
    # mutations). 0 on records written before the epoch plane existed —
    # decoded tolerantly like params/reason, so old journals replay.
    epoch: int = 0

    def to_wire(self) -> dict:
        return {
            "action_id": self.action_id,
            "seq": int(self.seq),
            "ts": float(self.ts),
            "phase": self.phase,
            "kind": self.kind,
            "target": self.target,
            "params": _jsonable(self.params or {}),
            "reason": self.reason,
            "signal": _jsonable(self.signal or {}),
            "result": _jsonable(self.result or {}),
            "epoch": int(self.epoch),
        }

    @classmethod
    def from_wire(cls, data: dict) -> "ActionRecord":
        return cls(
            action_id=str(data["action_id"]),
            seq=int(data["seq"]),
            ts=float(data["ts"]),
            phase=str(data["phase"]),
            kind=str(data["kind"]),
            target=str(data["target"]),
            params=dict(data.get("params") or {}),
            reason=str(data.get("reason", "")),
            signal=dict(data.get("signal") or {}),
            result=dict(data.get("result") or {}),
            epoch=int(data.get("epoch", 0) or 0),
        )


class ActionJournal:
    """Crash-tolerant append log of controller action records."""

    def __init__(self, path: str, sync_every: int = 1):
        self.path = path
        self.sync_every = max(1, sync_every)
        self._mu = new_lock()
        self._f = None
        self._since_sync = 0
        self._seq = 0
        self.appended = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # Resume the seq counter past any existing records so replayed +
        # new records stay totally ordered.
        for rec in self.replay():
            self._seq = max(self._seq, rec.seq)

    def _file(self):
        if self._f is None:
            self._f = open(self.path, "ab")
        return self._f

    def append(self, record: ActionRecord) -> ActionRecord:
        """Assign the next seq, frame, flush (fsync per ``sync_every``)."""
        with self._mu:
            self._seq += 1
            record.seq = self._seq
            body = canonical_cbor_encode(record.to_wire())
            rec = _HEADER.pack(len(body), zlib.crc32(body) & 0xFFFFFFFF) + body
            f = self._file()
            f.write(rec)
            f.flush()
            self.appended += 1
            self._since_sync += 1
            if self._since_sync >= self.sync_every:
                os.fsync(f.fileno())  # lint: allow-blocking (durability point: seq/_since_sync must match on-disk state, so fsync stays under _mu; bounded by sync_every)
                self._since_sync = 0
        return record

    def close(self) -> None:
        with self._mu:
            if self._f is not None:
                if self._since_sync:
                    self._f.flush()
                    os.fsync(self._f.fileno())  # lint: allow-blocking (final durability barrier on close; no concurrent appends after this)
                    self._since_sync = 0
                self._f.close()
                self._f = None
            fsync_dir(os.path.dirname(self.path) or ".")

    def replay(self) -> Iterator[ActionRecord]:
        """Yield records in append order; stops cleanly at a torn tail."""
        try:
            with open(self.path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return
        pos = 0
        while pos + _HEADER.size <= len(data):
            length, want_crc = _HEADER.unpack_from(data, pos)
            body_start = pos + _HEADER.size
            body_end = body_start + length
            if body_end > len(data):
                logger.warning(
                    "action journal %s: torn tail at offset %d "
                    "(%d bytes abandoned)", self.path, pos, len(data) - pos)
                return
            body = data[body_start:body_end]
            if (zlib.crc32(body) & 0xFFFFFFFF) != want_crc:
                logger.warning(
                    "action journal %s: crc mismatch at offset %d; stopping "
                    "replay (%d bytes abandoned)",
                    self.path, pos, len(data) - pos)
                return
            try:
                item = canonical_cbor_decode(body)
                record = ActionRecord.from_wire(item)
            except (CBORDecodeError, ValueError, TypeError, KeyError):
                logger.warning(
                    "action journal %s: undecodable record at offset %d; "
                    "stopping", self.path, pos)
                return
            pos = body_end
            yield record


def unresolved_actions(records: List[ActionRecord]) -> List[ActionRecord]:
    """``planned`` records with no later ``executed``/``failed``/``fenced``
    for the same action id — the in-flight actions a restart must
    re-verify. A fenced action is settled: a newer topology epoch already
    won, so replay must not resurrect it."""
    settled = {
        r.action_id for r in records
        if r.phase in (PHASE_EXECUTED, PHASE_FAILED, PHASE_FENCED)
    }
    out: List[ActionRecord] = []
    seen: set = set()
    for rec in records:
        if (rec.phase == PHASE_PLANNED and rec.action_id not in settled
                and rec.action_id not in seen):
            seen.add(rec.action_id)
            out.append(rec)
    return out


def last_settlement_ts(records: List[ActionRecord]) -> dict:
    """``kind`` → latest planned/executed ts (cooldown restoration)."""
    out: dict = {}
    for rec in records:
        if rec.phase in (PHASE_PLANNED, PHASE_EXECUTED):
            out[rec.kind] = max(out.get(rec.kind, 0.0), rec.ts)
    return out
