"""Warm-restart orchestration and the readiness state machine.

One :class:`RecoveryManager` owns the crash-tolerance lifecycle of an
indexer process::

    cold --> loading --> replaying --> warming --> ready
                                          |          |
                                          +-- drain -+--> draining --> stopped

* **loading** — newest valid snapshot restored into the index (corrupt
  ones quarantined, see recovery.snapshot).
* **replaying** — journal records past the snapshot's per-pod sequence
  watermark re-ingested through the pool's normal parse path.
* **warming** — live subscriptions are up, but the index's staleness
  estimate (events.pool.index_staleness_s) is still above
  ``warmupStalenessBoundS``; score responses carry ``degraded=True`` so
  routers can widen their fallback.
* **ready** — staleness under the bound; normal serving.

The per-pod sequence watermark is seeded back into the pool so sequence-
gap detection spans the restart: the first live message after a gap the
journal didn't cover is counted as a gap (and anti-entropy repairs the
content).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ..utils.lockdep import new_lock
from ..telemetry import flight_recorder, tracer
from ..telemetry.flight_recorder import KIND_RECOVERY
from ..utils.logging import get_logger
from .config import RecoveryConfig
from .journal import EventJournal
from .snapshot import SNAPSHOT_VERSION, SnapshotStore

logger = get_logger("recovery.manager")

STATE_COLD = "cold"
STATE_LOADING = "loading"
STATE_REPLAYING = "replaying"
STATE_WARMING = "warming"
STATE_READY = "ready"
STATE_DRAINING = "draining"
STATE_STOPPED = "stopped"

JOURNAL_NAME = "events.journal"


class RecoveryManager:
    """Snapshot timer + warm restart + readiness gate for one index/pool."""

    def __init__(
        self,
        cfg: RecoveryConfig,
        index,
        pool,
        store: Optional[SnapshotStore] = None,
        journal: Optional[EventJournal] = None,
    ):
        self.cfg = cfg
        self.index = index
        self.pool = pool
        self.store = store or SnapshotStore(cfg.snapshot_dir, keep=cfg.snapshot_keep)
        self.journal = journal or EventJournal(
            os.path.join(cfg.snapshot_dir, JOURNAL_NAME),
            sync_every=cfg.journal_sync_every,
        )
        self._mu = new_lock()
        self._state = STATE_COLD
        self._state_since = time.time()
        self.restored_entries = 0
        self.replayed_records = 0
        self.snapshots_written = 0
        self.loaded_snapshot: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._sink = None

    # -- state machine ---------------------------------------------------

    @property
    def state(self) -> str:
        # WARMING->READY is pulled, not pushed: every observer of the
        # state (healthz, score path) re-evaluates the staleness gate.
        with self._mu:
            state = self._state
        if state == STATE_WARMING and self._warmed():
            self._transition(STATE_READY, expect=STATE_WARMING)
            return STATE_READY
        return state

    @property
    def ready(self) -> bool:
        return self.state == STATE_READY

    def _warmed(self) -> bool:
        return self.pool.index_staleness_s() <= self.cfg.warmup_staleness_bound_s

    def _transition(self, new: str, expect: Optional[str] = None) -> None:
        with self._mu:
            if expect is not None and self._state != expect:
                return
            old, self._state = self._state, new
            self._state_since = time.time()
        logger.info("recovery state %s -> %s", old, new)
        flight_recorder().record(
            KIND_RECOVERY, {"op": "state", "from": old, "to": new}
        )

    # -- warm restart ----------------------------------------------------

    def warm_restart(self) -> dict:
        """Load newest snapshot, replay the journal past its watermark,
        enter WARMING. Call before live subscriptions start; safe (and a
        fast no-op) on a genuinely cold start."""
        with tracer().span("llm_d.kv_cache.recovery.warm_restart") as span:
            self._transition(STATE_LOADING)
            pod_seqs: dict = {}
            snapshot_ts = 0.0
            loaded = self.store.load_newest()
            if loaded is not None:
                doc, path = loaded
                if doc.get("version") != SNAPSHOT_VERSION:
                    self.store.quarantine(
                        path, f"unsupported version {doc.get('version')!r}"
                    )
                else:
                    self.loaded_snapshot = path
                    pod_seqs = dict(doc.get("pod_seqs") or {})
                    snapshot_ts = float(doc.get("created_unix") or 0.0)
                    index_state = doc.get("index")
                    if index_state:
                        self.restored_entries = self.index.restore_state(index_state)
                    logger.info(
                        "restored %d entries from %s (pods=%d)",
                        self.restored_entries, path, len(pod_seqs),
                    )
            self._transition(STATE_REPLAYING)
            for rec in self.journal.replay(pod_seqs):
                self.pool.replay_record(rec.topic, rec.sequence, rec.payload)
                self.replayed_records += 1
            # Seed the pool's per-pod watermarks so (a) gap detection spans
            # the restart and (b) staleness reflects the snapshot's age
            # until live events catch up — which is exactly the warmup gate.
            if pod_seqs and snapshot_ts > 0:
                self.pool.seed_sequences(pod_seqs, snapshot_ts)
            if self.loaded_snapshot is None and self.replayed_records == 0:
                # Genuinely cold start: nothing to warm from, serve normally.
                self._transition(STATE_READY)
            else:
                self._transition(STATE_WARMING)
            span.set_attribute("restored_entries", self.restored_entries)
            span.set_attribute("replayed_records", self.replayed_records)
        try:
            from ..metrics.collector import record_warm_restart

            record_warm_restart(self.restored_entries, self.replayed_records)
        except Exception:  # pragma: no cover  # lint: allow-swallow
            pass
        summary = {
            "snapshot": self.loaded_snapshot,
            "restored_entries": self.restored_entries,
            "replayed_records": self.replayed_records,
            "state": self.state,
        }
        flight_recorder().record(KIND_RECOVERY, {"op": "warm_restart", **summary})
        return summary

    # -- snapshots -------------------------------------------------------

    def attach_journal(self) -> None:
        """Start journaling live ingestion. Call *after* warm_restart so
        replayed records are not re-journaled."""
        # Keep the exact bound-method object: a fresh `self.journal.append`
        # on every access would never compare identical at detach time.
        self._sink = self.journal.append
        self.pool.journal_sink = self._sink

    def snapshot_now(self, reason: str = "interval") -> Optional[str]:
        """Write one snapshot and rotate the journal. Returns the path, or
        None when the backend has no dumpable state (e.g. bare Redis)."""
        state = self.index.dump_state()
        if state is None:
            return None
        pod_seqs = {
            pod: st.get("last_seq", -1)
            for pod, st in self.pool.lag_stats().get("pods", {}).items()
        }
        doc = {
            "version": SNAPSHOT_VERSION,
            "created_unix": time.time(),
            "reason": reason,
            "pod_seqs": pod_seqs,
            "index": state,
        }
        try:
            path = self.store.save(doc)
        except Exception:
            logger.exception("snapshot write failed")
            try:
                from ..metrics.collector import record_snapshot

                record_snapshot("failed", 0, 0.0)
            except Exception:  # pragma: no cover  # lint: allow-swallow
                pass
            return None
        self.snapshots_written += 1
        # The snapshot watermark supersedes the journal prefix.
        self.journal.rotate()
        return path

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Attach the journal and start the periodic snapshot thread."""
        self.attach_journal()
        if self.cfg.snapshot_interval_s <= 0:
            return
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def _loop() -> None:
            while not self._stop.wait(self.cfg.snapshot_interval_s):
                try:
                    self.snapshot_now("interval")
                except Exception:
                    logger.exception("periodic snapshot failed; continuing")

        self._thread = threading.Thread(
            target=_loop, name="kvtpu-snapshotter", daemon=True
        )
        self._thread.start()

    def stop(self, final_snapshot: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._sink is not None and getattr(self.pool, "journal_sink", None) is self._sink:
            self.pool.journal_sink = None
        if final_snapshot:
            try:
                self.snapshot_now("shutdown")
            except Exception:
                logger.exception("shutdown snapshot failed")
        self.journal.close()
        self._transition(STATE_STOPPED)

    # -- health ----------------------------------------------------------

    def health(self) -> dict:
        """Readiness payload for /healthz and the admin debug surface."""
        state = self.state
        with self._mu:
            since = self._state_since
        staleness = self.pool.index_staleness_s()
        return {
            "status": "ok" if state == STATE_READY else state,
            "state": state,
            "state_age_s": round(max(0.0, time.time() - since), 3),
            "staleness_s": round(staleness, 3),
            "staleness_bound_s": self.cfg.warmup_staleness_bound_s,
            "restored_entries": self.restored_entries,
            "replayed_records": self.replayed_records,
            "snapshots_written": self.snapshots_written,
            "snapshots_quarantined": self.store.quarantined,
            "loaded_snapshot": self.loaded_snapshot,
        }
