"""Versioned, checksummed index snapshots.

On-disk format of one ``index-<seq>.snap`` file::

    +--------------------+----------------------+------------------+
    | magic "KVTPUSNAP1\\n" | canonical CBOR doc  | CRC footer (1    |
    | (11 bytes)          | (the snapshot body)  | slot, integrity) |
    +--------------------+----------------------+------------------+

The CRC footer is the offload layer's checksum trailer
(``resilience/integrity.py``) with a single slot covering the CBOR body,
so snapshot verification shares code — and failure semantics — with
offload-file verification. Files are published durably
(``utils.atomic_io``: tmp + fsync + rename + dirsync) and named by a
monotonically increasing sequence so "newest" is unambiguous even when
mtimes are not.

A snapshot that fails verification (bad magic, CRC mismatch, CBOR decode
error, truncation) is *quarantined* — renamed to ``*.quarantine`` so it
stops being a load candidate but stays on disk for post-mortems — and
the next-newest snapshot is tried (docs/resilience.md runbook).
"""

from __future__ import annotations

import os
import re
import time
import zlib
from typing import Optional

from ..resilience.integrity import (
    IntegrityError,
    build_footer,
    footer_size,
    parse_footer,
)
from ..telemetry import flight_recorder, tracer
from ..telemetry.flight_recorder import KIND_RECOVERY
from ..utils.atomic_io import atomic_write_bytes
from ..utils.cbor import CBORDecodeError, canonical_cbor_decode, canonical_cbor_encode
from ..utils.logging import get_logger

logger = get_logger("recovery.snapshot")

SNAPSHOT_MAGIC = b"KVTPUSNAP1\n"
SNAPSHOT_VERSION = 1
QUARANTINE_SUFFIX = ".quarantine"

_NAME_RE = re.compile(r"^index-(\d{8})\.snap$")


class SnapshotError(Exception):
    """Snapshot file malformed or failed verification."""


def encode_snapshot(doc: dict) -> bytes:
    """Serialize a snapshot document to the on-disk byte format."""
    body = canonical_cbor_encode(doc)
    crc = zlib.crc32(body) & 0xFFFFFFFF
    return SNAPSHOT_MAGIC + body + build_footer([crc])


def decode_snapshot(blob: bytes) -> dict:
    """Parse + verify one snapshot blob; raise :class:`SnapshotError`."""
    if not blob.startswith(SNAPSHOT_MAGIC):
        raise SnapshotError("bad magic (not a snapshot, or truncated head)")
    tail = footer_size(1)
    if len(blob) < len(SNAPSHOT_MAGIC) + tail:
        raise SnapshotError("truncated snapshot (shorter than magic + footer)")
    body = blob[len(SNAPSHOT_MAGIC):-tail]
    try:
        (want,) = parse_footer(blob[-tail:], 1)
    except IntegrityError as e:
        raise SnapshotError(f"bad checksum footer: {e}") from e
    got = zlib.crc32(body) & 0xFFFFFFFF
    if got != want:
        raise SnapshotError(f"body crc mismatch: footer={want:#010x} data={got:#010x}")
    try:
        doc = canonical_cbor_decode(body)
    except CBORDecodeError as e:
        raise SnapshotError(f"undecodable snapshot body: {e}") from e
    if not isinstance(doc, dict):
        raise SnapshotError(f"snapshot body is {type(doc).__name__}, expected map")
    return doc


class SnapshotStore:
    """Directory of versioned snapshots with keep-N retention."""

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = max(1, keep)
        self.quarantined = 0
        os.makedirs(directory, exist_ok=True)

    def _sequences(self) -> list[tuple[int, str]]:
        """(seq, filename) of every valid-named snapshot, newest first."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            m = _NAME_RE.match(name)
            if m:
                out.append((int(m.group(1)), name))
        out.sort(reverse=True)
        return out

    def save(self, doc: dict) -> str:
        """Durably write ``doc`` as the next snapshot; returns its path."""
        start = time.perf_counter()
        existing = self._sequences()
        seq = (existing[0][0] + 1) if existing else 1
        path = os.path.join(self.directory, f"index-{seq:08d}.snap")
        blob = encode_snapshot(doc)
        with tracer().span(
            "llm_d.kv_cache.recovery.snapshot.save", seq=seq, bytes=len(blob)
        ):
            atomic_write_bytes(path, blob)
        self.prune()
        seconds = time.perf_counter() - start
        try:
            from ..metrics.collector import record_snapshot

            record_snapshot("written", len(blob), seconds)
        except Exception:  # pragma: no cover - metrics must never break snapshots  # lint: allow-swallow
            pass
        flight_recorder().record(
            KIND_RECOVERY,
            {"op": "snapshot", "seq": seq, "bytes": len(blob), "seconds": seconds},
        )
        logger.info("wrote snapshot %s (%d bytes, %.3fs)", path, len(blob), seconds)
        return path

    def quarantine(self, path: str, reason: str) -> None:
        """Rename a corrupt snapshot out of the load path, keep for triage."""
        self.quarantined += 1
        try:
            os.replace(path, path + QUARANTINE_SUFFIX)
            logger.error("quarantined corrupt snapshot %s: %s", path, reason)
        except OSError as e:
            logger.warning("could not quarantine %s: %s", path, e)
        try:
            from ..metrics.collector import record_snapshot_quarantine

            record_snapshot_quarantine()
        except Exception:  # pragma: no cover  # lint: allow-swallow
            pass
        flight_recorder().record(
            KIND_RECOVERY, {"op": "quarantine", "path": path, "reason": reason}
        )

    def load_newest(self) -> Optional[tuple[dict, str]]:
        """Load the newest snapshot that verifies; quarantine ones that
        don't. Returns ``(doc, path)`` or ``None`` when nothing loads."""
        for _seq, name in self._sequences():
            path = os.path.join(self.directory, name)
            try:
                with open(path, "rb") as f:
                    blob = f.read()
                return decode_snapshot(blob), path
            except OSError as e:
                logger.warning("could not read snapshot %s: %s", path, e)
            except SnapshotError as e:
                self.quarantine(path, str(e))
        return None

    def prune(self) -> None:
        """Delete all but the newest ``keep`` snapshots."""
        for _seq, name in self._sequences()[self.keep:]:
            path = os.path.join(self.directory, name)
            try:
                os.unlink(path)
            except OSError as e:  # pragma: no cover - racing cleanup
                logger.debug("prune of %s failed: %s", path, e)
