"""Recovery subsystem configuration.

All knobs ride the usual camelCase/snake_case ``from_dict`` convention
(docs/configuration.md). ``snapshotDir`` is the master switch: empty
(the default) disables the whole subsystem — no snapshot timer, no
journal, no warmup gate — preserving the pre-recovery behavior exactly.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class RecoveryConfig:
    # Directory for snapshots + the event journal; "" disables recovery.
    snapshot_dir: str = ""
    # Periodic snapshot cadence; <= 0 means only shutdown/drain snapshots.
    snapshot_interval_s: float = 30.0
    # Newest snapshots retained (older ones pruned after each save).
    snapshot_keep: int = 3
    # Warm restart serves degraded scores until the index-staleness
    # estimate (events.pool.index_staleness_s) drops below this bound.
    warmup_staleness_bound_s: float = 5.0
    # Graceful drain must finish (intake stop + queue drain + offload
    # flush + final snapshot) within this budget; whatever is left undone
    # at the deadline is abandoned (crash-only: the periodic snapshot
    # still bounds the loss).
    drain_deadline_s: float = 10.0
    # Anti-entropy digest-exchange cadence; <= 0 disables the loop (it
    # also needs a digest source wired in, see recovery.reconcile).
    reconcile_interval_s: float = 0.0
    # Continuous divergence-audit cadence (recovery.reconcile.
    # DivergenceAuditor — digest compare without repair, feeding the
    # kvtpu_index_divergence_* families and the index_divergence SLI);
    # <= 0 keeps it manual. Shares the reconciler's digest source.
    divergence_audit_interval_s: float = 0.0
    # Fraction of pods each divergence-audit round checks (rotating
    # coverage); 1.0 audits every pod every round.
    divergence_audit_sample: float = 1.0
    # Journal fsync cadence in records (1 = every append; higher trades
    # the crash-loss window for ingest throughput).
    journal_sync_every: int = 64

    @property
    def enabled(self) -> bool:
        return bool(self.snapshot_dir)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "RecoveryConfig":
        if not d:
            return cls()
        return cls(
            snapshot_dir=d.get("snapshotDir", d.get("snapshot_dir", "")) or "",
            snapshot_interval_s=d.get(
                "snapshotIntervalS", d.get("snapshot_interval_s", 30.0)
            ),
            snapshot_keep=d.get("snapshotKeep", d.get("snapshot_keep", 3)) or 3,
            warmup_staleness_bound_s=d.get(
                "warmupStalenessBoundS", d.get("warmup_staleness_bound_s", 5.0)
            ),
            drain_deadline_s=d.get(
                "drainDeadlineS", d.get("drain_deadline_s", 10.0)
            ),
            reconcile_interval_s=d.get(
                "reconcileIntervalS", d.get("reconcile_interval_s", 0.0)
            ),
            divergence_audit_interval_s=d.get(
                "divergenceAuditIntervalS",
                d.get("divergence_audit_interval_s", 0.0)
            ),
            divergence_audit_sample=d.get(
                "divergenceAuditSample",
                d.get("divergence_audit_sample", 1.0)
            ),
            journal_sync_every=d.get(
                "journalSyncEvery", d.get("journal_sync_every", 64)
            ) or 64,
        )
