"""Graceful drain: stop intake, flush, snapshot — under a deadline.

SIGTERM (the orchestrator's shutdown signal) should leave the process's
durable state as close to truth as the deadline allows, in strictly
decreasing order of value:

1. **stop intake** — unsubscribe/stop the ZMQ feeds so the queues only
   shrink from here;
2. **drain the event pool** — process everything already queued so the
   final snapshot includes it;
3. **flush in-flight offload jobs** — completed transfers get reported
   (and their checksums land) instead of being abandoned;
4. **final snapshot** — persist the fully-drained index + watermarks.

Every step charges against one shared ``drainDeadlineS`` budget. A step
that exceeds the remaining budget is *abandoned* (its helper thread is
daemonized), the shortfall is recorded, and the next step gets whatever
is left — crash-only design means an unfinished drain is never worse
than the crash the periodic snapshot already protects against.
"""

from __future__ import annotations

import signal
import threading
import time
from typing import Callable, Optional, Sequence

from ..utils.lockdep import new_lock
from ..telemetry import flight_recorder, tracer
from ..telemetry.flight_recorder import KIND_DRAIN
from ..utils.logging import get_logger

logger = get_logger("recovery.drain")


class DrainCoordinator:
    """Runs the 4-step drain under a deadline; installable on SIGTERM."""

    def __init__(
        self,
        deadline_s: float = 10.0,
        intake_stoppers: Sequence[Callable[[], None]] = (),
        pool=None,
        offload=None,
        manager=None,
        on_complete: Optional[Callable[[], None]] = None,
    ):
        self.deadline_s = deadline_s
        self.intake_stoppers = list(intake_stoppers)
        self.pool = pool
        self.offload = offload
        self.manager = manager
        self.on_complete = on_complete
        self._mu = new_lock()
        self._drained = False
        self.last_report: Optional[dict] = None

    def _bounded(self, name: str, fn: Callable[[], None], remaining: float) -> bool:
        """Run ``fn`` but give up after ``remaining`` seconds; True if it
        finished inside the budget."""
        if remaining <= 0:
            logger.warning("drain step %s skipped: deadline exhausted", name)
            return False
        done = threading.Event()
        err: list = []

        def _run() -> None:
            try:
                fn()
            except Exception as e:
                err.append(e)
                logger.exception("drain step %s failed", name)
            finally:
                done.set()

        t = threading.Thread(target=_run, name=f"kvtpu-drain-{name}", daemon=True)
        t.start()
        finished = done.wait(remaining)
        if not finished:
            logger.warning(
                "drain step %s abandoned after %.2fs (deadline)", name, remaining
            )
        return finished and not err

    def drain(self) -> dict:
        """Execute the drain once (idempotent); returns a step report."""
        with self._mu:
            if self._drained:
                return self.last_report or {"completed": True, "steps": {}}
            self._drained = True
        start = time.monotonic()
        deadline = start + self.deadline_s
        steps: dict = {}
        if self.manager is not None:
            self.manager._transition("draining")
        with tracer().span("llm_d.kv_cache.recovery.drain", deadline_s=self.deadline_s):
            def _stop_intake() -> None:
                for stop in self.intake_stoppers:
                    stop()

            steps["stop_intake"] = self._bounded(
                "stop_intake", _stop_intake, deadline - time.monotonic()
            )
            if self.pool is not None:
                steps["drain_pool"] = self._bounded(
                    "drain_pool", self.pool.shutdown, deadline - time.monotonic()
                )
            if self.offload is not None:
                remaining = deadline - time.monotonic()
                steps["flush_offload"] = (
                    remaining > 0 and self.offload.flush(deadline_s=remaining)
                )
            if self.manager is not None:
                steps["final_snapshot"] = self._bounded(
                    "final_snapshot",
                    lambda: self.manager.stop(final_snapshot=True),
                    deadline - time.monotonic(),
                )
        seconds = time.monotonic() - start
        report = {
            "completed": all(steps.values()) if steps else True,
            "steps": steps,
            "seconds": round(seconds, 3),
            "deadline_s": self.deadline_s,
        }
        self.last_report = report
        logger.info("drain finished in %.2fs: %s", seconds, steps)
        flight_recorder().record(KIND_DRAIN, dict(report))
        try:
            from ..metrics.collector import record_drain

            record_drain(seconds)
        except Exception:  # pragma: no cover  # lint: allow-swallow
            pass
        if self.on_complete is not None:
            try:
                self.on_complete()
            except Exception:
                logger.exception("drain on_complete callback failed")
        return report

    def install(self, signals: Sequence[int] = (signal.SIGTERM,)) -> None:
        """Install signal handlers that run the drain off-thread (signal
        handlers must return quickly). Call from the main thread."""

        def _handler(signum, frame):  # pragma: no cover - signal path
            logger.info("signal %d received; starting graceful drain", signum)
            threading.Thread(
                target=self.drain, name="kvtpu-drain", daemon=True
            ).start()

        for sig in signals:
            signal.signal(sig, _handler)
