"""Anti-entropy reconciliation: digest exchange + incremental repair.

The event stream is lossy (ZMQ PUB/SUB drops under backpressure, the
bounded shard queues drop-oldest under overload, and a restart loses
whatever was published while the process was down). Snapshots + journal
replay bound the loss; this module closes the residual gap the way
Dynamo-style systems do — by periodically comparing a cheap *digest* of
each pod's indexed blocks against the pod's advertised truth and
repairing only the divergent pods, incrementally.

A pod digest is order-independent::

    digest(pod) = XOR over blocks of fnv1a_64(cbor([request_key, row]))

where ``row`` is the snapshot row ``[pod, tier, flags, group_idx]``.
XOR-of-hashes makes the digest insensitive to iteration order and O(1)
to compare; matching digests skip the pod entirely, so steady-state
rounds touch no index state.

The truth side is abstracted behind :class:`DigestSource` — in tests and
single-host deployments an :class:`IndexDigestSource` wraps a live
reference index; a cluster deployment implements the protocol over the
pods' advertised state (events ``reconciler``/``subscriber_manager``
discovery).
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Protocol

from ..core.keys import KeyType, PodEntry
from ..telemetry import flight_recorder, tracer
from ..telemetry.flight_recorder import KIND_AUDIT, KIND_RECOVERY
from ..utils.cbor import canonical_cbor_encode
from ..utils.logging import get_logger

logger = get_logger("recovery.reconcile")


def _fnv1a_64(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def _row_hash(request_key: int, row: list) -> int:
    return _fnv1a_64(canonical_cbor_encode([request_key, list(row)]))


def pod_blocks_from_state(state: Optional[dict], pod: str) -> dict:
    """``{request_key: {row_tuple, ...}}`` for one pod, from a
    ``dump_state()`` document."""
    out: dict = {}
    if not state:
        return out
    for request_key, rows in state.get("entries", []):
        mine = {tuple(r) for r in rows if r[0] == pod}
        if mine:
            out[request_key] = mine
    return out


def digest_from_blocks(blocks: dict) -> dict:
    """Order-independent ``{"count": n, "digest": x}`` over pod blocks."""
    digest = 0
    count = 0
    for request_key, rows in blocks.items():
        for row in rows:
            digest ^= _row_hash(request_key, list(row))
            count += 1
    return {"count": count, "digest": digest}


class DigestSource(Protocol):
    """A pod's advertised cache truth, digest-first."""

    def pods(self) -> list:
        """Pods this source can answer for."""

    def digest(self, pod: str) -> dict:
        """``{"count", "digest"}`` of the pod's advertised blocks."""

    def blocks(self, pod: str) -> dict:
        """Full ``{request_key: {row_tuple,...}}`` — only fetched when the
        digests already disagreed."""


class IndexDigestSource:
    """DigestSource over a live Index treated as ground truth (tests,
    in-process replicas)."""

    def __init__(self, index):
        self.index = index

    def _state(self) -> Optional[dict]:
        return self.index.dump_state()

    def pods(self) -> list:
        state = self._state()
        if not state:
            return []
        seen = set()
        for _rk, rows in state.get("entries", []):
            for row in rows:
                seen.add(row[0])
        return sorted(seen)

    def digest(self, pod: str) -> dict:
        return digest_from_blocks(pod_blocks_from_state(self._state(), pod))

    def blocks(self, pod: str) -> dict:
        return pod_blocks_from_state(self._state(), pod)


def _entry_from_row(row) -> PodEntry:
    pod, tier, flags, group_idx = row[0], row[1], int(row[2]), int(row[3])
    return PodEntry(
        pod_identifier=pod,
        device_tier=tier,
        speculative=bool(flags & 1),
        has_group=bool(flags & 2),
        group_idx=group_idx,
    )


class AntiEntropyReconciler:
    """Background digest exchange + repair loop.

    Modeled on :class:`~llmd_kv_cache_tpu.events.reconciler.PodReconciler`:
    an Event-stopped daemon thread running ``reconcile_once()`` every
    ``interval_s``. ``reconcile_once()`` is also callable directly
    (tests, admin-triggered repair).
    """

    def __init__(self, index, source: DigestSource, interval_s: float = 30.0):
        self.index = index
        self.source = source
        self.interval_s = interval_s
        self.runs = 0
        self.repaired_added = 0
        self.repaired_removed = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one round -------------------------------------------------------

    def _repair_pod(self, pod: str, local: dict, remote: dict) -> tuple[int, int]:
        """Make the local index's view of ``pod`` match ``remote``."""
        added = 0
        removed = 0
        for request_key, rows in remote.items():
            missing = rows - local.get(request_key, set())
            if missing:
                entries = [_entry_from_row(r) for r in sorted(missing)]
                self.index.add(None, [request_key], entries)
                added += len(entries)
        for request_key, rows in local.items():
            extra = rows - remote.get(request_key, set())
            if extra:
                entries = [_entry_from_row(r) for r in sorted(extra)]
                self.index.evict(request_key, KeyType.REQUEST, entries)
                removed += len(entries)
        return added, removed

    def reconcile_once(self) -> dict:
        """One digest-exchange round; returns its stats."""
        self.runs += 1
        added = 0
        removed = 0
        divergent: list = []
        with tracer().span("llm_d.kv_cache.recovery.reconcile") as span:
            state = self.index.dump_state()
            pods = set(self.source.pods())
            # Pods only we know about still need checking (the source may
            # have cleared them entirely).
            if state:
                for _rk, rows in state.get("entries", []):
                    for row in rows:
                        pods.add(row[0])
            for pod in sorted(pods):
                local_blocks = pod_blocks_from_state(state, pod)
                if digest_from_blocks(local_blocks) == self.source.digest(pod):
                    continue
                divergent.append(pod)
                a, r = self._repair_pod(pod, local_blocks, self.source.blocks(pod))
                added += a
                removed += r
            span.set_attribute("pods_checked", len(pods))
            span.set_attribute("divergent", len(divergent))
            span.set_attribute("repaired_added", added)
            span.set_attribute("repaired_removed", removed)
        self.repaired_added += added
        self.repaired_removed += removed
        stats = {
            "pods_checked": len(pods),
            "divergent": divergent,
            "repaired_added": added,
            "repaired_removed": removed,
        }
        if divergent:
            logger.info(
                "anti-entropy repaired %d pods (+%d/-%d entries): %s",
                len(divergent), added, removed, divergent,
            )
            flight_recorder().record(KIND_RECOVERY, {"op": "reconcile", **stats})
        try:
            from ..metrics.collector import record_reconcile

            record_reconcile(added, removed)
        except Exception:  # pragma: no cover - metrics must never break repair  # lint: allow-swallow
            pass
        return stats

    # -- background loop -------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def _loop() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.reconcile_once()
                except Exception:
                    logger.exception("anti-entropy round failed; continuing")

        self._thread = threading.Thread(
            target=_loop, name="kvtpu-anti-entropy", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


class DivergenceAuditor:
    """Always-on sampled divergence audit: digest compare, no repair.

    The reconciler above *fixes* divergence but only tells you about it
    after the fact (flight record + repair counters); by then the SLI
    question — "how long was routing running on a wrong view, and on
    which pod?" — is unanswerable. This auditor runs the same XOR-digest
    comparison continuously WITHOUT repairing, so divergence is a
    measured condition rather than a repair side effect:

    - per pod: **phantom** blocks (the index advertises them, the
      engine's truth lacks them — scores overshoot) and **ghost** blocks
      (the engine holds them unindexed — scores undershoot), exported as
      ``kvtpu_index_divergence_*`` gauges;
    - checked/divergent counters per round, the feed for the
      ``index_divergence`` SLI burn windows in the fleet collector;
    - a divergence-age histogram observed when an episode heals (repair
      or natural event-stream convergence), plus a :data:`KIND_AUDIT`
      flight record at each divergence onset and heal.

    ``sample`` audits only that fraction of pods per round (rotating, so
    every pod is still covered within ``1/sample`` rounds) — the digest
    is cheap but ``dump_state()`` on a huge index is not free. Repair
    stays the reconciler's job; deployments typically run both off the
    same :class:`DigestSource`.
    """

    def __init__(self, index, source: DigestSource, interval_s: float = 10.0,
                 sample: float = 1.0, clock=time.time):
        self.index = index
        self.source = source
        self.interval_s = interval_s
        self.sample = min(max(sample, 0.0), 1.0) or 1.0
        self.rounds = 0
        self._clock = clock
        self._cursor = 0
        # pod -> episode-start ts, for the divergence-age histogram.
        self._since: dict[str, float] = {}
        # pod -> {"phantom": n, "ghost": n} as of its last audited round.
        self._last: dict[str, dict] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _pods_this_round(self, pods: list) -> list:
        if not pods or self.sample >= 1.0:
            return pods
        n = max(1, int(len(pods) * self.sample))
        start = self._cursor % len(pods)
        self._cursor = (start + n) % len(pods)
        return [pods[(start + i) % len(pods)] for i in range(n)]

    def audit_once(self) -> dict:
        """One audit round; returns its stats (and never mutates the index)."""
        self.rounds += 1
        now = self._clock()
        divergent: dict[str, dict] = {}
        with tracer().span("llm_d.kv_cache.recovery.divergence_audit") as span:
            state = self.index.dump_state()
            pods = set(self.source.pods())
            if state:
                for _rk, rows in state.get("entries", []):
                    for row in rows:
                        pods.add(row[0])
            audited = self._pods_this_round(sorted(pods))
            for pod in audited:
                local = pod_blocks_from_state(state, pod)
                phantom = 0
                ghost = 0
                if digest_from_blocks(local) != self.source.digest(pod):
                    remote = self.source.blocks(pod)
                    for rk, rows in local.items():
                        phantom += len(rows - remote.get(rk, set()))
                    for rk, rows in remote.items():
                        ghost += len(rows - local.get(rk, set()))
                is_div = bool(phantom or ghost)
                if is_div:
                    divergent[pod] = {"phantom": phantom, "ghost": ghost}
                    if pod not in self._since:
                        self._since[pod] = now
                        flight_recorder().record(KIND_AUDIT, {
                            "op": "divergence_onset", "pod": pod,
                            "phantom": phantom, "ghost": ghost,
                        })
                elif pod in self._since:
                    age = max(now - self._since.pop(pod), 0.0)
                    flight_recorder().record(KIND_AUDIT, {
                        "op": "divergence_healed", "pod": pod,
                        "age_s": age,
                    })
                    try:
                        from ..metrics.collector import record_divergence_healed

                        record_divergence_healed(age)
                    except Exception:  # pragma: no cover - metrics never break the audit  # lint: allow-swallow
                        pass
                self._last[pod] = {"phantom": phantom, "ghost": ghost}
                try:
                    from ..metrics.collector import record_divergence_audit

                    record_divergence_audit(pod, is_div, phantom, ghost)
                except Exception:  # pragma: no cover - metrics never break the audit  # lint: allow-swallow
                    pass
            span.set_attribute("pods_checked", len(audited))
            span.set_attribute("divergent", len(divergent))
        if divergent:
            logger.info("divergence audit: %d pod(s) divergent: %s",
                        len(divergent), sorted(divergent))
        return {
            "pods_checked": len(audited),
            "divergent": divergent,
        }

    def debug_view(self) -> dict:
        """JSON-able state for ``/debug/vars`` / kvdiag."""
        now = self._clock()
        return {
            "rounds": self.rounds,
            "interval_s": self.interval_s,
            "sample": self.sample,
            "divergent_now": {
                pod: {**self._last.get(pod, {}),
                      "age_s": round(max(now - since, 0.0), 3)}
                for pod, since in self._since.items()
            },
        }

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def _loop() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.audit_once()
                except Exception:
                    logger.exception("divergence audit round failed; continuing")

        self._thread = threading.Thread(
            target=_loop, name="kvtpu-divergence-audit", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
