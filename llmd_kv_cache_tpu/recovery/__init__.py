"""Crash-tolerant state: snapshots, journaled warm restart, anti-entropy
reconciliation, and graceful drain (docs/resilience.md, "Crash recovery
& drain")."""

from .config import RecoveryConfig
from .drain import DrainCoordinator
from .journal import EventJournal, JournalRecord
from .manager import (
    RecoveryManager,
    STATE_COLD,
    STATE_DRAINING,
    STATE_LOADING,
    STATE_READY,
    STATE_REPLAYING,
    STATE_STOPPED,
    STATE_WARMING,
)
from .reconcile import (
    AntiEntropyReconciler,
    DigestSource,
    IndexDigestSource,
    digest_from_blocks,
    pod_blocks_from_state,
)
from .snapshot import SnapshotError, SnapshotStore, decode_snapshot, encode_snapshot

__all__ = [
    "AntiEntropyReconciler",
    "DigestSource",
    "DrainCoordinator",
    "EventJournal",
    "IndexDigestSource",
    "JournalRecord",
    "RecoveryConfig",
    "RecoveryManager",
    "SnapshotError",
    "SnapshotStore",
    "STATE_COLD",
    "STATE_DRAINING",
    "STATE_LOADING",
    "STATE_READY",
    "STATE_REPLAYING",
    "STATE_STOPPED",
    "STATE_WARMING",
    "decode_snapshot",
    "digest_from_blocks",
    "encode_snapshot",
    "pod_blocks_from_state",
]
