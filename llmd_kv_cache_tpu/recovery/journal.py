"""Append-only, checksummed per-pod event-sequence journal.

The journal sits between snapshots: every raw event message the pool
parses is appended here, and a warm restart replays the records whose
per-pod sequence is newer than the snapshot's ``pod_seqs`` watermark.
Snapshot + journal-suffix therefore reconstructs the index to within the
ZMQ messages lost while the process was down (which anti-entropy then
repairs).

Record framing (all little-endian)::

    +-----------+-----------+------------------------------+
    | u32 length| u32 crc32 | canonical CBOR               |
    | (of body) | (of body) | [pod_id, seq, topic, payload,|
    |           |           |  event_ts]                   |
    +-----------+-----------+------------------------------+

Appends are flushed per record and fsync'd every ``sync_every`` records,
so a crash loses at most ``sync_every`` events past the last sync — and
those are exactly what anti-entropy exists for. A torn tail (partial
record from a crash mid-append) is tolerated: replay stops at the first
record that fails length/CRC/decode checks, logging how many bytes were
abandoned.

Rotation (``rotate()``) happens after each successful snapshot: the
snapshot's watermark supersedes the journal prefix, so the file restarts
empty (published atomically, never truncated in place).
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator, Optional

from ..utils.lockdep import new_lock
from ..utils.atomic_io import atomic_write_bytes, fsync_dir
from ..utils.cbor import CBORDecodeError, canonical_cbor_decode, canonical_cbor_encode
from ..utils.logging import get_logger

logger = get_logger("recovery.journal")

_HEADER = struct.Struct("<II")  # body length, body crc32


class JournalRecord:
    """One replayable event message."""

    __slots__ = ("pod_id", "sequence", "topic", "payload", "event_ts")

    def __init__(self, pod_id: str, sequence: int, topic: str, payload: bytes,
                 event_ts: float):
        self.pod_id = pod_id
        self.sequence = sequence
        self.topic = topic
        self.payload = payload
        self.event_ts = event_ts


class EventJournal:
    """Crash-tolerant append log of raw event messages."""

    def __init__(self, path: str, sync_every: int = 64):
        self.path = path
        self.sync_every = max(1, sync_every)
        self._mu = new_lock()
        self._f = None
        self._since_sync = 0
        self.appended = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def _file(self):
        if self._f is None:
            self._f = open(self.path, "ab")
        return self._f

    def append(self, pod_id: str, sequence: int, topic: str, payload: bytes,
               event_ts: float) -> None:
        """Append one record (thread-safe); flushes every call, fsyncs
        every ``sync_every`` records."""
        body = canonical_cbor_encode(
            [pod_id, sequence, topic, bytes(payload), float(event_ts)]
        )
        rec = _HEADER.pack(len(body), zlib.crc32(body) & 0xFFFFFFFF) + body
        with self._mu:
            f = self._file()
            f.write(rec)
            f.flush()
            self.appended += 1
            self._since_sync += 1
            if self._since_sync >= self.sync_every:
                os.fsync(f.fileno())  # lint: allow-blocking (durability point: _since_sync must match on-disk state, so fsync stays under _mu; bounded by sync_every)
                self._since_sync = 0

    def sync(self) -> None:
        """Force an fsync of any unsynced appends."""
        with self._mu:
            if self._f is not None and self._since_sync:
                self._f.flush()
                os.fsync(self._f.fileno())  # lint: allow-blocking (explicit durability barrier; callers opt into the wait)
                self._since_sync = 0

    def rotate(self) -> None:
        """Restart the journal empty (after a snapshot superseded it).

        The empty file is published atomically so a crash mid-rotate
        leaves either the old journal (extra idempotent replays) or the
        new empty one — never a half-truncated file.
        """
        with self._mu:
            if self._f is not None:
                self._f.close()
                self._f = None
            atomic_write_bytes(self.path, b"")
            self._since_sync = 0

    def close(self) -> None:
        with self._mu:
            if self._f is not None:
                if self._since_sync:
                    self._f.flush()
                    os.fsync(self._f.fileno())  # lint: allow-blocking (final durability barrier on close; no concurrent appends after this)
                    self._since_sync = 0
                self._f.close()
                self._f = None
            fsync_dir(os.path.dirname(self.path) or ".")

    def replay(self, min_seqs: Optional[dict] = None) -> Iterator[JournalRecord]:
        """Yield records with ``sequence > min_seqs[pod_id]`` (all pods
        absent from ``min_seqs`` replay in full). Stops cleanly at a torn
        tail."""
        min_seqs = min_seqs or {}
        try:
            with open(self.path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return
        pos = 0
        while pos + _HEADER.size <= len(data):
            length, want_crc = _HEADER.unpack_from(data, pos)
            body_start = pos + _HEADER.size
            body_end = body_start + length
            if body_end > len(data):
                logger.warning(
                    "journal %s: torn tail at offset %d (%d bytes abandoned)",
                    self.path, pos, len(data) - pos,
                )
                return
            body = data[body_start:body_end]
            if (zlib.crc32(body) & 0xFFFFFFFF) != want_crc:
                logger.warning(
                    "journal %s: crc mismatch at offset %d; stopping replay "
                    "(%d bytes abandoned)", self.path, pos, len(data) - pos,
                )
                return
            try:
                item = canonical_cbor_decode(body)
                pod_id, sequence, topic, payload, event_ts = item
            except (CBORDecodeError, ValueError, TypeError):
                logger.warning(
                    "journal %s: undecodable record at offset %d; stopping",
                    self.path, pos,
                )
                return
            pos = body_end
            if sequence > min_seqs.get(pod_id, -1):
                yield JournalRecord(pod_id, sequence, topic, payload, event_ts)
