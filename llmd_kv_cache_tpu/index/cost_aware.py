"""Cost-aware (byte-budget) in-memory index backend.

Counterpart of reference ``pkg/kvcache/kvblock/cost_aware_memory.go`` (which
builds on ristretto). Rather than bounding the number of keys, the backend
bounds the approximate resident byte size of the index, evicting
least-recently-used request keys when over budget. This implementation uses
a strict LRU with exact cost bookkeeping instead of ristretto's sampled
admission/eviction — simpler, deterministic, and sufficient since the hot
path is dict-speed either way.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from ..utils.lockdep import new_lock
from ..core.keys import BlockHash, KeyType, PodEntry
from ..utils.humanize import parse_bytes
from ..utils.logging import get_logger
from ..utils.lru import LRUCache
from .base import Index, infer_engine_mappings

logger = get_logger("index.cost_aware")

DEFAULT_MAX_COST = "2GiB"  # reference cost_aware_memory.go:47-51

# Approximate per-object overheads used for cost accounting, mirroring the
# role of CostPodCache.CalculateByteSize (cost_aware_memory.go:191).
_KEY_COST = 8 + 48  # uint64 key + map slot overhead
_ENTRY_BASE_COST = 64

# Tier-latency discount hook (ROADMAP item 4 down payment): restore
# latency is folded as an EMA per tier, and ``tier_discount`` maps it to a
# multiplicative factor in (0, 1] — 1.0 for an unobserved/fast tier,
# approaching 0 as observed restore latency dwarfs the baseline. Consumed
# only by residency-aware scoring (scoring.residency wires it through
# Indexer.attach_residency); the base prefix scores never see it.
_TIER_LATENCY_ALPHA = 0.2
_TIER_DISCOUNT_BASELINE_S = 0.05


def _entry_cost(entry: PodEntry) -> int:
    return _ENTRY_BASE_COST + len(entry.pod_identifier) + len(entry.device_tier)


@dataclass
class CostAwareMemoryIndexConfig:
    max_cost: str | int = DEFAULT_MAX_COST
    # Engine→request mappings are kept in a bounded LRU sized by entry count;
    # each mapping is tiny (two uint64s), so a count bound suffices.
    mapping_size: int = 2_000_000

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "CostAwareMemoryIndexConfig":
        if not d:
            return cls()
        return cls(
            max_cost=d.get("maxCost", d.get("max_cost", DEFAULT_MAX_COST)) or DEFAULT_MAX_COST,
            mapping_size=d.get("mappingSize", d.get("mapping_size", 2_000_000)) or 2_000_000,
        )


class _CostPodCache:
    __slots__ = ("entries", "mu", "cost")

    def __init__(self) -> None:
        self.entries: dict[PodEntry, None] = {}
        self.mu = new_lock()
        self.cost = _KEY_COST


class CostAwareMemoryIndex(Index):
    """Byte-budgeted LRU index."""

    def __init__(self, cfg: Optional[CostAwareMemoryIndexConfig] = None):
        cfg = cfg or CostAwareMemoryIndexConfig()
        self._max_cost = parse_bytes(cfg.max_cost)
        if self._max_cost <= 0:
            raise ValueError(f"max_cost must be positive, got {cfg.max_cost!r}")
        # Outer map with LRU ordering; capacity is effectively unbounded by
        # count — the byte budget drives eviction.
        self._data: LRUCache[BlockHash, _CostPodCache] = LRUCache(2**62)
        self._engine_to_request: LRUCache[BlockHash, list[BlockHash]] = LRUCache(cfg.mapping_size)
        self._total_cost = 0
        self._mu = new_lock()
        # Tier restore-latency EMAs feeding ``tier_discount`` (see module
        # header); observed by whoever times restores against the tier
        # (the engine's deferred-restore path via on_restore_latency).
        self._tier_latency_ema: dict[str, float] = {}

    @property
    def total_cost(self) -> int:
        return self._total_cost

    def observe_tier_latency(self, tier: str, seconds: float) -> None:
        """Fold one restore-latency observation into the tier's EMA."""
        seconds = max(float(seconds), 0.0)
        with self._mu:
            prev = self._tier_latency_ema.get(tier)
            self._tier_latency_ema[tier] = (
                seconds if prev is None
                else prev + _TIER_LATENCY_ALPHA * (seconds - prev)
            )

    def tier_discount(self, tier: str) -> float:
        """Restore-latency discount for ``tier`` in (0, 1].

        ``baseline / (baseline + ema)``: 1.0 when the tier has never been
        observed, ~0.5 at the baseline latency, and decaying toward 0 for
        tiers whose restores are slow enough that recomputing locally
        starts to win. Applied only when residency scoring is on.
        """
        with self._mu:
            ema = self._tier_latency_ema.get(tier)
        if ema is None:
            return 1.0
        return _TIER_DISCOUNT_BASELINE_S / (_TIER_DISCOUNT_BASELINE_S + ema)

    def lookup(
        self,
        request_keys: Sequence[BlockHash],
        pod_identifier_set: Optional[set[str]] = None,
    ) -> dict[BlockHash, list[PodEntry]]:
        if not request_keys:
            raise ValueError("no request_keys provided for lookup")

        pods_per_key: dict[BlockHash, list[PodEntry]] = {}
        filter_pods = bool(pod_identifier_set)

        for key in request_keys:
            pod_cache = self._data.get(key)
            if pod_cache is None:
                continue
            with pod_cache.mu:
                entries = list(pod_cache.entries.keys())
            if not entries:
                return pods_per_key  # chain broken at a known key
            if filter_pods:
                filtered = [e for e in entries if e.pod_identifier in pod_identifier_set]
                if filtered:
                    pods_per_key[key] = filtered
            else:
                pods_per_key[key] = entries
        return pods_per_key

    def add(
        self,
        engine_keys: Optional[Sequence[BlockHash]],
        request_keys: Sequence[BlockHash],
        entries: Sequence[PodEntry],
    ) -> None:
        if not request_keys or not entries:
            raise ValueError("no keys or entries provided for adding to index")

        if engine_keys is not None:
            for ek, rks in infer_engine_mappings(engine_keys, request_keys).items():
                self._engine_to_request.add(ek, rks)

        with self._mu:
            for key in request_keys:
                pod_cache, _ = self._data.get_or_create(key, _CostPodCache)
                with pod_cache.mu:
                    if pod_cache.cost == _KEY_COST and not pod_cache.entries:
                        self._total_cost += _KEY_COST  # newly admitted key
                    for entry in entries:
                        if entry not in pod_cache.entries:
                            delta = _entry_cost(entry)
                            pod_cache.entries[entry] = None
                            pod_cache.cost += delta
                            self._total_cost += delta
            self._evict_over_budget_locked()

    def _evict_over_budget_locked(self) -> None:
        """Evict least-recently-used keys until under the byte budget."""
        while self._total_cost > self._max_cost:
            keys = self._data.keys()  # oldest first
            if not keys:
                break
            victim = keys[0]
            pod_cache = self._data.peek(victim)
            self._data.remove(victim)
            if pod_cache is not None:
                with pod_cache.mu:
                    self._total_cost -= pod_cache.cost
                    pod_cache.entries.clear()
                    pod_cache.cost = 0

    def evict(
        self,
        key: BlockHash,
        key_type: KeyType,
        entries: Sequence[PodEntry],
    ) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction from index")

        if key_type is KeyType.ENGINE:
            rks = self._engine_to_request.get(key)
            if rks is None:
                return
            for rk in rks:
                self._evict_pods_from_request_key(rk, entries)
            with self._mu:
                all_empty = all(
                    (pc := self._data.get(rk)) is None or not pc.entries for rk in rks
                )
                if all_empty:
                    self._engine_to_request.remove(key)
        elif key_type is KeyType.REQUEST:
            self._evict_pods_from_request_key(key, entries)
        else:  # pragma: no cover
            raise ValueError(f"unknown key type: {key_type}")

    def _evict_pods_from_request_key(
        self, request_key: BlockHash, entries: Sequence[PodEntry]
    ) -> None:
        with self._mu:
            # Re-fetch under the global lock: a concurrent over-budget
            # eviction + re-add may have replaced the cache object, and
            # removing via a stale reference would delete the new entries
            # and leak their accounted cost (cf. in_memory.go:300-312).
            pod_cache = self._data.get(request_key)
            if pod_cache is None:
                return
            with pod_cache.mu:
                for entry in entries:
                    if entry in pod_cache.entries:
                        delta = _entry_cost(entry)
                        del pod_cache.entries[entry]
                        pod_cache.cost -= delta
                        self._total_cost -= delta
                if not pod_cache.entries:
                    if self._data.remove(request_key):
                        self._total_cost -= pod_cache.cost
                        pod_cache.cost = 0

    def get_request_key(self, engine_key: BlockHash) -> Optional[BlockHash]:
        rks = self._engine_to_request.get(engine_key)
        if not rks:
            return None
        return rks[-1]

    def clear(self, pod_identifier: str) -> None:
        for request_key in self._data.keys():
            pod_cache = self._data.peek(request_key)
            if pod_cache is None:
                continue
            with pod_cache.mu:
                matched = [
                    e for e in pod_cache.entries if e.pod_identifier == pod_identifier
                ]
            if matched:
                self._evict_pods_from_request_key(request_key, matched)

    def __len__(self) -> int:
        return len(self._data)
