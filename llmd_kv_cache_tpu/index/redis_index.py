"""Redis/Valkey index backend.

Counterpart of reference ``pkg/kvcache/kvblock/redis.go``, sharing its data
layout so deployments can migrate between the Go and TPU indexers without a
flush:

- request key ``<hash>``: a Redis hash whose *field names* are JSON-encoded
  pod entries (values unused) — lookup is a single pipelined ``HKEYS`` per
  key (one RTT for the whole prefix chain, ``redis.go:190-199``)
- engine key ``engine:<hash>``: a sorted set of request-key strings scored
  by chain index; ``get_request_key`` returns the highest-scored member

The client is injectable for tests (the reference uses miniredis; we use an
in-process fake implementing the handful of commands exercised). The real
client requires the optional ``redis`` package.

Valkey is wire-compatible; ``backend_type="valkey"`` only changes address
defaulting (RDMA transport is a server-side concern, ``redis.go:98-107``).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Optional, Sequence

from ..core.keys import BlockHash, KeyType, PodEntry
from ..resilience.failpoints import failpoints
from ..utils.logging import get_logger
from .base import Index, infer_engine_mappings

logger = get_logger("index.redis")

# Failpoint guarding every Redis round-trip; armed by chaos tests to
# simulate a down/flapping server (see docs/resilience.md).
FP_REDIS_OP = "index.redis.op"


@dataclass
class RedisIndexConfig:
    address: str = "redis://127.0.0.1:6379"
    backend_type: str = "redis"  # or "valkey"
    enable_rdma: bool = False

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "RedisIndexConfig":
        if not d:
            return cls()
        return cls(
            address=d.get("address", "redis://127.0.0.1:6379"),
            backend_type=d.get("backendType", d.get("backend_type", "redis")),
            enable_rdma=d.get("enableRDMA", d.get("enable_rdma", False)),
        )


def _encode_pod_field(entry: PodEntry) -> str:
    # Stable JSON field encoding; key order fixed for field equality.
    return json.dumps(
        {
            "PodIdentifier": entry.pod_identifier,
            "DeviceTier": entry.device_tier,
            "Speculative": entry.speculative,
            "HasGroup": entry.has_group,
            "GroupIdx": entry.group_idx,
        },
        separators=(",", ":"),
    )


def _decode_pod_field(field: str | bytes) -> Optional[PodEntry]:
    if isinstance(field, bytes):
        field = field.decode("utf-8")
    try:
        d = json.loads(field)
        return PodEntry(
            pod_identifier=d["PodIdentifier"],
            device_tier=d["DeviceTier"],
            speculative=d.get("Speculative", False),
            has_group=d.get("HasGroup", False),
            group_idx=d.get("GroupIdx", 0),
        )
    except (json.JSONDecodeError, KeyError, TypeError):
        return None


def _engine_redis_key(engine_key: BlockHash) -> str:
    return f"engine:{engine_key}"


# Atomic prunes, mirroring the reference's server-side scripts
# (``redis.go:148-169``): deleting an empty hash / an engine mapping whose
# request hashes are all empty must be atomic with the emptiness check, or
# a concurrent Add between check and delete loses its entry.
PRUNE_REQUEST_KEY_SCRIPT = """
if redis.call('HLEN', KEYS[1]) == 0 then
  redis.call('DEL', KEYS[1])
  return 1
end
return 0
"""

PRUNE_ENGINE_KEY_SCRIPT = """
local rks = redis.call('ZRANGE', KEYS[1], 0, -1)
for i = 1, #rks do
  if redis.call('HLEN', rks[i]) > 0 then
    return 0
  end
end
redis.call('DEL', KEYS[1])
return 1
"""


class RedisIndex(Index):
    """Redis/Valkey-backed index."""

    def __init__(
        self,
        cfg: Optional[RedisIndexConfig | dict] = None,
        client=None,
    ):
        if isinstance(cfg, dict):
            cfg = RedisIndexConfig.from_dict(cfg)
        cfg = cfg or RedisIndexConfig()
        self._cfg = cfg
        if client is not None:
            self._client = client
        else:
            try:
                import redis as _redis  # optional dependency
            except ImportError as e:  # pragma: no cover
                raise RuntimeError(
                    "RedisIndex requires the 'redis' package (not installed); "
                    "pass an explicit client or use another backend"
                ) from e
            address = cfg.address
            if address.startswith("valkey://"):
                address = "redis://" + address[len("valkey://"):]
            elif "://" not in address:
                address = "redis://" + address
            self._client = _redis.Redis.from_url(address)
        # Atomic prunes need server-side scripting (registered once,
        # EVALSHA per call when the client supports it); clients without
        # scripting degrade to check-then-delete — a racing Add re-creates
        # state on the next event, which the soft-state model tolerates.
        self._prune_req = self._make_script(PRUNE_REQUEST_KEY_SCRIPT)
        self._prune_eng = self._make_script(PRUNE_ENGINE_KEY_SCRIPT)
        self._scripting = self._prune_req is not None

    def _make_script(self, text: str):
        reg = getattr(self._client, "register_script", None)
        if reg is not None:
            script = reg(text)
            return lambda keys: script(keys=keys)
        ev = getattr(self._client, "eval", None)
        if ev is not None:
            return lambda keys: ev(text, len(keys), *keys)
        return None

    def _prune_request_key(self, request_key: str) -> None:
        if self._scripting:
            self._prune_req([request_key])
        elif self._client.hlen(request_key) == 0:
            self._client.delete(request_key)

    def _prune_engine_key(self, engine_key: BlockHash,
                          rks: Sequence[str]) -> None:
        # The script re-reads the request-key set from the engine zset
        # server-side: a client-side snapshot would miss request keys a
        # concurrent Add registers between snapshot and delete.
        if self._scripting:
            self._prune_eng([_engine_redis_key(engine_key)])
        elif all(self._client.hlen(rk) == 0 for rk in rks):
            self._client.delete(_engine_redis_key(engine_key))

    def lookup(
        self,
        request_keys: Sequence[BlockHash],
        pod_identifier_set: Optional[set[str]] = None,
    ) -> dict[BlockHash, list[PodEntry]]:
        if not request_keys:
            raise ValueError("no request_keys provided for lookup")
        failpoints.hit(FP_REDIS_OP)

        pipe = self._client.pipeline()
        for key in request_keys:
            pipe.hkeys(str(key))
        results = pipe.execute()

        pods_per_key: dict[BlockHash, list[PodEntry]] = {}
        filter_pods = bool(pod_identifier_set)
        for key, fields in zip(request_keys, results):
            if not fields:
                # Redis cannot distinguish "absent" from "known but empty":
                # a missing hash has no fields either way, so any gap breaks
                # the chain (mirrors redis.go:216,231-232 early stops).
                return pods_per_key
            entries = [e for f in fields if (e := _decode_pod_field(f)) is not None]
            if filter_pods:
                entries = [e for e in entries if e.pod_identifier in pod_identifier_set]
            if entries:
                pods_per_key[key] = entries
        return pods_per_key

    def add(
        self,
        engine_keys: Optional[Sequence[BlockHash]],
        request_keys: Sequence[BlockHash],
        entries: Sequence[PodEntry],
    ) -> None:
        if not request_keys or not entries:
            raise ValueError("no keys or entries provided for adding to index")
        failpoints.hit(FP_REDIS_OP)

        pipe = self._client.pipeline()
        if engine_keys is not None:
            for ek, rks in infer_engine_mappings(engine_keys, request_keys).items():
                for i, rk in enumerate(rks):
                    pipe.zadd(_engine_redis_key(ek), {str(rk): float(i)})
        for rk in request_keys:
            for entry in entries:
                pipe.hset(str(rk), _encode_pod_field(entry), "")
        pipe.execute()

    def evict(
        self,
        key: BlockHash,
        key_type: KeyType,
        entries: Sequence[PodEntry],
    ) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction from index")
        failpoints.hit(FP_REDIS_OP)

        if key_type is KeyType.ENGINE:
            rks = self._get_request_keys(key)
            if not rks:
                return
            for rk in rks:
                self._evict_pods_from_request_key(rk, entries)
            # Prune the engine mapping only if every mapped request hash is
            # empty — atomically (server-side script), so a concurrent Add
            # between the emptiness check and the delete cannot be lost.
            self._prune_engine_key(key, rks)
        elif key_type is KeyType.REQUEST:
            self._evict_pods_from_request_key(str(key), entries)
        else:  # pragma: no cover
            raise ValueError(f"unknown key type: {key_type}")

    def evict_batch(
        self,
        keys: Sequence[BlockHash],
        key_type: KeyType,
        entries: Sequence[PodEntry],
    ) -> None:
        """Evict many keys with pipelined round-trips.

        A BlockRemoved digest of N engine keys costs two pipelines (resolve
        + delete) instead of 2N sequential ones; end state is identical to
        looping ``evict`` (the prune scripts only check emptiness).
        """
        if not entries:
            raise ValueError("no entries provided for eviction from index")
        if not keys:
            return
        failpoints.hit(FP_REDIS_OP)
        fields = [_encode_pod_field(e) for e in entries]

        if key_type is KeyType.REQUEST:
            pipe = self._client.pipeline()
            for key in keys:
                for f in fields:
                    pipe.hdel(str(key), f)
            pipe.execute()
            for key in keys:
                self._prune_request_key(str(key))
            return
        if key_type is not KeyType.ENGINE:  # pragma: no cover
            raise ValueError(f"unknown key type: {key_type}")

        pipe = self._client.pipeline()
        for key in keys:
            pipe.zrange(_engine_redis_key(key), 0, -1)
        resolved = pipe.execute()

        per_key_rks: list[list[str]] = []
        pipe = self._client.pipeline()
        n_deletes = 0
        for vals in resolved:
            rks = [v.decode("utf-8") if isinstance(v, bytes) else v for v in vals]
            per_key_rks.append(rks)
            for rk in rks:
                for f in fields:
                    pipe.hdel(rk, f)
                    n_deletes += 1
        if n_deletes:
            pipe.execute()
        for key, rks in zip(keys, per_key_rks):
            if not rks:
                continue
            for rk in rks:
                self._prune_request_key(rk)
            self._prune_engine_key(key, rks)

    def _evict_pods_from_request_key(
        self, request_key: str, entries: Sequence[PodEntry]
    ) -> None:
        pipe = self._client.pipeline()
        for entry in entries:
            pipe.hdel(request_key, _encode_pod_field(entry))
        pipe.execute()
        self._prune_request_key(request_key)

    def _get_request_keys(self, engine_key: BlockHash) -> list[str]:
        vals = self._client.zrange(_engine_redis_key(engine_key), 0, -1)
        return [v.decode("utf-8") if isinstance(v, bytes) else v for v in vals]

    def get_request_key(self, engine_key: BlockHash) -> Optional[BlockHash]:
        failpoints.hit(FP_REDIS_OP)
        rks = self._get_request_keys(engine_key)
        if not rks:
            return None
        return int(rks[-1])

    def clear(self, pod_identifier: str) -> None:
        failpoints.hit(FP_REDIS_OP)
        # SCAN in batches; fields are JSON pod entries, so match by decoding
        # and comparing PodIdentifier — catches every tier/group/speculative
        # variant (redis.go:411-445).
        cursor = 0
        while True:
            cursor, keys = self._client.scan(cursor=cursor, count=512)
            for key in keys:
                key_str = key.decode("utf-8") if isinstance(key, bytes) else key
                if key_str.startswith("engine:"):
                    continue
                fields = self._client.hkeys(key_str)
                stale = [
                    f
                    for f in fields
                    if (e := _decode_pod_field(f)) is not None
                    and e.pod_identifier == pod_identifier
                ]
                if stale:
                    self._client.hdel(key_str, *stale)
                    self._prune_request_key(key_str)
            if cursor == 0:
                break
