"""KV-block index backends.

Counterpart of the reference's index layer (``pkg/kvcache/kvblock/``):
a thread-safe store mapping request block keys → pod localities, with a
dual key space (engine keys vs indexer-computed request keys).
"""

from .base import Index, IndexConfig, create_index
from .in_memory import InMemoryIndex, InMemoryIndexConfig
from .cost_aware import CostAwareMemoryIndex, CostAwareMemoryIndexConfig
from .instrumented import InstrumentedIndex, TracedIndex

__all__ = [
    "Index",
    "IndexConfig",
    "create_index",
    "InMemoryIndex",
    "InMemoryIndexConfig",
    "CostAwareMemoryIndex",
    "CostAwareMemoryIndexConfig",
    "InstrumentedIndex",
    "TracedIndex",
]
