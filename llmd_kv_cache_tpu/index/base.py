"""Index contract and backend selection.

Counterpart of reference ``pkg/kvcache/kvblock/index.go``. The index is
LRU-bounded soft state that converges from the KV-event stream; it tracks,
for each request key (content-addressed block hash), which pods hold the
block and on which device tier.

Dual key space (``index.go:108-155``): *request keys* are computed by the
indexer from tokens at the canonical block size; *engine keys* are whatever
hashes the engine itself emits. ``add`` learns the engine→request mapping
from the length ratio of the two key lists (both derive from the same token
count, so they divide evenly): 1:1, many:1 or 1:many.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Optional, Sequence

from ..core.keys import BlockHash, KeyType, PodEntry


class Index(abc.ABC):
    """Thread-safe KV-block index backend contract."""

    @abc.abstractmethod
    def lookup(
        self,
        request_keys: Sequence[BlockHash],
        pod_identifier_set: Optional[set[str]] = None,
    ) -> dict[BlockHash, list[PodEntry]]:
        """Return pods per request key, filtered to ``pod_identifier_set``.

        An empty/None pod set returns all pods. A key present in the index
        with an empty pod set terminates the scan early (prefix chain broken
        at a once-known block); a key simply absent does not.
        """

    @abc.abstractmethod
    def add(
        self,
        engine_keys: Optional[Sequence[BlockHash]],
        request_keys: Sequence[BlockHash],
        entries: Sequence[PodEntry],
    ) -> None:
        """Store request-key → pod entries; learn engine→request mappings.

        ``engine_keys=None`` adds speculative entries with no mapping.
        """

    @abc.abstractmethod
    def evict(
        self,
        key: BlockHash,
        key_type: KeyType,
        entries: Sequence[PodEntry],
    ) -> None:
        """Remove the given pod entries from a key.

        ``KeyType.ENGINE`` resolves through the engine→request mapping
        first; ``KeyType.REQUEST`` operates on the key directly.
        """

    @abc.abstractmethod
    def get_request_key(self, engine_key: BlockHash) -> Optional[BlockHash]:
        """Resolve an engine key to its last (highest-index) request key.

        Returns ``None`` when the mapping is unknown (e.g. already evicted);
        reference raises an error (``in_memory.go:355-361``) — callers here
        treat ``None`` identically.
        """

    def get_request_keys(self, engine_key: BlockHash) -> Optional[list[BlockHash]]:
        """Resolve an engine key to ALL of its mapped request keys.

        The sharded control plane (cluster/) needs the full fan-out: an
        engine-key evict must reach every owning shard of every mapped
        request key, not just the last one. Default falls back to the
        single-key resolution; backends that store the full list override.
        """
        rk = self.get_request_key(engine_key)
        return None if rk is None else [rk]

    def add_mappings(
        self, mappings: dict[BlockHash, list[BlockHash]]
    ) -> None:
        """Learn engine→request mappings without storing any pod entries.

        The sharded ingestion filter (cluster.sharded_index) keeps the full
        mapping table on every shard (mappings are small ints; chained
        parent resolution must never dead-end) while entries are stored
        only on owning shards. Default routes through ``restore_state``,
        which every snapshot-capable backend already implements.
        """
        if mappings:
            self.restore_state({
                "entries": [],
                "mappings": [[ek, list(rks)] for ek, rks in mappings.items()],
            })

    @abc.abstractmethod
    def clear(self, pod_identifier: str) -> None:
        """Drop every entry for a pod, across all device tiers.

        Backs the pod-wide AllBlocksCleared KV-event (engine prefix-cache
        reset, e.g. after a weight rollout). O(N), off the hot path.
        """

    def lookup_chunked(
        self,
        request_keys: Sequence[BlockHash],
        pod_identifier_set: Optional[set[str]] = None,
        chunk_size: int = 128,
    ) -> dict[BlockHash, list[PodEntry]]:
        """``lookup`` issued in chunks, stopping at the first chunk with
        zero hits.

        Sound for longest-prefix scoring only: the scorer counts
        consecutive-from-0 runs, and an all-miss chunk proves the run ended
        inside or before it, so later keys cannot contribute. The result
        may therefore be a *subset* of a full ``lookup`` (hits after a gap
        are skipped) — identical scores, fewer backend round-trips.
        ``chunk_size <= 0`` degrades to a single full lookup.
        """
        n = len(request_keys)
        if chunk_size <= 0 or n <= chunk_size:
            return self.lookup(request_keys, pod_identifier_set)
        result: dict[BlockHash, list[PodEntry]] = {}
        for start in range(0, n, chunk_size):
            chunk = request_keys[start:start + chunk_size]
            found = self.lookup(chunk, pod_identifier_set)
            if not found:
                break
            result.update(found)
            # A partial chunk means some key in it missed, so the
            # consecutive-from-0 run ends inside this chunk; later chunks
            # cannot change any longest-prefix score.
            if len(found) < len(chunk):
                break
        return result

    def evict_batch(
        self,
        keys: Sequence[BlockHash],
        key_type: KeyType,
        entries: Sequence[PodEntry],
    ) -> None:
        """Evict the same pod entries from many keys.

        Default loops ``evict``; backends override to amortize per-call
        costs (one Redis pipeline, one native entry-packing pass).
        """
        for key in keys:
            self.evict(key, key_type, entries)

    # -- snapshot capability (recovery/) ----------------------------------

    def dump_state(self) -> Optional[dict]:
        """Serialize the index contents for a crash-recovery snapshot.

        Returns ``{"entries": [[request_key, [[pod, tier, flags,
        group_idx], ...]], ...], "mappings": [[engine_key, [request_key,
        ...]], ...]}`` — plain ints/strings/lists, directly
        canonical-CBOR-encodable. ``flags`` packs bit0=speculative,
        bit1=has_group (the native backend's wire layout).

        Returns ``None`` for backends without snapshot support — e.g. the
        Redis/Valkey backend, which is already durable on its own and
        survives indexer restarts without our help.
        """
        return None

    def restore_state(self, state: dict) -> int:
        """Load a :meth:`dump_state` document; returns entries restored.

        Restored state is soft: live events layered on top converge it,
        so a restore into a non-empty index is additive, not destructive.
        Backends without snapshot support return 0.
        """
        return 0


def infer_engine_mappings(
    engine_keys: Sequence[BlockHash], request_keys: Sequence[BlockHash]
) -> dict[BlockHash, list[BlockHash]]:
    """Infer engine→request key mappings from the length ratio.

    Mirrors reference ``in_memory.go:164-180``: with ``n = max(len(e),
    len(r))`` the i-th virtual slot maps ``engine[i*len(e)//n] →
    request[i*len(r)//n]``, producing 1:1, many:1 or 1:many fan-outs.
    """
    mappings: dict[BlockHash, list[BlockHash]] = {}
    ne, nr = len(engine_keys), len(request_keys)
    if ne == 0 or nr == 0:
        return mappings
    n = max(ne, nr)
    for i in range(n):
        ek = engine_keys[i * ne // n]
        rk = request_keys[i * nr // n]
        mappings.setdefault(ek, []).append(rk)
    return mappings


@dataclass
class IndexConfig:
    """Backend selection config (reference ``index.go:29-57``).

    Priority when several are set: cost-aware > native > redis > in-memory
    (the reference also supports Valkey, same wire as Redis).
    """

    in_memory_config: Optional["InMemoryIndexConfig"] = None  # noqa: F821
    cost_aware_memory_config: Optional["CostAwareMemoryIndexConfig"] = None  # noqa: F821
    redis_config: Optional[dict] = None
    # Native C++ index (csrc/kvindex): the high-throughput in-process
    # backend; same contract, GIL-free hot paths.
    native_config: Optional["NativeIndexConfig"] = None  # noqa: F821
    enable_metrics: bool = False
    # Wrap the backend with OTel spans per operation (child spans under
    # score_tokens). Off by default: even no-op span managers cost on the
    # lookup hot path.
    enable_tracing: bool = False
    metrics_logging_interval_s: float = 0.0
    # Wrap a remote backend (Redis/Valkey) in a FailoverIndex: ops run
    # under retry + circuit breaker, and trip to a warm in-memory replica
    # while the primary is down (docs/resilience.md). No-op for backends
    # that are already in-process.
    failover_to_memory: bool = False

    @classmethod
    def default(cls) -> "IndexConfig":
        """Default backend: the native C++ index when its library builds
        (same contract, GIL-free hot paths), else the Python in-memory
        index. Both mirror the reference's default in-memory semantics."""
        try:
            from . import native

            if native.native_available():
                return cls(native_config=native.NativeIndexConfig())
        except Exception:  # pragma: no cover - toolchain-less envs  # lint: allow-swallow (fall through to in-memory index)
            pass
        from .in_memory import InMemoryIndexConfig

        return cls(in_memory_config=InMemoryIndexConfig())


def create_index(cfg: Optional[IndexConfig] = None) -> Index:
    """Create an index backend per config priority (``index.go:60-106``)."""
    from .in_memory import InMemoryIndex, InMemoryIndexConfig

    if cfg is None:
        cfg = IndexConfig.default()

    idx: Index
    if cfg.cost_aware_memory_config is not None:
        from .cost_aware import CostAwareMemoryIndex

        idx = CostAwareMemoryIndex(cfg.cost_aware_memory_config)
    elif cfg.native_config is not None:
        from .native import NativeIndex

        idx = NativeIndex(cfg.native_config)
    elif cfg.redis_config is not None:
        from .redis_index import RedisIndex

        idx = RedisIndex(cfg.redis_config)
        if cfg.failover_to_memory:
            from ..resilience.failover import FailoverIndex

            idx = FailoverIndex(idx, InMemoryIndex(InMemoryIndexConfig()))
    elif cfg.in_memory_config is not None:
        idx = InMemoryIndex(cfg.in_memory_config)
    else:
        idx = InMemoryIndex(InMemoryIndexConfig())

    if cfg.enable_metrics:
        from .instrumented import InstrumentedIndex

        idx = InstrumentedIndex(idx)
        if cfg.metrics_logging_interval_s > 0:
            from ..metrics.collector import start_metrics_logging

            start_metrics_logging(cfg.metrics_logging_interval_s)

    if cfg.enable_tracing:
        from .instrumented import TracedIndex

        idx = TracedIndex(idx)

    return idx
