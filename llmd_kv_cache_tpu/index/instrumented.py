"""Metrics and tracing decorators for Index backends.

Counterparts of reference ``instrumented_index.go`` / ``traced_index.go``:
wrap any Index with Prometheus counters on lookups/admissions/evictions and
OTel spans around each operation. Wrapping is cheap and no-ops when tracing
is unconfigured.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

from ..core.keys import BlockHash, KeyType, PodEntry
from ..metrics.collector import (
    INDEX_ADMISSIONS,
    INDEX_EVICTIONS,
    INDEX_LOOKUP_HITS,
    INDEX_LOOKUP_LATENCY,
    INDEX_LOOKUP_REQUESTS,
    INDEX_MAX_POD_HIT_COUNT,
)
from ..telemetry import tracer
from .base import Index


class InstrumentedIndex(Index):
    """Prometheus-instrumented Index decorator."""

    def __init__(self, inner: Index):
        self._inner = inner

    def lookup(self, request_keys, pod_identifier_set=None):
        INDEX_LOOKUP_REQUESTS.inc()
        start = time.perf_counter()
        try:
            result = self._inner.lookup(request_keys, pod_identifier_set)
        finally:
            INDEX_LOOKUP_LATENCY.observe(time.perf_counter() - start)
        INDEX_LOOKUP_HITS.inc(len(result))
        if result:
            pod_hits: dict[str, int] = {}
            for entries in result.values():
                for e in entries:
                    pod_hits[e.pod_identifier] = pod_hits.get(e.pod_identifier, 0) + 1
            INDEX_MAX_POD_HIT_COUNT.inc(max(pod_hits.values()))
        return result

    def add(self, engine_keys, request_keys, entries):
        self._inner.add(engine_keys, request_keys, entries)
        INDEX_ADMISSIONS.inc(len(request_keys))

    def evict(self, key, key_type, entries):
        self._inner.evict(key, key_type, entries)
        INDEX_EVICTIONS.inc()

    def evict_batch(self, keys, key_type, entries):
        # Delegate so the backend's batched implementation (pipelined
        # Redis, packed-once native) isn't degraded to an evict loop.
        self._inner.evict_batch(keys, key_type, entries)
        INDEX_EVICTIONS.inc(len(keys))

    def get_request_key(self, engine_key: BlockHash) -> Optional[BlockHash]:
        return self._inner.get_request_key(engine_key)

    def clear(self, pod_identifier: str) -> None:
        self._inner.clear(pod_identifier)

    def dump_state(self):
        return self._inner.dump_state()

    def restore_state(self, state: dict) -> int:
        return self._inner.restore_state(state)


class TracedIndex(Index):
    """OTel-span Index decorator (no-op without a provider)."""

    def __init__(self, inner: Index):
        self._inner = inner
        self._tracer = tracer()

    def lookup(
        self,
        request_keys: Sequence[BlockHash],
        pod_identifier_set=None,
    ):
        with self._tracer.span(
            "llm_d.kv_cache.index.lookup", key_count=len(request_keys)
        ) as span:
            result = self._inner.lookup(request_keys, pod_identifier_set)
            span.set_attribute("hit_count", len(result))
            return result

    def add(self, engine_keys, request_keys, entries):
        with self._tracer.span(
            "llm_d.kv_cache.index.add",
            engine_key_count=len(engine_keys) if engine_keys else 0,
            request_key_count=len(request_keys),
            entry_count=len(entries),
        ):
            self._inner.add(engine_keys, request_keys, entries)

    def evict(self, key: BlockHash, key_type: KeyType, entries: Sequence[PodEntry]):
        with self._tracer.span("llm_d.kv_cache.index.evict", key_type=key_type.value):
            self._inner.evict(key, key_type, entries)

    def evict_batch(self, keys, key_type: KeyType, entries: Sequence[PodEntry]):
        with self._tracer.span(
            "llm_d.kv_cache.index.evict_batch",
            key_type=key_type.value, key_count=len(keys),
        ):
            self._inner.evict_batch(keys, key_type, entries)

    def get_request_key(self, engine_key: BlockHash) -> Optional[BlockHash]:
        return self._inner.get_request_key(engine_key)

    def clear(self, pod_identifier: str) -> None:
        with self._tracer.span("llm_d.kv_cache.index.clear", pod=pod_identifier):
            self._inner.clear(pod_identifier)

    def dump_state(self):
        with self._tracer.span("llm_d.kv_cache.index.dump_state"):
            return self._inner.dump_state()

    def restore_state(self, state: dict) -> int:
        with self._tracer.span("llm_d.kv_cache.index.restore_state") as span:
            restored = self._inner.restore_state(state)
            span.set_attribute("restored_entries", restored)
            return restored
