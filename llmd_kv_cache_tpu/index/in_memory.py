"""In-memory index backend: two-level LRU.

Counterpart of reference ``pkg/kvcache/kvblock/in_memory.go``. Outer LRU maps
request key → per-key pod LRU (bounded, default 10 pods); a sibling LRU maps
engine key → request key list. All state is soft and converges from the
event stream.

Concurrency notes carried over from the reference (its documented TOCTOU
guards, ``in_memory.go:80-82,185-186,300-312``): a global mutex serializes
Evict's all-empty check + mapping removal against Add's entry insertion, and
empty-key removal re-checks emptiness under the per-key lock.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from ..utils.lockdep import new_lock
from ..core.keys import BlockHash, KeyType, PodEntry
from ..utils.logging import get_logger
from ..utils.lru import LRUCache
from .base import Index, infer_engine_mappings

logger = get_logger("index.in_memory")

DEFAULT_INDEX_SIZE = 10**8  # max request keys (reference in_memory.go:35)
DEFAULT_PODS_PER_KEY = 10  # max pod entries per key (in_memory.go:36)


@dataclass
class InMemoryIndexConfig:
    size: int = DEFAULT_INDEX_SIZE
    pod_cache_size: int = DEFAULT_PODS_PER_KEY

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "InMemoryIndexConfig":
        if not d:
            return cls()
        return cls(
            size=d.get("size", DEFAULT_INDEX_SIZE) or DEFAULT_INDEX_SIZE,
            pod_cache_size=d.get("podCacheSize", d.get("pod_cache_size", DEFAULT_PODS_PER_KEY))
            or DEFAULT_PODS_PER_KEY,
        )


class _PodCache:
    """Bounded LRU of pod entries for one request key."""

    __slots__ = ("cache", "mu")

    def __init__(self, capacity: int):
        self.cache: LRUCache[PodEntry, None] = LRUCache(capacity)
        self.mu = new_lock()


class InMemoryIndex(Index):
    """Two-level-LRU in-memory index."""

    def __init__(self, cfg: Optional[InMemoryIndexConfig] = None):
        cfg = cfg or InMemoryIndexConfig()
        self._data: LRUCache[BlockHash, _PodCache] = LRUCache(cfg.size)
        self._engine_to_request: LRUCache[BlockHash, list[BlockHash]] = LRUCache(cfg.size)
        self._pod_cache_size = cfg.pod_cache_size
        # Serializes engine-key-level check-and-act (Evict's all-empty check
        # + mapping removal vs Add's insertion) — reference in_memory.go:80-82.
        self._mu = new_lock()

    def lookup(
        self,
        request_keys: Sequence[BlockHash],
        pod_identifier_set: Optional[set[str]] = None,
    ) -> dict[BlockHash, list[PodEntry]]:
        if not request_keys:
            raise ValueError("no request_keys provided for lookup")

        pods_per_key: dict[BlockHash, list[PodEntry]] = {}
        filter_pods = bool(pod_identifier_set)

        for key in request_keys:
            pod_cache = self._data.get(key)
            if pod_cache is None:
                continue  # absent key does not break the scan (in_memory.go:142-144)
            entries = pod_cache.cache.keys()
            if not entries:
                # Known key with no pods: prefix chain breaks here — stop.
                return pods_per_key
            if filter_pods:
                filtered = [e for e in entries if e.pod_identifier in pod_identifier_set]
                if filtered:
                    pods_per_key[key] = filtered
            else:
                pods_per_key[key] = entries
        return pods_per_key

    def add(
        self,
        engine_keys: Optional[Sequence[BlockHash]],
        request_keys: Sequence[BlockHash],
        entries: Sequence[PodEntry],
    ) -> None:
        if not request_keys or not entries:
            raise ValueError("no keys or entries provided for adding to index")

        if engine_keys is not None:
            for ek, rks in infer_engine_mappings(engine_keys, request_keys).items():
                self._engine_to_request.add(ek, rks)

        with self._mu:
            for key in request_keys:
                pod_cache, _ = self._data.get_or_create(
                    key, lambda: _PodCache(self._pod_cache_size)
                )
                with pod_cache.mu:
                    for entry in entries:
                        pod_cache.cache.add(entry, None)

    def evict(
        self,
        key: BlockHash,
        key_type: KeyType,
        entries: Sequence[PodEntry],
    ) -> None:
        if not entries:
            raise ValueError("no entries provided for eviction from index")

        if key_type is KeyType.ENGINE:
            rks = self._engine_to_request.get(key)
            if rks is None:
                return  # unknown engine key: nothing to evict
            for rk in rks:
                self._evict_pods_from_request_key(rk, entries)
            with self._mu:
                all_empty = True
                for rk in rks:
                    pc = self._data.get(rk)
                    if pc is not None and len(pc.cache) > 0:
                        all_empty = False
                        break
                if all_empty:
                    self._engine_to_request.remove(key)
        elif key_type is KeyType.REQUEST:
            self._evict_pods_from_request_key(key, entries)
        else:  # pragma: no cover - enum exhaustive
            raise ValueError(f"unknown key type: {key_type}")

    def _evict_pods_from_request_key(
        self, request_key: BlockHash, entries: Sequence[PodEntry]
    ) -> None:
        pod_cache = self._data.get(request_key)
        if pod_cache is None:
            return

        with pod_cache.mu:
            for entry in entries:
                pod_cache.cache.remove(entry)
            is_empty = len(pod_cache.cache) == 0

        if not is_empty:
            return

        # Remove the now-empty key; re-check emptiness under the per-key
        # lock to avoid racing a concurrent Add (in_memory.go:300-312).
        current = self._data.get(request_key)
        if current is None:
            return
        with current.mu:
            if len(current.cache) == 0:
                self._data.remove(request_key)

    def get_request_key(self, engine_key: BlockHash) -> Optional[BlockHash]:
        rks = self._engine_to_request.get(engine_key)
        if not rks:
            return None
        return rks[-1]

    def get_request_keys(self, engine_key: BlockHash) -> Optional[list[BlockHash]]:
        rks = self._engine_to_request.get(engine_key)
        return list(rks) if rks else None

    def clear(self, pod_identifier: str) -> None:
        # Peek so the scan does not promote LRU recency (in_memory.go:327-330).
        # The engine→request mapping is intentionally left untouched: it is
        # LRU-bounded, self-heals on re-Add, and stale mappings resolve to
        # emptied request keys that correctly break the prefix chain.
        for request_key in self._data.keys():
            pod_cache = self._data.peek(request_key)
            if pod_cache is None:
                continue
            with pod_cache.mu:
                matched = [
                    e for e in pod_cache.cache.keys() if e.pod_identifier == pod_identifier
                ]
            if matched:
                self._evict_pods_from_request_key(request_key, matched)

    # -- snapshot capability (recovery/) --

    def dump_state(self) -> dict:
        entries: list = []
        # Peek so the full-table scan does not promote LRU recency.
        for request_key in self._data.keys():
            pod_cache = self._data.peek(request_key)
            if pod_cache is None:
                continue
            with pod_cache.mu:
                rows = [
                    [
                        e.pod_identifier,
                        e.device_tier,
                        (1 if e.speculative else 0) | (2 if e.has_group else 0),
                        e.group_idx,
                    ]
                    for e in pod_cache.cache.keys()
                ]
            entries.append([int(request_key), rows])
        mappings: list = []
        for engine_key in self._engine_to_request.keys():
            rks = self._engine_to_request.peek(engine_key)
            if rks:
                mappings.append([int(engine_key), [int(rk) for rk in rks]])
        return {"entries": entries, "mappings": mappings}

    def restore_state(self, state: dict) -> int:
        restored = 0
        for request_key, rows in state.get("entries", []):
            pod_entries = [
                PodEntry(
                    pod_identifier=pod,
                    device_tier=tier,
                    speculative=bool(flags & 1),
                    has_group=bool(flags & 2),
                    group_idx=group_idx,
                )
                for pod, tier, flags, group_idx in rows
            ]
            if pod_entries:
                self.add(None, [request_key], pod_entries)
                restored += len(pod_entries)
        for engine_key, rks in state.get("mappings", []):
            self._engine_to_request.add(engine_key, list(rks))
        return restored

    # -- introspection helpers (not part of the Index contract) --

    def __len__(self) -> int:
        return len(self._data)
